"""Static-analysis gate: run the raft_sim_tpu invariant auditor.

Five passes (raft_sim_tpu/analysis): Pass A lowers the real step/scan
programs per config tier and audits the jaxprs (dtype discipline,
loop-invariant carry, recompile forks); Pass B lints the package source
(traced branches, float literals) and cross-checks the types.py dtype
comments and the checkpoint version pin against the live structures; Pass C
prices the same lowered programs (scan-carry bytes/tick, live-set peak,
entry-point donation, roofline at the pinned HBM rate) against the pins in
tests/golden_cost_model.json; Pass D audits host<->device concurrency
(use-after-donate dataflow over the standing loops, overlap write-set
disjointness, PRNG key-stream and single-writer sink discipline), with an
optional runtime donation-poison leg (--dynamic); Pass E abstract-interprets
the same lowered jaxprs over integer intervals (overflow on narrowing
casts, pack-width fit, gather/scatter index bounds, stale range comments,
safe soak horizons) against the pins in tests/golden_ranges.json. Lowering
only -- no device execution, and the only XLA compiles are tiny-shape
donation probes (plus the short sanitizer sessions when --dynamic is given)
-- so the whole gate runs in well under a minute on CPU. CI runs it before
the tier-1 tests.

    python tools/check.py --all                  # all passes, text report
    python tools/check.py --all --format=json    # machine-readable (CI artifact)
    python tools/check.py --ast                  # source + contract rules only
    python tools/check.py --jaxpr --configs config3,config5
    python tools/check.py --cost                 # Pass C (cost model) only
    python tools/check.py --race                 # Pass D (concurrency) only
    python tools/check.py --race --dynamic       # + runtime donation poison
    python tools/check.py --range                # Pass E (value ranges) only
    python tools/check.py --cost-diff            # pinned-vs-current cost table
    python tools/check.py --range-diff           # pinned-vs-current range table
    python tools/check.py --update-goldens       # re-pin golden_cost_model.json
                                                 #   + golden_ranges.json

Exit codes: 0 = no unwaived findings, 1 = unwaived findings (or a stale /
malformed waiver file), 2 = usage error. Intentional exceptions live in
raft_sim_tpu/analysis/waivers.json with one-line justifications
(docs/ANALYSIS.md documents the format and the rule catalogue).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--all", action="store_true", help="run all passes (default)")
    ap.add_argument("--ast", action="store_true", help="Pass B only (AST + contracts)")
    ap.add_argument("--jaxpr", action="store_true", help="Pass A only (jaxpr audit)")
    ap.add_argument("--cost", action="store_true", help="Pass C only (cost model)")
    ap.add_argument(
        "--race", action="store_true",
        help="Pass D only (host<->device concurrency: use-after-donate "
             "dataflow, overlap write-set, key-stream + sink-writer "
             "discipline)",
    )
    ap.add_argument(
        "--range", action="store_true", dest="range_",
        help="Pass E only (value-range abstract interpretation: narrowing "
             "overflow, pack-width fit, index bounds, annotation drift, "
             "safe soak horizons vs tests/golden_ranges.json)",
    )
    ap.add_argument(
        "--dynamic", action="store_true",
        help="with the race pass: also run the runtime donation-poison "
             "sanitizer (short sanitizer-armed standing-loop sessions, "
             "bit-exactness pinned vs plain)",
    )
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument(
        "--configs",
        default=None,
        help="comma-separated preset names for the jaxpr/cost passes "
             "(default: the analysis.jaxpr_audit.AUDIT_CONFIGS tiers)",
    )
    ap.add_argument(
        "--waivers",
        default=None,
        help="waiver file (default: raft_sim_tpu/analysis/waivers.json); "
             "'none' disables waiving",
    )
    ap.add_argument(
        "--update-goldens", action="store_true",
        help="regenerate tests/golden_cost_model.json AND "
             "tests/golden_ranges.json from the current tree (the cost-model "
             "and value-range pins; mirrors tests/test_golden_jaxpr.py "
             "--update) and exit",
    )
    ap.add_argument(
        "--cost-diff", action="store_true",
        help="print the pinned-vs-current cost table (bytes/tick, live peak, "
             "donation) and exit 0 -- the CI failure-triage rendering",
    )
    ap.add_argument(
        "--range-diff", action="store_true",
        help="print the pinned-vs-current value-range table (carry "
             "intervals, safe horizons, pack widths) and exit 0 -- the CI "
             "failure-triage rendering",
    )
    ap.add_argument(
        "--cost-report", default=None, metavar="PATH",
        help="also write the full derived cost document (per-leg carry "
             "model, donation audit, rooflines) as JSON to PATH",
    )
    ap.add_argument(
        "--range-report", default=None, metavar="PATH",
        help="also write the full derived range document (per-leg carry "
             "intervals, escapes, horizons, pack widths, ceilings) as JSON "
             "to PATH",
    )
    args = ap.parse_args(argv)

    from raft_sim_tpu.analysis import cost_model, jaxpr_audit, range_audit, run
    from raft_sim_tpu.analysis import findings as F
    from raft_sim_tpu.utils.config import PRESETS

    config_names = jaxpr_audit.AUDIT_CONFIGS
    if args.configs:
        config_names = tuple(c.strip() for c in args.configs.split(","))
        unknown = [c for c in config_names if c not in PRESETS]
        if unknown:
            print(f"unknown preset(s) {unknown}", file=sys.stderr)
            return 2

    if args.update_goldens:
        if args.configs:
            # A partial golden would fail the full gate as out-of-sync; the
            # pins always cover every audited tier.
            print("--update-goldens ignores --configs: the golden file pins "
                  "ALL audited tiers", file=sys.stderr)
        paths = [cost_model.update_golden(), range_audit.update_golden()]
        for path in paths:
            print(f"wrote {path} (jax {__import__('jax').__version__})")
        print("review the diff and commit the files alongside the change "
              "they pin")
        return 0

    if args.cost_diff:
        derived = cost_model.derive_all(config_names)
        try:
            with open(cost_model.golden_path()) as f:
                golden = json.load(f)
        except (OSError, json.JSONDecodeError) as ex:
            print(f"golden cost file unreadable: {ex}", file=sys.stderr)
            golden = {}
        cost_model.diff_table(derived, golden)
        return 0

    if args.range_diff:
        derived, _finds = range_audit.derive_all(config_names)
        try:
            with open(range_audit.golden_path()) as f:
                golden = json.load(f)
        except (OSError, json.JSONDecodeError) as ex:
            print(f"golden range file unreadable: {ex}", file=sys.stderr)
            golden = {}
        range_audit.diff_table(derived, golden)
        return 0

    picked = args.ast or args.jaxpr or args.cost or args.race or args.range_
    do_ast = args.all or args.ast or not picked
    do_jaxpr = args.all or args.jaxpr or not picked
    do_cost = args.all or args.cost or not picked
    do_race = args.all or args.race or not picked
    do_range = args.all or args.range_ or not picked
    waivers_path = run.DEFAULT_WAIVERS
    if args.waivers:
        waivers_path = None if args.waivers == "none" else args.waivers
    if args.dynamic and not do_race:
        print("--dynamic needs the race pass (add --race or --all)",
              file=sys.stderr)
        return 2

    t0 = time.time()
    found, unused, problems, timings = run.run_all(
        do_ast=do_ast, do_jaxpr=do_jaxpr, do_cost=do_cost, do_race=do_race,
        do_range=do_range, do_dynamic=args.dynamic,
        config_names=config_names, waivers_path=waivers_path,
    )
    elapsed = time.time() - t0
    unwaived = [f for f in found if not f.waived]

    if args.cost_report and do_cost:
        with open(args.cost_report, "w") as f:
            json.dump(cost_model.derive_all(config_names), f, indent=1,
                      sort_keys=True)
            f.write("\n")
    elif args.cost_report:
        print("--cost-report ignored: the cost pass is not selected (add "
              "--cost or --all)", file=sys.stderr)

    if args.range_report and do_range:
        derived, _finds = range_audit.derive_all(config_names)
        with open(args.range_report, "w") as f:
            json.dump(derived, f, indent=1, sort_keys=True)
            f.write("\n")
    elif args.range_report:
        print("--range-report ignored: the range pass is not selected (add "
              "--range or --all)", file=sys.stderr)

    if args.format == "json":
        doc = F.report(
            found,
            unused_waivers=unused,
            extras={
                "elapsed_s": round(elapsed, 2),
                "pass_elapsed_s": timings,
                "waiver_problems": problems,
            },
        )
        print(json.dumps(doc, indent=2))
    else:
        for f in found:
            tag = f"WAIVED ({f.waiver_reason})" if f.waived else "FAIL"
            print(f"[{tag}] {f.rule} {f.location()}\n    {f.message}")
        for w in unused:
            print(f"[STALE WAIVER] {w.get('rule')} {w.get('path')}: "
                  f"matched no finding -- remove it ({w.get('reason')})")
        for p in problems:
            print(f"[WAIVER FILE ERROR] {p}")
        per_pass = " ".join(f"{k}={v:.1f}s" for k, v in timings.items())
        print(
            f"{len(found)} finding(s): {len(unwaived)} unwaived, "
            f"{len(found) - len(unwaived)} waived, {len(unused)} stale waiver(s) "
            f"({elapsed:.1f}s: {per_pass})"
        )
    return 1 if (unwaived or unused or problems) else 0


if __name__ == "__main__":
    sys.exit(main())
