"""Render and diff telemetry runs (the host sink's consumer).

Reads the schema `utils/telemetry_sink.py` writes (driver --telemetry-dir,
bench --telemetry-dir) and renders it for humans: a run header from the
manifest, the merged run totals (losslessly re-summed from the integer window
stream), a tail of the window table, and flight-recorder renderings via
`sim/trace.info_lines`. `--diff` compares two runs -- either two telemetry
directories or a telemetry directory against a bench artifact (BENCH_*.json /
`python bench.py` output), so a fresh run can be checked against the recorded
history without eyeballing raw JSON.

    python tools/metrics_report.py out/telemetry                # summary table
    python tools/metrics_report.py out/telemetry --validate     # schema check only
    python tools/metrics_report.py out/telemetry --flight 7     # render a recording
    python tools/metrics_report.py --diff out/a out/b
    python tools/metrics_report.py --diff out/telemetry BENCH_r05.json --config config2
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from raft_sim_tpu.parallel.mesh import _hist_percentile
from raft_sim_tpu.types import LAT_HIST_BINS, StepInfo
from raft_sim_tpu.utils import telemetry_sink as sink


def _merge_windows(rows: list[dict]) -> dict:
    """Fold the window stream back into run totals (exact: the stream carries
    integer sums, so re-summing is lossless)."""
    if not rows:
        return {}
    hist = np.zeros(LAT_HIST_BINS, np.int64)
    rhist = np.zeros(LAT_HIST_BINS, np.int64)
    tot = {k: 0 for k in ("violations", "msgs", "cmds", "lat_sum", "lat_cnt",
                          "lat_excluded", "noop_blocked", "lm_skipped_pairs",
                          "multi_leader", "reads", "read_lat_sum", "ticks")}
    first_viol = None
    mx = {"max_term": 0, "max_commit": 0}
    for r in rows:
        for k in tot:
            if k in ("reads", "read_lat_sum"):
                # Only the v3 read-class keys may be absent (pre-v3 lines,
                # BENCH_r* rows); a missing CORE key is corruption and must
                # keep raising, not merge as zero.
                tot[k] += r.get(k, 0)
            else:
                tot[k] += r[k]
        for k in mx:
            mx[k] = max(mx[k], r[k])
        hist += np.asarray(r["lat_hist"], np.int64)
        # Pre-v3 window lines carry no read traffic class: treat as zero.
        rhist += np.asarray(r.get("read_hist", [0] * LAT_HIST_BINS), np.int64)
        if first_viol is None and r.get("first_viol_tick") is not None:
            first_viol = r["first_viol_tick"]
    out = tot | mx
    out["first_viol_tick"] = first_viol
    out["lat_p50"] = _hist_percentile(hist, 0.50)
    out["lat_p95"] = _hist_percentile(hist, 0.95)
    out["lat_p99"] = _hist_percentile(hist, 0.99)
    out["mean_commit_latency"] = (
        round(tot["lat_sum"] / tot["lat_cnt"], 3) if tot["lat_cnt"] else None
    )
    out["read_p50"] = _hist_percentile(rhist, 0.50)
    out["read_p99"] = _hist_percentile(rhist, 0.99)
    out["mean_read_latency"] = (
        round(tot["read_lat_sum"] / tot["reads"], 3) if tot["reads"] else None
    )
    return out


def load_run(path: str, config: str | None = None) -> tuple[str, dict]:
    """(label, comparable-metrics dict) from a telemetry directory OR a bench
    JSON artifact (BENCH_*.json / `python bench.py` stdout saved to a file).
    For bench artifacts, `config` picks the matrix row (default: the headline
    workload)."""
    if os.path.isdir(path):
        # Same gate as the report path: a crash-truncated or malformed
        # directory gets the INVALID listing, not a raw traceback.
        errors = sink.validate(path)
        if errors:
            raise SystemExit(
                f"{path}: invalid telemetry directory:\n  " + "\n  ".join(errors)
            )
        man = sink.read_manifest(path)
        totals = _merge_windows(sink.read_windows(path))
        summary_path = os.path.join(path, "summary.json")
        if os.path.isfile(summary_path):
            # End-of-run rollup keys (p50_stable_tick, ...) that the window
            # stream alone cannot provide; window-derived totals win on clash.
            with open(summary_path) as f:
                totals = json.load(f) | totals
        label = (
            f"{path} [{man.get('source', '?')}: batch={man.get('batch')} "
            f"seed={man.get('seed')} cfg={man.get('config_hash', '?')[:8]}]"
        )
        return label, totals
    with open(path) as f:
        data = json.load(f)
    if "matrix" not in data and ("tail" in data or "parsed" in data):
        # BENCH_r*.json wrapper: a capture of bench.py's stdout ({n, cmd, rc,
        # tail, parsed}); the bench JSON line is `parsed` when present, else
        # embedded in the tail text -- which is a BYTE-truncated capture, so
        # recover whatever complete matrix rows survive in it.
        if data.get("parsed"):
            data = data["parsed"]
        else:
            from raft_sim_tpu.analysis import cost_model

            rows = cost_model.bench_matrix(data)
            if not rows:
                raise SystemExit(f"{path}: bench wrapper carries no recoverable rows")
            data = {"matrix": rows, "workload": None}
    if "matrix" in data:  # bench artifact
        name = config or data.get("workload") or next(iter(data["matrix"]))
        if name not in data["matrix"]:
            raise SystemExit(f"{path}: no matrix row {name!r} "
                             f"(have {sorted(data['matrix'])})")
        row = dict(data["matrix"][name])
        label = f"{path} [bench row {name}]"
        # Align bench field names with the telemetry totals where they mean
        # the same thing.
        row["cmds"] = row.pop("total_cmds", None)
        return label, row
    raise SystemExit(f"{path}: neither a telemetry directory nor a bench artifact")


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:,.3f}".rstrip("0").rstrip(".")
    return f"{v:,}"


def report(directory: str, n_windows: int, out=None) -> None:
    man = sink.read_manifest(directory)
    rows = sink.read_windows(directory)
    totals = _merge_windows(rows)
    cfg = man.get("config", {})
    print(
        f"telemetry run: {directory}\n"
        f"  source={man.get('source')} schema=v{man.get('schema_version')} "
        f"backend={man.get('backend')} jax={man.get('jax_version')}\n"
        f"  config {man.get('config_hash')}: N={cfg.get('n_nodes')} "
        f"CAP={cfg.get('log_capacity')} batch={man.get('batch')} "
        f"seed={man.get('seed')} window={man.get('window')} "
        f"ring={man.get('ring')}",
        file=out,
    )
    if not rows:
        print("  (no windows recorded)", file=out)
        return
    print(f"\n  {len(rows)} windows, {totals['ticks']} ticks per cluster", file=out)
    keys = ("violations", "first_viol_tick", "msgs", "cmds", "max_commit",
            "mean_commit_latency", "lat_p50", "lat_p95", "lat_p99",
            "lat_excluded", "noop_blocked", "lm_skipped_pairs", "multi_leader",
            "reads", "mean_read_latency", "read_p50", "read_p99")
    for k in keys:
        print(f"  {k:22} {_fmt(totals.get(k)):>14}", file=out)

    tail = rows[-n_windows:]
    print(f"\n  last {len(tail)} windows:", file=out)
    cols = ("window", "start", "ticks", "violations", "msgs", "cmds",
            "lat_cnt", "lat_excluded")
    print("  " + " ".join(f"{c:>12}" for c in cols), file=out)
    for r in tail:
        print("  " + " ".join(f"{_fmt(r[c]):>12}" for c in cols), file=out)

    flights = sorted(
        f for f in os.listdir(directory)
        if f.startswith("flight_") and f.endswith(".jsonl")
    )
    if flights:
        print(
            f"\n  flight recordings: {', '.join(flights)} "
            f"(render with --flight <cluster>)",
            file=out,
        )


def render_flight(directory: str, cluster: int, out=None) -> None:
    """Rebuild the stacked StepInfo from a flight_<c>.jsonl and render it with
    the same decoder the live trace path uses (sim/trace.info_lines)."""
    from raft_sim_tpu.sim import trace

    path = os.path.join(directory, f"flight_{cluster}.jsonl")
    with open(path) as f:
        rows = [json.loads(line) for line in f if line.strip()]
    if not rows:
        print(f"{path}: empty recording", file=out)
        return
    infos = StepInfo(*(np.asarray([r[f] for r in rows]) for f in StepInfo._fields))
    ticks = [r["tick"] for r in rows]
    print(f"flight recorder, cluster {cluster}: ticks {ticks[0]}..{ticks[-1]} "
          f"({len(rows)} captured; frozen at the first violation)", file=out)
    for t, line in zip(ticks, trace.info_lines(infos)):
        # info_lines numbers from 0 within the stack; re-anchor at the
        # recorder's absolute ticks.
        print(f"tick {t:>8}  {line[line.index('leader='):]}", file=out)


def report_trace(directory: str, clusters=None, limit: int = 40,
                 perfetto: str | None = None, out=None) -> None:
    """Render a directory's protocol trace (trace.jsonl) as per-cluster
    timelines, run the whole-history checker over it, and optionally export
    Chrome-trace/Perfetto JSON (`perfetto` path): one process per cluster,
    one track per node, instant events named by kind -- opens in
    ui.perfetto.dev next to the --profile captures (PR 8)."""
    from raft_sim_tpu.trace import checker as tchecker
    from raft_sim_tpu.trace import history as thistory

    hist = thistory.load(directory)
    if not hist.events:
        raise SystemExit(
            f"{directory}: no trace.jsonl events (run with --trace to record)"
        )
    total = sum(len(v) for v in hist.events.values())
    dropped = sum(hist.dropped.values())
    print(
        f"protocol trace: {directory}\n"
        f"  {total} events, {len(hist.events)} clusters, "
        f"{hist.n_windows} windows, {dropped} dropped"
        + ("" if hist.complete else "  [INCOMPLETE]"),
        file=out,
    )
    sel = sorted(hist.events) if clusters is None else list(clusters)
    for c in sel:
        evs = hist.events.get(c, [])
        if not evs:
            continue
        print(f"\n  cluster {c}: {len(evs)} events"
              + (f" ({hist.dropped.get(c, 0)} dropped)" if hist.dropped.get(c) else ""),
              file=out)
        lines = list(thistory.timeline_lines(hist, c))
        shown = lines if limit is None or len(lines) <= limit else lines[:limit]
        for line in shown:
            print(f"    {line}", file=out)
        if len(lines) > len(shown):
            print(f"    ... {len(lines) - len(shown)} more "
                  f"(--trace-limit 0 for all)", file=out)
    rep = tchecker.check_history(hist)
    print("\n  history checks:", file=out)
    for name, r in rep.results.items():
        verdict = {True: "ok", False: "VIOLATED", None: "undecided"}[r.ok]
        print(f"    {name:<22} {verdict}" + (f"  ({r.note})" if r.note else ""),
              file=out)
    if perfetto:
        doc = thistory.chrome_trace(hist, clusters=sel)
        with open(perfetto, "w") as f:
            json.dump(doc, f)
        print(f"\n  perfetto trace written: {perfetto} "
              f"({len(doc['traceEvents'])} events; open in ui.perfetto.dev)",
              file=out)


def report_perf_dir(directory: str, out=None) -> None:
    """Render a telemetry directory's perf.jsonl (obs.ChunkTimer rows): the
    per-chunk attribution table, the steady-state rollup, and the
    reconciliation of measured throughput against the cost-model pins."""
    from raft_sim_tpu.obs import reconcile

    rows = reconcile.read_perf(directory)
    if not rows:
        raise SystemExit(
            f"{directory}: no perf.jsonl (run with --perf to record one)"
        )
    res = reconcile.reconcile_perf_dir(directory)
    s = res["summary"]
    print(f"perf stream: {directory} ({len(rows)} chunks, "
          f"{s['steady_chunks']} steady)", file=out)
    cols = ("chunk", "ticks", "wall_s", "dispatch_s", "host_s",
            "device_wait_s", "gap_s")
    print("  " + " ".join(f"{c:>13}" for c in cols) + "  flags", file=out)
    for r in rows:
        flags = "warmup" if r.get("warmup") else ""
        if r.get("recompiled"):
            flags += " RECOMPILED"
        print("  " + " ".join(f"{_fmt(r[c]):>13}" for c in cols)
              + f"  {flags}", file=out)
    print("\n  steady state:", file=out)
    for k in ("steady_ticks", "steady_wall_s", "steady_cluster_ticks_per_s",
              "device_wait_s", "host_gap_s", "host_gap_frac",
              "live_bytes_peak", "recompiled_after_warmup"):
        v = s.get(k)
        v = str(v) if isinstance(v, bool) else _fmt(v)
        print(f"  {k:28} {v:>14}", file=out)
    for name, size in (s.get("jit_cache_final") or {}).items():
        print(f"  jit cache {name:28} {size}", file=out)
    _print_reconciliation([res["reconciliation"]], out=out)


def _print_reconciliation(rows: list[dict], out=None) -> None:
    print("\n  measured vs predicted (cost-model pins):", file=out)
    cols = ("config", "measured_ticks_per_s", "predicted_roofline_ticks_per_s",
            "roofline_fraction", "achieved_bytes_per_s", "anchor")
    hdr = ("config", "measured t/s", "predicted t/s", "roofline frac",
           "achieved B/s", "anchor")
    print("  " + " ".join(f"{h:>16}" for h in hdr), file=out)
    for r in rows:
        vals = []
        for c in cols:
            v = r.get(c)
            if c == "anchor":
                vals.append("ANCHOR" if v else "non-anchor")
            elif isinstance(v, str):
                vals.append(v)
            else:
                vals.append(_fmt(v))
        print("  " + " ".join(f"{v:>16}" for v in vals), file=out)
    for r in rows:
        for reason in r.get("non_anchor_reasons", []):
            print(f"    {r['config']}: non-anchor: {reason}", file=out)
        for note in r.get("notes", []):
            print(f"    {r['config']}: note: {note}", file=out)


def report_measurement(path: str, out=None) -> None:
    """Render a MEASUREMENT_r*.json artifact (bench.py --measurement-pass):
    the measured-vs-predicted roofline table, the three A/B deltas, and the
    BENCH_r01 -> now trajectory with the unmeasured gap flagged."""
    with open(path) as f:
        doc = json.load(f)
    if doc.get("schema") != "measurement-pass-v1":
        raise SystemExit(
            f"{path}: not a measurement-pass artifact "
            f"(schema {doc.get('schema')!r})"
        )
    print(
        f"measurement pass: {path}\n"
        f"  backend={doc.get('backend')} jax={doc.get('jax_version')} "
        f"smoke={doc.get('smoke')} repeats={doc.get('repeats')}",
        file=out,
    )
    rec = doc.get("reconciliation") or {}
    _print_reconciliation(rec.get("rows", []), out=out)
    for note in rec.get("notes", []):
        print(f"  note: {note}", file=out)

    print("\n  A/B deltas:", file=out)
    ab = doc.get("ab") or {}
    for key in ("fault_lattice", "serve_offer_plane",
                "layout_dense_vs_compact", "durability",
                "transfer_during_joint"):
        arm = ab.get(key) or {}
        ratio = arm.get("on_over_off_ticks_per_s")
        print(f"  {key:18} on/off throughput ratio: {_fmt(ratio)} "
              f"({arm.get('label', '')})", file=out)
        for note in arm.get("notes", []):
            print(f"    note: {note}", file=out)
    bp = ab.get("bitpack_vs_r05") or {}
    print("  bitpack_vs_r05     measured/r05 per config: "
          + (", ".join(f"{k}={_fmt(v)}" for k, v in
                       (bp.get("measured_over_r05") or {}).items())
             or "(not computable on this backend/sizing)"), file=out)
    for note in bp.get("notes", []):
        print(f"    note: {note}", file=out)

    traj = doc.get("trajectory") or []
    if traj:
        configs = sorted({c for t in traj for c in t.get("ticks_per_s", {})})
        print("\n  trajectory (BENCH_r01 -> now, legacy headline t/s):", file=out)
        print("  " + f"{'artifact':>16}" + " ".join(f"{c:>14}" for c in configs),
              file=out)
        for t in traj:
            vals = [t["ticks_per_s"].get(c) for c in configs]
            print("  " + f"{t['source']:>16}"
                  + " ".join(f"{_fmt(v):>14}" for v in vals), file=out)
        this = {
            n: r.get("steady_ticks_per_s")
            for n, r in (doc.get("matrix") or {}).items() if n in configs
        }
        print("  " + f"{'this pass':>16}"
              + " ".join(f"{_fmt(this.get(c)):>14}" for c in configs)
              + f"  [{doc.get('backend')}{' smoke' if doc.get('smoke') else ''}]",
              file=out)
    for note in doc.get("notes", []):
        print(f"  note: {note}", file=out)


def report_health(directory: str, out=None) -> None:
    """Render a directory's health plane (health.jsonl + alerts.jsonl +
    evidence bundles -- raft_sim_tpu/health, written by any standing loop run
    with health monitoring armed): per-scope SLI rollups, the burn-rate
    state-machine history, each alert transition with its triaged worst
    clusters, and the frozen evidence bundles' inventories."""
    hpath = os.path.join(directory, "health.jsonl")
    if not os.path.isfile(hpath):
        raise SystemExit(
            f"{directory}: no health.jsonl (arm monitoring with --health on "
            "run/serve/scenario farm, or Session.attach_health)"
        )
    with open(hpath) as f:
        health = [json.loads(line) for line in f if line.strip()]
    apath = os.path.join(directory, "alerts.jsonl")
    alerts = []
    if os.path.isfile(apath):
        with open(apath) as f:
            alerts = [json.loads(line) for line in f if line.strip()]

    scopes: dict[str, list[dict]] = {}
    for row in health:
        scopes.setdefault(row["scope"], []).append(row)
    print(f"health plane: {directory}\n"
          f"  {len(health)} evals across {len(scopes)} scopes, "
          f"{len(alerts)} alert transitions", file=out)
    for scope, rows in scopes.items():
        last = rows[-1]
        print(f"\n  scope {scope}: {len(rows)} evals, "
              f"{sum(r['ticks'] for r in rows)} ticks, "
              f"last status {last['status'].upper()}", file=out)
        for k, v in sorted(last.get("slis", {}).items()):
            # One measurement group per objective: render the group's
            # key=value pairs on the objective's line.
            body = " ".join(f"{kk}={_fmt(vv)}" for kk, vv in v.items())
            print(f"    {k:16} {body}", file=out)
        burns = last.get("burn") or {}
        if burns:
            print(f"    {'objective':>16} {'rule':>8} {'burn short':>12} "
                  f"{'burn long':>12}", file=out)
            for obj, by_rule in sorted(burns.items()):
                for rule, (short, long_) in sorted(by_rule.items()):
                    print(f"    {obj:>16} {rule:>8} "
                          f"{_fmt(short):>12} {_fmt(long_):>12}", file=out)

    if alerts:
        print("\n  alert transitions:", file=out)
        cols = ("eval", "scope", "objective", "rule", "state",
                "burn_short", "burn_long")
        print("  " + " ".join(f"{c:>11}" for c in cols)
              + "  worst clusters / evidence", file=out)
        for a in alerts:
            worst = ",".join(
                str(w["cluster"]) + ("*" if w.get("outlier") else "")
                for w in a.get("worst_clusters", [])
            ) or "-"
            ev = a.get("evidence") or ""
            cells = [
                v if isinstance(v, str) else _fmt(v)
                for v in (a.get(c) for c in cols)
            ]
            print("  " + " ".join(f"{v:>11}" for v in cells)
                  + f"  {worst}" + (f" -> {ev}" if ev else ""), file=out)
        print("  (* = robust outlier: modified z-score above the spec "
              "threshold)", file=out)

    bundles = sorted(
        d for d in os.listdir(directory)
        if d.startswith("evidence_")
        and os.path.isdir(os.path.join(directory, d))
    )
    for name in bundles:
        path = os.path.join(directory, name)
        with open(os.path.join(path, "alert.json")) as f:
            doc = json.load(f)
        al = doc.get("alert") or {}
        print(f"\n  evidence bundle {name}: "
              f"{al.get('scope')}/{al.get('objective')}/{al.get('rule')} "
              f"at eval {al.get('eval')}", file=out)
        for fname in doc.get("files", []):
            size = os.path.getsize(os.path.join(path, fname))
            print(f"    {fname:24} {size:>10,} bytes", file=out)
        refs = doc.get("refs") or {}
        if refs:
            print("    refs: "
                  + " ".join(f"{k}={v}" for k, v in sorted(refs.items())),
                  file=out)


def report_multichip(paths: list[str], out=None) -> None:
    """Render MULTICHIP_r*.json artifacts (tools/multihost_check.py --out;
    schema'd by telemetry_sink.validate_multichip) as a trajectory table:
    one row per artifact -- parity verdict, shape, sharded vs reference
    throughput, and the Pass C per-device byte price. Legacy rc-only stubs
    (pre-multichip-v2) are listed as such, never silently skipped."""
    cols = ("artifact", "match", "dev x proc", "batch", "ticks",
            "sharded t/s", "reference t/s", "overhead", "B/tick/dev")
    print("multichip proof artifacts:", file=out)
    print("  " + " ".join(f"{c:>14}" for c in cols), file=out)
    notes = []
    for path in paths:
        name = os.path.basename(path)
        errors = sink.validate_multichip(path)
        with open(path) as f:
            doc = json.load(f)
        if "schema" not in doc:
            print(f"  {name:>14}" + f"{'(legacy rc-only stub)':>29}"
                  + f"{_fmt(doc.get('n_devices')) + ' dev':>15}"
                  + f"{'rc=' + _fmt(doc.get('rc')):>15}", file=out)
            notes.append(f"{name}: legacy stub -- regenerate with "
                         "tools/multihost_check.py --out")
            continue
        if errors:
            for e in errors:
                notes.append(f"INVALID: {e}")
            continue
        ratio = (
            round(doc["reference_ticks_per_s"] / doc["throughput_ticks_per_s"], 3)
            if doc["throughput_ticks_per_s"] and
            doc.get("reference_ticks_per_s") else None
        )
        vals = (
            name, "MATCH" if doc["match"] else "MISMATCH",
            f"{doc['n_devices']}x{doc['n_processes']}", _fmt(doc["batch"]),
            _fmt(doc["ticks"]), _fmt(doc["throughput_ticks_per_s"]),
            _fmt(doc.get("reference_ticks_per_s")), _fmt(ratio),
            _fmt(doc["per_device_bytes_per_tick"]),
        )
        print("  " + " ".join(f"{v:>14}" for v in vals), file=out)
        notes.append(
            f"{name}: platform={doc['platform']} "
            f"violations={doc['violations']} "
            f"parity={doc['parity_hash'][:12]}..."
            + (" (cpu rows never anchor the roofline)"
               if doc["platform"] == "cpu" else "")
        )
    for n in notes:
        print(f"  {n}", file=out)


def diff(path_a: str, path_b: str, config: str | None, out=None) -> None:
    label_a, a = load_run(path_a, config)
    label_b, b = load_run(path_b, config)
    keys = [k for k in (
        "violations", "cmds", "msgs", "max_commit", "p50_stable_tick",
        "cluster_ticks_per_s", "steady_ticks_per_s", "repeat_cv",
        "predicted_roofline_ticks_per_s",
        "roofline_headroom", "mean_commit_latency", "p50_commit_latency",
        "lat_p50", "lat_p95", "lat_p99", "lat_excluded", "noop_blocked",
        "lm_skipped_pairs", "multi_leader",
    ) if k in a or k in b]
    print(f"A: {label_a}\nB: {label_b}\n", file=out)
    print(f"{'metric':22} {'A':>14} {'B':>14} {'delta':>14}", file=out)
    for k in keys:
        va, vb = a.get(k), b.get(k)
        if isinstance(va, (int, float)) and isinstance(vb, (int, float)):
            d = _fmt(round(vb - va, 6))
        else:
            d = "-"
        print(f"{k:22} {_fmt(va):>14} {_fmt(vb):>14} {d:>14}", file=out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="telemetry directory (or two with --diff)")
    ap.add_argument("--validate", action="store_true",
                    help="schema-check the directory and exit (nonzero on errors)")
    ap.add_argument("--diff", action="store_true",
                    help="compare two runs (telemetry dirs or bench JSON files)")
    ap.add_argument("--config", default=None,
                    help="matrix row to read from a bench artifact (default: headline)")
    ap.add_argument("--windows", type=int, default=8,
                    help="window-table rows to show (default 8)")
    ap.add_argument("--flight", type=int, default=None, metavar="CLUSTER",
                    help="render flight_<CLUSTER>.jsonl via trace.info_lines")
    ap.add_argument("--perf", action="store_true",
                    help="runtime-perf report: a telemetry directory's "
                         "perf.jsonl (chunk attribution + reconciliation vs "
                         "the cost-model pins) or a MEASUREMENT_r*.json "
                         "artifact (measured-vs-predicted roofline table, "
                         "A/B deltas, BENCH trajectory)")
    ap.add_argument("--trace", action="store_true",
                    help="protocol-trace report: per-cluster event timelines "
                         "from trace.jsonl plus the whole-history checker "
                         "verdicts (raft_sim_tpu/trace)")
    ap.add_argument("--health", action="store_true",
                    help="health-plane report: per-scope SLI rollups and "
                         "burn-rate history from health.jsonl, alert "
                         "transitions with triaged worst clusters from "
                         "alerts.jsonl, and evidence-bundle inventories "
                         "(raft_sim_tpu/health; any directory a monitored "
                         "run streamed into)")
    ap.add_argument("--multichip", action="store_true",
                    help="render MULTICHIP_r*.json proof artifacts "
                         "(tools/multihost_check.py --out) as a trajectory "
                         "table: parity verdict, sharded vs reference "
                         "throughput, per-device byte price")
    ap.add_argument("--trace-cluster", type=int, action="append", default=None,
                    metavar="C", help="restrict --trace to cluster C (repeatable)")
    ap.add_argument("--trace-limit", type=int, default=40,
                    help="timeline lines shown per cluster (0 = all; default 40)")
    ap.add_argument("--perfetto", metavar="OUT.json", default=None,
                    help="with --trace: also export the timelines as "
                         "Chrome-trace/Perfetto JSON (one track per node, "
                         "events named by kind; open in ui.perfetto.dev)")
    args = ap.parse_args(argv)

    if args.multichip:
        if not args.paths:
            ap.error("--multichip needs at least one MULTICHIP_r*.json path")
        report_multichip(args.paths)
        return 0

    if args.health:
        if len(args.paths) != 1:
            ap.error("--health needs exactly one directory")
        # validate_health_files alone, not the full sink gate: farm out-dirs
        # carry health streams without ever being telemetry directories.
        errors = sink.validate_health_files(args.paths[0])
        if errors:
            for e in errors:
                print(f"INVALID: {e}", file=sys.stderr)
            return 1
        report_health(args.paths[0])
        return 0

    if args.trace:
        if len(args.paths) != 1:
            ap.error("--trace needs exactly one telemetry directory")
        errors = sink.validate(args.paths[0])
        if errors:
            for e in errors:
                print(f"INVALID: {e}", file=sys.stderr)
            return 1
        report_trace(
            args.paths[0], clusters=args.trace_cluster,
            limit=args.trace_limit or None, perfetto=args.perfetto,
        )
        return 0

    if args.perf:
        if len(args.paths) != 1:
            ap.error("--perf needs exactly one path (telemetry dir or "
                     "MEASUREMENT_r*.json)")
        path = args.paths[0]
        if os.path.isdir(path):
            errors = sink.validate(path)
            if errors:
                for e in errors:
                    print(f"INVALID: {e}", file=sys.stderr)
                return 1
            report_perf_dir(path)
        else:
            report_measurement(path)
        return 0

    if args.diff:
        if len(args.paths) != 2:
            ap.error("--diff needs exactly two paths")
        diff(args.paths[0], args.paths[1], args.config)
        return 0
    if len(args.paths) != 1:
        ap.error("need exactly one telemetry directory")
    directory = args.paths[0]
    errors = sink.validate(directory)
    if errors:
        for e in errors:
            print(f"INVALID: {e}", file=sys.stderr)
        return 1
    if args.validate:
        print(f"{directory}: schema v{sink.TELEMETRY_SCHEMA_VERSION} OK")
        return 0
    if args.flight is not None:
        render_flight(directory, args.flight)
        return 0
    report(directory, args.windows)
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # report piped into head/less and closed early
        sys.exit(0)
