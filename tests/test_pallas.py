"""Pallas engine parity (interpret mode on CPU).

The Pallas engine runs the identical step_b body inside a pallas_call gridded over
cluster blocks, so parity here extends the oracle -> raft.py -> raft_batched.py chain
to the kernelized execution path. On this image's TPU toolchain the compiled path is
blocked by a compiler limitation, which demoted the engine to experiments/ (see
experiments/pallas_engine.py docstring); interpret mode exercises the full
pallas_call machinery (blocking, ref plumbing, shape lifting) on CPU and keeps the
tick kernel pallas-compatible for the day the toolchain can lower it.
"""

import jax
import numpy as np
import pytest

from raft_sim_tpu import RaftConfig, init_batch
from raft_sim_tpu.experiments import pallas_engine
from raft_sim_tpu.models import raft_batched
from raft_sim_tpu.sim import faults, scan


@pytest.mark.parametrize(
    "cfg",
    [
        # Slow tier (870s budget): n3-small + the run-loop parity below keep
        # the interpret-mode engine pinned in tier-1.
        pytest.param(
            RaftConfig(n_nodes=5, client_interval=4, drop_prob=0.2),
            id="n5-faults",
            marks=pytest.mark.slow,
        ),
        pytest.param(RaftConfig(n_nodes=3, log_capacity=8, max_entries_per_rpc=2), id="n3-small"),
    ],
)
def test_step_pallas_matches_step_b(cfg):
    B = 8
    state = init_batch(cfg, jax.random.key(0), B)
    keys = jax.random.split(jax.random.key(1), B)
    inp = jax.vmap(lambda k, now: faults.make_inputs(cfg, k, now))(keys, state.now)
    s_t = raft_batched.to_batch_minor(state)
    i_t = raft_batched.to_batch_minor(inp)

    ref = raft_batched.step_b(cfg, s_t, i_t)
    got = pallas_engine.step_pallas(cfg, s_t, i_t, block_b=4, interpret=True)
    for a, b in zip(jax.tree.leaves(jax.device_get(ref)), jax.tree.leaves(jax.device_get(got))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_run_pallas_matches_run_batch_minor():
    cfg = RaftConfig(n_nodes=5, client_interval=8)
    B = 8
    state = init_batch(cfg, jax.random.key(2), B)
    keys = jax.random.split(jax.random.key(3), B)

    f_ref, m_ref = jax.jit(lambda s, k: scan.run_batch_minor(cfg, s, k, 60))(state, keys)
    f_pl, m_pl = pallas_engine.run_pallas(cfg, state, keys, 60, 4, True)
    for a, b in zip(jax.tree.leaves(jax.device_get((f_ref, m_ref))), jax.tree.leaves(jax.device_get((f_pl, m_pl)))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_step_pallas_rejects_bad_block():
    cfg = RaftConfig(n_nodes=3)
    state = init_batch(cfg, jax.random.key(0), 6)
    keys = jax.random.split(jax.random.key(1), 6)
    inp = jax.vmap(lambda k, now: faults.make_inputs(cfg, k, now))(keys, state.now)
    with pytest.raises(ValueError, match="multiple of"):
        pallas_engine.step_pallas(
            cfg,
            raft_batched.to_batch_minor(state),
            raft_batched.to_batch_minor(inp),
            block_b=4,
            interpret=True,
        )
