"""ScenarioGenome: per-cluster batched fault parameters as traced data.

A genome is one point in fault space -- drop rate, rolling-partition period
and probability, crash probability and down-span, clock-skew probability,
client cadence -- encoded so the whole tick stays integer-only (the dtype
policy types.py states and the analyzer enforces): every probability is a
uint32 Bernoulli THRESHOLD (`faults.p_to_u32`; an event fires iff a fresh
uint32 draw is < the threshold), every cadence/span an int32. Each leaf
carries a leading `[S]` segment axis (S = 1 for an unphased genome;
program.py builds S > 1 nemesis timelines); `genome.broadcast` tiles to the
public batched `[B, S]` layout, where row b is cluster b's private fault
setting -- the heterogeneous-fleet form `sim/scan` vmaps over.

The genome deliberately covers only TUNING knobs. Structural config --
topology, log shape, timer windows, the client routing model, feature gates
like pre_vote/compaction -- stays on RaftConfig, because those legitimately
change the compiled program; a genome must never fork a compile
(analysis/jaxpr_audit.py, rule recompile-fork, scenario pairs).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_sim_tpu.sim.faults import p_to_u32
from raft_sim_tpu.utils.config import RaftConfig

U32_SPAN = float(1 << 32)


class ScenarioGenome(NamedTuple):
    """Per-segment fault parameters, `[S]` per leaf (batched: `[B, S]`).

    Field order is load-bearing: `analysis/policy.py:scenario_genome_leaves`
    and the traffic audit enumerate `_fields`, and sim/faults.py duck-types
    the attribute names (sim/ never imports this module)."""

    drop: jax.Array  # [S] uint32: per-edge message-drop threshold
    part_period: jax.Array  # [S] int32: rolling-partition window ticks (0 = off)
    part: jax.Array  # [S] uint32: per-window partition-activation threshold
    crash: jax.Array  # [S] uint32: per-window per-node crash threshold
    crash_down: jax.Array  # [S] int32: max down-span ticks (uniform 1..this)
    skew: jax.Array  # [S] uint32: clock-skew threshold (half stall, half jump)
    client_interval: jax.Array  # [S] int32: client offer cadence (0 = none)
    # Reconfiguration-plane cadences (raft_sim_tpu/reconfig): membership
    # change / leadership transfer / ReadIndex read offers. Tuning knobs like
    # client_interval -- the STRUCTURAL gate stays on RaftConfig
    # (reconfig_interval/transfer_interval/read_interval > 0), the genome
    # retimes commands within it (validate() enforces the pairing).
    reconfig_interval: jax.Array  # [S] int32: membership-toggle cadence (0 = none)
    transfer_interval: jax.Array  # [S] int32: leadership-transfer cadence (0 = none)
    read_interval: jax.Array  # [S] int32: ReadIndex offer cadence (0 = none)
    # Disk-fault axes (raft_sim_tpu/storage): fsync cadence / latency-jitter
    # stalls / torn-tail truncation on restart. Tuning knobs over the durable
    # storage plane -- the STRUCTURAL gate stays on RaftConfig
    # (fsync_interval > 0), the genome retimes flushes and reshapes the crash
    # lattice within it (validate() enforces the pairing).
    fsync_interval: jax.Array  # [S] int32: fsync cadence ticks (0 = plane off)
    fsync_jitter: jax.Array  # [S] uint32: per-node flush-stall threshold
    torn: jax.Array  # [S] uint32: torn-tail-on-restart threshold
    torn_span: jax.Array  # [S] int32: max extra entries a torn tail rejects


# The threshold-encoded (uint32) fields; everything else is int32. The ONE
# source of the dtype partition -- from_segments/from_raw here and the
# analyzer's genome avals (analysis/policy.scenario_genome_leaves,
# jaxpr_audit._genome_avals) all derive from it, so a field add/rename cannot
# silently fork the audited program's dtypes from the real one's.
U32_FIELDS = frozenset({"drop", "part", "crash", "skew", "fsync_jitter", "torn"})


def leaf_dtype(field: str):
    """The genome leaf dtype for a ScenarioGenome field name."""
    return jnp.uint32 if field in U32_FIELDS else jnp.int32


def segment(
    *,
    drop_prob: float = 0.0,
    partition_period: int = 0,
    partition_prob: float = 0.0,
    crash_prob: float = 0.0,
    crash_down_ticks: int = 1,
    clock_skew_prob: float = 0.0,
    client_interval: int = 0,
    reconfig_interval: int = 0,
    transfer_interval: int = 0,
    read_interval: int = 0,
    fsync_interval: int = 0,
    fsync_jitter_prob: float = 0.0,
    torn_tail_prob: float = 0.0,
    lost_suffix_span: int = 1,
) -> dict:
    """One segment's parameters in HUMAN units (probabilities as floats),
    encoded to the genome's integer fields. The declarative scenario-file
    vocabulary (program.py) is exactly these keyword names."""
    return {
        "drop": p_to_u32(drop_prob),
        "part_period": int(partition_period),
        "part": p_to_u32(partition_prob),
        "crash": p_to_u32(crash_prob),
        "crash_down": int(crash_down_ticks),
        "skew": p_to_u32(clock_skew_prob),
        "client_interval": int(client_interval),
        "reconfig_interval": int(reconfig_interval),
        "transfer_interval": int(transfer_interval),
        "read_interval": int(read_interval),
        "fsync_interval": int(fsync_interval),
        "fsync_jitter": p_to_u32(fsync_jitter_prob),
        "torn": p_to_u32(torn_tail_prob),
        "torn_span": int(lost_suffix_span),
    }


def from_segments(segments: list[dict]) -> ScenarioGenome:
    """Stack encoded segment dicts (see `segment`) into an `[S]` genome."""
    if not segments:
        raise ValueError("a genome needs at least one segment")
    return ScenarioGenome(
        **{
            f: jnp.asarray([s[f] for s in segments], leaf_dtype(f))
            for f in ScenarioGenome._fields
        }
    )


def from_config(cfg: RaftConfig) -> ScenarioGenome:
    """The homogeneous genome replicating cfg's fault scalars (S = 1). A
    fleet running this genome is bit-exact with the scalar path for state,
    metrics, and telemetry windows (tests/test_scenario.py) -- the parity
    anchor for everything the search mutates away from."""
    if cfg.drop_prob_uniform:
        raise ValueError(
            "drop_prob_uniform draws a hidden per-cluster rate; genomes "
            "express per-cluster heterogeneity directly -- give each cluster "
            "its own drop threshold instead"
        )
    return from_segments([
        segment(
            drop_prob=cfg.drop_prob,
            partition_period=cfg.partition_period,
            partition_prob=cfg.partition_prob,
            crash_prob=cfg.crash_prob,
            crash_down_ticks=cfg.crash_down_ticks if cfg.crash_prob > 0 else 1,
            clock_skew_prob=cfg.clock_skew_prob,
            client_interval=cfg.client_interval,
            reconfig_interval=cfg.reconfig_interval,
            transfer_interval=cfg.transfer_interval,
            read_interval=cfg.read_interval,
            fsync_interval=cfg.fsync_interval,
            fsync_jitter_prob=cfg.fsync_jitter_prob,
            torn_tail_prob=cfg.torn_tail_prob,
            lost_suffix_span=cfg.lost_suffix_span,
        )
    ])


def broadcast(genome: ScenarioGenome, batch: int) -> ScenarioGenome:
    """Tile an `[S]` genome to the batched `[B, S]` fleet layout (every
    cluster gets the same setting; search.py builds heterogeneous rows)."""
    return ScenarioGenome(
        *(jnp.broadcast_to(leaf[None], (batch,) + leaf.shape) for leaf in genome)
    )


def stack_rows(rows: list[ScenarioGenome]) -> ScenarioGenome:
    """Stack B per-cluster `[S]` genomes into the batched `[B, S]` layout --
    the heterogeneous-fleet constructor (one row per cluster)."""
    return ScenarioGenome(
        *(jnp.stack([getattr(r, f) for r in rows]) for f in ScenarioGenome._fields)
    )


def validate(cfg: RaftConfig, genome: ScenarioGenome) -> None:
    """Host-side sanity for an `[S]` or `[B, S]` genome against its base
    config. Raises ValueError naming the first offense."""
    shapes = {f: np.asarray(getattr(genome, f)).shape for f in genome._fields}
    if len(set(shapes.values())) != 1:
        raise ValueError(f"genome leaves disagree on shape: {shapes}")
    (shape,) = set(shapes.values())
    if len(shape) not in (1, 2) or shape[-1] < 1:
        raise ValueError(f"genome leaves must be [S] or [B, S] with S >= 1, got {shape}")
    pp = np.asarray(genome.part_period)
    if (pp < 0).any():
        raise ValueError("part_period must be >= 0 (0 disables partitions)")
    cd = np.asarray(genome.crash_down)
    if (cd < 1).any() or (cd > cfg.crash_period).any():
        raise ValueError(
            f"crash_down must lie in [1, crash_period={cfg.crash_period}] "
            "(spans clip at the window edge; see faults.alive_at)"
        )
    ci = np.asarray(genome.client_interval)
    if (ci < 0).any():
        raise ValueError("client_interval must be >= 0 (0 disables the client)")
    if (ci > 0).any() and cfg.client_interval == 0:
        raise ValueError(
            "genome injects client traffic but cfg.client_interval == 0: the "
            "step kernel's commit-latency path is a STRUCTURAL gate (it only "
            "compiles in when the config carries a client workload) -- set a "
            "nonzero cfg.client_interval as the base cadence the genome tunes"
        )
    for field, gate, knob in (
        ("reconfig_interval", cfg.reconfig, "reconfig_interval"),
        ("transfer_interval", cfg.leader_transfer, "transfer_interval"),
        ("read_interval", cfg.read_index, "read_interval"),
    ):
        v = np.asarray(getattr(genome, field))
        if (v < 0).any():
            raise ValueError(f"{field} must be >= 0 (0 disables the stream)")
        if (v > 0).any() and not gate:
            raise ValueError(
                f"genome drives {field} but the config's {knob} is 0: the "
                "reconfiguration-plane handlers are STRUCTURAL gates (they "
                "only compile in when the config enables the extension) -- "
                f"set a nonzero cfg.{knob} as the base cadence the genome "
                "tunes (docs/PROTOCOL.md)"
            )
    fi = np.asarray(genome.fsync_interval)
    if (fi < 0).any():
        raise ValueError("fsync_interval must be >= 0 (0 disables fsync)")
    if (fi > 0).any() and not cfg.durable_storage:
        raise ValueError(
            "genome drives fsync_interval but the config's fsync_interval is "
            "0: the durable storage plane is a STRUCTURAL gate (the durable "
            "watermark carry legs and section-3.8 ack/grant gates only "
            "compile in when the config enables it) -- set a nonzero "
            "cfg.fsync_interval as the base cadence the genome tunes "
            "(raft_sim_tpu/storage)"
        )
    for field in ("fsync_jitter", "torn"):
        v = np.asarray(getattr(genome, field))
        if (v > 0).any() and not cfg.durable_storage:
            raise ValueError(
                f"genome sets {field} but the config's fsync_interval is 0: "
                "disk faults perturb the durable storage plane -- set a "
                "nonzero cfg.fsync_interval as the base cadence they perturb"
            )
    ts = np.asarray(genome.torn_span)
    if (ts < 1).any() or (ts > cfg.log_capacity).any():
        raise ValueError(
            f"torn_span must lie in [1, log_capacity={cfg.log_capacity}] "
            "(the torn-tail draw rejects 1..span extra entries; see "
            "faults._storage_draws)"
        )


def decode(genome: ScenarioGenome) -> list[dict]:
    """`[S]` genome -> human-readable per-segment dicts (thresholds back to
    float probabilities), for reports and JSON artifacts."""
    g = {f: np.asarray(getattr(genome, f)) for f in genome._fields}
    (s_count,) = g["drop"].shape
    return [
        {
            "drop_prob": round(float(g["drop"][i]) / U32_SPAN, 9),
            "partition_period": int(g["part_period"][i]),
            "partition_prob": round(float(g["part"][i]) / U32_SPAN, 9),
            "crash_prob": round(float(g["crash"][i]) / U32_SPAN, 9),
            "crash_down_ticks": int(g["crash_down"][i]),
            "clock_skew_prob": round(float(g["skew"][i]) / U32_SPAN, 9),
            "client_interval": int(g["client_interval"][i]),
            "reconfig_interval": int(g["reconfig_interval"][i]),
            "transfer_interval": int(g["transfer_interval"][i]),
            "read_interval": int(g["read_interval"][i]),
            "fsync_interval": int(g["fsync_interval"][i]),
            "fsync_jitter_prob": round(float(g["fsync_jitter"][i]) / U32_SPAN, 9),
            "torn_tail_prob": round(float(g["torn"][i]) / U32_SPAN, 9),
            "lost_suffix_span": int(g["torn_span"][i]),
        }
        for i in range(s_count)
    ]


def to_raw(genome: ScenarioGenome) -> dict:
    """Exact integer leaves as JSON-ready lists -- the bit-exact half of a
    repro artifact (decode() rounds; this does not)."""
    return {f: np.asarray(getattr(genome, f)).tolist() for f in genome._fields}


# The only fields from_raw may backfill when absent, with the value that
# reproduces the old trajectory exactly: pre-v22 artifacts predate the
# reconfiguration-plane cadences and pre-v25 artifacts the disk-fault axes;
# an absent cadence/threshold decodes as its disabled value (0 -- disabled
# streams draw nothing the kernels consume) and an absent torn_span as the
# no-op span floor 1 (validate() requires span >= 1; with the torn threshold
# 0 it is never consumed). CORE fields stay strict: a missing one is artifact
# corruption and must raise, not silently replay a different scenario.
_OPTIONAL_FIELDS = {
    "reconfig_interval": 0,
    "transfer_interval": 0,
    "read_interval": 0,
    "fsync_interval": 0,
    "fsync_jitter": 0,
    "torn": 0,
    "torn_span": 1,
}


def from_raw(raw: dict) -> ScenarioGenome:
    """Inverse of to_raw: rebuild the exact genome from artifact integers
    (see _OPTIONAL_FIELDS for the pre-v22/pre-v25 compatibility rule)."""
    shape = np.asarray(raw["drop"]).shape
    return ScenarioGenome(
        **{
            f: jnp.asarray(
                raw.get(f, np.full(shape, _OPTIONAL_FIELDS[f], dtype=int).tolist())
                if f in _OPTIONAL_FIELDS
                else raw[f],
                leaf_dtype(f),
            )
            for f in ScenarioGenome._fields
        }
    )
