"""HealthMonitor: the streaming evaluator every standing loop folds in.

One monitor watches one SCOPE -- the whole fleet, one tenant's cluster
slice, or a farm generation stream -- and consumes exactly what the loop's
sink path already exports: stacked WindowRecords (windowed loops), cumulative
RunMetrics deltas (the plain chunked loop), and the ChunkTimer's perf rows.
Every `eval_windows` window units it computes the SLIs (sli.py), advances the
burn-rate state machines (burn.py), appends one health.jsonl line, and on
each alert transition appends an alerts.jsonl line -- firing transitions
triage the culprit clusters (triage.py) and freeze an evidence bundle
(evidence.py) with whatever the loop's `capture` hook can snapshot (live
flight rings, run refs).

Bit-exactness contract: a monitor only ever READS host copies of device
outputs the loop had already fetched (or fetches its own read-only copy on
the plain path). It never touches the carry, never adds a lowering, never
changes a dispatch -- an instrumented run's trajectories, goldens, and jit
cache are byte-identical to a plain run's. Multiple monitors (serve's fleet +
per-tenant set) share one HealthWriter so the streams stay single-file with a
`scope` column, per-scope eval indices contiguous (telemetry_sink.validate
checks this).
"""

from __future__ import annotations

import os
import shutil

import numpy as np

from raft_sim_tpu.health import burn as burn_mod
from raft_sim_tpu.health import evidence as evidence_mod
from raft_sim_tpu.health import sli as sli_mod
from raft_sim_tpu.health import triage as triage_mod
from raft_sim_tpu.health.spec import load_spec


class HealthWriter:
    """Appender for one directory's health.jsonl / alerts.jsonl + the
    evidence_NNNN allocator. Creating one truncates the streams and removes
    stale evidence dirs (telemetry-sink discipline: a rebuilt run must not
    inherit another run's alerts). append_health/append_alert are the
    streams' REGISTERED single writers: analysis Pass D's `race-sink-writer`
    rule gates any second appender (monitors sharing one directory must
    share one HealthWriter, as ServeSession's per-tenant monitors do)."""

    def __init__(self, directory: str):
        import json

        self._json = json
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.health_path = os.path.join(directory, "health.jsonl")
        self.alerts_path = os.path.join(directory, "alerts.jsonl")
        open(self.health_path, "w").close()
        open(self.alerts_path, "w").close()
        for name in sorted(os.listdir(directory)):
            p = os.path.join(directory, name)
            if name.startswith("evidence_") and os.path.isdir(p):
                shutil.rmtree(p)
        self._evidence_n = 0

    def append_health(self, row: dict) -> None:
        with open(self.health_path, "a") as f:
            f.write(self._json.dumps(row) + "\n")

    def append_alert(self, row: dict) -> None:
        with open(self.alerts_path, "a") as f:
            f.write(self._json.dumps(row) + "\n")

    def next_evidence_dir(self) -> str:
        path = os.path.join(self.directory, f"evidence_{self._evidence_n:04d}")
        self._evidence_n += 1
        return path


# RunMetrics counters that are additive across ticks: the plain chunked
# loop's per-chunk deltas of these reconstruct window-unit counters.
_ADDITIVE = (
    "violations", "total_cmds", "reads_served", "lat_sum", "lat_cnt",
    "lat_hist", "read_hist", "fsync_lag_sum",
)

# The per-cluster arrays of a window unit (everything except start/ticks).
UNIT_ARRAYS = (
    "violations", "leaderless", "cmds", "reads", "lat_sum", "lat_cnt",
    "lat_hist", "read_hist", "fsync_lag_sum", "fsync_lag_max",
)


def slice_units(units: list[dict], lo: int, hi: int) -> list[dict]:
    """A tenant's [lo, hi) cluster-slice view of window units -- numpy views,
    no copies: the serve loop computes units once per chunk and fans them
    out, the same single-fetch discipline as its window export."""
    out = []
    for u in units:
        v = dict(u)
        for k in UNIT_ARRAYS:
            v[k] = u[k][lo:hi]
        out.append(v)
    return out


class HealthMonitor:
    """Streaming SLO evaluation for one scope (class docstring above).

    `perf` is an obs.ChunkTimer whose rows are consumed incrementally at each
    eval (the runtime SLIs); `capture` is the loop's evidence hook, called on
    each firing transition as capture(alert, clusters) -> {"flights":
    {cluster: (ticks, StepInfo)}, "refs": {...}} -- both optional."""

    def __init__(
        self,
        spec,
        *,
        batch: int,
        writer: HealthWriter,
        scope: str = "fleet",
        cluster_base: int = 0,
        perf=None,
        capture=None,
    ):
        self.spec = load_spec(spec) if not isinstance(spec, dict) else spec
        self.batch = int(batch)
        self.writer = writer
        self.scope = scope
        self.cluster_base = int(cluster_base)
        self.perf = perf
        self.capture = capture
        self.engine = burn_mod.BurnEngine(self.spec)
        self.alerts: list[dict] = []
        self._units: list[dict] = []
        self._eval = 0
        self._windows_seen = 0
        self._perf_seen = 0
        self._cum: dict | None = None
        self._prev_done = 0
        self._tick_base = 0  # absolute offset across begin_run() calls

    # ------------------------------------------------------------ observers

    def observe_records(self, records) -> None:
        """Feed one chunk's stacked WindowRecord (already on host from the
        loop's own device_get; leaves [B, n_windows, ...])."""
        from raft_sim_tpu.sim import telemetry

        self.observe_units(telemetry.window_cluster_counters(records))

    def observe_units(self, units: list[dict]) -> None:
        """Feed pre-split window units (telemetry.window_cluster_counters
        output) -- the serve loop splits once and fans the SAME units to the
        fleet monitor and each tenant's slice_units view."""
        self._units.extend(units)
        self._drain()

    def begin_run(self) -> None:
        """Plain-path epoch mark: each `run_chunked` call restarts its
        cumulative metrics and tick counter from zero, so the delta baseline
        must restart with it (and the absolute window offset carries on).
        Call before every run_chunked whose callback feeds observe_chunk."""
        self._tick_base += self._prev_done
        self._prev_done = 0
        self._cum = None

    def observe_chunk(self, done: int, metrics) -> None:
        """Feed the plain chunked loop's cumulative RunMetrics: per-chunk
        deltas of the additive counters become one window unit per chunk
        (the chunk is this path's window). Availability is coarser here --
        with no per-window fold, `leaderless` marks clusters that have never
        elected AT ALL (first_leader_tick still NEVER), the recoverable
        signal without touching traced code."""
        from raft_sim_tpu.sim import telemetry

        cum = {
            f: np.asarray(getattr(metrics, f)).astype(np.int64)
            for f in _ADDITIVE
        }
        first = np.asarray(metrics.first_leader_tick)
        prev = self._cum or {f: np.zeros_like(v) for f, v in cum.items()}
        delta = {f: cum[f] - prev[f] for f in _ADDITIVE}
        self._units.append({
            "start": self._tick_base + self._prev_done,
            "ticks": int(done) - self._prev_done,
            "violations": delta["violations"],
            "leaderless": first == telemetry.NEVER,
            "cmds": delta["total_cmds"],
            "reads": delta["reads_served"],
            "lat_sum": delta["lat_sum"],
            "lat_cnt": delta["lat_cnt"],
            "lat_hist": delta["lat_hist"],
            "read_hist": delta["read_hist"],
            "fsync_lag_sum": delta["fsync_lag_sum"],
            # RunMetrics.fsync_lag_max is a RUNNING max, so its delta is
            # meaningless; the chunk window reports the cumulative max --
            # conservative (a lag spike stays visible in every later chunk),
            # matching this path's coarser leaderless semantics above.
            "fsync_lag_max": np.asarray(metrics.fsync_lag_max).astype(np.int64),
        })
        self._cum = cum
        self._prev_done = int(done)
        self._drain()

    # ------------------------------------------------------------ evaluation

    def _drain(self) -> None:
        e = self.spec["eval_windows"]
        while len(self._units) >= e:
            self._evaluate(self._units[:e])
            del self._units[:e]

    def _evaluate(self, units: list[dict]) -> None:
        rows: list[dict] = []
        if self.perf is not None:
            rows = list(self.perf.rows[self._perf_seen:])
            self._perf_seen = len(self.perf.rows)
        out = sli_mod.compute_slis(self.spec, units, rows)
        transitions = self.engine.update(out["errs"], out["budgets"])
        health_row = {
            "eval": self._eval,
            "scope": self.scope,
            "window_start": int(units[0]["start"]),
            "windows": len(units),
            "ticks": int(sum(u["ticks"] for u in units)),
            "slis": out["slis"],
            "burn": {
                name: self.engine.burns(name, out["budgets"][name])
                for name in self.spec["objectives"]
            },
            "status": self.engine.status(),
        }
        self.writer.append_health(health_row)
        for tr in transitions:
            name = tr["objective"]
            worst: list[dict] = []
            pc = out["percluster"].get(name)
            if pc is not None:
                worst = triage_mod.outlier_clusters(
                    pc, self.spec["worst_k"], self.spec["outlier_score"],
                    self.cluster_base,
                )
            alert = {
                "eval": self._eval,
                "scope": self.scope,
                **tr,
                "worst_clusters": worst,
                "evidence": None,
            }
            if tr["state"] == "firing":
                clusters = [w["cluster"] for w in worst]
                path = self.writer.next_evidence_dir()
                alert["evidence"] = os.path.basename(path)
                cap = {}
                if self.capture is not None:
                    cap = self.capture(alert, clusters) or {}
                evidence_mod.write_bundle(
                    path,
                    alert=alert,
                    objective=self.spec["objectives"][name],
                    window_rows=evidence_mod.window_rows_for(
                        units, clusters, self._windows_seen, self.cluster_base,
                    ),
                    perf_rows=rows,
                    flights=cap.get("flights"),
                    refs=cap.get("refs"),
                )
            self.writer.append_alert(alert)
            self.alerts.append(alert)
        self._windows_seen += len(units)
        self._eval += 1

    # -------------------------------------------------------------- surface

    @property
    def status(self) -> str:
        return self.engine.status()

    def status_line(self) -> str:
        """The live one-liner `driver serve` prints: scope, eval count, worst
        state, and which (objective, rule) pairs are firing."""
        s = self.engine.status()
        line = f"health[{self.scope}] eval {self._eval}: {s}"
        firing = self.engine.firing()
        if firing:
            line += " (" + ", ".join(f"{o}/{r}" for o, r in firing) + ")"
        return line

    def finalize(self) -> dict:
        """Evaluate any partial trailing period, then return the rollup the
        loops fold into their summaries."""
        if self._units:
            self._evaluate(self._units)
            self._units = []
        return {
            "scope": self.scope,
            "evals": self._eval,
            "status": self.engine.status(),
            "alerts": len(self.alerts),
            "fired_objectives": sorted({
                a["objective"] for a in self.alerts if a["state"] == "firing"
            }),
        }
