"""Pass E -- value-range abstract interpretation over the lowered kernels.

An interval abstract interpreter runs over the SAME lowered jaxprs Pass A
audits (`jaxpr_audit.programs`, shared lru-cached lowerings): every integer
leg carries an interval `[lo, hi]`, seeded from config bounds and the
types.py range clauses (`policy.declared_ranges`), and propagated through
the integer op vocabulary of the kernels. Scan carries run a widening fixed
point: legs that stabilize are PROVEN inductive invariants; legs that grow
at a constant measured rate (term, commit totals, metric accumulators) get
a pinned safe horizon -- the tick count before their dtype wraps; anything
else widens to dtype-top, tainted.

The rules (docs/ANALYSIS.md has the catalogue and the legit-range-change
workflow):

- range-dtype-overflow  -- a proven interval exceeds the leg's dtype, or a
  narrowing `astype` whose fit is unproven. Unsigned planes are modular by
  design (RNG words) and never fire; tainted (audit-horizon-widened) int32+
  values are the horizon machinery's jurisdiction and are exempt here.
- range-pack-width      -- the compact layout's planes must fit the
  `ops/tile.pack_width_table` widths (single-sourced: tile.py's plans, this
  pass, and tests/oracle.py's independent restatement read one table).
- range-index-oob       -- a gather/scatter lowered with
  PROMISE_IN_BOUNDS whose index interval is not proven inside the operand
  extents. Clip idioms (max/min on the index) are interval-precise, so an
  explicitly clipped index discharges the proof; dynamic_slice clamps by
  lax semantics and never fires.
- range-annotation-stale -- a declared range not implied by the computed
  interval (the one-tick image escapes it, or it could not be proven
  inductive), or wildly looser than the proven interval.
- range-horizon         -- a monotone PROTOCOL leg (state/mailbox; metric
  and trace accumulators are pinned as diagnostics but not gated -- their
  overflow corrupts telemetry, not trajectories) whose wrap horizon is
  below the 10M-tick soak budget.
- range-golden          -- the meta-rule, mirroring Pass C's cost-golden: a
  missing golden file, a pin drift against tests/golden_ranges.json, or a
  failed derivation. A program whose ranges cannot be derived fires a
  VISIBLE "gates NOT being checked" finding instead of silently skipping.
"""

from __future__ import annotations

import functools
import json
import os

import jax
import numpy as np

from raft_sim_tpu.analysis import jaxpr_audit, policy
from raft_sim_tpu.analysis.findings import Finding
from raft_sim_tpu.utils.config import PRESETS, RaftConfig

try:  # jax >= 0.4.36 exposes the stable alias
    from jax.extend import core as _jcore
except ImportError:  # pragma: no cover
    from jax import core as _jcore

_Literal = _jcore.Literal

RULES = frozenset({
    "range-dtype-overflow",
    "range-pack-width",
    "range-index-oob",
    "range-annotation-stale",
    "range-horizon",
    "range-golden",
})

#: The soak budget a monotone protocol leg must survive (docs/PERF.md).
SOAK_TICKS = 10_000_000
#: Audited horizon: widened monotone legs are valued at 2x the soak budget,
#: so arithmetic DOWNSTREAM of a widened leg is checked with soak headroom.
H_AUDIT = 2 * SOAK_TICKS
#: Horizons are capped here so the golden stays readable (a leg that wraps
#: after 1e12 ticks is "never" at any plausible tick rate).
HORIZON_CAP = 10**12
#: Widening fixed-point iterations for the audited tick loop (rate
#: measurement needs >= 3 history points) / for generic outer loops.
MAX_ITERS = 4
MAX_ITERS_GENERIC = 2
#: A declared range is "wildly looser" than the proven interval when its
#: width exceeds LOOSE_FACTOR x the proven width plus LOOSE_SLACK.
LOOSE_FACTOR = 4
LOOSE_SLACK = 8

DEFAULT_TOLERANCE = {"horizon_rel": 0.0}

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_REGEN = "regenerate with `python tools/check.py --update-goldens` if intended"


def golden_path() -> str:
    return os.path.join(_REPO_ROOT, "tests", "golden_ranges.json")


# ----------------------------------------------------------- interval domain
#
# An abstract value is `(lo, hi, taint)`: lo/hi are Python ints or None
# (None = unbounded / non-integer, e.g. RNG key planes), taint marks values
# derived from an audit-horizon widening (downstream overflow findings on
# int32+ are suppressed for tainted values -- the horizon rule owns them).

_TOP = (None, None, False)


def _dtype_bounds(dtype):
    try:
        dtype = np.dtype(dtype)
    except TypeError:
        return None
    if dtype == np.bool_:
        return (0, 1)
    if dtype.kind in ("i", "u"):
        ii = np.iinfo(dtype)
        return (int(ii.min), int(ii.max))
    return None


def _aval_bounds(aval):
    dt = getattr(aval, "dtype", None)
    return None if dt is None else _dtype_bounds(dt)


def _top(aval):
    # True unknown: dtype bounds are NOT materialized as known values --
    # arithmetic over unknowns must stay unknown, or every add of two
    # unseeded int32 planes would "prove" an overflow.
    return _TOP


def _join(a, b):
    lo = None if a[0] is None or b[0] is None else min(a[0], b[0])
    hi = None if a[1] is None or b[1] is None else max(a[1], b[1])
    return (lo, hi, a[2] or b[2])


def _join_all(vals):
    return functools.reduce(_join, vals) if vals else _TOP


def _known(*vals):
    return all(v[0] is not None and v[1] is not None for v in vals)


def _const_iv(x):
    arr = np.asarray(x)
    if arr.dtype == np.bool_:
        arr = arr.astype(np.int32)
    if arr.dtype.kind not in ("i", "u"):
        return _TOP
    if arr.size == 0:
        return (0, 0, False)
    return (int(arr.min()), int(arr.max()), False)


def _corners(a, b, op):
    t = a[2] or b[2]
    if not _known(a, b):
        return (None, None, t)
    vals = [op(x, y) for x in (a[0], a[1]) for y in (b[0], b[1])]
    return (min(vals), max(vals), t)


def _fmt(v):
    lo = "?" if v[0] is None else v[0]
    hi = "?" if v[1] is None else v[1]
    return f"[{lo}, {hi}]"


def _protocol_leg(name: str) -> bool:
    """Horizon-GATED legs: the protocol state/mailbox planes. Metric/trace
    accumulators and auxiliary legs are pinned in the golden as diagnostics
    but not gated (their wrap corrupts telemetry, not trajectories)."""
    return not name.startswith(("metric.", "trace.", "extra")) and name != "first_viol"


def _trunc_div(a, b):
    q = abs(a) // abs(b)
    return q if (a >= 0) == (b >= 0) else -q


def _src(eqn) -> str:
    """The user-frame source location of an eqn -- findings must name the
    kernel line, not just the program."""
    try:
        from jax._src import source_info_util

        return source_info_util.summarize(eqn.source_info)
    except Exception:
        return "?"


# ------------------------------------------------------------ the interpreter


class _Interp:
    """One abstract-interpretation run over one lowered program. Holds the
    findings sink, the declared ranges to seed/check, and the identity of
    the TARGET scan (the tick loop with `target_nk` carry legs, named by
    `leg_names`); every other scan gets the generic widening treatment."""

    def __init__(self, program, cfg, *, declared, leg_names, target_nk,
                 invariant, findings):
        self.program = program
        self.cfg = cfg
        self.declared = declared or {}
        self.leg_names = leg_names or []
        self.target_nk = target_nk
        self.invariant = invariant or set()
        self.findings = findings
        self.report = True
        self.scan_record = None
        self.loop_depth = 0  # nesting: target scan re-entered from an outer
        # loop sees widened (not initial) carries -- init checks only at 0
        self.parts = {}  # concatenate outvar -> per-operand intervals
        self._seen = set()

    def emit(self, rule, message):
        if not self.report:
            return
        key = (rule, message)
        if key in self._seen:
            return
        self._seen.add(key)
        self.findings.append(Finding(rule=rule, path=self.program, message=message))

    # ---- evaluation core

    def eval_closed(self, closed, args):
        env = {}
        for v, c in zip(closed.jaxpr.constvars, closed.consts):
            env[v] = _const_iv(c)
        return self.eval_jaxpr(closed.jaxpr, args, env)

    def eval_jaxpr(self, jaxpr, args, env=None):
        env = {} if env is None else env
        for v, a in zip(jaxpr.invars, args):
            env[v] = a
        for eqn in jaxpr.eqns:
            self.eval_eqn(eqn, env)
        return [self.read(env, a) for a in jaxpr.outvars]

    def read(self, env, atom):
        if isinstance(atom, _Literal):
            return _const_iv(atom.val)
        v = env.get(atom)
        return _top(atom.aval) if v is None else v

    def eval_eqn(self, eqn, env):
        prim = eqn.primitive.name
        ins = [self.read(env, a) for a in eqn.invars]
        if prim == "scan":
            outs = self._scan(eqn, ins)
        else:
            handler = getattr(self, "_p_" + prim.replace("-", "_"), None)
            if handler is not None:
                outs = handler(eqn, ins)
            elif "call_jaxpr" in eqn.params and hasattr(eqn.params["call_jaxpr"], "jaxpr"):
                outs = self.eval_closed(eqn.params["call_jaxpr"], ins)
            else:
                # Unknown primitive: unknown output is sound; taint
                # propagates so a widened leg keeps its horizon exemption.
                taint = any(v[2] for v in ins)
                outs = [(None, None, taint) for _ in eqn.outvars]
        if not isinstance(outs, list):
            outs = [outs]
        for o, val in zip(eqn.outvars, outs):
            env[o] = self._fit(val, o.aval, eqn)

    def _fit(self, val, aval, eqn):
        """Dtype admission: emit range-dtype-overflow when a PROVEN signed
        interval escapes the output dtype, then drop to UNKNOWN (a wrapped
        value reaches anywhere in the dtype, and unknownness stops one wrap
        point from cascading into a finding per downstream op). Unsigned
        and bool planes are modular by design; tainted int32+ values are
        the horizon rule's jurisdiction."""
        b = _aval_bounds(aval)
        if b is None:
            return val
        lo, hi, t = val
        escapes = (lo is not None and lo < b[0]) or (hi is not None and hi > b[1])
        if not escapes:
            return val
        if b[0] < 0 and not (t and np.dtype(aval.dtype).itemsize >= 4):
            self.emit(
                "range-dtype-overflow",
                f"{eqn.primitive.name}: proven interval {_fmt(val)} exceeds "
                f"{aval.dtype} [{b[0]}, {b[1]}] at {_src(eqn)}",
            )
        if t:
            return (lo, hi, t)  # keep the ideal value for rate measurement
        return (None, None, t)

    # ---- arithmetic / comparison handlers

    def _p_add(self, eqn, ins):
        a, b = ins
        t = a[2] or b[2]
        lo = None if a[0] is None or b[0] is None else a[0] + b[0]
        hi = None if a[1] is None or b[1] is None else a[1] + b[1]
        return [(lo, hi, t)]

    def _p_sub(self, eqn, ins):
        a, b = ins
        t = a[2] or b[2]
        lo = None if a[0] is None or b[1] is None else a[0] - b[1]
        hi = None if a[1] is None or b[0] is None else a[1] - b[0]
        return [(lo, hi, t)]

    def _p_mul(self, eqn, ins):
        return [_corners(ins[0], ins[1], lambda x, y: x * y)]

    def _p_neg(self, eqn, ins):
        a = ins[0]
        lo = None if a[1] is None else -a[1]
        hi = None if a[0] is None else -a[0]
        return [(lo, hi, a[2])]

    def _p_abs(self, eqn, ins):
        a = ins[0]
        if not _known(a):
            return [(0, None, a[2])]
        if a[0] >= 0:
            return [a]
        lo = 0 if a[0] <= 0 <= a[1] else min(abs(a[0]), abs(a[1]))
        return [(lo, max(abs(a[0]), abs(a[1])), a[2])]

    def _p_sign(self, eqn, ins):
        a = ins[0]
        if _known(a):
            lo = -1 if a[0] < 0 else (0 if a[0] == 0 else 1)
            hi = 1 if a[1] > 0 else (0 if a[1] == 0 else -1)
            return [(lo, hi, a[2])]
        return [(-1, 1, a[2])]

    def _p_max(self, eqn, ins):
        a, b = ins
        t = a[2] or b[2]
        los = [x for x in (a[0], b[0]) if x is not None]
        lo = max(los) if los else None  # max(a,b) >= each known lower bound
        hi = None if a[1] is None or b[1] is None else max(a[1], b[1])
        return [(lo, hi, t)]

    def _p_min(self, eqn, ins):
        a, b = ins
        t = a[2] or b[2]
        his = [x for x in (a[1], b[1]) if x is not None]
        hi = min(his) if his else None
        lo = None if a[0] is None or b[0] is None else min(a[0], b[0])
        return [(lo, hi, t)]

    def _p_clamp(self, eqn, ins):
        lo_b, x, hi_b = ins
        m = self._p_min(eqn, [x, hi_b])[0]
        return self._p_max(eqn, [lo_b, m])

    def _p_div(self, eqn, ins):
        a, b = ins
        t = a[2] or b[2]
        if not _known(a, b) or b[0] <= 0 <= b[1]:
            return [(None, None, t)]
        vals = [_trunc_div(x, y) for x in (a[0], a[1]) for y in (b[0], b[1])]
        return [(min(vals), max(vals), t)]

    def _p_rem(self, eqn, ins):
        a, b = ins
        t = a[2] or b[2]
        if not _known(b):
            return [(None, None, t)]
        m = max(abs(b[0]), abs(b[1]))
        if m == 0:
            return [(None, None, t)]
        # lax.rem: sign follows the dividend, magnitude < |divisor|.  An
        # unsigned dividend is non-negative even when its interval is unknown
        # (jax.random.randint's modulo chain runs on uint32 random bits).
        nonneg = (a[0] is not None and a[0] >= 0) or not np.issubdtype(
            np.dtype(eqn.outvars[0].aval.dtype), np.signedinteger
        )
        lo = 0 if nonneg else -(m - 1)
        hi = 0 if (a[1] is not None and a[1] <= 0) else m - 1
        if _known(a) and a[0] >= 0:
            hi = min(hi, a[1])
        return [(lo, hi, t)]

    def _cmp(self, eqn, ins, true_if, false_if):
        """Comparison with static resolution: a provably-constant predicate
        lets select_n collapse to one branch -- which is what discharges
        jax's negative-index normalization (`select(i < 0, i + N, i)`)
        whenever the index is proven non-negative."""
        a, b = ins
        t = a[2] or b[2]
        if _known(a, b):
            if true_if(a, b):
                return [(1, 1, t)]
            if false_if(a, b):
                return [(0, 0, t)]
        return [(0, 1, t)]

    def _p_lt(self, eqn, ins):
        return self._cmp(eqn, ins, lambda a, b: a[1] < b[0],
                         lambda a, b: a[0] >= b[1])

    def _p_le(self, eqn, ins):
        return self._cmp(eqn, ins, lambda a, b: a[1] <= b[0],
                         lambda a, b: a[0] > b[1])

    def _p_gt(self, eqn, ins):
        return self._cmp(eqn, ins, lambda a, b: a[0] > b[1],
                         lambda a, b: a[1] <= b[0])

    def _p_ge(self, eqn, ins):
        return self._cmp(eqn, ins, lambda a, b: a[0] >= b[1],
                         lambda a, b: a[1] < b[0])

    def _p_eq(self, eqn, ins):
        return self._cmp(eqn, ins,
                         lambda a, b: a[0] == a[1] == b[0] == b[1],
                         lambda a, b: a[1] < b[0] or a[0] > b[1])

    def _p_ne(self, eqn, ins):
        return self._cmp(eqn, ins,
                         lambda a, b: a[1] < b[0] or a[0] > b[1],
                         lambda a, b: a[0] == a[1] == b[0] == b[1])

    # ---- bitwise / shift handlers

    def _bitop(self, eqn, ins, kind):
        a, b = ins
        t = a[2] or b[2]
        if _known(a, b) and a[0] >= 0 and b[0] >= 0:
            if kind == "and":
                return [(0, min(a[1], b[1]), t)]
            bl = max(a[1], b[1]).bit_length()
            return [(0, (1 << bl) - 1, t)]
        return [(None, None, t)]

    def _p_and(self, eqn, ins):
        return self._bitop(eqn, ins, "and")

    def _p_or(self, eqn, ins):
        return self._bitop(eqn, ins, "or")

    def _p_xor(self, eqn, ins):
        return self._bitop(eqn, ins, "or")

    def _p_not(self, eqn, ins):
        a = ins[0]
        b = _aval_bounds(eqn.outvars[0].aval)
        if b is None or not _known(a):
            return [(None, None, a[2])]
        if b == (0, 1):
            return [(1 - a[1], 1 - a[0], a[2])]
        if b[0] < 0:  # signed: ~x == -1 - x
            return [(-1 - a[1], -1 - a[0], a[2])]
        return [(b[1] - a[1], b[1] - a[0], a[2])]  # unsigned complement

    def _p_shift_left(self, eqn, ins):
        a, s = ins
        t = a[2] or s[2]
        if not _known(a, s) or s[0] < 0:
            return [(None, None, t)]
        vals = [x << y for x in (a[0], a[1]) for y in (s[0], s[1])]
        return [(min(vals), max(vals), t)]

    def _p_shift_right_logical(self, eqn, ins):
        a, s = ins
        t = a[2] or s[2]
        if _known(a, s) and a[0] >= 0 and s[0] >= 0:
            return [(a[0] >> s[1], a[1] >> s[0], t)]
        return [(None, None, t)]

    def _p_shift_right_arithmetic(self, eqn, ins):
        a, s = ins
        t = a[2] or s[2]
        if _known(a, s) and s[0] >= 0:
            vals = [x >> y for x in (a[0], a[1]) for y in (s[0], s[1])]
            return [(min(vals), max(vals), t)]
        return [(None, None, t)]

    def _p_population_count(self, eqn, ins):
        a = ins[0]
        bits = np.dtype(eqn.invars[0].aval.dtype).itemsize * 8
        hi = bits
        if _known(a) and a[0] >= 0:
            hi = a[1].bit_length()
        return [(0, hi, a[2])]

    # ---- structural handlers

    def _identity(self, eqn, ins):
        n = len(eqn.outvars)
        return [ins[0]] * n if len(ins) == 1 else list(ins[:n])

    _p_broadcast_in_dim = _identity
    _p_reshape = _identity
    _p_transpose = _identity
    _p_squeeze = _identity
    _p_slice = _identity
    _p_rev = _identity
    _p_copy = _identity
    _p_device_put = _identity
    _p_cummax = _identity
    _p_cummin = _identity
    _p_sort = _identity
    _p_stop_gradient = _identity
    _p_reduce_precision = _identity
    _p_optimization_barrier = _identity

    def _p_concatenate(self, eqn, ins):
        # Remember the per-operand intervals: a multi-component gather index
        # tensor is built by concatenating its components along the last
        # axis, and the joint interval would mix (wide) slot indices into
        # the (narrow) node-index bound check.
        if eqn.params.get("dimension") == eqn.outvars[0].aval.ndim - 1:
            self.parts[eqn.outvars[0]] = list(ins)
        return [_join_all(ins)]

    def _p_pad(self, eqn, ins):
        return [_join(ins[0], ins[1])]

    def _p_select_n(self, eqn, ins):
        # A predicate proven constant selects exactly one branch.  This pairs
        # with the static comparison handlers to see through jax's
        # negative-index normalization instead of joining `i` with `i + N`.
        p = ins[0]
        if p[0] is not None and p[0] == p[1] and 0 <= p[0] < len(ins) - 1:
            case = ins[1 + p[0]]
            return [(case[0], case[1], case[2] or p[2])]
        return [_join_all(ins[1:])]

    def _p_iota(self, eqn, ins):
        shape = eqn.params["shape"]
        dim = eqn.params["dimension"]
        n = shape[dim] if shape else 0
        return [(0, max(n - 1, 0), False)]

    def _p_convert_element_type(self, eqn, ins):
        v = ins[0]
        out_aval = eqn.outvars[0].aval
        b = _aval_bounds(out_aval)
        if b is None or not _known(v):
            return [(None, None, v[2])]
        if v[0] < b[0] or v[1] > b[1]:
            if b[0] < 0 and not (v[2] and np.dtype(out_aval.dtype).itemsize >= 4):
                self.emit(
                    "range-dtype-overflow",
                    f"narrowing astype to {out_aval.dtype}: fit unproven for "
                    f"interval {_fmt(v)} (source {eqn.invars[0].aval.dtype}) "
                    f"at {_src(eqn)}",
                )
            if v[2]:
                return [v]
            return [(None, None, v[2])]
        return [v]

    # ---- reductions

    def _reduced_n(self, eqn):
        shape = getattr(eqn.invars[0].aval, "shape", ())
        axes = eqn.params.get("axes", ())
        n = 1
        for a in axes:
            n *= shape[a]
        return n

    def _p_reduce_sum(self, eqn, ins):
        a = ins[0]
        n = self._reduced_n(eqn)
        if not _known(a):
            return [(None, None, a[2])]
        lo = min(0, a[0] * n)
        hi = max(0, a[1] * n)
        return [(lo, hi, a[2])]

    def _p_reduce_max(self, eqn, ins):
        return [ins[0]]

    _p_reduce_min = _p_reduce_max

    def _p_reduce_or(self, eqn, ins):
        a = ins[0]
        if _known(a) and a[0] >= 0:
            return [(0, (1 << a[1].bit_length()) - 1, a[2])]
        return [(None, None, a[2])]

    def _p_reduce_and(self, eqn, ins):
        a = ins[0]
        if _known(a) and a[0] >= 0:
            return [(0, a[1], a[2])]
        return [(None, None, a[2])]

    def _p_cumsum(self, eqn, ins):
        a = ins[0]
        shape = getattr(eqn.invars[0].aval, "shape", ())
        axis = eqn.params.get("axis", 0)
        n = shape[axis] if shape else 1
        if not _known(a):
            return [(None, None, a[2])]
        return [(min(a[0], a[0] * n), max(a[1], a[1] * n), a[2])]

    # ---- indexing

    def _p_gather(self, eqn, ins):
        operand, indices = ins[0], ins[1]
        mode = eqn.params.get("mode")
        if mode is not None and "PROMISE_IN_BOUNDS" in str(mode):
            comps = self.parts.get(eqn.invars[1])
            self._oob_check(
                "gather",
                eqn,
                eqn.invars[0].aval,
                eqn.params["dimension_numbers"].start_index_map,
                eqn.params["slice_sizes"],
                indices,
                comps,
            )
        if mode is not None and "FILL" in str(mode):
            return [(None, None, operand[2] or indices[2])]
        return [(operand[0], operand[1], operand[2] or indices[2])]

    def _p_scatter(self, eqn, ins):
        operand, indices, updates = ins[0], ins[1], ins[2]
        mode = eqn.params.get("mode")
        if mode is not None and "PROMISE_IN_BOUNDS" in str(mode):
            dims = eqn.params["dimension_numbers"].scatter_dims_to_operand_dims
            sizes = tuple(1 for _ in eqn.invars[0].aval.shape)  # slot extent
            op_aval = eqn.invars[0].aval
            comps = self.parts.get(eqn.invars[1])
            self._oob_check("scatter", eqn, op_aval, dims, sizes, indices, comps)
        return [_join(operand, updates)]

    def _p_scatter_add(self, eqn, ins):
        base = self._p_scatter(eqn, ins)[0]
        return [_corners(base, ins[2], lambda x, y: x + y)]

    _p_scatter_max = _p_scatter
    _p_scatter_min = _p_scatter

    def _p_dynamic_slice(self, eqn, ins):
        return [ins[0]]  # start indices clamp by lax semantics: never oob

    def _p_dynamic_update_slice(self, eqn, ins):
        return [_join(ins[0], ins[1])]

    def _oob_check(self, what, eqn, op_aval, dims, slice_sizes, idx_iv, comps):
        if not self.report:
            return
        bounds = []
        for d in dims:
            # slice_sizes is per OPERAND DIM (full rank), not per index
            # component: the valid start range for component -> dim d is
            # [0, shape[d] - slice_sizes[d]].
            size = slice_sizes[d] if d < len(slice_sizes) else 1
            bounds.append(op_aval.shape[d] - size)
        if not bounds:
            return
        if comps is not None and len(comps) == len(bounds):
            # Component-precise: the index tensor was a last-axis
            # concatenation of one plane per indexed operand dim.
            for i, (c, bound) in enumerate(zip(comps, bounds)):
                if not _known(c):
                    self.emit(
                        "range-index-oob",
                        f"{what} with PROMISE_IN_BOUNDS but an unproven "
                        f"index interval for component {i} (operand shape "
                        f"{tuple(op_aval.shape)}) at {_src(eqn)}",
                    )
                elif c[0] < 0 or c[1] > bound:
                    self.emit(
                        "range-index-oob",
                        f"{what} promises in-bounds indices but component "
                        f"{i} has proven interval {_fmt(c)}, not within "
                        f"[0, {bound}] (operand shape {tuple(op_aval.shape)}, "
                        f"slice sizes {tuple(slice_sizes)}) at {_src(eqn)}",
                    )
            return
        if not _known(idx_iv):
            self.emit(
                "range-index-oob",
                f"{what} with PROMISE_IN_BOUNDS but an unproven index interval "
                f"over operand shape {tuple(op_aval.shape)} at {_src(eqn)}",
            )
            return
        # Single-component starts prove exactly; multi-component without
        # recoverable components uses the weak (max-extent) bound --
        # documented in docs/ANALYSIS.md.
        bound = bounds[0] if len(bounds) == 1 else max(bounds)
        if idx_iv[0] < 0 or idx_iv[1] > bound:
            self.emit(
                "range-index-oob",
                f"{what} promises in-bounds indices but the proven interval "
                f"{_fmt(idx_iv)} is not within [0, {bound}] (operand shape "
                f"{tuple(op_aval.shape)}, slice sizes {tuple(slice_sizes)}) "
                f"at {_src(eqn)}",
            )

    # ---- control flow

    def _p_pjit(self, eqn, ins):
        return self.eval_closed(eqn.params["jaxpr"], ins)

    def _p_cond(self, eqn, ins):
        branches = eqn.params["branches"]
        ops = list(ins[1:])
        results = [self.eval_closed(br, list(ops)) for br in branches]
        return [_join_all(list(vals)) for vals in zip(*results)]

    def _p_while(self, eqn, ins):
        p = eqn.params
        cn, bn = p["cond_nconsts"], p["body_nconsts"]
        bconsts = ins[cn:cn + bn]
        carry = list(ins[cn + bn:])
        saved, self.report = self.report, False
        cur = carry
        self.loop_depth += 1
        for _ in range(MAX_ITERS_GENERIC):
            out = self.eval_closed(p["body_jaxpr"], list(bconsts) + cur)
            nxt = [_join(a, b) for a, b in zip(cur, out)]
            if all(n[:2] == c[:2] for n, c in zip(nxt, cur)):
                cur = nxt
                break
            cur = nxt
        else:
            cur = [
                (None, None, True) if n[:2] != c[:2] else c
                for n, c in zip(nxt, carry)
            ]
        self.report = saved
        out = self.eval_closed(p["body_jaxpr"], list(bconsts) + cur)
        self.loop_depth -= 1
        return [_join(a, b) for a, b in zip(cur, out)]

    # ---- the scan protocol (the centerpiece)

    def _scan(self, eqn, ins):
        p = eqn.params
        closed_body = p["jaxpr"]
        nc, nk = p["num_consts"], p["num_carry"]
        length = p.get("length") or 1
        body = closed_body.jaxpr
        consts, carry0, xs = list(ins[:nc]), list(ins[nc:nc + nk]), list(ins[nc + nk:])
        carry_avals = [v.aval for v in body.invars[nc:nc + nk]]
        dbounds = [_aval_bounds(a) for a in carry_avals]
        is_target = self.target_nk is not None and nk == self.target_nk
        names = self.leg_names if is_target else [f"leg{i}" for i in range(nk)]

        entry = list(carry0)
        if is_target:
            for i, nm in enumerate(names):
                d = self.declared.get(nm)
                if d is None:
                    continue
                b = dbounds[i]
                if b is not None and (d[0] < b[0] or d[1] > b[1]):
                    self.emit(
                        "range-dtype-overflow",
                        f"carry leg `{nm}`: declared range [{d[0]}, {d[1]}] "
                        f"does not fit its {carry_avals[i].dtype} plane "
                        f"[{b[0]}, {b[1]}]",
                    )
                    entry[i] = (max(d[0], b[0]), min(d[1], b[1]), False)
                    continue
                c0 = carry0[i]
                # Only a *known, top-level* initial interval can contradict
                # the declaration: serve/trace tick loops are re-entered from
                # an outer window scan whose carry already holds the widened
                # per-window image, not the program's initial state.
                if (self.loop_depth == 0 and _known(c0)
                        and not (d[0] <= c0[0] and c0[1] <= d[1])):
                    self.emit(
                        "range-annotation-stale",
                        f"carry leg `{nm}`: initial-value interval {_fmt(c0)} "
                        f"is not within the declared range [{d[0]}, {d[1]}]",
                    )
                entry[i] = (d[0], d[1], False)

        # Declared legs are PINNED at their declaration for the whole fixed
        # point: the declaration is the trusted axiom (its one-tick overshoot
        # is what the golden `escape` pin records), and letting an unprovable
        # leg's join grow would leak -- e.g. log_len's guarded `+ do_write`
        # would reclassify every leg derived from it as monotone.
        pinned = [
            is_target and self.declared.get(names[i]) is not None
            for i in range(nk)
        ]

        # Widening fixed point (muted: iteration passes must not duplicate
        # eqn-level findings; only the final pass reports).
        saved, self.report = self.report, False
        iters = MAX_ITERS if is_target else MAX_ITERS_GENERIC
        hist = [[(v[0], v[1]) for v in entry]]
        cur = list(entry)
        image0 = None
        self.loop_depth += 1
        for _ in range(iters):
            out = self._body_pass(closed_body, consts, cur, xs)[:nk]
            if image0 is None:
                image0 = out
            nxt = [
                e if pin else _join(a, b)
                for pin, e, a, b in zip(pinned, entry, cur, out)
            ]
            hist.append([(v[0], v[1]) for v in nxt])
            stable_all = all(n[:2] == c[:2] for n, c in zip(nxt, cur))
            cur = nxt
            if stable_all:
                break

        # Classify each leg: stable (proven invariant), monotone (constant
        # measured growth rate -> safe horizon), or widened to dtype-top.
        widened = list(cur)
        legrec = []
        for i in range(nk):
            stable = hist[-1][i] == hist[-2][i]
            rate = horizon = None
            if not stable:
                b = dbounds[i]
                los = [row[i][0] for row in hist]
                his = [row[i][1] for row in hist]
                lo_ok = los[-1] is not None and los[-1] == los[-2]
                d1 = (None if his[-1] is None or his[-2] is None
                      else his[-1] - his[-2])
                d2 = (None if len(his) < 3 or his[-2] is None or his[-3] is None
                      else his[-2] - his[-3])
                ent_hi = entry[i][1]
                if (lo_ok and d1 is not None and d1 > 0 and d2 == d1
                        and b is not None and ent_hi is not None):
                    rate = d1
                    horizon = min((b[1] - ent_hi) // rate, HORIZON_CAP)
                    grow = H_AUDIT if is_target else length
                    widened[i] = (los[-1], ent_hi + rate * grow, True)
                else:
                    widened[i] = (None, None, True)
            legrec.append({"stable": stable, "rate": rate, "horizon": horizon})

        # Final reporting pass over the widened carries.
        self.report = saved
        outs_full = self._body_pass(closed_body, consts, widened, xs)
        self.loop_depth -= 1
        final = [_join(w, o) for w, o in zip(widened, outs_full[:nk])]

        if is_target and self.report:
            # The escape/looseness checks compare declarations against the
            # FINAL-pass image (body over the widened carries): the first
            # muted pass still has undeclared legs at their init constants,
            # which would make every dependent leg look artificially tight.
            self._target_checks(names, entry, cur, outs_full[:nk], legrec,
                                carry_avals, dbounds)
        return final + list(outs_full[nk:])

    def _body_pass(self, closed_body, consts, carry, xs):
        return self.eval_closed(closed_body, list(consts) + list(carry) + list(xs))

    def _target_checks(self, names, entry, cur, image0, legrec, carry_avals,
                       dbounds):
        record = {}
        for i, nm in enumerate(names):
            r = legrec[i]
            d = self.declared.get(nm)
            ent = {"dtype": str(carry_avals[i].dtype)}
            if d is not None:
                # Declared legs are seeded from the declaration, so the record
                # pins the declaration plus the *escape*: how far the one-tick
                # image provably leaves it.  Path-insensitive intervals cannot
                # discharge masked-garbage idioms (a kernel computes junk that
                # a downstream `where(ok, ...)` discards), so a nonzero escape
                # is not a finding -- it is pinned in the golden and any DRIFT
                # in it is.  escape null = image unknown (unprovable either
                # way); no escape key = proven inductive.
                ent["lo"], ent["hi"] = d[0], d[1]
                iw = image0[i] if image0 is not None else _TOP
                if _known(iw):
                    esc = [min(0, iw[0] - d[0]), max(0, iw[1] - d[1])]
                    if esc != [0, 0]:
                        ent["escape"] = esc
                    elif nm not in self.invariant:
                        dw, cw = d[1] - d[0], iw[1] - iw[0]
                        if dw > LOOSE_FACTOR * cw + LOOSE_SLACK:
                            self.emit(
                                "range-annotation-stale",
                                f"carry leg `{nm}`: declared range [{d[0]}, "
                                f"{d[1]}] is wildly looser than the proven "
                                f"interval {_fmt(iw)}",
                            )
                else:
                    ent["escape"] = None
            elif r["stable"]:
                ent["lo"], ent["hi"] = cur[i][0], cur[i][1]
            elif r["rate"] is not None:
                ent["lo"], ent["hi"] = entry[i][0], entry[i][1]
                ent["rate"], ent["horizon"] = r["rate"], r["horizon"]
            else:
                b = dbounds[i]
                ent["lo"], ent["hi"] = (b[0], b[1]) if b else (None, None)
                ent["widened"] = True
            record[nm] = ent

            if (r["horizon"] is not None and r["horizon"] < SOAK_TICKS
                    and _protocol_leg(nm)):
                self.emit(
                    "range-horizon",
                    f"carry leg `{nm}` ({carry_avals[i].dtype}) grows by "
                    f"{r['rate']}/tick from {entry[i][1]}: wraps after "
                    f"~{r['horizon']:,} ticks, below the {SOAK_TICKS:,}-tick "
                    f"soak budget",
                )
        if self.scan_record is None:
            self.scan_record = record


# -------------------------------------------------------------- program audit


def _leg_names(kind: str) -> list[str]:
    if kind == "trace_scan":
        return policy.trace_carry_leaf_names()
    names = list(policy.carry_leaf_names())
    if kind == "serve_scan":
        names.append("first_viol")
    return names


def _step_seed(closed, cfg: RaftConfig, declared):
    """Map the declared ranges onto a step program's state invars (pytree
    flatten order == policy.carry_leaf_names minus the metric legs). Returns
    (args, ok): a mapping mismatch returns ok=False so the caller fires a
    VISIBLE derivation-failure finding instead of mis-seeded checks."""
    state_names = [n for n in policy.carry_leaf_names()
                   if not n.startswith("metric.")]
    invars = closed.jaxpr.invars
    try:
        state, inputs, _info = policy.state_avals(cfg)
    except Exception:
        return None, False
    n_state = len(jax.tree_util.tree_leaves(state))
    n_inputs = len(jax.tree_util.tree_leaves(inputs))
    if len(state_names) != n_state or len(invars) != n_state + n_inputs:
        return None, False
    args = [_top(v.aval) for v in invars]
    for i, nm in enumerate(state_names):
        d = declared.get(nm)
        if d is None:
            continue
        b = _aval_bounds(invars[i].aval)
        if b is None or d[0] < b[0] or d[1] > b[1]:
            continue  # the scan-side seeding names a dtype misfit per leg
        args[i] = (d[0], d[1], False)
    return args, True


def audit_program(program: str, closed, kind: str, cfg: RaftConfig, *,
                  declared=None, leg_names=None):
    """Run the interval interpreter over one lowered program. Returns
    (findings, scan_record): scan_record is the tick loop's per-leg record
    (None for step programs or when no matching scan was found -- the
    caller MUST turn that into a range-golden finding). `declared` and
    `leg_names` are injectable for the seeded-negative tests."""
    if declared is None:
        declared = policy.declared_ranges(cfg)
        # Packed tiers carry some legs as bit-packed words: a value-domain
        # declaration must not seed (or pin) the packed plane -- the word
        # ranges live in a different domain.  Their value ranges are checked
        # by check_pack_widths against the tile table instead.
        if getattr(cfg, "compact_planes", False):
            from raft_sim_tpu.ops import tile

            packed = {f for f, mode, *_ in tile.state_plan(cfg)
                      if mode == "pack"}
            packed |= {f"mb.{f}" for f, mode, *_ in tile.mailbox_plan(cfg)
                       if mode == "pack"}
            declared = {k: v for k, v in declared.items() if k not in packed}
    findings: list[Finding] = []
    invariant = policy.invariant_leaves(cfg)
    if kind == "step":
        interp = _Interp(program, cfg, declared=declared, leg_names=None,
                         target_nk=None, invariant=invariant, findings=findings)
        args, ok = _step_seed(closed, cfg, declared)
        if not ok:
            findings.append(Finding(
                rule="range-golden", path=program,
                message=(
                    "step input mapping did not match the policy state "
                    "template: the value-range gates for this program are "
                    "NOT being checked"
                ),
            ))
            return findings, None
        interp.eval_closed(closed, args)
        return findings, None
    names = leg_names if leg_names is not None else _leg_names(kind)
    interp = _Interp(program, cfg, declared=declared, leg_names=names,
                     target_nk=len(names), invariant=invariant,
                     findings=findings)
    args = [_top(v.aval) for v in closed.jaxpr.invars]
    interp.eval_closed(closed, args)
    if interp.scan_record is None:
        findings.append(Finding(
            rule="range-golden", path=program,
            message=(
                f"no scan with the expected {len(names)}-leg carry found: "
                f"the value-range gates for this program are NOT being checked"
            ),
        ))
    return findings, interp.scan_record


# ------------------------------------------------------- tier-level checks


def check_pack_widths(cfg: RaftConfig, name: str, *, widths=None,
                      declared=None) -> list[Finding]:
    """range-pack-width: every compact-plane range must fit its allotted
    bits after biasing, and a types.py declared range on the same leg must
    agree with the table. `widths`/`declared` injectable for tests."""
    from raft_sim_tpu.ops import tile

    if widths is None:
        widths = tile.pack_width_table(cfg)
    if declared is None:
        declared = policy.declared_ranges(cfg)
    out: list[Finding] = []
    path = f"range:{name}/pack"
    for leg, (bits, bias, lo, hi) in sorted(widths.items()):
        if lo + bias < 0 or hi + bias >= (1 << bits):
            out.append(Finding(
                rule="range-pack-width", path=path,
                message=(
                    f"compact plane `{leg}`: value range [{lo}, {hi}] with "
                    f"bias {bias} does not fit {bits} bit(s) (biased range "
                    f"must sit in [0, {(1 << bits) - 1}])"
                ),
            ))
        d = declared.get(leg)
        if d is not None and tuple(d) != (lo, hi):
            out.append(Finding(
                rule="range-pack-width", path=path,
                message=(
                    f"compact plane `{leg}`: pack-width table range "
                    f"[{lo}, {hi}] disagrees with the types.py declared "
                    f"range [{d[0]}, {d[1]}]"
                ),
            ))
    return out


def check_ceilings():
    """Re-derive the types.py narrow-dtype ceilings from the config-module
    formulas (satellite of the same PR that made them policy-sourced) and
    compare. Returns (findings, ceilings-record)."""
    from raft_sim_tpu import types as rst_types
    from raft_sim_tpu.utils import config as cfg_mod

    out: list[Finding] = []
    path = "raft_sim_tpu/types.py"
    derived = {
        "MAX_INT8_LOG_CAPACITY": cfg_mod.max_log_capacity_for(127),
        "MAX_INT8_NODES": cfg_mod.max_nodes_for(127),
    }
    for nm, want in derived.items():
        have = getattr(rst_types, nm)
        if have != want:
            out.append(Finding(
                rule="range-dtype-overflow", path=path,
                message=(
                    f"{nm} is {have} but the encoding-bound formula derives "
                    f"{want}: the ceiling no longer matches the policy it "
                    f"claims to encode"
                ),
            ))
    enc = cfg_mod.window_min_encoding_max(cfg_mod.MAX_LOG_CAPACITY)
    if enc > 32767:
        out.append(Finding(
            rule="range-dtype-overflow", path="raft_sim_tpu/utils/config.py",
            message=(
                f"MAX_LOG_CAPACITY={cfg_mod.MAX_LOG_CAPACITY} drives the "
                f"window-min encoding to {enc}, beyond int16"
            ),
        ))
    ceilings = dict(derived)
    ceilings["MAX_LOG_CAPACITY"] = cfg_mod.MAX_LOG_CAPACITY
    ceilings["window_min_encoding_max"] = enc
    return out, ceilings


# ------------------------------------------------------------- derive / pins


def _range_label(program: str) -> str:
    # Pass B labels programs "jaxpr:<tier>/<prog>"; Pass E findings live
    # under "range:<tier>/<prog>" so waivers scope per pass.
    return "range:" + program.split(":", 1)[1] if ":" in program else program


@functools.lru_cache(maxsize=4)
def _derive_all(config_names: tuple):
    findings: list[tuple[str, str, str]] = []
    tiers: dict[str, dict] = {}
    for name in config_names:
        cfg, _batch = PRESETS[name]
        for program, closed, kind, rule_cfg in jaxpr_audit.programs(name, cfg):
            label = _range_label(program)
            try:
                fs, record = audit_program(label, closed, kind, rule_cfg)
            except Exception as ex:  # derivation failure must be VISIBLE
                fs = [Finding(
                    rule="range-golden", path=label,
                    message=(
                        f"range derivation failed ({type(ex).__name__}: {ex}): "
                        f"the value-range gates for this program are NOT "
                        f"being checked"
                    ),
                )]
                record = None
            findings.extend((f.rule, f.path, f.message) for f in fs)
            if program.endswith("/simulate") and record is not None:
                tiers[name] = {"legs": record}
        tiers.setdefault(name, {"legs": {}})
        from raft_sim_tpu.ops import tile

        tiers[name]["pack_widths"] = {
            leg: list(w) for leg, w in sorted(tile.pack_width_table(cfg).items())
        }
        findings.extend(
            (f.rule, f.path, f.message) for f in check_pack_widths(cfg, name)
        )
    ceil_finds, ceilings = check_ceilings()
    findings.extend((f.rule, f.path, f.message) for f in ceil_finds)
    doc = {
        "jax_version": jax.__version__,
        "audit_horizon": H_AUDIT,
        "soak_ticks": SOAK_TICKS,
        "ceilings": ceilings,
        "tiers": tiers,
    }
    return doc, tuple(findings)


def derive_all(config_names=jaxpr_audit.AUDIT_CONFIGS):
    """Derived ranges + the derivation-time findings. The cache stores
    findings as plain tuples so waiver application never mutates cached
    state across runs."""
    doc, finds = _derive_all(tuple(config_names))
    return doc, [Finding(rule=r, path=p, message=m) for r, p, m in finds]


def _legs_equal(d: dict, g: dict, tol_rel: float) -> bool:
    for k in ("lo", "hi", "dtype", "rate", "widened"):
        if d.get(k) != g.get(k):
            return False
    # `escape` distinguishes absent (proven inductive) from null (image
    # unknown) from a pinned [lo, hi] overshoot -- all three must match.
    if ("escape" in d, d.get("escape")) != ("escape" in g, g.get("escape")):
        return False
    dh, gh = d.get("horizon"), g.get("horizon")
    if dh is None or gh is None:
        return dh == gh
    return abs(dh - gh) <= tol_rel * abs(gh)


def compare(derived: dict, golden: dict, *, full: bool = True) -> list[Finding]:
    """All golden-pin findings: derived ranges vs tests/golden_ranges.json.
    `full` = the derivation covered every audited tier, so golden tiers with
    no derived counterpart are stale."""
    out: list[Finding] = []
    tol = (golden.get("tolerance") or {}).get(
        "horizon_rel", DEFAULT_TOLERANCE["horizon_rel"])
    g_tiers = golden.get("tiers") or {}
    for name, d in derived["tiers"].items():
        g = g_tiers.get(name)
        if g is None:
            out.append(Finding(
                rule="range-golden", path=f"range:{name}/golden",
                message=f"audited tier has no golden range pins -- {_REGEN}",
            ))
            continue
        diffs = []
        g_legs = g.get("legs") or {}
        for leg, dl in d["legs"].items():
            gl = g_legs.get(leg)
            if gl is None:
                diffs.append(f"`{leg}` has no pin")
            elif not _legs_equal(dl, gl, tol):
                diffs.append(
                    f"`{leg}` pinned [{gl.get('lo')}, {gl.get('hi')}] "
                    f"h={gl.get('horizon')} now [{dl.get('lo')}, "
                    f"{dl.get('hi')}] h={dl.get('horizon')}"
                )
        for leg in g_legs:
            if leg not in d["legs"]:
                diffs.append(f"`{leg}` pinned but no longer derived")
        if d.get("pack_widths") != g.get("pack_widths"):
            diffs.append("pack-width table drifted from its pin")
        if diffs:
            shown = "; ".join(diffs[:4])
            more = f" (+{len(diffs) - 4} more)" if len(diffs) > 4 else ""
            out.append(Finding(
                rule="range-golden", path=f"range:{name}/golden",
                message=f"range pins drifted: {shown}{more} -- {_REGEN}",
            ))
    if full:
        for name in g_tiers:
            if name not in derived["tiers"]:
                out.append(Finding(
                    rule="range-golden", path=f"range:{name}/golden",
                    message=(
                        f"golden pins a tier the audit no longer derives "
                        f"-- {_REGEN}"
                    ),
                ))
    if derived.get("ceilings") != golden.get("ceilings"):
        out.append(Finding(
            rule="range-golden", path="range:ceilings/golden",
            message=(
                f"pinned dtype ceilings {golden.get('ceilings')} differ from "
                f"derived {derived.get('ceilings')} -- {_REGEN}"
            ),
        ))
    return out


def run_pass(config_names=jaxpr_audit.AUDIT_CONFIGS,
             golden_file: str | None = None) -> list[Finding]:
    """The full value-range pass: derive, load pins, compare. A missing or
    unreadable golden file is itself a finding -- the gate must force the
    pins into existence, not silently pass without them."""
    golden_file = golden_file or golden_path()
    rel = os.path.relpath(golden_file, _REPO_ROOT)
    derived, findings = derive_all(config_names)
    try:
        with open(golden_file) as f:
            golden = json.load(f)
    except FileNotFoundError:
        return findings + [Finding(
            rule="range-golden", path=rel,
            message=(
                "no golden range pins: generate them with "
                "`python tools/check.py --update-goldens` and commit the file"
            ),
        )]
    except (OSError, json.JSONDecodeError) as ex:
        return findings + [Finding(
            rule="range-golden", path=rel,
            message=f"golden range file unreadable: {ex}",
        )]
    full = tuple(config_names) == tuple(jaxpr_audit.AUDIT_CONFIGS)
    return findings + compare(derived, golden, full=full)


def update_golden(path: str | None = None,
                  config_names=jaxpr_audit.AUDIT_CONFIGS) -> str:
    """Regenerate tests/golden_ranges.json from the current tree (the
    `tools/check.py --update-goldens` path). A tuned tolerance in the
    existing file survives regeneration (Pass C precedent)."""
    path = path or golden_path()
    derived, _findings = derive_all(config_names)
    tolerance = dict(DEFAULT_TOLERANCE)
    try:
        with open(path) as f:
            tolerance.update(json.load(f).get("tolerance") or {})
    except (OSError, json.JSONDecodeError):
        pass
    doc = {
        "jax_version": derived["jax_version"],
        "audit_horizon": derived["audit_horizon"],
        "soak_ticks": derived["soak_ticks"],
        "tolerance": tolerance,
        "ceilings": derived["ceilings"],
        "tiers": derived["tiers"],
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def diff_table(derived: dict, golden: dict, out=None) -> None:
    """Pinned-vs-current interval table (the CI failure-triage rendering:
    only legs that moved are printed, per tier)."""
    import sys

    out = out or sys.stdout
    tol = (golden.get("tolerance") or {}).get(
        "horizon_rel", DEFAULT_TOLERANCE["horizon_rel"])
    g_tiers = golden.get("tiers") or {}
    print(f"{'tier/leg':44} {'pinned':>24} {'current':>24}", file=out)
    fmt = lambda e: (f"[{e.get('lo')}, {e.get('hi')}]"
                     + (f" h={e.get('horizon')}" if e.get("horizon") is not None
                        else "")) if e else "-"
    for name in sorted(set(derived.get("tiers") or {}) | set(g_tiers)):
        d_legs = (derived.get("tiers", {}).get(name) or {}).get("legs") or {}
        g_legs = (g_tiers.get(name) or {}).get("legs") or {}
        for leg in sorted(set(d_legs) | set(g_legs)):
            dl, gl = d_legs.get(leg), g_legs.get(leg)
            if dl and gl and _legs_equal(dl, gl, tol):
                continue
            print(f"{name + '/' + leg:44} {fmt(gl):>24} {fmt(dl):>24}",
                  file=out)
        dp = (derived.get("tiers", {}).get(name) or {}).get("pack_widths")
        gp = (g_tiers.get(name) or {}).get("pack_widths")
        if dp != gp:
            print(f"{name + '/pack_widths':44} {str(gp):>24} {str(dp):>24}",
                  file=out)
