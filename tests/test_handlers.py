"""Per-handler unit tests against hand-built states (SURVEY.md section 4, unit tier).

Each test constructs a precise cluster state + mailbox, runs one tick, and asserts the
spec-mandated outcome -- especially at the points where the reference deviates from the
Raft paper (SURVEY.md section 2.3): term adoption on RequestVote (2.3.2), the real
up-to-date check (2.3.3/2.3.4), leader-commit advancement from majority match (2.3.8),
nextIndex = match+1 (2.3.10), and commit = min(leaderCommit, last new entry) (2.3.6).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_sim_tpu import (
    CANDIDATE,
    FOLLOWER,
    LEADER,
    NIL,
    RaftConfig,
    StepInputs,
    init_state,
)
from raft_sim_tpu.models import raft
from raft_sim_tpu import types as raft_types
from raft_sim_tpu.ops import bitplane
from raft_sim_tpu.types import REQ_APPEND, REQ_VOTE, RESP_APPEND, RESP_VOTE

CFG = RaftConfig(n_nodes=5, log_capacity=8, max_entries_per_rpc=4)


def quiet_inputs(cfg, far=1000, deliver=None):
    """No faults, no client traffic, clocks advancing but timers far away.
    `deliver` overrides the (full) [N, N] bool delivery mask; StepInputs carries
    it bit-packed (ops/bitplane.py)."""
    n = cfg.n_nodes
    if deliver is None:
        deliver = jnp.ones((n, n), bool)
    return StepInputs(
        deliver_mask=bitplane.pack(deliver, axis=1),
        skew=jnp.ones((n,), jnp.int32),
        timeout_draw=jnp.full((n,), far, jnp.int32),
        client_cmd=jnp.int32(NIL),
        client_target=jnp.int32(0),
        client_bounce=jnp.zeros((cfg.client_pipeline,), jnp.int32),
        alive=jnp.ones((n,), bool),
        restarted=jnp.zeros((n,), bool),
    )


def base_state(cfg=CFG, far=1000):
    """All-follower state with timers pushed far out so nothing fires by itself."""
    s = init_state(cfg, jax.random.key(0))
    return s._replace(deadline=jnp.full((cfg.n_nodes,), far, jnp.int32))


def with_log(s, node, terms):
    """Install a log (list of entry terms; values = 100+slot) on one node.
    Entries are stamped as client offers (log_tick = value, the pre-decoupling
    identity), so hand-built states stay visible to the latency metric."""
    lt = s.log_term.at[node, : len(terms)].set(jnp.asarray(terms, jnp.int32))
    vals = 100 + jnp.arange(len(terms), dtype=jnp.int32)
    lv = s.log_val.at[node, : len(terms)].set(vals)
    ltk = s.log_tick.at[node, : len(terms)].set(vals)
    return s._replace(
        log_term=lt, log_val=lv, log_tick=ltk,
        log_len=s.log_len.at[node].set(len(terms)),
    )


import functools


@functools.lru_cache(maxsize=None)
def _jitted_step(cfg):
    return jax.jit(lambda s_, i_: raft.step(cfg, s_, i_))


def step(cfg, s, inp=None):
    return _jitted_step(cfg)(s, inp if inp is not None else quiet_inputs(cfg))


# Wire-format v9 helpers (Mailbox docstring): requests are per-sender broadcasts,
# responses are a [receiver, responder] type plane + per-responder payloads
# (grant target, ack target, success match, nack hint, term).


def rv_wire(s, src, term, last_idx=0, last_term=0):
    """Broadcast a RequestVote from `src` (delivery decides who sees it)."""
    mb = s.mailbox._replace(
        req_type=s.mailbox.req_type.at[src].set(REQ_VOTE),
        req_term=s.mailbox.req_term.at[src].set(term),
        req_last_index=s.mailbox.req_last_index.at[src].set(last_idx),
        req_last_term=s.mailbox.req_last_term.at[src].set(last_term),
    )
    return s._replace(mailbox=mb)


def resp_wire(s, q, r, rtype, term, ok, match=0):
    """Wire a response from responder `r` to requester `q`. An ok response names
    `q` as r's one grant/ack target; `match` lands in the success-match field for
    an ok append and in the nack-hint field otherwise."""
    mb = s.mailbox._replace(
        resp_kind=s.mailbox.resp_kind.at[q, r].set(rtype),
        resp_term=s.mailbox.resp_term.at[r].set(term),
    )
    if rtype == RESP_VOTE and ok:
        mb = mb._replace(v_to=mb.v_to.at[r].set(q))
    if rtype == RESP_APPEND:
        if ok:
            mb = mb._replace(
                a_ok_to=mb.a_ok_to.at[r].set(q),
                a_match=mb.a_match.at[r].set(match),
            )
        else:
            mb = mb._replace(a_hint=mb.a_hint.at[r].set(match))
    return s._replace(mailbox=mb)


def resp_type_of(mb, q, r):
    return int(mb.resp_kind[q, r])


def resp_ok_of(mb, q, r):
    kind = int(mb.resp_kind[q, r])
    if kind == RESP_VOTE:
        return int(mb.v_to[r]) == q
    if kind == RESP_APPEND:
        return int(mb.a_ok_to[r]) == q
    return False


def resp_match_of(mb, q, r):
    return int(mb.a_match[r] if resp_ok_of(mb, q, r) else mb.a_hint[r])


# ---------------------------------------------------------------- RequestVote handling


def test_vote_granted_and_term_adopted():
    """A higher-term RequestVote makes the receiver adopt the term (reference bug
    2.3.2: it never did) and grant when the candidate's log is up to date."""
    s = rv_wire(base_state(), 0, term=5)
    s2, _ = step(CFG, s)
    assert int(s2.term[1]) == 5
    assert int(s2.voted_for[1]) == 0
    assert resp_type_of(s2.mailbox, 0, 1) == RESP_VOTE
    assert resp_ok_of(s2.mailbox, 0, 1)
    assert int(s2.mailbox.resp_term[1]) == 5


def test_vote_denied_stale_term():
    s = base_state()
    s = s._replace(term=s.term.at[1].set(9))
    s = rv_wire(s, 0, term=5)
    s2, _ = step(CFG, s)
    assert int(s2.voted_for[1]) == NIL
    # Response still sent, carrying the newer term so the candidate steps down.
    assert resp_type_of(s2.mailbox, 0, 1) == RESP_VOTE
    assert not resp_ok_of(s2.mailbox, 0, 1)
    assert int(s2.mailbox.resp_term[1]) == 9


def test_vote_denied_stale_log():
    """Up-to-date check (spec 5.4.1): voter's last entry term 3 > candidate's 2."""
    s = with_log(base_state(), 1, [1, 3])
    s = s._replace(term=s.term.at[1].set(4))
    s = rv_wire(s, 0, term=4, last_idx=5, last_term=2)
    s2, _ = step(CFG, s)
    assert not resp_ok_of(s2.mailbox, 0, 1)
    assert int(s2.voted_for[1]) == NIL


def test_vote_denied_shorter_log_same_term():
    """Same last term, candidate's index shorter -> deny."""
    s = with_log(base_state(), 1, [2, 2, 2])
    s = s._replace(term=s.term.at[1].set(3))
    s = rv_wire(s, 0, term=3, last_idx=2, last_term=2)
    s2, _ = step(CFG, s)
    assert not resp_ok_of(s2.mailbox, 0, 1)


def test_single_vote_per_term_lowest_wins():
    """Two simultaneous candidates: one grant only, to the lowest id; the vote is
    remembered in voted_for."""
    s = rv_wire(rv_wire(base_state(), 2, term=2), 3, term=2)
    s2, _ = step(CFG, s)
    assert int(s2.voted_for[0]) == 2
    assert resp_ok_of(s2.mailbox, 2, 0)
    assert not resp_ok_of(s2.mailbox, 3, 0)


def test_revote_same_candidate_is_idempotent():
    """A retransmitted RequestVote from the already-voted-for candidate re-grants."""
    s = base_state()
    s = s._replace(term=s.term.at[0].set(2), voted_for=s.voted_for.at[0].set(2))
    s = rv_wire(rv_wire(s, 2, term=2), 3, term=2)
    s2, _ = step(CFG, s)
    assert resp_ok_of(s2.mailbox, 2, 0)
    assert not resp_ok_of(s2.mailbox, 3, 0)
    assert int(s2.voted_for[0]) == 2


# ------------------------------------------------------------- AppendEntries handling


def ae_wire(s, src, term, prev_i, prev_t, commit, ents, ent_start=None):
    """Broadcast an AppendEntries from `src` (wire format v8): the shared window is
    positioned at `ent_start` (default prev_i, i.e. offset j = 0) and every edge
    carries the offset j = prev_i - ent_start, so each receiver reconstructs
    (prev_i, prev_t, ents). For j >= 1 the window slot j-1 holds prev_t, as a real
    sender's consistent window would."""
    mb = s.mailbox
    start = prev_i if ent_start is None else ent_start
    j = prev_i - start
    mb = mb._replace(
        req_type=mb.req_type.at[src].set(REQ_APPEND),
        req_term=mb.req_term.at[src].set(term),
        req_commit=mb.req_commit.at[src].set(commit),
        ent_start=mb.ent_start.at[src].set(start),
        ent_count=mb.ent_count.at[src].set(j + len(ents)),
        req_off=mb.req_off.at[src, :].set(j),
    )
    if j == 0:
        mb = mb._replace(ent_prev_term=mb.ent_prev_term.at[src].set(prev_t))
    else:
        mb = mb._replace(ent_term=mb.ent_term.at[src, j - 1].set(prev_t))
    for k, (t, v) in enumerate(ents):
        mb = mb._replace(
            ent_term=mb.ent_term.at[src, j + k].set(t),
            ent_val=mb.ent_val.at[src, j + k].set(v),
        )
    return s._replace(mailbox=mb)


def test_append_accept_and_commit_min():
    """Entries appended; follower commit = min(leaderCommit, last new entry) -- the
    reference committed everything unconditionally (bug 2.3.6)."""
    s = base_state()
    s = s._replace(term=s.term.at[1].set(2))
    s = ae_wire(s, 0, term=2, prev_i=0, prev_t=0, commit=5, ents=[(2, 7), (2, 8)])
    s2, _ = step(CFG, s)
    assert int(s2.log_len[1]) == 2
    assert int(s2.commit_index[1]) == 2  # min(5, 2), not 5
    assert int(s2.leader_id[1]) == 0
    assert resp_ok_of(s2.mailbox, 0, 1)
    assert resp_match_of(s2.mailbox, 0, 1) == 2
    np.testing.assert_array_equal(np.asarray(s2.log_val[1, :2]), [7, 8])


def test_append_reject_inconsistent():
    """prev entry missing -> reject, nothing appended (spec 5.3)."""
    s = base_state()
    s = s._replace(term=s.term.at[1].set(2))
    s = ae_wire(s, 0, term=2, prev_i=3, prev_t=1, commit=0, ents=[(2, 7)])
    s2, _ = step(CFG, s)
    assert int(s2.log_len[1]) == 0
    assert resp_type_of(s2.mailbox, 0, 1) == RESP_APPEND
    assert not resp_ok_of(s2.mailbox, 0, 1)


def test_append_conflict_truncates():
    """Follower has [1,1,3]; leader sends prev=1/term1 + entries [(2),(2)] ->
    conflicting suffix replaced, log = [1,2,2] (spec: delete existing entry and all
    that follow; the reference's remove-from! truncated the wrong end, bug 2.3.7)."""
    s = with_log(base_state(), 1, [1, 1, 3])
    s = s._replace(term=s.term.at[1].set(4))
    s = ae_wire(s, 0, term=4, prev_i=1, prev_t=1, commit=0, ents=[(2, 7), (2, 8)])
    s2, _ = step(CFG, s)
    assert int(s2.log_len[1]) == 3
    np.testing.assert_array_equal(np.asarray(s2.log_term[1, :3]), [1, 2, 2])
    np.testing.assert_array_equal(np.asarray(s2.log_val[1, 1:3]), [7, 8])


def test_append_prefix_match_no_truncate():
    """A stale AE covering an existing matching prefix must NOT shrink the log."""
    s = with_log(base_state(), 1, [1, 1, 1, 1])
    s = s._replace(term=s.term.at[1].set(2))
    s = ae_wire(s, 0, term=2, prev_i=0, prev_t=0, commit=0, ents=[(1, 100)])
    s2, _ = step(CFG, s)
    assert int(s2.log_len[1]) == 4  # max(4, 1): matching prefix kept


def test_heartbeat_resets_election_timer_and_demotes_candidate():
    s = base_state()
    s = s._replace(
        role=s.role.at[1].set(CANDIDATE),
        term=s.term.at[1].set(3),
        deadline=s.deadline.at[1].set(2),  # would expire soon
    )
    s = ae_wire(s, 0, term=3, prev_i=0, prev_t=0, commit=0, ents=[])
    inp = quiet_inputs(CFG, far=50)
    s2, _ = step(CFG, s, inp)
    assert int(s2.role[1]) == FOLLOWER
    assert int(s2.leader_id[1]) == 0
    assert int(s2.deadline[1]) == int(s2.clock[1]) + 50


# ------------------------------------------------------------------ response handling


def make_leader(s, node, term):
    n = CFG.n_nodes
    return s._replace(
        role=s.role.at[node].set(LEADER),
        term=s.term.at[node].set(term),
        leader_id=jnp.full((n,), node, jnp.int32),
        next_index=s.next_index.at[node].set(
            jnp.full((n,), int(s.log_len[node]) + 1, s.next_index.dtype)
        ),
    )


def test_candidate_wins_with_quorum():
    s = base_state()
    s = s._replace(
        role=s.role.at[0].set(CANDIDATE),
        term=s.term.at[0].set(2),
        voted_for=s.voted_for.at[0].set(0),
        votes=bitplane.set_bit(s.votes, 0, 0),  # self-vote
    )
    s = resp_wire(s, 0, 1, RESP_VOTE, term=2, ok=True)
    s = resp_wire(s, 0, 2, RESP_VOTE, term=2, ok=True)
    s2, info = step(CFG, s)
    assert int(s2.role[0]) == LEADER
    assert int(s2.leader_id[0]) == 0
    # Fresh leader state: nextIndex = lastLog+1 = 1, matchIndex = 0 (core.clj:40-42).
    assert all(int(x) == 1 for x in np.asarray(s2.next_index[0]))
    assert all(int(x) == 0 for x in np.asarray(s2.match_index[0]))
    # Immediate heartbeat broadcast (core.clj:137-138): empty log -> every peer's
    # window offset is 0 and the window is empty.
    assert int(s2.mailbox.req_type[0]) == REQ_APPEND
    assert int(s2.mailbox.ent_count[0]) == 0
    for p in range(1, 5):
        assert int(s2.mailbox.req_off[0, p]) == 0
    assert int(info.n_leaders) == 1


def test_candidate_needs_quorum():
    """2 of 5 votes (self + one) is not a majority -> still candidate."""
    s = base_state()
    s = s._replace(
        role=s.role.at[0].set(CANDIDATE),
        term=s.term.at[0].set(2),
        votes=bitplane.set_bit(s.votes, 0, 0),
    )
    s = resp_wire(s, 0, 1, RESP_VOTE, term=2, ok=True)
    s2, _ = step(CFG, s)
    assert int(s2.role[0]) == CANDIDATE


def test_stale_vote_response_ignored():
    """A vote response from an older term must not count (core.clj:131-132)."""
    s = base_state()
    s = s._replace(
        role=s.role.at[0].set(CANDIDATE),
        term=s.term.at[0].set(5),
        votes=bitplane.set_bit(s.votes, 0, 0),
    )
    s = resp_wire(s, 0, 1, RESP_VOTE, term=4, ok=True)
    s = resp_wire(s, 0, 2, RESP_VOTE, term=4, ok=True)
    s2, _ = step(CFG, s)
    assert int(s2.role[0]) == CANDIDATE


def test_append_response_success_updates_indices():
    """nextIndex = ackedIndex + 1 (the reference set nextIndex = ackedIndex, 2.3.10)."""
    s = with_log(base_state(), 0, [1, 1, 1])
    s = make_leader(s, 0, 1)
    s = resp_wire(s, 0, 1, RESP_APPEND, term=1, ok=True, match=2)
    s2, _ = step(CFG, s)
    assert int(s2.match_index[0, 1]) == 2
    assert int(s2.next_index[0, 1]) == 4  # max(4, 2+1): never regress below lastLog+1


def test_append_response_failure_decrements_next_index():
    """A nack's match field carries the responder's log length (the conflict-index
    hint, PARITY.md "protocol additions"): next = max(min(next-1, hint+1), 1) --
    an adjacent conflict still steps back one, a far-behind follower is reached in
    one round trip instead of one slot per heartbeat."""
    s = with_log(base_state(), 0, [1, 1, 1])
    s = make_leader(s, 0, 1)
    s = resp_wire(s, 0, 1, RESP_APPEND, term=1, ok=False, match=3)  # hint: len 3
    s = resp_wire(s, 0, 2, RESP_APPEND, term=1, ok=False, match=0)  # hint: empty log
    s2, _ = step(CFG, s)
    assert int(s2.next_index[0, 1]) == 3  # min(4-1, 3+1): plain decrement
    assert int(s2.next_index[0, 2]) == 1  # min(4-1, 0+1): jump straight to 1


def test_leader_steps_down_on_higher_term_response():
    """Higher term in any response -> revert to follower (core.clj:129-130, 144-145)."""
    s = make_leader(base_state(), 0, 2)
    s = resp_wire(s, 0, 1, RESP_APPEND, term=7, ok=False)
    s2, _ = step(CFG, s)
    assert int(s2.role[0]) == FOLLOWER
    assert int(s2.term[0]) == 7
    assert int(s2.leader_id[0]) == NIL


# ----------------------------------------------------------- leader commit advancement


def test_leader_commits_on_majority_match():
    """match = [3(self),2,2,0,0] -> quorum(3)-th largest = 2 -> commit 2. Absent in the
    reference entirely (bug 2.3.8)."""
    s = with_log(base_state(), 0, [1, 1, 1])
    s = make_leader(s, 0, 1)
    s = s._replace(
        match_index=s.match_index.at[0, 1].set(2).at[0, 2].set(2),
    )
    s2, _ = step(CFG, s)
    assert int(s2.commit_index[0]) == 2


def test_leader_does_not_commit_older_term_entries():
    """Spec 5.4.2: only current-term entries commit by counting. Log terms [1,1] but
    leader is at term 3 -> no commit even with full match."""
    s = with_log(base_state(), 0, [1, 1])
    s = make_leader(s, 0, 3)
    s = s._replace(match_index=s.match_index.at[0].set(jnp.full((5,), 2, s.match_index.dtype)))
    s2, _ = step(CFG, s)
    assert int(s2.commit_index[0]) == 0


# ----------------------------------------------------------------- timers & elections


def test_timeout_starts_election():
    cfg = CFG
    s = base_state()
    s = s._replace(deadline=s.deadline.at[2].set(1))  # expires on this tick
    inp = quiet_inputs(cfg, far=20)
    s2, _ = step(cfg, s, inp)
    assert int(s2.role[2]) == CANDIDATE
    assert int(s2.term[2]) == 2
    assert int(s2.voted_for[2]) == 2
    assert bool(bitplane.get_bit(s2.votes, 2, 2))
    assert int(s2.mailbox.req_type[2]) == REQ_VOTE  # broadcast to all peers
    assert int(s2.mailbox.req_term[2]) == 2


def test_leader_heartbeats_on_timer():
    s = with_log(base_state(), 0, [1])
    s = make_leader(s, 0, 1)
    # Peers haven't acked entry 1 yet: nextIndex = 1 -> the heartbeat ships it.
    s = s._replace(
        deadline=s.deadline.at[0].set(1),
        next_index=s.next_index.at[0].set(jnp.ones((5,), s.next_index.dtype)),
    )
    s2, _ = step(CFG, s)
    assert int(s2.mailbox.req_type[0]) == REQ_APPEND
    # Each peer's offset j = 0 into a 1-entry window -> it receives the entry.
    assert int(s2.mailbox.ent_count[0]) == 1
    for p in range(1, 5):
        assert int(s2.mailbox.req_off[0, p]) == 0
    assert int(s2.deadline[0]) == int(s2.clock[0]) + CFG.heartbeat_ticks


def test_dropped_messages_are_dropped():
    """deliver_mask=False edges deliver nothing (the reference's swallowed HTTP
    exception, client.clj:38-40)."""
    s = rv_wire(base_state(), 0, term=5)
    inp = quiet_inputs(CFG, deliver=jnp.ones((5, 5), bool).at[1, 0].set(False))
    s2, _ = step(CFG, s, inp)
    assert int(s2.term[1]) == 1  # nothing adopted
    assert resp_type_of(s2.mailbox, 0, 1) == 0  # no response


def test_client_command_lands_on_leader_only():
    s = make_leader(base_state(), 0, 1)
    inp = quiet_inputs(CFG)._replace(client_cmd=jnp.int32(42))
    s2, _ = step(CFG, s, inp)
    assert int(s2.log_len[0]) == 1
    assert int(s2.log_val[0, 0]) == 42
    assert all(int(x) == 0 for x in np.asarray(s2.log_len[1:]))


# ---------------------------------------------------------- crash/restart fault tests


def test_restart_wipes_volatile_keeps_persistent():
    """Restart keeps the Raft persistent triple (currentTerm, votedFor, log[]) and
    wipes everything else (fig. 2 state table) -- unlike the reference, where only
    committed values survive a process death (log.clj:16-18, bug 2.3.12)."""
    s = with_log(base_state(), 0, [1, 2, 2])
    s = make_leader(s, 0, 2)
    s = s._replace(
        voted_for=s.voted_for.at[0].set(0),
        votes=s.votes.at[0].set(bitplane.full_row(5)),
        match_index=s.match_index.at[0].set(jnp.full((5,), 3, s.match_index.dtype)),
        commit_index=s.commit_index.at[0].set(3),
    )
    s = raft_types.with_commit_chk(s)  # hand-set commit needs a matching checksum
    inp = quiet_inputs(CFG)._replace(restarted=jnp.zeros((5,), bool).at[0].set(True))
    s2, info = step(CFG, s, inp)
    # Persistent: term, vote, log survive.
    assert int(s2.term[0]) == 2
    assert int(s2.voted_for[0]) == 0
    assert int(s2.log_len[0]) == 3
    np.testing.assert_array_equal(np.asarray(s2.log_term[0, :3]), [1, 2, 2])
    # Volatile: role, leader bookkeeping, commit, votes wiped.
    assert int(s2.role[0]) == FOLLOWER
    assert int(s2.leader_id[0]) == NIL
    assert int(s2.commit_index[0]) == 0
    assert int(np.asarray(s2.votes[0]).sum()) == 0
    assert all(int(x) == 1 for x in np.asarray(s2.next_index[0]))
    assert all(int(x) == 0 for x in np.asarray(s2.match_index[0]))
    # The commit wipe is a restart, not a monotonicity violation.
    assert not bool(info.viol_commit)


def test_down_leader_is_silent():
    """A crashed leader fires no heartbeat and emits nothing, so followers' election
    timers run out (the reference analogue: a killed process's peers see timeouts)."""
    s = make_leader(base_state(), 0, 1)
    s = s._replace(deadline=s.deadline.at[0].set(1))  # heartbeat due now
    inp = quiet_inputs(CFG)._replace(alive=jnp.ones((5,), bool).at[0].set(False))
    s2, _ = step(CFG, s, inp)
    assert int(np.asarray(s2.mailbox.req_type).sum()) == 0  # nothing sent
    assert int(s2.role[0]) == LEADER  # state frozen, not demoted, while down
    assert int(s2.deadline[0]) == 1  # timer did not fire or reset


def test_down_node_receives_nothing():
    """Messages to a down node die in flight: no response, no vote, no term adoption."""
    s = rv_wire(base_state(), 0, term=5)
    inp = quiet_inputs(
        CFG,
        # Scope delivery to the down node so live receivers don't react instead.
        deliver=jnp.eye(5, dtype=bool) | jnp.zeros((5, 5), bool).at[1, 0].set(True),
    )._replace(alive=jnp.ones((5,), bool).at[1].set(False))
    s2, _ = step(CFG, s, inp)
    assert int(s2.term[1]) == 1
    assert int(s2.voted_for[1]) == NIL
    assert resp_type_of(s2.mailbox, 0, 1) == 0


def test_down_candidate_cannot_win_on_banked_votes():
    s = base_state()
    s = s._replace(
        role=s.role.at[0].set(CANDIDATE),
        term=s.term.at[0].set(2),
        voted_for=s.voted_for.at[0].set(0),
        votes=s.votes.at[0].set(bitplane.full_row(5)),
    )
    inp = quiet_inputs(CFG)._replace(alive=jnp.ones((5,), bool).at[0].set(False))
    s2, _ = step(CFG, s, inp)
    assert int(s2.role[0]) == CANDIDATE  # not leader while down


def test_append_shared_window_rebase():
    """The shared-window wire format: a receiver whose prev is PAST the window start
    rebases into the sender's shared window (offset > 0) and appends the right
    entries (Mailbox docstring; the per-edge-window form this replaced was the N^2
    mailbox bandwidth hog)."""
    s = with_log(base_state(), 1, [1])  # receiver already has entry 1
    s = s._replace(term=s.term.at[1].set(2))
    # Sender's shared window starts at slot 0 holding [(1,100), (2,7)]; this
    # receiver's prev is 1, so only (2,7) at window offset 1 is for it.
    s = ae_wire(
        s, 0, term=2, prev_i=1, prev_t=1, commit=0,
        ents=[(2, 7)], ent_start=0,
    )
    mb = s.mailbox._replace(
        ent_term=s.mailbox.ent_term.at[0, 0].set(1),
        ent_val=s.mailbox.ent_val.at[0, 0].set(100),
    )
    s2, _ = step(CFG, s._replace(mailbox=mb))
    assert resp_ok_of(s2.mailbox, 0, 1)
    assert int(s2.log_len[1]) == 2
    np.testing.assert_array_equal(np.asarray(s2.log_term[1, :2]), [1, 2])
    np.testing.assert_array_equal(np.asarray(s2.log_val[1, :2]), [100, 7])


def test_committed_prefix_corruption_detected():
    """The carried-checksum invariant (log_ops module comment) must flag a committed
    entry whose value changes -- including corruption introduced BETWEEN ticks, which
    the old same-tick old-vs-new compare could not see."""
    s = with_log(base_state(), 0, [1, 1, 1])
    s = make_leader(s, 0, 1)
    s = s._replace(commit_index=s.commit_index.at[0].set(2))
    s = raft_types.with_commit_chk(s)
    _, info = step(CFG, s)
    assert not bool(info.viol_commit)  # consistent state: no violation
    corrupted = s._replace(log_val=s.log_val.at[0, 1].set(999))  # committed slot
    _, info = step(CFG, corrupted)
    assert bool(info.viol_commit)


def test_window_fallback_when_no_peer_responsive():
    """A leader whose peers ALL aged out of the ack window (total isolation longer
    than ack_timeout_ticks) falls back to the min prev over all peers for the shared
    window start, so its next heartbeat still ships the entries a healed laggard
    needs (raft.py phase 8 fallback arm)."""
    s = with_log(base_state(), 0, [1, 1, 1])
    s = make_leader(s, 0, 1)
    s = s._replace(
        deadline=s.deadline.at[0].set(1),  # heartbeat due now
        # Peer 1 is far behind (next=1 -> prev=0); everyone stale beyond the window.
        next_index=s.next_index.at[0, 1].set(1),
        ack_age=s.ack_age.at[0].set(
            jnp.full((5,), CFG.ack_timeout_ticks + 5, s.ack_age.dtype)
        ),
    )
    s2, _ = step(CFG, s)
    assert int(s2.mailbox.req_type[0]) == REQ_APPEND
    # Fallback: window starts at the ALL-peers min prev (0), not at the responsive
    # min (which is empty); entries from slot 0 ship.
    assert int(s2.mailbox.ent_start[0]) == 0
    assert int(s2.mailbox.ent_count[0]) == 3
    assert int(s2.mailbox.req_off[0, 1]) == 0


def test_stale_peer_excluded_from_window_start():
    """A single unresponsive laggard must NOT pin the window: the shared window
    starts at the min prev over RESPONSIVE peers, and the stale peer's offset is
    lifted to the window start."""
    s = with_log(base_state(), 0, [1, 1, 1])
    s = make_leader(s, 0, 1)
    ages = jnp.zeros((5,), s.ack_age.dtype).at[1].set(CFG.ack_timeout_ticks + 5)
    s = s._replace(
        deadline=s.deadline.at[0].set(1),
        # Stale peer 1 is far behind; responsive peers 2-4 are at prev=2.
        next_index=s.next_index.at[0].set(
            jnp.asarray([4, 1, 3, 3, 3], s.next_index.dtype)
        ),
        ack_age=s.ack_age.at[0].set(ages),
    )
    s2, _ = step(CFG, s)
    assert int(s2.mailbox.req_type[0]) == REQ_APPEND
    assert int(s2.mailbox.ent_start[0]) == 2  # responsive min, not peer 1's 0
    assert int(s2.mailbox.req_off[0, 1]) == 0  # stale peer lifted to window start
    assert int(s2.mailbox.req_off[0, 2]) == 0  # responsive peers at their own prev


def test_stale_append_entries_nacked_with_newer_term():
    """An AE from a deposed leader (lower term) must be rejected, and the response
    must carry the follower's newer term so the stale leader steps down (the
    request side of core.clj:144-145's step-down; spec 5.1)."""
    s = base_state()
    s = s._replace(term=s.term.at[1].set(5))
    s = ae_wire(s, 0, term=3, prev_i=0, prev_t=0, commit=0, ents=[(3, 7)])
    s2, _ = step(CFG, s)
    assert int(s2.log_len[1]) == 0  # nothing appended
    assert resp_type_of(s2.mailbox, 0, 1) == RESP_APPEND  # still answered
    assert not resp_ok_of(s2.mailbox, 0, 1)
    assert int(s2.mailbox.resp_term[1]) == 5  # carries the newer term
    assert int(s2.leader_id[1]) == NIL  # stale sender not adopted as leader


def test_client_command_rejected_when_log_full():
    """A leader whose fixed-capacity log is full must drop offered commands (the
    static-shape analogue of the reference's unbounded vector, SURVEY.md 7.3) --
    and report the offer as not accepted."""
    s = with_log(base_state(), 0, [1] * CFG.log_capacity)  # full log
    s = make_leader(s, 0, 1)
    inp = quiet_inputs(CFG)._replace(client_cmd=jnp.int32(42))
    s2, info = step(CFG, s, inp)
    assert int(s2.log_len[0]) == CFG.log_capacity  # unchanged
    assert 42 not in np.asarray(s2.log_val[0])
    assert int(info.cmds_injected) == 0
