from raft_sim_tpu.parallel.mesh import (
    AXIS,
    FleetSummary,
    make_mesh,
    simulate_sharded,
    summarize,
)

__all__ = ["AXIS", "FleetSummary", "make_mesh", "simulate_sharded", "summarize"]
