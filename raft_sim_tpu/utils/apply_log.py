"""Committed-value export stream -- the `node_<id>.log` analogue.

The reference's only durable artifact is an append-only file per node to which
every committed value is written at apply time: the writer is opened per node
(log.clj:32, filename `node_<id>.log` from core.clj:17) and `apply-entries!`
appends each newly committed value plus newline (log.clj:16-18, 74-75). The
file is never read back -- it exists as a host-observable apply stream.

The simulator's equivalent: an `ApplyLogWriter` attached to ONE selected
cluster exports each node's newly committed values to `node_<i>.log` in a
directory, appended at chunk boundaries (driver.Session.run drives it between
jitted chunks; the values are read host-side from the ring, so the export
costs one tiny device_get per chunk and nothing inside the scan).

Two deliberate deltas from a naive file tail:
  - Leader no-op entries (types.NOOP, appended on election wins under
    compaction) are internal protocol filler, not applied client values --
    they are skipped, so the stream is exactly the committed CLIENT values.
  - Ring compaction can discard entries before they were ever exported (a
    node that catches up via the InstallSnapshot analogue never materializes
    the compacted prefix -- there is nothing to read). Such spans appear as a
    `# snapshot gap A..B` marker line, mirroring what the reference node
    would experience if it could snapshot: the values themselves are simply
    not observable at this node. On healthy chunk cadences (chunk ticks small
    enough that commit advances less than CAP - margin per chunk) no gaps
    occur; tests/test_apply_log.py pins both regimes.
"""

from __future__ import annotations

import os

import jax
import numpy as np

from raft_sim_tpu.types import NOOP
from raft_sim_tpu.utils.config import RaftConfig


class ApplyLogWriter:
    """Appends newly committed values of one cluster to per-node files.

    `update(state)` exports everything committed since the last call; call it
    at chunk boundaries (Session wires this automatically) and once at the end.
    Files are truncated on construction (the reference's writer also starts
    fresh per process, log.clj:32).
    """

    def __init__(self, directory: str, cfg: RaftConfig, cluster: int = 0):
        self.cfg = cfg
        self.cluster = cluster
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.paths = [
            os.path.join(directory, f"node_{i}.log") for i in range(cfg.n_nodes)
        ]
        for p in self.paths:
            open(p, "w").close()
        # Last exported 1-based entry index per node (host-side, monotone --
        # a restarted node's regressed commit simply exports nothing new).
        self.frontier = [0] * cfg.n_nodes

    def update(self, state) -> int:
        """Export entries committed since the last call. `state` is the batched
        [B, ...] ClusterState; returns the number of values written. Only the
        three leaves the export reads cross to the host (commit, base, values
        of the one selected cluster) -- not the whole state."""
        c = self.cluster
        commits, bases, log_vals = jax.device_get(
            (state.commit_index[c], state.log_base[c], state.log_val[c])
        )
        cap = self.cfg.log_capacity
        written = 0
        for i in range(self.cfg.n_nodes):
            commit = int(commits[i])
            base = int(bases[i])
            # The ring read below assumes every entry in (base, commit] is
            # still LIVE (the export runs at chunk boundaries, before further
            # ticks can compact past it). If a layout or call-ordering
            # regression ever violates that, the reads would silently decode
            # unrelated ring content as committed values -- fail loudly
            # instead (round-5 advisor hardening). A real raise, not `assert`:
            # the guard must survive `python -O`.
            if commit - base > cap:
                raise RuntimeError(
                    f"apply-log export would read compacted slots: node {i} "
                    f"commit {commit} - base {base} > capacity {cap} "
                    "(state advanced past a chunk boundary before update()?)"
                )
            f = self.frontier[i]
            if commit <= f:
                continue
            with open(self.paths[i], "a") as fh:
                if f < base:
                    # Entries (f, base] were compacted before this export saw
                    # them: they exist only as the snapshot triple. Gap-marking
                    # happens at READ time (idx1 <= base never reaches the
                    # value loop below), so a span lost to compaction can
                    # never be exported as garbage values.
                    fh.write(f"# snapshot gap {f + 1}..{base}\n")
                    f = base
                vals = np.asarray(log_vals[i])
                for idx1 in range(f + 1, commit + 1):
                    v = int(vals[(idx1 - 1) % cap])
                    if v != NOOP:
                        fh.write(f"{v}\n")
                        written += 1
            self.frontier[i] = commit
        return written

    def values(self, node: int) -> list[int]:
        """The exported value stream of one node (gap markers excluded)."""
        out = []
        with open(self.paths[node]) as fh:
            for line in fh:
                if not line.startswith("#"):
                    out.append(int(line))
        return out

    def gaps(self, node: int) -> list[tuple[int, int]]:
        """(first, last) 1-based index spans lost to compaction at `node`."""
        out = []
        with open(self.paths[node]) as fh:
            for line in fh:
                if line.startswith("# snapshot gap "):
                    a, b = line.split()[-1].split("..")
                    out.append((int(a), int(b)))
        return out
