"""The obs subsystem: per-chunk runtime attribution, perf.jsonl schema, and
measured-vs-predicted reconciliation.

The load-bearing properties, pinned:
  - perf is INERT: a run with a ChunkTimer attached is bit-exact with one
    without (the timer is host-side by construction), and `--profile` capture
    is likewise bit-exact vs no capture.
  - perf.jsonl is schema'd: the sink validates the stream, and a corrupted
    row is a visible validation error, not a silent skip.
  - the recompile watchdog fires on a real mid-run recompile and stays quiet
    on the known one-time donated-carry respecialization (obs/timer.py
    docstring).
  - reconciliation math against the REAL golden Pass C pins, including the
    trap this PR exists to close: a CPU / smoke / non-production row is
    explicitly non-anchor and can never rebase the roofline.

Compile budget: one tiny chunk program (module fixture, 3-node shapes shared
with the forced-recompile test's warm phase), one n=8 chunk variant (the
forced recompile itself), and one tiny `scan.simulate` (shared by the profile
guard and the bench steady-stats test). The serve-session and search perf
streams ride the slow tier: their tier-1 siblings (test_serve, test_scenario)
already compile those programs, and the hooks they exercise are the same
ChunkTimer the fixture covers.
"""

import io
import json
import os
import sys

import jax
import numpy as np
import pytest

from raft_sim_tpu import RaftConfig, init_batch
from raft_sim_tpu.obs import (
    ChunkTimer,
    load_pins,
    reconcile_matrix,
    reconcile_perf_dir,
    reconcile_row,
)
from raft_sim_tpu.obs.timer import summarize_rows
from raft_sim_tpu.obs.reconcile import read_perf
from raft_sim_tpu.sim import chunked, scan
from raft_sim_tpu.utils import telemetry_sink

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CFG = RaftConfig(n_nodes=3, log_capacity=8, client_interval=4)
BATCH, TICKS, CHUNK = 2, 64, 16


def _setup(seed=0):
    root = jax.random.key(seed)
    ki, kr = jax.random.split(root)
    return init_batch(CFG, ki, BATCH), jax.random.split(kr, BATCH)


def tree_eq(a, b, msg=""):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb), err_msg=msg)


@pytest.fixture(scope="module")
def perf_run(tmp_path_factory):
    """ONE chunked run instrumented with a sink-attached ChunkTimer, plus the
    identical un-instrumented run -- shared by the bit-exactness, schema, and
    attribution tests (one compiled chunk program for the module)."""
    state, keys = _setup()
    plain = chunked.run_chunked(CFG, state, keys, TICKS, chunk=CHUNK)
    d = str(tmp_path_factory.mktemp("perf_sink"))
    sink = telemetry_sink.TelemetrySink(
        d, CFG, seed=0, batch=BATCH, window=CHUNK, ring=0, source="test"
    )
    timer = ChunkTimer(label="run", batch=BATCH, sink=sink)
    inst = chunked.run_chunked(CFG, state, keys, TICKS, chunk=CHUNK, perf=timer)
    return {"plain": plain, "inst": inst, "timer": timer, "dir": d,
            "state": state, "keys": keys}


def test_perf_is_bit_exact(perf_run):
    """Acceptance: the instrumented run's state AND metrics equal the plain
    run's bit-for-bit -- attribution never perturbs a trajectory."""
    tree_eq(perf_run["plain"], perf_run["inst"], "perf instrumentation drifted")


def test_perf_jsonl_schema_validates(perf_run):
    assert telemetry_sink.validate(perf_run["dir"]) == []
    rows = read_perf(perf_run["dir"])
    assert len(rows) == TICKS // CHUNK
    assert [r["chunk"] for r in rows] == list(range(len(rows)))


def test_perf_attribution_semantics(perf_run):
    """Warmup flags, phase arithmetic, and the file-vs-live rollup contract."""
    t = perf_run["timer"]
    rows = t.rows
    assert [r["warmup"] for r in rows] == [True, True, False, False]
    for r in rows:
        assert r["ticks"] == CHUNK
        # Phases partition the wall (to rounding).
        assert abs(
            r["wall_s"] - (r["dispatch_s"] + r["host_s"] + r["device_wait_s"])
        ) < 1e-5
        assert isinstance(r["jit_cache"], dict) and r["jit_cache"]
    s = t.summary()
    assert s["steady_chunks"] == 2 and s["steady_ticks"] == 2 * CHUNK
    assert s["steady_cluster_ticks_per_s"] > 0
    assert not s["recompiled_after_warmup"]
    # Re-reading perf.jsonl reproduces the live summary (what metrics_report
    # --perf renders must be what the driver printed).
    refile = summarize_rows(read_perf(perf_run["dir"]), label="run", batch=BATCH)
    assert refile == s


def test_validate_catches_corrupt_perf_rows(perf_run, tmp_path):
    import shutil

    d = str(tmp_path / "bad")
    shutil.copytree(perf_run["dir"], d)
    with open(os.path.join(d, "perf.jsonl"), "a") as f:
        f.write(json.dumps({"chunk": 99, "ticks": -1}) + "\n")
    errors = telemetry_sink.validate(d)
    assert any("perf.jsonl" in e and "wall_s" in e for e in errors)
    assert any("chunk index 99" in e for e in errors)


def test_recompile_watchdog_fires_on_forced_recompile(perf_run):
    """Negative: a chunk-size change mid-stream forces a fresh lowering of
    the chunk program; the watchdog must mark the row and the summary, and
    finish() must print the visible finding. The warm phases reuse the
    fixture's compiled program, so this costs ONE tiny n=8 compile."""
    state, keys = perf_run["state"], perf_run["keys"]
    t = ChunkTimer(label="run", batch=BATCH)
    # Warmup + baseline at the fixture's (cached) chunk shape...
    chunked.run_chunked(CFG, state, keys, 2 * CHUNK, chunk=CHUNK, perf=t)
    chunked.run_chunked(CFG, state, keys, 2 * CHUNK, chunk=CHUNK, perf=t)
    assert not t.summary()["recompiled_after_warmup"]
    # ...then a different static chunk length = a forced recompile.
    chunked.run_chunked(CFG, state, keys, 8, chunk=8, perf=t)
    assert t.rows[-1]["recompiled"]
    err = io.StringIO()
    s = t.finish(out=err)
    assert s["recompiled_after_warmup"]
    assert "perf watchdog" in err.getvalue()
    assert "chunked._chunk_donate" in err.getvalue()


@pytest.mark.slow  # budget re-tier (PR 12): the profiler context wraps the
# UNCHANGED jitted calls (capture-vs-no-capture is a jax-runtime property,
# not a program of ours), and the serve/search profile captures already ride
# the slow tier -- this run-loop capture guard joins them; every other
# test_obs row (bit-exactness of instrumented runs, watchdog, schema) stays
# tier-1.
def test_profile_capture_is_bit_exact(tmp_path):
    """Tier-1 guard for the promoted --profile flag: a run captured under
    jax.profiler.trace equals an uncaptured run bit-for-bit."""
    ref = scan.simulate(CFG, 0, BATCH, 32)
    with jax.profiler.trace(str(tmp_path / "trace")):
        cap = scan.simulate(CFG, 0, BATCH, 32)
    tree_eq(ref, cap, "profiler capture changed the trajectory")


def test_bench_rows_carry_steady_stats():
    """Satellite: bench rows exclude the warmup repeat from steady-state
    ticks/s, expose per-repeat variance, keep the legacy field under a
    `legacy` marker, and record the backend (the anchor filter's key)."""
    import bench as bench_mod

    row = bench_mod.bench(CFG, BATCH, 32, repeats=3, config_name="custom")
    assert row["steady_ticks_per_s"] > 0
    assert len(row["repeat_walls_s"]) == 3
    assert row["repeat_cv"] is not None and row["repeat_cv"] >= 0
    assert "cluster_ticks_per_s" in row["legacy"]
    assert row["backend"] == jax.default_backend()
    # Steady math: mean of the non-warmup walls.
    steady = row["repeat_walls_s"][1:]
    expect = BATCH * 32 / np.mean(steady)
    assert abs(row["steady_ticks_per_s"] - expect) / expect < 0.05


# --------------------------------------------------------- reconciliation


PINS = load_pins()


def test_reconcile_math_against_golden_pins():
    """Satellite: reconciliation against the REAL Pass C pins -- a synthetic
    chip row at half the pinned config5 roofline must come back with
    fraction 0.5, achieved bytes/s = measured x pinned bytes/tick, and
    anchor eligibility."""
    pin = PINS["programs"]["config5/simulate"]
    half = pin["roofline_ticks_per_s"] / 2
    row = {"steady_ticks_per_s": half, "batch": 10_000, "backend": "tpu"}
    r = reconcile_row("config5", row, PINS)
    assert r["anchor"] and r["non_anchor_reasons"] == []
    assert abs(r["roofline_fraction"] - 0.5) < 1e-3
    assert abs(
        r["achieved_bytes_per_s"] - half * pin["bytes_per_tick_padded"]
    ) < 1.0
    assert r["measured_source"] == "steady"


def test_reconcile_legacy_rows_fall_back_with_note():
    row = {"cluster_ticks_per_s": 2.0e6, "batch": 10_000}
    r = reconcile_row("config5", row, PINS, default_backend=None)
    assert r["measured_source"] == "legacy-best"
    assert any("legacy" in n for n in r["notes"])
    # Unknown backend is conservatively non-anchor.
    assert not r["anchor"]
    assert any("backend unrecorded" in n for n in r["non_anchor_reasons"])


def test_reconcile_cpu_row_never_anchors():
    """THE trap this subsystem must not reopen: a CPU row at production
    batch, not smoke, not scenario -- still non-anchor, explicitly."""
    row = {"steady_ticks_per_s": 5.0e4, "batch": 10_000, "backend": "cpu"}
    r = reconcile_row("config5", row, PINS)
    assert not r["anchor"]
    assert any("CPU run can never rebase" in n for n in r["non_anchor_reasons"])
    doc = reconcile_matrix({"matrix": {"config5": row}}, pins=PINS)
    assert doc["anchor_eligible"] == []
    assert any("must not be saved" in n for n in doc["notes"])


def test_reconcile_smoke_and_batch_rules():
    smoke = {"steady_ticks_per_s": 1e6, "batch": 10_000, "backend": "tpu",
             "smoke": True}
    assert not reconcile_row("config5", smoke, PINS)["anchor"]
    off_batch = {"steady_ticks_per_s": 1e6, "batch": 16, "backend": "tpu"}
    r = reconcile_row("config5", off_batch, PINS)
    assert not r["anchor"]
    assert any("production" in n for n in r["non_anchor_reasons"])


def test_reconcile_stale_pin_note():
    """Measured ABOVE the pinned roofline = the pins are stale; the row must
    say so (the regenerate signal, mirroring bench's headroom semantics)."""
    pin = PINS["programs"]["config5/simulate"]
    row = {"steady_ticks_per_s": pin["roofline_ticks_per_s"] * 1.2,
           "batch": 10_000, "backend": "tpu"}
    r = reconcile_row("config5", row, PINS)
    assert r["roofline_fraction"] > 1.0
    assert any("stale" in n for n in r["notes"])


def test_reconcile_without_pins_degrades_visibly():
    doc = reconcile_matrix(
        {"matrix": {"config5": {"steady_ticks_per_s": 1e6, "batch": 10_000,
                                "backend": "tpu"}}},
        pins={},
    )
    assert any("pins unavailable" in n for n in doc["notes"])
    assert doc["rows"][0]["roofline_fraction"] is None


def test_reconcile_perf_dir_joins_manifest_and_rows(perf_run):
    res = reconcile_perf_dir(perf_run["dir"], pins=PINS)
    s = perf_run["timer"].summary()
    assert res["summary"]["steady_cluster_ticks_per_s"] == (
        s["steady_cluster_ticks_per_s"]
    )
    r = res["reconciliation"]
    assert not r["anchor"]  # cpu backend from the manifest
    # The module config matches no preset: reported, not crashed.
    assert any("no preset" in n for n in r["notes"])


# ------------------------------------------------- measurement-pass artifact


def _synthetic_measurement(tmp_path) -> str:
    doc = {
        "schema": "measurement-pass-v1",
        "backend": "cpu", "jax_version": jax.__version__, "smoke": True,
        "repeats": 2,
        "matrix": {"config5": {"steady_ticks_per_s": 5.0e4, "batch": 16,
                               "backend": "cpu", "smoke": True}},
        "ab": {
            "bitpack_vs_r05": {"r05": {}, "measured": {},
                               "measured_over_r05": {}, "notes": []},
            "fault_lattice": {"label": "x", "off": {}, "on": {},
                              "on_over_off_ticks_per_s": 0.5, "notes": []},
            "serve_offer_plane": {"label": "x", "off": {}, "on": {},
                                  "on_over_off_ticks_per_s": 0.99, "notes": []},
        },
        "reconciliation": reconcile_matrix(
            {"matrix": {"config5": {"steady_ticks_per_s": 5.0e4, "batch": 16,
                                    "backend": "cpu", "smoke": True}}},
            pins=PINS,
        ),
        "trajectory": [{"source": "BENCH_r05.json", "round": 5,
                        "ticks_per_s": {"config5": 2078975.4}}],
        "notes": ["newest hardware artifact is round 5"],
    }
    path = str(tmp_path / "MEASUREMENT_r99.json")
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def test_measurement_report_renders(tmp_path):
    from tools import metrics_report

    out = io.StringIO()
    metrics_report.report_measurement(_synthetic_measurement(tmp_path), out=out)
    text = out.getvalue()
    assert "measured vs predicted" in text
    assert "non-anchor" in text
    assert "fault_lattice" in text and "serve_offer_plane" in text
    assert "BENCH_r05.json" in text  # the trajectory table
    assert "round 5" in text  # the unmeasured-gap flag


def test_measurement_report_refuses_unknown_schema(tmp_path):
    from tools import metrics_report

    path = str(tmp_path / "bogus.json")
    with open(path, "w") as f:
        json.dump({"schema": "not-a-measurement"}, f)
    with pytest.raises(SystemExit):
        metrics_report.report_measurement(path)


# ------------------------------------------------------ loop streams (slow)


@pytest.mark.slow
def test_serve_session_perf_stream(tmp_path):
    """The serve loop's perf stream: warmup accounting covers the session's
    warmup chunks + the respecialization chunk, rows validate through the
    sink, and the flat-cache discipline test_serve pins shows up as a quiet
    watchdog. Slow tier: the tier-1 serve fixture already compiles this
    program shape; this exercises only the timer plumbing around it."""
    from raft_sim_tpu.serve.ingest import CommandSource
    from raft_sim_tpu.serve.loop import ServeSession, serve_config

    cfg = serve_config(RaftConfig(n_nodes=3, log_capacity=8))
    d = str(tmp_path / "sink")
    sink = telemetry_sink.TelemetrySink(
        d, cfg, seed=0, batch=BATCH, window=16, ring=0, source="serve"
    )
    t = ChunkTimer(label="serve", batch=BATCH, sink=sink)
    sess = ServeSession(cfg, batch=BATCH, seed=0, chunk=32, window=16,
                        sink=sink, warmup_ticks=32, perf=t)
    stats = sess.serve(CommandSource(iter([5, 6, 7])), drain_chunks=2)
    assert stats["perf"]["chunks"] == len(t.rows) >= 3
    # Session warmup chunk + first serving chunk are both warmup rows.
    assert t.warmup_chunks == 2
    assert not stats["perf"]["recompiled_after_warmup"]
    assert telemetry_sink.validate(d) == []


@pytest.mark.slow
def test_search_perf_stream():
    """The hunt's per-generation attribution: one row per generation, the
    windowed program's cache sampled and flat (genomes are traced data)."""
    from raft_sim_tpu.scenario import search as search_mod

    t = ChunkTimer(label="search", batch=8)
    spec = search_mod.SearchSpec(generations=3, population=8, ticks=32,
                                 window=16)
    search_mod.search(CFG, spec, perf=t)
    assert len(t.rows) == 3
    assert all(r["ticks"] == 32 for r in t.rows)
    caches = [r["jit_cache"]["telemetry.simulate_windowed"] for r in t.rows]
    assert len(set(caches)) == 1
    assert not t.summary()["recompiled_after_warmup"]
