"""Shared randomness helpers (single source of truth for timer distributions)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_sim_tpu.utils.config import RaftConfig


def draw_timeouts(cfg: RaftConfig, key: jax.Array, n: int) -> jax.Array:
    """Randomized election timeouts in ticks, one per node (the reference's
    5000 + rand(5000) ms, core.clj:174). Used both for initial deadlines and for every
    timer reset so both come from the same distribution."""
    return jax.random.randint(
        key,
        (n,),
        cfg.election_min_ticks,
        cfg.election_min_ticks + cfg.election_range_ticks,
        jnp.int32,
    )
