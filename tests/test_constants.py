"""Drift tests: the oracle re-states several implementation constants in its own
words (tests/oracle.py must stay import-independent of raft_sim_tpu so it is a real
second implementation). These tests pin each restated constant/formula to the
original, so an update to one side without the other fails loudly instead of
surfacing as a mystery parity diff."""

import numpy as np

from raft_sim_tpu import types
from raft_sim_tpu.ops import log_ops
from raft_sim_tpu.utils import config
from tests import oracle


def test_ack_age_sat_matches():
    assert oracle.ACK_AGE_SAT == config.ACK_AGE_SAT == types.ACK_AGE_SAT
    assert oracle.ACK_AGE_SAT_NARROW == config.ACK_AGE_SAT_NARROW == types.ACK_AGE_SAT_NARROW
    # The saturation-ceiling selection formula, restated by the oracle, must
    # agree with the config property at both tiers.
    from raft_sim_tpu.utils.config import RaftConfig

    for timeout in (7, 12, 100, 119, 120, 500):
        cfg = RaftConfig(ack_timeout_ticks=timeout)
        assert oracle.ack_age_sat(cfg) == cfg.ack_age_sat


def test_pack_width_table_matches():
    """The compacted layout's pack-width table -- bits, bias, AND value range
    per leg -- single-sourced in ops/tile.pack_width_table (the plans and the
    value-range audit read it) and restated independently by the oracle
    (oracle.pack_widths). Pinned across every audited tier, including the
    compacted ones (config5c/config7x) and a compaction tier (no index legs)."""
    from raft_sim_tpu.analysis.jaxpr_audit import AUDIT_CONFIGS
    from raft_sim_tpu.ops import tile

    for name in AUDIT_CONFIGS:
        cfg, _batch = config.PRESETS[name]
        assert oracle.pack_widths(cfg) == tile.pack_width_table(cfg), name
    # The plans must size their pack legs from the same table.
    for name in ("config5c", "config7x", "config6"):
        cfg, _batch = config.PRESETS[name]
        widths = tile.pack_width_table(cfg)
        plans = list(tile.state_plan(cfg)) + [
            (f"mb.{f}", mode, shape, bits, bias, dt)
            for f, mode, shape, bits, bias, dt in tile.mailbox_plan(cfg)
        ]
        for f, mode, _shape, bits, bias, _dt in plans:
            if mode != "pack":
                continue
            wbits, wbias, lo, hi = widths[f]
            assert (bits, bias) == (wbits, wbias), (name, f)
            # The declared range, biased, must exactly need the allotted bits.
            assert lo + wbias == 0 or f == "next_index", (name, f)
            assert hi + wbias < (1 << wbits), (name, f)
            assert hi + wbias >= (1 << (wbits - 1)) or wbits == 1, (name, f)


def test_int8_ceilings_derive_from_encoding_bounds():
    """types.py's int8 ceilings are policy-sourced, not hand literals: they
    derive from the window-min encoding bound (3*cap + 2 fits the dtype) and
    the node-id sentinel bound (n fits with a slot to spare)."""
    assert types.MAX_INT8_LOG_CAPACITY == config.max_log_capacity_for(127) == 41
    assert types.MAX_INT8_NODES == config.max_nodes_for(127) == 126
    assert config.window_min_encoding_max(types.MAX_INT8_LOG_CAPACITY) <= 127
    assert config.window_min_encoding_max(types.MAX_INT8_LOG_CAPACITY + 1) > 127
    assert config.window_min_encoding_max(config.MAX_LOG_CAPACITY) <= 32767


def test_noop_sentinel_matches():
    assert oracle.NOOP == types.NOOP
    assert types.NOOP != types.NIL  # distinct sentinels


def test_chk_weights_at_extends_chk_weights():
    """The absolute-index weight form (ring compaction) agrees with the per-slot
    form on the first CAP indices and with the oracle far beyond them."""
    import jax.numpy as jnp

    cap = 32
    w_t, w_v = log_ops.chk_weights(cap)
    w_t2, w_v2 = log_ops.chk_weights_at(jnp.arange(cap, dtype=jnp.uint32))
    np.testing.assert_array_equal(np.asarray(w_t), np.asarray(w_t2))
    np.testing.assert_array_equal(np.asarray(w_v), np.asarray(w_v2))
    far = np.array([100, 5000, 2**20, 2**31 - 1], dtype=np.uint32)
    g_t, g_v = log_ops.chk_weights_at(jnp.asarray(far))
    want = np.array([oracle.chk_weights(int(a)) for a in far], dtype=np.uint32)
    np.testing.assert_array_equal(np.asarray(g_t), want[:, 0])
    np.testing.assert_array_equal(np.asarray(g_v), want[:, 1])


def test_chk_weights_match():
    cap = 64
    w_t, w_v = log_ops.chk_weights(cap)
    want = np.array([oracle.chk_weights(k) for k in range(cap)], dtype=np.uint32)
    np.testing.assert_array_equal(np.asarray(w_t), want[:, 0])
    np.testing.assert_array_equal(np.asarray(w_v), want[:, 1])


def test_wire_constants_match():
    """Roles, request/response kinds, and the nil sentinel -- the enums both the
    mailbox type plane (v9) and the oracle's dispatch compare against."""
    assert (oracle.FOLLOWER, oracle.CANDIDATE, oracle.LEADER) == (
        types.FOLLOWER,
        types.CANDIDATE,
        types.LEADER,
    )
    assert (oracle.REQ_NONE, oracle.REQ_VOTE, oracle.REQ_APPEND) == (
        types.REQ_NONE,
        types.REQ_VOTE,
        types.REQ_APPEND,
    )
    assert (oracle.RESP_NONE, oracle.RESP_VOTE, oracle.RESP_APPEND) == (
        types.RESP_NONE,
        types.RESP_VOTE,
        types.RESP_APPEND,
    )
    assert oracle.NIL == types.NIL
