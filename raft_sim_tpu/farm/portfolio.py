"""Portfolio members: the farm's parallel fitness functions.

One CE hunt optimizes ONE notion of "closer to breaking", and the scalar
weights encode one hypothesis about where bugs live. The farm hedges: it
partitions the fleet's batch axis among MEMBERS -- each a named fitness
function with its own CE distribution -- exactly the way serve/tenancy.py
partitions tenants, so a 5-member portfolio still evaluates in ONE
`simulate_windowed` call per generation (the genome rows differ per cluster;
the compiled program never sees the partition).

Each fitness is a host-side function over the member's slice of the fetched
telemetry windows: `f(records, metrics, novelty) -> [b] float64`, where
`novelty` is the per-cluster count of coverage bits unseen farm-wide before
this generation (None when the farm runs untraced). Violations dominate
lexicographically in EVERY member -- the portfolio diversifies the gradient
toward trouble, never the definition of trouble itself.
"""

from __future__ import annotations

import numpy as np

from raft_sim_tpu.scenario import search as search_mod


# The counter interpretations (leaderless windows, term churn, commit
# stalls) are search.py's shared extractors -- one reading of the telemetry
# plane for the scalar blend and every member here.
def _viol(metrics) -> np.ndarray:
    return search_mod.W_VIOLATION * np.asarray(metrics.violations, np.float64)


def fit_scalar(records, metrics, novelty) -> np.ndarray:
    """The hand-tuned distress blend `scenario search` default mode uses."""
    return search_mod.fitness_from_records(records, metrics)


def fit_coverage(records, metrics, novelty) -> np.ndarray:
    """Transition-coverage novelty against the FARM-WIDE seen set: new
    protocol behavior scores, repeats do not (violations still dominate --
    an all-bits-seen generation must not zero the violation term)."""
    if novelty is None:
        raise ValueError("coverage member needs a traced farm (novelty=None)")
    return _viol(metrics) + novelty


def fit_multi_leader(records, metrics, novelty) -> np.ndarray:
    """Hunt split-brain exposure directly: concurrent LEADER ticks are the
    election-safety precursor (docs/SCENARIOS.md), here promoted from one
    term of the scalar blend to the member's whole objective."""
    multi = np.asarray(metrics.multi_leader, np.float64)
    return _viol(metrics) + 50.0 * multi + search_mod.term_churn(metrics)


def fit_commit_stall(records, metrics, novelty) -> np.ndarray:
    """Hunt liveness collapse: windows whose commit frontier froze under a
    live client workload -- the precondition for commit/completeness breaks
    (a leader that cannot advance is a leader about to be replaced by one
    missing entries)."""
    return (
        _viol(metrics)
        + 20.0 * search_mod.commit_stalls(records, metrics)
        + 5.0 * search_mod.leaderless_windows(records)
    )


def fit_read_staleness(records, metrics, novelty) -> np.ndarray:
    """Hunt stale-read preconditions: a deposed-but-unaware leader serving
    reads needs concurrent leadership AND read traffic actually flowing, so
    weight split-brain exposure with a small served-read term (no reads, no
    stale serves) -- viol_read_stale itself rides the dominant violation
    term (scan.step_bad folds it)."""
    multi = np.asarray(metrics.multi_leader, np.float64)
    reads = np.asarray(metrics.reads_served, np.float64)
    return (
        _viol(metrics)
        + 30.0 * multi
        + 5.0 * search_mod.leaderless_windows(records)
        + search_mod.term_churn(metrics)
        + 0.01 * reads
    )


def fit_durability(records, metrics, novelty) -> np.ndarray:
    """Hunt committed-while-volatile exposure (raft_sim_tpu/storage): the
    ack-before-fsync loss needs the commit frontier to ADVANCE while the
    durable watermark lags (entries counted off volatile acks), then crash
    churn to truncate and re-elect -- so weight each window's commit advance
    by its fsync lag and add the churn that converts exposure into loss.
    The pure-distress members anti-select this (a stalled cluster commits
    nothing, so nothing it commits can be lost); zero exposure term when
    the storage plane is off (the lag counters are gated device zeros)."""
    mc = np.asarray(records.metrics.max_commit, np.float64)  # [B, W]
    lag = np.asarray(records.metrics.fsync_lag_max, np.float64)  # [B, W]
    if mc.shape[1] > 1:
        adv = np.clip(np.diff(mc, axis=1), 0.0, None)
        exposure = (adv * np.minimum(lag[:, 1:], 8.0)).sum(axis=1)
    else:
        exposure = np.zeros(mc.shape[0])
    # Exposure DOMINANT, churn a tiebreak only: the distress terms the other
    # members lean on anti-correlate with the traffic this exposure needs,
    # and letting them lead walks the CE distribution into partition-dead
    # clusters (churn without commits can never lose a committed entry).
    return (
        _viol(metrics)
        + 5.0 * exposure
        + search_mod.term_churn(metrics)
    )


# name -> (fitness fn, needs the trace-variant program for its signal).
FITNESS = {
    "scalar": (fit_scalar, False),
    "coverage": (fit_coverage, True),
    "multi_leader": (fit_multi_leader, False),
    "commit_stall": (fit_commit_stall, False),
    "read_staleness": (fit_read_staleness, False),
    "durability": (fit_durability, False),
}


def parse_portfolio(names) -> tuple[str, ...]:
    """Validate a portfolio member list (a comma string or iterable of
    registry names). Duplicate members are legal -- two 'scalar' members run
    independent CE distributions over disjoint slices -- but get distinct
    hunt-stream names from the farm (scalar, scalar2, ...)."""
    if isinstance(names, str):
        names = [n.strip() for n in names.split(",") if n.strip()]
    names = tuple(names)
    if not names:
        raise ValueError("a portfolio needs at least one member")
    unknown = [n for n in names if n not in FITNESS]
    if unknown:
        raise ValueError(
            f"unknown portfolio member(s) {unknown} (have {sorted(FITNESS)})"
        )
    return names
