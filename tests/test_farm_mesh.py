"""Batch-sharding the farm over the cluster mesh (parallel.simulate_windowed_sharded
+ farm/core.py `mesh`): every generation is ONE shard_map'ped windowed scan, and
the hunt is BIT-IDENTICAL to the unsharded farm at any device count -- keys split
outside the sharded region, so hits / coverage / manifest hash never depend on
the hardware. The jit cache holds exactly one entry per (config, mesh): genome
values are traced data, so generations never recompile."""

import dataclasses

import jax
import numpy as np
import pytest

from raft_sim_tpu import RaftConfig
from raft_sim_tpu.farm import FarmSpec, run_farm
from raft_sim_tpu.parallel import make_mesh
from raft_sim_tpu.parallel import mesh as mesh_mod
from raft_sim_tpu.sim import telemetry

CFG = RaftConfig(n_nodes=5, client_interval=6, drop_prob=0.15, crash_prob=0.05,
                 crash_period=32, crash_down_ticks=8)


def _assert_tree_equal(a, b, tag=""):
    la, lb = jax.tree.leaves(jax.device_get(a)), jax.tree.leaves(jax.device_get(b))
    assert len(la) == len(lb)
    for i, (x, y) in enumerate(zip(la, lb)):
        np.testing.assert_array_equal(x, y, err_msg=f"{tag}[{i}]")


@pytest.mark.slow
def test_windowed_sharded_parity():
    """The evaluator alone: state + metrics + window records bit-equal to
    telemetry.simulate_windowed over 8 devices (untraced, no genome).
    Slow tier: the CI mesh-smoke job owns it; tier-1 keeps the wired-in
    farm parity (test_farm_mesh_parity_and_flat_cache) instead."""
    out_s = mesh_mod.simulate_windowed_sharded(CFG, 3, 16, 120, 30, make_mesh(8))
    out_d = telemetry.simulate_windowed(CFG, 3, 16, 120, 30)
    for tag, a, b in zip(("state", "metrics", "records"), out_s[:3], out_d[:3]):
        _assert_tree_equal(a, b, tag)
    assert out_s[3] is None  # the recorder slot (farms never ring)


def test_farm_mesh_parity_and_flat_cache():
    """The wired farm, tier-1 slice: an unguided scalar hunt over the 8-device
    mesh produces the SAME generation rows, hits, and manifest hash as the
    unsharded farm -- and the whole hunt costs ONE compile (genome rows are
    traced data; the cache must not grow past the first generation). The
    guided variant (trace plane + genome path live) rides the slow tier
    below; CI mesh-smoke runs it every PR."""
    spec = FarmSpec(portfolio=("scalar",), budget_gens=2, population=8,
                    ticks=64, window=32, seed=11, guided=False,
                    stop_on="budget")
    r_d = run_farm(CFG, spec)
    n0 = mesh_mod.simulate_windowed_sharded._cache_size()
    r_s = run_farm(CFG, spec, mesh=make_mesh(8))
    assert r_s.generations == r_d.generations
    assert r_s.hits == r_d.hits
    assert r_s.manifest["manifest_hash"] == r_d.manifest["manifest_hash"]
    # One (config, mesh) program for the whole hunt, not one per generation.
    assert mesh_mod.simulate_windowed_sharded._cache_size() == n0 + 1


@pytest.mark.slow
def test_farm_mesh_guided_parity_and_flat_cache():
    """The guided hunt (trace plane + genome path live) over the mesh:
    identical rows, hits, manifest hash AND coverage bits vs the unsharded
    farm, still one compile for the whole hunt."""
    spec = FarmSpec(portfolio=("scalar", "coverage"), budget_gens=2,
                    population=16, ticks=128, window=32, seed=11,
                    stop_on="budget")
    r_d = run_farm(CFG, spec)
    n0 = mesh_mod.simulate_windowed_sharded._cache_size()
    r_s = run_farm(CFG, spec, mesh=make_mesh(8))
    assert r_s.generations == r_d.generations
    assert r_s.hits == r_d.hits
    assert r_s.manifest["manifest_hash"] == r_d.manifest["manifest_hash"]
    assert r_s.manifest["cov_bits_total"] == r_d.manifest["cov_bits_total"]
    assert mesh_mod.simulate_windowed_sharded._cache_size() == n0 + 1


def test_farm_rejects_indivisible_population():
    with pytest.raises(ValueError, match="divide over"):
        run_farm(CFG, FarmSpec(population=10, budget_gens=1),
                 mesh=make_mesh(8))


@pytest.mark.slow
def test_farm_device_count_invariance():
    """1/2/4/8 devices: identical hunt rows at every width, one cache entry
    per mesh (the device-count axis adds programs, generations never do)."""
    spec = FarmSpec(portfolio=("scalar",), budget_gens=2, population=16,
                    ticks=96, window=32, seed=7, guided=False,
                    stop_on="budget")
    base = run_farm(CFG, spec).generations
    n0 = mesh_mod.simulate_windowed_sharded._cache_size()
    for i, d in enumerate((1, 2, 4, 8), start=1):
        r = run_farm(CFG, spec, mesh=make_mesh(d))
        assert r.generations == base, f"{d} devices diverged"
        assert mesh_mod.simulate_windowed_sharded._cache_size() == n0 + i


# ------------------------------------------- device-count-keyed anchor guard


def test_bench_anchor_rejects_device_count_mismatched_rows(tmp_path):
    """A mesh_scaling row (n_devices > 1) reports AGGREGATE mesh throughput
    and must never rebase the single-device roofline anchor -- the same trap
    class bench_anchor already closes for layouts. Rows without the field
    (every pre-mesh artifact) are single-device and still anchor; an explicit
    n_devices=1 row anchors too."""
    import json

    from raft_sim_tpu.analysis import cost_model

    doc = {
        "matrix": {
            "config3": {"cluster_ticks_per_s": 320e6, "batch": 100_000,
                        "n_devices": 8},
            "config4": {"cluster_ticks_per_s": 23e6, "batch": 100_000,
                        "n_devices": 1},
            "config5": {"cluster_ticks_per_s": 9e6, "batch": 10_000},
        }
    }
    (tmp_path / "BENCH_r99.json").write_text(json.dumps(doc))
    anchors, source, notes = cost_model.bench_anchor(str(tmp_path))
    assert "config3" not in anchors
    assert anchors == {"config4": 23e6, "config5": 9e6}
    assert any("config3" in n and "devices" in n for n in notes)


def test_reconcile_marks_device_count_mismatch_non_anchor():
    from raft_sim_tpu.obs import reconcile

    row = {"steady_ticks_per_s": 320e6, "batch": 100_000, "n_devices": 8}
    reasons = reconcile.non_anchor_reasons("config3", row, "tpu")
    assert any("single-device roofline" in r for r in reasons)
    one = {"steady_ticks_per_s": 40e6, "batch": 100_000, "n_devices": 1}
    assert reconcile.non_anchor_reasons("config3", one, "tpu") == []
    legacy = {"steady_ticks_per_s": 40e6, "batch": 100_000}
    assert reconcile.non_anchor_reasons("config3", legacy, "tpu") == []


@pytest.mark.slow
def test_mesh_scaling_leg_rows_are_cpu_non_anchor():
    """bench --measurement-pass's mesh_scaling leg end to end on the virtual
    mesh: one fixed global batch at 1/2/4/8 devices, every CPU row marked
    non-anchor, and D>1 rows carrying the device-count reason on top."""
    import types

    import bench as bench_mod

    args = types.SimpleNamespace(mesh_preset="config1", repeats=1)
    leg = bench_mod._mesh_scaling_leg(args, True, "cpu")
    assert set(leg["rows"]) == {"1dev", "2dev", "4dev", "8dev"}
    for row in leg["rows"].values():
        assert row["anchor"] is False
        assert any("CPU run" in r for r in row["non_anchor_reasons"])
    assert any("single-device roofline" in r
               for r in leg["rows"]["8dev"]["non_anchor_reasons"])
    assert not any("single-device roofline" in r
                   for r in leg["rows"]["1dev"]["non_anchor_reasons"])
    assert leg["speedup_vs_1dev"]["1dev"] == 1.0


@pytest.mark.slow
def test_windowed_sharded_genome_values_do_not_recompile():
    """New genome VALUES reuse the compiled program (the scenario-engine
    contract, extended to the sharded evaluator)."""
    from raft_sim_tpu.scenario import genome as gm
    from raft_sim_tpu.scenario import search as sm

    tcfg = dataclasses.replace(CFG, track_trace=True)
    from raft_sim_tpu.trace.ring import TraceSpec

    ts = TraceSpec(depth=8, coverage=True)
    knobs = sm.default_knobs(tcfg)
    rng = np.random.default_rng(0)
    mk = lambda: gm.stack_rows(
        [sm.decode_row(tcfg, knobs, x) for x in rng.random((8, len(knobs)))]
    )
    mesh = make_mesh(8)
    mesh_mod.simulate_windowed_sharded(tcfg, 5, 8, 64, 32, mesh,
                                       genome=mk(), trace=ts)
    n0 = mesh_mod.simulate_windowed_sharded._cache_size()
    mesh_mod.simulate_windowed_sharded(tcfg, 6, 8, 64, 32, mesh,
                                       genome=mk(), trace=ts)
    assert mesh_mod.simulate_windowed_sharded._cache_size() == n0
