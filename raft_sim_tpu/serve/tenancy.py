"""Multi-tenant partitioning of a standing serve fleet.

The serve loop holds B independent Raft clusters as one compiled program; a
TENANT is a named contiguous slice of that cluster range with its own
CommandSource, its own ReadIndex demand, and its own export streams. The
batch axis IS the tenancy axis: the router below turns per-tenant ingest
queues into the [T, B] per-cluster offer/read planes `run_windowed_served`
consumes, and splits the per-cluster outputs (window records, delta rows)
back per tenant -- so adding, removing, or resizing tenants changes HOST
bookkeeping only. The compiled chunk program never sees the partition
(shapes are (chunk, B) at every tenant count; tests pin the jit cache flat
across 1/2/4-tenant sessions).

Export layout under a serving sink directory (docs/OBSERVABILITY.md):

    <dir>/tenants.json                 {name: {"lo": c0, "hi": c1,
                                        "offered", "acked", "reads_offered",
                                        "reads_served"}} -- written at the
                                        end of the session (ServeSession).
    <dir>/tenants/<name>/windows.jsonl the tenant's cluster slice aggregated
                                        with the SAME line schema as the
                                        fleet windows.jsonl (one shared
                                        aggregation: telemetry_sink.
                                        window_lines).
    <dir>/tenants/<name>/deltas.jsonl  the tenant's commit-delta rows, with
                                        clusters renumbered TENANT-LOCAL
                                        (cluster - lo), so a tenant's stream
                                        is self-contained and validates with
                                        serve.deltas.validate_deltas.

The fleet-level windows.jsonl / deltas.jsonl keep the whole-fleet streams;
the per-tenant files are views, not replacements.
"""

from __future__ import annotations

import json
import os

import numpy as np

from raft_sim_tpu.serve import deltas as deltas_mod
from raft_sim_tpu.serve.ingest import CommandSource, pack_plane
from raft_sim_tpu.types import NIL, NOOP


def split_even(total: int, n: int) -> list[int]:
    """Balanced contiguous partition sizes: `total` clusters over `n` tenants,
    remainders to the earliest. THE partition policy -- the serve CLI and the
    bench serve row both build their tenant lists from it, so a future policy
    change (e.g. weighted CLUSTER shares) is one edit. Tick-share QoS is the
    other axis and already exists: Tenant.weight gates the offer schedule."""
    if not 1 <= n <= total:
        raise ValueError(f"cannot split {total} clusters over {n} tenants")
    return [total // n + (i < total % n) for i in range(n)]


class Tenant:
    """One logical tenant: `clusters` of the fleet's batch range, a command
    source (any payload iterable / CommandSource; None = read-only tenant),
    and a ReadIndex demand of `reads` reads offered at most one per cluster
    every `read_every` ticks. Reads are fungible (no payload), so the
    tenant's read ack is its served-read count reaching the demand -- the
    router re-offers until the telemetry windows credit enough serves, which
    makes dropped offers (leaderless tick, busy read slot) retries, not
    losses."""

    def __init__(self, name: str, clusters: int, source=None, reads: int = 0,
                 read_every: int = 2, broadcast: bool = False,
                 weight: int = 1):
        if clusters < 1:
            raise ValueError(f"tenant {name!r} needs >= 1 cluster")
        if reads < 0:
            raise ValueError(f"tenant {name!r}: reads must be >= 0")
        if read_every < 1:
            raise ValueError(f"tenant {name!r}: read_every must be >= 1")
        if not isinstance(weight, int) or weight < 1:
            raise ValueError(
                f"tenant {name!r}: weight must be an integer >= 1 (integer "
                "Bresenham credit -- floats would make the offer schedule "
                "platform-dependent)"
            )
        self.name = name
        self.clusters = clusters
        # QoS weight (ROADMAP item 2's named follow-up): the share of OFFER
        # TICKS this tenant gets relative to the heaviest tenant. The
        # scheduler is host-side only -- it changes which slots of the
        # packed [chunk, B] planes carry NIL, never a shape -- so the jit
        # cache stays flat across any weighting (tests pin it).
        self.weight = weight
        if source is not None and not isinstance(source, CommandSource):
            source = CommandSource(source)
        self.source = source
        self.reads = reads
        self.read_every = read_every
        # broadcast=True: one logical client over the tenant's whole slice --
        # each command is offered to EVERY cluster of the slice that tick
        # (the pre-tenancy ServeSession semantics; serve()'s legacy source
        # path uses it for its "default" tenant). False: commands spread one
        # per (tick, cluster) slot, pack_plane order.
        self.broadcast = broadcast
        # Assigned by TenantRouter:
        self.lo = self.hi = 0
        # Read-cadence position IN THE TENANT'S ACTIVE-TICK SEQUENCE (the
        # router advances it by the weighted schedule's row count each
        # chunk). Counting active ticks -- not raw global phase -- keeps the
        # cadence and the weight schedule composable: a global-phase anchor
        # can land on a residue the Bresenham schedule never selects
        # (weight 1 of w_max 2 activates odd ticks only; a read_every=2
        # phase gate wants even ones) and starve a tenant's reads forever.
        self._read_seq = 0
        # Ledgers:
        self.reads_offered = 0
        self.reads_served = 0  # credited from collected window records
        self.acked_values: list[int] = []
        self.delta_rows: list[dict] = []

    @property
    def writes_done(self) -> bool:
        return self.source is None or self.source.exhausted

    @property
    def reads_done(self) -> bool:
        return self.reads_served >= self.reads

    @property
    def offered(self) -> int:
        return 0 if self.source is None else self.source.offered


class TenantRouter:
    """Partition a B-cluster fleet among tenants and route planes/streams.

    `pack(chunk)` -> (cmds [chunk, B], reads [chunk, B] | None): each
    tenant's queued commands packed into its lane slice (ingest.pack_plane,
    the one packing helper) and its outstanding read demand offered at its
    cadence. `credit_windows(records)` / `route_deltas(rows)` push each
    chunk's outputs back to the owning tenants (and their sink files, when a
    directory is attached).
    """

    def __init__(self, tenants: list[Tenant], batch: int,
                 reads_enabled: bool):
        if not tenants:
            raise ValueError("need at least one tenant")
        names = [t.name for t in tenants]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate tenant names: {names}")
        total = sum(t.clusters for t in tenants)
        if total != batch:
            raise ValueError(
                f"tenant cluster counts sum to {total}, fleet batch is "
                f"{batch}: the partition must cover the cluster range exactly"
            )
        if any(t.reads for t in tenants) and not reads_enabled:
            raise ValueError(
                "a tenant demands reads but the serve config carries no "
                "ReadIndex plane (cfg.serve_reads / read cadence)"
            )
        self.tenants = tenants
        self.batch = batch
        self.reads_enabled = reads_enabled
        lo = 0
        for t in tenants:
            t.lo, t.hi = lo, lo + t.clusters
            lo = t.hi
        self._by_cluster = np.zeros(batch, np.int32)
        for i, t in enumerate(tenants):
            self._by_cluster[t.lo:t.hi] = i
        self._dir = None
        self._tenant_windows: dict[str, int] = {}
        self._read_phase = 0  # global tick phase of the read cadence
        # Weighted offer scheduler (per-tenant QoS): tenant t is offered on
        # the tick slots where its Bresenham credit line crosses an integer
        # -- floor((k+1) * w_t / w_max) > floor(k * w_t / w_max) at global
        # tick k -- so over any window its offer ticks are w_t / w_max of
        # the heaviest tenant's, deterministically and without drift. All
        # weights equal (the default) makes every slot active: the
        # pre-weights schedule, bit-for-bit.
        self._w_max = max(t.weight for t in tenants)

    # ------------------------------------------------------------- export IO

    def attach_dir(self, directory: str) -> None:
        """Arm per-tenant stream files under `directory`/tenants/<name>/
        (truncated up front, like the fleet streams)."""
        self._dir = directory
        for t in self.tenants:
            d = os.path.join(directory, "tenants", t.name)
            os.makedirs(d, exist_ok=True)
            open(os.path.join(d, "windows.jsonl"), "w").close()
            open(os.path.join(d, "deltas.jsonl"), "w").close()
            self._tenant_windows[t.name] = 0

    def write_manifest(self, path: str) -> None:
        doc = {
            t.name: {
                "lo": t.lo, "hi": t.hi,
                "offered": t.offered,
                "acked": len(t.acked_values),
                "reads_offered": t.reads_offered,
                "reads_served": t.reads_served,
            }
            for t in self.tenants
        }
        with open(path, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")

    # ------------------------------------------------------------ plane side

    def _active_rows(self, t: Tenant, chunk: int) -> list[int]:
        """The weighted offer schedule: which of this chunk's tick slots
        tenant t may offer in (writes AND read re-offers). Bresenham credit
        against the heaviest weight, anchored on the global tick phase."""
        w, wm = t.weight, self._w_max
        k0 = self._read_phase
        return [
            k for k in range(chunk)
            if ((k0 + k + 1) * w) // wm > ((k0 + k) * w) // wm
        ]

    def pack(self, chunk: int) -> tuple[np.ndarray, np.ndarray | None]:
        """The next chunk's per-cluster planes from every tenant's queues."""
        cmds = np.full((chunk, self.batch), NIL, np.int32)
        reads = (
            np.full((chunk, self.batch), NIL, np.int32)
            if self.reads_enabled
            else None
        )
        for t in self.tenants:
            rows = self._active_rows(t, chunk)
            if t.source is not None and not t.source.exhausted and rows:
                if t.broadcast:
                    vals = t.source.next_values(len(rows))
                    cmds[rows, t.lo:t.hi] = pack_plane(vals, len(rows), 1)
                else:
                    vals = t.source.next_values(len(rows) * t.clusters)
                    cmds[rows, t.lo:t.hi] = pack_plane(
                        vals, len(rows), t.clusters
                    )
            if reads is not None and t.reads_served < t.reads:
                # Offer up to the OUTSTANDING demand (demand minus serves
                # already credited -- crediting lags a chunk, so the
                # over-offer is bounded by one chunk's serves; reads are
                # fungible and extra serves are harmless), at most one read
                # per cluster every read_every ACTIVE ticks of the tenant's
                # weighted schedule (t._read_seq -- see its init comment for
                # why the cadence must not anchor on global phase): dropped
                # offers re-offer next chunk. All weights equal, rows is
                # every tick and _read_seq IS the global phase -- the
                # pre-weights schedule bit-for-bit.
                want = t.reads - t.reads_served
                for j, k in enumerate(rows):
                    if want <= 0:
                        break
                    if (t._read_seq + j) % t.read_every:
                        continue
                    lanes = min(want, t.clusters)
                    reads[k, t.lo:t.lo + lanes] = 1
                    t.reads_offered += lanes
                    want -= lanes
            t._read_seq = (t._read_seq + len(rows)) % (2 ** 30)
        self._read_phase = (self._read_phase + chunk) % (2 ** 30)
        return cmds, reads

    # ----------------------------------------------------------- output side

    def credit_windows(self, records) -> None:
        """Per-tenant telemetry: slice this chunk's stacked WindowRecord by
        cluster range, credit served reads against each tenant's demand, and
        append tenant windows.jsonl lines (the shared window_lines schema)."""
        import jax

        from raft_sim_tpu.utils.telemetry_sink import window_lines

        for t in self.tenants:
            sl = jax.tree.map(lambda x: np.asarray(x)[t.lo:t.hi], records)
            t.reads_served += int(
                np.asarray(sl.metrics.reads_served, np.int64).sum()
            )
            if self._dir is not None:
                lines = window_lines(sl, self._tenant_windows[t.name])
                path = os.path.join(
                    self._dir, "tenants", t.name, "windows.jsonl"
                )
                with open(path, "a") as f:
                    for line in lines:
                        f.write(json.dumps(line) + "\n")
                self._tenant_windows[t.name] += len(lines)

    def route_deltas(self, rows: list[dict]) -> None:
        """Split drained delta rows by owning tenant: tenant-local cluster
        renumbering, ack ledger, and the per-tenant deltas.jsonl stream."""
        per: dict[str, list[dict]] = {t.name: [] for t in self.tenants}
        for row in rows:
            t = self.tenants[int(self._by_cluster[row["cluster"]])]
            local = dict(row, cluster=row["cluster"] - t.lo)
            t.delta_rows.append(local)
            t.acked_values.extend(v for v in row["values"] if v != NOOP)
            per[t.name].append(local)
        if self._dir is not None:
            for t in self.tenants:
                if per[t.name]:
                    deltas_mod.append_delta_rows(
                        os.path.join(
                            self._dir, "tenants", t.name, "deltas.jsonl"
                        ),
                        per[t.name],
                    )

    # ----------------------------------------------------------- stop logic

    @property
    def exhausted(self) -> bool:
        """Every tenant's write source is dry AND every read demand met."""
        return all(t.writes_done and t.reads_done for t in self.tenants)

    @property
    def offered(self) -> int:
        return sum(t.offered for t in self.tenants)

    @property
    def reads_offered(self) -> int:
        return sum(t.reads_offered for t in self.tenants)

    @property
    def reads_served(self) -> int:
        return sum(t.reads_served for t in self.tenants)
