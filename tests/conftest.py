"""Test env: run JAX on CPU with 8 virtual devices so the multi-chip sharding tier can
be tested without TPU hardware (SURVEY.md section 4). The TPU plugin in this image
registers itself via sitecustomize and overrides JAX_PLATFORMS, so the CPU platform is
forced through jax.config after import instead; XLA_FLAGS must still carry the virtual
device count before the CPU client is first created."""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")
