"""Drift tests: the oracle re-states several implementation constants in its own
words (tests/oracle.py must stay import-independent of raft_sim_tpu so it is a real
second implementation). These tests pin each restated constant/formula to the
original, so an update to one side without the other fails loudly instead of
surfacing as a mystery parity diff."""

import numpy as np

from raft_sim_tpu import types
from raft_sim_tpu.ops import log_ops
from raft_sim_tpu.utils import config
from tests import oracle


def test_ack_age_sat_matches():
    assert oracle.ACK_AGE_SAT == config.ACK_AGE_SAT == types.ACK_AGE_SAT


def test_noop_sentinel_matches():
    assert oracle.NOOP == types.NOOP
    assert types.NOOP != types.NIL  # distinct sentinels


def test_chk_weights_at_extends_chk_weights():
    """The absolute-index weight form (ring compaction) agrees with the per-slot
    form on the first CAP indices and with the oracle far beyond them."""
    import jax.numpy as jnp

    cap = 32
    w_t, w_v = log_ops.chk_weights(cap)
    w_t2, w_v2 = log_ops.chk_weights_at(jnp.arange(cap, dtype=jnp.uint32))
    np.testing.assert_array_equal(np.asarray(w_t), np.asarray(w_t2))
    np.testing.assert_array_equal(np.asarray(w_v), np.asarray(w_v2))
    far = np.array([100, 5000, 2**20, 2**31 - 1], dtype=np.uint32)
    g_t, g_v = log_ops.chk_weights_at(jnp.asarray(far))
    want = np.array([oracle.chk_weights(int(a)) for a in far], dtype=np.uint32)
    np.testing.assert_array_equal(np.asarray(g_t), want[:, 0])
    np.testing.assert_array_equal(np.asarray(g_v), want[:, 1])


def test_chk_weights_match():
    cap = 64
    w_t, w_v = log_ops.chk_weights(cap)
    want = np.array([oracle.chk_weights(k) for k in range(cap)], dtype=np.uint32)
    np.testing.assert_array_equal(np.asarray(w_t), want[:, 0])
    np.testing.assert_array_equal(np.asarray(w_v), want[:, 1])


def test_pack_resp_matches():
    import jax.numpy as jnp

    samples = [
        (rtype, ok, match)
        for rtype in (0, 1, 2, 3)
        for ok in (0, 1)
        for match in (0, 1, 7, 2047, config.MAX_LOG_CAPACITY)
    ]
    for rtype, ok, match in samples:
        want = oracle.pack_resp(rtype, ok, match)
        got = types.pack_resp(
            jnp.int32(rtype), jnp.int32(ok), jnp.int32(match)
        )
        assert int(got) == np.int16(want), (rtype, ok, match)
        for unpack in (types.unpack_resp, oracle.unpack_resp):
            rt, o, m = unpack(np.int16(want))
            assert (int(rt), int(o), int(m)) == (rtype, ok, match)
