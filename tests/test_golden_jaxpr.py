"""Golden jaxpr snapshots + the compile-count regression pin.

Two structural guards over the hot-path programs, both lowering-only (no XLA
compile, so this module costs seconds, not scan-compile minutes):

1. **Op-histogram snapshot** (tests/golden_jaxpr_hist.json): primitive counts
   bucketed by output dtype for the N=5 (config3) and N=51 (config5) step
   programs, both kernel forms. A hot-path regression -- a new [N, N, B]
   materialization, a dtype flip, a lost fusion opportunity -- shows up as a
   reviewable count diff instead of a benchmark surprise on the next chip
   session. Counts are exact for a fixed jax version (recorded in the file);
   under a different jax the exact comparison is skipped and only the
   version-independent invariants (no float primitives) are asserted.

   Regenerate after an INTENDED kernel change:
       JAX_PLATFORMS=cpu python tests/test_golden_jaxpr.py --update

2. **Compile-count pin**: the number of distinct jit lowerings the preset
   matrix induces, for the step kernel and the full scan program. Every
   distinct scan program costs ~15-40 s of tier-1 compile time on CPU
   (ROADMAP's 870 s budget); this pin makes adding one a conscious, reviewed
   bump instead of a silent budget leak. The fork-pair rule (analysis
   rule recompile-fork, run in the tools/check.py gate) guards the other
   direction: tuning-only config changes must NOT add programs.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import pytest

from raft_sim_tpu.analysis import jaxpr_audit as JA
from raft_sim_tpu.utils.config import PRESETS

GOLDEN_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden_jaxpr_hist.json")

# The snapshotted step programs: (golden key, preset, batched kernel form).
SNAPSHOT_PROGRAMS = (
    ("config3/step", "config3", False),
    ("config3/step_b", "config3", True),
    ("config5/step", "config5", False),
    ("config5/step_b", "config5", True),
)

# Distinct lowerings across the preset matrix (8 presets, all structurally
# distinct today: different N/CAP/E shapes or different feature gates). Bump
# ONLY with a new preset or a deliberate program fork -- each distinct scan
# program is ~15-40 s of tier-1 compile budget. The pins live in
# golden_jaxpr_hist.json ("lowerings"; these constants are the
# regeneration defaults) so a bump is a reviewable snapshot diff.
# The scenario engine adds AT MOST one scan-shaped lowering per preset (the
# genome input path; step kernels are untouched, so zero extra step
# lowerings) and NEVER one per genome or segment -- genome values are traced
# data, pinned by the analyzer's scenario fork check (jaxpr_audit).
# 10 = the 8 pre-v22 presets' programs + config3p (the PreVote bench row:
# pre_vote is a structural gate, so its program is a deliberate fork) +
# config8 (the reconfiguration plane: membership/transfer/read legs live).
# 11 adds config9 (lease-based reads: the lease serve predicate, vote
# denial, and the read_fr staleness leg are structural).
# 12 adds config5c (the compacted carry layout, ops/tile.py: pack/unpack at
# the kernel boundary is a structural fork by design -- one program per
# LAYOUT, never per tuning value, which the config5c fork pair pins).
# 14 adds the giant-N tiers config7 (N=101) / config7x (N=255, compacted):
# cluster size is a shape, so each is one deliberate program fork.
PINNED_STEP_LOWERINGS = 14
PINNED_SCAN_LOWERINGS = 14
PINNED_SCENARIO_SCAN_LOWERINGS = 14
# The standing-fleet serve program (serve/loop.py simulate_serve): one program
# per structurally distinct serve-mode config. Serve variants collapse the
# scheduled cadence (client_interval -> 0), so presets differing ONLY in their
# cadence share one serve program (config2's serve variant IS config3's) --
# which is why this pin sits below the preset count. Command values are traced
# data: a multi-chunk `driver serve` session compiles nothing after warmup.
# (+ config3p / config8 serve variants: 7 -> 9; + config9's lease-read
# serve variant: 10; + config5c's compacted-layout serve variant: 11;
# + config7 / config7x giant-N serve variants: 13.)
PINNED_SERVE_SCAN_LOWERINGS = 13
# The protocol-trace program (telemetry windowed scan + event ring + coverage
# legs, raft_sim_tpu/trace): at most one per preset -- these are "the pinned
# trace variants" ISSUE 9's acceptance names: tracing adds ZERO step lowerings
# (extraction is delta-based outside the kernels) and the coverage search's
# generations all reuse one trace program (genomes are traced data; the
# analyzer's trace fork pairs pin value-invariance).
# + config3p/config8/config9 trace variants; + config5c's compacted-layout
# trace variant (12); + the config7/config7x giant-N trace variants (14).
PINNED_TRACE_SCAN_LOWERINGS = 14


def _pins():
    try:
        with open(GOLDEN_PATH) as f:
            low = json.load(f).get("lowerings", {})
    except FileNotFoundError:
        low = {}
    return (
        low.get("step", PINNED_STEP_LOWERINGS),
        low.get("scan", PINNED_SCAN_LOWERINGS),
        low.get("scenario_scan", PINNED_SCENARIO_SCAN_LOWERINGS),
        low.get("serve_scan", PINNED_SERVE_SCAN_LOWERINGS),
        low.get("trace_scan", PINNED_TRACE_SCAN_LOWERINGS),
    )


def _histograms():
    out = {}
    for key, preset, batched in SNAPSHOT_PROGRAMS:
        cfg, _ = PRESETS[preset]
        out[key] = JA.op_histogram(JA.step_jaxpr(cfg, batched=batched))
    return out


def test_golden_op_histograms():
    with open(GOLDEN_PATH) as f:
        golden = json.load(f)
    actual = _histograms()
    # Version-independent invariant first: the step programs are float-free.
    for key, hist in actual.items():
        floats = [k for k in hist if "float" in k or "bfloat" in k]
        assert not floats, f"{key}: float primitives in the step program: {floats}"
    if golden["jax_version"] != jax.__version__:
        pytest.skip(
            f"golden recorded under jax {golden['jax_version']}, running "
            f"{jax.__version__}: exact op counts are version-specific"
        )
    for key, hist in actual.items():
        want = golden["programs"][key]
        if hist != want:
            diff = {
                k: (want.get(k, 0), hist.get(k, 0))
                for k in sorted(set(want) | set(hist))
                if want.get(k, 0) != hist.get(k, 0)
            }
            raise AssertionError(
                f"{key}: op histogram drifted (golden, actual): {diff}\n"
                "If the kernel change is intended, regenerate with:\n"
                "  JAX_PLATFORMS=cpu python tests/test_golden_jaxpr.py --update"
            )


def test_compile_count_pin():
    pin_step, pin_scan, pin_scenario, pin_serve, pin_trace = _pins()
    step_hashes = set()
    scan_hashes = set()
    scenario_hashes = set()
    serve_hashes = set()
    trace_hashes = set()
    for name, (cfg, _) in PRESETS.items():
        # The giant-N tiers pay ~11s of N=101/255 tracing per family; their
        # fork-detection runs in the slow sweep below (CI mesh-smoke owns it
        # via test_nodeshard's slow set every PR). The pins cover them, so
        # the tier-1 subset can only under-count, never false-pass a fork
        # among the standing presets.
        if name.startswith("config7"):
            continue
        step_hashes.add(JA.program_hash(JA.step_jaxpr(cfg, batched=True)))
        scan_hashes.add(JA.program_hash(JA.scan_jaxpr(cfg)))
        scenario_hashes.add(JA.program_hash(JA.scenario_scan_jaxpr(cfg)))
        serve_hashes.add(
            JA.program_hash(JA.serve_scan_jaxpr(JA.serve_variant(cfg)))
        )
        trace_hashes.add(
            JA.program_hash(JA.trace_scan_jaxpr(JA.trace_variant(cfg)))
        )
    assert len(step_hashes) <= pin_step, (
        f"{len(step_hashes)} distinct step_b lowerings across the preset "
        f"matrix (pinned {pin_step}): a config that should share "
        "a program now forks one. Each distinct scan program costs ~15-40 s "
        "of tier-1 compile budget -- deduplicate, or bump the pin consciously."
    )
    assert len(scan_hashes) <= pin_scan, (
        f"{len(scan_hashes)} distinct scan lowerings across the preset matrix "
        f"(pinned {pin_scan}); see golden_jaxpr_hist.json 'lowerings'."
    )
    # The scenario (genome-path) scan: at most ONE lowering per preset --
    # never one per genome or per segment count in use (genomes are traced
    # data; the analyzer's scenario fork pairs pin value-invariance, this
    # pins the preset-matrix total).
    assert len(scenario_hashes) <= pin_scenario, (
        f"{len(scenario_hashes)} distinct scenario_simulate lowerings across "
        f"the preset matrix (pinned {pin_scenario}): the genome path must add "
        "at most one program per preset; a genome- or segment-dependent "
        "structure is the exact recompile-per-sweep-point failure the "
        "scenario engine exists to remove."
    )
    # The serve program: at most one lowering per structurally distinct
    # serve-mode config (command values are traced data -- a standing
    # `driver serve` session must compile NOTHING after warmup).
    assert len(serve_hashes) <= pin_serve, (
        f"{len(serve_hashes)} distinct serve_simulate lowerings across the "
        f"preset matrix (pinned {pin_serve}): a command- or chunk-content-"
        "dependent structure would recompile the standing fleet mid-session."
    )
    # The trace program: at most one per preset, and ZERO extra step
    # lowerings (the step_hashes pin above already covers that claim --
    # trace-mode configs compile the same step kernels).
    assert len(trace_hashes) <= pin_trace, (
        f"{len(trace_hashes)} distinct trace_simulate lowerings across the "
        f"preset matrix (pinned {pin_trace}): a trace-depth- or coverage-"
        "dependent structural fork would recompile the coverage hunt per "
        "sweep point (the scenario-engine failure mode, ISSUE 4/9)."
    )


def _update():
    doc = {
        "jax_version": jax.__version__,
        "lowerings": {
            "step": PINNED_STEP_LOWERINGS,
            "scan": PINNED_SCAN_LOWERINGS,
            "scenario_scan": PINNED_SCENARIO_SCAN_LOWERINGS,
            "serve_scan": PINNED_SERVE_SCAN_LOWERINGS,
            "trace_scan": PINNED_TRACE_SCAN_LOWERINGS,
        },
        "programs": _histograms(),
    }
    with open(GOLDEN_PATH, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {GOLDEN_PATH} under jax {jax.__version__}")


if __name__ == "__main__":
    if "--update" in sys.argv:
        _update()
    else:
        print(__doc__)
