"""Fixed-capacity replicated-log array ops.

The reference's log is an unbounded Clojure vector in an atom with 1-based indexing
where index 0 means "no entry" (log.clj:20-23, 33). XLA needs static shapes, so here a
log is a [N, CAP] term/value array pair plus a [N] length counter; every reference log
op maps to a masked gather/scatter:

  last-entry (log.clj:47-49)      -> last_index/last_term   (spec-correct: the actual
                                     last *log* entry; the reference returns the commit
                                     index instead -- documented bug, SURVEY.md 2.3.3)
  val-at (log.clj:20-23)          -> term_at (1-based, 0 -> "no entry" sentinel 0)
  entries-from (log.clj:51-53)    -> window (bounded E-entry slice; the reference ships
                                     arbitrary suffixes, core.clj:59-67)
  append-entries!/remove-from!
  (log.clj:61-64, 78-81)          -> the caller writes via write_window (truncation is
                                     just a smaller length + overwrite; spec-correct,
                                     unlike remove-from!'s drop-last bug, SURVEY.md 2.3.7)

All functions are written for a single cluster ([N, CAP] / [N] shapes) and are vmap'd
over the batch axis by the step kernel's callers.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def term_at(log_term: jax.Array, index1: jax.Array) -> jax.Array:
    """Term of the 1-based `index1`-th entry per node; 0 where index1 == 0 (no entry).

    log_term: [N, CAP]; index1: [N] or [N, K] -> result matches index1's shape.
    """
    cap = log_term.shape[-1]
    idx = jnp.clip(index1 - 1, 0, cap - 1)
    if index1.ndim == 1:
        got = jnp.take_along_axis(log_term, idx[:, None], axis=1)[:, 0]
    else:
        got = jnp.take_along_axis(log_term, idx, axis=1)
    return jnp.where(index1 > 0, got, 0)


def last_index_term(log_term: jax.Array, log_len: jax.Array):
    """(last 1-based index, term of last entry) per node -- spec-correct `last-entry`."""
    return log_len, term_at(log_term, log_len)


def window(arr: jax.Array, start0: jax.Array, e: int) -> jax.Array:
    """Gather an E-entry window per (row, start): out[..., k] = arr[row, start0 + k].

    arr: [N, CAP]; start0: [N] or [N, M] 0-based start slot. Out-of-range slots return
    arr's last slot (callers mask with an explicit count).
    """
    cap = arr.shape[-1]
    ks = jnp.arange(e, dtype=jnp.int32)
    pos = jnp.clip(start0[..., None] + ks, 0, cap - 1)  # [N, (M,) E]
    n = arr.shape[0]
    if start0.ndim == 1:
        rows = jnp.arange(n)[:, None]
    else:
        rows = jnp.arange(n)[:, None, None]
    return arr[rows, pos]


def write_window(
    arr: jax.Array,
    start0: jax.Array,
    vals: jax.Array,
    mask: jax.Array,
) -> jax.Array:
    """Scatter vals[n, k] into arr[n, start0[n] + k] where mask[n, k]; masked-off or
    out-of-capacity writes are dropped.

    arr: [N, CAP]; start0: [N]; vals/mask: [N, E].
    """
    n, cap = arr.shape
    e = vals.shape[-1]
    ks = jnp.arange(e, dtype=jnp.int32)
    pos = start0[:, None] + ks  # [N, E]
    # Route masked-off writes out of bounds; mode='drop' discards them.
    pos = jnp.where(mask, pos, cap)
    rows = jnp.broadcast_to(jnp.arange(n)[:, None], (n, e))
    return arr.at[rows, pos].set(vals, mode="drop")


# --------------------------------------------------------------------------------------
# Committed-prefix checksum. The "committed entries are immutable" invariant used to
# compare the full old vs new log arrays every tick -- the single most expensive
# fusion of the config3 tick (~15%, it re-reads 4 [N, CAP, B] arrays). Instead the
# state carries a weighted checksum of the committed prefix (ClusterState.commit_chk):
# one masked pass over the NEW arrays both recomputes the old-prefix sum (must equal
# the carried checksum -- any rewrite of a committed slot changes it w.h.p.) and
# extends it to the new commit bound. Detection is probabilistic (a rewrite must
# preserve a weighted sum mod 2^32 to escape; weights are odd mixing constants), which
# is ample for an implementation-bug detector, and it additionally catches committed
# -prefix corruption *between* ticks, which the old same-tick compare could not.
# Weights formula duplicated in tests/oracle.py -- keep in sync.
# --------------------------------------------------------------------------------------


def chk_weights_at(abs0):
    """Odd uint32 mixing weights (terms, values) for an array of ABSOLUTE 0-based
    entry indices -- the general form of chk_weights, needed once compaction makes
    a slot's entry index exceed its slot number (ring layout)."""
    a = abs0.astype(jnp.uint32)
    w_term = (a * jnp.uint32(2654435761) + jnp.uint32(0x9E3779B9)) | jnp.uint32(1)
    w_val = (a * jnp.uint32(0x85EBCA77) + jnp.uint32(0xC2B2AE3D)) | jnp.uint32(1)
    return w_term, w_val


def chk_weights(cap: int):
    """Per-slot weights for the prefix (non-ring) layout, where slot k holds the
    0-based entry k."""
    return chk_weights_at(jnp.arange(cap, dtype=jnp.uint32))


def prefix_chk2(log_term, log_val, upto_a, upto_b):
    """Checksums of the prefixes below 1-based counts `upto_a` and `upto_b`, in one
    pass. log_term/log_val: [N, CAP]; upto_*: [N] -> (uint32 [N], uint32 [N])."""
    cap = log_term.shape[-1]
    w_t, w_v = chk_weights(cap)
    contrib = log_term.astype(jnp.uint32) * w_t + log_val.astype(jnp.uint32) * w_v
    ks = jnp.arange(cap, dtype=jnp.int32)
    in_a = ks[None, :] < upto_a[:, None]
    in_b = ks[None, :] < upto_b[:, None]
    z = jnp.uint32(0)
    return (
        jnp.sum(jnp.where(in_a, contrib, z), axis=1, dtype=jnp.uint32),
        jnp.sum(jnp.where(in_b, contrib, z), axis=1, dtype=jnp.uint32),
    )


def prefix_chk2_b(log_term, log_val, upto_a, upto_b):
    """Batch-minor prefix_chk2. log_term/log_val: [N, CAP, B]; upto_*: [N, B]."""
    cap = log_term.shape[1]
    w_t, w_v = chk_weights(cap)
    contrib = (
        log_term.astype(jnp.uint32) * w_t[None, :, None]
        + log_val.astype(jnp.uint32) * w_v[None, :, None]
    )
    ks = iota((1, cap, 1), 1)
    in_a = ks < upto_a[:, None, :]
    in_b = ks < upto_b[:, None, :]
    z = jnp.uint32(0)
    return (
        jnp.sum(jnp.where(in_a, contrib, z), axis=1, dtype=jnp.uint32),
        jnp.sum(jnp.where(in_b, contrib, z), axis=1, dtype=jnp.uint32),
    )


# --------------------------------------------------------------------------------------
# Ring variants (compaction, cfg.compact_margin > 0): 1-based entry i lives at slot
# (i - 1) mod CAP; live slots hold entries (log_base, log_len] with
# log_len - log_base <= CAP (types.ClusterState). Entries at or below log_base exist
# only as (log_base, base_term, base_chk). With log_base == 0 every ring form
# degenerates to its prefix counterpart bit-for-bit; the kernels still call the
# prefix forms for non-compaction configs so those stay mod-free.
# --------------------------------------------------------------------------------------


def term_at_r(log_term: jax.Array, base: jax.Array, base_term: jax.Array, index1):
    """Ring-aware term_at: the ring slot's term for base < index1 <= base + CAP;
    base_term for 0 < index1 <= base (the compacted prefix -- callers gate on what
    the protocol may actually compare there); 0 for index1 == 0.

    log_term: [N, CAP]; base/base_term: [N]; index1: [N] or [N, K].
    """
    cap = log_term.shape[-1]
    idx = (index1 - 1) % cap
    if index1.ndim == 1:
        got = jnp.take_along_axis(log_term, idx[:, None], axis=1)[:, 0]
    else:
        got = jnp.take_along_axis(log_term, idx, axis=1)
        base = base[:, None]
        base_term = base_term[:, None]
    return jnp.where(index1 == 0, 0, jnp.where(index1 <= base, base_term, got))


def window_r(arr: jax.Array, start0: jax.Array, e: int) -> jax.Array:
    """Ring window: out[..., k] = arr[row, (start0 + k) mod CAP]. Callers mask with
    an explicit count (slots past the live range hold unrelated ring content)."""
    cap = arr.shape[-1]
    ks = jnp.arange(e, dtype=jnp.int32)
    # Unsigned modulo: start0 is an absolute (non-negative) ring anchor, so the
    # uint view is value-identical -- and it skips the python-mod sign-fix
    # select, leaving a provably in-[0, CAP) index (Pass E range-index-oob).
    pos = ((start0[..., None] + ks).astype(jnp.uint32) % cap).astype(jnp.int32)
    n = arr.shape[0]
    rows = jnp.arange(n)[:, None] if start0.ndim == 1 else jnp.arange(n)[:, None, None]
    return arr[rows, pos]


def write_window_r(
    arr: jax.Array, start0: jax.Array, vals: jax.Array, mask: jax.Array
) -> jax.Array:
    """Ring write_window: vals[n, k] -> arr[n, (start0[n] + k) mod CAP] where
    mask[n, k]. Masked-on positions are distinct mod CAP because the caller keeps
    the retained window within CAP (log_len - log_base <= CAP)."""
    n, cap = arr.shape
    e = vals.shape[-1]
    ks = jnp.arange(e, dtype=jnp.int32)
    pos = jnp.where(mask, (start0[:, None] + ks) % cap, cap)
    rows = jnp.broadcast_to(jnp.arange(n)[:, None], (n, e))
    return arr.at[rows, pos].set(vals, mode="drop")


def ring_chk(log_term, log_val, base, uptos: tuple):
    """Checksum sums over live ring entries (base, upto] for each upto in `uptos`,
    weighted by ABSOLUTE entry index -- the ring generalization of prefix_chk2
    (bit-identical for base == 0). A node's checksum-at-prefix-p is then
    base_chk + ring_chk(..., (p,))[0] for any p in [base, log_len].

    log_term/log_val: [N, CAP]; base: [N]; returns a tuple of uint32 [N].
    """
    cap = log_term.shape[-1]
    s = jnp.arange(cap, dtype=jnp.int32)[None, :]
    abs0 = base[:, None] + (s - base[:, None]) % cap  # 0-based entry index of slot s
    w_t, w_v = chk_weights_at(abs0)
    contrib = log_term.astype(jnp.uint32) * w_t + log_val.astype(jnp.uint32) * w_v
    z = jnp.uint32(0)
    return tuple(
        jnp.sum(jnp.where(abs0 < u[:, None], contrib, z), axis=1, dtype=jnp.uint32)
        for u in uptos
    )


# --------------------------------------------------------------------------------------
# Batch-minor variants: identical semantics with a trailing batch axis B. The batch
# rides the TPU lane dimension (128-wide minor tile), so these are the hot-path forms
# (models/raft_batched.py); the unsuffixed single-cluster forms above stay as the
# readable reference semantics used via vmap in debug/trace paths.
#
# No gather/scatter anywhere: with the batch minor, dynamic indices would vary along
# the lane dimension, which TPU gathers serialize (measured ~5x slower than the whole
# rest of the tick). CAP/E are small static dims, so every indexed access is instead a
# one-hot compare-and-reduce over the indexed axis -- pure elementwise work that
# vectorizes across lanes. Equality with the gather forms is pinned by
# tests/test_batched_parity.py.
# --------------------------------------------------------------------------------------


def log2_bin(v: jax.Array, n_bins: int) -> jax.Array:
    """Elementwise floor(log2(v)) clamped to [0, n_bins): the latency
    histogram's bin index (types.LAT_HIST_BINS semantics), via an unrolled
    binary bit-length reduction -- no float log in any hot loop. The ONE
    copy both kernels' commit-latency AND read-latency histograms bin with
    (four call sites; a binning change is one edit). v must be >= 0; v in
    {0, 1} lands in bin 0."""
    bl = jnp.zeros_like(v)
    for sft in (16, 8, 4, 2, 1):
        m_ = v >= (1 << sft)
        bl = bl + m_ * sft
        v = jnp.where(m_, v >> sft, v)
    return jnp.minimum(bl, n_bins - 1)


def iota(shape, d):
    """int32 iota built at its final rank. The single shared helper for all batched
    kernels: Mosaic (Pallas TPU) cannot lower the unit-dim-appending reshapes that
    `jnp.arange(...)[None, :, None]` produces, and these ops run inside the
    pallas_engine kernel."""
    return jax.lax.broadcasted_iota(jnp.int32, shape, d)





def term_at_b(log_term: jax.Array, index1: jax.Array) -> jax.Array:
    """Batched term_at. log_term: [N, CAP, B]; index1: [N, B] or [N, M, B].

    index1 == 0 matches no slot and yields 0 (the "no entry" sentinel), like the
    where(index1 > 0, ...) mask in the gather form. Precondition (both variants):
    index1 <= cap — callers clip to log_len <= cap. Above cap this form yields 0
    while the gather form clamps to the last slot; do not rely on either.
    """
    cap = log_term.shape[1]
    if index1.ndim == 2:  # [N, B] -> [N, B]
        oh = iota((1, cap, 1), 1) == (index1 - 1)[:, None, :]  # [N, CAP, B]
        return jnp.sum(jnp.where(oh, log_term, 0), axis=1)
    # [N, M, B] -> [N, M, B]
    oh = iota((1, 1, cap, 1), 2) == (index1 - 1)[:, :, None, :]  # [N, M, CAP, B]
    return jnp.sum(jnp.where(oh, log_term[:, None], 0), axis=2)


def last_index_term_b(log_term: jax.Array, log_len: jax.Array):
    """Batched last_index_term. log_term: [N, CAP, B]; log_len: [N, B]."""
    return log_len, term_at_b(log_term, log_len)


def window_b(arr: jax.Array, start0: jax.Array, e: int) -> jax.Array:
    """Batched window. arr: [N, CAP, B]; start0: [N, B] -> [N, E, B], or
    [N, M, B] -> [N, M, E, B]. Out-of-range slots clamp to the last slot (callers mask
    with an explicit count), matching the clipped gather form."""
    cap = arr.shape[1]
    if start0.ndim == 2:  # [N, B]
        pos = jnp.clip(start0[:, None, :] + iota((1, e, 1), 1), 0, cap - 1)  # [N, E, B]
        oh = iota((1, 1, cap, 1), 2) == pos[:, :, None, :]  # [N, E, CAP, B]
        return jnp.sum(jnp.where(oh, arr[:, None], 0), axis=2)
    # [N, M, B]
    pos = jnp.clip(start0[:, :, None, :] + iota((1, 1, e, 1), 2), 0, cap - 1)
    oh = iota((1, 1, 1, cap, 1), 3) == pos[:, :, :, None, :]  # [N, M, E, CAP, B]
    return jnp.sum(jnp.where(oh, arr[:, None, None], 0), axis=3)


def write_window_b(
    arr: jax.Array,
    start0: jax.Array,
    vals: jax.Array,
    gate: jax.Array,
    count: jax.Array,
) -> jax.Array:
    """Batched write_window, restricted to the contiguous-prefix writes the kernels
    actually do: where `gate[n, b]`, write vals[n, k, b] into arr[n, start0 + k, b]
    for k < count[n, b]. arr: [N, CAP, B]; start0/gate/count: [N, B]; vals: [N, E, B].

    Taking (gate, count) instead of a free-form [N, E, B] mask makes the old
    implicit precondition (mask must be a contiguous prefix along E) structural:
    the written-slot test below is two compares against [start0, start0 + count)
    instead of an E-way any-reduce, and no caller can pass a mask shape it would
    silently mis-handle. Window positions are strictly increasing in k, so each
    capacity slot is hit by at most one written entry; out-of-range entries are
    routed to position `cap`, which matches no slot (the scatter form's
    mode='drop')."""
    cap = arr.shape[1]
    e = vals.shape[1]
    count = jnp.minimum(jnp.where(gate, count, 0), e).astype(jnp.int32)  # [N, B]
    mask = iota((1, e, 1), 1) < count[:, None, :]  # [N, E, B]; count is 0 where ~gate
    pos = start0[:, None, :] + iota((1, e, 1), 1)  # [N, E, B]
    pos = jnp.where(mask, pos, cap)
    oh = iota((1, 1, cap, 1), 2) == pos[:, :, None, :]  # [N, E, CAP, B]
    cs = iota((1, cap, 1), 1)
    hit = (cs >= start0[:, None, :]) & (cs < (start0 + count)[:, None, :])
    val = jnp.sum(jnp.where(oh, vals[:, :, None, :], 0), axis=1)
    return jnp.where(hit, val, arr)


# ---- batch-minor ring forms (compaction; see the ring section above) -----------------


def term_at_rb(log_term, base, base_term, index1):
    """Batched term_at_r. log_term: [N, CAP, B]; base/base_term/index1: [N, B]."""
    cap = log_term.shape[1]
    oh = iota((1, cap, 1), 1) == ((index1 - 1) % cap)[:, None, :]  # [N, CAP, B]
    got = jnp.sum(jnp.where(oh, log_term, 0), axis=1)
    return jnp.where(index1 == 0, 0, jnp.where(index1 <= base, base_term, got))


def window_rb(arr: jax.Array, start0: jax.Array, e: int) -> jax.Array:
    """Batched window_r. arr: [N, CAP, B]; start0: [N, B] -> [N, E, B]."""
    cap = arr.shape[1]
    pos = (start0[:, None, :] + iota((1, e, 1), 1)) % cap  # [N, E, B]
    oh = iota((1, 1, cap, 1), 2) == pos[:, :, None, :]  # [N, E, CAP, B]
    return jnp.sum(jnp.where(oh, arr[:, None], 0), axis=2)


def write_window_rb(arr, start0, vals, gate, lo, count):
    """Batched ring write over the window-slice [lo, count): where gate[n, b],
    write vals[n, k, b] into slot (start0 + k) mod CAP for lo <= k < count.
    The extra `lo` bound (vs write_window_b) is the compaction skip: shipped
    entries at or below the receiver's log_base are already committed and
    compacted, so the write starts partway into the window. Written positions are
    distinct mod CAP (retained window <= CAP)."""
    cap = arr.shape[1]
    e = vals.shape[1]
    count = jnp.minimum(jnp.where(gate, count, 0), e).astype(jnp.int32)  # [N, B]
    lo = jnp.clip(lo, 0, e).astype(jnp.int32)
    ks = iota((1, e, 1), 1)
    mask = (ks >= lo[:, None, :]) & (ks < count[:, None, :])  # [N, E, B]
    pos = jnp.where(mask, (start0[:, None, :] + ks) % cap, cap)
    oh = iota((1, 1, cap, 1), 2) == pos[:, :, None, :]  # [N, E, CAP, B]
    rel = (iota((1, cap, 1), 1) - start0[:, None, :]) % cap  # slot's window offset
    hit = (rel >= lo[:, None, :]) & (rel < count[:, None, :])
    val = jnp.sum(jnp.where(oh, vals[:, :, None, :], 0), axis=1)
    return jnp.where(hit, val, arr)


def ring_chk_b(log_term, log_val, base, uptos: tuple):
    """Batched ring_chk. log_term/log_val: [N, CAP, B]; base/uptos: [N, B]."""
    cap = log_term.shape[1]
    s = iota((1, cap, 1), 1)
    abs0 = base[:, None, :] + (s - base[:, None, :]) % cap  # [N, CAP, B]
    w_t, w_v = chk_weights_at(abs0)
    contrib = log_term.astype(jnp.uint32) * w_t + log_val.astype(jnp.uint32) * w_v
    z = jnp.uint32(0)
    return tuple(
        jnp.sum(jnp.where(abs0 < u[:, None, :], contrib, z), axis=1, dtype=jnp.uint32)
        for u in uptos
    )
