"""Pass C: a jaxpr-derived cost model -- the roofline as a gated invariant.

Every perf verdict in docs/PERF.md rests on bytes-per-tick accounting, and
until this pass that accounting was a hand-maintained leaf table in
`tools/traffic_audit.py` plus a hardcoded throughput anchor -- both able to
drift silently from the programs we actually compile. Pass C prices the SAME
closed jaxprs Pass A audits (`jaxpr_audit.programs`: step, step_b, simulate,
scenario_simulate per config tier), equation by equation:

  carry bytes/tick   the scan carry extracted from the lowered run loop
                     itself: every leg's aval, priced logically and
                     TPU-padded (policy.padded_bytes, the batch-minor tiling
                     single-sourced in analysis/policy.py), with
                     identity-passthrough legs (invar IS outvar in the body,
                     the legs XLA elides from the per-tick HBM round trip)
                     derived from the jaxpr instead of declared by hand.
                     `tools/traffic_audit.py` now consumes this as its
                     primary source; its eval_shape leaf table is the
                     cross-check (derived == hand-priced is asserted in
                     tests/test_cost_model.py).
  live-set peak      a linear liveness walk over the program (nested bodies
                     included): the byte-maximum of simultaneously-live
                     values -- an HBM footprint estimate that catches a newly
                     materialized [N, N, B] temporary even when the carry is
                     untouched. Lowering-level, so exact per jax version
                     (compared against the golden only under the recorded
                     version, like the op-histogram snapshots).
  donation           the jitted entry points' buffer aliasing, read from the
                     lowering (`tf.aliasing_output` marks) and confirmed via
                     `lower().compile().memory_analysis()` where the backend
                     supports it: `chunked._chunk_donate` must actually donate
                     the chunk carry; dropping `donate_argnums` is a finding,
                     not a quiet 2x HBM residency regression.
  roofline           bytes/tick x the pinned implied HBM rate -> a ticks/s
                     upper bound per preset. The anchor derives from the
                     newest BENCH_r*.json artifact (`bench_anchor`), falling
                     back to the pinned round-5 chip numbers with a warning,
                     so it follows the bench trajectory instead of rotting.
                     The rate is implied from THIS program's bytes/tick at
                     the anchor throughput, so at pin time the roofline
                     equals the anchor by construction -- the pin is a
                     bytes/tick fence (it moves exactly when the program's
                     traffic does), not a layout-vs-layout bound; the
                     packed-vs-dense / bool-free physical bounds live in
                     tools/traffic_audit.py, which implies its rate from the
                     dense carry the recorded round actually ran.

Everything is pinned in tests/golden_cost_model.json (regenerate after an
INTENDED change: `python tools/check.py --update-goldens`) and gated through
the findings/waiver engine by `tools/check.py --cost`:

  cost-carry-bytes   a new moving carry leg, a widened leg, or a >tolerance
                     bytes/tick regression vs the pin
  cost-live-peak     live-set peak drift beyond tolerance (same jax version)
  cost-donation      an entry point's donation status changed vs the pin
  cost-roofline      the derived ticks/s bound at the pinned HBM rate fell
                     more than tolerance below the pinned bound
  cost-golden        pins out of sync with the tree (missing/stale/improved:
                     regenerate goldens), or an unreadable golden file

Tracing + a tiny-shape compile per donating entry point (the donation
probes) -- no device execution -- so the whole pass stays inside the
analyzer's <60 s CPU budget (pinned in tests/test_cost_model.py).
"""

from __future__ import annotations

import functools
import json
import os
import re

import jax
import jax.numpy as jnp

from raft_sim_tpu.analysis import jaxpr_audit, policy
from raft_sim_tpu.analysis.findings import Finding
from raft_sim_tpu.utils.config import PRESETS, RaftConfig

# Every rule slug this pass can emit (run.run_all scopes stale-waiver
# detection to the passes that actually ran).
RULES = frozenset({
    "cost-carry-bytes", "cost-live-peak", "cost-donation", "cost-roofline",
    "cost-golden", "cost-mesh-bytes",
})

# Drift tolerances (fractions) against the golden pins. The golden file can
# override these under "tolerance"; the defaults are deliberately tight --
# carry bytes are struct-derived and exactly reproducible, so 1% is headroom
# for float rounding, not for regressions.
DEFAULT_TOLERANCE = {"carry_bytes": 0.01, "live_peak": 0.05, "roofline": 0.02}

# Recorded round-5 chip throughput (docs/PERF.md history table): the anchor
# fallback when no BENCH_r*.json artifact is present (fresh clone, installed
# package). Single-sourced here -- tools/traffic_audit.py imports it too.
FALLBACK_ANCHOR_R05 = {
    "config3": 38.1e6,
    "config4": 22.7e6,
    "config5": 2.14e6,
}

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def golden_path() -> str:
    return os.path.join(_REPO_ROOT, "tests", "golden_cost_model.json")


# ------------------------------------------------------------- anchor source


def bench_matrix(doc: dict) -> dict:
    """Matrix rows from a bench stdout capture ({n, cmd, rc, tail, parsed}
    wrapper or raw bench.py output). The bench JSON is `parsed` when present,
    else `matrix` at top level, else recovered row-by-row from the
    byte-truncated `tail`. Single-sourced here for bench_anchor and
    tools/metrics_report.py so the two gates can't drift apart."""
    parsed = doc.get("parsed")
    if isinstance(parsed, dict) and isinstance(parsed.get("matrix"), dict):
        return dict(parsed["matrix"])
    if isinstance(doc.get("matrix"), dict):  # a raw bench.py stdout capture
        return dict(doc["matrix"])
    dec = json.JSONDecoder()
    tail = doc.get("tail") or ""
    rows = {}
    for mt in re.finditer(r'"(config[A-Za-z0-9_]*)":\s*\{', tail):
        try:
            row, _ = dec.raw_decode(tail[mt.end() - 1:])
        except json.JSONDecodeError:
            continue
        if isinstance(row, dict) and "cluster_ticks_per_s" in row:
            rows[mt.group(1)] = row
    return rows


def bench_anchor(root: str | None = None):
    """(anchors, source, notes): per-config cluster-ticks/s from the NEWEST
    BENCH_r*.json artifact in the repo root. Artifacts are stdout captures
    ({n, cmd, rc, tail, parsed}); rows come from `bench_matrix`. Returns
    ({}, None, notes) when no artifact yields rows -- callers fall back to
    FALLBACK_ANCHOR_R05 (see `anchor()`)."""
    root = root or _REPO_ROOT
    try:
        paths = [f for f in os.listdir(root) if re.fullmatch(r"BENCH_r\d+\.json", f)]
    except OSError as ex:
        return {}, None, [f"{root}: unlistable: {ex}"]
    if not paths:
        return {}, None, ["no BENCH_r*.json artifact found"]
    newest = max(paths, key=lambda p: int(re.search(r"r(\d+)", p).group(1)))
    path = os.path.join(root, newest)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as ex:
        return {}, None, [f"{newest}: unreadable: {ex}"]
    matrix = bench_matrix(doc)
    anchors = {}
    notes = []
    for k, v in matrix.items():
        if not (isinstance(v, dict) and v.get("cluster_ticks_per_s")):
            continue
        # A row measured at a non-production batch (--smoke, custom --batch)
        # must never become the roofline anchor: its throughput is not the
        # number the bytes/tick projection is anchored against. Rows with no
        # batch field (hand-recovered tails) are kept -- nothing to judge.
        prod = PRESETS.get(k)
        if prod and v.get("batch") is not None and v["batch"] != prod[1]:
            notes.append(
                f"{newest}: {k} row measured at batch={v['batch']} "
                f"(production {prod[1]}): ignored for the anchor"
            )
            continue
        # A --smoke row can sit at the production batch (config1: batch 1
        # both ways; SMOKE_TICKS is what shrinks it), so the batch comparison
        # above cannot catch it -- bench marks such rows and they must never
        # rebase the anchor onto CPU smoke throughput.
        if v.get("smoke"):
            notes.append(
                f"{newest}: {k} row measured with --smoke: ignored for the "
                "anchor"
            )
            continue
        # A row measured on a CPU backend can never rebase the roofline: the
        # pins project chip HBM rates, and a CPU measurement pass (bench
        # >= r06 records `backend` per row; obs/reconcile.py marks such rows
        # non-anchor for the same reason) would silently rebase the implied
        # rate onto host-memory throughput. Rows with no backend field
        # (BENCH_r01-r05) are kept: all were recorded on the real chip.
        if v.get("backend") == "cpu":
            notes.append(
                f"{newest}: {k} row measured on the cpu backend: ignored "
                "for the anchor"
            )
            continue
        # A row measured on the scenario path (bench --scenario) prices the
        # genome input lattice, not the plain run loop the roofline
        # projects -- bench itself refuses to attach headroom to such rows.
        if v.get("scenario"):
            notes.append(
                f"{newest}: {k} row measured on the scenario path "
                f"({v['scenario']}): ignored for the anchor"
            )
            continue
        # A row measured under a different carry LAYOUT than the preset's
        # current one must never rebase its roofline: the bytes/tick the
        # anchor implies a rate against are the layout's (bench >= r14
        # records `layout` per row; earlier rows are all dense). The
        # PR 5/PR 8 smoke-row trap class, closed for layouts too.
        if prod and (v.get("layout") or "dense") != layout_of(prod[0]):
            notes.append(
                f"{newest}: {k} row measured under the "
                f"{v.get('layout') or 'dense'} layout (preset is "
                f"{layout_of(prod[0])}): ignored for the anchor"
            )
            continue
        # A row measured across D>1 devices (bench >= r16 mesh_scaling leg
        # records `n_devices` per row; every earlier row is single-device)
        # reports AGGREGATE mesh throughput -- rebasing the single-device
        # roofline onto it would inflate the implied HBM rate D-fold. Same
        # trap class as layouts, closed for device counts.
        if (v.get("n_devices") or 1) != 1:
            notes.append(
                f"{newest}: {k} row measured across {v['n_devices']} "
                "devices: ignored for the anchor"
            )
            continue
        anchors[k] = float(v["cluster_ticks_per_s"])
    if not anchors:
        return {}, None, notes + [f"{newest}: no recoverable matrix rows"]
    return anchors, newest, notes


def layout_of(cfg) -> str:
    """Physical carry layout of a config: "compact" (ops/tile.py,
    cfg.compact_planes) or "dense". Bench rows record this per row; the
    anchor/reconcile guards key on it so a row measured under one layout can
    never rebase the other layout's roofline."""
    return "compact" if getattr(cfg, "compact_planes", False) else "dense"


def dense_base(name: str) -> str | None:
    """The dense-layout base preset of a compacted preset (config5c ->
    config5): the preset whose config differs ONLY in compact_planes and
    whose production batch matches. None for dense presets or when no base
    exists."""
    import dataclasses

    entry = PRESETS.get(name)
    if entry is None or not entry[0].compact_planes:
        return None
    want = dataclasses.replace(entry[0], compact_planes=False)
    for other, (cfg, batch) in PRESETS.items():
        if other != name and cfg == want and batch == entry[1]:
            return other
    return None


def anchor(root: str | None = None):
    """The roofline anchor with the documented fallback: rows from the newest
    bench artifact when one is readable, the pinned round-5 chip numbers for
    any config the artifact does not cover (BENCH_r*.json tails are
    byte-truncated captures, so individual rows can be missing) -- each
    fallback is a note the caller should surface, never a silent
    substitution."""
    anchors, source, notes = bench_anchor(root)
    if not anchors:
        notes = notes + ["falling back to the pinned round-5 chip anchors"]
        return dict(FALLBACK_ANCHOR_R05), "pinned-r05-fallback", notes
    merged = dict(FALLBACK_ANCHOR_R05)
    merged.update(anchors)
    missing = sorted(set(FALLBACK_ANCHOR_R05) - set(anchors))
    if missing:
        notes = notes + [
            f"{source} carries no row for {', '.join(missing)}: using the "
            "pinned round-5 anchors there"
        ]
        source = f"{source} (+pinned r05: {', '.join(missing)})"
    return merged, source, notes


# ------------------------------------------------------------ byte derivation


def _aval_bytes(v) -> int:
    aval = getattr(v, "aval", None)
    if aval is None or not hasattr(aval, "shape") or not hasattr(aval, "dtype"):
        return 0
    return policy.logical_bytes(tuple(aval.shape), aval.dtype.itemsize)


def _find_run_scan(jaxpr):
    """The run loop's scan eqn: the scan with the WIDEST carry anywhere in the
    program (nested pjit bodies included) -- the tick loop carries the whole
    (state, metrics) pytree, so it dominates any helper scan."""
    best = None
    for eqn in jaxpr_audit.iter_eqns(jaxpr):
        if eqn.primitive.name == "scan":
            if best is None or eqn.params["num_carry"] > best.params["num_carry"]:
                best = eqn
    return best


def carry_model(closed, batch: int, names: list[str] | None = None):
    """Price the scan carry of a lowered run program, per cluster-tick.

    Carry avals come from the run scan's body jaxpr (trailing axis = the
    batch, the batch-minor layout contract); MOVING legs -- body output var
    is not the input var -- cost a read+write per tick, identity-passthrough
    legs cost nothing (XLA elides them; Pass A's `carry-passthrough` rule
    pins that the policy's invariant set is in fact identity). Padded bytes
    use `batch` (the preset's real batch) for the lane/sublane tiling, NOT
    the small audit batch the program was traced with -- padding amortizes
    over the batch, so the priced footprint is the production one.

    Returns None when the program contains no scan (step kernels)."""
    eqn = _find_run_scan(closed.jaxpr)
    if eqn is None:
        return None
    body = eqn.params["jaxpr"].jaxpr
    nc, nk = eqn.params["num_consts"], eqn.params["num_carry"]
    carry_in = body.invars[nc:nc + nk]
    carry_out = body.outvars[:nk]
    if names is None or len(names) != nk:
        std = policy.carry_leaf_names()
        if len(std) == nk:
            names = std
        elif nk > len(std):
            # Surplus legs (a temp riding the scan carry -- the headline
            # regression this pass gates): keep the declared names for the
            # prefix so the findings name the new leg(s) instead of
            # renaming every leg positionally. Best-effort: an insertion
            # mid-struct shifts names from that point on.
            names = list(std) + [f"extra{i}" for i in range(len(std), nk)]
        else:
            names = [f"leg{i}" for i in range(nk)]
    legs = {}
    carry_logical = 0
    carry_padded = 0.0
    for nm, a, b in zip(names, carry_in, carry_out):
        aval = b.aval
        pshape = tuple(aval.shape[:-1])  # trailing axis is the batch
        isz = aval.dtype.itemsize
        moving = a is not b
        padded = policy.padded_bytes(pshape, isz, batch)
        legs[nm] = {
            "shape": list(pshape),
            "dtype": str(aval.dtype),
            "padded": round(padded, 1),
            "moving": moving,
        }
        if moving:
            carry_logical += 2 * policy.logical_bytes(pshape, isz)
            carry_padded += 2 * padded
    return {
        "n_legs": nk,
        "legs": legs,
        "moving_legs": {
            nm: leg["padded"] for nm, leg in legs.items() if leg["moving"]
        },
        "carry_logical": carry_logical,
        "carry_padded": round(carry_padded, 1),
    }


@functools.lru_cache(maxsize=None)
def input_bytes(cfg: RaftConfig, batch: int):
    """(logical, padded) bytes of the per-tick StepInputs, materialized once
    per tick from the key stream inside the scan body (eval_shape over the
    real `faults.make_inputs`, per cluster)."""
    from raft_sim_tpu.sim import faults

    key = jax.eval_shape(lambda: jax.random.key(0))
    inputs = jax.eval_shape(lambda k: faults.make_inputs(cfg, k, jnp.int32(0)), key)
    log = sum(
        policy.logical_bytes(tuple(v.shape), v.dtype.itemsize) for v in inputs
    )
    pad = sum(
        policy.padded_bytes(tuple(v.shape), v.dtype.itemsize, batch) for v in inputs
    )
    return log, round(pad, 1)


def live_peak_bytes(closed) -> tuple[int, int]:
    """(live-set peak, total materialized bytes) for a closed jaxpr.

    Peak: a linear liveness walk -- each var is live from its defining eqn to
    its last use (program outputs to the end); the peak is the byte-maximum
    of the live set, with nested bodies (pjit/scan/cond) contributing their
    own inner peak on top of the outer live set at their call eqn. Total:
    the sum of every eqn's output bytes (all temporaries ever written).
    Both are estimates of the lowering (pre-XLA-fusion), exact and
    reproducible per jax version -- the golden comparison is version-gated
    exactly like the op-histogram snapshots."""
    memo: dict[int, int] = {}
    total = 0
    for eqn in jaxpr_audit.iter_eqns(closed.jaxpr):
        for v in eqn.outvars:
            total += _aval_bytes(v)
    return _live_peak(closed.jaxpr, memo), total


def _live_peak(jaxpr, memo: dict[int, int]) -> int:
    key = id(jaxpr)
    if key in memo:
        return memo[key]
    last: dict = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if hasattr(v, "count"):
                last[v] = i
    for v in jaxpr.outvars:
        if hasattr(v, "count"):
            last[v] = len(jaxpr.eqns)
    cur = 0
    alive = set()
    for v in (*jaxpr.invars, *jaxpr.constvars):
        if hasattr(v, "count") and v in last and v not in alive:
            alive.add(v)
            cur += _aval_bytes(v)
    peak = cur
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.outvars:
            if hasattr(v, "count") and v not in alive:
                alive.add(v)
                cur += _aval_bytes(v)
        inner = max(
            (_live_peak(sub, memo) for sub in jaxpr_audit._sub_jaxprs(eqn)),
            default=0,
        )
        peak = max(peak, cur + inner)
        dead = {
            v for v in (*eqn.invars, *eqn.outvars)
            if hasattr(v, "count") and v in alive and last.get(v, -1) <= i
        }
        for v in dead:
            alive.discard(v)
            cur -= _aval_bytes(v)
    memo[key] = peak
    return peak


# ------------------------------------------------------------ donation audit

# Shapes for the donation-audit lowerings: the smallest legal cluster. The
# aliasing decision is structural (argument pytree <-> output pytree), so a
# tiny instance proves the same property as the production shapes while its
# one `compile()` costs seconds, not the 15-40 s of a real scan program.
_TINY_CFG = RaftConfig(n_nodes=3, log_capacity=4, max_entries_per_rpc=1)
_TINY_BATCH = 2
_TINY_TICKS = 2


def _tiny_avals():
    from raft_sim_tpu.types import init_batch

    key = jax.eval_shape(lambda: jax.random.key(0))
    state = jax.eval_shape(lambda k: init_batch(_TINY_CFG, k, _TINY_BATCH), key)
    keys = jax.eval_shape(lambda k: jax.random.split(k, _TINY_BATCH), key)
    return state, keys


def entry_points():
    """(label, expected status, lower thunk) for every jitted entry point the
    donation pin covers. Labels and expectations come from the single-source
    registry `policy.donating_entry_points()` (Pass D's dataflow lint and the
    runtime sanitizer read the SAME registry); only the tiny-aval lower thunks
    live here. Expectations are design decisions, restated so the golden
    regeneration and the rule messages agree:

      _chunk_donate  donates the chunk carry (the long-horizon hot loop)
      _chunk_t_donate  the telemetry soak loop's chunk: same donation contract
      _serve_chunk   the standing-fleet serve loop's chunk: donates the fleet
                     between chunks (a service session must hold ONE fleet in
                     HBM forever, not two -- ISSUE 6's never-double-buffers
                     acceptance bullet)
      _chunk         input-preserving ON PURPOSE: tools/repro.py replays from
                     the chunk-start state after a violation
      simulate(+scenario)  seed/genome inputs only -- nothing donatable; the
                     scan carry double-buffers inside one executable, which
                     is XLA's job, not the caller's

    Only `cost_pinned` registry entries appear (the trace variant shares
    `_chunk_t_donate`'s donation decorator line and is covered by Pass D's
    registry-coverage rule instead of a second golden row).
    """
    import dataclasses as _dc

    from raft_sim_tpu.serve import loop as serve_loop
    from raft_sim_tpu.sim import chunked, scan as scan_mod, telemetry

    state, keys = _tiny_avals()
    seed = jax.ShapeDtypeStruct((), jnp.int32)
    genome = jaxpr_audit._genome_avals(_TINY_BATCH, 2)
    serve_cfg = _dc.replace(_TINY_CFG, serve_ingest=True)
    cmds = jax.ShapeDtypeStruct((_TINY_TICKS, _TINY_BATCH), jnp.int32)
    thunks = {
        "sim.chunked._chunk_donate":
            lambda: chunked._chunk_donate.lower(
                _TINY_CFG, state, keys, _TINY_TICKS, None, 1),
        "sim.telemetry._chunk_t_donate":
            lambda: telemetry._chunk_t_donate.lower(
                _TINY_CFG, state, keys, None, _TINY_TICKS, _TINY_TICKS, 0,
                None, 1),
        "serve.loop._serve_chunk":
            lambda: serve_loop._serve_chunk.lower(
                serve_cfg, state, keys, cmds, None, _TINY_TICKS),
        "sim.chunked._chunk":
            lambda: chunked._chunk.lower(
                _TINY_CFG, state, keys, _TINY_TICKS, None, 1),
        "sim.scan.simulate":
            lambda: scan_mod.simulate.lower(
                _TINY_CFG, seed, _TINY_BATCH, _TINY_TICKS),
        "sim.scan.simulate_scenario":
            lambda: scan_mod.simulate_scenario.lower(
                _TINY_CFG, seed, _TINY_BATCH, _TINY_TICKS, genome, 16),
    }
    return tuple(
        (e.label, e.expected, thunks[e.label])
        for e in policy.donating_entry_points() if e.cost_pinned
    )


def lowered_donation_status(lowered) -> dict:
    """Donation as the LOWERING records it: jax marks each donated argument
    buffer with a `tf.aliasing_output` attribute in the StableHLO module.
    Zero marks = nothing will be aliased, whatever the Python decorators
    claim."""
    n = lowered.as_text().count("tf.aliasing_output")
    return {"status": "donated" if n else "not-donated", "aliased_args": n}


def _memory_confirm(lowered) -> dict:
    """The compile-level confirmation ISSUE asks for:
    `lower().compile().memory_analysis()` -- alias_size_in_bytes > 0 means the
    executable really reuses donated input buffers. Unavailable on some
    backends; recorded as such rather than guessed."""
    try:
        stats = lowered.compile().memory_analysis()
        alias = getattr(stats, "alias_size_in_bytes", None)
        if alias is None:
            return {"available": False}
        return {
            "available": True,
            "alias_size_in_bytes": int(alias),
            "temp_size_in_bytes": int(getattr(stats, "temp_size_in_bytes", 0)),
        }
    except Exception as ex:  # backend without memory stats must not kill the gate
        return {"available": False, "error": str(ex)[:200]}


@functools.lru_cache(maxsize=None)
def donation_audit() -> tuple:
    """Audit every registered entry point. Cached: the one tiny compile (for
    memory_analysis on the donating entry) is paid once per process, shared
    by the gate and the tests. Returns a tuple of (label, result-dict) pairs
    (hashable for the cache; callers dict() it)."""
    out = []
    for label, expected, lower_thunk in entry_points():
        lowered = lower_thunk()
        res = lowered_donation_status(lowered)
        res["expected"] = expected
        if expected == "donated":
            mem = _memory_confirm(lowered)
            res["memory_analysis"] = mem
            if mem.get("available") and mem.get("alias_size_in_bytes") == 0:
                # Marked in the lowering but the executable aliases nothing:
                # the donation is decorative (layout/shape mismatch).
                res["status"] = "marked-not-aliased"
        out.append((label, res))
    return tuple(out)


# --------------------------------------------------------------- derivation


def derive_program(key: str, closed, kind: str, cfg: RaftConfig, batch: int) -> dict:
    peak, temp = live_peak_bytes(closed)
    entry: dict = {"kind": kind, "live_peak": peak, "temp_bytes": temp}
    if kind not in ("scan", "serve_scan", "trace_scan"):
        return entry
    # serve_scan: the widest scan is the serve loop's inner window scan, whose
    # carry = the (state, metrics) template + the first-violation aux leg --
    # so the offer-tick plane legs are priced exactly like every other carry
    # leg (ISSUE 6: the plane's cost is a gated number, not prose).
    # trace_scan: likewise, plus the named trace ring/coverage legs
    # (policy.trace_carry_leaf_names) -- the trace plane's sizing guidance in
    # docs/OBSERVABILITY.md reads from these pins.
    names = policy.trace_carry_leaf_names() if kind == "trace_scan" else None
    cm = carry_model(closed, batch, names=names)
    if cm is None:
        entry["error"] = "no scan found in a scan-kind program"
        return entry
    entry.update(cm)
    in_log, in_pad = input_bytes(cfg, batch)
    entry["inputs_logical"] = in_log
    entry["inputs_padded"] = in_pad
    total = cm["carry_padded"] + in_pad
    if key.endswith("/scenario_simulate"):
        # The genome program table, read once per tick (scan consts, never
        # carry): S audit segments x the policy leaf set, 4 B each.
        gen = sum(
            policy.padded_bytes((jaxpr_audit._AUDIT_SEGMENTS,), 4, batch)
            for _ in policy.scenario_genome_leaves()
        )
        entry["genome_padded"] = round(gen, 1)
        total += gen
    entry["bytes_per_tick_padded"] = round(total, 1)
    entry["bytes_per_tick_logical"] = cm["carry_logical"] + in_log
    return entry


# ------------------------------------------------------------- mesh pricing

# The (preset, node-shard count) pairs the mesh section pins: the giant-N
# tiers over the standing 8-way mesh (CI's forced 8-device CPU mesh; one
# pod-slice row on hardware). A different device count changes ONLY n_pad --
# re-derive with node_shard_model(name, D) for ad-hoc shapes.
MESH_TIERS: tuple[tuple[str, int], ...] = (("config7", 8), ("config7x", 8))

# Mailbox legs _gather_mailbox all_gathers (models/raft_batched.py) and the
# config gate that turns each group on. Kept in sync by the derivation below
# failing KeyError-loudly if a leg name leaves the carry, and by the mesh
# parity/collective tests lowering the real program.
_GATHERED_ALWAYS = (
    "mb.req_type", "mb.req_term", "mb.req_commit", "mb.req_last_index",
    "mb.req_last_term", "mb.ent_start", "mb.ent_prev_term", "mb.ent_count",
    "mb.ent_term", "mb.ent_val", "mb.req_off", "mb.resp_kind", "mb.v_to",
    "mb.a_ok_to", "mb.a_match", "mb.a_hint", "mb.resp_term",
)


def node_shard_model(name: str, n_devices: int) -> dict:
    """Analytic per-device cost of the node-sharded program
    (parallel/nodeshard.py) for one preset: the dense tier's moving carry legs
    re-priced at the row-partitioned shapes (first node axis n -> nl = n_pad /
    D, peer axes n -> n_pad), plus the all_gather traffic -- the bytes the
    hot loop's one mailbox gather (and the invariants' leader gather)
    materializes per cluster-tick, of which each device RECEIVES the
    (D-1)/D off-device fraction over ICI. Pure shape arithmetic on the dense
    twin's jaxpr: needs no devices, so the pins regenerate anywhere."""
    import numpy as np

    from raft_sim_tpu import types as rst_types
    from raft_sim_tpu.parallel import nodeshard

    cfg0, batch = PRESETS[name]
    cfg = rst_types.compact_twin(cfg0, False)  # sharded carries run dense
    n = cfg.n_nodes
    n_pad = nodeshard.check_shardable(cfg, n_devices)
    nl = n_pad // n_devices
    cm = carry_model(jaxpr_audit.scan_jaxpr(cfg), batch)
    axes_of = {f: a for f, (a, _) in nodeshard._STATE_PAD.items()}
    axes_of.update(
        {f"mb.{f}": a for f, (a, _) in nodeshard._MAILBOX_PAD.items()}
    )

    def shard_shape(nm: str, shape: list[int]) -> tuple[int, ...]:
        out = list(shape)
        for ax in axes_of.get(nm, ()):
            out[ax] = nl if ax == 0 else n_pad
        return tuple(out)

    carry = 0.0
    for nm, leg in cm["legs"].items():
        if not leg["moving"]:
            continue
        isz = np.dtype(leg["dtype"]).itemsize
        carry += 2 * policy.padded_bytes(shard_shape(nm, leg["shape"]), isz, batch)

    gathered = list(_GATHERED_ALWAYS)
    if cfg.track_offer_ticks:
        gathered.append("mb.ent_tick")
    if cfg.compaction:
        gathered += ["mb.req_base", "mb.req_base_term", "mb.req_base_chk"]
    if cfg.pre_vote:
        gathered.append("mb.pv_grant")
    ag = 0.0
    legs_out = {}
    for nm in gathered:
        leg = cm["legs"][nm]
        full = tuple(
            n_pad if ax in axes_of[nm] else d
            for ax, d in enumerate(leg["shape"])
        )
        b = policy.padded_bytes(full, np.dtype(leg["dtype"]).itemsize, batch)
        legs_out[nm] = round(b, 1)
        ag += b
    if cfg.check_invariants:
        # The election-safety leaders-by-term gather (_step_info_b).
        b = policy.padded_bytes((n_pad,), 4, batch)
        legs_out["leaders_by_term"] = round(b, 1)
        ag += b

    _, in_pad = input_bytes(cfg, batch)
    entry = {
        "n_nodes": n,
        "n_devices": n_devices,
        "n_pad": n_pad,
        "nl": nl,
        "per_device_carry_padded": round(carry, 1),
        # Inputs are drawn redundantly on every device (zero communication);
        # each device pays the full per-cluster input materialization.
        "per_device_inputs_padded": in_pad,
        "per_device_bytes_per_tick": round(carry + in_pad, 1),
        "allgather_bytes_per_tick": round(ag, 1),
        "ici_recv_bytes_per_tick": round(ag * (n_devices - 1) / n_devices, 1),
        "gathered_legs": legs_out,
    }
    return entry


def derive_mesh() -> dict:
    return {
        f"{name}@{d}dev": node_shard_model(name, d) for name, d in MESH_TIERS
    }


def compare_mesh(derived: dict, golden: dict, *, full: bool = True) -> list[Finding]:
    """Mesh-section findings: per-device HBM bytes/tick and all_gather (ICI)
    bytes/tick against the pins, carry-bytes tolerance both ways."""
    out = []
    g_mesh = golden.get("mesh") or {}
    tol = _tol(golden, "carry_bytes")
    keys = ("per_device_bytes_per_tick", "allgather_bytes_per_tick")
    for key, d in derived.items():
        g = g_mesh.get(key)
        path = f"cost:mesh/{key}"
        if g is None:
            out.append(Finding(
                rule="cost-golden", path=path,
                message=f"mesh tier has no golden cost pin -- {_REGEN}",
            ))
            continue
        for k in keys:
            gv, dv = g.get(k), d.get(k)
            if not gv or dv is None:
                continue
            if dv > gv * (1 + tol):
                side = "ICI all_gather" if k.startswith("allgather") else "per-device HBM"
                out.append(Finding(
                    rule="cost-mesh-bytes", path=path,
                    message=(
                        f"{side} traffic regressed {gv:.0f} -> {dv:.0f} B per "
                        f"cluster-tick (>{100 * tol:.0f}% tolerance): a leg "
                        "widened or newly crosses the mesh -- "
                        f"{_REGEN}"
                    ),
                ))
            elif dv < gv * (1 - tol):
                out.append(Finding(
                    rule="cost-golden", path=path,
                    message=(
                        f"mesh {k} improved {gv:.0f} -> {dv:.0f} B: the pin is "
                        f"stale -- {_REGEN} to lock in the win"
                    ),
                ))
    if full:
        for key in g_mesh:
            if key not in derived:
                out.append(Finding(
                    rule="cost-golden", path=f"cost:mesh/{key}",
                    message=f"golden pins a mesh tier no longer derived -- {_REGEN}",
                ))
    return out


def derive_all(config_names=jaxpr_audit.AUDIT_CONFIGS) -> dict:
    """The full derived cost document for the audited tiers: one entry per
    program (the same zoo Pass A walks), plus the donation audit and the
    roofline anchor in use. Cached per config set: the gate, the --cost-report
    writer, and --update-goldens all want the same document in one process,
    and the liveness walks dominate the pass -- callers treat the result as
    read-only."""
    return _derive_all(tuple(config_names))


@functools.lru_cache(maxsize=4)
def _derive_all(config_names: tuple) -> dict:
    programs = {}
    for name in config_names:
        cfg, batch = PRESETS[name]
        for prog, closed, kind, rule_cfg in jaxpr_audit.programs(name, cfg):
            key = prog.split("jaxpr:", 1)[1]
            programs[key] = derive_program(key, closed, kind, rule_cfg, batch)
    anchors, source, notes = anchor()
    for key, entry in programs.items():
        cfg_name, prog = key.split("/", 1)
        if prog == "simulate" and cfg_name in anchors:
            a = anchors[cfg_name]
            entry["anchor_ticks_per_s"] = a
            entry["implied_hbm_bytes_per_s"] = round(
                a * entry["bytes_per_tick_padded"], 1
            )
            entry["roofline_ticks_per_s"] = round(a, 1)
    # Layout twins: a compacted tier (cfg.compact_planes) whose DENSE base
    # preset is anchored inherits the base's implied HBM rate, so its pin
    # carries a genuine layout PREDICTION (rate / own bytes) instead of the
    # anchored tiers' by-construction drift detector. The anchor itself
    # stays keyed by layout -- `bench_anchor` and obs/reconcile.py reject
    # layout-mismatched rows -- so a compacted bench artifact can never
    # silently rebase the dense roofline (the PR 5/PR 8 smoke-row trap
    # class, closed for layouts too).
    for key, entry in programs.items():
        cfg_name, prog = key.split("/", 1)
        if prog != "simulate" or "roofline_ticks_per_s" in entry:
            continue
        base = dense_base(cfg_name)
        base_entry = programs.get(f"{base}/simulate") if base else None
        rate = (base_entry or {}).get("implied_hbm_bytes_per_s")
        if rate:
            entry["layout_base"] = base
            entry["implied_hbm_bytes_per_s"] = rate
            entry["roofline_ticks_per_s"] = round(
                rate / entry["bytes_per_tick_padded"], 1
            )
    return {
        "jax_version": jax.__version__,
        "anchor_source": source,
        "anchor_notes": notes,
        "donation": {k: dict(v) for k, v in donation_audit()},
        "programs": programs,
        # Node-sharded tiers: derived only when every mesh preset is in the
        # audited set (a --configs subset run prices what it audits).
        "mesh": (
            derive_mesh()
            if all(name in config_names for name, _ in MESH_TIERS)
            else {}
        ),
    }


# --------------------------------------------------------------- comparison


def _tol(golden: dict, key: str) -> float:
    return float((golden.get("tolerance") or {}).get(key, DEFAULT_TOLERANCE[key]))


_REGEN = "regenerate with `python tools/check.py --update-goldens` if intended"


def compare_program(key: str, d: dict, g: dict, *, version_match: bool,
                    golden: dict) -> list[Finding]:
    """Findings for one program's derived entry vs its golden pin. Regressions
    fire the cost rules; improvements fire `cost-golden` (the pin is stale --
    a fence that only ratchets one way rots)."""
    out = []
    path = f"cost:{key}"
    tol_b = _tol(golden, "carry_bytes")
    if d.get("error"):
        # A scan-kind program whose run scan can't be located would otherwise
        # skip every carry/bytes-per-tick/roofline comparison below with zero
        # findings -- the gate must go red VISIBLY when it stops gating, same
        # as the jax-version stale-pin rule.
        out.append(Finding(
            rule="cost-golden", path=path,
            message=(
                f"cost derivation failed ({d['error']}): the pinned "
                "carry/bytes-per-tick/roofline gates for this program are NOT "
                f"being checked -- fix the derivation or {_REGEN}"
            ),
        ))
    if d.get("kind") == "scan" and "moving_legs" in d and "moving_legs" in g:
        g_moving = g["moving_legs"]
        leg_findings = 0
        for nm, padded in d["moving_legs"].items():
            leg = d["legs"][nm]
            if nm not in g_moving:
                leg_findings += 1
                out.append(Finding(
                    rule="cost-carry-bytes", path=path,
                    message=(
                        f"carry widened: leg '{nm}' (shape {leg['shape']}, "
                        f"{leg['dtype']}, {padded:.0f} B padded/cluster-tick) "
                        "newly rides the scan-carry HBM round trip; the pinned "
                        f"moving set does not include it -- {_REGEN}"
                    ),
                ))
            elif padded > g_moving[nm] * (1 + tol_b):
                leg_findings += 1
                out.append(Finding(
                    rule="cost-carry-bytes", path=path,
                    message=(
                        f"carry leg '{nm}' grew {g_moving[nm]:.0f} -> "
                        f"{padded:.0f} B padded/cluster-tick "
                        f"(>{100 * tol_b:.0f}% tolerance): a dtype or shape "
                        f"widening on the hot carry -- {_REGEN}"
                    ),
                ))
        for nm in g_moving:
            if nm not in d.get("moving_legs", {}):
                out.append(Finding(
                    rule="cost-golden", path=path,
                    message=(
                        f"pinned moving carry leg '{nm}' no longer moves "
                        "(eliminated, renamed, or now loop-invariant): the "
                        f"golden is stale -- {_REGEN}"
                    ),
                ))
        gp, dp = g.get("carry_padded"), d.get("carry_padded")
        if gp and dp is not None and not leg_findings and dp > gp * (1 + tol_b):
            out.append(Finding(
                rule="cost-carry-bytes", path=path,
                message=(
                    f"scan-carry bytes/tick regressed {gp:.0f} -> {dp:.0f} B "
                    f"padded/cluster-tick (>{100 * tol_b:.0f}% tolerance) "
                    f"-- {_REGEN}"
                ),
            ))
        elif gp and dp is not None and dp < gp * (1 - tol_b):
            out.append(Finding(
                rule="cost-golden", path=path,
                message=(
                    f"scan-carry bytes/tick improved {gp:.0f} -> {dp:.0f} B: "
                    f"the golden pin is stale -- {_REGEN} to lock in the win"
                ),
            ))
        # Roofline at the PINNED implied HBM rate: deterministic (anchor
        # drift alone can never fire it; only bytes/tick growth can).
        g_rate, g_roof = g.get("implied_hbm_bytes_per_s"), g.get("roofline_ticks_per_s")
        bpt = d.get("bytes_per_tick_padded")
        if g_rate and g_roof and bpt:
            tol_r = _tol(golden, "roofline")
            roof_now = g_rate / bpt
            if roof_now < g_roof * (1 - tol_r):
                out.append(Finding(
                    rule="cost-roofline", path=path,
                    message=(
                        f"roofline at the pinned HBM rate fell "
                        f"{g_roof / 1e6:.2f}M -> {roof_now / 1e6:.2f}M ticks/s "
                        f"(bytes/tick {g.get('bytes_per_tick_padded', 0):.0f} "
                        f"-> {bpt:.0f} B, >{100 * tol_r:.0f}% tolerance) "
                        f"-- {_REGEN}"
                    ),
                ))
    if version_match and g.get("live_peak") and d.get("live_peak") is not None:
        tol_p = _tol(golden, "live_peak")
        gp, dp = g["live_peak"], d["live_peak"]
        if dp > gp * (1 + tol_p):
            out.append(Finding(
                rule="cost-live-peak", path=path,
                message=(
                    f"live-set peak grew {gp:,} -> {dp:,} B "
                    f"(>{100 * tol_p:.0f}% tolerance; total materialized "
                    f"{g.get('temp_bytes', 0):,} -> {d.get('temp_bytes', 0):,} B): "
                    f"a new temporary is being materialized -- {_REGEN}"
                ),
            ))
        elif dp < gp * (1 - tol_p):
            out.append(Finding(
                rule="cost-golden", path=path,
                message=(
                    f"live-set peak improved {gp:,} -> {dp:,} B: the golden "
                    f"pin is stale -- {_REGEN} to lock in the win"
                ),
            ))
    return out


def compare_donation(derived: dict, golden_donation: dict, *, full: bool = True) -> list[Finding]:
    out = []
    for label, res in derived.items():
        pin = golden_donation.get(label)
        if pin is None:
            out.append(Finding(
                rule="cost-golden", path=f"cost:donation/{label}",
                message=(
                    f"entry point has no pinned donation status -- {_REGEN}"
                ),
            ))
        elif res["status"] != pin:
            out.append(Finding(
                rule="cost-donation", path=f"cost:donation/{label}",
                message=(
                    f"donation status changed: pinned '{pin}', lowered "
                    f"'{res['status']}' ({res.get('aliased_args', 0)} aliased "
                    "args" + (
                        f", alias_size={res['memory_analysis'].get('alias_size_in_bytes')} B"
                        if res.get("memory_analysis", {}).get("available") else ""
                    ) + "). A dropped `donate_argnums` doubles steady-state "
                    "HBM residency of the chunk loop; if the change is "
                    f"intended, {_REGEN}"
                ),
            ))
    if full:
        for label in golden_donation:
            if label not in derived:
                out.append(Finding(
                    rule="cost-golden", path=f"cost:donation/{label}",
                    message=(
                        f"pinned entry point no longer audited -- {_REGEN}"
                    ),
                ))
    return out


def compare(derived: dict, golden: dict, *, full: bool = True) -> list[Finding]:
    """All Pass C findings: derived document vs golden pins. `full` = the
    derivation covered every audited tier, so golden entries with no derived
    counterpart are stale (a --configs subset run must not condemn them)."""
    out = []
    version_match = golden.get("jax_version") == derived.get("jax_version")
    g_programs = golden.get("programs") or {}
    if not version_match and any("live_peak" in g for g in g_programs.values()):
        # The live-peak comparison is lowering-exact per jax version, so a
        # mismatch disables it -- which must be a VISIBLE stale-pin finding,
        # never a gate that silently stays green across a jax upgrade.
        out.append(Finding(
            rule="cost-golden", path="cost:jax-version",
            message=(
                f"golden cost pins were recorded under jax "
                f"{golden.get('jax_version')} but this run is jax "
                f"{derived.get('jax_version')}: live-set peak comparisons are "
                f"disabled until the pins are regenerated -- {_REGEN}"
            ),
        ))
    for key, d in derived["programs"].items():
        g = g_programs.get(key)
        if g is None:
            out.append(Finding(
                rule="cost-golden", path=f"cost:{key}",
                message=f"audited program has no golden cost pin -- {_REGEN}",
            ))
            continue
        out.extend(compare_program(key, d, g, version_match=version_match,
                                   golden=golden))
    if full:
        for key in g_programs:
            if key not in derived["programs"]:
                out.append(Finding(
                    rule="cost-golden", path=f"cost:{key}",
                    message=(
                        f"golden pins a program the audit no longer lowers "
                        f"-- {_REGEN}"
                    ),
                ))
    out.extend(compare_donation(
        derived.get("donation", {}), golden.get("donation") or {}, full=full
    ))
    if derived.get("mesh"):
        out.extend(compare_mesh(derived["mesh"], golden, full=full))
    return out


# --------------------------------------------------------------- entry point


def run_pass(config_names=jaxpr_audit.AUDIT_CONFIGS,
             golden_file: str | None = None) -> list[Finding]:
    """The full cost pass: derive, load pins, compare. A missing or unreadable
    golden file is itself a finding -- the gate must force the pins into
    existence, not silently pass without them."""
    golden_file = golden_file or golden_path()
    rel = os.path.relpath(golden_file, _REPO_ROOT)
    derived = derive_all(config_names)
    try:
        with open(golden_file) as f:
            golden = json.load(f)
    except FileNotFoundError:
        return [Finding(
            rule="cost-golden", path=rel,
            message=(
                "no golden cost pins: generate them with "
                "`python tools/check.py --update-goldens` and commit the file"
            ),
        )]
    except (OSError, json.JSONDecodeError) as ex:
        return [Finding(
            rule="cost-golden", path=rel,
            message=f"golden cost file unreadable: {ex}",
        )]
    full = tuple(config_names) == tuple(jaxpr_audit.AUDIT_CONFIGS)
    return compare(derived, golden, full=full)


def _pin_program(entry: dict) -> dict:
    """The golden subset of a derived entry: totals and the moving-leg map --
    enough to name a regression precisely, without pinning every leg's shape
    (those live in the derived report, regenerated on demand)."""
    keep = (
        "kind", "n_legs", "moving_legs", "carry_logical", "carry_padded",
        "inputs_padded", "genome_padded", "bytes_per_tick_padded",
        "bytes_per_tick_logical", "live_peak", "temp_bytes",
        "anchor_ticks_per_s", "implied_hbm_bytes_per_s", "roofline_ticks_per_s",
        # Layout-twin attribution: a compacted tier's roofline is a
        # PREDICTION at its dense base's implied rate (not an anchored
        # drift detector) -- the pin says whose rate it borrowed.
        "layout_base",
    )
    return {k: entry[k] for k in keep if k in entry}


def update_golden(path: str | None = None,
                  config_names=jaxpr_audit.AUDIT_CONFIGS) -> str:
    """Regenerate tests/golden_cost_model.json from the current tree (the
    `tools/check.py --update-goldens` path, mirroring
    `tests/test_golden_jaxpr.py --update`)."""
    path = path or golden_path()
    derived = derive_all(config_names)
    # Tolerances are maintainer-tunable in the golden file (docs/ANALYSIS.md);
    # a regeneration re-pins the MEASUREMENTS but must not silently revert a
    # tuned tolerance back to the defaults.
    tolerance = dict(DEFAULT_TOLERANCE)
    try:
        with open(path) as f:
            tolerance.update(json.load(f).get("tolerance") or {})
    except (OSError, json.JSONDecodeError):
        pass
    doc = {
        "jax_version": derived["jax_version"],
        "anchor_source": derived["anchor_source"],
        "tolerance": tolerance,
        "donation": {
            label: res["status"] for label, res in derived["donation"].items()
        },
        "programs": {
            key: _pin_program(entry)
            for key, entry in sorted(derived["programs"].items())
        },
        "mesh": derived.get("mesh") or {},
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def diff_table(derived: dict, golden: dict, out=None) -> None:
    """Pinned-vs-current table (the CI failure-triage rendering: a regression
    must be diagnosable from the job log, without a local repro)."""
    import sys

    out = out or sys.stdout
    g_programs = golden.get("programs") or {}
    print(
        f"{'program':32} {'pin B/tick':>12} {'now B/tick':>12} {'delta':>8} "
        f"{'pin peak':>12} {'now peak':>12}",
        file=out,
    )
    for key in sorted(set(derived["programs"]) | set(g_programs)):
        d = derived["programs"].get(key, {})
        g = g_programs.get(key, {})
        db, gb = d.get("bytes_per_tick_padded"), g.get("bytes_per_tick_padded")
        delta = (
            f"{100 * (db - gb) / gb:+.1f}%" if db and gb else "-"
        )
        fmt = lambda v: f"{v:,.0f}" if isinstance(v, (int, float)) else "-"
        print(
            f"{key:32} {fmt(gb):>12} {fmt(db):>12} {delta:>8} "
            f"{fmt(g.get('live_peak')):>12} {fmt(d.get('live_peak')):>12}",
            file=out,
        )
    for label, res in derived.get("donation", {}).items():
        pin = (golden.get("donation") or {}).get(label, "-")
        print(f"donation {label:40} pin={pin} now={res['status']}", file=out)
