"""Fault-injection and scale tiers (BASELINE configs 3-5 semantics, shrunk for CPU;
SURVEY.md section 4: property/invariant, integration, distributed, fuzz)."""

import jax
import numpy as np
import pytest

from raft_sim_tpu import RaftConfig
from raft_sim_tpu.sim import scan

NEVER = scan.NEVER


def metrics_of(cfg, seed, batch, ticks):
    _, m = scan.simulate(cfg, seed, batch, ticks)
    return jax.device_get(m)


def test_batch_size_invariance():
    """Cluster i's trajectory must not depend on how many other clusters ran with it:
    key splits are prefix-stable, so batch=4 is a prefix of batch=64 (SURVEY.md
    section 4, vmap/pmap parity)."""
    cfg = RaftConfig(n_nodes=5, client_interval=8, drop_prob=0.2)
    small_f, small_m = scan.simulate(cfg, 9, 4, 200)
    big_f, big_m = scan.simulate(cfg, 9, 64, 200)
    for a, b in zip(jax.tree.leaves(jax.device_get(small_f)), jax.tree.leaves(jax.device_get(big_f))):
        np.testing.assert_array_equal(a, b[:4])
    for a, b in zip(jax.tree.leaves(jax.device_get(small_m)), jax.tree.leaves(jax.device_get(big_m))):
        np.testing.assert_array_equal(a, b[:4])


def test_config3_randomized_timeouts():
    """Reliable net, randomized election timeouts: every cluster elects quickly and
    safely (config 3 shrunk)."""
    m = metrics_of(RaftConfig(n_nodes=5), 0, 128, 300)
    assert int(m.violations.sum()) == 0
    assert (m.first_leader_tick < NEVER).all()
    stable = scan.stable_leader_ticks(m)
    assert (np.asarray(stable) < NEVER).all()
    assert float(np.median(m.first_leader_tick)) < 30


@pytest.mark.slow
def test_config4_drop_and_skew():
    """Bernoulli drop p in [0, 0.3] + clock skew (config 4 shrunk): safety never
    violated; the vast majority of clusters still stabilize."""
    cfg = RaftConfig(
        n_nodes=7, drop_prob=0.3, drop_prob_uniform=True, clock_skew_prob=0.1
    )
    m = metrics_of(cfg, 1, 128, 400)
    assert int(m.violations.sum()) == 0
    stable = np.asarray(scan.stable_leader_ticks(m))
    assert (stable < NEVER).sum() >= 115  # >=90%


def test_config5_wide_cluster_partitions():
    """51-node clusters under rolling partitions with full invariant checking
    (config 5 shrunk): no safety violation ever; elections still succeed."""
    cfg = RaftConfig(
        n_nodes=51,
        log_capacity=16,
        partition_period=32,
        partition_prob=0.5,
        client_interval=8,
        check_log_matching=True,
    )
    m = metrics_of(cfg, 2, 8, 300)
    assert int(m.violations.sum()) == 0
    assert (m.first_leader_tick < NEVER).all()
    assert int(m.max_commit.max()) > 0  # commits happen even while partitioned halves churn


@pytest.mark.slow
def test_partition_heals_and_reconverges():
    """A permanently partitioned cluster cannot elect with quorum on the minority
    side; after the partition window passes, commits resume monotonically. Verified
    via the partition schedule being OFF (prob 0) vs ON (prob 1) with period spanning
    half the run."""
    base = dict(n_nodes=5, client_interval=4)
    never = metrics_of(RaftConfig(**base), 3, 32, 200)
    always = metrics_of(
        RaftConfig(**base, partition_period=25, partition_prob=1.0), 3, 32, 200
    )
    # Partitions strictly reduce progress but never break safety.
    assert int(always.violations.sum()) == 0
    assert int(always.max_commit.sum()) < int(never.max_commit.sum())
    assert int(always.max_term.max()) >= int(never.max_term.max())


def test_even_cluster_size_quorum():
    """N=4 needs 3 votes (strict majority; the reference's ceil(N/2) bug 2.3 would
    accept 2-of-4). Elections still succeed on a reliable net."""
    cfg = RaftConfig(n_nodes=4)
    assert cfg.quorum == 3
    m = metrics_of(cfg, 4, 32, 300)
    assert int(m.violations.sum()) == 0
    assert (m.first_leader_tick < NEVER).all()


def test_skew_only_still_safe():
    m = metrics_of(RaftConfig(n_nodes=5, clock_skew_prob=0.5), 5, 64, 300)
    assert int(m.violations.sum()) == 0
    assert (m.first_leader_tick < NEVER).all()


@pytest.mark.slow
def test_crash_restart_fuzz():
    """Node crash/restart fuzzing (VERDICT round-1 item 3): with leaders regularly
    crashing, safety invariants hold everywhere and clusters re-elect and keep
    committing. The crash schedule is a pure function of the cluster key
    (faults.alive_at), so the trajectory is replayable."""
    cfg = RaftConfig(
        n_nodes=5,
        client_interval=8,
        crash_prob=0.6,
        crash_period=30,
        crash_down_ticks=15,
        check_log_matching=True,
    )
    m = metrics_of(cfg, 6, 64, 400)
    assert int(m.violations.sum()) == 0
    assert (m.first_leader_tick < NEVER).all()
    # Crashes force churn: terms climb past the no-fault baseline...
    base = metrics_of(RaftConfig(n_nodes=5, client_interval=8), 6, 64, 400)
    assert int(np.median(m.max_term)) > int(np.median(base.max_term))
    # ...yet clusters keep making progress (committing) through crash cycles.
    assert int(np.median(m.max_commit)) > 0


def test_leader_crash_triggers_reelection():
    """Deterministic observation of the crash fault's signature event: find a tick
    where the current leader goes down, then watch a *different* node win a later
    term (the reference's process-death -> election-timeout story, SURVEY.md 2.3.12)."""
    import jax.numpy as jnp

    from raft_sim_tpu import init_state
    from raft_sim_tpu.sim import faults

    cfg = RaftConfig(
        n_nodes=5, crash_prob=0.9, crash_period=25, crash_down_ticks=12
    )
    found = False
    for seed in range(8):
        key = jax.random.key(seed)
        k_init, k_run = jax.random.split(key)
        state = init_state(cfg, k_init)
        _, m, infos = jax.jit(
            lambda s, k: scan.run(cfg, s, k, 250, trace=True)
        )(state, k_run)
        assert int(m.violations) == 0
        leaders = np.asarray(jax.device_get(infos.leader))  # [T]
        terms = np.asarray(jax.device_get(infos.max_term))  # [T]
        ckey = faults.crash_key(k_run)
        alive = np.stack(
            [np.asarray(faults.alive_at(cfg, ckey, jnp.int32(t))) for t in range(250)]
        )  # [T, N]
        for t in range(249):
            lead = int(leaders[t])
            if lead < 0 or alive[t + 1, lead]:
                continue
            # Leader `lead` crashed at t+1. Did someone else win a LATER term? (The
            # term check pins a genuine re-election, not a stale leader resurfacing.)
            after = leaders[t + 1 :]
            taken_over = (after >= 0) & (after != lead) & (terms[t + 1 :] > terms[t])
            if taken_over.any():
                found = True
                break
        if found:
            break
    assert found, "no leader-crash -> re-election event observed across 8 seeds"


def test_kitchen_sink_all_faults_at_once():
    """Every fault class simultaneously -- Bernoulli drop (uniform per-cluster rate),
    rolling partitions, clock skew, node crash/restart -- with client traffic and
    FULL invariant checking (election safety, commit sanity via the carried
    checksum, committed-prefix value log matching) every tick. Safety must hold
    unconditionally; liveness is only required of clusters the fault mix actually
    lets breathe (we assert a majority elects at least once, and that the fleet
    commits). PreVote is ON in this tier (VERDICT weak #3): thesis-9.6 probe
    rounds now run under the full fault mix too, sharing this tier's one
    compiled scan program instead of adding another."""
    cfg = RaftConfig(
        n_nodes=5,
        log_capacity=64,
        client_interval=4,
        pre_vote=True,
        drop_prob=0.3,
        drop_prob_uniform=True,
        clock_skew_prob=0.15,
        partition_period=40,
        partition_prob=0.5,
        crash_prob=0.3,
        crash_period=40,
        crash_down_ticks=15,
        check_log_matching=True,
    )
    m = metrics_of(cfg, 11, 64, 400)
    assert int(m.violations.sum()) == 0
    assert int((m.first_leader_tick < NEVER).sum()) > 32
    assert int(m.max_commit.max()) > 0


@pytest.mark.slow
def test_kitchen_sink_with_compaction_and_redirect():
    """The round-4 surface under the same everything-at-once fault mix: a small
    compaction ring (absolute indices, snapshots, election no-ops) fed through
    the 302-redirect client path, ring-aware log matching checked every tick.
    Also pins batch-size invariance for the new client/compaction state."""
    cfg = RaftConfig(
        n_nodes=5,
        log_capacity=16,
        compact_margin=4,
        max_entries_per_rpc=4,
        client_interval=2,
        client_redirect=True,
        drop_prob=0.3,
        drop_prob_uniform=True,
        clock_skew_prob=0.15,
        partition_period=40,
        partition_prob=0.5,
        crash_prob=0.3,
        crash_period=40,
        crash_down_ticks=15,
        check_log_matching=True,
    )
    m = metrics_of(cfg, 12, 64, 600)
    assert int(m.violations.sum()) == 0
    assert int((m.first_leader_tick < NEVER).sum()) > 32
    # the ring really wrapped under fire somewhere in the fleet
    assert int(m.max_commit.max()) > cfg.log_capacity
    # cluster trajectories (incl. client_pend/log_base state) are batch-invariant
    small_f, small_m = scan.simulate(cfg, 12, 4, 200)
    big_f, big_m = scan.simulate(cfg, 12, 64, 200)
    for a, b in zip(
        jax.tree.leaves(jax.device_get(small_f)), jax.tree.leaves(jax.device_get(big_f))
    ):
        np.testing.assert_array_equal(a, b[:4])
    for a, b in zip(
        jax.tree.leaves(jax.device_get(small_m)), jax.tree.leaves(jax.device_get(big_m))
    ):
        np.testing.assert_array_equal(a, b[:4])
