"""Pass C's own tests: the derived cost model must agree with the eval_shape
hand pricing it replaced (the 1% acceptance bound), the golden file must pin
every audited program and entry point, each cost rule must fire on a seeded
violation (widened carry, materialized float temporary, dropped donation)
and stay silent on the clean tree, and the analyzer itself must fit a pinned
runtime budget so the gate can never eat the 870 s tier-1 budget.

Everything here is lowering/liveness-walk only plus a tiny-shape compile
per donating entry point (the donation probes, shared via
cost_model.donation_audit's cache with the gate) -- no device execution, and every real-program lowering rides the same
`jaxpr_audit` lru_caches the Pass A tests already warm.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax

from raft_sim_tpu.analysis import cost_model as CM
from raft_sim_tpu.analysis import jaxpr_audit as JA
from raft_sim_tpu.utils.config import PRESETS
from tools import traffic_audit as TA

@functools.lru_cache(maxsize=None)
def _golden():
    # Loaded lazily so a missing/corrupt golden fails only the tests that
    # read it (the gate's cost-golden finding stays the diagnosis elsewhere).
    with open(CM.golden_path()) as f:
        return json.load(f)

# The acceptance tiers: derived-vs-hand agreement is asserted where the
# roofline verdicts live (docs/PERF.md prints configs 3/4/5; config5c is the
# compacted-layout tier whose pin IS the ISSUE-14 bytes/tick verdict, so the
# 1% cross-check covers the packed-leg pricing too).
AGREEMENT_CONFIGS = ("config3", "config4", "config5", "config5c")


# ------------------------------------------- derived vs eval_shape agreement


def test_derived_carry_agrees_with_eval_shape_pricing():
    """ISSUE-5 acceptance: the jaxpr-derived scan-carry bytes/tick and
    traffic_audit's eval_shape leaf pricing agree within 1% on configs
    3/4/5 -- and the derived moving set COVERS the hand-priced set (derived
    superset of hand: a leaf the hand table prices but the lowering does not move
    would mean the policy and the program disagree)."""
    for name in AGREEMENT_CONFIGS:
        cfg, batch = PRESETS[name]
        cm = CM.carry_model(JA.scan_jaxpr(cfg), batch)
        hand = [r for r in TA._leaf_rows(cfg) if r[0] != "inputs"]
        hand_log = sum(2 * TA._logical(s, i) for _, _, s, i in hand)
        hand_pad = sum(2 * TA._padded(s, i, batch) for _, _, s, i in hand)
        assert abs(cm["carry_logical"] - hand_log) <= 0.01 * hand_log, name
        assert abs(cm["carry_padded"] - hand_pad) <= 0.01 * hand_pad, name
        hand_names = {n for _, n, _, _ in hand}
        assert hand_names <= set(cm["moving_legs"]), (
            f"{name}: hand-priced leaves not moving in the lowered scan: "
            f"{hand_names - set(cm['moving_legs'])}"
        )


def test_golden_pin_matches_traffic_audit_table():
    """The gated pin and the docs/PERF.md roofline table are the same model:
    golden config5 bytes/tick == traffic_audit's packed per-cluster-tick
    total (carry + inputs) within 1% -- so the config5 bool-free bound the
    PERF table prints is cross-checked against what CI actually gates."""
    cfg, batch = PRESETS["config5"]
    a = TA.audit(cfg, batch)
    pin = _golden()["programs"]["config5/simulate"]["bytes_per_tick_padded"]
    assert abs(pin - a["packed_padded"]) <= 0.01 * a["packed_padded"]
    assert a["boolfree_padded"] < a["packed_padded"]


def test_derived_rows_are_traffic_audits_primary_source():
    """audit() must price the carry from the derived rows (same totals)."""
    cfg, batch = PRESETS["config5"]
    rows = TA._derived_carry_rows(cfg)
    a = TA.audit(cfg, batch)
    carry_pad = sum(2 * TA._padded(s, i, batch) for _, _, s, i in rows)
    input_pad = sum(
        TA._padded(s, i, batch)
        for g, _, s, i in TA._leaf_rows(cfg) if g == "inputs"
    )
    assert abs(a["packed_padded"] - (carry_pad + input_pad)) < 1.0


# ------------------------------------------------------------- golden pins


def test_golden_pins_every_audited_program():
    """ISSUE-5 acceptance: golden_cost_model.json pins bytes/tick + padded
    footprint + donation status for every audited program."""
    want = {
        f"{c}/{p}"
        for c in JA.AUDIT_CONFIGS
        for p in ("step", "step_b", "simulate", "scenario_simulate",
                  "serve_simulate", "trace_simulate")
    }
    assert set(_golden()["programs"]) == want
    for key, entry in _golden()["programs"].items():
        assert entry["live_peak"] > 0, key
        if key.endswith("simulate"):
            assert entry["carry_padded"] > 0, key
            assert entry["bytes_per_tick_padded"] > entry["carry_padded"], key
            assert entry["moving_legs"], key
    assert set(_golden()["donation"]) == {
        label for label, _, _ in CM.entry_points()
    }
    assert _golden()["donation"]["sim.chunked._chunk_donate"] == "donated"
    # The telemetry soak loop (the documented 10M-tick workflow) holds the
    # same contract: its chunk must donate too, or long runs double-buffer.
    assert _golden()["donation"]["sim.telemetry._chunk_t_donate"] == "donated"
    # ISSUE-6 acceptance: the standing-fleet serve loop never double-buffers
    # the fleet -- its chunk's donation status is pinned.
    assert _golden()["donation"]["serve.loop._serve_chunk"] == "donated"


def test_tree_gates_clean_cost_pass():
    assert CM.run_pass() == []


def test_subset_run_does_not_condemn_other_pins():
    """A --configs subset run must not report the other tiers' pins stale."""
    assert CM.run_pass(config_names=("config3",)) == []


def test_update_golden_preserves_tuned_tolerances(tmp_path):
    """Regenerating the pins must not silently revert a maintainer-tuned
    tolerance to the defaults (docs/ANALYSIS.md: tunable in the golden file);
    untuned keys still land on DEFAULT_TOLERANCE. Rides the process-cached
    derivation, so this costs no extra lowering."""
    path = tmp_path / "golden.json"
    path.write_text(json.dumps({"tolerance": {"live_peak": 0.10}}))
    CM.update_golden(path=str(path))
    doc = json.loads(path.read_text())
    assert doc["tolerance"]["live_peak"] == 0.10
    assert doc["tolerance"]["carry_bytes"] == CM.DEFAULT_TOLERANCE["carry_bytes"]


def test_missing_golden_is_itself_a_finding(tmp_path):
    got = CM.run_pass(
        config_names=("config3",), golden_file=str(tmp_path / "nope.json")
    )
    assert [f.rule for f in got] == ["cost-golden"]
    assert "--update-goldens" in got[0].message


# ------------------------------------------------------- seeded negatives

_N, _B = 6, 4


def _toy_scan(extra_leg=False, float_temp=False):
    """A miniature batch-minor run loop (trailing batch axis, like the real
    scan): two moving carry legs, optionally a third injected [N, N] int32
    leg (the carry-widening seed) or a materialized [N, 64, B] float32
    temporary (the live-peak seed)."""
    a0 = jax.ShapeDtypeStruct((_N, _N, _B), jnp.int8)
    b0 = jax.ShapeDtypeStruct((_N, _B), jnp.int32)
    e0 = jax.ShapeDtypeStruct((_N, _N, _B), jnp.int32)

    def body2(c, _):
        a, b = c
        if float_temp:
            f = b[:, None, :].astype(jnp.float32) * jnp.ones((1, 64, 1), jnp.float32)
            b = b + f.sum(axis=1).astype(jnp.int32)
        else:
            b = b + 1
        return ((a + 1).astype(jnp.int8), b), None

    def body3(c, _):
        a, b, e = c
        return ((a + 1).astype(jnp.int8), b + 1, e + 1), None

    if extra_leg:
        return jax.make_jaxpr(
            lambda a, b, e: lax.scan(body3, (a, b, e), None, length=4)[0]
        )(a0, b0, e0)
    return jax.make_jaxpr(
        lambda a, b: lax.scan(body2, (a, b), None, length=4)[0]
    )(a0, b0)


def _derive_toy(closed):
    peak, temp = CM.live_peak_bytes(closed)
    return {
        "kind": "scan", "live_peak": peak, "temp_bytes": temp,
        **CM.carry_model(closed, batch=_B),
    }


def _pin_toy(derived):
    return {
        "kind": "scan",
        "moving_legs": dict(derived["moving_legs"]),
        "carry_padded": derived["carry_padded"],
        "live_peak": derived["live_peak"],
        "temp_bytes": derived["temp_bytes"],
    }


def test_widened_carry_leg_is_caught():
    """Seeded negative 1: an extra [N, N] int32 plane entering the scan carry
    yields an unwaived cost-carry-bytes finding naming the new leg."""
    pin = _pin_toy(_derive_toy(_toy_scan()))
    widened = _derive_toy(_toy_scan(extra_leg=True))
    got = CM.compare_program(
        "toy/simulate", widened, pin, version_match=True, golden={}
    )
    carry = [f for f in got if f.rule == "cost-carry-bytes"]
    assert carry and not any(f.waived for f in carry)
    assert any("leg2" in f.message and "carry widened" in f.message for f in carry)


def test_float_temporary_is_caught():
    """Seeded negative 2: a materialized float32 temporary in the scan body
    inflates the live-set peak past tolerance -> cost-live-peak."""
    pin = _pin_toy(_derive_toy(_toy_scan()))
    hot = _derive_toy(_toy_scan(float_temp=True))
    assert hot["live_peak"] > pin["live_peak"] * 1.05
    got = CM.compare_program(
        "toy/simulate", hot, pin, version_match=True, golden={}
    )
    assert [f.rule for f in got] == ["cost-live-peak"]
    # ...and the same seed trips Pass A's float-op rule: the two passes fence
    # the same mistake from independent directions.
    assert JA.check_float_ops("jaxpr:toy/simulate", _toy_scan(float_temp=True))


def test_dropped_donation_is_caught():
    """Seeded negative 3: a jit wrapper that lost its donate_argnums lowers
    with zero aliased args -> cost-donation against the 'donated' pin."""
    x = jax.ShapeDtypeStruct((8,), jnp.int32)
    dropped = jax.jit(lambda v: v + 1).lower(x)
    kept = jax.jit(lambda v: v + 1, donate_argnums=(0,)).lower(x)
    assert CM.lowered_donation_status(kept)["status"] == "donated"
    res = CM.lowered_donation_status(dropped)
    assert res["status"] == "not-donated"
    got = CM.compare_donation({"toy.entry": res}, {"toy.entry": "donated"})
    assert [f.rule for f in got] == ["cost-donation"]
    assert "donate_argnums" in got[0].message
    # The kept wrapper matches its pin: no finding.
    assert CM.compare_donation(
        {"toy.entry": CM.lowered_donation_status(kept)}, {"toy.entry": "donated"}
    ) == []


def test_improvement_reports_stale_golden_not_regression():
    """A carry leg that STOPS moving is an improvement: the pin is stale
    (cost-golden), never a cost-carry-bytes regression."""
    base = _derive_toy(_toy_scan())
    pin = _pin_toy(base)
    pin["moving_legs"]["phantom"] = 123.0
    got = CM.compare_program(
        "toy/simulate", base, pin, version_match=True, golden={}
    )
    assert [f.rule for f in got] == ["cost-golden"]
    assert "phantom" in got[0].message


# -------------------------------------------------------- donation audit


def test_entry_point_donation_audit():
    """The real entry points hold their design statuses, and the donating
    chunk is CONFIRMED at the executable level where the backend reports
    memory stats (alias_size_in_bytes > 0), not just marked in the MLIR."""
    audit = dict(CM.donation_audit())
    for label, expected, _ in CM.entry_points():
        assert audit[label]["status"] == expected, label
    donate = audit["sim.chunked._chunk_donate"]
    assert donate["aliased_args"] > 0
    mem = donate["memory_analysis"]
    if mem.get("available"):
        assert mem["alias_size_in_bytes"] > 0


# ------------------------------------------------------------- anchor source


def test_bench_anchor_reads_newest_artifact_and_merges_pins():
    anchors, source, notes = CM.anchor()
    assert source and "BENCH_r" in source
    # Artifact rows win where present; pinned r05 fills truncated gaps.
    assert set(CM.FALLBACK_ANCHOR_R05) <= set(anchors)
    for name, v in anchors.items():
        assert v > 0, name


def test_bench_anchor_falls_back_with_a_note(tmp_path):
    anchors, source, notes = CM.anchor(root=str(tmp_path))
    assert anchors == CM.FALLBACK_ANCHOR_R05
    assert source == "pinned-r05-fallback"
    assert any("falling back" in n for n in notes)
    # A truncated artifact still yields whatever rows survive in its tail.
    (tmp_path / "BENCH_r07.json").write_text(json.dumps({
        "n": 7, "rc": 0, "parsed": None,
        "tail": 'garbage "config5": {"cluster_ticks_per_s": 2500000.0} more',
    }))
    anchors, source, notes = CM.anchor(root=str(tmp_path))
    assert anchors["config5"] == 2500000.0
    assert anchors["config3"] == CM.FALLBACK_ANCHOR_R05["config3"]
    assert "BENCH_r07.json" in source and "pinned r05" in source


def test_bench_anchor_rejects_non_production_batch_rows(tmp_path):
    """A --smoke / custom-batch round saved as the newest artifact must NOT
    rebase the roofline anchor onto its (orders-of-magnitude-off) throughput:
    rows whose `batch` differs from the preset's production batch are dropped
    with a note, and the anchor falls back."""
    (tmp_path / "BENCH_r08.json").write_text(json.dumps({
        "parsed": {"matrix": {
            "config5": {"cluster_ticks_per_s": 9.9e3, "batch": 7},
        }},
    }))
    anchors, source, notes = CM.anchor(root=str(tmp_path))
    assert anchors["config5"] == CM.FALLBACK_ANCHOR_R05["config5"]
    assert source == "pinned-r05-fallback"
    assert any("batch=7" in n and "ignored" in n for n in notes)


def test_bench_anchor_rejects_smoke_rows_at_production_batch(tmp_path):
    """config1's smoke batch equals its production batch, so the batch filter
    alone can't keep a saved --smoke artifact from becoming the anchor: the
    row's `smoke` marker (written by bench) must."""
    (tmp_path / "BENCH_r09.json").write_text(json.dumps({
        "parsed": {"matrix": {
            "config1": {"cluster_ticks_per_s": 123.4, "batch": 1,
                        "smoke": True},
        }},
    }))
    anchors, source, notes = CM.anchor(root=str(tmp_path))
    assert "config1" not in anchors or anchors["config1"] != 123.4
    assert any("--smoke" in n and "ignored" in n for n in notes)


def test_bench_anchor_rejects_cpu_backend_rows(tmp_path):
    """A non-smoke CPU run at production batch (bench >= r06 rows record
    `backend`) must never rebase the roofline anchor onto host-memory
    throughput -- the runtime mirror of obs/reconcile.py's non-anchor
    marking. Backend-less rows (BENCH_r01-r05, all chip-recorded) stay
    eligible."""
    (tmp_path / "BENCH_r10.json").write_text(json.dumps({
        "parsed": {"matrix": {
            "config5": {"cluster_ticks_per_s": 5.5e4, "batch": 10_000,
                        "backend": "cpu"},
            "config4": {"cluster_ticks_per_s": 21.0e6, "batch": 100_000},
        }},
    }))
    anchors, source, notes = CM.anchor(root=str(tmp_path))
    assert anchors["config5"] == CM.FALLBACK_ANCHOR_R05["config5"]
    assert anchors["config4"] == 21.0e6  # backend-less chip row still anchors
    assert any("cpu backend" in n and "ignored" in n for n in notes)


def test_failed_carry_derivation_is_a_visible_finding():
    """A scan-kind entry whose run scan could not be located must fire a
    cost-golden finding, not silently skip every carry/roofline comparison
    (the gate must go red when it stops gating)."""
    derived = {
        "jax_version": "1", "donation": {},
        "programs": {"x/simulate": {
            "kind": "scan", "live_peak": 10, "temp_bytes": 10,
            "error": "no scan found in a scan-kind program",
        }},
    }
    golden = {
        "jax_version": "1", "donation": {},
        "programs": {"x/simulate": {
            "kind": "scan", "live_peak": 10,
            "moving_legs": {"now": 4.0}, "carry_padded": 4.0,
        }},
    }
    got = CM.compare(derived, golden, full=False)
    assert [f.rule for f in got] == ["cost-golden"]
    assert "derivation failed" in got[0].message
    assert "NOT being checked" in got[0].message


def test_padded_bytes_prices_eight_byte_elements():
    """An int64 carry leg (a legal CONCRETE_DTYPES token, live whenever x64 is
    enabled) must be PRICED -- 64-bit lowers as paired 32-bit words on TPU, so
    it tiles like a 4-byte element at twice the bytes -- not crash the whole
    gate with a KeyError on exactly the carry-widening input Pass C exists to
    flag."""
    from raft_sim_tpu.analysis import policy

    assert policy.padded_bytes((6,), 8, 4) == 2 * policy.padded_bytes((6,), 4, 4)
    assert set(policy.SUBLANE) >= {1, 2, 4, 8}


def test_smoke_rows_never_attach_roofline_headroom():
    """config1's smoke batch EQUALS its production batch (1; SMOKE_TICKS is
    what shrinks it), so the batch comparison alone cannot keep a --smoke row
    from carrying chip-anchor-vs-CPU headroom once config1 gains a pin; the
    smoke flag itself must gate the pin."""
    import bench as B

    for name in ("config1", "config3"):
        cfg, prod = PRESETS[name]
        assert B._pin_applies(name, cfg, prod, smoke=False)
        assert not B._pin_applies(name, cfg, prod, smoke=True)
    cfg3 = PRESETS["config3"][0]
    assert not B._pin_applies("config3", cfg3, 64, smoke=False)  # custom batch
    assert not B._pin_applies("custom", cfg3, 64, smoke=False)   # no preset, no pin


def test_version_mismatch_is_a_visible_stale_pin_finding():
    """A jax upgrade disables the live-peak comparison -- that must surface
    as a cost-golden finding, never a gate that silently stays green."""
    derived = {"jax_version": "9.9.9", "programs": {}, "donation": {}}
    golden = {
        "jax_version": "0.0.1",
        "programs": {"x/simulate": {"live_peak": 10}},
        "donation": {},
    }
    got = CM.compare(derived, golden, full=False)
    assert [f.rule for f in got] == ["cost-golden"]
    assert "live-set peak" in got[0].message and "--update-goldens" in got[0].message
    # Same versions, or no live-peak pins at all: no such finding.
    assert CM.compare(
        {"jax_version": "1", "programs": {}, "donation": {}},
        {"jax_version": "1", "programs": {"x/simulate": {"live_peak": 10}},
         "donation": {}},
        full=False,
    ) == []


# ------------------------------------------------------------ runtime budget


def test_cost_pass_runtime_budget():
    """The gate itself is bounded: the whole cost pass (derive all tiers,
    donation probe, golden compare) must stay under 60 s on CPU -- lowering
    and the tiny donation probes only, so it can never crowd the 870 s tier-1
    budget. Earlier tests share the lru-cached lowerings, so this measures
    the warm gate CI actually pays per check.py run."""
    t0 = time.monotonic()
    found = CM.run_pass()
    elapsed = time.monotonic() - t0
    assert found == []
    assert elapsed < 60.0, f"cost pass took {elapsed:.1f}s (budget 60s)"
