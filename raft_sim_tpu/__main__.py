from raft_sim_tpu.driver import main

raise SystemExit(main())
