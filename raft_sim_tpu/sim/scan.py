"""The jit-compiled tick loop: `lax.scan` over raft.step with on-device metrics.

This replaces the reference's blocking event loop -- `loop [node (init-node id)] (recur
(wait system node))` (core.clj:202-203) -- with a single compiled scan. Where the
reference's observability is an unconditional println of node + message per iteration
(core.clj:182-186), here the cheap path accumulates a small `RunMetrics` reduction in
the scan carry, and trace modes optionally stack per-tick `StepInfo` or full states for
host-side inspection.

Everything is written for ONE cluster and lifted over the batch axis with `vmap`
(`run_batch`); sharding across chips happens one level up, in `raft_sim_tpu.parallel`.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_sim_tpu.models import raft
from raft_sim_tpu.sim import faults
from raft_sim_tpu.types import NIL, ClusterState, StepInfo
from raft_sim_tpu.utils.config import RaftConfig

# Sentinel for "never happened" tick values (first leader, stable leader). Public so
# consumers (parallel.summarize, tests) compare against the same constant. Kept a
# plain Python int: a module-level jnp array would initialize the JAX backend at
# import time, before driver.select_backend can pick the platform.
NEVER = 2**31 - 1
_BIG = NEVER


class RunMetrics(NamedTuple):
    """Per-cluster summary accumulated on device across a run.

    `first_leader_tick` is the first tick at which any node held LEADER; the
    north-star quality metric "ticks-to-stable-leader" is `last_leaderless_tick + 1`
    (the tick after which leadership was continuously held). Both are _BIG / -1
    sentinels when never reached.
    """

    violations: jax.Array  # int32: count of ticks with any invariant violation
    first_leader_tick: jax.Array  # int32 (_BIG if never)
    last_leaderless_tick: jax.Array  # int32 (-1 if a leader existed from tick 0)
    max_term: jax.Array  # int32
    max_commit: jax.Array  # int32
    min_commit: jax.Array  # int32: min over nodes at the final tick
    total_msgs: jax.Array  # int32: delivered records over the run
    total_cmds: jax.Array  # int32: client commands accepted by a live leader
    # Offer->commit latency accumulators (StepInfo.lat_sum/lat_cnt): this
    # cluster's mean commit latency is lat_sum / lat_cnt; parallel.summarize
    # rolls the fleet p50 of those means.
    lat_sum: jax.Array  # int32
    lat_cnt: jax.Array  # int32
    # Per-entry latency histogram (log2 bins, StepInfo.lat_hist): summed over the
    # fleet in parallel.summarize to recover true p50/p95/p99 percentiles. The
    # one non-scalar metric leaf: [LAT_HIST_BINS] per cluster (public [B, BINS]
    # layout; the batch-minor scan carries it [BINS, B] internally).
    lat_hist: jax.Array  # [LAT_HIST_BINS] int32
    # Latency coverage gap (StepInfo.lat_excluded): client entries the frontier
    # crossed in leaderless windows, permanently dropped from lat_sum/lat_cnt/
    # lat_hist -- the undercount docs/PERF.md documents, now measured.
    lat_excluded: jax.Array  # int32
    # Liveness/coverage counters (StepInfo.noop_blocked / lm_skipped_pairs).
    noop_blocked: jax.Array  # int32: election wins denied their no-op slot
    lm_skipped_pairs: jax.Array  # int32: pair-checks skipped by ring log matching
    # ReadIndex read traffic (StepInfo.reads_served/read_lat_sum/read_hist;
    # zeros unless cfg.read_index): reads served, their summed offer->serve
    # latency, and the log2-bin read-latency histogram -- the read-side
    # mirror of lat_sum/lat_cnt/lat_hist, so telemetry reports commit-vs-read
    # latency from one schema.
    reads_served: jax.Array  # int32
    read_lat_sum: jax.Array  # int32
    read_hist: jax.Array  # [LAT_HIST_BINS] int32
    # Durability lag (StepInfo.fsync_lag_sum/fsync_lag_max; zeros unless
    # cfg.durable_storage): summed node-tick lag and the run's max node lag,
    # in entries of un-fsynced log. The per-cluster mean lag is
    # fsync_lag_sum / (ticks * N); parallel.summarize rolls fleet
    # percentiles of those means and the max-of-max.
    fsync_lag_sum: jax.Array  # int32
    fsync_lag_max: jax.Array  # int32
    # Split-brain exposure: ticks with >= 2 concurrent LEADER roles
    # (StepInfo.n_leaders). LEGAL under partitions (a deposed leader has not
    # heard the news yet) -- only SAME-term double leadership violates
    # election safety -- but it is the graded precursor of that violation,
    # which makes it both a useful observability counter and the scenario
    # search's fitness signal toward election-safety breaks (a deceptive
    # landscape otherwise: message drop maximizes leaderless churn while
    # PREVENTING the concurrent successful elections a violation needs).
    multi_leader: jax.Array  # int32: ticks with n_leaders >= 2
    ticks: jax.Array  # int32


def init_metrics_batch(batch: int) -> RunMetrics:
    """Zeroed RunMetrics with a leading [batch] axis (the run_batch/driver carry)."""
    return jax.vmap(lambda _: init_metrics())(jnp.arange(batch))


def init_metrics() -> RunMetrics:
    from raft_sim_tpu.types import LAT_HIST_BINS

    z = jnp.int32(0)
    return RunMetrics(
        violations=z,
        first_leader_tick=jnp.int32(NEVER),
        last_leaderless_tick=jnp.int32(-1),
        max_term=z,
        max_commit=z,
        min_commit=z,
        total_msgs=z,
        total_cmds=z,
        lat_sum=z,
        lat_cnt=z,
        lat_hist=jnp.zeros((LAT_HIST_BINS,), jnp.int32),
        lat_excluded=z,
        noop_blocked=z,
        lm_skipped_pairs=z,
        reads_served=z,
        read_lat_sum=z,
        read_hist=jnp.zeros((LAT_HIST_BINS,), jnp.int32),
        fsync_lag_sum=z,
        fsync_lag_max=z,
        multi_leader=z,
        ticks=z,
    )


def _host_zero(x) -> bool:
    """True for a host-side constant zero StepInfo leaf: the kernels emit np
    constants (never jnp.zeros, which would lower an op) for metrics whose
    structural gate is off, and skipping the fold keeps the corresponding
    RunMetrics carry leg loop-invariant -- XLA elides it from the per-tick
    HBM round trip and the Pass C cost gate prices it at zero
    (zero-cost-when-off, the same contract the state legs follow)."""
    import numpy as np  # host-side predicate only; jnp arrays fall through

    return isinstance(x, (int, np.integer, np.ndarray)) and not np.any(x)


def _add_gated(a, b):
    return a if _host_zero(b) else a + b


def _max_gated(a, b):
    """The maximum-fold twin of _add_gated (same host-predicate gate): used by
    the fsync-lag max, whose neutral element under max-of-nonnegatives is the
    same host zero the sum folds skip on."""
    return a if _host_zero(b) else jnp.maximum(a, b)


def step_bad(info):
    """The per-tick any-invariant-tripped predicate, shared by every
    violations fold (metric accumulation, telemetry windows, the serve loop's
    first-violation tick). viol_read_stale joins the classic three only when
    its gate emitted a real array (cfg.read_lease AND check_invariants) --
    the kernels emit a host-constant zero otherwise, and skipping the fold
    (a HOST predicate, like _add_gated's) keeps disabled-mode programs
    byte-identical."""
    bad = info.viol_election_safety | info.viol_commit | info.viol_log_matching
    if not _host_zero(info.viol_read_stale):
        bad = bad | info.viol_read_stale
    return bad


def _accumulate(m: RunMetrics, info: StepInfo, tick: jax.Array) -> RunMetrics:
    bad = step_bad(info)
    has_leader = info.leader != NIL
    return RunMetrics(
        violations=m.violations + bad,
        first_leader_tick=jnp.minimum(
            m.first_leader_tick, jnp.where(has_leader, tick, _BIG)
        ),
        last_leaderless_tick=jnp.maximum(
            m.last_leaderless_tick, jnp.where(has_leader, -1, tick)
        ),
        max_term=jnp.maximum(m.max_term, info.max_term),
        max_commit=jnp.maximum(m.max_commit, info.max_commit),
        min_commit=info.min_commit,
        total_msgs=m.total_msgs + info.msgs_delivered,
        total_cmds=m.total_cmds + info.cmds_injected,
        lat_sum=m.lat_sum + info.lat_sum,
        lat_cnt=m.lat_cnt + info.lat_cnt,
        lat_hist=m.lat_hist + info.lat_hist,
        lat_excluded=m.lat_excluded + info.lat_excluded,
        noop_blocked=m.noop_blocked + info.noop_blocked,
        lm_skipped_pairs=m.lm_skipped_pairs + info.lm_skipped_pairs,
        reads_served=_add_gated(m.reads_served, info.reads_served),
        read_lat_sum=_add_gated(m.read_lat_sum, info.read_lat_sum),
        read_hist=_add_gated(m.read_hist, info.read_hist),
        fsync_lag_sum=_add_gated(m.fsync_lag_sum, info.fsync_lag_sum),
        fsync_lag_max=_max_gated(m.fsync_lag_max, info.fsync_lag_max),
        multi_leader=m.multi_leader + (info.n_leaders >= 2),
        ticks=m.ticks + 1,
    )


def run(
    cfg: RaftConfig,
    state: ClusterState,
    key: jax.Array,
    n_ticks: int,
    trace: bool = False,
    trace_states: bool = False,
    genome=None,
    seg_len: int = 1,
):
    """Scan one cluster forward `n_ticks`. Returns (final_state, metrics, outs) where
    `outs` is None, stacked StepInfo (trace=True), or (StepInfo, stacked states)
    (trace_states=True). `genome` (a ScenarioGenome with `[S]` leaves; `seg_len`
    static) switches input generation to the scenario path (sim/faults.py) --
    the step kernel itself is untouched."""

    def body(carry, _):
        s, m = carry
        inp = faults.make_inputs(cfg, key, s.now, genome=genome, seg_len=seg_len)
        s2, info = raft.step(cfg, s, inp)
        m2 = _accumulate(m, info, s.now)
        if trace_states:
            out = (info, s2)
        elif trace:
            out = info
        else:
            out = None
        return (s2, m2), out

    (final, metrics), outs = lax.scan(body, (state, init_metrics()), None, length=n_ticks)
    return final, metrics, outs


def run_batch(
    cfg: RaftConfig,
    state: ClusterState,
    keys: jax.Array,
    n_ticks: int,
    trace: bool = False,
    genome=None,
    seg_len: int = 1,
):
    """vmap'd `run` over the leading batch axis of `state` / `keys` (and, when
    given, the `[B, S]` genome rows -- one private fault setting per cluster)."""
    if genome is None:
        return jax.vmap(lambda s, k: run(cfg, s, k, n_ticks, trace=trace))(state, keys)
    return jax.vmap(
        lambda s, k, g: run(cfg, s, k, n_ticks, trace=trace, genome=g, seg_len=seg_len)
    )(state, keys, genome)


def run_batch_minor(
    cfg: RaftConfig,
    state: ClusterState,
    keys: jax.Array,
    n_ticks: int,
    step_fn=None,
    genome=None,
    seg_len: int = 1,
):
    """Batch-minor hot path: same trajectories as `run_batch` (bit-for-bit; see
    tests/test_batched_parity.py) via models/raft_batched.step_b, with the batch axis
    transposed to minor once at entry/exit so every per-tick array is TPU-tiled with
    the batch on the 128-lane dimension. State in/out keeps the public [B, ...]-leading
    convention. No per-tick trace output (use run_batch for tracing).

    `step_fn(cfg, state_minor, inputs_minor)` overrides the tick kernel (the Pallas
    engine passes its kernelized step here so both engines share one scan body).
    `genome` ([B, S] ScenarioGenome rows; `seg_len` static) switches input
    generation to the scenario path -- a heterogeneous fleet through ONE
    compiled program; the genome rides the scan as loop constants, never the
    carry."""
    from raft_sim_tpu.models import raft_batched

    if step_fn is None:
        step_fn = raft_batched.step_b
    batch = state.role.shape[0]
    s_t = raft_batched.to_batch_minor(state)

    def body(carry, _):
        s, m = carry
        s2, m2, _ = tick_batch_minor(
            cfg, s, keys, m, step_fn=step_fn, genome=genome, seg_len=seg_len
        )
        return (s2, m2), None

    # Metrics ride the scan batch-minor too (the histogram leaf is [BINS, B]
    # there; scalars-per-cluster are [B] in either layout).
    (final_t, metrics), _ = lax.scan(
        body,
        (s_t, raft_batched.to_batch_minor(init_metrics_batch(batch))),
        None,
        length=n_ticks,
    )
    return (
        raft_batched.from_batch_minor(final_t),
        raft_batched.from_batch_minor(metrics),
    )


def tick_batch_minor(
    cfg, s, keys, metrics, step_fn=None, client_cmd=None, genome=None, seg_len=1,
    events=False, read_cmd=None,
):
    """ONE tick of the batch-minor path: input generation, step, metric
    accumulation. `s` is batch-minor; `keys` keep their [B]-leading layout (input
    draws are vmapped batch-leading, then transposed). The single shared tick body
    for the scan loop above AND interactive single-tick drivers (Session.offer),
    so the two can never drift. `client_cmd` overrides the scheduled client input
    for this tick. Returns (state, metrics, StepInfo) -- the per-tick info rides
    batch-minor ([B] scalars, [BINS, B] histogram); callers that only need the
    carry drop it (XLA dead-code-eliminates the unused output).

    `events=True` (the trace plane, cfg.track_trace) additionally extracts
    this tick's protocol events from the state delta (trace/events.py) and
    returns (state, metrics, StepInfo, TickEvents). Extraction is read-only
    over values this body already computes plus the fault facts recomputed
    from the same key streams (faults.trace_fault_inputs) -- the first three
    return values are bit-identical either way (tests/test_trace.py)."""
    from raft_sim_tpu.models import raft_batched

    if step_fn is None:
        step_fn = raft_batched.step_b
    if genome is None:
        inp = jax.vmap(lambda k, now: faults.make_inputs(cfg, k, now))(keys, s.now)
    else:
        # [B, S] genome rows vmap alongside the keys: cluster b's inputs come
        # from ITS fault setting (sim/faults.py scenario path).
        inp = jax.vmap(
            lambda k, now, g: faults.make_inputs(
                cfg, k, now, genome=g, seg_len=seg_len
            )
        )(keys, s.now, genome)
    if client_cmd is not None:
        # Scalar (one offer broadcast fleet-wide: Session.offer) or [B]
        # (per-cluster offer plane: the tenancy serve loop, where the batch
        # axis IS the tenancy axis and each cluster gets its tenant's own
        # command this tick).
        inp = inp._replace(
            client_cmd=jnp.broadcast_to(
                jnp.asarray(client_cmd, inp.client_cmd.dtype),
                inp.client_cmd.shape,
            )
        )
    if read_cmd is not None:
        # External ReadIndex ingest (the read-only traffic class riding the
        # serve path beside offered writes): overrides the scheduled
        # read cadence for this tick, exactly like client_cmd above --
        # scalar or per-cluster [B]. The config must carry the structural
        # gate (cfg.read_index).
        inp = inp._replace(
            read_cmd=jnp.broadcast_to(
                jnp.asarray(read_cmd, inp.read_cmd.dtype), inp.read_cmd.shape
            )
        )
    inp_t = raft_batched.to_batch_minor(inp)
    s2, info = step_fn(cfg, s, inp_t)
    m2 = _accumulate(metrics, info, s.now)  # all fields [B]: elementwise
    if not events:
        return (s2, m2, info)
    from raft_sim_tpu.trace import events as tev

    if genome is None:
        crashed, cut_now, cut_prev = jax.vmap(
            lambda k, now: faults.trace_fault_inputs(cfg, k, now)
        )(keys, s.now)
    else:
        crashed, cut_now, cut_prev = jax.vmap(
            lambda k, now, g: faults.trace_fault_inputs(
                cfg, k, now, genome=g, seg_len=seg_len
            )
        )(keys, s.now, genome)
    ev = tev.extract(
        cfg, s, s2, inp_t, info, jnp.moveaxis(crashed, 0, -1), cut_now, cut_prev
    )
    return (s2, m2, info, ev)


@functools.partial(jax.jit, static_argnums=(0, 2, 3))
def simulate(cfg: RaftConfig, seed, batch: int, n_ticks: int):
    """One-call batched simulation from a seed: init + scan, fully on device.

    Returns (final_state, RunMetrics) with leading batch axis. Uses the batch-minor
    hot path (same trajectories as run_batch, bit-for-bit).
    """
    root = jax.random.key(seed)
    k_init, k_run = jax.random.split(root)
    from raft_sim_tpu.types import init_batch

    state = init_batch(cfg, k_init, batch)
    keys = jax.random.split(k_run, batch)
    return run_batch_minor(cfg, state, keys, n_ticks)


@functools.partial(jax.jit, static_argnums=(0, 2, 3, 5))
def simulate_scenario(cfg: RaftConfig, seed, batch: int, n_ticks: int, genome,
                      seg_len: int = 1):
    """`simulate` through the scenario path: one compiled program evaluating a
    heterogeneous fleet, cluster b under genome row b ([B, S] leaves, traced --
    new genome VALUES never recompile; only a new S or seg_len does). Init and
    key derivation are identical to `simulate`, so a homogeneous genome
    (scenario.genome.from_config) reproduces `simulate(cfg, seed, ...)`
    bit-for-bit and every (genome, seed) pair is replayable standalone."""
    root = jax.random.key(seed)
    k_init, k_run = jax.random.split(root)
    from raft_sim_tpu.types import init_batch

    state = init_batch(cfg, k_init, batch)
    keys = jax.random.split(k_run, batch)
    return run_batch_minor(cfg, state, keys, n_ticks, genome=genome, seg_len=seg_len)


def stable_leader_ticks(metrics: RunMetrics) -> jax.Array:
    """Ticks-to-stable-leader per cluster: the tick from which leadership was held
    continuously to the end of the run (_BIG if the run ended leaderless)."""
    ended_with_leader = metrics.last_leaderless_tick < metrics.ticks - 1
    return jnp.where(ended_with_leader, metrics.last_leaderless_tick + 1, _BIG)
