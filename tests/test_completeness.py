"""End-to-end client-data completeness (BASELINE config1 as the correctness ref).

The reference's write path is client-set -> redirect-to-leader -> append ->
replicate -> apply-entries! (core.clj:151-160, log.clj:69-76); its commit ack never
fires (bug 2.3.9) and nothing ever verifies the data survived. Here the property is
pinned end to end: on config1's reliable network, every command offered from the
first leader onward is accepted by the leader (RunMetrics.total_cmds), committed on
EVERY node, and the committed values are identical everywhere and exactly the
offered sequence. The on-device log-matching invariant additionally compares values
(not just terms) every tick.
"""

import jax
import numpy as np

from raft_sim_tpu.sim import scan
from raft_sim_tpu.utils.config import PRESETS

# A command lands in the leader log at its offer tick t and is committed everywhere
# within two heartbeat round trips: ship (<=3 ticks) + handle/ack (2) + commit
# broadcast on the next heartbeat (<=3) + handle (1).
SETTLE = 12


def test_config1_every_offered_command_commits():
    cfg, batch = PRESETS["config1"]
    assert batch == 1
    ticks = 10_000
    final, m = scan.simulate(cfg, 0, batch, ticks)
    m = jax.device_get(m)
    final = jax.device_get(final)

    assert int(m.violations[0]) == 0
    flt = int(m.first_leader_tick[0])
    assert flt < scan.NEVER
    # Reliable net: leadership, once gained, is never lost.
    assert int(scan.stable_leader_ticks(m)[0]) == flt

    # Commands are offered every client_interval ticks with value = tick + 1
    # (faults.make_inputs); all offered while a leader existed must be accepted.
    offered = [t + 1 for t in range(0, ticks, cfg.client_interval) if t >= flt]
    assert int(m.total_cmds[0]) == len(offered)

    # Every accepted command except the still-settling tail is committed on all
    # nodes, and all committed values agree and equal the offered sequence exactly.
    settled = [v for v in offered if v + SETTLE <= ticks]
    commit = np.asarray(final.commit_index[0])
    vals = np.asarray(final.log_val[0])
    n = cfg.n_nodes
    assert int(commit.min()) >= len(settled)
    assert int(commit.max()) == len(offered)  # the leader committed everything offered
    for i in range(n):
        c = int(commit[i])
        np.testing.assert_array_equal(vals[i, :c], offered[:c])


def test_commands_without_leader_vanish_and_are_not_counted():
    """Commands offered while no leader exists are dropped AND visible as the gap
    between the offer schedule and total_cmds -- the audit VERDICT round 1 asked for."""
    from raft_sim_tpu import RaftConfig

    cfg = RaftConfig(n_nodes=5, client_interval=1, drop_prob=1.0)
    _, m = scan.simulate(cfg, 0, 8, 100)
    m = jax.device_get(m)
    assert int(np.sum(m.total_cmds)) == 0  # no leader can ever exist
    assert int(np.max(m.max_commit)) == 0
