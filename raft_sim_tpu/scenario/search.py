"""Violation-hunting search: a cross-entropy loop where the fleet IS the
population.

Jepsen-style nemesis testing shows targeted fault schedules find bugs uniform
fuzz misses -- but targeting needs a search loop, and a search loop needs
cheap evaluations. Here one generation = ONE device call: the population of
candidate fault genomes becomes the `[B, S]` genome of a heterogeneous fleet
(telemetry.simulate_windowed through the scenario input path), so 100k
genome evaluations cost what one fuzz run already cost, and new genome
values never recompile (the genome is traced data).

Fitness is built from the PR 2 telemetry window counters -- invariant
violations dominate lexicographically; below them, *distress* signals
(leaderless windows, term churn, commit stalls, latency-coverage gaps) give
the cross-entropy update a gradient toward trouble even while the kernel is
still holding. The mutation fixture (scenario/mutation.py) is the ground
truth that this gradient actually hunts: a quorum-off-by-one kernel must
fall within a bounded generation budget, while the real kernel must survive
the same budget clean (tests/test_scenario.py, CI scenario smoke).

Everything is deterministic and replayable: generation g simulates under
seed `spec.seed + SEED_STRIDE * g`, the population is drawn from
`np.random.default_rng(spec.seed)`, and a hit is fully described by
(genome row, seed, batch, cluster, horizon) -- exactly what shrink.py
minimizes and tools/repro.py --scenario replays.
"""

from __future__ import annotations

import dataclasses
import json

import numpy as np

from raft_sim_tpu.scenario import genome as genome_mod
from raft_sim_tpu.sim import telemetry
from raft_sim_tpu.utils.config import RaftConfig

# Per-generation seed stride: keeps generation seeds disjoint and the whole
# schedule int32-representable for any sane generation count.
SEED_STRIDE = 100_003

# Fitness weights: violations are lexicographically dominant (any violation
# outranks any distress score); the rest shape the gradient toward trouble.
# multi_leader is the load-bearing precursor: concurrent LEADER roles are
# legal (a deposed leader that has not heard the news) but sit one term-
# collision away from an election-safety violation, and they reward exactly
# the schedules that make concurrent elections SUCCEED. Without it the
# landscape is deceptive -- message drop maximizes leaderless churn while
# preventing the successful split elections a violation needs (measured on
# the weak-quorum config5 hunt; docs/SCENARIOS.md).
W_VIOLATION = 1.0e6
W_MULTI_LEADER = 20.0
W_LEADERLESS_WINDOW = 10.0
W_COMMIT_STALL = 5.0
W_TERM_CHURN = 1.0
W_LAT_EXCLUDED = 1.0


@dataclasses.dataclass(frozen=True)
class Knob:
    """One searched genome dimension, normalized to [0, 1] for the CE update.
    kind 'prob' decodes to a float probability in [lo, hi]; 'int' to a
    rounded integer in [lo, hi]."""

    name: str
    lo: float
    hi: float
    kind: str = "prob"


def default_knobs(cfg: RaftConfig) -> tuple[Knob, ...]:
    """The searched fault dimensions and their bounds. Structural knobs
    (topology, timers, routing model) are deliberately absent -- genomes must
    never fork a compile. The client cadence stays pinned to cfg (the
    workload is part of the question, not the answer)."""
    base = (
        Knob("drop_prob", 0.0, 0.6),
        Knob("partition_period", 0.0, 64.0, kind="int"),
        Knob("partition_prob", 0.0, 1.0),
        Knob("crash_prob", 0.0, 0.6),
        Knob("crash_down_ticks", 1.0, float(cfg.crash_period), kind="int"),
        Knob("clock_skew_prob", 0.0, 0.3),
    )
    if cfg.durable_storage:
        # The disk-fault lattice joins the searched space only when the
        # config compiles the durable storage plane in (validate() rejects
        # the axes otherwise). fsync_interval stays >= 1: a zero cadence
        # never flushes, so the durable watermark pins every ack at 0 and
        # the hunt collapses into a commit-stall attractor that can never
        # produce a violation.
        base += (
            Knob("fsync_interval", 1.0, 8.0, kind="int"),
            Knob("fsync_jitter_prob", 0.0, 0.6),
            Knob("torn_tail_prob", 0.0, 0.6),
            Knob("lost_suffix_span", 1.0, float(cfg.log_capacity // 2), kind="int"),
        )
    return base


@dataclasses.dataclass(frozen=True)
class SearchSpec:
    """Search hyperparameters. `population` doubles as the fleet batch."""

    generations: int = 8
    population: int = 64
    ticks: int = 512
    window: int = 64
    elite_frac: float = 0.25
    seed: int = 0
    init_sigma: float = 0.35
    min_sigma: float = 0.05
    # Fitness mode. "scalar" (default): the hand-tuned distress weights
    # below. "coverage": transition-coverage NOVELTY -- each cluster's
    # fitness is the number of (role x event-kind) + (kind -> kind) coverage
    # bits it sets that NO earlier evaluation in this search has seen
    # (raft_sim_tpu/trace, ROADMAP item 5's coverage-guided seed), with
    # violations still lexicographically dominant. Coverage mode runs the
    # trace-variant windowed program -- ONE compiled program for the whole
    # hunt (genomes are traced data; pinned by the analyzer's trace fork
    # pairs), and the bitmap is deterministic for a fixed (genome, seed).
    fitness: str = "scalar"
    # Event-buffer depth of the coverage-mode trace program. Coverage only
    # needs the bitmap, so a shallow buffer keeps the carry cheap; events
    # past it are counted, not kept.
    trace_depth: int = 32
    # Proposal mode. "gaussian" (default): classic CE draws from N(mu,
    # sigma). "coverage-guided": up to `guided_frac` of each generation are
    # mutated clones of the previous generation's novelty-lit parents
    # (propose_coverage_guided -- coverage-guided MUTATION, the AFL move, on
    # top of coverage-as-fitness; requires fitness="coverage" for the
    # novelty signal). Deterministic per (genome, seed) either way.
    proposal: str = "gaussian"
    guided_frac: float = 0.5
    # CE smoothing toward the elite statistics (1.0 = classic full refit).
    # Each generation re-seeds the simulator, so fitness is NOISY; a full
    # refit lets one lucky generation yank the distribution off a promising
    # corner (observed on the config5 weak-quorum hunt: best fitness fell
    # 830 -> 207 over 4 generations before smoothing + best-carryover).
    smoothing: float = 0.6
    # Re-inject the best-so-far knob vector into every population (slot 0):
    # the hall-of-fame individual keeps the attractor sampled under fresh
    # seeds and feeds the elite set even when the new draws miss.
    carry_best: bool = True
    stop_on_hit: bool = True
    knobs: tuple[Knob, ...] | None = None  # None -> default_knobs(cfg)


def _decode_row(cfg: RaftConfig, knobs, x: np.ndarray) -> genome_mod.ScenarioGenome:
    """One normalized knob vector -> an [S=1] genome segment. Workload
    cadences (client traffic AND the reconfiguration-plane admin streams)
    stay pinned to cfg: the workload is part of the question, not the
    answer -- the hunt searches the FAULT space around it."""
    params = {
        "client_interval": cfg.client_interval,
        "reconfig_interval": cfg.reconfig_interval,
        "transfer_interval": cfg.transfer_interval,
        "read_interval": cfg.read_interval,
        "fsync_interval": cfg.fsync_interval,
        "fsync_jitter_prob": cfg.fsync_jitter_prob,
        "torn_tail_prob": cfg.torn_tail_prob,
        "lost_suffix_span": cfg.lost_suffix_span,
    }
    for k, xi in zip(knobs, x):
        v = k.lo + float(xi) * (k.hi - k.lo)
        params[k.name] = int(round(v)) if k.kind == "int" else v
    params["crash_down_ticks"] = max(1, min(int(params.get(
        "crash_down_ticks", 1)), cfg.crash_period))
    params["lost_suffix_span"] = max(1, min(int(params.get(
        "lost_suffix_span", 1)), cfg.log_capacity))
    if cfg.durable_storage:
        params["fsync_interval"] = max(1, int(params.get(
            "fsync_interval", cfg.fsync_interval)))
    return genome_mod.from_segments([genome_mod.segment(**params)])


# The farm (raft_sim_tpu/farm) decodes its portfolio members' knob vectors
# through the same function, so one knob vocabulary serves every hunter.
decode_row = _decode_row


# Distress-signal extractors shared by the scalar blend below and the
# farm's portfolio members (farm/portfolio.py) -- ONE interpretation of the
# telemetry counters, so a sentinel or encoding change in the window plane
# cannot silently fork the two.


def leaderless_windows(records) -> np.ndarray:
    """[B] windows whose fold saw any leaderless tick: such a window carries
    last_leaderless_tick >= 0 (absolute ticks; the window-local fold starts
    at the -1 sentinel)."""
    return (np.asarray(records.metrics.last_leaderless_tick) >= 0).sum(axis=1)


def term_churn(metrics) -> np.ndarray:
    """[B] elections burned over the run (terms start at 1)."""
    return np.maximum(np.asarray(metrics.max_term) - 1, 0)


def commit_stalls(records, metrics) -> np.ndarray:
    """[B] windows where max_commit failed to advance past the previous
    window's high-water mark (only meaningful under a client workload; zero
    contribution without one)."""
    mc = np.asarray(records.metrics.max_commit)  # [B, W], absolute high-water
    stalls = (np.diff(mc, axis=1) <= 0).sum(axis=1) if mc.shape[1] > 1 else 0
    return stalls * (np.asarray(metrics.total_cmds) > 0)


def fitness_from_records(records, metrics) -> np.ndarray:
    """[B] fitness from the telemetry window counters (higher = closer to
    breaking). All host-side numpy over the already-fetched records."""
    viol = np.asarray(metrics.violations, np.float64)
    lat_ex = np.asarray(metrics.lat_excluded, np.float64)
    multi = np.asarray(metrics.multi_leader, np.float64)
    return (
        W_VIOLATION * viol
        + W_MULTI_LEADER * multi
        + W_LEADERLESS_WINDOW * leaderless_windows(records)
        + W_COMMIT_STALL * commit_stalls(records, metrics)
        + W_TERM_CHURN * term_churn(metrics)
        + W_LAT_EXCLUDED * lat_ex
    )


def _popcount_words(words: np.ndarray) -> np.ndarray:
    """Set bits along the leading word axis of a uint32 array -> per-cluster
    counts ([C, B] -> [B]). The per-word popcount is the shared host helper
    (ops/bitplane.np_popcount_u32) so this can never drift from the sink's
    coverage rollup."""
    from raft_sim_tpu.ops.bitplane import np_popcount_u32

    return np_popcount_u32(words).sum(axis=0)


def coverage_novelty(cov: np.ndarray, seen: np.ndarray) -> np.ndarray:
    """[B] novelty counts: bits each cluster's [C, B] coverage bitmap sets
    beyond the accumulated [C] seen-bit union. Scoring is against the union
    as handed in (every cluster of one generation against the same baseline
    -- deterministic and order-free); the caller unions `cov` in afterwards
    (`seen_union`), which keeps multi-consumer scoring -- the farm's
    portfolio members share one hunt-wide seen set -- monotone and
    member-order-free."""
    cov = np.asarray(cov, np.uint32)
    return _popcount_words(cov & ~seen[:, None])


def seen_union(cov: np.ndarray, seen: np.ndarray) -> np.ndarray:
    """The updated [C] seen-bit union after a [C, B] generation bitmap."""
    return seen | np.bitwise_or.reduce(np.asarray(cov, np.uint32), axis=1)


def coverage_fitness(cov: np.ndarray, seen: np.ndarray, violations) -> tuple[np.ndarray, np.ndarray]:
    """([B] fitness, updated seen) from a [C, B] per-cluster coverage bitmap
    and the search's accumulated [C] seen-bit union. Novelty = bits this
    cluster sets beyond everything seen BEFORE this generation; violations
    stay lexicographically dominant -- an all-bits-already-seen generation
    (novelty 0 everywhere) still ranks violating clusters first."""
    fit = W_VIOLATION * np.asarray(violations, np.float64) + coverage_novelty(cov, seen)
    return fit, seen_union(cov, seen)


# --------------------------------------------------------------- proposals


def propose_gaussian(rng, mu: np.ndarray, sigma: np.ndarray, n: int) -> np.ndarray:
    """The classic CE proposal: n knob vectors ~ N(mu, sigma), clipped to the
    normalized cube."""
    return np.clip(rng.normal(mu, sigma, size=(n, mu.shape[0])), 0.0, 1.0)


def _parent_entropy(seed: int, x: np.ndarray) -> list[int]:
    """Deterministic rng entropy for one parent genome: the base seed plus
    the parent's knob vector quantized to the uint32 grid. Two searches with
    the same (genome, seed) mutate identically; any knob difference forks the
    stream."""
    return [int(seed) & 0xFFFFFFFF] + [
        int(v) for v in (np.clip(x, 0.0, 1.0) * 0xFFFFFFFF).astype(np.uint64)
    ]


def propose_coverage_guided(
    rng,
    mu: np.ndarray,
    sigma: np.ndarray,
    n: int,
    parents: np.ndarray | None,
    parent_novelty: np.ndarray | None,
    seed: int,
    frac: float = 0.5,
    mut_scale: float = 0.25,
) -> np.ndarray:
    """Coverage-guided mutation: AFL's core move (mutate what reached new
    coverage) on the CE population. Up to `frac` of the proposals are
    MUTATED CLONES of the previous generation's novelty-lit parents --
    genomes whose windows set (role x kind)/(kind -> kind) bits the hunt had
    never seen -- perturbed at the current sigma; the rest stay classic CE
    draws, so the distribution-level update keeps converging while the
    guided half exploits frontier genomes the mean/sigma statistics would
    average away. Mutation is SMALL by design (`mut_scale` x sigma): a
    frontier parent is a working key into rare behavior, and a full-sigma
    perturbation would be a fresh draw that forgets it (measured: at
    mut_scale 1.0 guided loses the bits-lit A/B it wins at 0.25 --
    tests/test_farm.py pins the win). Each child's noise stream is
    deterministic per (parent genome, seed) (`_parent_entropy`),
    independent of population layout, so a guided hunt replays exactly.
    With no lit parents (first generation, or a dry one) this degrades to
    the gaussian proposal."""
    if parents is None or parent_novelty is None or not np.any(parent_novelty > 0):
        return propose_gaussian(rng, mu, sigma, n)
    lit = np.flatnonzero(parent_novelty > 0)
    # Richest parents first (stable ties by population index).
    lit = lit[np.argsort(-parent_novelty[lit], kind="stable")]
    n_guided = min(int(round(frac * n)), n)
    xs = propose_gaussian(rng, mu, sigma, n)
    for j in range(n_guided):
        p = parents[lit[j % lit.size]]
        crng = np.random.default_rng(_parent_entropy(seed, p) + [j])
        xs[n - 1 - j] = np.clip(
            p + crng.normal(0.0, sigma * mut_scale), 0.0, 1.0
        )
    return xs


@dataclasses.dataclass
class SearchResult:
    """Outcome of one search: per-generation log plus the first violating
    hit (None if the kernel survived the budget -- the expected result for
    the real kernel)."""

    hit: dict | None
    generations: list[dict]
    spec: dict

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))


def search(cfg: RaftConfig, spec: SearchSpec | None = None,
           perf=None) -> SearchResult:
    """Run the cross-entropy hunt against `cfg` (pass a mutation.py config to
    hunt a weakened kernel). Returns the full generation log and, if any
    cluster tripped an on-device invariant, the replayable hit.

    `perf` (an obs.ChunkTimer) attributes each GENERATION (the search's
    "chunk": one simulate_windowed device call): dispatch vs device wait vs
    the host-side decode/CE-update gap, with the windowed program's jit
    cache sampled per generation -- fault genomes are traced data, so a
    cache that grows after generation 0 is the recompile watchdog firing."""
    spec = spec or SearchSpec()
    knobs = spec.knobs or default_knobs(cfg)
    if spec.ticks % spec.window:
        raise ValueError(f"ticks {spec.ticks} must divide by window {spec.window}")
    if spec.fitness not in ("scalar", "coverage"):
        raise ValueError(f"unknown fitness mode {spec.fitness!r} "
                         "(have: scalar, coverage)")
    if spec.proposal not in ("gaussian", "coverage-guided"):
        raise ValueError(f"unknown proposal mode {spec.proposal!r} "
                         "(have: gaussian, coverage-guided)")
    if spec.proposal == "coverage-guided" and spec.fitness != "coverage":
        raise ValueError(
            "proposal='coverage-guided' needs fitness='coverage': guided "
            "mutation selects parents by the novelty bits only the coverage "
            "bitmap provides"
        )
    trace_spec = None
    seen = None
    if spec.fitness == "coverage":
        import dataclasses as _dc

        from raft_sim_tpu.trace.ring import COV_WORDS, TraceSpec

        # The coverage hunt runs the trace-mode variant of cfg: same step
        # kernels, one extra (pinned) windowed lowering -- every generation
        # reuses it, exactly like the scalar mode's program.
        cfg = _dc.replace(cfg, track_trace=True)
        trace_spec = TraceSpec(depth=spec.trace_depth, coverage=True)
        seen = np.zeros(COV_WORDS, np.uint32)
    rng = np.random.default_rng(spec.seed)
    dim = len(knobs)
    mu = np.full(dim, 0.5)
    sigma = np.full(dim, spec.init_sigma)
    n_elite = max(2, int(round(spec.elite_frac * spec.population)))
    gens: list[dict] = []
    hit: dict | None = None
    best_x, best_fit = None, -np.inf
    prev_xs: np.ndarray | None = None  # coverage-guided parent pool
    prev_novelty: np.ndarray | None = None
    if perf is not None:
        perf.add_probe("telemetry.simulate_windowed", telemetry.simulate_windowed)

    for gen in range(spec.generations):
        if spec.proposal == "coverage-guided":
            xs = propose_coverage_guided(
                rng, mu, sigma, spec.population, prev_xs, prev_novelty,
                spec.seed, frac=spec.guided_frac,
            )
        else:
            xs = propose_gaussian(rng, mu, sigma, spec.population)
        if spec.carry_best and best_x is not None:
            xs[0] = best_x
        rows = [_decode_row(cfg, knobs, x) for x in xs]
        g = genome_mod.stack_rows(rows)  # [B, 1] leaves
        genome_mod.validate(cfg, g)
        sim_seed = spec.seed + SEED_STRIDE * gen
        if perf is not None:
            perf.begin(spec.ticks)
        if trace_spec is None:
            _, metrics, records, _ = telemetry.simulate_windowed(
                cfg, sim_seed, spec.population, spec.ticks, spec.window,
                genome=g,
            )
            tp = None
        else:
            _, metrics, records, _, _, tp = telemetry.simulate_windowed(
                cfg, sim_seed, spec.population, spec.ticks, spec.window,
                genome=g, trace=trace_spec,
            )
        import jax

        if perf is not None:
            # The sync on the small metrics leaf is the device wait; genome
            # decode (pre-begin) and the fitness/CE update below land in the
            # adjacent rows' gap_s -- host-attributed either way.
            perf.dispatched()
            perf.end(sync=lambda: np.asarray(metrics.ticks))
        metrics = jax.device_get(metrics)
        records = jax.device_get(records)
        if trace_spec is None:
            fit = fitness_from_records(records, metrics)
            cov_new = None
        else:
            before = int(_popcount_words(seen[:, None])[0])
            cov = np.asarray(tp.cov)
            novelty = coverage_novelty(cov, seen)
            fit = W_VIOLATION * np.asarray(metrics.violations, np.float64) + novelty
            seen = seen_union(cov, seen)
            cov_new = int(_popcount_words(seen[:, None])[0]) - before
            prev_xs, prev_novelty = xs, novelty
        order = np.argsort(-fit)
        elites = xs[order[:n_elite]]
        a = spec.smoothing
        mu = a * elites.mean(axis=0) + (1 - a) * mu
        sigma = np.maximum(
            a * elites.std(axis=0) + (1 - a) * sigma, spec.min_sigma
        )
        if fit[order[0]] > best_fit:
            best_fit, best_x = float(fit[order[0]]), xs[order[0]].copy()
        viol = np.asarray(metrics.violations)
        violating = np.flatnonzero(viol > 0)
        best = int(order[0])
        row = {
            "gen": gen,
            "seed": int(sim_seed),
            "best_fitness": float(fit[best]),
            "mean_fitness": float(fit.mean()),
            "violating_clusters": int(violating.size),
            "best_genome": genome_mod.decode(rows[best])[0],
        }
        if cov_new is not None:
            row["cov_new_bits"] = cov_new
            row["cov_total_bits"] = int(_popcount_words(seen[:, None])[0])
        gens.append(row)
        if violating.size and hit is None:
            c = int(violating[0])
            fv = np.asarray(records.first_viol_tick)[c]
            hit = {
                "seed": int(sim_seed),
                "batch": int(spec.population),
                "cluster": c,
                "ticks": int(spec.ticks),
                "seg_len": 1,
                "first_viol_tick": int(fv[fv < telemetry.NEVER].min()),
                "genome_raw": genome_mod.to_raw(rows[c]),
                "segments": genome_mod.decode(rows[c]),
            }
            if spec.stop_on_hit:
                break

    return SearchResult(
        hit=hit,
        generations=gens,
        spec={
            "generations": spec.generations,
            "population": spec.population,
            "ticks": spec.ticks,
            "window": spec.window,
            "elite_frac": spec.elite_frac,
            "seed": spec.seed,
            "fitness": spec.fitness,
            "proposal": spec.proposal,
            "knobs": [dataclasses.asdict(k) for k in knobs],
        },
    )
