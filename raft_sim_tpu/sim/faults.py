"""Fault injection and per-tick input generation, as pure data.

In the reference, faults are accidental: a dead or unreachable peer makes the outbound
HTTP call throw, the exception is swallowed, and the message vanishes (client.clj:38-40);
election timeouts are the only failure detector (core.clj:171-174); there is no fault
*injection* at all (SURVEY.md section 5). Here fault schedules are first-class pure
inputs derived deterministically from (cluster key, tick):

  - Bernoulli message drop, optionally with a per-cluster drop rate drawn from
    [0, drop_prob] (BASELINE config 4),
  - rolling partitions: every `partition_period` ticks the cluster is (with some
    probability) split into two random halves whose cross edges deliver nothing
    (BASELINE config 5),
  - clock skew: a node's local clock occasionally stalls (+0) or jumps (+2),
  - node crash/restart: a windowed renewal schedule (alive_at) downs nodes for
    bounded spans; restart wipes spec-volatile state but keeps the Raft persistent
    triple -- unlike the reference, whose restarted process loses term/vote/entries
    (log.clj:16-18, SURVEY.md 2.3.12),
  - randomized election-timeout draws (the reference's 5000+rand(5000) ms,
    core.clj:174),
  - client command injection on a fixed cadence (the reference's external curl against
    /client-set, server.clj:8-12).

Everything is a function of (key, now), so trajectories are replayable from a seed and
checkpoint/resume needs only (state, key) -- no RNG state in the carry.

Every Bernoulli event is drawn as a uint32 THRESHOLD COMPARE (`p_to_u32` /
`bern_u32`): `random_bits_u32 < threshold` instead of `uniform_float < p`. Two
reasons. (1) The whole per-tick input pipeline stays integer-only, so the
full compiled scan program -- not just the step kernels -- is float-free and
the analyzer's float-op rule extends to it. (2) The threshold is DATA, not a
baked Python float: the scenario engine (raft_sim_tpu/scenario) threads a
per-cluster `ScenarioGenome` of traced `[S]`-segment fault parameters through
`make_inputs`, and because the scalar-config path and the genome path draw
through the SAME helpers from the SAME key streams, a genome that replicates
the config scalars reproduces the scalar path's trajectories BIT-FOR-BIT
(tests/test_scenario.py pins this). The genome is duck-typed here (fields
`drop/part_period/part/crash/crash_down/skew/client_interval` plus the
reconfiguration-plane cadences `reconfig_interval/transfer_interval/
read_interval` and the disk-fault axes `fsync_interval/fsync_jitter/
torn/torn_span`, each a `[S]` per-segment leaf -- see scenario/genome.py);
sim/ never imports scenario/.

The per-cluster key is split once into disjoint streams (per-tick draws, per-cluster
drop rate, per-window partition layout) so no fold_in value can collide across
purposes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_sim_tpu.ops import bitplane
from raft_sim_tpu.types import NIL, StepInputs
from raft_sim_tpu.utils.config import RaftConfig
from raft_sim_tpu.utils.rng import draw_timeouts

# Threshold encoding of p = 0.5 (the partition group split): exactly half the
# uint32 space.
HALF_U32 = 1 << 31


def p_to_u32(p: float) -> int:
    """Probability -> uint32 Bernoulli threshold: an event fires iff a fresh
    uint32 draw is < the threshold, so P(fire) = threshold / 2^32. p = 1.0
    clamps to 2^32 - 1 (fires with probability 1 - 2^-32); p = 0.0 encodes to
    0 and can never fire. Host-side Python only -- the returned int enters the
    traced program as a uint32 literal (scalar configs) or rides a genome leaf
    (scenario engine)."""
    return max(0, min((1 << 32) - 1, int(round(p * (1 << 32)))))


def bern_u32(key: jax.Array, thresh, shape=()) -> jax.Array:
    """Bernoulli(thresh / 2^32) as an integer threshold compare over fresh
    uint32 bits. `thresh` is a uint32 scalar -- a literal on the scalar-config
    path, traced genome data on the scenario path; both consume the identical
    draw from `key`, which is what makes homogeneous-genome trajectories
    bit-exact with the scalar path."""
    return jax.random.bits(key, shape, jnp.uint32) < thresh


def crash_key(key: jax.Array) -> jax.Array:
    """The dedicated crash-schedule stream for a cluster key. fold_in(-1) is disjoint
    from the per-window fold_in(k_part, window >= 0) draws sharing this base."""
    _, _, k_part = jax.random.split(key, 3)
    return jax.random.fold_in(k_part, jnp.int32(-1))


def _alive_at_t(cfg: RaftConfig, ckey, now, crash_t, crash_down):
    """The ungated windowed-renewal body shared by the scalar path (alive_at)
    and the genome path (make_inputs): `crash_t` is the uint32 crash
    threshold, `crash_down` the max down-span -- literals on the scalar path,
    traced per-cluster genome data on the scenario path; the window length
    stays cfg.crash_period (static) either way."""
    n = cfg.n_nodes
    window = now // cfg.crash_period
    off = now - window * cfg.crash_period
    wkey = jax.random.fold_in(ckey, window)
    k_sel, k_start, k_dur = jax.random.split(wkey, 3)
    crashed = bern_u32(k_sel, crash_t, (n,))
    start = jax.random.randint(k_start, (n,), 0, cfg.crash_period)
    dur = jax.random.randint(k_dur, (n,), 1, crash_down + 1)
    down = crashed & (off >= start) & (off < start + dur) & (now >= 0)
    return ~down


def alive_at(cfg: RaftConfig, ckey: jax.Array, now: jax.Array) -> jax.Array:
    """[N] bool node liveness at tick `now` -- a pure function of the crash stream, so
    trajectories stay replayable with no RNG or downtime counter in the scan carry.

    Windowed renewal process: node i is down during ticks
    [w*P + start_i, w*P + start_i + dur_i) of window w (clipped at the window edge,
    so a node is never down across a window boundary) iff its per-window Bernoulli
    crash draw fired. `now < 0` reports alive (so tick 0 is never a "restart").
    """
    if cfg.crash_prob <= 0:
        return jnp.ones((cfg.n_nodes,), bool)
    return _alive_at_t(
        cfg, ckey, now, jnp.uint32(p_to_u32(cfg.crash_prob)), cfg.crash_down_ticks
    )


def _partition_cut(
    n: int, k_part: jax.Array, now: jax.Array, period, part_t
) -> jax.Array:
    """[N, N] bool: True on edges CUT by the rolling partition this tick.
    Assignment is stable within each window of `period` ticks because it is
    keyed by the window index, not the tick. `period` may be traced (genome
    path; 0 disables via the `period > 0` gate, the `maximum` only guards the
    division)."""
    window = now // jnp.maximum(period, 1)
    wkey = jax.random.fold_in(k_part, window)
    k_group, k_active = jax.random.split(wkey)
    group = bern_u32(k_group, jnp.uint32(HALF_U32), (n,))
    active = bern_u32(k_active, part_t) & (period > 0)
    same_side = group[:, None] == group[None, :]
    return ~same_side & active


def _skew_draw(n: int, k_skew: jax.Array, skew_t) -> jax.Array:
    """[N] int32 local-clock increments: stall (+0) on the first half of the
    threshold window, jump (+2) on the second, +1 otherwise."""
    r = jax.random.bits(k_skew, (n,), jnp.uint32)
    return jnp.where(r < (skew_t >> 1), 0, jnp.where(r < skew_t, 2, 1)).astype(
        jnp.int32
    )


def _admin_cmds(cfg: RaftConfig, tkey: jax.Array, now: jax.Array,
                rcfg_i, xfer_i, read_i, traced: bool):
    """(reconfig_cmd, transfer_cmd, read_cmd) draws -- the reconfiguration
    plane's admin offers (raft_sim_tpu/reconfig). Each cadence follows the
    client_interval pattern: `*_i` is a Python int on the scalar path
    (statically gated so disabled planes draw nothing) and traced genome data
    on the scenario path (`traced=True`: every command stream is computed
    unconditionally from the SAME dedicated key stream, so a homogeneous
    genome reproduces the scalar path bit-for-bit). Targets rotate randomly
    over nodes -- add/remove-under-fire and transfer-under-fire programs are
    target-diverse by default."""
    n = cfg.n_nodes
    k_rcfg, k_xfer = jax.random.split(jax.random.fold_in(tkey, 5))
    # Disabled planes return a TRACED NIL scalar, not the Python-int NIL the
    # StepInputs defaults use: these leaves flow through vmap (which would
    # broadcast a Python int into a real [B] array anyway -- no saving) and
    # the analyzer's eval_shape pricing (which needs shaped leaves). Cost:
    # the Pass C input-accounting prices 3x int32 = 12 B/cluster-tick on
    # every tier; the VALUES are loop constants XLA folds, and the kernels
    # never read them when the gate is off (the carry stays untouched).
    nil = jnp.int32(NIL)
    if traced or cfg.reconfig:
        on = (rcfg_i > 0) & (now % jnp.maximum(rcfg_i, 1) == 0) & (now > 0)
        tgt = jax.random.randint(k_rcfg, (), 0, n)
        reconfig_cmd = jnp.asarray(jnp.where(on, tgt, NIL), jnp.int32)
    else:
        reconfig_cmd = nil
    if traced or cfg.leader_transfer:
        on = (xfer_i > 0) & (now % jnp.maximum(xfer_i, 1) == 0) & (now > 0)
        tgt = jax.random.randint(k_xfer, (), 0, n)
        transfer_cmd = jnp.asarray(jnp.where(on, tgt, NIL), jnp.int32)
    else:
        transfer_cmd = nil
    if traced or cfg.read_index:
        on = (read_i > 0) & (now % jnp.maximum(read_i, 1) == 0)
        read_cmd = jnp.asarray(jnp.where(on, 1, NIL), jnp.int32)
    else:
        read_cmd = nil
    return reconfig_cmd, transfer_cmd, read_cmd


def _storage_draws(cfg: RaftConfig, tkey: jax.Array, now: jax.Array,
                   fs_i, jit_t, torn_t, span, traced: bool):
    """(fsync_fire, torn_drop) draws -- the durable storage plane's disk-fault
    lattice (raft_sim_tpu/storage). A node's flush completes on the fsync
    cadence tick unless its per-node latency-jitter Bernoulli stalls it (the
    slow-disk model: the due flush waits for the NEXT cadence tick, so the
    durable watermark falls a full interval behind). torn_drop is the
    torn-tail write: the extra entries (1..span, uniform) a restart's tail
    checksum rejects beyond the un-fsynced suffix -- drawn every tick from
    the dedicated stream so the draw sequence is schedule-independent, and
    consumed by the kernels only on restart ticks. `fs_i`/`span` are Python
    ints on the scalar path (statically gated) and traced genome data on the
    scenario path; `jit_t`/`torn_t` are uint32 thresholds either way. The
    fold_in(tkey, 7) stream is disjoint from the client-routing (3) and
    admin-command (5) streams sharing tkey."""
    n = cfg.n_nodes
    if traced or cfg.durable_storage:
        k_jit, k_torn, k_span = jax.random.split(jax.random.fold_in(tkey, 7), 3)
        stall = bern_u32(k_jit, jit_t, (n,))
        fire = (fs_i > 0) & (now % jnp.maximum(fs_i, 1) == 0) & ~stall
        torn = bern_u32(k_torn, torn_t, (n,))
        # Traced span bound is fine (precedent: crash_down in _alive_at_t).
        extra = jax.random.randint(k_span, (n,), 1, span + 1)
        torn_drop = jnp.where(torn, extra, 0).astype(jnp.int32)
        return fire, torn_drop
    # Gate off: real (dead) [N] arrays, not the StepInputs Python-int
    # defaults -- the dtype-comment contract fixes the rank per field, and
    # these leaves flow through vmap/eval_shape like the admin commands
    # above (Pass C prices ~5N B/cluster-tick of dead input on every tier;
    # the kernels never read them when the gate is off).
    return jnp.zeros((n,), bool), jnp.zeros((n,), jnp.int32)


def _client_routing(cfg: RaftConfig, tkey: jax.Array):
    """(client_target, client_bounce) draws -- the redirect-model routing
    randomness (core.clj:154); zeros when the omniscient direct client is
    active. Identical on the scalar and genome paths (routing model is a
    STRUCTURAL config gate; genomes tune only the cadence)."""
    n = cfg.n_nodes
    if cfg.client_redirect:
        k_tgt, k_bnc = jax.random.split(jax.random.fold_in(tkey, 3))
        client_target = jax.random.randint(k_tgt, (), 0, n)
        client_bounce = jax.random.randint(k_bnc, (cfg.client_pipeline,), 0, n)
    else:
        client_target = jnp.int32(0)
        client_bounce = jnp.zeros((cfg.client_pipeline,), jnp.int32)
    return client_target, client_bounce


def genome_at(genome, now: jax.Array, seg_len: int):
    """Resolve a `[S]`-segment genome to the segment active at tick `now`:
    dense-table read `leaves[clip(now // seg_len, 0, S - 1)]` on device (the
    phased-nemesis timeline of scenario/program.py). The final segment holds
    past the program's end; S = 1 short-circuits to a static index so plain
    (unphased) genomes pay no gather."""
    s_count = genome.drop.shape[0]
    if s_count == 1:
        return jax.tree.map(lambda t: t[0], genome)
    # clip, not minimum: `now` is -1 during the phantom pre-window (see
    # _cut_count), and a negative index would silently read the FINAL
    # segment instead of the first one (Pass E range-index-oob).
    seg = jnp.clip(now // seg_len, 0, s_count - 1)
    return jax.tree.map(lambda t: t[seg], genome)


def _cut_count(n: int, k_part: jax.Array, now: jax.Array, period, part_t) -> jax.Array:
    """Scalar int32: edges cut by the rolling partition at tick `now` (0 when
    inactive or before tick 0 -- a phantom 'window -1' layout must not read
    as a partition onset at tick 0)."""
    cut = jnp.sum(_partition_cut(n, k_part, now, period, part_t)).astype(jnp.int32)
    return jnp.where(now >= 0, cut, 0)


def trace_fault_inputs(cfg: RaftConfig, key: jax.Array, now: jax.Array,
                       genome=None, seg_len: int = 1):
    """(crashed [N] bool, cut_now scalar int32, cut_prev scalar int32) -- the
    fault-lattice facts event extraction (trace/events.py) needs that
    StepInputs does not carry: the crash EDGE (down now, up last tick; the
    mirror of `restarted`) and the partition cut-edge counts at `now` and
    `now - 1` (their inequality is the partition-change event). Recomputed
    from the SAME key streams and helpers as make_inputs, so the draws are
    identical (XLA CSEs the shared subexpressions) and the facts can never
    disagree with the inputs the kernel consumed. Genome path mirrors
    make_inputs' segment convention: both liveness reads use the segment
    active at `now` (docs/SCENARIOS.md)."""
    n = cfg.n_nodes
    _, _, k_part = jax.random.split(key, 3)
    if genome is not None:
        g = genome_at(genome, now, seg_len)
        ckey = crash_key(key)
        crashed = _alive_at_t(cfg, ckey, now - 1, g.crash, g.crash_down) & ~_alive_at_t(
            cfg, ckey, now, g.crash, g.crash_down
        )
        cut_now = _cut_count(n, k_part, now, g.part_period, g.part)
        cut_prev = _cut_count(n, k_part, now - 1, g.part_period, g.part)
        return crashed, cut_now, cut_prev
    if cfg.crash_prob > 0:
        ckey = crash_key(key)
        crashed = alive_at(cfg, ckey, now - 1) & ~alive_at(cfg, ckey, now)
    else:
        crashed = jnp.zeros((n,), bool)
    if cfg.partition_period > 0:
        part_t = jnp.uint32(p_to_u32(cfg.partition_prob))
        cut_now = _cut_count(n, k_part, now, cfg.partition_period, part_t)
        cut_prev = _cut_count(n, k_part, now - 1, cfg.partition_period, part_t)
    else:
        cut_now = jnp.int32(0)
        cut_prev = jnp.int32(0)
    return crashed, cut_now, cut_prev


def make_inputs(
    cfg: RaftConfig,
    key: jax.Array,
    now: jax.Array,
    genome=None,
    seg_len: int = 1,
) -> StepInputs:
    """Inputs for one cluster at tick `now`. `key` is the per-cluster base key.

    `genome=None` (the default) is the scalar-config path: fault parameters
    come from cfg, statically gated, exactly one mechanism set per compiled
    program. A `genome` (duck-typed ScenarioGenome, `[S]` per-segment leaves;
    `seg_len` static) switches to the scenario path: every mechanism is traced
    unconditionally from the genome's threshold-encoded parameters, so ONE
    compiled program evaluates a heterogeneous fleet -- per-cluster fault
    settings are data, not compile points. Both paths share the same draw
    helpers and key streams: a homogeneous genome built from cfg's scalars
    (scenario.genome.from_config) reproduces this function's scalar-path
    output bit-for-bit.
    """
    n = cfg.n_nodes
    k_ticks, k_rate, k_part = jax.random.split(key, 3)
    tkey = jax.random.fold_in(k_ticks, now)
    k_drop, k_timeout, k_skew = jax.random.split(tkey, 3)

    # Election-timeout draws (one per node per tick, used on any timer reset).
    timeout_draw = draw_timeouts(cfg, k_timeout, n)
    client_target, client_bounce = _client_routing(cfg, tkey)

    if genome is not None:
        g = genome_at(genome, now, seg_len)
        deliver = ~bern_u32(k_drop, g.drop, (n, n))
        deliver = deliver & ~_partition_cut(n, k_part, now, g.part_period, g.part)
        skew = _skew_draw(n, k_skew, g.skew)
        # Traced cadence: the `maximum` only guards the modulo; interval 0
        # disables via the `> 0` gate (same values as the scalar branch).
        ci = g.client_interval
        client_cmd = jnp.asarray(
            jnp.where((ci > 0) & (now % jnp.maximum(ci, 1) == 0), now + 1, NIL),
            jnp.int32,
        )
        ckey = crash_key(key)
        alive = _alive_at_t(cfg, ckey, now, g.crash, g.crash_down)
        # Restart edge = alive now, down last tick. Both liveness reads use
        # the segment active at `now`: across a segment boundary the edge is
        # evaluated under the NEW segment's crash parameters (deterministic
        # and replayable; documented in docs/SCENARIOS.md).
        restarted = alive & ~_alive_at_t(cfg, ckey, now - 1, g.crash, g.crash_down)
        reconfig_cmd, transfer_cmd, read_cmd = _admin_cmds(
            cfg, tkey, now, g.reconfig_interval, g.transfer_interval,
            g.read_interval, traced=True,
        )
        fsync_fire, torn_drop = _storage_draws(
            cfg, tkey, now, g.fsync_interval, g.fsync_jitter, g.torn,
            g.torn_span, traced=True,
        )
    else:
        # Message drop (the reference's silently-dropped RPC, client.clj:38-40).
        if cfg.drop_prob > 0:
            if cfg.drop_prob_uniform:
                # Per-cluster rate uniform over [0, drop_prob] (BASELINE
                # config 4), drawn directly in threshold space: a uint32
                # threshold uniform over [0, p_to_u32(drop_prob)]. The +1
                # modulus is clamped below 2^32 so p = 1.0 cannot wrap to a
                # zero modulus; the modulo bias is < 2^-31 relative.
                base = min(p_to_u32(cfg.drop_prob), (1 << 32) - 2)
                p_t = jax.random.bits(k_rate, (), jnp.uint32) % jnp.uint32(base + 1)
            else:
                p_t = jnp.uint32(p_to_u32(cfg.drop_prob))
            deliver = ~bern_u32(k_drop, p_t, (n, n))
        else:
            deliver = jnp.ones((n, n), bool)

        # Rolling partitions (window-stable assignment, see _partition_cut).
        if cfg.partition_period > 0:
            deliver = deliver & ~_partition_cut(
                n,
                k_part,
                now,
                cfg.partition_period,
                jnp.uint32(p_to_u32(cfg.partition_prob)),
            )

        # Clock skew.
        if cfg.clock_skew_prob > 0:
            skew = _skew_draw(n, k_skew, jnp.uint32(p_to_u32(cfg.clock_skew_prob)))
        else:
            skew = jnp.ones((n,), jnp.int32)

        # Client commands: value = tick at injection + 1 -- a deterministic,
        # human-readable payload choice, nothing more. Since the v21 decoupling
        # the commit-latency metric reads the offer-tick PLANE the kernels
        # stamp at injection (ClusterState.log_tick), never the value: any
        # int32 payload is legal (serve/ingest.py check_value), and a served
        # offer plane replaying this cadence is bit-exact with it
        # (tests/test_serve.py). Payload bytes carry no protocol meaning in
        # the reference either (log.clj:66-67).
        if cfg.client_interval > 0:
            client_cmd = jnp.where(now % cfg.client_interval == 0, now + 1, NIL)
        else:
            client_cmd = jnp.int32(NIL)
        client_cmd = jnp.asarray(client_cmd, jnp.int32)

        # Crash/restart schedule (restart edge = alive now, down last tick).
        if cfg.crash_prob > 0:
            ckey = crash_key(key)
            alive = alive_at(cfg, ckey, now)
            restarted = alive & ~alive_at(cfg, ckey, now - 1)
        else:
            alive = jnp.ones((n,), bool)
            restarted = jnp.zeros((n,), bool)

        reconfig_cmd, transfer_cmd, read_cmd = _admin_cmds(
            cfg, tkey, now, cfg.reconfig_interval, cfg.transfer_interval,
            cfg.read_interval, traced=False,
        )
        fsync_fire, torn_drop = _storage_draws(
            cfg, tkey, now, cfg.fsync_interval,
            jnp.uint32(p_to_u32(cfg.fsync_jitter_prob)),
            jnp.uint32(p_to_u32(cfg.torn_tail_prob)),
            cfg.lost_suffix_span, traced=False,
        )

    deliver_mask = bitplane.pack(deliver, axis=1)
    if cfg.compact_planes:
        # Compacted layout (ops/tile.py): the word plane ships FLAT so the
        # sublane tile stops padding its tiny word dim ([N, W] -> [N*W]; the
        # kernels reshape back at tick entry). Same words, same bits.
        deliver_mask = deliver_mask.reshape((-1,))
    return StepInputs(
        # Shipped bit-packed over the source axis (StepInputs docstring): the
        # same Bernoulli/partition draws, 32 edges per uint32 word -- the [N, N]
        # bool plane never leaves this function.
        deliver_mask=deliver_mask,
        skew=skew,
        timeout_draw=timeout_draw,
        client_cmd=client_cmd,
        client_target=client_target,
        client_bounce=client_bounce,
        alive=alive,
        restarted=restarted,
        reconfig_cmd=reconfig_cmd,
        transfer_cmd=transfer_cmd,
        read_cmd=read_cmd,
        fsync_fire=fsync_fire,
        torn_drop=torn_drop,
    )
