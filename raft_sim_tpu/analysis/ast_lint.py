"""Pass B: AST rules over `raft_sim_tpu/` enforcing the repo's source idioms,
plus the schema cross-checks that tie `types.py` comments and the checkpoint
version pin to the live structures.

Source rules (pure `ast`, no execution):

  traced-branch    no Python `if`/`while` on traced values in `models/` and
                   `sim/`. The kernels are `jnp.where` lattices by design
                   (models/raft.py docstring); a Python branch on a tracer
                   either crashes under jit or -- worse -- silently bakes one
                   trace-time path. Taint heuristic: parameters annotated with
                   traced types (ClusterState, StepInputs, Mailbox, StepInfo,
                   RunMetrics, FlightRecorder, jax.Array) are traced; taint
                   propagates through assignment, tuple unpacking, attribute
                   and subscript access, and the results of jnp./lax. calls.
                   Branches on static config (`if cfg.pre_vote:`) never taint.
  float-literal    no bare float literal as an argument of a jnp./lax. call in
                   the hot-path packages (`models/`, `sim/`, `ops/`): the
                   protocol path is integer-only, and a stray `1.0` promotes a
                   whole lattice. `jax.random` calls (probabilities) are the
                   documented exception and are not matched.

Contract rules (cheap execution -- eval_shape and one tiny npz round trip):

  dtype-comment            the `# [shape] dtype` field comments in types.py
                           parse (policy.parse_types_comments) and match the
                           ACTUAL dtypes/ndims `init_state`/`make_inputs`/
                           `raft.step` produce, across the policy tiers
                           (int8/int16 index planes, compaction's int32).
  checkpoint-version       the serialized-pytree field sets hash to the pin in
                           `checkpoint._SCHEMA_FINGERPRINT`, and the pin's
                           version equals `_FORMAT_VERSION`: changing
                           ClusterState/Mailbox/RunMetrics fields without
                           bumping the format version is caught here.
  checkpoint-serialization a real save() round trip's npz key set equals the
                           key set derived from the NamedTuple fields (pytree
                           fields vs serialized keys can never drift).
"""

from __future__ import annotations

import ast
import os
import tempfile

import jax
import numpy as np

from raft_sim_tpu.analysis import policy
from raft_sim_tpu.analysis.findings import Finding
from raft_sim_tpu.utils.config import PRESETS, RaftConfig

# Every rule slug this pass can emit (run.run_all scopes stale-waiver
# detection to the passes that actually ran).
RULES = frozenset({
    "traced-branch", "float-literal", "parse-error", "dtype-comment",
    "checkpoint-version", "checkpoint-serialization",
})

# Packages whose functions must not branch on traced values.
TRACED_BRANCH_DIRS = ("models", "sim", "trace")
# Packages where float literals must not enter jnp/lax calls.
FLOAT_LITERAL_DIRS = ("models", "sim", "ops", "trace")

# Parameter annotations that mark a value as traced.
TRACED_ANNOTATIONS = {
    "ClusterState", "StepInputs", "Mailbox", "StepInfo", "RunMetrics",
    "FlightRecorder", "WindowRecord", "Array", "jax.Array",
    "TickEvents", "TraceWin", "TracePersist",
}

# Config tiers the dtype-comment contract is verified against: the int8 index
# tier (config3, CAP 32), the int16 tier (config1, CAP 2048), compaction's
# int32 + redirect pipeline (config6r), and the wide cluster (config5).
COMMENT_CHECK_CONFIGS = ("config3", "config1", "config6r", "config5")


def _ann_name(node) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return f"{_ann_name(node.value)}.{node.attr}"
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.split("[")[0]
    return ""


def _root_name(node) -> str:
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else ""


def _names_in(node):
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _targets(node):
    """Flat Name targets of an assignment target (handles tuple unpacking)."""
    if isinstance(node, ast.Name):
        yield node.id
    elif isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            yield from _targets(elt)
    elif isinstance(node, ast.Starred):
        yield from _targets(node.value)


class _FunctionLint:
    """Taint analysis + branch check for one function body."""

    def __init__(self, fn: ast.FunctionDef, path: str, findings: list[Finding]):
        self.fn = fn
        self.path = path
        self.findings = findings
        self.tainted: set[str] = set()
        args = fn.args
        for a in (*args.posonlyargs, *args.args, *args.kwonlyargs):
            if a.annotation is not None and (
                _ann_name(a.annotation).split(".")[-1] in TRACED_ANNOTATIONS
                or _ann_name(a.annotation) in TRACED_ANNOTATIONS
            ):
                self.tainted.add(a.arg)

    def _expr_tainted(self, node) -> bool:
        """An expression is traced if it references a tainted name or calls
        into jnp/lax (whose results are arrays by construction)."""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and sub.id in self.tainted:
                return True
            if isinstance(sub, ast.Call) and _root_name(sub.func) in ("jnp", "lax"):
                return True
        return False

    def run(self):
        # Two propagation sweeps handle the (rare) use-before-later-taint
        # ordering inside straight-line kernel code.
        for _ in range(2):
            for node in ast.walk(self.fn):
                if isinstance(node, ast.Assign) and self._expr_tainted(node.value):
                    for tgt in node.targets:
                        self.tainted.update(_targets(tgt))
                elif isinstance(node, ast.AugAssign) and self._expr_tainted(node.value):
                    self.tainted.update(_targets(node.target))
        for node in ast.walk(self.fn):
            if isinstance(node, (ast.If, ast.While)) and self._expr_tainted(node.test):
                kind = "if" if isinstance(node, ast.If) else "while"
                names = sorted(_names_in(node.test) & self.tainted) or ["<jnp call>"]
                self.findings.append(Finding(
                    rule="traced-branch",
                    path=self.path,
                    line=node.lineno,
                    message=(
                        f"Python `{kind}` on traced value(s) {names} in "
                        f"{self.fn.name}(): kernels must use jnp.where/"
                        "lax.cond lattices, never Python control flow on "
                        "array values (models/raft.py docstring)"
                    ),
                ))


def _lint_traced_branches(tree: ast.AST, path: str, findings: list[Finding]):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _FunctionLint(node, path, findings).run()


def _lint_float_literals(tree: ast.AST, path: str, findings: list[Finding]):
    def scan_args(node, call_line):
        """Float constants in a call's argument subtree, not descending into
        nested calls rooted elsewhere (jax.random probabilities are legal)."""
        if isinstance(node, ast.Call) and _root_name(node.func) not in ("jnp", "lax"):
            return
        if isinstance(node, ast.Constant) and isinstance(node.value, float):
            findings.append(Finding(
                rule="float-literal",
                path=path,
                line=getattr(node, "lineno", call_line),
                message=(
                    f"bare float literal {node.value!r} entering a jnp/lax "
                    "call in a hot-path module: the protocol path is "
                    "integer-only (types.py); name the constant and cast "
                    "explicitly if a float is genuinely intended"
                ),
            ))
            return
        for child in ast.iter_child_nodes(node):
            scan_args(child, call_line)

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _root_name(node.func) in ("jnp", "lax"):
            for arg in (*node.args, *(kw.value for kw in node.keywords)):
                scan_args(arg, node.lineno)


def lint_source(source: str, path: str) -> list[Finding]:
    """Both source rules over one file's text. `path` decides which rules
    apply (TRACED_BRANCH_DIRS / FLOAT_LITERAL_DIRS membership) and anchors
    the findings."""
    try:
        tree = ast.parse(source)
    except SyntaxError as ex:
        return [Finding(rule="parse-error", path=path, line=ex.lineno or 0,
                        message=f"does not parse: {ex.msg}")]
    parts = path.replace("\\", "/").split("/")
    findings: list[Finding] = []
    if any(d in parts for d in TRACED_BRANCH_DIRS):
        _lint_traced_branches(tree, path, findings)
    if any(d in parts for d in FLOAT_LITERAL_DIRS):
        _lint_float_literals(tree, path, findings)
    return findings


def lint_tree(root: str) -> list[Finding]:
    """Run the source rules over every .py file under `root` (the
    raft_sim_tpu package dir), paths reported repo-relative."""
    findings: list[Finding] = []
    repo = os.path.dirname(os.path.abspath(root.rstrip("/")))
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if not d.startswith("__pycache__"))
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, repo)
            with open(full) as f:
                findings.extend(lint_source(f.read(), rel))
    return findings


# ------------------------------------------------------------ contract rules


def check_dtype_comments() -> list[Finding]:
    """Rule dtype-comment: the parsed types.py field contracts hold against
    the actual structures for every policy tier in COMMENT_CHECK_CONFIGS."""
    specs, problems = policy.parse_types_comments()
    findings = [
        Finding(rule="dtype-comment", path="raft_sim_tpu/types.py", line=ln,
                message=msg)
        for ln, msg in problems
    ]
    for name in COMMENT_CHECK_CONFIGS:
        cfg, _ = PRESETS[name]
        state, inputs, info = policy.state_avals(cfg)
        actual = {
            "ClusterState": {f: getattr(state, f) for f in state._fields if f != "mailbox"},
            "Mailbox": {f: getattr(state.mailbox, f) for f in state.mailbox._fields},
            "StepInputs": {f: getattr(inputs, f) for f in inputs._fields},
            "StepInfo": {f: getattr(info, f) for f in info._fields},
        }
        for cls, fields in actual.items():
            for fname, aval in fields.items():
                spec = specs.get(cls, {}).get(fname)
                if spec is None:
                    findings.append(Finding(
                        rule="dtype-comment",
                        path="raft_sim_tpu/types.py",
                        message=(
                            f"{cls}.{fname} has no parseable `# [shape] dtype` "
                            "comment: the dtype contract must stay "
                            "machine-readable (analysis/policy.py)"
                        ),
                    ))
                    continue
                allowed = policy.resolve_dtypes(spec, cfg)
                if aval.dtype not in allowed:
                    findings.append(Finding(
                        rule="dtype-comment",
                        path="raft_sim_tpu/types.py",
                        line=spec.line,
                        message=(
                            f"{cls}.{fname} is {aval.dtype} under {name} but "
                            f"the comment declares {'/'.join(spec.dtypes)}"
                        ),
                    ))
                if spec.ndim is not None and len(aval.shape) != spec.ndim:
                    findings.append(Finding(
                        rule="dtype-comment",
                        path="raft_sim_tpu/types.py",
                        line=spec.line,
                        message=(
                            f"{cls}.{fname} has ndim {len(aval.shape)} under "
                            f"{name} but the comment declares ndim {spec.ndim}"
                        ),
                    ))
    return _dedupe(findings)


def _dedupe(findings: list[Finding]) -> list[Finding]:
    seen, out = set(), []
    for f in findings:
        key = (f.rule, f.path, f.line, f.message)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out


def check_checkpoint_version() -> list[Finding]:
    """Rule checkpoint-version: the field-set fingerprint matches the pin and
    the pin names the current format version."""
    from raft_sim_tpu.utils import checkpoint

    path = "raft_sim_tpu/utils/checkpoint.py"
    out = []
    pin_version, pin_hash = checkpoint._SCHEMA_FINGERPRINT
    actual = policy.schema_fingerprint()
    if actual != pin_hash:
        out.append(Finding(
            rule="checkpoint-version",
            path=path,
            message=(
                f"serialized field sets hash to {actual} but "
                f"_SCHEMA_FINGERPRINT pins {pin_hash}: a ClusterState/Mailbox/"
                "RunMetrics field changed -- bump _FORMAT_VERSION (append a "
                "version-log line) and refresh the pin"
            ),
        ))
    if pin_version != checkpoint._FORMAT_VERSION:
        out.append(Finding(
            rule="checkpoint-version",
            path=path,
            message=(
                f"_SCHEMA_FINGERPRINT pins version {pin_version} but "
                f"_FORMAT_VERSION is {checkpoint._FORMAT_VERSION}: refresh "
                "the pin alongside the version bump"
            ),
        ))
    return out


def check_checkpoint_serialization() -> list[Finding]:
    """Rule checkpoint-serialization: one tiny real save()'s npz key set
    equals the key set derived from the NamedTuple fields, and load() round
    trips it."""
    from raft_sim_tpu.sim.scan import init_metrics_batch
    from raft_sim_tpu.types import init_batch
    from raft_sim_tpu.utils import checkpoint

    path = "raft_sim_tpu/utils/checkpoint.py"
    cfg = RaftConfig(n_nodes=2, log_capacity=4, max_entries_per_rpc=1)
    key = jax.random.key(0)
    state = init_batch(cfg, key, 1)
    keys = jax.random.split(key, 1)
    metrics = init_metrics_batch(1)
    out = []
    with tempfile.TemporaryDirectory() as td:
        fp = checkpoint.save(os.path.join(td, "ck"), cfg, state, keys, metrics)
        with np.load(fp) as z:
            actual = set(z.files)
        expected = policy.expected_checkpoint_keys()
        for missing in sorted(expected - actual):
            out.append(Finding(
                rule="checkpoint-serialization", path=path,
                message=f"save() omitted expected npz key {missing!r} "
                        "(pytree fields vs serialized keys must match)",
            ))
        for extra in sorted(actual - expected):
            out.append(Finding(
                rule="checkpoint-serialization", path=path,
                message=f"save() wrote unexpected npz key {extra!r} "
                        "(pytree fields vs serialized keys must match)",
            ))
        try:
            checkpoint.load(fp)
        except Exception as ex:  # any load failure is the finding itself
            out.append(Finding(
                rule="checkpoint-serialization", path=path,
                message=f"load() cannot read back save()'s output: {ex}",
            ))
    return out


def run_pass(package_root: str) -> list[Finding]:
    """The full AST + contract pass."""
    out = lint_tree(package_root)
    out.extend(check_dtype_comments())
    out.extend(check_checkpoint_version())
    out.extend(check_checkpoint_serialization())
    return out
