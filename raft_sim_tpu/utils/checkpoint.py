"""Simulator checkpoint/resume.

The reference's only persistence is an append-only file of committed values that is
never read back (log.clj:16-18, 74-75 -- no real resume, SURVEY.md section 5). Here the
checkpoint is the full simulator state: every ClusterState array plus the per-cluster
PRNG keys and the config, so a long fuzz run resumes bit-exactly (inputs are pure
functions of (key, state.now), faults.py, so no RNG stream state needs saving beyond
the keys themselves).

The accumulated RunMetrics ride along too: `last_leaderless_tick`/`first_leader_tick`
record *absolute* tick numbers (state.now), so metric accumulation only stays coherent
across a resume if the pre-checkpoint metrics are restored with the state.

Format: a single .npz with the config as a JSON string; loads with numpy only.
"""

from __future__ import annotations

import dataclasses
import json

import jax
import numpy as np

from raft_sim_tpu.sim.scan import RunMetrics
from raft_sim_tpu.types import ClusterState, Mailbox
from raft_sim_tpu.utils.config import RaftConfig

# v2: added the session seed to the archive.
# v3: RunMetrics gained total_cmds.
# v4: Mailbox entry payload became the per-sender shared window (ent_start/term/val).
# v5: req_* fields reoriented [sender, receiver], resp_* [receiver, responder].
# v6: ClusterState gained last_ack (shared-window responsiveness stamps).
# v7: mailbox wire format v7 -- per-sender request headers (req_type/term/commit,
#     RV last_index/last_term, AE window start/prev-term/count) + per-edge window
#     offsets (req_off) and packed response words (resp_word, per-responder term).
# v8: narrow dtypes (next/match int16, req_off int8, resp_word int16) and last_ack
#     replaced by the saturating int16 ack_age.
# v9: ClusterState gained commit_chk (committed-prefix checksum).
# v10: ring-log compaction -- ClusterState gained log_base/base_term/base_chk,
#      Mailbox gained the snapshot header (req_base/req_base_term/req_base_chk);
#      compaction configs widen next/match and resp_word to int32.
# v11: client write path -- ClusterState gained client_pend/client_dst (redirect
#      routing state), RunMetrics gained lat_sum/lat_cnt (commit latency).
# v12: mailbox wire format v9 -- the packed per-edge response word became an int8
#      resp_kind plane + per-responder payloads (v_to/a_ok_to/a_match/a_hint),
#      removing the packed word's 2^28 committed-entry bound.
# v13: int8 index planes (next/match and the match/hint wire fields) for
#      non-compaction configs with log_capacity <= 41.
# v14: metrics v2 -- ClusterState gained lat_frontier (monotone latency dedup
#      frontier); RunMetrics gained lat_hist (per-entry log2-bin latency
#      histogram), noop_blocked, and lm_skipped_pairs.
# v15: K-deep client pipeline -- client_pend/client_dst became [K] vectors
#      (cfg.client_pipeline slots).
# v16: PreVote (cfg.pre_vote) -- ClusterState gained heard_clock (last leader
#      contact, driving the thesis-9.6 pre-vote denial rule).
# v17: int8 ack-age plane (saturation at the narrow ceiling whenever the
#      responsiveness horizon fits under it).
# v18: bit-packed boolean planes (ops/bitplane.py) -- ClusterState.votes became
#      [N, W = ceil(N/32)] uint32 words; Mailbox gained pv_grant (packed
#      pre-vote grant bits, formerly bit 2 of resp_kind, which is now a pure
#      RESP_* 0..3 plane).
# v19: metrics v3 -- RunMetrics gained lat_excluded (the latency coverage-gap
#      counter: client entries first committed in leaderless windows, measured
#      instead of documented-away). ClusterState is unchanged.
# v20: scenario engine -- checkpoints gained the scenario_json key recording
#      the active nemesis program (scenario/program.py schema; '{}' for plain
#      runs). A scenario run's trajectory is a function of (config, genome,
#      seed), so resuming one WITHOUT its scenario would silently continue a
#      different experiment: plain resume rejects scenario checkpoints
#      (driver `scenario run --resume` restores the genome path). Metrics v4:
#      RunMetrics gained multi_leader (split-brain exposure ticks -- the
#      search's election-safety precursor signal). ClusterState/Mailbox are
#      unchanged.
# v21: payload/latency decoupling (the serve subsystem's enabler) --
#      ClusterState gained log_tick (the [N, CAP] offer-stamp plane the
#      commit-latency metric now reads, freeing log_val for arbitrary client
#      payloads) and client_tick (offer stamps riding the redirect pipeline
#      slots); Mailbox gained ent_tick (the shared-window stamp plane, so
#      replication carries the stamps). All three are zeros and loop-invariant
#      unless cfg.track_offer_ticks (client_interval > 0 or the new
#      RaftConfig.serve_ingest gate).
# v22: reconfiguration plane (raft_sim_tpu/reconfig) -- ClusterState gained
#      the joint-consensus membership plane (member_old/member_new packed
#      voting bitmaps, cfg_epoch, cfg_pend), the TimeoutNow transfer target
#      (xfer_to), and the ReadIndex read slot (read_idx/read_tick/read_acks);
#      Mailbox gained xfer_tgt (the TimeoutNow broadcast header). RunMetrics
#      gained the read traffic counters (reads_served/read_lat_sum/read_hist,
#      telemetry schema v3). All new leaves are zeros/NIL and loop-invariant
#      unless their structural gate (reconfig_interval / transfer_interval /
#      read_interval > 0) is on.
# v23: lease-based reads (thesis 6.4.1; the tenancy plane's read tier) --
#      ClusterState gained read_fr (the committed frontier banked at a
#      pending read's capture, the staleness anchor the viol_read_stale
#      device invariant compares served reads against). Zeros and
#      loop-invariant unless cfg.read_lease (read_lease_ticks > 0). Mailbox
#      and RunMetrics are unchanged (the staleness flag folds into the
#      existing violations counter).
# v24: log-carried configuration (ISSUE 13; models/cfglog.py) -- the admin
#      membership plane became PER-NODE derived state: member_old/member_new
#      are [N, W] rows (one per node, each derived from that node's own log
#      prefix), cfg_epoch/cfg_pend are [N] vectors; ClusterState gained the
#      log_cfg config-entry plane ([N, CAP] int32 commands beside the log)
#      and the snapshot config context (base_mold/base_pend/base_epoch).
#      Mailbox gained req_disrupt (the disruptive-RequestVote transfer
#      override flag), ent_cfg (the shared-window config-command plane), and
#      the snapshot config header (req_base_mold/req_base_pend/
#      req_base_epoch). All new leaves are zeros and loop-invariant unless
#      cfg.reconfig (and the snapshot legs additionally need
#      cfg.compaction). RunMetrics unchanged.
# v25: durable storage plane (ISSUE 19; raft_sim_tpu/storage) --
#      ClusterState gained the durable watermark triple: dur_len ([N] int32
#      entries the disk confirmed), dur_term/dur_vote ([N] int32 durable
#      term/votedFor snapshots; boot values 0/1/NIL match init_state's live
#      triple so a cold cluster is born consistent). RunMetrics gained the
#      fsync lag accumulators (fsync_lag_sum/fsync_lag_max, telemetry
#      schema v4). All new leaves are loop-invariant unless
#      cfg.durable_storage (fsync_interval > 0). Mailbox unchanged.
_FORMAT_VERSION = 25

# The single exported source of truth for the on-disk format version
# (re-exported as raft_sim_tpu.CHECKPOINT_FORMAT_VERSION). Everything that
# writes or gates on checkpoint compatibility must read THIS, not a copy.
FORMAT_VERSION = _FORMAT_VERSION

# Fingerprint of the serialized pytree schema: (version, sha256 of the ordered
# field names + leaf ranks/dtypes of ClusterState / Mailbox / RunMetrics under
# the analyzer's pinned canonical config). The static analyzer
# (raft_sim_tpu/analysis, rule `checkpoint-version`) recomputes the hash from
# the live NamedTuples and fails when the field set changed without BOTH
# bumping _FORMAT_VERSION (append a line to the version log above) and
# refreshing this pin -- the convention the v2..v19 log always relied on,
# now machine-checked. Refresh with:
#     python -c "from raft_sim_tpu.analysis import policy; print(policy.schema_fingerprint())"
_SCHEMA_FINGERPRINT = (25, "541dcec1cfa9709e")


def _normalize(path: str) -> str:
    """np.savez appends '.npz' to bare paths; normalize so save and load agree."""
    return path if path.endswith(".npz") else path + ".npz"


def save(
    path: str,
    cfg: RaftConfig,
    state: ClusterState,
    keys: jax.Array,
    metrics: RunMetrics,
    seed: int = 0,
    scenario: dict | None = None,
) -> str:
    """Write (config, batched state, per-cluster run keys, accumulated metrics, seed).
    Returns the actual path written (always .npz-suffixed). `scenario` is the
    declarative nemesis program driving the run (scenario/program.py to_dict
    schema) -- part of the trajectory's identity, so it rides the checkpoint;
    None marks a plain scalar-config run."""
    path = _normalize(path)
    arrays = {f"state_{f}": np.asarray(v) for f, v in zip(state._fields, state) if f != "mailbox"}
    arrays |= {f"mb_{f}": np.asarray(v) for f, v in zip(state.mailbox._fields, state.mailbox)}
    arrays |= {f"metrics_{f}": np.asarray(v) for f, v in zip(metrics._fields, metrics)}
    arrays["keys"] = np.asarray(jax.random.key_data(keys))
    np.savez_compressed(
        path,
        __version__=np.int32(_FORMAT_VERSION),
        seed=np.int64(seed),
        config_json=np.bytes_(json.dumps(dataclasses.asdict(cfg)).encode()),
        scenario_json=np.bytes_(json.dumps(scenario or {}).encode()),
        **arrays,
    )
    return path


def load(
    path: str,
) -> tuple[RaftConfig, ClusterState, jax.Array, RunMetrics, int, dict | None]:
    """Read a checkpoint; returns (cfg, state, keys, metrics, seed, scenario)
    ready to resume. `scenario` is None for plain runs, else the program dict
    `save` recorded -- the caller must resume through the scenario path."""
    with np.load(_normalize(path)) as z:
        version = int(z["__version__"])
        if version != _FORMAT_VERSION:
            direction = "older" if version < _FORMAT_VERSION else "newer"
            raise ValueError(
                f"checkpoint was written as format v{version}, but this build "
                f"reads v{_FORMAT_VERSION} (the file is {direction} than the "
                f"code). Checkpoints do not auto-migrate: the version log in "
                f"raft_sim_tpu/utils/checkpoint.py names the field change(s) "
                f"between v{min(version, _FORMAT_VERSION)} and "
                f"v{max(version, _FORMAT_VERSION)}; either re-generate the "
                f"checkpoint from its original (seed, config) with this build, "
                f"or load it with the release that wrote v{version}."
            )
        cfg = RaftConfig(**json.loads(bytes(z["config_json"]).decode()))
        mb = Mailbox(**{f: jax.numpy.asarray(z[f"mb_{f}"]) for f in Mailbox._fields})
        fields = {
            f: jax.numpy.asarray(z[f"state_{f}"])
            for f in ClusterState._fields
            if f != "mailbox"
        }
        state = ClusterState(mailbox=mb, **fields)
        keys = jax.random.wrap_key_data(jax.numpy.asarray(z["keys"]))
        metrics = RunMetrics(
            **{f: jax.numpy.asarray(z[f"metrics_{f}"]) for f in RunMetrics._fields}
        )
        seed = int(z["seed"])
        scenario = json.loads(bytes(z["scenario_json"]).decode()) or None
    return cfg, state, keys, metrics, seed, scenario
