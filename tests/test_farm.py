"""Fuzzing-farm tier: portfolio hunts, coverage-guided mutation, and the
auto-corpus policy (raft_sim_tpu/farm).

Compile budget: the flat-cache and negative-result tests share ONE
trace-variant windowed program (same config/shapes/depth -- the whole point
of the flat-cache pin); the dedup and fresh-freeze hunts each pay their own
kernel's program plus the small single-cluster shrink/replay/checker
programs; the A/B test compiles one config8-flavored trace program and runs
four searches through it. Everything else is host-side numpy.
"""

from __future__ import annotations

import importlib.util
import json
import os
import shutil

import numpy as np
import pytest

from raft_sim_tpu import RaftConfig
from raft_sim_tpu.farm import (
    FarmSpec,
    corpus as corpus_mod,
    parse_portfolio,
    run_farm,
    validate_farm_dir,
)
from raft_sim_tpu.farm import portfolio as portfolio_mod
from raft_sim_tpu.scenario import search as search_mod
from raft_sim_tpu.scenario.mutation import mutant_config
from raft_sim_tpu.sim import telemetry

# The scenario tier's kitchen-sink config (every fault mechanism live) at its
# shapes; the farm runs its TRACE VARIANT (guided mutation needs the bitmap).
CFG = RaftConfig(
    n_nodes=5,
    log_capacity=8,
    client_interval=4,
    drop_prob=0.2,
    partition_period=16,
    partition_prob=0.3,
    crash_prob=0.3,
    crash_period=32,
    crash_down_ticks=8,
    clock_skew_prob=0.1,
)
POP, TICKS, WINDOW, DEPTH = 16, 128, 32, 16


def _spec(portfolio, gens=2, **kw):
    kw.setdefault("population", POP)
    kw.setdefault("ticks", TICKS)
    kw.setdefault("window", WINDOW)
    kw.setdefault("trace_depth", DEPTH)
    kw.setdefault("seed", 0)
    return FarmSpec(portfolio=portfolio, budget_gens=gens, **kw)


CORPUS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "corpus")


def _load_repro_tool():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "repro_farm", os.path.join(repo, "tools", "repro.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------- one compiled program


def test_jit_cache_flat_across_portfolio_sizes():
    """The acceptance pin: ONE farm generation evaluates the WHOLE portfolio
    from one compiled program, and the jit cache stays flat across 1/2/4-
    member portfolios (the batch axis is the portfolio axis, tenancy-style:
    the compiled program never sees the partition)."""
    size0 = telemetry.simulate_windowed._cache_size()
    res1 = run_farm(CFG, _spec(("coverage",), stop_on="budget"))
    size1 = telemetry.simulate_windowed._cache_size()
    assert size1 - size0 <= 1, "a farm generation must cost ONE program"
    res2 = run_farm(CFG, _spec(("scalar", "coverage"), stop_on="budget"))
    res4 = run_farm(
        CFG,
        _spec(("scalar", "coverage", "multi_leader", "commit_stall"),
              stop_on="budget"),
    )
    assert telemetry.simulate_windowed._cache_size() == size1, (
        "portfolio size forked a compile: the partition must be host-only"
    )
    # Members really are partitioned: contiguous, disjoint, covering.
    for res, n in ((res1, 1), (res2, 2), (res4, 4)):
        ms = res.manifest["members"]
        assert len(ms) == n
        assert ms[0]["lo"] == 0 and ms[-1]["hi"] == POP
        for a, b in zip(ms, ms[1:]):
            assert a["hi"] == b["lo"]
    # Real kernel, full budget: a pinned NEGATIVE result with coverage data.
    assert res4.negative and res4.manifest["cov_bits_total"] > 0
    assert res4.manifest["generations_run"] == 2


def test_farm_negative_result_artifact(tmp_path):
    """A hitless budget ends in negative.json -- coverage numbers pinned,
    manifest flagged, directory schema-valid (same program as above)."""
    out = str(tmp_path / "farm")
    res = run_farm(
        CFG, _spec(("scalar", "coverage"), stop_on="budget"), out_dir=out
    )
    assert res.negative
    assert validate_farm_dir(out) == []
    neg = json.load(open(os.path.join(out, "negative.json")))
    assert neg["schema"] == "farm-negative-v1"
    assert neg["cov_bits_total"] > 0 and len(neg["cov_bits_by_gen"]) == 2
    assert neg["evaluations"] == 2 * POP
    assert neg["manifest_hash"] == res.manifest["manifest_hash"]
    man = json.load(open(os.path.join(out, "farm_manifest.json")))
    assert man["negative"] is True and man["hits"] == []
    # hunt.jsonl: one row per generation per member, contiguous gens.
    for m in man["members"]:
        rows = [
            json.loads(l) for l in open(
                os.path.join(out, "members", m["name"], "hunt.jsonl")
            )
        ]
        assert [r["gen"] for r in rows] == [0, 1]
        assert all(r["cov_total_bits"] > 0 for r in rows)
    # perf.jsonl: one PR 8 timer row per generation.
    perf = [json.loads(l) for l in open(os.path.join(out, "perf.jsonl"))]
    assert len(perf) == 2 and all(r["ticks"] == TICKS for r in perf)


# ---------------------------------------------- auto-corpus policy


_BLIND_BASE = RaftConfig(n_nodes=5, log_capacity=16, client_interval=2,
                         transfer_interval=9)


@pytest.mark.slow  # budget re-tier (ISSUE 13): CI's farm smoke runs this
# exact refind -> shrink -> dedup-reject flow against the live corpus on
# every push (plus the log-carried smoke's act-on-commit variant), and the
# freeze/provenance test below keeps the corpus WRITE path in tier 1 --
# the in-suite refind duplicate joins the slow tier.
def test_farm_refinds_known_hit_and_refuses_duplicate(tmp_path):
    """The acceptance pin: pointed at the blind-transfer mutant with the
    corpus pre-seeded, the farm re-finds the hit, shrinks it, and REFUSES
    to freeze a duplicate -- the (kernel, kinds, mechanism-set) signature
    matches the seeded artifact (same farm parameters as the fresh-axis
    test below, so the two share every compiled program)."""
    corpus = str(tmp_path / "corpus")
    shutil.copytree(CORPUS_DIR, corpus)
    res = run_farm(
        mutant_config("blind-transfer", _BLIND_BASE),
        _spec(("scalar", "coverage"), gens=4, ticks=192),
        mutant="blind-transfer", corpus_dir=corpus, freeze=True,
    )
    assert res.hits, "the farm must re-find the known blind-transfer hit"
    assert res.frozen == [], "a re-found known bug must NOT grow the corpus"
    assert res.dedup_rejected
    assert res.dedup_rejected[0]["duplicate_of"] == "blind-transfer-n5.json"
    assert sorted(os.listdir(corpus)) == sorted(os.listdir(CORPUS_DIR))
    assert res.manifest["negative"] is False


def test_farm_freezes_fresh_hit_provenance_stamped(tmp_path):
    """The other acceptance half: pointed at a FRESH mutant axis (blind-
    transfer; corpus without it -- cross-kernel signature non-collision is
    pinned host-side in test_signature_and_dedup_rules), the farm freezes a
    checker-rejected, provenance-stamped artifact that tools/repro.py
    --corpus then replays bit-exactly (the replay program is the shrink's
    own cached confirmation program -- same shapes)."""
    corpus = str(tmp_path / "corpus")
    os.makedirs(corpus)
    res = run_farm(
        mutant_config("blind-transfer", _BLIND_BASE),
        _spec(("scalar", "coverage"), gens=4, ticks=192),
        mutant="blind-transfer", corpus_dir=corpus, freeze=True,
    )
    assert len(res.frozen) == 1, res.manifest
    art = json.load(open(res.frozen[0]))
    assert art["schema"] == "scenario-repro-v2"
    assert corpus_mod.validate_artifact(art) == []
    prov = art["provenance"]
    assert prov["mutant"] == "blind-transfer"
    assert prov["fitness"] in ("scalar", "coverage")
    assert isinstance(prov["generation"], int)
    assert prov["farm"] == res.manifest["manifest_hash"]
    assert prov["checker_property"] in (
        "leader_completeness", "state_machine_safety", "leader_append_only",
    )
    assert prov["ablated"] == art["removed"]
    # The grown corpus replays bit-exactly, one command, in-process.
    repro = _load_repro_tool()
    assert repro.main(["--corpus", corpus]) == 0


def test_signature_and_dedup_rules():
    """Host-side: the dedup identity is (kernel, kinds, mechanism-set);
    mechanism sets nested either way are duplicates, disjoint sets are not."""
    art = {
        "mutant": "weak-quorum",
        "kinds": ["viol_election_safety"],
        "genome_raw": {
            "drop": [7], "part_period": [0], "part": [0], "crash": [0],
            "crash_down": [1], "skew": [0], "client_interval": [4],
            "reconfig_interval": [0], "transfer_interval": [0],
            "read_interval": [0],
        },
    }
    kernel, kinds, mech = corpus_mod.signature(art)
    assert kernel == "weak-quorum" and kinds == ("viol_election_safety",)
    assert mech == frozenset({"message drop", "client traffic"})
    # A halved-to-zero partition threshold with a standing period is NOT a
    # partition mechanism (both gating fields must be nonzero): a phantom
    # label here would mis-split dedup signatures.
    phantom = dict(art, genome_raw=dict(art["genome_raw"], part_period=[16]))
    assert "partitions" not in corpus_mod.mechanisms(phantom)
    # A real-kernel artifact gets the 'real' kernel label.
    assert corpus_mod.signature({**art, "mutant": None})[0] == "real"
    # Dedup against the on-disk corpus: the seeded artifact's mechanisms are
    # {client traffic, message drop, partitions}; a drop-only repro is a
    # SUBSET -> duplicate; adding a disjoint mechanism axis (skew, no drop/
    # partitions) -> not a duplicate.
    dup = corpus_mod.find_duplicate(art, CORPUS_DIR)
    assert dup is not None and dup["duplicate_of"] == "weak-quorum-n5.json"
    fresh = dict(art, genome_raw=dict(
        art["genome_raw"], drop=[0], skew=[9], crash=[5]
    ))
    assert corpus_mod.find_duplicate(fresh, CORPUS_DIR) is None
    # Different kinds never collide.
    other = dict(art, kinds=["viol_commit"])
    assert corpus_mod.find_duplicate(other, CORPUS_DIR) is None


# ---------------------------------------------- coverage fitness edges


def test_coverage_fitness_all_bits_seen_keeps_violation_term():
    """An all-bits-already-seen generation (novelty 0 fleet-wide) must not
    zero out the violation term: violations stay lexicographically dominant
    in every regime of the coverage landscape."""
    from raft_sim_tpu.trace.ring import COV_WORDS

    cov = np.full((COV_WORDS, 3), 0xFFFFFFFF, np.uint32)
    seen = np.full(COV_WORDS, 0xFFFFFFFF, np.uint32)
    viol = np.array([0, 2, 0])
    fit, seen2 = search_mod.coverage_fitness(cov, seen, viol)
    assert fit[1] == search_mod.W_VIOLATION * 2 and fit[0] == fit[2] == 0.0
    np.testing.assert_array_equal(seen2, seen)  # already saturated


def test_seen_set_monotone_and_member_order_free():
    """The farm-wide seen set only grows, and member scoring against the
    pre-generation baseline is member-order-free (every member scores
    before the union lands)."""
    from raft_sim_tpu.trace.ring import COV_WORDS

    rng = np.random.default_rng(0)
    seen = np.zeros(COV_WORDS, np.uint32)
    history = []
    for _ in range(4):
        cov = rng.integers(0, 2**32, size=(COV_WORDS, 8), dtype=np.uint32)
        # Two slices scored in both orders against the same baseline:
        n_a = search_mod.coverage_novelty(cov[:, :4], seen)
        n_b = search_mod.coverage_novelty(cov[:, 4:], seen)
        n_b2 = search_mod.coverage_novelty(cov[:, 4:], seen)
        n_a2 = search_mod.coverage_novelty(cov[:, :4], seen)
        np.testing.assert_array_equal(n_a, n_a2)
        np.testing.assert_array_equal(n_b, n_b2)
        seen = search_mod.seen_union(cov, seen)
        history.append(int(search_mod._popcount_words(seen[:, None])[0]))
    assert history == sorted(history), "seen-set popcount must be monotone"
    # Re-scoring any earlier bitmap after the union yields zero novelty.
    assert int(search_mod.coverage_novelty(cov, seen).sum()) == 0


def test_coverage_bitmap_word_boundary():
    """The last valid coverage bit (COV_BITS - 1, inside a partial trailing
    word) counts exactly once and unions cleanly -- no off-by-one at the
    word boundary, no phantom tail bits."""
    from raft_sim_tpu.trace.ring import COV_BITS, COV_WORDS

    assert COV_WORDS * 32 >= COV_BITS > (COV_WORDS - 1) * 32
    cov = np.zeros((COV_WORDS, 2), np.uint32)
    w, b = divmod(COV_BITS - 1, 32)
    cov[w, 0] = np.uint32(1 << b)
    seen = np.zeros(COV_WORDS, np.uint32)
    nov = search_mod.coverage_novelty(cov, seen)
    assert nov.tolist() == [1, 0]
    seen = search_mod.seen_union(cov, seen)
    assert int(search_mod._popcount_words(seen[:, None])[0]) == 1
    assert int(search_mod.coverage_novelty(cov, seen).sum()) == 0


# ---------------------------------------------- coverage-guided mutation


_CFG8 = RaftConfig(n_nodes=5, log_capacity=16, client_interval=2,
                   transfer_interval=9, reconfig_interval=31,
                   read_interval=5)


def _ab_bits(seed: int) -> dict:
    """Final bits-lit for gaussian vs coverage-guided at one seed (both
    hunts share ONE compiled trace-variant program)."""
    finals = {}
    for proposal in ("gaussian", "coverage-guided"):
        spec = search_mod.SearchSpec(
            generations=6, population=POP, ticks=TICKS, window=WINDOW,
            seed=seed, fitness="coverage", trace_depth=DEPTH,
            proposal=proposal,
        )
        res = search_mod.search(_CFG8, spec)
        finals[proposal] = res.generations[-1]["cov_total_bits"]
    return finals


def test_guided_mutation_beats_coverage_as_fitness():
    """The acceptance A/B: coverage-guided MUTATION (small perturbations of
    novelty-lit parents) beats coverage-AS-FITNESS alone on bits lit, in a
    deterministic seeded hunt pair over the reconfig x transfer x read
    interaction space (where unseen transitions are rare enough that a
    frontier parent is worth exploiting). Tier-1 pins seed 0 (227 vs 220
    bits); the seed-2 sibling below rides the slow tier (budget). The
    winning seeds were RE-PROBED for ISSUE 13: the log-carried config plane
    replaced EV_EPOCH with per-node cfg_append/apply/rollback kinds, which
    reshaped the transition-coverage space (pre-v24 pins: seeds 1/2)."""
    finals = _ab_bits(0)
    assert finals["coverage-guided"] > finals["gaussian"], finals


@pytest.mark.slow  # the second A/B seed: one seed could be luck (223 vs 219)
def test_guided_mutation_beats_coverage_as_fitness_second_seed():
    finals = _ab_bits(2)
    assert finals["coverage-guided"] > finals["gaussian"], finals


def test_guided_proposals_deterministic_and_bounded():
    """Host-side: guided proposals are deterministic per (genome, seed),
    clipped to the cube, and degrade to gaussian with no lit parents."""
    rng_args = dict(mu=np.full(6, 0.5), sigma=np.full(6, 0.3), n=8, seed=7)
    parents = np.random.default_rng(1).random((8, 6))
    novelty = np.array([0, 3, 0, 0, 9, 0, 0, 1])
    a = search_mod.propose_coverage_guided(
        np.random.default_rng(5), parents=parents, parent_novelty=novelty,
        **rng_args)
    b = search_mod.propose_coverage_guided(
        np.random.default_rng(5), parents=parents, parent_novelty=novelty,
        **rng_args)
    np.testing.assert_array_equal(a, b)
    assert (a >= 0).all() and (a <= 1).all()
    # Guided children sit NEAR their parents (small mutation: 0.25 x sigma
    # = 0.075 std here), tail-first; the richest parent (index 4) seeds the
    # last slot. A full-sigma perturbation would routinely exceed this.
    assert np.abs(a[-1] - parents[4]).max() < 0.4  # ~5 mutation-stds
    # No lit parents -> pure gaussian (same rng stream).
    g1 = search_mod.propose_coverage_guided(
        np.random.default_rng(5), parents=parents,
        parent_novelty=np.zeros(8, int), **rng_args)
    g2 = search_mod.propose_gaussian(
        np.random.default_rng(5), rng_args["mu"], rng_args["sigma"], 8)
    np.testing.assert_array_equal(g1, g2)


# ---------------------------------------------- validation & registry


def test_portfolio_and_spec_validation():
    with pytest.raises(ValueError, match="unknown portfolio member"):
        parse_portfolio("scalar,nonsense")
    with pytest.raises(ValueError, match="at least one member"):
        parse_portfolio("")
    assert parse_portfolio("scalar, coverage") == ("scalar", "coverage")
    with pytest.raises(ValueError, match="stop_on"):
        FarmSpec(stop_on="whenever")
    with pytest.raises(ValueError, match="divide"):
        FarmSpec(ticks=100, window=64)
    with pytest.raises(ValueError, match="coverage-guided"):
        search_mod.search(CFG, search_mod.SearchSpec(
            proposal="coverage-guided", fitness="scalar"))
    with pytest.raises(ValueError, match="novelty"):
        portfolio_mod.fit_coverage(None, None, None)
    # Duplicate members get distinct hunt-stream names.
    from raft_sim_tpu.farm.core import _member_names

    assert _member_names(("scalar", "scalar", "coverage")) == [
        "scalar", "scalar2", "coverage"
    ]


def test_validate_farm_dir_catches_defects(tmp_path):
    out = str(tmp_path / "farm")
    run_farm(CFG, _spec(("scalar", "coverage"), stop_on="budget"), out_dir=out)
    assert validate_farm_dir(out) == []
    # A TAIL-truncated hunt stream stays gen-contiguous, so the validator
    # must cross-check the row count against the manifest's generations_run.
    hunt = os.path.join(out, "members", "coverage", "hunt.jsonl")
    rows = open(hunt).read().splitlines()
    with open(hunt, "w") as f:
        f.write(rows[0] + "\n")
    problems = validate_farm_dir(out)
    assert any("truncated" in p for p in problems), problems
    # A non-contiguous (head-truncated) stream is caught by gen ordering.
    with open(hunt, "w") as f:
        f.write(rows[-1] + "\n")
    problems = validate_farm_dir(out)
    assert any("gen" in p for p in problems), problems
    # A missing manifest is fatal.
    os.remove(os.path.join(out, "farm_manifest.json"))
    assert validate_farm_dir(out) == [
        f"missing farm_manifest.json in {out}"
    ]
