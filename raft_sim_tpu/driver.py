"""Host driver: CLI + REPL workflow for the simulator.

The reference's dev loop is the Stuart Sierra "reloaded" REPL -- init/start/stop/go/
reset building a component system (dev/user.clj:13-29) -- and its CLI is
`lein run <self-id> <peer-id>...` (core.clj:197-203). The rebuild's equivalent is a
`Session` with the same verbs (init/run/reset) plus a `backend` option selecting
cpu|tpu (the north star's `:backend :tpu`), and a CLI:

    python -m raft_sim_tpu run --preset config1 --ticks 10000
    python -m raft_sim_tpu run --n-nodes 7 --batch 4096 --drop-prob 0.2 --summary
    python -m raft_sim_tpu run --preset config1 --trace-events --trace-cluster 0
    python -m raft_sim_tpu presets

Unlike the reference (one OS process per node, topology from argv), one process drives
every node of every simulated cluster; "topology" is just --n-nodes/--batch.
"""

from __future__ import annotations

import argparse
import dataclasses
import functools
import json
import sys
import time

import jax
import numpy as np

from raft_sim_tpu import init_batch
from raft_sim_tpu.sim import chunked, scan, trace
from raft_sim_tpu.utils import checkpoint
from raft_sim_tpu.utils.config import PRESETS, RaftConfig


def select_backend(backend: str) -> None:
    """Pick the JAX platform before any computation (north-star `:backend` option).

    `tpu` is resolved against whatever platform name the hardware actually registers
    under -- TPU plugins may expose a plugin-specific name (e.g. `axon` for a tunneled
    chip) that `jax.config.update("jax_platforms", "tpu")` would reject. Any other
    name (cpu, axon, ...) is passed through to jax_platforms directly.
    """
    if backend == "auto":
        return
    if backend == "tpu":
        # Clear any JAX_PLATFORMS=cpu env pin first: under default priority,
        # registered accelerator plugins outrank cpu, so "tpu" means "the
        # accelerator, whatever its platform name".
        jax.config.update("jax_platforms", "")
        plats = {d.platform for d in jax.devices()}  # initializes backends
        if not plats - {"cpu"}:
            raise RuntimeError(
                f"--backend tpu: no accelerator platform registered (found {sorted(plats)})"
            )
        return
    jax.config.update("jax_platforms", backend)


class Session:
    """REPL-friendly driver: the dev/user.clj workflow verbs over the simulator.

    >>> s = Session(RaftConfig(n_nodes=5, client_interval=8), batch=16, seed=0)
    >>> s.run(1000)        # scan forward, accumulating metrics
    >>> s.summary()        # fleet rollup dict
    >>> s.reset()          # back to tick 0 with the same seed (user/reset)

    `devices=N` shards the cluster batch over the first N local devices (a 1-D
    `parallel.make_mesh`): the jitted chunk calls see sharded inputs and XLA keeps
    the whole scan sharded -- the tick body has no cross-cluster ops, so no
    collectives appear in the hot loop. Trajectories are bit-identical at any
    device count (keys are split before sharding; pinned by tests/test_parallel.py).
    """

    def __init__(
        self, cfg: RaftConfig, batch: int = 1, seed: int = 0, devices: int | None = None
    ):
        self.cfg = cfg
        self.batch = batch
        self.seed = seed
        self.devices = devices
        self.apply_writer = None
        self.telemetry = None  # TelemetrySink (attach_telemetry)
        self._tel_rec = None  # flight-recorder carry (batch-minor)
        self._deltas = None  # serve.DeltaStream (offer's commit-ack watcher)
        self.perf = None  # obs.ChunkTimer (attach_perf)
        self._trace_spec = None  # trace.TraceSpec (attach_trace)
        self._trace_persist = None  # cross-chunk trace carry (batch-minor)
        self._trace_trigger = None  # flight-recorder event-kind trigger
        self.health = None  # health.HealthMonitor (attach_health)
        self._health_args = None  # (spec, directory) for reset re-attach
        self._live_rec = None  # this chunk's recorder (health evidence hook)
        self.reset()

    def reset(self) -> None:
        """Rebuild initial state from the seed (the reference's user/reset, minus code
        reloading, which Python REPLs handle themselves)."""
        root = jax.random.key(self.seed)
        k_init, k_run = jax.random.split(root)
        self.state = init_batch(self.cfg, k_init, self.batch)
        self.keys = jax.random.split(k_run, self.batch)
        self.metrics = scan.init_metrics_batch(self.batch)
        self._deltas = None  # a rebuilt experiment gets a fresh ack watermark
        self._apply_sharding()
        # A rebuilt experiment gets a rebuilt export stream: re-attach truncates
        # the files and zeroes the writer's frontier (a stale frontier would
        # silently drop the new run's early commits).
        if self.apply_writer is not None:
            self.attach_apply_log(self.apply_writer.directory, self.apply_writer.cluster)
        if self.telemetry is not None:
            self.attach_telemetry(
                self.telemetry.directory,
                window=self.telemetry.window,
                ring=self.telemetry.ring,
            )
        # A rebuilt experiment gets a fresh perf stream too (the re-attach
        # above already truncated the sink's perf.jsonl).
        if self.perf is not None:
            self.attach_perf(warmup_chunks=self.perf.warmup_chunks)
        # ... and a fresh trace stream (the telemetry re-attach truncated the
        # trace files; re-arming rewrites trace_meta.json and zeroes the
        # cross-window carry).
        if self._trace_spec is not None:
            spec = self._trace_spec
            self._trace_persist = None
            if self.telemetry is not None:
                self.telemetry.write_trace_meta(spec)
        # ... and a fresh health plane: re-attaching truncates health.jsonl /
        # alerts.jsonl and clears stale evidence dirs, and the burn-rate state
        # machines restart from ok (a rebuilt experiment's budget is fresh).
        self._live_rec = None
        if self._health_args is not None:
            self.attach_health(*self._health_args)

    def _apply_sharding(self) -> None:
        if self.devices is None:
            return
        if self.devices < 1:
            raise ValueError(f"devices must be >= 1, got {self.devices}")
        if self.batch % self.devices:
            raise ValueError(
                f"batch {self.batch} must divide over {self.devices} devices"
            )
        if self.devices == 1:
            return
        from jax.sharding import NamedSharding, PartitionSpec as P

        from raft_sim_tpu.parallel import mesh as pmesh

        sh = NamedSharding(pmesh.make_mesh(self.devices), P(pmesh.AXIS))
        place = lambda t: jax.tree.map(lambda x: jax.device_put(x, sh), t)
        self.state = place(self.state)
        self.keys = jax.device_put(self.keys, sh)
        self.metrics = place(self.metrics)

    def attach_apply_log(self, directory: str, cluster: int = 0) -> None:
        """Stream the selected cluster's committed values to per-node files --
        the reference's `node_<id>.log` apply stream (log.clj:16-18, 74-75),
        exported at chunk boundaries during run(). Keep chunks small enough
        that commit advances by less than CAP - compact_margin per chunk, or
        compacted-away spans appear as `# snapshot gap` markers
        (utils/apply_log.py)."""
        from raft_sim_tpu.utils.apply_log import ApplyLogWriter

        if not 0 <= cluster < self.batch:
            raise IndexError(f"cluster {cluster} out of range for batch {self.batch}")
        self.apply_writer = ApplyLogWriter(directory, self.cfg, cluster)
        self.apply_writer.update(self.state)  # anything already committed

    def attach_telemetry(self, directory: str, window: int = 64, ring: int = 32) -> None:
        """Stream windowed fleet telemetry to `directory` (manifest +
        windows.jsonl, utils/telemetry_sink.py) and arm a `ring`-deep flight
        recorder that freezes each cluster's last ticks at its first safety
        violation (ring=0 disables it). run() then scans through the telemetry
        path (sim/telemetry.py) -- trajectories stay bit-identical to the
        plain path; the only cost is the extra telemetry carry traffic
        (docs/OBSERVABILITY.md). Call finalize_telemetry() at the end of the
        experiment to export violating clusters' flight recordings."""
        from raft_sim_tpu.sim import telemetry
        from raft_sim_tpu.utils.telemetry_sink import TelemetrySink

        if window < 1:
            raise ValueError(f"telemetry window must be >= 1, got {window}")
        if ring < 0:
            raise ValueError(f"telemetry ring must be >= 0, got {ring}")
        self.telemetry = TelemetrySink(
            directory, self.cfg, seed=self.seed, batch=self.batch,
            window=window, ring=ring,
        )
        self._tel_rec = (
            telemetry.init_recorder(self.cfg, ring, self.batch) if ring else None
        )

    def attach_trace(
        self,
        depth: int = 128,
        freeze: str | None = None,
        trigger: str | None = None,
        coverage: bool = True,
    ) -> None:
        """Arm the protocol trace plane (raft_sim_tpu/trace; requires
        cfg.track_trace and an attached telemetry sink): run() extracts
        per-cluster protocol events on device and streams them per window as
        trace.jsonl + trace_windows.jsonl for timeline rendering
        (tools/metrics_report.py --trace) and whole-history checking
        (python -m raft_sim_tpu.trace.checker). `freeze` (an event-kind name,
        trace.KINDS) stops a cluster's recording after the first occurrence
        of that kind; `trigger` re-arms the FLIGHT RECORDER's freeze on an
        event kind instead of the default violation trigger -- "capture the
        lead-up to the first leadership change/crash" (docs/OBSERVABILITY.md,
        trigger semantics)."""
        from raft_sim_tpu.trace import KINDS, TraceSpec

        if not self.cfg.track_trace:
            raise ValueError(
                "attach_trace needs cfg.track_trace=True (the trace plane is "
                "a structural config gate -- utils/config.py)"
            )
        if self.telemetry is None:
            raise RuntimeError(
                "attach_trace needs an attached telemetry sink "
                "(attach_telemetry): trace windows stream through it"
            )

        def kind_code(name, what):
            if name is None:
                return None
            if name not in KINDS:
                raise ValueError(
                    f"unknown {what} event kind {name!r} (have {sorted(KINDS)})"
                )
            return KINDS[name]

        self._trace_spec = TraceSpec(
            depth=depth, coverage=coverage, freeze_kind=kind_code(freeze, "freeze") or 0
        )
        self._trace_trigger = kind_code(trigger, "trigger")
        self._trace_persist = None
        self.telemetry.write_trace_meta(self._trace_spec)

    def attach_perf(self, warmup_chunks: int | None = None) -> None:
        """Arm per-chunk runtime attribution (obs.ChunkTimer): run() streams
        perf.jsonl rows into the attached telemetry sink (or keeps them on
        `self.perf.rows` with no sink) -- wall time split device-vs-host,
        warmup vs steady state, device memory occupancy, and the jit-cache
        recompile watchdog. Purely host-side: trajectories, lowerings, and
        compile counts are untouched (docs/OBSERVABILITY.md, "Runtime
        perf")."""
        from raft_sim_tpu.obs import ChunkTimer

        kwargs = {} if warmup_chunks is None else {"warmup_chunks": warmup_chunks}
        self.perf = ChunkTimer(
            label="run", batch=self.batch, sink=self.telemetry, **kwargs
        )
        if self.health is not None:
            # Either attach order works: an already-armed monitor picks up
            # the new timer for its runtime SLIs (device-wait, recompiles).
            self.health.perf = self.perf

    def attach_health(self, spec="default", directory: str | None = None) -> None:
        """Arm the fleet health plane (raft_sim_tpu/health; docs/OBSERVABILITY.md
        "Fleet health & SLOs"): run() evaluates the SLO spec every
        `eval_windows` telemetry windows (or chunks, on the plain path) and
        streams health.jsonl + alerts.jsonl into the attached telemetry
        sink's directory -- or an explicit `directory` when no sink is
        attached (the plain chunked path). Firing burn-rate alerts triage
        the worst clusters and freeze an evidence bundle; with the flight
        recorder armed (attach_telemetry ring>0) the named clusters' live
        rings are snapshotted into it. Purely host-side: the monitor reads
        only host copies the loop already fetched, so instrumented runs are
        bit-exact vs plain (tier-1 pinned, tests/test_health.py)."""
        from raft_sim_tpu.health import HealthMonitor, HealthWriter, load_spec

        target = directory or (
            self.telemetry.directory if self.telemetry is not None else None
        )
        if target is None:
            raise RuntimeError(
                "attach_health needs somewhere to stream health.jsonl: "
                "attach a telemetry sink first (attach_telemetry) or pass "
                "directory="
            )
        self._health_args = (spec, directory)
        self.health = HealthMonitor(
            load_spec(spec) if not isinstance(spec, dict) else spec,
            batch=self.batch, writer=HealthWriter(target), scope="fleet",
            perf=self.perf, capture=self._health_capture,
        )

    def _health_capture(self, alert, clusters):
        """Evidence hook for the session's monitor: snapshot the triaged
        clusters' live flight-recorder rings (telemetry path with ring>0;
        the plain path has no recorder and contributes refs only)."""
        flights = {}
        rec = self._live_rec if self._live_rec is not None else self._tel_rec
        if rec is not None:
            from raft_sim_tpu.sim import telemetry

            for c in clusters:
                flights[int(c)] = telemetry.export_cluster(rec, int(c))
        return {
            "flights": flights,
            "refs": {"seed": self.seed, "batch": self.batch, "source": "run"},
        }

    def run(self, n_ticks: int, chunk: int = 4096, progress: bool = False) -> None:
        def progress_line(done, metrics):
            if progress:
                v = int(np.sum(np.asarray(metrics.violations)))
                print(f"  {done}/{n_ticks} ticks, violations={v}", file=sys.stderr)

        if self.telemetry is not None:
            from raft_sim_tpu.sim import telemetry

            def cb_t(done, state, metrics, records):
                self.telemetry.append_windows(records)
                if self.health is not None:
                    # After the sink append: the monitor reads the same host
                    # copy the export path fetched, never its own device_get.
                    self.health.observe_records(records)
                if self.apply_writer is not None:
                    self.apply_writer.update(state)
                progress_line(done, metrics)
                return False

            # The health evidence hook needs THIS chunk's carried recorder
            # (a firing alert snapshots the named clusters' live rings);
            # chunk_hook runs before cb_t, so the stash is always current.
            hook = None
            if self.health is not None:
                def hook(done, rec):
                    self._live_rec = rec

            if self._trace_spec is not None or self._trace_trigger is not None:
                out = telemetry.run_chunked_telemetry(
                    self.cfg, self.state, self.keys, n_ticks,
                    window=self.telemetry.window, recorder=self._tel_rec,
                    chunk=chunk, callback=cb_t, perf=self.perf,
                    trace_spec=self._trace_spec,
                    trace_persist=self._trace_persist,
                    trigger_kind=self._trace_trigger,
                    trace_callback=lambda done, traws:
                        self.telemetry.append_trace(traws),
                    chunk_hook=hook,
                )
                if self._trace_spec is not None:
                    self.state, m, self._tel_rec, self._trace_persist = out
                else:
                    self.state, m, self._tel_rec = out
            else:
                self.state, m, self._tel_rec = telemetry.run_chunked_telemetry(
                    self.cfg, self.state, self.keys, n_ticks,
                    window=self.telemetry.window, recorder=self._tel_rec,
                    chunk=chunk, callback=cb_t, perf=self.perf,
                    chunk_hook=hook,
                )
            self.metrics = chunked.merge_metrics(self.metrics, m)
            return

        def cb(done, state, metrics):
            if self.health is not None:
                # Plain path: the chunk is the window (observe_chunk derives
                # per-chunk counter deltas from the cumulative RunMetrics).
                self.health.observe_chunk(done, metrics)
            if self.apply_writer is not None:
                self.apply_writer.update(state)
            progress_line(done, metrics)
            return False

        if self.health is not None:
            # run_chunked restarts its cumulative metrics and tick counter
            # per call: re-baseline the monitor's delta accumulator.
            self.health.begin_run()

        self.state, m = chunked.run_chunked(
            self.cfg, self.state, self.keys, n_ticks, chunk=chunk, callback=cb,
            perf=self.perf,
        )
        self.metrics = chunked.merge_metrics(self.metrics, m)

    def finalize_telemetry(self, max_flights: int = 8) -> dict:
        """End-of-experiment telemetry export: write summary.json and, for up
        to `max_flights` clusters whose flight recorder froze (on a violation,
        or on the armed trigger kind -- attach_trace), the recorder's final
        ticks as flight_<cluster>.jsonl. Returns {"flights": [cluster ids
        exported], "flights_frozen": total frozen count, "flights_exported":
        count actually written, "summary": path} -- the frozen-vs-exported
        totals are also in summary.json, so clusters dropped by the
        max_flights cap are a REPORTED number, never a silent one."""
        if self.telemetry is None:
            raise RuntimeError("no telemetry attached (attach_telemetry)")
        from raft_sim_tpu.sim import telemetry

        flights = []
        frozen_total = 0
        if self._tel_rec is not None:
            frozen = np.flatnonzero(np.asarray(self._tel_rec.frozen))
            frozen_total = int(frozen.size)
            for cluster in frozen[:max_flights]:
                ticks, infos = telemetry.export_cluster(self._tel_rec, int(cluster))
                self.telemetry.write_flight(int(cluster), ticks, infos)
                flights.append(int(cluster))
            if frozen.size > max_flights:
                print(
                    f"telemetry: {frozen.size} frozen clusters, exported "
                    f"first {max_flights} flight recordings "
                    f"({frozen.size - max_flights} not exported -- raise "
                    "max_flights to keep them)",
                    file=sys.stderr,
                )
        summary = self.summary()
        summary["flights_frozen"] = frozen_total
        summary["flights_exported"] = len(flights)
        if self._trace_persist is not None:
            from raft_sim_tpu.trace.ring import cov_popcount

            tp = self._trace_persist
            summary["trace"] = {
                "events_emitted": int(np.asarray(tp.total, np.int64).sum()),
                "frozen_clusters": int(np.asarray(tp.frozen).sum()),
                "cov_bits_max": int(np.asarray(cov_popcount(tp.cov)).max()),
            }
        path = self.telemetry.write_summary(summary)
        return {
            "flights": flights,
            "flights_frozen": frozen_total,
            "flights_exported": len(flights),
            "summary": path,
        }

    def offer(self, value: int, wait: int = 0) -> dict:
        """Offer one client command and advance one tick -- the reference's ad-hoc
        `curl POST /client-set` (server.clj:8-12, core.clj:151-160; with
        cfg.client_redirect the kernel routes it through the 302 redirect dance).
        Overrides that tick's scheduled client input, metrics accumulate as in
        run(). Returns {"accepted", "committed", "waited"}: `accepted` counts
        clusters whose live leader appended the value ON the offer tick (under
        client_redirect acceptance usually lands on a LATER tick, after the
        bounces, so this undercounts there -- watch `committed` instead);
        `committed` counts clusters whose COMMIT-DELTA STREAM (the device-side
        node-0 apply stream, serve/deltas.py) delivered the value after the
        offer, stepping up to `wait` further ticks -- the per-entry ack the
        reference's commit watch was meant to deliver and never did
        (log.clj:83-87, bug 2.3.9; VERDICT missing #2). Acks match by
        (value, offer stamp) pair: the watermark excludes everything committed
        BEFORE the offer, and the stamp -- this offer's tick + 1, riding the
        v21 log_tick plane -- excludes colliding values committed DURING the
        wait window (e.g. a scheduled command whose value happens to equal
        this payload), so an ack is THIS entry, exactly. ANY int32 payload
        except the NIL/NOOP sentinels is legal -- the old "prefer values
        <= -3" collision caveat is gone. Acks follow node 0's commit, which
        trails the leader's by up to a heartbeat round trip (and stalls while
        node 0 is crashed): size `wait` accordingly.
        """
        value = int(value)
        if self._trace_spec is not None:
            # offer() ticks run outside the windowed telemetry scan, so their
            # events would be MISSING from the trace stream while the ticks
            # stay monotone -- an undetectable hole the checker would then
            # PASS over (the vacuous-pass class trace/history.py exists to
            # prevent). Refuse rather than record a silently gappy history.
            raise RuntimeError(
                "Session.offer() ticks are not covered by the armed trace "
                "stream; detach the trace, or ingest via run()'s scheduled "
                "cadence / the serve loop instead"
            )
        from raft_sim_tpu.serve.ingest import check_value

        check_value(value)  # same NIL/NOOP/int32 rule as the serve ingest
        if self._deltas is None:
            from raft_sim_tpu.serve.deltas import DeltaStream

            self._deltas = DeltaStream(self.batch, depth=32)
        # Only commits that happen AFTER this offer can ack it.
        self._deltas.skip_to_now(self.state)
        # The fleet ticks in lockstep: the offered entry's stamp is the shared
        # pre-offer `now` + 1 everywhere it lands (redirect bounces carry the
        # stamp of the OFFER tick, not the acceptance tick). Without the tick
        # plane (track_offer_ticks off) stamps are all zero and the match
        # falls back to value-only: no scheduled traffic exists to collide
        # with (client_interval == 0), and skip_to_now excludes everything
        # committed anywhere pre-offer -- what can still alias is a PRIOR
        # offer of the same value accepted but uncommitted at offer time (the
        # snapshot-diff poll this replaces had the identical caveat; tracked
        # configs are exact).
        track = self.cfg.track_offer_ticks
        stamp = int(np.asarray(self.state.now).ravel()[0]) + 1
        acked: set[int] = set()

        def fresh() -> int:
            for row in self._deltas.drain(self.state):
                for v, tk in zip(row["values"], row["ticks"]):
                    if v == value and (not track or tk == stamp):
                        acked.add(row["cluster"])
            return len(acked)

        self.state, self.metrics, accepted = _offer_tick(
            self.cfg, self.state, self.keys, self.metrics, value
        )
        if self.apply_writer is not None:
            # offer() ticks outside run()'s chunk loop: keep the export stream
            # current even when offer() is the session's last action.
            self.apply_writer.update(self.state)
        accepted = int(np.sum(np.asarray(accepted)))
        committed, waited = fresh(), 0
        # Direct mode: commitment can only reach the same-tick acceptance count.
        # Redirect mode: acceptance trickles in over the bounces, so keep
        # stepping until every cluster committed or the wait budget runs out.
        goal = self.batch if self.cfg.client_redirect else accepted
        while waited < wait and committed < goal:
            self.run(1, chunk=1)
            waited += 1
            committed = fresh()
        return {"accepted": accepted, "committed": committed, "waited": waited}

    def offer_read(self, wait: int = 0) -> dict:
        """Offer one ReadIndex read and advance one tick -- the read-side
        Session.offer (the `Session.offer_read` verb docs/SERVE.md named as
        the missing follow-up). Overrides that tick's scheduled read input
        via the same shared tick body (scan.tick_batch_minor read_cmd=).

        The ack path mirrors offer()'s delta-stream acks at the read side's
        natural granularity: a write is acked when the commit-delta stream
        delivers its (value, stamp) pair; a read produces no log entry, so
        its ack is the served-read COUNTER advancing (reads are fungible --
        StepInfo.reads_served, the same counter the serve loop's per-tenant
        read crediting reads). Returns {"captured", "served", "waited"}:
        `captured` counts clusters whose leader captured the read on the
        offer tick (a leaderless or busy-slotted cluster drops it -- retry),
        `served` counts clusters whose read was served within `wait` further
        ticks (confirmation round, or the lease fast path under
        cfg.read_lease). Requires the ReadIndex plane (cfg.read_index:
        read_interval > 0 or serve_reads)."""
        if self._trace_spec is not None:
            # Same hole as offer(): out-of-scan ticks would punch undetectable
            # monotone-tick gaps into the armed trace stream.
            raise RuntimeError(
                "Session.offer_read() ticks are not covered by the armed "
                "trace stream; detach the trace, or ingest reads via the "
                "scheduled cadence / the serve loop instead"
            )
        if not self.cfg.read_index:
            raise ValueError(
                "offer_read needs the ReadIndex plane: set read_interval > 0 "
                "or serve_reads=True (utils/config.py)"
            )
        before = np.asarray(self.metrics.reads_served).astype(np.int64).copy()
        stamp = int(np.asarray(self.state.now).ravel()[0]) + 1
        self.state, self.metrics = _offer_read_tick(
            self.cfg, self.state, self.keys, self.metrics
        )
        if self.apply_writer is not None:
            self.apply_writer.update(self.state)
        # Captures from THIS offer only: a fresh capture stamps read_tick
        # with the offer tick + 1 (older pending slots -- e.g. config9's
        # scheduled cadence -- carry earlier stamps and must not count).
        captured = int(np.sum(np.any(
            (np.asarray(self.state.read_idx) > 0)
            & (np.asarray(self.state.read_tick) == stamp),
            axis=1,
        )))

        def served_now() -> int:
            return int(
                np.sum(np.asarray(self.metrics.reads_served) - before)
            )

        served, waited = served_now(), 0
        while waited < wait and served < self.batch:
            self.run(1, chunk=1)
            waited += 1
            served = served_now()
        return {"captured": captured, "served": served, "waited": waited}

    def _committed_mask(self, value: int) -> np.ndarray:
        """[batch] bool: clusters in which `value` is a committed live entry
        (host-side ring scan; entries compacted past the base are no longer
        attributable). SUPERSEDED by the commit-delta stream for offer() acks
        (the full-state device_get + scan this does per probe is exactly what
        serve/deltas.py removes); kept as the snapshot-diff CROSS-CHECK the
        delta tests compare against (tests/test_serve.py)."""
        st = jax.device_get(self.state)
        lv = np.asarray(st.log_val)  # [B, N, CAP]
        commit = np.asarray(st.commit_index)[:, :, None]
        base = np.asarray(st.log_base)[:, :, None]
        cap = self.cfg.log_capacity
        sl = np.arange(cap)[None, None, :]
        abs1 = base + (sl - base) % cap + 1  # absolute 1-based index per slot
        hit = (lv == value) & (abs1 > base) & (abs1 <= commit)
        return np.any(hit, axis=(1, 2))

    def trace(self, n_ticks: int, cluster: int = 0):
        """Step a single selected cluster with full per-tick info + states captured
        (heavy; debugging only). Does not advance the session."""
        if not 0 <= cluster < self.batch:
            raise IndexError(f"cluster {cluster} out of range for batch {self.batch}")
        one = jax.tree.map(lambda x: x[cluster], self.state)
        _, _, outs = _traced_run(self.cfg, n_ticks)(one, self.keys[cluster])
        return outs  # (stacked StepInfo, stacked states)

    def summary(self) -> dict:
        from raft_sim_tpu.parallel import summarize

        s = summarize(self.metrics)
        return s._asdict()

    def save(self, path: str) -> str:
        return checkpoint.save(
            path, self.cfg, self.state, self.keys, self.metrics, seed=self.seed
        )

    @classmethod
    def restore(cls, path: str, devices: int | None = None) -> "Session":
        """Resume exactly: state, keys, accumulated metrics, AND the original seed come
        back, so summary() after more run() calls matches a never-interrupted session
        and reset() rebuilds the same experiment. `devices` reshards on load (a
        checkpoint is device-layout agnostic). Scenario checkpoints (driver
        `scenario run --save`) are rejected: a Session has no genome path, so
        continuing one here would silently run a DIFFERENT experiment."""
        cfg, state, keys, metrics, seed, scenario = checkpoint.load(path)
        if scenario is not None:
            raise ValueError(
                f"checkpoint {path!r} carries scenario "
                f"{scenario.get('name', '?')!r}: resume it with "
                "`python -m raft_sim_tpu scenario run --resume`, not a plain "
                "Session"
            )
        self = cls.__new__(cls)
        self.apply_writer = None
        self.telemetry = None
        self._tel_rec = None
        self._deltas = None
        self.perf = None
        self._trace_spec = None
        self._trace_persist = None
        self._trace_trigger = None
        self.health = None
        self._health_args = None
        self._live_rec = None
        self.cfg = cfg
        self.batch = state.role.shape[0]
        self.seed = seed
        self.devices = devices
        self.state = state
        self.keys = keys
        self.metrics = metrics
        self._apply_sharding()
        return self


@functools.lru_cache(maxsize=8)
def _traced_run(cfg: RaftConfig, n_ticks: int):
    return jax.jit(lambda s, k: scan.run(cfg, s, k, n_ticks, trace_states=True))


@functools.partial(jax.jit, static_argnums=0)
def _offer_read_tick(cfg: RaftConfig, state, keys, metrics):
    """One tick with a ReadIndex read offered (Session.offer_read), through
    the same shared tick body as the scan loop."""
    from raft_sim_tpu.models import raft_batched

    s_t = raft_batched.to_batch_minor(state)
    m_t = raft_batched.to_batch_minor(metrics)
    s2, m2, _ = scan.tick_batch_minor(cfg, s_t, keys, m_t, read_cmd=1)
    return raft_batched.from_batch_minor(s2), raft_batched.from_batch_minor(m2)


@functools.partial(jax.jit, static_argnums=0)
def _offer_tick(cfg: RaftConfig, state, keys, metrics, value):
    """One tick with the scheduled client input overridden by `value`
    (Session.offer), through the SAME shared tick body as the scan loop
    (scan.tick_batch_minor), so the interactive path can never drift from run()."""
    from raft_sim_tpu.models import raft_batched

    s_t = raft_batched.to_batch_minor(state)
    m_t = raft_batched.to_batch_minor(metrics)  # histogram leaf is [BINS, B] inside
    before = metrics.total_cmds
    s2, m2, _ = scan.tick_batch_minor(cfg, s_t, keys, m_t, client_cmd=value)
    metrics = raft_batched.from_batch_minor(m2)
    return raft_batched.from_batch_minor(s2), metrics, metrics.total_cmds - before


def _profile_ctx(path: str | None):
    """The --profile capture context, shared by run/serve/scenario-search:
    a jax.profiler perfetto trace into `path`, or a no-op without one.
    Capture is bit-exact vs no capture (tier-1 pinned, tests/test_obs.py)."""
    import contextlib

    if not path:
        return contextlib.nullcontext()
    return jax.profiler.trace(path, create_perfetto_trace=True)


def _sanitize_ctx(args):
    """The --sanitize arming context, shared by run/serve: the donation-poison
    sanitizer over every registered donating entry point
    (analysis/sanitizer.py), or a no-op without the flag. Yields the
    sanitizer's coverage stats (None when unarmed)."""
    import contextlib

    if not getattr(args, "sanitize", False):
        return contextlib.nullcontext()
    from raft_sim_tpu.analysis import sanitizer

    return sanitizer.armed()


def _sanitize_report(args, san) -> None:
    if san is None:
        return
    calls = ", ".join(f"{k}x{v}" for k, v in sorted(san["calls"].items()))
    print(
        f"sanitizer: clean ({calls or 'no donating dispatches'}; "
        f"{san['pre_deleted']} buffers invalidated by donation, "
        f"{san['poisoned']} poisoned as backstop)",
        file=sys.stderr,
    )


_FLAG_TYPES = {"int": int, "float": float}


def _add_config_flags(p: argparse.ArgumentParser) -> None:
    """One CLI flag per RaftConfig field (field types are strings under
    `from __future__ import annotations`)."""
    for f in dataclasses.fields(RaftConfig):
        flag = "--" + f.name.replace("_", "-")
        if f.type == "bool":
            p.add_argument(flag, type=lambda s: s.lower() in ("1", "true", "yes"),
                           default=None, metavar="BOOL")
        else:
            p.add_argument(flag, type=_FLAG_TYPES.get(f.type, str), default=None)


def build_config(args) -> tuple[RaftConfig, int]:
    """(config, batch) from preset + CLI overrides; batch falls back preset -> 1."""
    preset_batch = 1
    if args.preset:
        cfg, preset_batch = PRESETS[args.preset]
    else:
        cfg = RaftConfig()
    batch = args.batch if args.batch is not None else preset_batch
    overrides = {
        f.name: getattr(args, f.name)
        for f in dataclasses.fields(RaftConfig)
        if getattr(args, f.name) is not None
    }
    return (dataclasses.replace(cfg, **overrides) if overrides else cfg), batch


def _nondefault_config(cfg: RaftConfig) -> dict:
    """cfg's non-default fields (the portable config encoding repro artifacts
    and hit files carry; RaftConfig(**this) rebuilds it)."""
    return {
        f.name: getattr(cfg, f.name)
        for f in dataclasses.fields(RaftConfig)
        if getattr(cfg, f.name) != f.default
    }


def _scenario_run(args, ap) -> int:
    """`scenario run`: a fleet under a declarative nemesis program
    (docs/SCENARIOS.md). One compiled program drives the whole phased
    timeline; checkpoints carry the scenario (format v20) so resume cannot
    silently continue a different experiment."""
    from raft_sim_tpu.parallel import summarize
    from raft_sim_tpu.scenario import genome as genome_mod
    from raft_sim_tpu.scenario import program as program_mod

    if args.resume:
        conflicting = [
            f.name for f in dataclasses.fields(RaftConfig)
            if getattr(args, f.name) is not None
        ]
        for flag in ("preset", "scenario", "batch", "seed"):
            if getattr(args, flag) is not None:
                conflicting.append(flag)
        if conflicting:
            ap.error(
                f"--resume is exclusive with config/scenario flags: "
                f"{', '.join(conflicting)}"
            )
        cfg, state, keys, metrics, seed, scen = checkpoint.load(args.resume)
        if scen is None:
            ap.error(
                f"{args.resume!r} is a plain checkpoint (no scenario); resume "
                "it with `run --resume`"
            )
        prog = program_mod.from_dict(scen, cfg)
        batch = state.role.shape[0]
    else:
        if not args.scenario:
            ap.error("scenario run needs --scenario FILE (or --resume)")
        cfg, batch = build_config(args)
        try:
            prog = program_mod.load(args.scenario, cfg)
        except ValueError as ex:
            ap.error(f"--scenario {args.scenario}: {ex}")
        seed = args.seed if args.seed is not None else 0
        root = jax.random.key(seed)
        k_init, k_run = jax.random.split(root)
        state = init_batch(cfg, k_init, batch)
        keys = jax.random.split(k_run, batch)
        metrics = scan.init_metrics_batch(batch)

    g = genome_mod.broadcast(prog.genome, batch)

    def cb(done, _state, m):
        if args.progress:
            v = int(np.sum(np.asarray(m.violations)))
            print(f"  {done}/{args.ticks} ticks, violations={v}", file=sys.stderr)
        return False

    t0 = time.perf_counter()
    state, m = chunked.run_chunked(
        cfg, state, keys, args.ticks, chunk=args.chunk, callback=cb,
        genome=g, seg_len=prog.seg_len,
    )
    metrics = chunked.merge_metrics(metrics, m)
    out = summarize(metrics)._asdict()
    dt = time.perf_counter() - t0
    out["scenario"] = prog.name
    out["segments"] = prog.n_segments
    out["seg_len"] = prog.seg_len
    out["wall_s"] = round(dt, 3)
    out["cluster_ticks_per_s"] = round(batch * args.ticks / dt, 1)
    print(json.dumps(out))
    if args.save:
        # exact=True rides the integer genome leaves along: a resumed run
        # must draw from the IDENTICAL thresholds, not a 9-decimal rounding
        # of them (checkpoint.py v20 contract).
        checkpoint.save(
            args.save, cfg, state, keys, metrics, seed=seed,
            scenario=program_mod.to_dict(prog, exact=True),
        )
    return 0


def _scenario_search(args, ap) -> int:
    """`scenario search`: the cross-entropy violation hunt (scenario/search.py).
    Prints the full result JSON; --out writes a replayable hit file for
    `scenario shrink` when a violating genome was found."""
    from raft_sim_tpu.scenario import search as search_mod

    cfg, _ = build_config(args)
    mutant = args.mutant
    if mutant:
        from raft_sim_tpu.scenario.mutation import mutant_config

        try:
            cfg = mutant_config(mutant, cfg)
        except ValueError as ex:
            ap.error(str(ex))
    spec = search_mod.SearchSpec(
        generations=args.generations,
        population=args.population,
        ticks=args.ticks,
        window=args.window,
        elite_frac=args.elite_frac,
        seed=args.seed if args.seed is not None else 0,
        fitness=args.fitness,
        trace_depth=args.trace_depth,
        proposal=args.proposal,
    )
    try:
        with _profile_ctx(args.profile):
            res = search_mod.search(cfg, spec)
    except ValueError as ex:
        ap.error(str(ex))
    doc = {
        "found": res.hit is not None,
        "hit": res.hit,
        "generations": res.generations,
        "spec": res.spec,
        "mutant": mutant,
    }
    if res.hit is not None and args.out:
        hit_doc = {"config": _nondefault_config(cfg), "mutant": mutant, **res.hit}
        with open(args.out, "w") as f:
            json.dump(hit_doc, f, indent=1)
            f.write("\n")
        doc["hit_file"] = args.out
    print(json.dumps(doc))
    return 0


def _scenario_shrink(args, ap) -> int:
    """`scenario shrink`: minimize a search hit file to a repro artifact that
    `tools/repro.py --scenario` replays bit-exactly."""
    from raft_sim_tpu.scenario import shrink as shrink_mod

    with open(args.hit) as f:
        hit = json.load(f)
    cfg = RaftConfig(**hit.get("config", {}))
    if hit.get("mutant"):
        from raft_sim_tpu.scenario.mutation import mutant_config

        cfg = mutant_config(hit["mutant"], cfg)
    try:
        art = shrink_mod.shrink(
            cfg, hit, mutant=hit.get("mutant"),
            halving_rounds=args.halving_rounds, context=args.context,
        )
    except ValueError as ex:
        ap.error(str(ex))
    shrink_mod.save_artifact(args.out, art)
    print(json.dumps({
        "artifact": args.out,
        "tick": art["tick"],
        "kinds": art["kinds"],
        "removed": art["removed"],
        "segments": art["segments"],
        "repro_cmd": f"python tools/repro.py --scenario {args.out}",
    }))
    return 0


def _scenario_farm(args, ap) -> int:
    """`scenario farm`: the fuzzing farm (raft_sim_tpu/farm) -- a portfolio
    of fitness members hunted in parallel from ONE compiled program per
    generation, coverage-guided mutation against a farm-wide seen set, and
    the auto-corpus policy (shrink -> dedup -> provenance-stamp ->
    checker-gate -> freeze). Ends in either a frozen hit or a pinned
    negative result (out-dir/negative.json with coverage numbers)."""
    from raft_sim_tpu.farm import FarmSpec, parse_portfolio, run_farm

    cfg, _ = build_config(args)
    mutant = args.mutant
    if mutant:
        from raft_sim_tpu.scenario.mutation import mutant_config

        try:
            cfg = mutant_config(mutant, cfg)
        except ValueError as ex:
            ap.error(str(ex))
    mesh = None
    if args.mesh is not None:
        from raft_sim_tpu.parallel import make_mesh

        try:
            mesh = make_mesh(args.mesh or None)
        except ValueError as ex:
            ap.error(str(ex))
    try:
        spec = FarmSpec(
            portfolio=parse_portfolio(args.portfolio),
            budget_gens=args.budget_gens,
            # Under --mesh the population scales with the device count:
            # --population is the per-device share of the fleet.
            population=args.population * (mesh.devices.size if mesh else 1),
            ticks=args.ticks,
            window=args.window,
            elite_frac=args.elite_frac,
            seed=args.seed if args.seed is not None else 0,
            trace_depth=args.trace_depth,
            guided=not args.no_guided,
            stop_on=args.stop_on,
        )
        with _profile_ctx(args.profile):
            res = run_farm(
                cfg, spec, mutant=mutant, out_dir=args.out_dir,
                corpus_dir=args.corpus_dir, freeze=args.freeze, mesh=mesh,
                health=args.health,
            )
    except ValueError as ex:
        ap.error(str(ex))
    print(json.dumps({
        "found": bool(res.hits),
        "hits": res.manifest["hits"],
        "frozen": res.manifest["frozen"],
        "dedup_rejected": res.dedup_rejected,
        "negative": res.negative,
        "generations_run": res.manifest["generations_run"],
        "evaluations": res.manifest["evaluations"],
        "cov_bits_total": res.manifest["cov_bits_total"],
        "manifest_hash": res.manifest["manifest_hash"],
        "out_dir": args.out_dir,
    }))
    return 0


def _shard_round_robin(it, weights: list[int]):
    """Split one lazy payload iterator into len(weights) shard iterators,
    dealing commands in weighted round-robin order (shard i gets weights[i]
    consecutive commands per cycle) -- how `serve --tenants N` divides a
    single JSONL stream among tenants. Weighting by each tenant's cluster
    count matters beyond fairness: consumption per chunk is proportional to
    cluster count, so a uniform deal against unequal slices would grow the
    smaller tenants' buffers by ~one command per tick FOREVER; the weighted
    deal keeps every queue bounded by one chunk's imbalance."""
    from collections import deque

    src = iter(it)
    order = [i for i, w in enumerate(weights) for _ in range(w)]
    queues = [deque() for _ in weights]
    turn = [0]  # position in the weighted deal order

    def shard(i: int):
        while True:
            if queues[i]:
                yield queues[i].popleft()
                continue
            try:
                v = next(src)
            except StopIteration:
                return
            queues[order[turn[0]]].append(v)
            turn[0] = (turn[0] + 1) % len(order)

    return [shard(i) for i in range(len(weights))]


def _serve(args, ap) -> int:
    """`serve`: the standing-fleet service loop (docs/SERVE.md). A long-lived
    fleet accepts streamed client commands between chunks (JSONL source, '-'
    = stdin) and continuously streams telemetry windows + commit deltas to
    the schema'd sink. Zero recompiles after the first chunk: the chunk
    program is fixed, commands are data. `--tenants N` partitions the
    cluster range among N tenants (the batch axis is the tenancy axis: same
    compiled program at every N), sharding the command stream round-robin;
    `--reads-per-tenant R` adds R ReadIndex reads to each tenant's demand
    (requires a read-carrying config, e.g. config9)."""
    from raft_sim_tpu.parallel import summarize
    from raft_sim_tpu.serve import CommandSource, ServeSession, jsonl_commands
    from raft_sim_tpu.serve.loop import serve_config

    cfg, batch = build_config(args)
    cfg = serve_config(cfg)
    if args.source != "-":
        # Fail fast: jsonl_commands opens lazily (first next_chunk), which is
        # AFTER the session has compiled and run its warmup -- a typo'd path
        # must not cost minutes before erroring.
        try:
            open(args.source).close()
        except OSError as ex:
            ap.error(f"--source: {ex}")
    sink = None
    if args.sink:
        from raft_sim_tpu.utils.telemetry_sink import TelemetrySink

        sink = TelemetrySink(
            args.sink, cfg, seed=args.seed or 0, batch=batch,
            window=args.window, ring=0, source="serve",
        )
    perf = None
    if args.perf:
        from raft_sim_tpu.obs import ChunkTimer

        perf = ChunkTimer(label="serve", batch=batch, sink=sink)
    tenants = None
    if args.reads_per_tenant < 0:
        ap.error("--reads-per-tenant must be >= 0")
    if args.tenants is not None and not 1 <= args.tenants <= batch:
        ap.error(f"--tenants must be in [1, batch={batch}]")
    if args.tenants is not None or args.reads_per_tenant:
        from raft_sim_tpu.serve.tenancy import Tenant

        if args.tenants is None:
            # --reads-per-tenant alone: ONE tenant whose writes keep the
            # legacy broadcast semantics (each command to every cluster) --
            # a read demand must never silently reshape the write path.
            tenants = [
                Tenant("tenant0", batch,
                       source=jsonl_commands(args.source),
                       reads=args.reads_per_tenant, broadcast=True)
            ]
        else:
            # Explicit --tenants N (N = 1 included): the partitioned form,
            # command stream sharded round-robin, one slot per
            # (tick, cluster).
            from raft_sim_tpu.serve.tenancy import split_even

            n_ten = args.tenants
            sizes = split_even(batch, n_ten)
            shards = _shard_round_robin(jsonl_commands(args.source), sizes)
            tenants = [
                Tenant(f"tenant{i}", sizes[i], source=shards[i],
                       reads=args.reads_per_tenant)
                for i in range(n_ten)
            ]
    if args.health and not args.sink:
        ap.error("--health needs --sink (the health/alert streams ride the "
                 "telemetry sink directory)")
    try:
        sess = ServeSession(
            cfg, batch=batch, seed=args.seed or 0, chunk=args.chunk,
            window=args.window, delta_depth=args.delta_depth, sink=sink,
            warmup_ticks=args.warmup, perf=perf, tenants=tenants,
            health=args.health,
        )
    except ValueError as ex:
        ap.error(str(ex))
    source = (
        None if tenants is not None
        else CommandSource(jsonl_commands(args.source))
    )

    def progress(st):
        if args.progress:
            print(
                f"  chunk {st['chunks']}: {st['ticks']} ticks, "
                f"{st['deltas_exported']} deltas, "
                f"{st['reads_served']} reads, "
                f"violations={st['violations']}",
                file=sys.stderr,
            )

    try:
        with _profile_ctx(args.profile), _sanitize_ctx(args) as san:
            stats = sess.serve(
                source, chunks=args.chunks, drain_chunks=args.drain_chunks,
                progress=progress,
            )
    except ValueError as ex:
        ap.error(str(ex))
    _sanitize_report(args, san)
    out = summarize(sess.metrics)._asdict()
    out.update(stats)
    if stats["wall_s"] > 0:
        out["cluster_ticks_per_s"] = round(
            batch * stats["ticks"] / stats["wall_s"], 1
        )
        # The service's own throughput unit: completed work (committed
        # entries exported + reads served) per second -- the bench serve
        # row's headline (commands+reads/s), never ticks.
        out["ops_per_s"] = round(stats["ops_done"] / stats["wall_s"], 1)
    if args.sink:
        out["sink"] = args.sink
    print(json.dumps(out))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="raft_sim_tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)

    run_p = sub.add_parser("run", help="simulate a batch of clusters")
    run_p.add_argument("--preset", choices=sorted(PRESETS), default=None)
    run_p.add_argument("--batch", type=int, default=None)
    run_p.add_argument("--ticks", type=int, default=1000)
    run_p.add_argument("--seed", type=int, default=None,
                       help="PRNG seed (default 0; stored in checkpoints, so "
                            "exclusive with --resume)")
    run_p.add_argument("--chunk", type=int, default=4096)
    run_p.add_argument("--backend", default="auto", metavar="NAME",
                       help="auto | cpu | tpu | any registered jax platform name "
                            "(e.g. 'axon'); 'tpu' resolves to the machine's "
                            "accelerator whatever it registers as")
    run_p.add_argument("--profile", metavar="DIR", default=None,
                       help="capture a jax.profiler trace of the run into DIR "
                            "(view with tensorboard/xprof)")
    run_p.add_argument("--devices", type=int, default=None, metavar="N",
                       help="shard the cluster batch over the first N local devices "
                            "(trajectories are device-count invariant)")
    run_p.add_argument("--progress", action="store_true")
    run_p.add_argument("--trace-ticks", type=int, default=0,
                       help="print per-tick info lines for one cluster")
    run_p.add_argument("--trace-events", action="store_true",
                       help="print decoded state-change events for one cluster")
    run_p.add_argument("--trace-cluster", type=int, default=0)
    run_p.add_argument("--save", metavar="PATH", help="write a checkpoint at the end")
    run_p.add_argument("--resume", metavar="PATH", help="start from a checkpoint")
    run_p.add_argument("--apply-log", metavar="DIR", default=None,
                       help="stream one cluster's committed values to "
                            "DIR/node_<i>.log (the reference's per-node apply "
                            "file, log.clj:74-75)")
    run_p.add_argument("--apply-cluster", type=int, default=0,
                       help="cluster index --apply-log exports (default 0)")
    run_p.add_argument("--telemetry-dir", metavar="DIR", default=None,
                       help="write windowed fleet telemetry (manifest + "
                            "windows.jsonl, utils/telemetry_sink.py) and "
                            "flight recordings of violating clusters to DIR")
    run_p.add_argument("--telemetry-window", type=int, default=64, metavar="W",
                       help="ticks aggregated per telemetry window record "
                            "(default 64)")
    run_p.add_argument("--telemetry-ring", type=int, default=32, metavar="K",
                       help="flight-recorder depth: last K ticks of StepInfo "
                            "per cluster, frozen at the first violation "
                            "(0 disables; default 32)")
    run_p.add_argument("--trace", action="store_true",
                       help="protocol trace plane (raft_sim_tpu/trace; "
                            "requires --telemetry-dir): extract per-cluster "
                            "protocol events on device and stream them as "
                            "trace.jsonl for timeline rendering "
                            "(tools/metrics_report.py --trace) and "
                            "whole-history Raft safety checking "
                            "(python -m raft_sim_tpu.trace.checker DIR). "
                            "Sets cfg.track_trace; trajectories stay "
                            "bit-exact vs an untraced run")
    run_p.add_argument("--trace-depth", type=int, default=128, metavar="R",
                       help="events retained per cluster per telemetry "
                            "window (overflow is counted, the checker then "
                            "reports the history incomplete; default 128)")
    run_p.add_argument("--trace-freeze", metavar="KIND", default=None,
                       help="stop a cluster's trace recording after the "
                            "first event of KIND (e.g. 'leader', 'crash'; "
                            "default: record forever). Capture economy, not "
                            "checking: the whole-history checker reports a "
                            "freeze-truncated stream as undecided, never as "
                            "a pass")
    run_p.add_argument("--trace-trigger", metavar="KIND", default=None,
                       help="freeze the FLIGHT RECORDER on the first event "
                            "of KIND instead of the first violation -- "
                            "capture the lead-up to a non-violating anomaly "
                            "(implies cfg.track_trace; default: violation)")
    run_p.add_argument("--mutant", default=None, metavar="NAME",
                       help="TEST-ONLY: run a deliberately weakened kernel "
                            "(scenario/mutation.py registry, e.g. "
                            "'weak-quorum') -- the trace/checker CI smoke's "
                            "known-bad target")
    run_p.add_argument("--perf", action="store_true",
                       help="per-chunk runtime attribution (obs.ChunkTimer): "
                            "device-vs-host wall split, warmup vs steady "
                            "state, memory occupancy, jit-cache recompile "
                            "watchdog; streams perf.jsonl into "
                            "--telemetry-dir when given, and prints the "
                            "steady-state rollup either way. Host-side only: "
                            "trajectories and lowerings are untouched")
    run_p.add_argument("--health", nargs="?", const="default", default=None,
                       metavar="SPEC",
                       help="arm the fleet health plane (raft_sim_tpu/health; "
                            "requires --telemetry-dir): evaluate the SLO spec "
                            "(omit SPEC for the built-in default, or give a "
                            "JSON spec file) every eval period, streaming "
                            "health.jsonl + alerts.jsonl into the sink; "
                            "firing burn-rate alerts triage worst clusters "
                            "and freeze evidence bundles with live "
                            "flight-ring snapshots. Host-side only: "
                            "trajectories stay bit-exact vs an unmonitored "
                            "run")
    run_p.add_argument("--sanitize", action="store_true",
                       help="arm the donation-poison sanitizer "
                            "(analysis/sanitizer.py): every donating chunk "
                            "dispatch deletes its donated input buffers the "
                            "moment the outputs land, so any host "
                            "use-after-donate raises at the access site "
                            "instead of reading stale memory on a real "
                            "donating backend. Serializes the "
                            "dispatch->sync overlap (debug mode, not for "
                            "benchmarking); values stay bit-exact")
    _add_config_flags(run_p)

    sub.add_parser("presets", help="list the BASELINE config presets")

    serve_p = sub.add_parser(
        "serve",
        help="standing-fleet service loop: streamed client ingest between "
             "chunks, telemetry windows + commit deltas streamed out "
             "(docs/SERVE.md)",
    )
    serve_p.add_argument("--source", metavar="FILE", default="-",
                         help="JSONL command source: one command per line, a "
                              "bare int or {\"value\": v}; '-' = stdin "
                              "(default)")
    serve_p.add_argument("--preset", choices=sorted(PRESETS), default=None)
    serve_p.add_argument("--batch", type=int, default=None)
    serve_p.add_argument("--seed", type=int, default=None)
    serve_p.add_argument("--chunk", type=int, default=256,
                         help="ticks per device chunk (the ingest<->export "
                              "exchange cadence; default 256)")
    serve_p.add_argument("--window", type=int, default=64,
                         help="telemetry window ticks (must divide --chunk; "
                              "default 64)")
    serve_p.add_argument("--chunks", type=int, default=None,
                         help="stop after N chunks (default: run until the "
                              "source is exhausted + --drain-chunks)")
    serve_p.add_argument("--drain-chunks", type=int, default=4,
                         help="empty chunks run after source exhaustion so "
                              "trailing commits flush through the delta "
                              "stream (default 4)")
    serve_p.add_argument("--warmup", type=int, default=0, metavar="TICKS",
                         help="ticks simulated before the first offer (elect "
                              "leaders so early offers are not dropped)")
    serve_p.add_argument("--tenants", type=int, default=None, metavar="N",
                         help="partition the fleet's cluster range among N "
                              "logical tenants (per-tenant sources, sinks, "
                              "and read demands; one compiled program at "
                              "any N -- serve/tenancy.py). The command "
                              "stream is sharded round-robin")
    serve_p.add_argument("--reads-per-tenant", type=int, default=0,
                         metavar="R",
                         help="ReadIndex reads each tenant must get served "
                              "(re-offered until acked; requires a "
                              "read-carrying config, e.g. --preset config9)")
    serve_p.add_argument("--delta-depth", type=int, default=64,
                         help="per-cluster commit-delta buffer depth per "
                              "extraction round (backpressure bound, not a "
                              "loss bound; default 64)")
    serve_p.add_argument("--sink", metavar="DIR", default=None,
                         help="stream telemetry windows (windows.jsonl) and "
                              "commit deltas (deltas.jsonl) to DIR under the "
                              "telemetry sink schema")
    serve_p.add_argument("--backend", default="auto", metavar="NAME")
    serve_p.add_argument("--progress", action="store_true")
    serve_p.add_argument("--perf", action="store_true",
                         help="per-chunk runtime attribution of the serving "
                              "loop (dispatch / ingest-pack host gap / "
                              "device wait; jit-cache watchdog); streams "
                              "perf.jsonl into --sink when given")
    serve_p.add_argument("--health", nargs="?", const="default", default=None,
                         metavar="SPEC",
                         help="arm fleet + per-tenant health monitoring "
                              "(raft_sim_tpu/health; requires --sink): one "
                              "SLO evaluator per scope streams health.jsonl "
                              "+ alerts.jsonl, prints live status "
                              "transitions, and freezes evidence bundles on "
                              "firing burn-rate alerts. Omit SPEC for the "
                              "built-in default, or give a JSON spec file")
    serve_p.add_argument("--profile", metavar="DIR", default=None,
                         help="capture a jax.profiler trace of the serving "
                              "session into DIR (view with tensorboard/"
                              "xprof); capture is bit-exact vs no capture "
                              "(tier-1 pinned)")
    serve_p.add_argument("--sanitize", action="store_true",
                         help="arm the donation-poison sanitizer over the "
                              "serving loop (analysis/sanitizer.py): late "
                              "host access to a donated carry raises at the "
                              "access site. Serializes the serve overlap "
                              "(debug mode); stats stay bit-exact")
    _add_config_flags(serve_p)

    sc = sub.add_parser(
        "scenario",
        help="adversarial scenario engine: phased nemesis runs, the "
             "violation-hunting search, and hit shrinking (docs/SCENARIOS.md)",
    )
    ssub = sc.add_subparsers(dest="scmd", required=True)

    srun = ssub.add_parser("run", help="run a fleet under a JSON nemesis program")
    srun.add_argument("--scenario", metavar="FILE", default=None,
                      help="declarative scenario file (scenario/program.py schema)")
    srun.add_argument("--preset", choices=sorted(PRESETS), default=None)
    srun.add_argument("--batch", type=int, default=None)
    srun.add_argument("--ticks", type=int, default=1000)
    srun.add_argument("--seed", type=int, default=None)
    srun.add_argument("--chunk", type=int, default=4096)
    srun.add_argument("--backend", default="auto", metavar="NAME")
    srun.add_argument("--progress", action="store_true")
    srun.add_argument("--save", metavar="PATH",
                      help="checkpoint at the end (records the scenario; "
                           "format v20)")
    srun.add_argument("--resume", metavar="PATH",
                      help="resume a scenario checkpoint (restores the genome "
                           "path; plain checkpoints are rejected)")
    _add_config_flags(srun)

    ssearch = ssub.add_parser(
        "search", help="cross-entropy hunt for violating fault genomes"
    )
    ssearch.add_argument("--preset", choices=sorted(PRESETS), default=None)
    # build_config reads args.batch; the search population IS the batch.
    ssearch.add_argument("--batch", type=int, default=None, help=argparse.SUPPRESS)
    ssearch.add_argument("--mutant", default=None, metavar="NAME",
                         help="TEST-ONLY: hunt a deliberately weakened kernel "
                              "(scenario/mutation.py registry, e.g. "
                              "'weak-quorum') to prove the hunt hunts")
    ssearch.add_argument("--generations", type=int, default=8)
    ssearch.add_argument("--population", type=int, default=64,
                         help="genomes per generation = fleet batch size")
    ssearch.add_argument("--ticks", type=int, default=512)
    ssearch.add_argument("--window", type=int, default=64,
                         help="telemetry window (fitness resolution)")
    ssearch.add_argument("--elite-frac", type=float, default=0.25)
    ssearch.add_argument("--fitness", choices=("scalar", "coverage"),
                         default="scalar",
                         help="fitness mode: 'scalar' = the hand-tuned "
                              "distress weights; 'coverage' = transition-"
                              "coverage novelty from the protocol trace "
                              "plane (newly set role x event-kind and "
                              "kind->kind bits across the fleet; violations "
                              "stay dominant) -- one compiled trace-variant "
                              "program for the whole hunt")
    ssearch.add_argument("--trace-depth", type=int, default=32, metavar="R",
                         help="coverage mode's per-window event-buffer depth "
                              "(the bitmap needs no deep buffer; default 32)")
    ssearch.add_argument("--proposal", choices=("gaussian", "coverage-guided"),
                         default="gaussian",
                         help="proposal mode: 'gaussian' = classic CE draws; "
                              "'coverage-guided' = mutate the previous "
                              "generation's novelty-lit parents (requires "
                              "--fitness=coverage) -- coverage-guided "
                              "MUTATION, not just coverage-as-fitness")
    ssearch.add_argument("--seed", type=int, default=None)
    ssearch.add_argument("--backend", default="auto", metavar="NAME")
    ssearch.add_argument("--out", metavar="FILE", default=None,
                         help="write the first violating hit (replayable; "
                              "feeds `scenario shrink --hit`)")
    ssearch.add_argument("--profile", metavar="DIR", default=None,
                         help="capture a jax.profiler trace of the hunt into "
                              "DIR (view with tensorboard/xprof); capture is "
                              "bit-exact vs no capture (tier-1 pinned)")
    _add_config_flags(ssearch)

    sfarm = ssub.add_parser(
        "farm",
        help="the fuzzing farm: portfolio hunts, coverage-guided mutation, "
             "and the self-growing safety corpus (raft_sim_tpu/farm; "
             "docs/SCENARIOS.md 'Running the farm')",
    )
    sfarm.add_argument("--preset", choices=sorted(PRESETS), default=None)
    # build_config reads args.batch; the farm population IS the batch.
    sfarm.add_argument("--batch", type=int, default=None, help=argparse.SUPPRESS)
    sfarm.add_argument("--mutant", default=None, metavar="NAME",
                       help="TEST-ONLY: hunt a deliberately weakened kernel "
                            "(scenario/mutation.py registry)")
    sfarm.add_argument("--portfolio", default="scalar,coverage",
                       metavar="M1,M2,...",
                       help="comma list of fitness members hunted in "
                            "parallel over disjoint slices of the fleet "
                            "(farm/portfolio.py registry: scalar, coverage, "
                            "multi_leader, commit_stall, read_staleness, "
                            "durability; "
                            "default scalar,coverage)")
    sfarm.add_argument("--budget-gens", type=int, default=8,
                       help="generation budget; exhausting it hitless pins "
                            "a negative result (out-dir/negative.json)")
    sfarm.add_argument("--population", type=int, default=64,
                       help="fleet batch, split among the members; under "
                            "--mesh this is the PER-DEVICE population (the "
                            "total scales with the device count)")
    sfarm.add_argument("--mesh", type=int, default=None, metavar="D",
                       help="shard each generation over D devices (0 = all "
                            "available): one shard_map'ped evaluation per "
                            "generation, bit-identical hits at any device "
                            "count (parallel.simulate_windowed_sharded)")
    sfarm.add_argument("--ticks", type=int, default=512)
    sfarm.add_argument("--window", type=int, default=64,
                       help="telemetry window (fitness resolution)")
    sfarm.add_argument("--elite-frac", type=float, default=0.25)
    sfarm.add_argument("--trace-depth", type=int, default=32, metavar="R")
    sfarm.add_argument("--no-guided", action="store_true",
                       help="disable coverage-guided mutation (pure "
                            "per-member CE; a trace-free portfolio then "
                            "runs the untraced program)")
    sfarm.add_argument("--stop-on", choices=("hit", "frozen", "budget"),
                       default="hit",
                       help="early-stop policy: first processed hit "
                            "(default), first NEWLY FROZEN artifact "
                            "(dedup-rejected re-finds keep hunting), or "
                            "never (run the whole budget)")
    sfarm.add_argument("--seed", type=int, default=None)
    sfarm.add_argument("--out-dir", metavar="DIR", required=True,
                       help="farm output: farm_manifest.json, "
                            "members/<name>/hunt.jsonl, perf.jsonl, "
                            "negative.json on a hitless budget")
    sfarm.add_argument("--corpus-dir", metavar="DIR", default=None,
                       help="arm the auto-corpus policy against DIR "
                            "(hits are shrunk + dedup'd by (kernel, kinds, "
                            "mechanism-set) signature; e.g. tests/corpus)")
    sfarm.add_argument("--freeze", action="store_true",
                       help="let the farm WRITE new checker-gated, "
                            "provenance-stamped artifacts into --corpus-dir")
    sfarm.add_argument("--health", nargs="?", const="default", default=None,
                       metavar="SPEC",
                       help="arm health monitoring over the hunt fleet "
                            "(raft_sim_tpu/health): each generation's window "
                            "records feed the SLO evaluator, streaming "
                            "health.jsonl + alerts.jsonl into --out-dir "
                            "(safety alerts fire immediately on a violating "
                            "generation). Omit SPEC for the default spec")
    sfarm.add_argument("--backend", default="auto", metavar="NAME")
    sfarm.add_argument("--profile", metavar="DIR", default=None,
                       help="capture a jax.profiler trace of the farm into "
                            "DIR (view with tensorboard/xprof)")
    _add_config_flags(sfarm)

    sshrink = ssub.add_parser(
        "shrink", help="minimize a search hit to a repro artifact"
    )
    sshrink.add_argument("--hit", metavar="FILE", required=True,
                         help="hit file from `scenario search --out`")
    sshrink.add_argument("--out", metavar="FILE", required=True,
                         help="repro artifact path (tools/repro.py --scenario)")
    sshrink.add_argument("--halving-rounds", type=int, default=3)
    sshrink.add_argument("--context", type=int, default=30)
    sshrink.add_argument("--backend", default="auto", metavar="NAME")

    args = ap.parse_args(argv)

    if args.cmd == "scenario":
        select_backend(args.backend)
        return {
            "run": _scenario_run,
            "search": _scenario_search,
            "farm": _scenario_farm,
            "shrink": _scenario_shrink,
        }[args.scmd](args, ap)

    if args.cmd == "serve":
        select_backend(args.backend)
        return _serve(args, ap)

    if args.cmd == "presets":
        for name, (cfg, batch) in sorted(PRESETS.items()):
            print(f"{name}: batch={batch} {cfg}")
        return 0

    select_backend(args.backend)
    if args.resume:
        # A checkpoint IS the config; silently rerunning it under different flags
        # would mislabel the results.
        conflicting = [
            f.name for f in dataclasses.fields(RaftConfig)
            if getattr(args, f.name) is not None
        ]
        if args.preset:
            conflicting.append("preset")
        if args.batch is not None:
            conflicting.append("batch")
        if args.seed is not None:
            conflicting.append("seed")  # the checkpoint carries its own seed
        if args.mutant:
            conflicting.append("mutant")
        if args.trace or args.trace_trigger or args.trace_freeze:
            conflicting.append("trace")  # track_trace is part of the config
        if conflicting:
            ap.error(f"--resume is exclusive with config flags: {', '.join(conflicting)}")
        # Checkpoint problems (bad path, stale format) surface as real errors;
        # only --devices misuse gets the argparse usage-error framing.
        sess = Session.restore(args.resume)
        if args.devices is not None:
            try:
                sess.devices = args.devices
                sess._apply_sharding()
            except ValueError as ex:
                ap.error(str(ex))
    else:
        cfg, batch = build_config(args)
        if args.mutant:
            from raft_sim_tpu.scenario.mutation import mutant_config

            try:
                cfg = mutant_config(args.mutant, cfg)
            except ValueError as ex:
                ap.error(str(ex))
        if args.trace or args.trace_trigger or args.trace_freeze:
            # --trace-trigger / --trace-freeze imply the trace plane: both
            # are meaningless without the extracted event stream, so an
            # explicitly set kind must never be silently dropped.
            if not args.telemetry_dir:
                ap.error("--trace/--trace-trigger/--trace-freeze need "
                         "--telemetry-dir (trace windows stream through the "
                         "telemetry sink)")
            cfg = dataclasses.replace(cfg, track_trace=True)
        try:
            sess = Session(
                cfg,
                batch=batch,
                seed=args.seed if args.seed is not None else 0,
                devices=args.devices,
            )
        except ValueError as ex:
            ap.error(str(ex))

    if args.trace_ticks or args.trace_events:
        if (args.save or args.profile or args.apply_log or args.telemetry_dir
                or args.perf or args.health):
            ap.error("--save/--profile/--apply-log/--telemetry-dir/--perf/"
                     "--health have no effect with --trace-ticks/"
                     "--trace-events (tracing does not advance the session)")
        n = args.trace_ticks or args.ticks
        infos, states = sess.trace(n, cluster=args.trace_cluster)
        if args.trace_events:
            for t, ev in trace.events(states):
                print(f"tick {t:>6}  {ev}")
        else:
            for line in trace.info_lines(infos):
                print(line)
        return 0

    if args.apply_log:
        try:
            sess.attach_apply_log(args.apply_log, cluster=args.apply_cluster)
        except IndexError as ex:
            ap.error(str(ex))

    if args.telemetry_dir:
        try:
            sess.attach_telemetry(
                args.telemetry_dir,
                window=args.telemetry_window,
                ring=args.telemetry_ring,
            )
        except ValueError as ex:
            ap.error(str(ex))
        if args.trace or args.trace_trigger or args.trace_freeze:
            # --trace-trigger/--trace-freeze imply the trace plane: their
            # predicates are computed from the same extracted events the
            # stream exports.
            try:
                sess.attach_trace(
                    depth=args.trace_depth,
                    freeze=args.trace_freeze,
                    trigger=args.trace_trigger,
                )
            except ValueError as ex:
                ap.error(str(ex))

    if args.perf:
        # After attach_telemetry so perf.jsonl streams into the same sink
        # directory; without --telemetry-dir the rows stay in memory and
        # only the steady-state rollup is printed.
        sess.attach_perf()

    if args.health:
        if not args.telemetry_dir:
            ap.error("--health needs --telemetry-dir (the health/alert "
                     "streams ride the telemetry sink directory; the "
                     "sink-free plain path is the Session.attach_health "
                     "API's directory= form)")
        try:
            sess.attach_health(args.health)
        except ValueError as ex:
            ap.error(str(ex))

    t0 = time.perf_counter()
    with _profile_ctx(args.profile), _sanitize_ctx(args) as san:
        sess.run(args.ticks, chunk=args.chunk, progress=args.progress)
        # Time to the host-side rollup, not block_until_ready: this TPU stack's
        # block can return before execution finishes (see bench.py docstring);
        # summary()'s device_get provably waits for real data.
        out = sess.summary()
    dt = time.perf_counter() - t0
    _sanitize_report(args, san)
    out["wall_s"] = round(dt, 3)
    out["cluster_ticks_per_s"] = round(sess.batch * args.ticks / dt, 1)
    if args.perf:
        # Steady-state attribution rollup + the recompile-watchdog finding
        # (finish() prints it to stderr if a steady-state chunk compiled).
        out["perf"] = sess.perf.finish()
    if sess.health is not None:
        # Trailing partial eval period included; the rollup names every
        # objective that fired so a scripted run can gate on it.
        out["health"] = sess.health.finalize()
        print(sess.health.status_line(), file=sys.stderr)
    print(json.dumps(out))

    if args.telemetry_dir:
        fin = sess.finalize_telemetry()
        if fin["flights"]:
            print(
                f"telemetry: flight recordings exported for clusters "
                f"{fin['flights']} under {args.telemetry_dir}",
                file=sys.stderr,
            )

    if args.save:
        sess.save(args.save)
    return 0


if __name__ == "__main__":
    sys.exit(main())
