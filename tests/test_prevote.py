"""PreVote (cfg.pre_vote; Raft thesis 9.6) -- BEYOND the reference, which has
neither pre-vote nor leadership transfer (SURVEY.md 2.3.12).

The property pre-vote exists for: a node partitioned away keeps timing out, but
its probes are denied by peers who still hear their leader, so its TERM NEVER
INFLATES -- and when the partition heals it rejoins as a follower instead of
deposing a healthy leader with a giant term. Without pre-vote the same scenario
forces a gratuitous re-election on heal.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_sim_tpu import RaftConfig, StepInputs, init_state
from raft_sim_tpu.models import raft
from raft_sim_tpu.ops import bitplane
from raft_sim_tpu.sim import scan
from raft_sim_tpu.types import (
    CANDIDATE,
    LEADER,
    NIL,
    PRECANDIDATE,
    REQ_PREVOTE,
    REQ_VOTE,
    RESP_PREVOTE,
)
from tests.test_handlers import base_state, make_leader, quiet_inputs, step

CFG = RaftConfig(n_nodes=5, log_capacity=8, pre_vote=True)


def isolate(cfg, node, far=1000):
    """Inputs with `node` partitioned away from everyone (both directions)."""
    n = cfg.n_nodes
    mask = jnp.ones((n, n), bool).at[node, :].set(False).at[:, node].set(False)
    return quiet_inputs(cfg, far=far, deliver=mask)


# -------------------------------------------------------------- grant/deny rules


def pv_wire(s, src, term_prospective, last_idx=0, last_term=0):
    """Broadcast a PreVote probe from `src` carrying its prospective term."""
    mb = s.mailbox._replace(
        req_type=s.mailbox.req_type.at[src].set(REQ_PREVOTE),
        req_term=s.mailbox.req_term.at[src].set(term_prospective),
        req_last_index=s.mailbox.req_last_index.at[src].set(last_idx),
        req_last_term=s.mailbox.req_last_term.at[src].set(last_term),
    )
    return s._replace(mailbox=mb)


def pv_resp_of(mb, q, r):
    """(responded, granted) for the pre-vote response edge [q, r]: the type
    rides resp_kind, the grant bit the packed pv_grant plane."""
    kind = int(mb.resp_kind[q, r])
    return kind == RESP_PREVOTE, bool(bitplane.get_bit(mb.pv_grant, q, r))


def test_quiet_voter_grants_probe_without_adopting_term():
    s = base_state(CFG)  # heard_clock init: quiet from boot
    s2, _ = step(CFG, pv_wire(s, 0, term_prospective=2))
    responded, granted = pv_resp_of(s2.mailbox, 0, 1)
    assert responded and granted
    assert int(s2.term[1]) == 1  # the prospective term is NOT adopted
    assert int(s2.voted_for[1]) == NIL  # grants are non-binding


def test_voter_who_hears_a_leader_denies_probe():
    """The thesis-9.6 denial: a voter with recent leader contact refuses."""
    s = base_state(CFG)
    s = s._replace(heard_clock=s.heard_clock.at[1].set(0))  # heard at clock 0
    s2, _ = step(CFG, pv_wire(s, 0, term_prospective=2))
    responded, granted = pv_resp_of(s2.mailbox, 0, 1)
    assert responded and not granted
    # ... while a long-quiet peer still grants on the same tick.
    responded3, granted3 = pv_resp_of(s2.mailbox, 0, 3)
    assert responded3 and granted3


def test_leader_denies_probe():
    s = make_leader(base_state(CFG), 2, 1)
    s2, _ = step(CFG, pv_wire(s, 0, term_prospective=2))
    responded, granted = pv_resp_of(s2.mailbox, 0, 2)
    assert responded and not granted


def test_stale_log_denied_probe():
    from tests.test_handlers import with_log

    s = with_log(base_state(CFG), 1, [1, 1])  # voter's log is ahead
    s2, _ = step(CFG, pv_wire(s, 0, term_prospective=2, last_idx=0, last_term=0))
    responded, granted = pv_resp_of(s2.mailbox, 0, 1)
    assert responded and not granted


def test_pre_quorum_promotes_to_real_candidate():
    """A precandidate holding grant bits from a majority promotes: only then
    does the term bump and a real RequestVote broadcast go out."""
    s = base_state(CFG)
    s = s._replace(
        role=s.role.at[0].set(PRECANDIDATE),
        votes=s.votes.at[0].set(
            bitplane.pack(
                jnp.zeros((5,), bool).at[0].set(True).at[1].set(True).at[2].set(True)
            )
        ),
    )
    s2, _ = step(CFG, s)
    assert int(s2.role[0]) == CANDIDATE
    assert int(s2.term[0]) == 2  # bumped at promotion, not before
    assert int(s2.voted_for[0]) == 0
    assert int(s2.mailbox.req_type[0]) == REQ_VOTE
    assert int(s2.mailbox.req_term[0]) == 2


def test_expiry_starts_probe_not_election():
    s = base_state(CFG)._replace(deadline=jnp.zeros((5,), jnp.int32).at[0].set(0))
    s = s._replace(deadline=s.deadline.at[1].set(1000).at[2].set(1000).at[3].set(1000).at[4].set(1000))
    s2, _ = step(CFG, s)
    assert int(s2.role[0]) == PRECANDIDATE
    assert int(s2.term[0]) == 1  # unchanged
    assert int(s2.mailbox.req_type[0]) == REQ_PREVOTE
    assert int(s2.mailbox.req_term[0]) == 2  # prospective


# -------------------------------------------------------- the disruption property


def _run(cfg, s, inputs, ticks):
    st = jax.jit(lambda s_, i_: raft.step(cfg, s_, i_), static_argnums=())
    for _ in range(ticks):
        s, _ = st(s, inputs)
    return s


@pytest.mark.slow
def test_partitioned_node_cannot_depose_a_stable_leader():
    """The headline behavior: isolate one node under a stable leader for a long
    time, then heal. With pre_vote its term never inflates and the leader
    survives the heal; without, the rejoiner's inflated term forces the leader
    out (term adoption -> step down). Slow tier (long eager isolate/heal
    loops both ways; the handler-level probe tests above and the prevote
    parity/fuzz tiers stay tier-1)."""
    for pre_vote, disruptive in ((True, False), (False, True)):
        cfg = RaftConfig(n_nodes=5, log_capacity=8, pre_vote=pre_vote)
        s = init_state(cfg, jax.random.key(0))
        # Elect a stable leader with everyone connected.
        fin, _, _ = scan.run(cfg, s, jax.random.key(1), 60)
        leader = int(np.argmax(np.asarray(fin.role) == LEADER))
        assert int(np.sum(np.asarray(fin.role) == LEADER)) == 1
        victim = (leader + 1) % 5
        lead_term = int(fin.term[leader])
        # Isolate the victim long enough for many timeout cycles.
        s_iso = _run(cfg, fin, isolate(cfg, victim, far=9), 120)
        if pre_vote:
            assert int(s_iso.term[victim]) == lead_term  # term never inflated
        else:
            assert int(s_iso.term[victim]) > lead_term + 3  # classic inflation
        # Heal and run on: does the established leader survive?
        healed = _run(cfg, s_iso, quiet_inputs(cfg, far=9)._replace(
            timeout_draw=jnp.full((5,), 9, jnp.int32)), 12)
        still_leader = int(healed.role[leader]) == LEADER
        assert still_leader == (not disruptive)
        if pre_vote:
            assert int(np.max(np.asarray(healed.term))) == lead_term


def test_prevote_cluster_elects_and_commits():
    """Liveness from cold start: pre-vote rounds still elect, client commands
    still commit, invariants hold, and terms stay minimal (one probe round +
    one real election = term 2)."""
    cfg = RaftConfig(n_nodes=5, client_interval=8, pre_vote=True)
    _, m = scan.simulate(cfg, 0, 64, 400)
    md = jax.device_get(m)
    assert int(md.violations.sum()) == 0
    assert int((md.first_leader_tick < 2**31 - 1).sum()) == 64
    assert int(md.min_commit.min()) > 0
    assert int(md.max_term.max()) <= 3  # no term churn on a reliable net


def test_prevote_under_partition_fuzz_is_safe():
    cfg = RaftConfig(
        n_nodes=5, partition_period=32, partition_prob=0.5, pre_vote=True,
        check_log_matching=True, client_interval=8,
    )
    _, m = scan.simulate(cfg, 0, 48, 400)
    md = jax.device_get(m)
    assert int(md.violations.sum()) == 0
    assert int((md.first_leader_tick < 2**31 - 1).sum()) > 40
