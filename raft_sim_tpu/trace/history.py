"""Host-side history reconstruction: exported trace windows -> per-cluster
timelines.

The device side (ring.py) exports one bounded event buffer per telemetry
window; this module is the other half of the contract: it decodes those
buffers (straight off the device or back out of a sink directory's
trace.jsonl) into per-cluster event TIMELINES with an explicit completeness
verdict. Completeness is load-bearing: the checker (trace/checker.py) must
never pass vacuously on a history with holes, so every reconstruction tracks
per-cluster dropped-event counts (window overflow), window contiguity, and
per-cluster tick monotonicity, and `History.complete` is False the moment
any of them fails.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Iterator, NamedTuple

import numpy as np

from raft_sim_tpu.trace import events as tev


class Event(NamedTuple):
    tick: int
    node: int  # NIL (-1) = cluster-scope
    kind: int  # EV_* (trace/events.py)
    detail: int

    def to_dict(self, cluster: int | None = None) -> dict:
        d = {
            "tick": self.tick,
            "node": self.node,
            "kind": tev.KIND_NAMES.get(self.kind, str(self.kind)),
            "detail": self.detail,
        }
        if cluster is not None:
            d["cluster"] = cluster
        return d


@dataclasses.dataclass
class History:
    """Per-cluster event timelines plus the completeness facts about them."""

    events: dict[int, list[Event]]  # cluster -> events, (tick, slot) order
    emitted: dict[int, int]  # cluster -> events emitted on device
    dropped: dict[int, int]  # cluster -> events lost to window overflow
    n_windows: int
    problems: list[str]  # ordering/contiguity defects found while loading
    # A freeze_kind was armed (TraceSpec / trace_meta.json): recording stops
    # per cluster after the chosen event, so the history is a DELIBERATE
    # prefix -- fine for capture economy, but the checker must still refuse
    # to pass it as a whole-run verdict (ticks stay monotone and nothing
    # counts as dropped, so this flag is the only trace of the truncation).
    freeze_armed: bool = False

    @property
    def complete(self) -> bool:
        """True iff every cluster's full event stream is present and in
        order -- the precondition for a checker PASS (a violation found in a
        partial history is still a violation; a pass needs the whole story).
        Freeze-armed streams are by-design prefixes: never complete."""
        return (not self.problems and not any(self.dropped.values())
                and not self.freeze_armed)

    def incomplete_clusters(self) -> list[int]:
        return sorted(c for c, d in self.dropped.items() if d)


def iter_window_events(traws) -> Iterator[tuple[int, int, list[Event]]]:
    """Decode a batch-minor stacked TraceWindowOut (leaves [W, R, B] / [W, B])
    into (window_index, cluster, events) triples, clusters with events only.
    Slot order within a window IS event order (ring.py clamps, never wraps)."""
    win = traws.win
    tick = np.asarray(win.ev_tick)
    node = np.asarray(win.ev_node)
    kind = np.asarray(win.ev_kind)
    detail = np.asarray(win.ev_detail)
    n = np.asarray(win.n)
    n_windows, depth, batch = tick.shape
    for w in range(n_windows):
        for c in range(batch):
            kept = int(min(n[w, c], depth))
            if not kept:
                continue
            evs = [
                Event(int(tick[w, i, c]), int(node[w, i, c]),
                      int(kind[w, i, c]), int(detail[w, i, c]))
                for i in range(kept)
            ]
            yield w, c, evs


def from_device(traws, spec=None) -> History:
    """Build a History straight from one run's stacked trace windows (the
    in-memory path tests and the search use; production runs go through the
    sink and `load`). Pass the run's TraceSpec so a freeze-armed capture is
    marked as the deliberate prefix it is."""
    n = np.asarray(traws.win.n)
    n_windows, b = n.shape
    depth = np.asarray(traws.win.ev_kind).shape[1]
    events: dict[int, list[Event]] = {c: [] for c in range(b)}
    for _, c, evs in iter_window_events(traws):
        events[c].extend(evs)
    emitted = {c: int(n[:, c].sum()) for c in range(b)}
    dropped = {
        c: int(np.maximum(n[:, c] - depth, 0).sum()) for c in range(b)
    }
    return History(
        events=events, emitted=emitted, dropped=dropped,
        n_windows=n_windows, problems=[],
        freeze_armed=bool(spec is not None and spec.freeze_kind),
    )


def load(directory: str) -> History:
    """Rebuild a History from a sink directory's trace stream (trace.jsonl +
    trace_windows.jsonl, utils/telemetry_sink.py). Defects -- unparseable
    lines, non-contiguous window indices, per-cluster tick regressions
    (truncated or reordered files) -- are collected as `problems`, making the
    history incomplete rather than silently droppable."""
    problems: list[str] = []
    events: dict[int, list[Event]] = {}
    emitted: dict[int, int] = {}
    dropped: dict[int, int] = {}
    wpath = os.path.join(directory, "trace_windows.jsonl")
    epath = os.path.join(directory, "trace.jsonl")
    n_windows = 0
    prev_w = -1
    freeze_armed = False
    meta_path = os.path.join(directory, "trace_meta.json")
    if os.path.isfile(meta_path):
        try:
            with open(meta_path) as f:
                freeze_armed = bool(json.load(f).get("freeze_kind"))
        except (OSError, json.JSONDecodeError) as ex:
            problems.append(f"trace_meta.json unreadable: {ex}")
    if os.path.isfile(wpath):
        with open(wpath) as f:
            for ln, raw in enumerate(f, 1):
                try:
                    row = json.loads(raw)
                except json.JSONDecodeError as ex:
                    problems.append(f"trace_windows.jsonl:{ln}: not JSON: {ex}")
                    continue
                w = row.get("window")
                if not isinstance(w, int) or w != prev_w + 1:
                    problems.append(
                        f"trace_windows.jsonl:{ln}: window index {w!r} "
                        f"(expected {prev_w + 1}) -- stream truncated or "
                        "reordered"
                    )
                if isinstance(w, int):
                    prev_w = w
                n_windows += 1
                for c, d in (row.get("dropped_by_cluster") or {}).items():
                    dropped[int(c)] = dropped.get(int(c), 0) + int(d)
    else:
        problems.append("missing trace_windows.jsonl")
    if not os.path.isfile(epath):
        problems.append("missing trace.jsonl")
        return History(events, emitted, dropped, n_windows, problems,
                       freeze_armed)
    last_tick: dict[int, int] = {}
    with open(epath) as f:
        for ln, raw in enumerate(f, 1):
            try:
                row = json.loads(raw)
                c, t = int(row["c"]), int(row["t"])
                e = Event(t, int(row["node"]), int(row["k"]), int(row["d"]))
            except (json.JSONDecodeError, KeyError, TypeError, ValueError) as ex:
                problems.append(f"trace.jsonl:{ln}: bad event line: {ex}")
                continue
            if t < last_tick.get(c, -1):
                problems.append(
                    f"trace.jsonl:{ln}: cluster {c} tick {t} after tick "
                    f"{last_tick[c]} -- out-of-order or spliced stream"
                )
            last_tick[c] = max(last_tick.get(c, -1), t)
            events.setdefault(c, []).append(e)
            emitted[c] = emitted.get(c, 0) + 1
    # emitted-on-device counts include dropped events; file counts do not.
    for c, d in dropped.items():
        emitted[c] = emitted.get(c, 0) + d
    return History(events, emitted, dropped, n_windows, problems, freeze_armed)


def timeline_lines(hist: History, cluster: int, every: int = 1) -> Iterator[str]:
    """Render one cluster's timeline as human-readable lines (the
    metrics_report --trace view)."""
    for i, e in enumerate(hist.events.get(cluster, [])):
        if i % every:
            continue
        yield (
            f"tick {e.tick:>6}  "
            f"{'cluster' if e.node < 0 else f'node {e.node}':<8} "
            f"{tev.KIND_NAMES.get(e.kind, str(e.kind)):<12} {e.detail}"
        )


def chrome_trace(hist: History, clusters=None) -> dict:
    """Export histories as Chrome-trace / Perfetto JSON: one process per
    cluster, one track (tid) per node (cluster-scope events on a 'cluster'
    track), instant events named by kind -- opens in ui.perfetto.dev or
    chrome://tracing next to the --profile captures (PR 8)."""
    out = []
    sel = sorted(hist.events) if clusters is None else list(clusters)
    for c in sel:
        evs = hist.events.get(c, [])
        nodes = sorted({e.node for e in evs})
        for nd in nodes:
            out.append({
                "name": "thread_name", "ph": "M", "pid": c,
                "tid": nd + 1,
                "args": {"name": "cluster" if nd < 0 else f"node {nd}"},
            })
        for e in evs:
            out.append({
                "name": tev.KIND_NAMES.get(e.kind, str(e.kind)),
                "ph": "i",
                "s": "t",
                "ts": e.tick * 1000,  # 1 tick = 1ms, readable zoom levels
                "pid": c,
                "tid": e.node + 1,
                "args": {"detail": e.detail},
            })
    return {"traceEvents": out, "displayTimeUnit": "ms"}
