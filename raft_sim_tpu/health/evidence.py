"""Evidence bundles: a firing alert captures its own forensics.

The repo's post-hoc planes (flight recorder, protocol traces, perf rows)
answer "what happened" only if someone was already recording the right
cluster. A firing alert closes that loop: the monitor snapshots the live
flight-recorder ring for each named cluster (host-side ring read -- the
device carry is untouched), gathers the evaluation period's per-cluster
window rows and perf rows, and freezes them under the telemetry directory:

    evidence_NNNN/
      alert.json        the alert row + its objective spec + run refs
                        (config_hash/seed/checkpoint) + a file inventory
      windows.jsonl     per-(cluster, window) counters for the named
                        clusters over the firing eval period
      perf.jsonl        the period's runtime attribution rows, verbatim
      flight_<c>.jsonl  per-tick StepInfo snapshot of cluster c's ring at
                        alert time (same line schema as the sink's
                        violation flights -- metrics_report renders both)

`tools/metrics_report.py --health` renders a directory's alerts with their
bundles end to end; validate_bundle is the dependency-free schema check,
folded into telemetry_sink.validate for any evidence dir an alert names.
"""

from __future__ import annotations

import json
import os

import numpy as np

EVIDENCE_SCHEMA = "health-evidence-v1"

# Required integer fields of an evidence windows.jsonl row (per cluster per
# window -- unlike the sink's fleet-aggregated stream, these keep the
# cluster axis: the whole point is per-culprit forensics).
EVIDENCE_WINDOW_FIELDS = (
    "window", "start", "ticks", "cluster", "violations", "cmds", "reads",
    "lat_cnt", "lat_sum", "fsync_lag_sum", "fsync_lag_max",
)


def window_rows_for(units: list[dict], clusters: list[int],
                    first_window: int, cluster_base: int = 0) -> list[dict]:
    """Per-(cluster, window) evidence rows for the named clusters out of one
    eval period's window units (cluster ids are fleet-global; units are
    indexed locally from cluster_base)."""
    rows = []
    for w, u in enumerate(units):
        for c in clusters:
            i = c - cluster_base
            if not 0 <= i < len(u["violations"]):
                continue
            rows.append({
                "window": first_window + w,
                "start": int(u["start"]),
                "ticks": int(u["ticks"]),
                "cluster": int(c),
                "violations": int(u["violations"][i]),
                "leaderless": bool(u["leaderless"][i]),
                "cmds": int(u["cmds"][i]),
                "reads": int(u["reads"][i]),
                "lat_cnt": int(u["lat_cnt"][i]),
                "lat_sum": int(u["lat_sum"][i]),
                "lat_hist": [int(x) for x in np.asarray(u["lat_hist"][i])],
                "fsync_lag_sum": int(u["fsync_lag_sum"][i]),
                "fsync_lag_max": int(u["fsync_lag_max"][i]),
            })
    return rows


def write_bundle(
    directory: str,
    alert: dict,
    objective: dict,
    window_rows: list[dict],
    perf_rows: list[dict],
    flights: dict | None = None,
    refs: dict | None = None,
) -> str:
    """Write one bundle. `flights` maps global cluster id -> (ticks, StepInfo)
    as returned by telemetry.export_cluster; `refs` carries run identity
    (config_hash, seed, checkpoint path...). Returns the directory.

    Everything handed in must already be HOST data: the capture hooks run
    inside the standing loops' chunk callbacks, where the device carry is
    only valid until the callback returns (and is deleted outright under the
    donation-poison sanitizer). export_cluster/device_get at capture time is
    the contract Pass D's use-after-donate lint enforces on the callers."""
    from raft_sim_tpu.utils.telemetry_sink import flight_lines

    os.makedirs(directory, exist_ok=True)
    files = ["alert.json", "windows.jsonl", "perf.jsonl"]
    with open(os.path.join(directory, "windows.jsonl"), "w") as f:
        for row in window_rows:
            f.write(json.dumps(row) + "\n")
    with open(os.path.join(directory, "perf.jsonl"), "w") as f:
        for row in perf_rows:
            f.write(json.dumps(row) + "\n")
    for c, (ticks, infos) in sorted((flights or {}).items()):
        name = f"flight_{c}.jsonl"
        with open(os.path.join(directory, name), "w") as f:
            for line in flight_lines(ticks, infos):
                f.write(json.dumps(line) + "\n")
        files.append(name)
    doc = {
        "schema": EVIDENCE_SCHEMA,
        "alert": alert,
        "objective": objective,
        "refs": refs or {},
        "files": sorted(files),
    }
    with open(os.path.join(directory, "alert.json"), "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    return directory


def validate_bundle(directory: str) -> list[str]:
    """Schema-check one evidence bundle ([] = valid): alert.json identity,
    the file inventory actually on disk, windows.jsonl field types, and
    flight files carrying full StepInfo rows."""
    from raft_sim_tpu.types import StepInfo

    errors = []
    base = os.path.basename(directory.rstrip(os.sep))
    path = os.path.join(directory, "alert.json")
    if not os.path.isfile(path):
        return [f"{base}: missing alert.json"]
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as ex:
        return [f"{base}/alert.json unreadable: {ex}"]
    if doc.get("schema") != EVIDENCE_SCHEMA:
        errors.append(
            f"{base}/alert.json: schema {doc.get('schema')!r}, expected "
            f"{EVIDENCE_SCHEMA}"
        )
    alert = doc.get("alert")
    if not isinstance(alert, dict):
        errors.append(f"{base}/alert.json: alert must be a map")
        alert = {}
    for k in ("objective", "rule", "state", "scope"):
        if not isinstance(alert.get(k), str) or not alert.get(k):
            errors.append(f"{base}/alert.json: alert.{k} missing")
    if not isinstance(doc.get("objective"), dict):
        errors.append(f"{base}/alert.json: objective spec missing")
    files = doc.get("files")
    if not isinstance(files, list):
        errors.append(f"{base}/alert.json: files inventory missing")
        files = []
    for name in files:
        if not os.path.isfile(os.path.join(directory, name)):
            errors.append(f"{base}: inventoried file {name} missing on disk")
    win_path = os.path.join(directory, "windows.jsonl")
    if os.path.isfile(win_path):
        with open(win_path) as f:
            for ln, raw in enumerate(f, 1):
                try:
                    row = json.loads(raw)
                except json.JSONDecodeError as ex:
                    errors.append(f"{base}/windows.jsonl:{ln}: not JSON: {ex}")
                    continue
                for k in EVIDENCE_WINDOW_FIELDS:
                    if not isinstance(row.get(k), int) or row.get(k) is True:
                        errors.append(
                            f"{base}/windows.jsonl:{ln}: field {k!r} missing "
                            "or non-int"
                        )
                if not isinstance(row.get("leaderless"), bool):
                    errors.append(
                        f"{base}/windows.jsonl:{ln}: leaderless must be bool"
                    )
    for name in sorted(os.listdir(directory)):
        if not (name.startswith("flight_") and name.endswith(".jsonl")):
            continue
        with open(os.path.join(directory, name)) as f:
            for ln, raw in enumerate(f, 1):
                try:
                    row = json.loads(raw)
                except json.JSONDecodeError as ex:
                    errors.append(f"{base}/{name}:{ln}: not JSON: {ex}")
                    continue
                missing = [
                    k for k in ("tick", *StepInfo._fields) if k not in row
                ]
                if missing:
                    errors.append(f"{base}/{name}:{ln}: missing fields {missing}")
    return errors
