"""Durable storage plane: the device-side fsync/WAL model.

The reference persists its log through a file-backed atom (log.clj:16-18)
with no fsync discipline, and its restart path forgets term/vote (SURVEY.md
2.3.12); the simulator's base model is the opposite extreme -- a PERFECT
disk where every write is durable the instant it happens -- so the whole
class of durability failures the dissertation's section 3.8 persistence
requirements exist to prevent was inexpressible. This subsystem makes
persistence explicit, as three rules both kernels state through
`storage.plane` and the scalar oracle restates independently
(tests/oracle.py):

  1. WATERMARKS. Each node carries a durable snapshot of the Raft
     persistent triple: `dur_len` (entries the disk has confirmed; entry
     IDENTITY rides the checksum chain, so a length is a prefix) plus
     `dur_term`/`dur_vote`. The snapshot advances ONLY when the node's
     fsync completes -- the cadence tick minus a per-node latency-jitter
     stall (`sim/faults._storage_draws`, the uint32-threshold machinery) --
     and a completed flush snaps it to the node's final live state that
     tick. Log truncation clamps the watermark (truncation makes the
     removed suffix non-durable AS CONTENT; the disk still confirmed the
     bytes, but recovery re-reads the new chain).

  2. THE SECTION-3.8 GATE (`cfg.durable_acks`). Everything a node EXPOSES
     about its persistent state reflects only durable state: AE ack match
     indices clamp to `dur_len` (replication stalls behind a slow disk
     instead of lying about it), the leader's own self-match counts toward
     commit only up to its durable watermark, and a vote grant is exposed
     only once the (term, votedFor) pair it commits to is durable -- a
     grant whose covering flush lands on a LATER tick emits a late
     RESP_VOTE then (the array form of "respond after the fsync returns").

  3. RECOVERY. A restart rewinds term/vote to the durable snapshot and
     recovers `max(dur_len, log_len - torn_drop)` log entries: the fsynced
     prefix is a FLOOR (a completed flush can never tear), the un-fsynced
     tail survives only as far as the in-flight writes reached, and the
     torn-tail draw (`torn_drop`, checksum-detected partial final records)
     eats up to `lost_suffix_span` entries of that salvageable suffix.
     Rule 2 makes the rewind sound: everything the node ever exposed was
     durable first, so recovery un-promises nothing -- which is exactly
     the property the two TEST-ONLY mutants break (scenario/mutation.py:
     `ack-before-fsync` -> leader_completeness, `volatile-vote` ->
     election_safety; frozen hunts in tests/corpus/).

Structural gate: `cfg.durable_storage` (fsync_interval > 0). Off, the
plane is zero-cost -- the watermark legs and lag metrics are carry
passthroughs (analysis/policy.invariant_leaves), the step goldens are
byte-identical, and the disk is perfect again. The cadence and every
disk-fault probability are tuning knobs inside the gate: the scenario
genome retimes them as traced data (disk-fault axes, scenario/genome.py),
so fault sweeps never recompile (jaxpr_audit FORK_PAIRS, config10).

v1 restriction: mutually exclusive with ring-log compaction
(compact_margin > 0) -- the durable watermark does not fold across
snapshot installs and compaction rebases yet (utils/config.py assert).
"""

from raft_sim_tpu.storage.plane import (  # noqa: F401
    covered,
    flush,
    recover,
    recovered_log_len,
)
