"""Long-horizon runs: chunked scans with periodic host offload.

The trajectory axis (simulated time) is one of the two "long axes" the rebuild scales
without materializing (SURVEY.md section 5, long-context analogue): a 10M-tick fuzz run
must not stack 10M StepInfos on device. `run_chunked` scans in fixed-size jitted chunks
and merges the small per-chunk RunMetrics on the way, optionally invoking a host
callback between chunks (progress reporting, checkpointing, early abort on violation).

Metric merge works because `scan._accumulate` records absolute tick numbers (state.now),
which persist across chunk boundaries in the carry.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from raft_sim_tpu.sim import scan
from raft_sim_tpu.types import ClusterState
from raft_sim_tpu.utils.config import RaftConfig


def merge_metrics(a: scan.RunMetrics, b: scan.RunMetrics) -> scan.RunMetrics:
    """Combine metrics of two consecutive run segments (a then b). Every op is
    elementwise, so this works unchanged on scalar or [batch]-shaped metrics."""
    return scan.RunMetrics(
        violations=a.violations + b.violations,
        first_leader_tick=jnp.minimum(a.first_leader_tick, b.first_leader_tick),
        last_leaderless_tick=jnp.maximum(a.last_leaderless_tick, b.last_leaderless_tick),
        max_term=jnp.maximum(a.max_term, b.max_term),
        max_commit=jnp.maximum(a.max_commit, b.max_commit),
        min_commit=b.min_commit,  # "at final tick" -> later segment wins
        total_msgs=a.total_msgs + b.total_msgs,
        total_cmds=a.total_cmds + b.total_cmds,
        lat_sum=a.lat_sum + b.lat_sum,
        lat_cnt=a.lat_cnt + b.lat_cnt,
        lat_hist=a.lat_hist + b.lat_hist,
        lat_excluded=a.lat_excluded + b.lat_excluded,
        noop_blocked=a.noop_blocked + b.noop_blocked,
        lm_skipped_pairs=a.lm_skipped_pairs + b.lm_skipped_pairs,
        reads_served=a.reads_served + b.reads_served,
        read_lat_sum=a.read_lat_sum + b.read_lat_sum,
        read_hist=a.read_hist + b.read_hist,
        fsync_lag_sum=a.fsync_lag_sum + b.fsync_lag_sum,
        fsync_lag_max=jnp.maximum(a.fsync_lag_max, b.fsync_lag_max),
        multi_leader=a.multi_leader + b.multi_leader,
        ticks=a.ticks + b.ticks,
    )


@jax.jit
def _own_copy(state):
    """A buffer-distinct copy of the fleet state: run_chunked donates its way
    through the chunk loop, and this one up-front copy is what keeps the
    CALLER's arrays alive while the loop consumes its own. A trivial program
    (one copy op per leaf) -- compiling it costs milliseconds, unlike a
    second donating/non-donating variant of the scan program would."""
    return jax.tree.map(jnp.copy, state)


@functools.partial(jax.jit, static_argnums=(0, 3, 5))
def _chunk(cfg: RaftConfig, state: ClusterState, keys: jax.Array, n: int,
           genome=None, seg_len: int = 1):
    """Input-preserving chunk: the caller's `state` stays valid after the call
    (tools/repro.py replays from the chunk-START state on a violation, so it
    must NOT be donated)."""
    return scan.run_batch_minor(cfg, state, keys, n, genome=genome, seg_len=seg_len)


@functools.partial(jax.jit, static_argnums=(0, 3, 5), donate_argnums=(1,))
def _chunk_donate(cfg: RaftConfig, state: ClusterState, keys: jax.Array, n: int,
                  genome=None, seg_len: int = 1):
    """The steady-state chunk: the previous chunk's carry is DONATED back to
    XLA, so a long-horizon run holds one fleet state in HBM instead of two
    (at config3 scale, batch=100k x ~4 KB/cluster, double-buffering is ~0.4 GB
    of dead residency per chunk boundary). `keys` are reused across chunks and
    are never donated. The cost model's donation audit
    (analysis/cost_model.py, rule `cost-donation`) pins that this entry point
    actually aliases its carry buffers -- dropping `donate_argnums` fails the
    gate statically."""
    return scan.run_batch_minor(cfg, state, keys, n, genome=genome, seg_len=seg_len)


def run_chunked(
    cfg: RaftConfig,
    state: ClusterState,
    keys: jax.Array,
    n_ticks: int,
    chunk: int = 1024,
    callback: Callable[[int, ClusterState, scan.RunMetrics], bool] | None = None,
    genome=None,
    seg_len: int = 1,
    perf=None,
):
    """Scan a batched state forward `n_ticks` in jitted chunks.

    `callback(ticks_done, state, merged_metrics)` runs between chunks; returning True
    stops early (e.g. on a violation during invariant fuzzing). Returns
    (final_state, merged_metrics). `genome`/`seg_len` select the scenario
    input path (scan.run_batch_minor); segment boundaries are driven by the
    absolute tick in state.now, so chunking never shifts a nemesis phase.

    Buffer ownership: the caller's `state` buffers stay valid (the loop takes
    ONE device copy up front -- trivial next to a single chunk's work -- and
    owns it), and every state the loop produces is donated to the next chunk,
    so the steady state holds one fleet in HBM, not two. One consequence: a
    `state` captured inside `callback` is only valid until the callback
    returns -- copy (`jax.device_get`) anything a callback needs to keep, as
    the checkpoint/apply-log consumers already do. This discipline is a
    GATED fact: analysis Pass D's use-after-donate dataflow lint walks this
    loop (rule `race-use-after-donate`, with `_own_copy` and
    fetch-before-donate blessed), and `tools/check.py --race --dynamic` /
    `driver run --sanitize` re-run it with donated buffers poisoned so any
    violation raises at the access site (analysis/sanitizer.py).

    `perf` (an obs.ChunkTimer) records per-chunk runtime attribution to
    perf.jsonl: each chunk is synced to a host copy of its small metrics leaf
    (device-wait timing; serializes the dispatch pipelining the loop would
    otherwise overlap -- docs/OBSERVABILITY.md "Runtime perf") and the chunk
    program's jit cache is sampled as the recompile watchdog. None (the
    default) leaves the loop byte-identical to pre-perf behaviour.
    """
    batch = state.role.shape[0]
    metrics = scan.init_metrics_batch(batch)
    done = 0
    state = _own_copy(state)
    if perf is not None:
        perf.add_probe("chunked._chunk_donate", _chunk_donate)
    while done < n_ticks:
        n = min(chunk, n_ticks - done)
        if perf is not None:
            perf.begin(n)
        state, m = _chunk_donate(cfg, state, keys, n, genome, seg_len)
        if perf is not None:
            perf.dispatched()
        metrics = merge_metrics(metrics, m)
        done += n
        # Callback host work (export, checkpointing) is part of the chunk's
        # host gap; the timer closes AFTER it, syncing on this chunk's own
        # metric leaf.
        stop = callback is not None and callback(done, state, metrics)
        if perf is not None:
            perf.end(sync=lambda: np.asarray(m.ticks))
        if stop:
            break
    return state, metrics
