"""Multi-host execution proof: two cooperating OS processes, one global mesh.

The reference's deployment shape is N cooperating OS processes (`lein run 1 2 3`
etc., core.clj:197-203). This framework's multi-HOST analogue is pure
orchestration -- independent clusters shard over every chip of every host -- and
this tool proves the code path actually executes: it spawns TWO local processes
(CPU backend, 4 virtual devices each) that form a JAX distributed cluster over a
localhost coordinator, run `simulate_sharded` on the global 8-device mesh, gather
metrics to every process (`parallel.gather_metrics` -- the non-addressable-shard
path of `summarize`), and verifies process 0's result matches a single-process
8-device run of the same (cfg, seed, batch, ticks) BIT FOR BIT (the
device-layout-invariance property of tests/test_parallel.py, extended across
process boundaries).

Usage:
    python tools/multihost_check.py            # orchestrates everything; prints
                                               # one JSON verdict line, exit 0 on match
    python tools/multihost_check.py --out P    # ...and write the schema'd
                                               # MULTICHIP artifact (multichip-v2:
                                               # throughput, per-device bytes,
                                               # parity hash) to P -- the diffable
                                               # standing row, validated by
                                               # utils.telemetry_sink.validate_multichip

Internal modes (spawned by the orchestrator; fresh interpreters are required
because --xla_force_host_platform_device_count must precede backend init):
    _MH_MODE=child _MH_PID={0,1} _MH_PORT=...  distributed worker
    _MH_MODE=local                             single-process reference run
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
if REPO not in sys.path:  # the artifact pricer imports raft_sim_tpu directly
    sys.path.insert(0, REPO)

# One meaty workload: faults + client traffic + invariants, riding the full
# round-4 surface (compaction ring + snapshot catch-up + 302 redirect routing).
CFG_KW = dict(
    n_nodes=5,
    log_capacity=16,
    compact_margin=4,
    client_interval=4,
    client_redirect=True,
    drop_prob=0.1,
    clock_skew_prob=0.1,
)
SEED, BATCH, TICKS = 0, 16, 200


def _run_and_dump() -> dict:
    """Run the sharded simulation on the (possibly multi-process) global mesh and
    return every RunMetrics field as lists, plus the fleet summary and a timed
    steady-state repeat (the first call pays the compile; the second, same
    program, is the throughput sample -- cluster-ticks/s)."""
    import time

    import jax
    import numpy as np

    from raft_sim_tpu import RaftConfig
    from raft_sim_tpu.parallel import gather_metrics, make_mesh, simulate_sharded, summarize

    cfg = RaftConfig(**CFG_KW)
    mesh = make_mesh()
    final, metrics = simulate_sharded(cfg, SEED, BATCH, TICKS, mesh)
    jax.block_until_ready(metrics)
    t0 = time.perf_counter()
    _, m2 = simulate_sharded(cfg, SEED, BATCH, TICKS, mesh)
    jax.block_until_ready(m2)
    wall = time.perf_counter() - t0
    summary = summarize(metrics)._asdict()  # exercises the gather path itself
    m = gather_metrics(metrics)
    fields = {f: np.asarray(v).tolist() for f, v in zip(m._fields, m)}
    return {"metrics": fields, "summary": summary,
            "throughput_ticks_per_s": round(BATCH * TICKS / wall, 1)}


def _per_device_bytes() -> float:
    """Pass C price of one device's cluster slice: (carry + inputs) padded
    bytes/tick per cluster x the local batch share (batch sharding moves no
    planes across devices, so per-device traffic is just the slice)."""
    from raft_sim_tpu import RaftConfig
    from raft_sim_tpu.analysis import cost_model, jaxpr_audit

    cfg = RaftConfig(**CFG_KW)
    local = BATCH // 8  # the global mesh is always 8 devices here
    cm = cost_model.carry_model(jaxpr_audit.scan_jaxpr(cfg), local)
    _, in_pad = cost_model.input_bytes(cfg, local)
    return round((cm["carry_padded"] + in_pad) * local, 1)


def _parity_hash(out: dict) -> str:
    """sha256 over the gathered metrics JSON: equal across processes iff the
    trajectories matched bit-for-bit."""
    import hashlib

    blob = json.dumps(out["metrics"], sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()


def child(pid: int, port: str) -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    from raft_sim_tpu.parallel import init_distributed

    got_pid = init_distributed(
        coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid
    )
    assert got_pid == pid
    assert jax.device_count() == 8, jax.device_count()
    assert jax.local_device_count() == 4, jax.local_device_count()
    out = _run_and_dump()
    if pid == 0:
        print(json.dumps(out), flush=True)
    jax.distributed.shutdown()


def local() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    assert jax.device_count() == 8, jax.device_count()
    print(json.dumps(_run_and_dump()), flush=True)


def single() -> None:
    """Single-process fallback for images whose CPU backend lacks cross-process
    collectives (jax < 0.5: "Multiprocess computations aren't implemented").
    The parity claim degrades from cross-PROCESS to cross-PROGRAM but stays
    bit-exact: the 8-device sharded run against the dense unsharded kernel,
    same (cfg, seed, batch, ticks). Re-arms to the two-process proof
    automatically once the environment supports it (orchestrate)."""
    import jax
    import numpy as np

    jax.config.update("jax_platforms", "cpu")
    assert jax.device_count() == 8, jax.device_count()
    out = _run_and_dump()
    from raft_sim_tpu import RaftConfig
    from raft_sim_tpu.sim import scan

    _, md = scan.simulate(RaftConfig(**CFG_KW), SEED, BATCH, TICKS)
    out["dense_metrics"] = {
        f: np.asarray(v).tolist() for f, v in zip(md._fields, md)
    }
    print(json.dumps(out), flush=True)


def _emit_artifact(out_path: str, verdict: dict, parity_hash: str,
                   throughput: float, reference: float, n_processes: int) -> None:
    doc = {
        "schema": "multichip-v2",  # telemetry_sink.MULTICHIP_SCHEMA
        "match": verdict["match"],
        "n_devices": 8,
        "n_processes": n_processes,
        "batch": BATCH,
        "ticks": TICKS,
        "violations": verdict["violations"],
        # Steady-state sample, cluster-ticks/s: the sharded run under test,
        # with the reference program's sample riding along for the overhead
        # diff. CPU rows are never roofline anchors (obs/reconcile rules).
        "throughput_ticks_per_s": throughput,
        "reference_ticks_per_s": reference,
        "per_device_bytes_per_tick": _per_device_bytes(),
        "parity_hash": parity_hash,
        "platform": "cpu",
    }
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")


def _spawn(env, *, me: str):
    return subprocess.Popen(
        [sys.executable, "-u", me], env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, cwd=REPO,
    )


def orchestrate_single(out_path: str | None = None) -> int:
    """The jax<0.5 fallback orchestration: one 8-device process, sharded vs
    dense bit-exactness (see `single`)."""
    me = os.path.abspath(__file__)
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["_MH_MODE"] = "single"
    p = _spawn(env, me=me)
    try:
        out, err = p.communicate(timeout=480)
    except subprocess.TimeoutExpired:
        p.kill()
        print(json.dumps({"match": False, "error": "single-process run timed out"}))
        return 1
    if p.returncode != 0:
        print(json.dumps({"match": False, "error": f"rc={p.returncode}",
                          "stderr_tail": err[-2000:]}))
        return 1
    got = json.loads(out.strip().splitlines()[-1])
    h_got = _parity_hash(got)
    h_want = _parity_hash({"metrics": got["dense_metrics"]})
    match = h_got == h_want
    verdict = {
        "match": match,
        "n_processes": 1,
        "global_devices": 8,
        "batch": BATCH,
        "ticks": TICKS,
        "violations": sum(got["metrics"]["violations"]),
        "summary": got["summary"],
        "note": "single-process fallback (jax<0.5 CPU backend): sharded vs "
                "dense parity; two-process proof re-arms on newer jax",
    }
    print(json.dumps(verdict))
    if out_path is not None:
        _emit_artifact(out_path, verdict, h_got,
                       got["throughput_ticks_per_s"],
                       got["throughput_ticks_per_s"], n_processes=1)
    return 0 if match else 1


def orchestrate(out_path: str | None = None) -> int:
    import jax

    if tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 5):
        return orchestrate_single(out_path)
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = str(s.getsockname()[1])
    s.close()

    def env_for(mode: str, n_dev: int, pid: int | None = None) -> dict:
        env = dict(os.environ)
        env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_dev}"
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["_MH_MODE"] = mode
        env["_MH_PORT"] = port
        if pid is not None:
            env["_MH_PID"] = str(pid)
        return env

    me = os.path.abspath(__file__)
    workers = [
        subprocess.Popen(
            [sys.executable, "-u", me],
            env=env_for("child", 4, pid),
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            cwd=REPO,
        )
        for pid in range(2)
    ]
    ref = subprocess.Popen(
        [sys.executable, "-u", me],
        env=env_for("local", 8),
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        cwd=REPO,
    )

    outs = []
    for i, p in enumerate(workers + [ref]):
        try:
            out, err = p.communicate(timeout=480)
        except subprocess.TimeoutExpired:
            for q in workers + [ref]:
                q.kill()
            print(json.dumps({"match": False, "error": f"process {i} timed out"}))
            return 1
        if p.returncode != 0:
            print(json.dumps({"match": False, "error": f"process {i} rc={p.returncode}",
                              "stderr_tail": err[-2000:]}))
            return 1
        outs.append(out)

    # Gloo prints connection banners on stdout; the JSON payload is the last line.
    got = json.loads(outs[0].strip().splitlines()[-1])  # worker process 0
    want = json.loads(outs[2].strip().splitlines()[-1])  # single-process reference
    # Parity is over metrics + summary ONLY: the timed throughput sample is
    # machine noise by construction and must not break the bit-exactness claim.
    h_got, h_want = _parity_hash(got), _parity_hash(want)
    match = h_got == h_want and got["summary"] == want["summary"]
    verdict = {
        "match": match,
        "n_processes": 2,
        "global_devices": 8,
        "batch": BATCH,
        "ticks": TICKS,
        "violations": sum(got["metrics"]["violations"]),
        "summary": got["summary"],
    }
    print(json.dumps(verdict))
    if out_path is not None:
        _emit_artifact(out_path, verdict, h_got,
                       got["throughput_ticks_per_s"],
                       want["throughput_ticks_per_s"], n_processes=2)
    return 0 if match else 1


def main() -> int:
    mode = os.environ.get("_MH_MODE")
    if mode == "child":
        child(int(os.environ["_MH_PID"]), os.environ["_MH_PORT"])
        return 0
    if mode == "local":
        local()
        return 0
    if mode == "single":
        single()
        return 0
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="write the schema'd MULTICHIP artifact "
                         "(multichip-v2) here")
    args = ap.parse_args()
    return orchestrate(args.out)


if __name__ == "__main__":
    sys.exit(main())
