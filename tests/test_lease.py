"""Lease-based reads (thesis 6.4.1; ISSUE 11): the lease serve predicate,
the thesis-4.2.3 vote denial it leans on, the read_fr staleness anchor, and
the viol_read_stale device invariant.

Kernel-vs-oracle bit-exactness rides tests/test_oracle_parity.py
(n5-lease-reads); this file pins the protocol semantics directly: a leader
with a fresh ack quorum serves in ONE tick with no confirmation round, an
expired lease falls back to confirmation, voters deny RequestVote while
lease-quiet (and stop denying after the local-clock window / a restart), a
stale lease serve raises viol_read_stale (and ONLY a stale one), and the
frozen lease-skew corpus artifact's genome leaves the REAL kernel clean.

Program budget: the semantic tests drive single `step` calls (tiny jit
programs, two configs); the real-kernel corpus replay is one small traced
scan; the trace-checker rejection of the lease mutant rides the slow tier
(CI's serve smoke runs the fleet-scale version every push).
"""

from __future__ import annotations

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_sim_tpu import RaftConfig, init_state
from raft_sim_tpu.models import raft
from raft_sim_tpu.ops import bitplane
from raft_sim_tpu.scenario.mutation import mutant_config
from raft_sim_tpu.sim import scan
from raft_sim_tpu.types import (
    FOLLOWER,
    LEADER,
    NIL,
    REQ_VOTE,
    StepInputs,
    with_commit_chk,
)

CORPUS = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "corpus", "lease-skew-n5.json"
)

# Scheduled-read lease tier: the read gate is read_interval > 0, but the
# cadence is parked far out so tests drive read offers explicitly via the
# read_cmd input (the Session.offer_read / serve-plane path).
LCFG = RaftConfig(
    n_nodes=5,
    log_capacity=8,
    election_min_ticks=12,
    election_range_ticks=6,
    client_interval=4,
    read_interval=1000,
    read_lease_ticks=4,
)


def _quiet_inputs(cfg: RaftConfig, **over) -> StepInputs:
    n = cfg.n_nodes
    base = dict(
        deliver_mask=bitplane.pack(jnp.ones((n, n), bool), axis=1),
        skew=jnp.ones((n,), jnp.int32),
        timeout_draw=jnp.full((n,), 10_000, jnp.int32),
        client_cmd=jnp.int32(NIL),
        client_target=jnp.int32(0),
        client_bounce=jnp.zeros((cfg.client_pipeline,), jnp.int32),
        alive=jnp.ones((n,), bool),
        restarted=jnp.zeros((n,), bool),
        reconfig_cmd=jnp.int32(NIL),
        transfer_cmd=jnp.int32(NIL),
        read_cmd=jnp.int32(NIL),
    )
    base.update(over)
    return StepInputs(**base)


def _leader_state(cfg, ack_age_val=0):
    """Node 0 an established leader of term 2 with one current-term committed
    entry (the 6.4 capture gate), deadlines parked, acks at `ack_age_val`."""
    n = cfg.n_nodes
    s = init_state(cfg, jax.random.key(0))
    s = s._replace(
        role=s.role.at[0].set(LEADER),
        term=jnp.full((n,), 2, jnp.int32),
        leader_id=jnp.zeros((n,), jnp.int32),
        log_term=s.log_term.at[:, 0].set(2),
        log_val=s.log_val.at[:, 0].set(41),
        log_tick=s.log_tick.at[:, 0].set(1),
        log_len=jnp.ones((n,), jnp.int32),
        commit_index=jnp.ones((n,), jnp.int32),
        lat_frontier=jnp.int32(1),
        ack_age=jnp.full((n, n), ack_age_val, s.ack_age.dtype),
        deadline=jnp.full((n,), 10_000, jnp.int32),
    )
    return with_commit_chk(s)


def test_lease_serves_in_one_tick_with_no_confirmation_round():
    """Capture then serve on the NEXT tick purely from the fresh ack quorum:
    zero AppendEntries confirmation responses ever arrive (the mailbox stays
    quiet), yet the read serves with latency 1 -- the zero-quorum-round
    steady state 6.4.1 promises. The slot and its staleness anchor clear."""
    step = jax.jit(lambda st, i: raft.step(LCFG, st, i))
    s = _leader_state(LCFG)
    s, info = step(s, _quiet_inputs(LCFG, read_cmd=jnp.int32(1)))
    assert int(s.read_idx[0]) == 2  # captured commit 1 (+1 encoding)
    assert int(s.read_fr[0]) == 1  # frontier banked at capture
    assert int(info.reads_served) == 0
    s, info = step(s, _quiet_inputs(LCFG))
    assert int(info.reads_served) == 1
    assert int(info.read_lat_sum) == 1  # offer tick -> next tick
    assert not bool(info.viol_read_stale)
    assert int(s.read_idx[0]) == 0 and int(s.read_fr[0]) == 0
    assert not bool(scan.step_bad(info))


def test_expired_lease_withholds_the_serve():
    """With every ack older than the lease window (and no confirmation
    responses), the pending read stays pending: the lease never serves on
    stale acknowledgments."""
    step = jax.jit(lambda st, i: raft.step(LCFG, st, i))
    s = _leader_state(LCFG, ack_age_val=50)
    s, _ = step(s, _quiet_inputs(LCFG, read_cmd=jnp.int32(1)))
    assert int(s.read_idx[0]) == 2
    for _ in range(3):
        s, info = step(s, _quiet_inputs(LCFG))
        assert int(info.reads_served) == 0
        assert int(s.read_idx[0]) == 2  # still pending, never served


def test_stale_lease_serve_raises_viol_read_stale_and_only_stale():
    """A served read whose captured index sits below its banked
    capture-frontier is the linearizability break: viol_read_stale fires and
    folds into the violations predicate (scan.step_bad -- the hunt's fitness
    signal). The legal twin (anchor covered by the capture) stays clean."""
    step = jax.jit(lambda st, i: raft.step(LCFG, st, i))
    base = _leader_state(LCFG)
    stale = base._replace(
        read_idx=base.read_idx.at[0].set(2),   # captured commit 1...
        read_tick=base.read_tick.at[0].set(1),
        read_fr=base.read_fr.at[0].set(3),     # ...but 3 were committed at issue
    )
    _, info = step(stale, _quiet_inputs(LCFG))
    assert int(info.reads_served) == 1  # the lease DID serve it
    assert bool(info.viol_read_stale)
    assert bool(scan.step_bad(info))
    legal = base._replace(
        read_idx=base.read_idx.at[0].set(2),
        read_tick=base.read_tick.at[0].set(1),
        read_fr=base.read_fr.at[0].set(1),     # capture covered the frontier
    )
    _, info = step(legal, _quiet_inputs(LCFG))
    assert int(info.reads_served) == 1
    assert not bool(info.viol_read_stale)


def test_lease_vote_denial_and_local_clock_expiry():
    """Thesis 4.2.3 under the lease gate: a voter that heard a leader within
    election_min_ticks of LOCAL clock denies RequestVote outright; once the
    local window elapses (or a restart wipes the memory), it grants."""
    n = LCFG.n_nodes
    step = jax.jit(lambda st, i: raft.step(LCFG, st, i))
    s = init_state(LCFG, jax.random.key(1))
    mb = s.mailbox
    # Node 1 broadcasts an up-to-date RequestVote at everyone's term.
    s = s._replace(
        term=jnp.full((n,), 2, jnp.int32),
        role=s.role.at[1].set(1),  # CANDIDATE
        deadline=jnp.full((n,), 10_000, jnp.int32),
        heard_clock=jnp.zeros((n,), jnp.int32),  # heard a leader "just now"
        mailbox=mb._replace(
            req_type=mb.req_type.at[1].set(REQ_VOTE),
            req_term=mb.req_term.at[1].set(2),
        ),
    )
    s2, _ = step(s, _quiet_inputs(LCFG))
    assert int(np.sum(np.asarray(s2.mailbox.v_to) != NIL)) == 0  # all denied
    # Same request against voters whose local clocks long passed the window.
    s3 = s._replace(heard_clock=jnp.full((n,), -50, jnp.int32))
    s4, _ = step(s3, _quiet_inputs(LCFG))
    granted = np.asarray(s4.mailbox.v_to)
    assert (granted[np.arange(n) != 1] == 1).any()  # grants flowed to node 1
    # A restarted voter holds no lease obligation: wipe -> immediate grant.
    s5, _ = step(
        s,
        _quiet_inputs(
            LCFG,
            restarted=jnp.asarray([False, False, True, False, False]),
        ),
    )
    # The restarted node misses THIS delivery (messages to a restarting node
    # die with it) but its heard_clock is wiped to "long quiet":
    assert int(s5.heard_clock[2]) == -LCFG.election_min_ticks
    assert int(s5.read_fr[2]) == 0  # the staleness anchor dies with the slot


def test_config_validator_pins_the_lease_bounds():
    with pytest.raises(AssertionError, match="skew-safe bound"):
        RaftConfig(n_nodes=5, client_interval=4, read_interval=3,
                   read_lease_ticks=4)  # default election_min 6 < 2*4+4
    with pytest.raises(AssertionError, match="ReadIndex plane"):
        RaftConfig(n_nodes=5, client_interval=4, election_min_ticks=12,
                   read_lease_ticks=4)
    with pytest.raises(AssertionError, match="offer-tick plane"):
        RaftConfig(n_nodes=5, read_interval=3, election_min_ticks=12,
                   read_lease_ticks=4)
    # Leases + TimeoutNow transfers COEXIST since the disruptive-RequestVote
    # override (ISSUE 13): the PR-11 mutual-exclusion validator is gone.
    # The deterministic transfer-under-lease completion is pinned in
    # tests/test_reconfig.py::test_transfer_overrides_lease_denial_*.
    RaftConfig(n_nodes=5, client_interval=4, read_interval=3,
               election_min_ticks=14, read_lease_ticks=4,
               transfer_interval=9)


def test_zero_cost_when_off_carry_contract():
    """The policy side of zero-cost-when-off: read_fr (and heard_clock,
    absent pre_vote) are loop-invariant legs on every non-lease config, and
    go live under the gate. The lowered-program side is pinned by the
    byte-identical disabled-mode step goldens (tests/test_golden_jaxpr.py)
    and the Pass A carry-passthrough rule over the preset matrix."""
    from raft_sim_tpu.analysis import policy

    plain = RaftConfig(n_nodes=5, client_interval=4, read_interval=3)
    inv = policy.invariant_leaves(plain)
    assert "read_fr" in inv and "heard_clock" in inv
    inv_lease = policy.invariant_leaves(LCFG)
    assert "read_fr" not in inv_lease and "heard_clock" not in inv_lease
    assert "read_fr" in policy.invariant_leaves(RaftConfig(n_nodes=5))


def test_corpus_artifact_shape():
    """The frozen lease-skew hit: found by the hunt, shrunk with the SKEW
    mechanism retained (ablating it kills the violation -- the clock
    assumption is load-bearing), named viol_read_stale. (tests/
    test_corpus.py replays the mutant side bit-exactly in tier 1.)"""
    with open(CORPUS) as f:
        art = json.load(f)
    assert art["mutant"] == "lease-skew"
    assert art["kinds"] == ["viol_read_stale"]
    assert art["segments"][0]["clock_skew_prob"] > 0
    assert art["config"]["read_lease_ticks"] > 0


@pytest.mark.slow
def test_corpus_genome_leaves_real_kernel_clean():
    """The REAL kernel replayed over the corpus hit's identical (genome,
    seed, cluster, horizon) is clean: the skew-safe lease bound holds where
    the mutant's no-skew bound breaks. Slow tier (one fresh scan compile):
    the CI lease smoke replays the real kernel FLEET-wide every push, and
    tier-1's corpus replay pins the mutant side bit-exactly."""
    with open(CORPUS) as f:
        art = json.load(f)
    from raft_sim_tpu.scenario import genome as gm
    from raft_sim_tpu.scenario.shrink import _replay_fn, _single_cluster

    real_cfg = RaftConfig(**art["config"])
    g = gm.from_raw(art["genome_raw"])
    state, key = _single_cluster(
        real_cfg, art["seed"], art["batch"], art["cluster"]
    )
    _, metrics, _ = _replay_fn(real_cfg, int(art["ticks"]), int(art["seg_len"]))(
        state, key, g
    )
    assert int(np.asarray(metrics.violations)) == 0


@pytest.mark.slow
def test_checker_rejects_lease_mutant_naming_read_linearizability():
    """Whole-history form of the corpus hit: the lease-skew mutant's fleet
    under the hunted genome, traced, fails read_linearizability with the
    minimal (issue, serve) witness; the REAL kernel over the identical fleet
    passes all six properties -- under skew. Slow tier: two fleet-scale
    trace-variant programs (CI's serve smoke runs the same legs per push)."""
    from raft_sim_tpu.sim import telemetry
    from raft_sim_tpu.trace import checker as tchecker
    from raft_sim_tpu.trace import history as thistory
    from raft_sim_tpu.trace.ring import TraceSpec

    with open(CORPUS) as f:
        art = json.load(f)
    from raft_sim_tpu.scenario import genome as gm

    real_cfg = dataclasses.replace(
        RaftConfig(**art["config"]), track_trace=True
    )
    mut_cfg = mutant_config("lease-skew", real_cfg)
    g = gm.broadcast(gm.from_raw(art["genome_raw"]), art["batch"])
    spec = TraceSpec(depth=512)
    out = telemetry.simulate_windowed(
        mut_cfg, art["seed"], art["batch"], 768, 64, 0, g, 1, spec
    )
    rep = tchecker.check_history(thistory.from_device(out[4]))
    assert "read_linearizability" in rep.violated
    w = rep.results["read_linearizability"].witness
    assert [e["kind"] for e in w] == ["read_issue", "read_serve"]
    out_real = telemetry.simulate_windowed(
        real_cfg, art["seed"], art["batch"], 768, 64, 0, g, 1, spec
    )
    rep_real = tchecker.check_history(thistory.from_device(out_real[4]))
    assert rep_real.complete, rep_real.problems
    assert rep_real.ok, {
        k: r.note for k, r in rep_real.results.items() if not r.ok
    }
