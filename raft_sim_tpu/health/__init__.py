"""Fleet health plane: streaming SLIs, burn-rate alerts, triage, evidence.

The closed loop over the repo's observability streams: spec.py declares the
SLOs, sli.py computes the indicators from window counters + perf rows,
burn.py runs the multi-window multi-burn-rate state machines, triage.py
names the worst-K clusters, evidence.py freezes the forensics, and
monitor.py is the streaming evaluator every standing loop
(run/soak/serve/farm) folds into its sink path. Host-side only by
construction -- docs/OBSERVABILITY.md "Fleet health & SLOs".
"""

from raft_sim_tpu.health.burn import ALERT_STATES, BURN_INF, BurnEngine
from raft_sim_tpu.health.evidence import (
    EVIDENCE_SCHEMA,
    validate_bundle,
    write_bundle,
)
from raft_sim_tpu.health.monitor import HealthMonitor, HealthWriter
from raft_sim_tpu.health.spec import (
    DEFAULT_SPEC,
    HEALTH_SPEC_SCHEMA,
    load_spec,
    validate_spec,
)

__all__ = [
    "ALERT_STATES",
    "BURN_INF",
    "BurnEngine",
    "DEFAULT_SPEC",
    "EVIDENCE_SCHEMA",
    "HEALTH_SPEC_SCHEMA",
    "HealthMonitor",
    "HealthWriter",
    "load_spec",
    "validate_bundle",
    "validate_spec",
    "write_bundle",
]
