"""Committed-value export stream (utils/apply_log.py) -- the reference's
per-node `node_<id>.log` apply file (log.clj:16-18, 74-75, core.clj:17),
validated against the offered command schedule across compaction boundaries."""

import jax
import numpy as np
import pytest

from raft_sim_tpu import RaftConfig
from raft_sim_tpu.driver import Session

# A small ring under continuous client traffic: the run commits several
# multiples of the physical capacity, so every export necessarily crosses
# compaction boundaries.
CFG = RaftConfig(
    n_nodes=5, log_capacity=32, compact_margin=8, max_entries_per_rpc=4,
    client_interval=4,
)


def scheduled_values(ticks):
    """The offered schedule: value t+1 at every tick t with t % interval == 0
    (faults.make_inputs)."""
    return {t + 1 for t in range(ticks) if t % CFG.client_interval == 0}


def test_export_matches_offered_schedule_across_compaction(tmp_path):
    sess = Session(CFG, batch=2, seed=0)
    sess.attach_apply_log(str(tmp_path), cluster=0)
    sess.run(800, chunk=32)  # chunk well under CAP - margin commits: no gaps
    w = sess.apply_writer

    st = jax.device_get(jax.tree.map(lambda x: x[0], sess.state))
    assert int(np.max(st.log_base)) > 3 * CFG.log_capacity  # ring really wrapped

    offered = scheduled_values(800)
    for i in range(CFG.n_nodes):
        vals = w.values(i)
        assert w.gaps(i) == []
        assert len(vals) > 2 * CFG.log_capacity  # far past physical capacity
        # Every exported value is an offered command, in offer order (the
        # committed log of a healthy cluster is an offer-ordered subsequence).
        assert set(vals) <= offered
        assert vals == sorted(vals)
        # COMPLETE, not merely ordered: on a reliable single-leader run every
        # offer between the first and last exported value was accepted and
        # committed, so the stream must be exactly that contiguous slice of the
        # schedule -- a silently dropped value would leave a hole here.
        assert vals == sorted(v for v in offered if vals[0] <= v <= vals[-1])
    # Reliable net: all nodes export the SAME stream (log matching made
    # observable on the host) up to the shortest frontier.
    streams = [w.values(i) for i in range(CFG.n_nodes)]
    shortest = min(len(s) for s in streams)
    for s in streams:
        assert s[:shortest] == streams[0][:shortest]


@pytest.mark.slow  # budget re-tier (PR 12): the offer-tick export-currency
# contract is held by the cheaper apply-log chunk-boundary tests plus the
# driver offer ack tests; this session-offer interplay soak (its own
# compile) joins the apply_log reset-restart soak in the slow tier.
def test_export_survives_session_offer_and_counts_it(tmp_path):
    sess = Session(CFG, batch=2, seed=0)
    sess.attach_apply_log(str(tmp_path), cluster=0)
    sess.run(100, chunk=25)
    r = sess.offer(-50, wait=10)  # offer() ticks outside run(); next run catches up
    assert r["committed"] >= 1
    sess.run(50, chunk=25)
    assert -50 in sess.apply_writer.values(0)


@pytest.mark.slow
def test_reset_restarts_the_export_stream(tmp_path):
    """Session.reset rebuilds the experiment; an attached writer must restart
    too (truncated files, zeroed frontier) -- a stale frontier would silently
    drop the new run's early commits (code-review finding). Slow tier (two
    200-tick runs; the export-correctness tests above stay tier-1)."""
    sess = Session(CFG, batch=1, seed=0)
    sess.attach_apply_log(str(tmp_path), cluster=0)
    sess.run(200, chunk=25)
    first = sess.apply_writer.values(0)
    assert len(first) > 10
    sess.reset()
    sess.run(200, chunk=25)
    assert sess.apply_writer.values(0) == first  # same seed -> same stream again


def test_update_rejects_overwide_committed_window(tmp_path):
    """Round-5 advisor hardening: update() reads the ring assuming every entry
    in (base, commit] is live (commit - base <= CAP). A state violating that --
    the signature of ticks advancing past a chunk boundary before export, or a
    layout regression -- must fail loudly instead of exporting ring garbage."""
    import jax.numpy as jnp
    import pytest

    from raft_sim_tpu.utils.apply_log import ApplyLogWriter
    from raft_sim_tpu import init_batch

    state = init_batch(CFG, jax.random.key(0), 1)
    bad = state._replace(
        commit_index=jnp.full_like(state.commit_index, CFG.log_capacity + 1)
    )
    w = ApplyLogWriter(str(tmp_path), CFG, cluster=0)
    with pytest.raises(RuntimeError, match="compacted slots"):
        w.update(bad)


def test_oversized_chunk_reports_snapshot_gap(tmp_path):
    """One giant chunk commits many multiples of the ring: the compacted spans
    are not observable and must surface as explicit gap markers, with the
    post-gap suffix still exact."""
    sess = Session(CFG, batch=1, seed=1)
    sess.attach_apply_log(str(tmp_path), cluster=0)
    sess.run(800, chunk=800)
    w = sess.apply_writer
    gaps = w.gaps(0)
    assert gaps, "an 800-tick chunk must outrun the 32-slot ring"
    st = jax.device_get(jax.tree.map(lambda x: x[0], sess.state))
    commit = int(st.commit_index[0])
    base = int(st.log_base[0])
    # The exported suffix after the last gap equals the live committed ring
    # entries (skipping no-ops).
    from raft_sim_tpu.types import NOOP

    cap = CFG.log_capacity
    want = [
        int(st.log_val[0][(idx1 - 1) % cap])
        for idx1 in range(gaps[-1][1] + 1, commit + 1)
    ]
    want = [v for v in want if v != NOOP]
    vals = w.values(0)
    assert vals[-len(want):] == want if want else True
    # Gap spans + exported values exactly tile (0, commit]: nothing silently
    # dropped. (Values below the first gap were exported before it opened.)
    covered = sum(b - a + 1 for a, b in gaps) + len(vals)
    noops = commit - base - len(want)  # live no-ops were skipped, count them
    assert covered + noops >= commit
