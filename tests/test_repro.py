"""The violation repro/shrink tool (tools/repro.py) demonstrated against an
artificially broken kernel: a config whose quorum is one vote short of a real
majority, so split votes crown two leaders in the same term and the on-device
election-safety invariant fires. The tool must isolate the first offending
(cluster, tick) from a seeded batch run and emit usable context."""

import importlib.util
import os

import numpy as np

from raft_sim_tpu import RaftConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "repro", os.path.join(REPO, "tools", "repro.py")
)
repro = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(repro)


class BrokenQuorum(RaftConfig):
    """quorum - 1: deliberately unsafe (the reference's even-N majority bug,
    SURVEY.md quorum note, made worse)."""

    @property
    def quorum(self):
        return self.n_nodes // 2


def test_shrink_isolates_first_violation():
    cfg = BrokenQuorum(n_nodes=5, drop_prob=0.3)
    res = repro.shrink(cfg, seed=1, batch=64, n_ticks=1024, chunk=256)
    assert res is not None
    assert 0 <= res["cluster"] < 64
    assert res["kinds"], "violation kinds must be named"
    assert "viol_election_safety" in res["kinds"]
    # the event window shows the competing elections that produced two leaders
    assert any("becomes leader" in e for _, e in res["events"])
    assert len(res["state_lines"]) == cfg.n_nodes
    # the standalone command carries the non-default config and the exact horizon
    assert "--drop-prob 0.3" in res["repro_cmd"]
    assert f"--ticks {res['tick'] + 1}" in res["repro_cmd"]
    assert f"--seed 1" in res["repro_cmd"]

    # It really is the FIRST violating tick of that cluster: replaying the whole
    # run and scanning per-tick info agrees.
    import jax

    from raft_sim_tpu import init_batch
    from raft_sim_tpu.sim import scan

    root = jax.random.key(1)
    k_init, k_run = jax.random.split(root)
    state = init_batch(cfg, k_init, 64)
    keys = jax.random.split(k_run, 64)
    one = jax.tree.map(lambda x: x[res["cluster"]], state)
    _, _, infos = jax.jit(
        lambda s, k: scan.run(cfg, s, k, res["tick"] + 8, trace=True)
    )(one, keys[res["cluster"]])
    bad = (
        np.asarray(infos.viol_election_safety)
        | np.asarray(infos.viol_commit)
        | np.asarray(infos.viol_log_matching)
    )
    assert int(np.argmax(bad)) == res["tick"]


def test_shrink_clean_run_returns_none():
    assert repro.shrink(RaftConfig(n_nodes=5), seed=0, batch=8, n_ticks=256) is None
