"""Fault injection and per-tick input generation, as pure data.

In the reference, faults are accidental: a dead or unreachable peer makes the outbound
HTTP call throw, the exception is swallowed, and the message vanishes (client.clj:38-40);
election timeouts are the only failure detector (core.clj:171-174); there is no fault
*injection* at all (SURVEY.md section 5). Here fault schedules are first-class pure
inputs derived deterministically from (cluster key, tick):

  - Bernoulli message drop, optionally with a per-cluster drop rate drawn from
    [0, drop_prob] (BASELINE config 4),
  - rolling partitions: every `partition_period` ticks the cluster is (with some
    probability) split into two random halves whose cross edges deliver nothing
    (BASELINE config 5),
  - clock skew: a node's local clock occasionally stalls (+0) or jumps (+2),
  - node crash/restart: a windowed renewal schedule (alive_at) downs nodes for
    bounded spans; restart wipes spec-volatile state but keeps the Raft persistent
    triple -- unlike the reference, whose restarted process loses term/vote/entries
    (log.clj:16-18, SURVEY.md 2.3.12),
  - randomized election-timeout draws (the reference's 5000+rand(5000) ms,
    core.clj:174),
  - client command injection on a fixed cadence (the reference's external curl against
    /client-set, server.clj:8-12).

Everything is a function of (key, now), so trajectories are replayable from a seed and
checkpoint/resume needs only (state, key) -- no RNG state in the carry.

The per-cluster key is split once into disjoint streams (per-tick draws, per-cluster
drop rate, per-window partition layout) so no fold_in value can collide across
purposes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_sim_tpu.ops import bitplane
from raft_sim_tpu.types import NIL, StepInputs
from raft_sim_tpu.utils.config import RaftConfig
from raft_sim_tpu.utils.rng import draw_timeouts


def crash_key(key: jax.Array) -> jax.Array:
    """The dedicated crash-schedule stream for a cluster key. fold_in(-1) is disjoint
    from the per-window fold_in(k_part, window >= 0) draws sharing this base."""
    _, _, k_part = jax.random.split(key, 3)
    return jax.random.fold_in(k_part, jnp.int32(-1))


def alive_at(cfg: RaftConfig, ckey: jax.Array, now: jax.Array) -> jax.Array:
    """[N] bool node liveness at tick `now` -- a pure function of the crash stream, so
    trajectories stay replayable with no RNG or downtime counter in the scan carry.

    Windowed renewal process: node i is down during ticks
    [w*P + start_i, w*P + start_i + dur_i) of window w (clipped at the window edge,
    so a node is never down across a window boundary) iff its per-window Bernoulli
    crash draw fired. `now < 0` reports alive (so tick 0 is never a "restart").
    """
    n = cfg.n_nodes
    if cfg.crash_prob <= 0:
        return jnp.ones((n,), bool)
    window = now // cfg.crash_period
    off = now - window * cfg.crash_period
    wkey = jax.random.fold_in(ckey, window)
    k_sel, k_start, k_dur = jax.random.split(wkey, 3)
    crashed = jax.random.bernoulli(k_sel, cfg.crash_prob, (n,))
    start = jax.random.randint(k_start, (n,), 0, cfg.crash_period)
    dur = jax.random.randint(k_dur, (n,), 1, cfg.crash_down_ticks + 1)
    down = crashed & (off >= start) & (off < start + dur) & (now >= 0)
    return ~down


def make_inputs(cfg: RaftConfig, key: jax.Array, now: jax.Array) -> StepInputs:
    """Inputs for one cluster at tick `now`. `key` is the per-cluster base key."""
    n = cfg.n_nodes
    k_ticks, k_rate, k_part = jax.random.split(key, 3)
    tkey = jax.random.fold_in(k_ticks, now)
    k_drop, k_timeout, k_skew = jax.random.split(tkey, 3)

    # Message drop (the reference's silently-dropped RPC, client.clj:38-40).
    if cfg.drop_prob > 0:
        if cfg.drop_prob_uniform:
            p = jax.random.uniform(k_rate, (), maxval=cfg.drop_prob)
        else:
            p = cfg.drop_prob
        deliver = ~jax.random.bernoulli(k_drop, p, (n, n))
    else:
        deliver = jnp.ones((n, n), bool)

    # Rolling partitions: assignment is stable within each window of
    # `partition_period` ticks because it is keyed by the window index, not the tick.
    if cfg.partition_period > 0:
        window = now // cfg.partition_period
        wkey = jax.random.fold_in(k_part, window)
        k_group, k_active = jax.random.split(wkey)
        group = jax.random.bernoulli(k_group, 0.5, (n,))
        active = jax.random.bernoulli(k_active, cfg.partition_prob)
        same_side = group[:, None] == group[None, :]
        deliver = deliver & (same_side | ~active)

    # Clock skew.
    if cfg.clock_skew_prob > 0:
        u = jax.random.uniform(k_skew, (n,))
        skew = jnp.where(
            u < cfg.clock_skew_prob / 2,
            0,
            jnp.where(u < cfg.clock_skew_prob, 2, 1),
        ).astype(jnp.int32)
    else:
        skew = jnp.ones((n,), jnp.int32)

    # Election-timeout draws (one per node per tick, used on any timer reset).
    timeout_draw = draw_timeouts(cfg, k_timeout, n)

    # Client commands: value = tick at injection + 1 (payload bytes carry no
    # protocol meaning in the reference either, log.clj:66-67; the +1 keeps 0 free
    # and lets the commit-latency metric recover the offer tick from the value).
    if cfg.client_interval > 0:
        client_cmd = jnp.where(now % cfg.client_interval == 0, now + 1, NIL)
    else:
        client_cmd = jnp.int32(NIL)
    client_cmd = jnp.asarray(client_cmd, jnp.int32)

    # Client routing draws (redirect model only): the random node a fresh offer
    # POSTs to, and the random peer each pipeline slot's leaderless redirect
    # bounces to.
    if cfg.client_redirect:
        k_tgt, k_bnc = jax.random.split(jax.random.fold_in(tkey, 3))
        client_target = jax.random.randint(k_tgt, (), 0, n)
        client_bounce = jax.random.randint(k_bnc, (cfg.client_pipeline,), 0, n)
    else:
        client_target = jnp.int32(0)
        client_bounce = jnp.zeros((cfg.client_pipeline,), jnp.int32)

    # Crash/restart schedule (restart edge = alive now, down last tick).
    if cfg.crash_prob > 0:
        ckey = crash_key(key)
        alive = alive_at(cfg, ckey, now)
        restarted = alive & ~alive_at(cfg, ckey, now - 1)
    else:
        alive = jnp.ones((n,), bool)
        restarted = jnp.zeros((n,), bool)

    return StepInputs(
        # Shipped bit-packed over the source axis (StepInputs docstring): the
        # same Bernoulli/partition draws, 32 edges per uint32 word -- the [N, N]
        # bool plane never leaves this function.
        deliver_mask=bitplane.pack(deliver, axis=1),
        skew=skew,
        timeout_draw=timeout_draw,
        client_cmd=client_cmd,
        client_target=client_target,
        client_bounce=client_bounce,
        alive=alive,
        restarted=restarted,
    )
