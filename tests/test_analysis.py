"""The static analyzer's own tests: every rule must fire on a seeded
violation and stay silent on the idiomatic forms, the waiver engine must
match/mark/report-stale exactly, and the current tree must gate clean.

The negative seeds here are the acceptance proof the analyzer is real: an
injected float upcast is caught by the jaxpr pass (rules float-op and
plane-widening), and an unbumped checkpoint field change is caught by the AST
pass (rule checkpoint-version) -- neither relies on the violation happening to
break a runtime parity test.

Everything here is lowering/AST only -- no scan compiles -- so the module
stays cheap inside the tier-1 budget (the heaviest items are eval_shape
traces of the step kernel).
"""

from __future__ import annotations

import dataclasses
import json

import jax
import jax.numpy as jnp
import pytest

from raft_sim_tpu.analysis import ast_lint, jaxpr_audit, policy, run
from raft_sim_tpu.analysis import findings as F
from raft_sim_tpu.utils import checkpoint
from raft_sim_tpu.utils.config import PRESETS

CFG3 = PRESETS["config3"][0]


# ------------------------------------------------------------- AST pass rules


def test_traced_branch_fires_on_seeded_kernel():
    src = (
        "import jax.numpy as jnp\n"
        "from raft_sim_tpu.types import ClusterState\n"
        "def step(cfg, s: ClusterState, x):\n"
        "    t = s.term + 1\n"
        "    if t.max() > 3:\n"            # Python branch on traced value
        "        return s\n"
        "    while s.commit_index.any():\n"  # and a traced while
        "        pass\n"
        "    return s\n"
    )
    got = ast_lint.lint_source(src, "raft_sim_tpu/models/bad.py")
    rules = [f.rule for f in got]
    assert rules.count("traced-branch") == 2
    assert {f.line for f in got} == {5, 7}


def test_traced_branch_ignores_static_config_branches():
    src = (
        "import jax.numpy as jnp\n"
        "def step(cfg, n_ticks):\n"
        "    if cfg.pre_vote:\n"
        "        k = 2\n"
        "    while n_ticks > 0:\n"
        "        n_ticks -= 1\n"
        "    return k\n"
    )
    assert ast_lint.lint_source(src, "raft_sim_tpu/models/ok.py") == []


def test_float_literal_fires_in_hot_path_only():
    src = "import jax.numpy as jnp\ndef f(x):\n    return jnp.maximum(x, 1.5)\n"
    got = ast_lint.lint_source(src, "raft_sim_tpu/ops/bad.py")
    assert [f.rule for f in got] == ["float-literal"]
    # jax.random probabilities are the documented exception...
    src_ok = "import jax\ndef f(k, n):\n    return jax.random.bernoulli(k, 0.5, (n,))\n"
    assert ast_lint.lint_source(src_ok, "raft_sim_tpu/sim/ok.py") == []
    # ...and non-hot-path packages are out of scope for this rule.
    assert ast_lint.lint_source(src, "raft_sim_tpu/utils/ok.py") == []


# ------------------------------------------------------------ jaxpr pass rules


def _plane(n=5, dtype=jnp.int8):
    return jax.ShapeDtypeStruct((n, n), dtype)


def test_float_upcast_caught_by_jaxpr_pass():
    # The seeded negative: an [N, N] protocol plane upcast to float (a mean).
    bad = jax.make_jaxpr(lambda p: p.astype(jnp.float32).mean())(_plane())
    assert any(f.rule == "float-op"
               for f in jaxpr_audit.check_float_ops("jaxpr:neg/step", bad))


def test_plane_widening_caught_and_reduction_exempt():
    widen = jax.make_jaxpr(lambda p: p.astype(jnp.int32) * 2)(_plane())
    got = jaxpr_audit.check_plane_widening("jaxpr:neg/step", widen, CFG3)
    assert [f.rule for f in got] == ["plane-widening"]
    # Widening straight into a reduction is the one legal form.
    ok = jax.make_jaxpr(lambda p: jnp.sum(p.astype(jnp.int32)))(_plane())
    assert jaxpr_audit.check_plane_widening("jaxpr:ok/step", ok, CFG3) == []


def test_step_kernels_are_float_free_and_unwidened():
    for batched in (False, True):
        jx = jaxpr_audit.step_jaxpr(CFG3, batched=batched)
        assert jaxpr_audit.check_float_ops("jaxpr:config3", jx) == []
        assert jaxpr_audit.check_plane_widening("jaxpr:config3", jx, CFG3) == []


def test_carry_passthrough_fires_on_rewritten_invariant_leg():
    # Audit a pre_vote program under a no-pre-vote policy: heard_clock and
    # mb.pv_grant ARE rewritten there, which is exactly what the rule must
    # report for a config whose policy says they are loop-invariant.
    cfg_pv = dataclasses.replace(CFG3, pre_vote=True)
    jx = jaxpr_audit.scan_jaxpr(cfg_pv)
    got = jaxpr_audit.check_carry_passthrough("jaxpr:neg/simulate", jx, CFG3)
    names = {f.message.split("'")[1] for f in got if f.rule == "carry-passthrough"}
    assert names == {"heard_clock", "mb.pv_grant"}
    # And the real pairing is clean.
    assert jaxpr_audit.check_carry_passthrough(
        "jaxpr:config3/simulate", jaxpr_audit.scan_jaxpr(CFG3), CFG3
    ) == []


def test_invariant_leaves_match_lowered_scan():
    # The policy list traffic_audit prices and the rule enforces must agree
    # with the lowered program for a feature-rich tier too.
    cfg6, _ = PRESETS["config6"]
    assert jaxpr_audit.check_carry_passthrough(
        "jaxpr:config6/simulate", jaxpr_audit.scan_jaxpr(cfg6), cfg6
    ) == []


def test_recompile_fork_guard():
    # pre_vote genuinely forks the program: the guard must see it on BOTH the
    # plain scan and the scenario (genome-path) scan ...
    got = jaxpr_audit.check_recompile_forks((("config3", {"pre_vote": True}),))
    assert [f.rule for f in got] == ["recompile-fork"] * 4
    assert {f.path for f in got} == {
        "jaxpr:config3/simulate", "jaxpr:config3/scenario_simulate",
        "jaxpr:config3/serve_simulate", "jaxpr:config3/trace_simulate",
    }
    # ... while a tuning-only change must not (one standing pair, cheap) --
    # and on the scenario program that includes the fault knobs themselves:
    # genomes exist so fault sweeps are data, never compiles.
    assert jaxpr_audit.check_recompile_forks(
        (("config2", {"client_interval": 12}),)
    ) == []


def test_large_constant_rule():
    import numpy as np

    table = jnp.asarray(np.arange(50_000, dtype=np.int32))
    bad = jax.make_jaxpr(lambda i: table[i])(jax.ShapeDtypeStruct((), jnp.int32))
    assert [f.rule for f in jaxpr_audit.check_large_constants("jaxpr:neg", bad)] \
        == ["large-constant"]


# -------------------------------------------------- contract + schema rules


def test_types_comments_parse_and_hold():
    specs, problems = policy.parse_types_comments()
    assert problems == []
    # Full field coverage: every field of the four structures has a contract.
    # v22: +member_old/member_new/cfg_epoch/cfg_pend (joint-consensus
    # membership plane), +xfer_to (TimeoutNow), +read_idx/read_tick/read_acks
    # (ReadIndex slot); v24: +log_cfg (config-entry plane) +base_mold/
    # base_pend/base_epoch (snapshot config context); v25: +dur_len/dur_term/
    # dur_vote (storage plane's durable watermarks)
    assert len(specs["ClusterState"]) == 41  # v23: +read_fr (lease anchor)
    # v24: +req_disrupt +ent_cfg +req_base_mold/req_base_pend/req_base_epoch
    assert len(specs["Mailbox"]) == 28  # v22: +xfer_tgt
    # v25: +fsync_fire/torn_drop (disk-fault lattice draws)
    assert len(specs["StepInputs"]) == 13  # v22: +reconfig/transfer/read cmds
    # v25: +fsync_lag_sum/fsync_lag_max (durability-lag SLI counters)
    assert len(specs["StepInfo"]) == 22  # v23: +viol_read_stale
    assert ast_lint.check_dtype_comments() == []


def test_dtype_comment_rule_fires_on_drift():
    src = (
        "class ClusterState(NamedTuple):\n"
        "    role: jax.Array  # [N] int8\n"  # actually int32
    )
    specs, problems = policy.parse_types_comments(
        "import jax\nfrom typing import NamedTuple\n" + src
    )
    assert problems == []
    spec = specs["ClusterState"]["role"]
    assert policy.resolve_dtypes(spec, CFG3) == {jnp.dtype(jnp.int8)}
    state, _, _ = policy.state_avals(CFG3)
    assert state.role.dtype not in policy.resolve_dtypes(spec, CFG3)


def test_checkpoint_version_rule(monkeypatch):
    assert ast_lint.check_checkpoint_version() == []
    # Seeded negative: a field change that was not pinned (hash drifts).
    monkeypatch.setattr(
        checkpoint, "_SCHEMA_FINGERPRINT",
        (checkpoint._FORMAT_VERSION, "deadbeefdeadbeef"),
    )
    got = ast_lint.check_checkpoint_version()
    assert [f.rule for f in got] == ["checkpoint-version"]
    assert "bump _FORMAT_VERSION" in got[0].message
    # Second negative: fingerprint refreshed but version pin left behind.
    monkeypatch.setattr(
        checkpoint, "_SCHEMA_FINGERPRINT", (18, policy.schema_fingerprint())
    )
    got = ast_lint.check_checkpoint_version()
    assert [f.rule for f in got] == ["checkpoint-version"]
    assert "refresh the pin alongside" in got[0].message


def test_checkpoint_serialization_round_trip():
    assert ast_lint.check_checkpoint_serialization() == []


def test_checkpoint_version_is_exported():
    import raft_sim_tpu

    assert raft_sim_tpu.CHECKPOINT_FORMAT_VERSION == checkpoint._FORMAT_VERSION
    assert checkpoint.FORMAT_VERSION == checkpoint._FORMAT_VERSION


def test_checkpoint_mismatch_error_names_versions(tmp_path, monkeypatch):
    from raft_sim_tpu.sim.scan import init_metrics_batch
    from raft_sim_tpu.types import init_batch
    from raft_sim_tpu.utils.config import RaftConfig

    cfg = RaftConfig(n_nodes=2, log_capacity=4, max_entries_per_rpc=1)
    key = jax.random.key(0)
    path = checkpoint.save(
        str(tmp_path / "ck"), cfg, init_batch(cfg, key, 1),
        jax.random.split(key, 1), init_metrics_batch(1),
    )
    monkeypatch.setattr(checkpoint, "_FORMAT_VERSION", checkpoint._FORMAT_VERSION + 1)
    with pytest.raises(ValueError) as ex:
        checkpoint.load(path)
    msg = str(ex.value)
    assert f"written as format v{checkpoint._FORMAT_VERSION - 1}" in msg
    assert f"reads v{checkpoint._FORMAT_VERSION}" in msg
    assert "version log" in msg


# ------------------------------------------------- findings + waiver engine


def _finding(rule="traced-branch", path="raft_sim_tpu/sim/x.py", msg="boom in f()"):
    return F.Finding(rule=rule, path=path, message=msg, line=3)


def test_waiver_matching_and_stale_reporting():
    found = [_finding(), _finding(path="raft_sim_tpu/sim/y.py")]
    waivers = [
        {"rule": "traced-branch", "path": "raft_sim_tpu/sim/x.py",
         "contains": "f()", "reason": "host-side"},
        {"rule": "float-op", "path": "nowhere.py", "reason": "stale"},
    ]
    unused = F.apply_waivers(found, waivers)
    assert found[0].waived and found[0].waiver_reason == "host-side"
    assert not found[1].waived
    assert unused == [waivers[1]]
    # `contains` mismatch must not waive.
    f2 = [_finding(msg="other message")]
    assert F.apply_waivers(f2, [waivers[0]]) == [waivers[0]]
    assert not f2[0].waived


def test_report_schema_validates_and_catches_corruption():
    found = [_finding()]
    F.apply_waivers(found, [])
    doc = F.report(found)
    assert F.validate(doc) == []
    assert F.validate(json.loads(json.dumps(doc))) == []  # survives JSON round trip
    bad = dict(doc, n_unwaived=0)
    assert F.validate(bad) != []
    bad2 = dict(doc, findings=[{k: v for k, v in doc["findings"][0].items()
                                if k != "rule"}])
    assert F.validate(bad2) != []


def test_waiver_file_format_errors_are_loud(tmp_path):
    p = tmp_path / "w.json"
    p.write_text("{not json")
    entries, problems = F.load_waivers(str(p))
    assert entries == [] and problems
    p.write_text(json.dumps({"schema_version": 1, "waivers": [{"rule": "r"}]}))
    entries, problems = F.load_waivers(str(p))
    assert problems  # missing path/reason
    # A non-dict entry is a reported problem, never a crash.
    p.write_text(json.dumps({"schema_version": 1, "waivers": ["oops"]}))
    entries, problems = F.load_waivers(str(p))
    assert entries == [] and any("must be an object" in m for m in problems)
    assert F.load_waivers(str(tmp_path / "missing.json")) == ([], [])


@pytest.mark.slow  # budget re-tier (PR 12): gate integrity for PARTIAL
# runs (a --ast-only run must not mark jaxpr-pass waivers stale) -- the
# full-run staleness path stays tier-1 via test_tree_gates_clean and the
# CI check job runs --all on every push, so a regression here cannot land
# silently; the partial-run permutation (two full pass invocations) rides
# the slow tier.
def test_partial_run_does_not_report_other_passes_waivers_stale():
    # The standing waivers belong to the AST pass; a jaxpr-only run must not
    # condemn them as stale (they were never given a chance to match).
    found, unused, problems, timings = run.run_all(
        do_ast=False, do_cost=False, do_race=False, do_range=False,
        config_names=("config3",)
    )
    assert set(timings) == {"jaxpr"}
    assert problems == []
    assert unused == []
    assert [f for f in found if not f.waived] == []


def test_structural_hash_sees_params_not_literals():
    x = jax.ShapeDtypeStruct((5, 5), jnp.int32)
    h0 = jaxpr_audit.structural_hash(jax.make_jaxpr(lambda p: jnp.sum(p, axis=0))(x))
    h1 = jaxpr_audit.structural_hash(jax.make_jaxpr(lambda p: jnp.sum(p, axis=1))(x))
    # Same avals everywhere on a square input; only the reduce axes param
    # differs -- the hash must still fork.
    assert h0 != h1
    # Literal-only differences must NOT fork.
    g0 = jaxpr_audit.structural_hash(jax.make_jaxpr(lambda p: p + 3)(x))
    g1 = jaxpr_audit.structural_hash(jax.make_jaxpr(lambda p: p + 7)(x))
    assert g0 == g1


# --------------------------------------------------------------- gate status


def test_tree_gates_clean_ast_pass():
    """The merged tree has zero unwaived AST/contract findings (the jaxpr and
    cost passes run as the tools/check.py CI gate; their per-rule coverage on
    the real kernels is pinned by the tests above and by
    tests/test_cost_model.py)."""
    found, unused, problems, _ = run.run_all(
        do_jaxpr=False, do_cost=False, do_range=False)
    assert problems == []
    assert unused == [], f"stale waivers: {unused}"
    unwaived = [f for f in found if not f.waived]
    assert unwaived == [], "\n".join(
        f"{f.rule} {f.location()}: {f.message}" for f in unwaived
    )
