"""Pass D: host<->device concurrency audit -- the static legs.

Four standing loops donate their fleet carry between chunks
(`sim/chunked.run_chunked`, `sim/telemetry.run_chunked_telemetry`,
`serve/loop.ServeSession`, and -- via the non-donating eval path --
`farm/core.run_farm`) while host code deliberately works INSIDE the
dispatch->sync window (the serve loop's overlapped export/pack, the health
plane's folds, the evidence hooks). The race class this invites is
use-after-donate: a host reference still pointing at buffers the previous
dispatch handed back to XLA. On backends that really alias (TPU), a late read
returns torn or recycled memory; on CPU, donation is ignored and the bug sits
latent until the first chip session. This pass makes the discipline a gated
fact instead of docstring prose. All rules are host-side AST dataflow -- no
lowering, no execution -- so the whole pass runs in well under a second.

Rules:

  race-use-after-donate      a reference aliasing a donated argument (the
                             name itself, a view derived from it, or a closure
                             that captured it) is read or retained after the
                             donating dispatch without being rebound from the
                             call's outputs. Donating entry points are
                             single-sourced from `policy.donating_entry_points`
                             (the registry Pass C's donation audit reads), so
                             the lint and the lowering pin can never cover
                             different sets. Blessed idioms: `_own_copy`
                             (the loops' up-front buffer-distinct copy) and
                             fetch-before-donate (`DeltaStream.begin_rounds`/
                             `finish_rounds`, enqueued on the device stream
                             behind the chunk) never alias the dead carry.
  race-window-mutation       host code between a donating dispatch and its
                             sync point (the overlap window) rebinds, mutates,
                             or deletes the in-flight carry root. The overlap
                             write-set is derived statically
                             (`overlap_write_sets`) and must stay disjoint
                             from the donated carry's reachable set -- PR 11's
                             "overlap is a perf.jsonl fact" as a CHECKED fact.
  race-key-reuse             a PRNG key is consumed twice (double draw, double
                             split, same-salt fold_in, or a draw mixed with
                             any other consumption) in `sim/faults.py`,
                             `scenario/`, or `farm/`. Deriving distinct
                             streams -- one split plus fold_ins with distinct
                             salts -- is the blessed discipline.
  race-sink-writer           an append-mode `open()` on a telemetry/health
                             stream outside the registered single-writer set
                             (`APPEND_OWNERS`): each .jsonl stream has exactly
                             one writer per scope (the truncate-on-rearm
                             discipline HealthWriter/TelemetrySink follow).
                             Stale registry rows are findings too.
  race-unregistered-donation a `donate_argnums` entry point missing from
                             `policy.donating_entry_points` (or a registered
                             donating entry whose decorator is gone): the
                             registry is self-checking in both directions.
  race-donation-poison       the RUNTIME leg's rule (analysis/sanitizer.py):
                             a sanitizer-armed standing-loop session either
                             tripped a poisoned-buffer access or diverged from
                             the plain run. Emitted by `tools/check.py --race
                             --dynamic`, never by the static pass.

Intentional exceptions go through the same waiver engine as Passes A/B/C
(analysis/waivers.json); docs/ANALYSIS.md has the catalogue and the
"writing overlap-safe host code" guidance.
"""

from __future__ import annotations

import ast
import functools
import os

from raft_sim_tpu.analysis import policy
from raft_sim_tpu.analysis.findings import Finding

# Every rule slug this pass can emit (run.run_all scopes stale-waiver
# detection to the passes that actually ran). race-donation-poison belongs to
# the dynamic leg (sanitizer.py) but is part of this pass's rule set.
RULES = frozenset({
    "race-use-after-donate", "race-window-mutation", "race-key-reuse",
    "race-sink-writer", "race-unregistered-donation", "race-donation-poison",
    "race-parse-error",
})

# Assigning THROUGH these calls never aliases the donated carry: _own_copy is
# the loops' up-front buffer-distinct copy (sim/chunked.py).
BLESSED_COPY_CALLS = frozenset({"_own_copy"})

# Calls that end the dispatch->sync overlap window: the loop is provably
# blocked on (a host copy of) the dispatched chunk's outputs after any of
# these. `end` counts only with its `sync=` keyword (obs/timer.py ChunkTimer).
SYNC_CALLS = frozenset({
    "block_until_ready", "device_get", "drain", "finish_rounds", "_collect",
})

# Host-side method wrappers around a donating entry point: calling one kills
# the named carry expression exactly like the entry point itself, and (when
# rebinds is True) rebinds it to the new carry before returning. Keyed by
# repo-relative path so a same-named method elsewhere is not misread.
DONATING_WRAPPERS: dict[str, dict[str, str]] = {
    "raft_sim_tpu/serve/loop.py": {"_dispatch": "self.state"},
}

# jax.random consumption classes for the key-stream discipline rule.
_RANDOM_DRAWS = frozenset({
    "bits", "bernoulli", "randint", "uniform", "normal", "choice",
    "categorical", "permutation", "exponential", "gamma", "laplace",
    "truncated_normal", "gumbel",
})
_RANDOM_CREATES = frozenset({"key", "PRNGKey", "wrap_key_data", "key_data"})

# The single-writer registry: every append-mode open() of a stream file in
# the package, keyed (repo-relative path, enclosing function). A second code
# path appending to the same stream -- or an append site this table does not
# know -- is a race-sink-writer finding; so is a stale row here. Stream names
# are documentation (the site key is what is enforced).
APPEND_OWNERS: dict[tuple[str, str], str] = {
    ("raft_sim_tpu/serve/deltas.py", "append_delta_rows"): "deltas.jsonl",
    ("raft_sim_tpu/serve/tenancy.py", "credit_windows"):
        "tenants/<name>/windows.jsonl",
    ("raft_sim_tpu/health/monitor.py", "append_health"): "health.jsonl",
    ("raft_sim_tpu/health/monitor.py", "append_alert"): "alerts.jsonl",
    ("raft_sim_tpu/farm/core.py", "append_hunt"):
        "members/<name>/hunt.jsonl",
    ("raft_sim_tpu/farm/core.py", "append_perf"): "perf.jsonl (farm dir)",
    ("raft_sim_tpu/utils/telemetry_sink.py", "append_windows"):
        "windows.jsonl",
    ("raft_sim_tpu/utils/telemetry_sink.py", "append_perf"): "perf.jsonl",
    ("raft_sim_tpu/utils/telemetry_sink.py", "append_trace"):
        "trace.jsonl + trace_windows.jsonl",
    ("raft_sim_tpu/utils/apply_log.py", "update"): "node_<i>.jsonl",
}


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _dotted(node) -> str | None:
    """Full dotted name of a Name/Attribute chain ('self.state'); None when
    the base is not a plain name (call results, literals)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _collect_reads(node, out: list[str]) -> None:
    """Maximal dotted names read inside an expression subtree. Subscripts read
    their base ('x[i]' reads 'x') and their index; lambda bodies are included
    with the lambda's own parameters shadowed out."""
    if isinstance(node, (ast.Name, ast.Attribute)):
        d = _dotted(node)
        if d is not None:
            out.append(d)
            return
    if isinstance(node, ast.Subscript):
        d = _dotted(node.value)
        if d is not None:
            out.append(d)
        else:
            _collect_reads(node.value, out)
        _collect_reads(node.slice, out)
        return
    if isinstance(node, ast.Lambda):
        inner: list[str] = []
        _collect_reads(node.body, inner)
        params = {a.arg for a in (
            *node.args.posonlyargs, *node.args.args, *node.args.kwonlyargs)}
        out.extend(d for d in inner if d.split(".")[0] not in params)
        return
    for child in ast.iter_child_nodes(node):
        _collect_reads(child, out)


def _flat_targets(node) -> list[str]:
    """Dotted names an assignment target binds (tuple unpacking included)."""
    if isinstance(node, (ast.Name, ast.Attribute, ast.Subscript)):
        base = node.value if isinstance(node, ast.Subscript) else node
        d = _dotted(base)
        return [d] if d is not None else []
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            out.extend(_flat_targets(elt))
        return out
    if isinstance(node, ast.Starred):
        return _flat_targets(node.value)
    return []


def _call_name(call: ast.Call) -> str:
    """Last segment of the called function's dotted name ('' if exotic)."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


@functools.lru_cache(maxsize=None)
def donating_signatures() -> dict:
    """{func name: (donated arg index, donated param name, registry label)}
    for every donated entry in `policy.donating_entry_points()`, with the
    parameter index parsed from each entry's own source file (so the lint's
    call-site matching can never disagree with the real signature)."""
    repo = _repo_root()
    sigs: dict[str, tuple[int, str, str]] = {}
    for e in policy.donating_entry_points():
        if e.donated_param is None:
            continue
        try:
            with open(os.path.join(repo, e.path)) as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.FunctionDef) and node.name == e.func:
                params = [a.arg for a in
                          (*node.args.posonlyargs, *node.args.args)]
                if e.donated_param in params:
                    sigs[e.func] = (
                        params.index(e.donated_param), e.donated_param,
                        e.label,
                    )
                break
    return sigs


def _donated_arg_expr(call: ast.Call, idx: int, pname: str):
    if idx < len(call.args) and not any(
        isinstance(a, ast.Starred) for a in call.args[:idx + 1]
    ):
        return call.args[idx]
    for kw in call.keywords:
        if kw.arg == pname:
            return kw.value
    return None


class _St:
    """Dataflow state at one program point of the donation lint."""

    __slots__ = ("dead", "anc", "window", "outs")

    def __init__(self):
        self.dead: dict[str, tuple[int, str]] = {}  # name -> (kill line, label)
        self.anc: dict[str, set[str]] = {}          # name -> view ancestors
        self.window: str | None = None              # in-flight carry root
        self.outs: set[str] = set()                 # donating call's raw outputs

    def copy(self) -> "_St":
        st = _St()
        st.dead = dict(self.dead)
        st.anc = {k: set(v) for k, v in self.anc.items()}
        st.window = self.window
        st.outs = set(self.outs)
        return st

    def merge(self, other: "_St") -> None:
        for k, v in other.dead.items():
            self.dead.setdefault(k, v)
        for k, v in other.anc.items():
            self.anc.setdefault(k, set()).update(v)
        self.window = self.window or other.window
        self.outs |= other.outs


def _is_prefix(name: str, root: str) -> bool:
    return name == root or name.startswith(root + ".")


class _DonationLint:
    """Use-after-donate + overlap-window dataflow over one function body.

    Statement-ordered walk (If branches forked and re-merged; loop bodies
    containing a donating call walked twice, so statements textually BEFORE
    the call are also checked in their post-donation next-iteration role).
    """

    def __init__(self, fn, path: str, findings: list[Finding],
                 write_sets: dict | None = None):
        self.fn = fn
        self.path = path
        self.findings = findings
        self.sigs = donating_signatures()
        self.wrappers = DONATING_WRAPPERS.get(path, {})
        self.closures: list[tuple[int, set[str]]] = []
        self.write_sets = write_sets

    # ------------------------------------------------------------- plumbing

    def run(self) -> None:
        self._walk(self.fn.body, _St())

    def _walk(self, stmts, st: _St) -> None:
        for stmt in stmts:
            self._proc(stmt, st)

    def _walk_loop(self, body, st: _St) -> None:
        self._walk(body, st)
        if any(
            isinstance(n, ast.Call)
            and (_call_name(n) in self.sigs or _call_name(n) in self.wrappers)
            for s in body for n in ast.walk(s)
        ):
            # Wraparound sweep: the loop's next iteration re-executes the
            # statements before the donating call with the kill state live.
            self._walk(body, st)

    def _proc(self, stmt, st: _St) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            free = {
                n.id for n in ast.walk(stmt) if isinstance(n, ast.Name)
            } - {a.arg for a in (
                *stmt.args.posonlyargs, *stmt.args.args, *stmt.args.kwonlyargs
            )}
            self.closures.append((stmt.lineno, free))
            return
        if isinstance(stmt, ast.ClassDef):
            return
        if isinstance(stmt, ast.If):
            self._check_reads(stmt.test, st, stmt.lineno)
            a, b = st.copy(), st.copy()
            self._walk(stmt.body, a)
            self._walk(stmt.orelse, b)
            a.merge(b)
            st.dead, st.anc, st.window, st.outs = a.dead, a.anc, a.window, a.outs
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._check_reads(stmt.iter, st, stmt.lineno)
            self._walk_loop(stmt.body, st)
            self._walk(stmt.orelse, st)
            return
        if isinstance(stmt, ast.While):
            self._check_reads(stmt.test, st, stmt.lineno)
            self._walk_loop(stmt.body, st)
            self._walk(stmt.orelse, st)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._check_reads(item.context_expr, st, stmt.lineno)
            self._walk(stmt.body, st)
            return
        if isinstance(stmt, ast.Try):
            self._walk(stmt.body, st)
            for h in stmt.handlers:
                hv = st.copy()
                self._walk(h.body, hv)
                st.merge(hv)
            self._walk(stmt.orelse, st)
            self._walk(stmt.finalbody, st)
            return
        self._simple(stmt, st)

    # ------------------------------------------------------ simple statements

    def _simple(self, stmt, st: _St) -> None:
        # Record escaping closures (late-binding: dangerous only for names the
        # donation kill leaves dead, checked at kill time below).
        for n in ast.walk(stmt):
            if isinstance(n, ast.Lambda):
                params = {a.arg for a in (
                    *n.args.posonlyargs, *n.args.args, *n.args.kwonlyargs)}
                free = {
                    x.id for x in ast.walk(n.body) if isinstance(x, ast.Name)
                } - params
                self.closures.append((n.lineno, free))

        targets: list[str] = []
        if isinstance(stmt, ast.Assign):
            self._check_reads(stmt.value, st, stmt.lineno)
            for t in stmt.targets:
                targets.extend(_flat_targets(t))
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            if stmt.value is not None:
                self._check_reads(stmt.value, st, stmt.lineno)
            if isinstance(stmt, ast.AugAssign):
                self._check_reads(stmt.target, st, stmt.lineno)
            targets.extend(_flat_targets(stmt.target))
        else:
            self._check_reads(stmt, st, stmt.lineno)

        donate = self._find_donating_call(stmt)

        # Overlap write-set audit: writes landing inside the dispatch->sync
        # window must stay disjoint from the in-flight carry.
        if st.window is not None and targets:
            if self.write_sets is not None:
                self.write_sets.setdefault(
                    f"{self.path}::{self.fn.name}", set()
                ).update(targets)
            allowed = donate is not None or self._carry_unpack(stmt, st)
            if not allowed:
                for t in targets:
                    if _is_prefix(t, st.window) or _is_prefix(st.window, t):
                        self.findings.append(Finding(
                            rule="race-window-mutation",
                            path=self.path,
                            line=stmt.lineno,
                            message=(
                                f"`{t}` is written inside the dispatch->sync "
                                f"overlap window of the in-flight carry "
                                f"`{st.window}` in {self.fn.name}(): host "
                                "code between a donating dispatch and its "
                                "sync must never rebind or mutate the carry "
                                "(docs/ANALYSIS.md, overlap-safe host code)"
                            ),
                        ))
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                for d in _flat_targets(t):
                    if st.window is not None and _is_prefix(d, st.window):
                        self.findings.append(Finding(
                            rule="race-window-mutation",
                            path=self.path,
                            line=stmt.lineno,
                            message=(
                                f"`del {d}` inside the dispatch->sync window "
                                f"of `{st.window}` in {self.fn.name}()"
                            ),
                        ))

        if donate is not None:
            call, dexpr, label, rebinds = donate
            self._kill(stmt, call, dexpr, label, targets, st,
                       rebinds=rebinds)
        # Any rebinding resurrects the name (and everything under it).
        for t in targets:
            for k in [k for k in st.dead if _is_prefix(k, t)]:
                del st.dead[k]
            st.anc.pop(t, None)
        # View-alias propagation: a call in the value produces fresh buffers
        # (device_get/np.asarray/jnp copies); a pure name/attr/subscript chain
        # is a VIEW of its roots and dies with them.
        if isinstance(stmt, ast.Assign) and targets:
            if not any(isinstance(n, ast.Call) for n in ast.walk(stmt.value)):
                roots: list[str] = []
                _collect_reads(stmt.value, roots)
                anc = set()
                for r in roots:
                    anc.add(r)
                    anc |= st.anc.get(r, set())
                if anc:
                    for t in targets:
                        st.anc[t] = set(anc)

        # Sync recognition closes the window (after the write check: a write
        # in the same statement still happened pre-sync).
        for n in ast.walk(stmt):
            if isinstance(n, ast.Call):
                name = _call_name(n)
                if name in SYNC_CALLS or (
                    name == "end" and any(kw.arg == "sync" for kw in n.keywords)
                ):
                    st.window = None

    def _carry_unpack(self, stmt, st: _St) -> bool:
        """`state, m, ... = out` where `out` holds a donating call's raw
        output tuple: the blessed rebind of the new carry."""
        if not isinstance(stmt, ast.Assign):
            return False
        d = _dotted(stmt.value)
        if d is not None and d in st.outs:
            return True
        call = next(
            (n for n in ast.walk(stmt.value) if isinstance(n, ast.Call)), None)
        return call is not None and _call_name(call) in BLESSED_COPY_CALLS

    def _find_donating_call(self, stmt):
        for n in ast.walk(stmt):
            if not isinstance(n, ast.Call):
                continue
            name = _call_name(n)
            if name in self.wrappers:
                # A donating-wrapper METHOD rebinds the carry to the new
                # chunk's output before returning: the carry name survives,
                # stale views/copies of the old carry do not.
                return n, self.wrappers[name], f"{self.path}::{name}", True
            if name in self.sigs:
                idx, pname, label = self.sigs[name]
                expr = _donated_arg_expr(n, idx, pname)
                if expr is not None:
                    d = _dotted(expr)
                    if d is not None:
                        return n, d, label, False
        return None

    def _kill(self, stmt, call, dexpr: str, label: str, targets, st: _St,
              rebinds: bool = False):
        newly = {dexpr}
        for n, ancs in st.anc.items():
            if any(_is_prefix(a, dexpr) or _is_prefix(dexpr, a) for a in ancs):
                newly.add(n)
        # Rebinding in the same statement keeps the name live (bound to the
        # NEW carry from this call's outputs); a wrapper rebinds internally.
        if rebinds:
            newly = {k for k in newly if not _is_prefix(k, dexpr)}
        for t in targets:
            newly = {k for k in newly if not _is_prefix(k, t)}
        # A closure that captured a name this kill leaves dead retains the
        # donated buffers past the dispatch (late binding does not save it:
        # the name is never rebound).
        for cl_line, free in self.closures:
            for k in sorted(newly):
                if "." not in k and k in free:
                    self.findings.append(Finding(
                        rule="race-use-after-donate",
                        path=self.path,
                        line=cl_line,
                        message=(
                            f"closure defined at line {cl_line} captures "
                            f"`{k}`, whose buffers are donated by "
                            f"{label} at line {stmt.lineno} and never "
                            f"rebound in {self.fn.name}(): fetch a host copy "
                            "before the dispatch (jax.device_get / _own_copy)"
                        ),
                    ))
        for k in newly:
            st.dead[k] = (stmt.lineno, label)
        if isinstance(stmt, ast.Assign):
            st.outs = {t for t in targets if "." not in t}
        st.window = dexpr

    def _check_reads(self, node, st: _St, lineno: int) -> None:
        if not st.dead:
            return
        reads: list[str] = []
        _collect_reads(node, reads)
        for d in reads:
            for dd, (kline, label) in st.dead.items():
                if _is_prefix(d, dd):
                    self.findings.append(Finding(
                        rule="race-use-after-donate",
                        path=self.path,
                        line=getattr(node, "lineno", lineno),
                        message=(
                            f"`{d}` is read after its buffers were donated "
                            f"to {label} at line {kline} in "
                            f"{self.fn.name}(): rebind it from the call's "
                            "outputs, or take a host copy before the "
                            "dispatch (jax.device_get / _own_copy)"
                        ),
                    ))
                    break


# ------------------------------------------------------- key-stream discipline


class _KeyStreamLint:
    """PRNG-key consumption discipline over one function: every jax.random
    consumption site must come from a fresh split/fold_in. Illegal: a second
    identical consumption (double draw, double split, same-salt fold_in) and
    a draw mixed with ANY other consumption of the same key. Legal (the
    faults.py idiom): one split plus fold_ins with distinct salts -- distinct
    derived streams. Rebinding a key name resets its ledger
    (`key, sub = split(key)` is the canonical refresh)."""

    def __init__(self, fn, path: str, findings: list[Finding]):
        self.fn = fn
        self.path = path
        self.findings = findings

    def run(self) -> None:
        self._walk(self.fn.body, {})

    def _walk(self, stmts, ledger: dict) -> None:
        for stmt in stmts:
            self._proc(stmt, ledger)

    def _proc(self, stmt, ledger: dict) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return
        if isinstance(stmt, ast.If):
            a = {k: dict(v) for k, v in ledger.items()}
            b = {k: dict(v) for k, v in ledger.items()}
            self._consume_in(stmt.test, a)
            self._consume_in(stmt.test, b)
            self._walk(stmt.body, a)
            self._walk(stmt.orelse, b)
            ledger.clear()
            for src in (a, b):
                for name, sigs in src.items():
                    dst = ledger.setdefault(name, {})
                    for sig, cnt in sigs.items():
                        dst[sig] = max(dst.get(sig, 0), cnt)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            head = stmt.iter if isinstance(stmt, (ast.For, ast.AsyncFor)) else stmt.test
            self._consume_in(head, ledger)
            self._walk(stmt.body, ledger)
            self._walk(stmt.orelse, ledger)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            self._walk(stmt.body, ledger)
            return
        if isinstance(stmt, ast.Try):
            self._walk(stmt.body, ledger)
            for h in stmt.handlers:
                self._walk(h.body, ledger)
            self._walk(stmt.orelse, ledger)
            self._walk(stmt.finalbody, ledger)
            return
        self._consume_in(stmt, ledger)
        targets: list[str] = []
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                targets.extend(_flat_targets(t))
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets.extend(_flat_targets(stmt.target))
        for t in targets:
            ledger.pop(t, None)

    def _consume_in(self, node, ledger: dict) -> None:
        for n in ast.walk(node):
            if not isinstance(n, ast.Call):
                continue
            fname = _call_name(n)
            parent = (
                _dotted(n.func.value)
                if isinstance(n.func, ast.Attribute) else None
            )
            # Only jax.random.* (or an explicit `random.` / `jrandom.` alias)
            # consumption sites count; same-named methods elsewhere do not.
            if parent is None or "random" not in parent.split("."):
                continue
            if fname in _RANDOM_CREATES:
                continue
            if fname in _RANDOM_DRAWS:
                sig = ("draw",)
            elif fname == "split":
                sig = ("split",)
            elif fname == "fold_in":
                salt = ast.unparse(n.args[1]) if len(n.args) > 1 else "?"
                sig = ("fold", salt)
            else:
                continue
            key = n.args[0] if n.args else None
            if key is None:
                for kw in n.keywords:
                    if kw.arg == "key":
                        key = kw.value
            kname = _dotted(key) if key is not None else None
            if kname is None:
                continue
            sigs = ledger.setdefault(kname, {})
            prior_draw = sigs.get(("draw",), 0) > 0
            sigs[sig] = sigs.get(sig, 0) + 1
            reuse = sigs[sig] > 1 or (
                sig == ("draw",) and len(sigs) > 1
            ) or (sig != ("draw",) and prior_draw)
            if reuse:
                self.findings.append(Finding(
                    rule="race-key-reuse",
                    path=self.path,
                    line=n.lineno,
                    message=(
                        f"PRNG key `{kname}` is consumed again "
                        f"({fname}) in {self.fn.name}() after an earlier "
                        "consumption: every jax.random call needs a fresh "
                        "split/fold_in stream -- a reused key repeats the "
                        "same randomness (sim/faults.py key discipline)"
                    ),
                ))


# ------------------------------------------------------------ per-file lints


def _lint_donation(tree, path: str, findings: list[Finding],
                   write_sets: dict | None = None) -> None:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _DonationLint(node, path, findings, write_sets).run()


def _key_scope(path: str) -> bool:
    parts = path.replace("\\", "/").split("/")
    return (
        path.endswith("sim/faults.py")
        or "scenario" in parts
        or "farm" in parts
    )


def _lint_keys(tree, path: str, findings: list[Finding]) -> None:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _KeyStreamLint(node, path, findings).run()


def _append_sites(tree, path: str):
    """(func name, lineno, stream hint) for every append-mode open() in the
    file, with the innermost enclosing function resolved by a parent walk."""
    func_of: dict[int, str] = {}

    def mark(node, fname):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mark(child, child.name)
            else:
                mark(child, fname)
        func_of[id(node)] = fname

    mark(tree, "<module>")
    sites = []
    for n in ast.walk(tree):
        if not (isinstance(n, ast.Call) and _call_name(n) == "open"):
            continue
        mode = None
        if len(n.args) > 1 and isinstance(n.args[1], ast.Constant):
            mode = n.args[1].value
        for kw in n.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                mode = kw.value.value
        if not (isinstance(mode, str) and "a" in mode):
            continue
        hint = next(
            (c.value for c in ast.walk(n)
             if isinstance(c, ast.Constant) and isinstance(c.value, str)
             and c.value.endswith(".jsonl")),
            "<unresolved stream>",
        )
        sites.append((func_of.get(id(n), "<module>"), n.lineno, hint))
    return sites


def _lint_sink_sites(tree, path: str, findings: list[Finding]):
    sites = _append_sites(tree, path)
    for fname, lineno, hint in sites:
        if (path, fname) not in APPEND_OWNERS:
            findings.append(Finding(
                rule="race-sink-writer",
                path=path,
                line=lineno,
                message=(
                    f"append-mode open() of {hint} in {fname}() is not in the "
                    "single-writer registry (race_audit.APPEND_OWNERS): each "
                    ".jsonl stream has exactly one writer per scope -- "
                    "register the owner (with justification) or route the "
                    "rows through the existing writer"
                ),
            ))
    return sites


def _donate_decorated(tree, path: str):
    """(func name, lineno) of every function carrying a donate_argnums
    decorator in the file."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            if any(
                isinstance(kw, ast.keyword) and kw.arg == "donate_argnums"
                for c in ast.walk(dec) if isinstance(c, ast.Call)
                for kw in c.keywords
            ):
                out.append((node.name, node.lineno))
    return out


def _lint_donate_registry(tree, path: str, findings: list[Finding]):
    decorated = _donate_decorated(tree, path)
    registered = {
        e.func for e in policy.donating_entry_points()
        if e.path == path and e.expected == "donated"
    }
    for fname, lineno in decorated:
        if fname not in registered:
            findings.append(Finding(
                rule="race-unregistered-donation",
                path=path,
                line=lineno,
                message=(
                    f"{fname}() has donate_argnums but is not in "
                    "policy.donating_entry_points: register it so the "
                    "use-after-donate lint and the runtime sanitizer cover "
                    "it (and Pass C can pin its aliasing)"
                ),
            ))
    return decorated


def _dedupe(findings: list[Finding]) -> list[Finding]:
    seen, out = set(), []
    for f in findings:
        key = (f.rule, f.path, f.line, f.message)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out


def lint_source(source: str, path: str,
                write_sets: dict | None = None) -> list[Finding]:
    """All per-file Pass D rules over one file's text. `path` (repo-relative)
    anchors findings and scopes the key-stream rule; tree-level reverse
    checks (stale APPEND_OWNERS rows, registry entries whose decorator is
    gone) live in `run_pass`."""
    try:
        tree = ast.parse(source)
    except SyntaxError as ex:
        return [Finding(rule="race-parse-error", path=path, line=ex.lineno or 0,
                        message=f"does not parse: {ex.msg}")]
    findings: list[Finding] = []
    _lint_donation(tree, path, findings, write_sets)
    if _key_scope(path):
        _lint_keys(tree, path, findings)
    _lint_sink_sites(tree, path, findings)
    _lint_donate_registry(tree, path, findings)
    return _dedupe(findings)


def _iter_package_files(root: str):
    repo = os.path.dirname(os.path.abspath(root.rstrip("/")))
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames if not d.startswith("__pycache__"))
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                full = os.path.join(dirpath, fn)
                yield full, os.path.relpath(full, repo)


def lint_tree(root: str, write_sets: dict | None = None) -> list[Finding]:
    """Per-file rules over every .py file under `root` (the raft_sim_tpu
    package dir) plus the tree-level reverse checks."""
    findings: list[Finding] = []
    seen_appends: set[tuple[str, str]] = set()
    seen_decorated: set[tuple[str, str]] = set()
    for full, rel in _iter_package_files(root):
        with open(full) as f:
            source = f.read()
        try:
            tree = ast.parse(source)
        except SyntaxError as ex:
            findings.append(Finding(
                rule="race-parse-error", path=rel, line=ex.lineno or 0,
                message=f"does not parse: {ex.msg}"))
            continue
        _lint_donation(tree, rel, findings, write_sets)
        if _key_scope(rel):
            _lint_keys(tree, rel, findings)
        for fname, _, _ in _lint_sink_sites(tree, rel, findings):
            seen_appends.add((rel, fname))
        for fname, _ in _lint_donate_registry(tree, rel, findings):
            seen_decorated.add((rel, fname))
    for (path, fname), stream in sorted(APPEND_OWNERS.items()):
        if (path, fname) not in seen_appends:
            findings.append(Finding(
                rule="race-sink-writer",
                path=path,
                message=(
                    f"APPEND_OWNERS registers {fname}() as the writer of "
                    f"{stream} but no append-mode open() exists there: "
                    "remove the stale registry row"
                ),
            ))
    for e in policy.donating_entry_points():
        if e.expected != "donated":
            continue
        if (e.path, e.func) not in seen_decorated:
            findings.append(Finding(
                rule="race-unregistered-donation",
                path=e.path,
                message=(
                    f"policy.donating_entry_points registers {e.func}() as "
                    "donating but it carries no donate_argnums decorator "
                    f"in {e.path}: fix the registry or the entry point"
                ),
            ))
    return _dedupe(findings)


def overlap_write_sets(package_root: str | None = None) -> dict[str, list[str]]:
    """The statically derived overlap write-set: for every function that
    dispatches a donating chunk, the host names written between dispatch and
    sync. The race-window-mutation rule proves each set disjoint from the
    in-flight carry; this surface is for docs/tests (the checked fact,
    printable)."""
    if package_root is None:
        package_root = os.path.join(_repo_root(), "raft_sim_tpu")
    sets: dict[str, set[str]] = {}
    lint_tree(package_root, write_sets=sets)
    return {k: sorted(v) for k, v in sorted(sets.items())}


def run_pass(package_root: str) -> list[Finding]:
    """The full static Pass D (the dynamic donation-poison leg is
    analysis/sanitizer.py, run via `tools/check.py --race --dynamic`)."""
    return lint_tree(package_root)
