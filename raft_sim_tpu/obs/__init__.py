"""Runtime performance observability: the seventh subsystem (docs/OBSERVABILITY.md).

The repo's perf story so far is *predictive*: analyzer Pass C derives
bytes/tick, live-set peak, and donation status from the lowered programs and
gates them against golden pins (analysis/cost_model.py). What it cannot see is
anything that happens at RUN time -- host stalls between chunks, dispatch
gaps, compile time bleeding into "steady state", device-memory pressure, a jit
cache quietly growing mid-soak. This package closes the loop:

- `timer.ChunkTimer` -- per-chunk runtime attribution woven into every
  standing loop (sim/chunked, sim/telemetry soak, serve/loop, scenario
  search): wall time split into dispatch / host gap / device wait, warmup vs
  steady state, chunk-boundary device-memory occupancy and jit-cache sizes,
  streamed as schema'd perf.jsonl into the telemetry sink. Off by default and
  host-side only: with it enabled no traced code changes and no new programs
  compile; with it disabled the loops are byte-identical to before.
- `reconcile` -- joins what a run *measured* (bench rows, perf.jsonl) against
  what Pass C *predicted* (tests/golden_cost_model.json): achieved bytes/s,
  roofline fraction per config, live-peak headroom -- with CPU / smoke /
  non-production rows explicitly marked non-anchor, so a CPU run can never
  rebase the roofline (the same trap class PR 5 closed for smoke rows).

The one-command consumer is `python bench.py --measurement-pass`
(docs/PERF.md "chip measurement-pass checklist").
"""

from raft_sim_tpu.obs.timer import ChunkTimer, device_live_bytes
from raft_sim_tpu.obs.reconcile import (
    load_pins,
    reconcile_matrix,
    reconcile_perf_dir,
    reconcile_row,
)

__all__ = [
    "ChunkTimer",
    "device_live_bytes",
    "load_pins",
    "reconcile_matrix",
    "reconcile_perf_dir",
    "reconcile_row",
]
