"""SLI math: fold one evaluation period's window units into indicator values.

The inputs are WINDOW UNITS -- per-window dicts of per-cluster numpy arrays
(sim/telemetry.py `window_cluster_counters` for the windowed loops; the plain
run_chunked path synthesizes one unit per chunk from metric deltas) -- plus
the period's perf.jsonl rows. Everything is host-side numpy over counters the
device already exported: no new lowerings, no trajectory impact.

Latency objectives count "good" events straight off the log2-binned
histograms: bin k holds latencies in [2^k, 2^(k+1)), so every bin whose
UPPER edge is <= the threshold is wholly good and partial bins count bad --
an exact threshold at powers of two, conservative elsewhere. Percentiles use
the same lower-edge-clamped linear interpolation as the mesh report
(parallel/mesh.py _hist_percentile; tests pin the two against each other).
"""

from __future__ import annotations

import numpy as np

from raft_sim_tpu.types import LAT_HIST_BINS


def hist_percentile(hist, q: float) -> float | None:
    """q-quantile estimate from a log2-binned latency histogram (bin k =
    [2^k, 2^(k+1))): linear interpolation within the hit bin, clamped to the
    lower edge when the bin is the first nonempty one. None on an empty
    histogram. Same estimator as parallel/mesh.py's mesh report -- the health
    plane and the mesh summaries must never disagree on a percentile."""
    hist = np.asarray(hist, dtype=np.int64)
    total = int(hist.sum())
    if total == 0:
        return None
    need = q * total
    cum = 0
    for k in range(len(hist)):
        c = int(hist[k])
        if c and cum + c >= need:
            lo, hi = float(1 << k), float(1 << (k + 1))
            if cum == 0:
                return lo
            return lo + (need - cum) / c * (hi - lo)
        cum += c
    return float(1 << len(hist))


def fast_bins(threshold_ticks: int) -> int:
    """Number of leading histogram bins wholly under the threshold: bins
    0..n-1 cover [1, 2^n), so latency < threshold exactly when the threshold
    is a power of two, conservatively (partial bin counts bad) otherwise."""
    n = 0
    while n < LAT_HIST_BINS and (1 << (n + 1)) <= threshold_ticks:
        n += 1
    return n


def _sum_field(units: list[dict], key: str) -> np.ndarray:
    """Per-cluster sum of an int counter across the period's units."""
    return np.sum([u[key] for u in units], axis=0, dtype=np.int64)


def compute_slis(spec: dict, units: list[dict], perf_rows: list[dict]) -> dict:
    """Evaluate every objective over one period. Returns
        {"slis":     {name: indicator values (floats/ints, JSON-able)},
         "errs":     {name: bad-event fraction in [0, 1]},
         "budgets":  {name: error budget (0 = page on any error)},
         "percluster": {name: [B] triage metric or None (no cluster axis)}}
    `errs`/`budgets` feed burn.BurnEngine; `percluster` feeds triage."""
    batch = len(units[0]["violations"])
    n = len(units)
    steady = [r for r in perf_rows if not r.get("warmup")]
    slis: dict = {}
    errs: dict = {}
    budgets: dict = {}
    percluster: dict = {}
    for name, obj in spec["objectives"].items():
        kind = obj["sli"]
        if kind == "availability":
            leaderless = _sum_field(units, "leaderless")  # [B] window counts
            bad = int(leaderless.sum())
            total = batch * n
            err = bad / total
            slis[name] = {
                "availability": round(1.0 - err, 6),
                "leaderless_cluster_windows": bad,
            }
            errs[name] = err
            budgets[name] = 1.0 - obj["target"]
            percluster[name] = leaderless.astype(np.float64)
        elif kind == "commit_latency":
            hist = _sum_field(units, "lat_hist")  # [B, BINS]
            nb = fast_bins(obj["threshold_ticks"])
            fast = hist[:, :nb].sum(axis=1)
            slow = hist.sum(axis=1) - fast
            total = int(hist.sum())
            fleet = hist.sum(axis=0)
            slis[name] = {
                "p50": hist_percentile(fleet, 0.50),
                "p95": hist_percentile(fleet, 0.95),
                "p99": hist_percentile(fleet, 0.99),
                "measured": total,
                "slow": int(slow.sum()),
            }
            errs[name] = (int(slow.sum()) / total) if total else 0.0
            budgets[name] = 1.0 - obj["target"]
            percluster[name] = slow.astype(np.float64)
        elif kind == "read_staleness":
            hist = _sum_field(units, "read_hist")
            nb = fast_bins(obj["stale_after_ticks"])
            fresh = hist[:, :nb].sum(axis=1)
            stale = hist.sum(axis=1) - fresh
            total = int(hist.sum())
            fleet = hist.sum(axis=0)
            slis[name] = {
                "p99": hist_percentile(fleet, 0.99),
                "measured": total,
                "stale": int(stale.sum()),
            }
            errs[name] = (int(stale.sum()) / total) if total else 0.0
            budgets[name] = 1.0 - obj["target"]
            percluster[name] = stale.astype(np.float64)
        elif kind == "throughput":
            ops = _sum_field(units, "cmds") + _sum_field(units, "reads")  # [B]
            per_window = int(ops.sum()) / n
            floor = obj["min_ops_per_window"]
            slis[name] = {"ops_per_window": round(per_window, 3), "floor": floor}
            errs[name] = 1.0 if (floor > 0 and per_window < floor) else 0.0
            budgets[name] = obj["budget"]
            # Triage metric: each cluster's deficit vs the fleet mean -- the
            # clusters dragging the floor down, not the busiest ones.
            mean = ops.sum() / batch
            percluster[name] = np.maximum(mean - ops, 0.0).astype(np.float64)
        elif kind == "safety":
            viol = _sum_field(units, "violations")
            bad = int(viol.sum())
            slis[name] = {"violations": bad}
            errs[name] = 1.0 if bad else 0.0
            budgets[name] = 0.0
            percluster[name] = viol.astype(np.float64)
        elif kind == "device_wait_share":
            wall = sum(r["wall_s"] for r in steady)
            wait = sum(r["device_wait_s"] for r in steady)
            share = (wait / wall) if wall > 0 else None
            floor = obj["min_share"]
            slis[name] = {
                "share": round(share, 6) if share is not None else None,
                "steady_chunks": len(steady),
            }
            errs[name] = (
                1.0 if (share is not None and floor > 0 and share < floor)
                else 0.0
            )
            budgets[name] = obj["budget"]
            percluster[name] = None  # runtime SLI: no cluster axis
        elif kind == "recompiles":
            bad = sum(1 for r in steady if r.get("recompiled"))
            slis[name] = {"recompiled_chunks": bad, "steady_chunks": len(steady)}
            errs[name] = 1.0 if bad else 0.0
            budgets[name] = 0.0
            percluster[name] = None
        elif kind == "durability_lag":
            # Storage-plane durability debt (window units fsync_lag_sum/max):
            # the page signal is the WORST instantaneous per-node lag in the
            # period vs the ceiling -- a disk stalled behind its log is a
            # local fact, so the fleet mean would hide exactly the cluster
            # that matters. The mean rides along as the trend readout.
            lagmax = np.max([u["fsync_lag_max"] for u in units], axis=0)  # [B]
            lag_sum = _sum_field(units, "fsync_lag_sum")
            ticks = sum(int(u["ticks"]) for u in units)
            ceiling = obj["max_lag"]
            worst = int(lagmax.max())
            slis[name] = {
                "max_lag": worst,
                "lag_per_tick": round(float(lag_sum.sum()) / ticks, 3)
                if ticks else None,
                "ceiling": ceiling,
            }
            errs[name] = 1.0 if (ceiling > 0 and worst > ceiling) else 0.0
            budgets[name] = obj["budget"]
            percluster[name] = lagmax.astype(np.float64)
        else:  # pragma: no cover - load_spec validates kinds
            raise ValueError(f"unknown sli kind {kind!r}")
    return {
        "slis": slis, "errs": errs, "budgets": budgets,
        "percluster": percluster,
    }
