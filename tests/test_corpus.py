"""Regression corpus: every bug the violation hunt ever found stays found.

tests/corpus/ holds shrunk `scenario-repro-v1` artifacts (scenario/shrink.py)
-- one per historical hunt hit, named `<mutant>-<topology>.json`. Each must
replay BIT-EXACTLY (identical violation tick AND kinds) via tools/repro.py,
the same replayer CI's scenario smoke uses: a drifting replay means the
(genome, seed, kernel) bookkeeping broke, and a clean replay of a mutant
artifact on a FIXED kernel would mean the regression resurfaced the bug's
preconditions without its effect -- either way the corpus is the tripwire.

Artifacts are deliberately SMALL (N=5, short horizons): replaying the corpus
costs one tiny scan compile per artifact, so it can grow by dozens before
threatening the tier-1 budget. Seed additions: the weak-quorum election-
safety hit and the blind-transfer commit-invariant hit (the PR-10
reconfiguration plane's coup mutant), both hunted, shrunk, and frozen here;
PR 11 adds the lease-skew read-staleness hit (a skewed-clock lease violation
-- the shrink RETAINED clock skew and partitions, the clock assumption made
load-bearing; tests/test_lease.py pins the real kernel clean on the same
genome).
"""

from __future__ import annotations

import glob
import os
import subprocess
import sys

import pytest

CORPUS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "corpus")
ARTIFACTS = sorted(glob.glob(os.path.join(CORPUS_DIR, "*.json")))


def test_corpus_is_seeded():
    """The corpus exists and carries at least the two seed artifacts."""
    names = {os.path.basename(p) for p in ARTIFACTS}
    assert "weak-quorum-n5.json" in names
    assert "blind-transfer-n5.json" in names
    assert "lease-skew-n5.json" in names


@pytest.mark.parametrize(
    "artifact", ARTIFACTS, ids=[os.path.basename(p) for p in ARTIFACTS]
)
def test_corpus_artifact_replays_bit_exactly(artifact):
    repo = os.path.dirname(CORPUS_DIR.rstrip(os.sep)).rsplit(os.sep, 1)[0]
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "repro.py"),
         "--scenario", artifact],
        capture_output=True,
        text=True,
        timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, (
        f"{os.path.basename(artifact)} did not replay bit-exactly "
        f"(exit {proc.returncode}):\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    )
