"""TEST-ONLY weakened kernel variants: the search loop's ground truth.

A violation hunter that never finds anything proves nothing -- maybe the
kernel is safe, maybe the hunt is blind. These config subclasses weaken the
kernel behind an explicit opt-in (driver `scenario search --mutant`, CI's
scenario smoke job, tests/test_scenario.py) so the search demo has a target
it MUST hit within a bounded generation budget: if the hunt cannot drive a
quorum-off-by-one kernel to an election-safety violation, the hunt is
broken, not the kernel. Never instantiate these outside tests/demos; the
class is deliberately NOT reachable from RaftConfig flags or scenario files.

The weakening rides the config (cfg.quorum feeds both kernels' vote counts
and commit rule), so no second kernel source exists to drift: the mutant
compiles the same step code at a different quorum literal -- one extra jit
compile, zero extra lowered program structures (literal-blind hashes equal;
analysis/jaxpr_audit.py structural_hash).
"""

from __future__ import annotations

from raft_sim_tpu.utils.config import RaftConfig


class WeakQuorumConfig(RaftConfig):
    """quorum - 1: floor(N/2) instead of floor(N/2)+1, so two split-vote
    candidates can both 'win' a term -- the reference's even-N majority bug
    (SURVEY.md quorum note) made unconditional. Election safety violates
    within a few elections once message drop forces vote splits."""

    @property
    def quorum(self) -> int:  # type: ignore[override]
        return self.n_nodes // 2


class SingleServerChangeConfig(RaftConfig):
    """Single-server membership change (cfg.joint_consensus False): every
    config change is ONE log entry that switches the configuration wholly at
    append -- no joint phase, no completing entry. The known-unsafe
    interleaving (thesis 4.1 / 4.3's motivating bug): two leaders'
    uncommitted single-entry changes yield majorities that need not
    intersect, so a leader missing committed entries gets elected and
    replicates its short log over them. Requires cfg.reconfig
    (reconfig_interval > 0)."""

    @property
    def joint_consensus(self) -> bool:  # type: ignore[override]
        return False


class ActOnCommitConfig(RaftConfig):
    """Configs applied at COMMIT instead of append (cfg.act_on_append
    False): each node derives its configuration from the committed prefix --
    the dissertation-ch.-4 anti-rule. Nodes then disagree about when a
    change takes effect (a config entry's commit is itself judged under some
    config), and the old configuration keeps electing leaders the new one
    cannot see: disjoint quorums, same-term double leadership. Requires
    cfg.reconfig (reconfig_interval > 0)."""

    @property
    def act_on_append(self) -> bool:  # type: ignore[override]
        return False


class IgnoreTruncationRollbackConfig(RaftConfig):
    """Truncation rollback skipped (cfg.truncation_rollback False): a node
    whose truncated log LOST config entries keeps acting on the stale
    derived configuration -- quorums drawn from member sets no log chain
    ever contained (a briefly-held uncommitted change survives its own
    truncation as a phantom electorate). Requires cfg.reconfig
    (reconfig_interval > 0)."""

    @property
    def truncation_rollback(self) -> bool:  # type: ignore[override]
        return False


class StaleReadConfig(RaftConfig):
    """ReadIndex without the confirmation round OR the current-term-commit
    capture gate (cfg.read_confirm False): a deposed leader stranded in a
    minority partition keeps serving reads from its stale commit state --
    reads below the committed frontier, the linearizability break the trace
    checker's read_linearizability property must reject. Requires
    cfg.read_index (read_interval > 0)."""

    @property
    def read_confirm(self) -> bool:  # type: ignore[override]
        return False


class BlindTransferConfig(RaftConfig):
    """TimeoutNow as a coup (cfg.xfer_election False): the leader fires
    without waiting for the target to catch up, and the target assumes
    leadership DIRECTLY -- no vote round, no up-to-date check -- so a behind
    target truncates committed entries off its followers (commit-invariant /
    leader-completeness breaks). Requires cfg.leader_transfer
    (transfer_interval > 0)."""

    @property
    def xfer_election(self) -> bool:  # type: ignore[override]
        return False


class LeaseSkewConfig(RaftConfig):
    """Lease reads judged on a no-skew clock model (cfg.lease_skew_safe
    False): the kernel serves lease reads for election_min_ticks + 2 global
    ticks instead of the configured skew-safe read_lease_ticks. Correct when
    every local clock advances exactly 1/tick; under clock skew a fast
    follower's lease-vote-denial window halves in global time, a new leader
    elects and commits INSIDE the optimistic lease, and the partitioned old
    leader serves a read below the committed frontier -- viol_read_stale on
    device (the hunt's fitness signal, driven by the skew genome axis) and a
    read_linearizability rejection from the trace checker. Requires
    cfg.read_lease (read_lease_ticks > 0)."""

    @property
    def lease_skew_safe(self) -> bool:  # type: ignore[override]
        return False


class AckBeforeFsyncConfig(RaftConfig):
    """Acks reflect volatile state (cfg.durable_acks False): an
    AppendEntries ack names entries whose fsync has not completed, and the
    leader's own self-match reads log_len instead of the durable watermark
    -- the canonical ack-before-fsync storage bug. A leader counts such an
    ack toward commit, the acking follower crashes, recovery truncates the
    un-fsynced suffix, and a committed entry exists on no quorum: a later
    leader elects without it and commits below the frontier
    (leader_completeness), and the AE that re-extends the deposed leader
    mutates its committed prefix (the device commit invariant --
    state_machine_safety). The disk itself stays honest -- only the
    acknowledgment lies. Requires cfg.durable_storage
    (fsync_interval > 0)."""

    @property
    def durable_acks(self) -> bool:  # type: ignore[override]
        return False


class VolatileVoteConfig(RaftConfig):
    """Crash recovery forgets votedFor (cfg.persist_vote False): term and
    log restore from the durable snapshot but the vote does not -- the
    reference's own restart bug (log.clj:16-18, SURVEY.md 2.3.12) expressed
    inside the storage plane. A voter grants, crashes, restarts with
    voted_for == NIL, and grants AGAIN in the same term to a different
    candidate: two leaders in one term (election_safety). Requires
    cfg.durable_storage (fsync_interval > 0)."""

    @property
    def persist_vote(self) -> bool:  # type: ignore[override]
        return False


MUTANTS = {
    "weak-quorum": WeakQuorumConfig,
    "single-server-change": SingleServerChangeConfig,
    # Back-compat alias (pre-ISSUE-13 name for the joint_consensus=False
    # weakening; under log-carried configs its precise shape is the
    # single-server change).
    "joint-bypass": SingleServerChangeConfig,
    "act-on-commit": ActOnCommitConfig,
    "ignore-truncation-rollback": IgnoreTruncationRollbackConfig,
    "stale-read": StaleReadConfig,
    "blind-transfer": BlindTransferConfig,
    "lease-skew": LeaseSkewConfig,
    "ack-before-fsync": AckBeforeFsyncConfig,
    "volatile-vote": VolatileVoteConfig,
}


def mutant_config(name: str, cfg: RaftConfig) -> RaftConfig:
    """Rebuild `cfg` under the named mutant class (same field values)."""
    import dataclasses

    if name not in MUTANTS:
        raise ValueError(f"unknown mutant {name!r} (have {sorted(MUTANTS)})")
    return MUTANTS[name](**dataclasses.asdict(cfg))
