"""Metrics v2: the carried commit-latency frontier, the per-entry latency
histogram, the no-op liveness counter, and log-matching sampling + skipped-pair
coverage.

The reference has no metrics at all beyond its println trace (core.clj:182-186);
these measurement surfaces are north-star machinery, so their accuracy gets its
own unit tier: the frontier tests pin the restart-regression dedup bug the
round-4 advisor found, the histogram tests pin that true percentiles are
recoverable, and the sampling tests pin that a real violation is still caught on
check ticks.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from raft_sim_tpu import CANDIDATE, LEADER, RaftConfig, types
from raft_sim_tpu.ops import bitplane
from raft_sim_tpu.parallel import summarize
from raft_sim_tpu.parallel.mesh import _hist_percentile
from raft_sim_tpu.sim import scan
from tests.test_compaction import CFG as RING_CFG
from tests.test_compaction import hist, with_ring_log
from tests.test_handlers import base_state, make_leader, quiet_inputs, step, with_log


# ------------------------------------------------------- commit-latency frontier

CLIENT_CFG = RaftConfig(n_nodes=5, log_capacity=8, client_interval=8)


def _committing_leader(node=0, frontier=0):
    """Node `node` is a leader whose full-match quorum advances commit 0 -> 3 on
    the next tick, over three tick-encoded client entries (values 100..102)."""
    s = base_state(CLIENT_CFG)
    s = with_log(s, node, [1, 1, 1])  # values 100 + slot
    s = make_leader(s, node, 1)
    s = s._replace(
        match_index=s.match_index.at[node].set(
            jnp.full((5,), 3, s.match_index.dtype)
        ),
        now=jnp.int32(200),  # values 100..102 lie in (0, now): tick-plausible
        lat_frontier=jnp.int32(frontier),
    )
    return s


def test_latency_counts_first_commit():
    s2, info = step(CLIENT_CFG, _committing_leader(frontier=0))
    assert int(s2.commit_index[0]) == 3
    assert int(info.lat_cnt) == 3
    # now=200, values 100..102 -> latencies 101, 100, 99 (now - value + 1)
    assert int(info.lat_sum) == 300
    # The frontier advances to the new commit maximum.
    assert int(s2.lat_frontier) == 3


def test_latency_frontier_blocks_recount():
    """Entries below the carried frontier never re-count, even though this
    leader's own commit advancement crosses them."""
    s2, info = step(CLIENT_CFG, _committing_leader(frontier=3))
    assert int(s2.commit_index[0]) == 3
    assert int(info.lat_cnt) == 0
    assert int(s2.lat_frontier) == 3


def test_latency_frontier_survives_restart():
    """The round-4 advisor finding: the old frontier was the max of the per-node
    commit vector, which a restarting max-commit node REGRESSES (commit wipes to
    log_base), so a leader re-advancing commit re-counted reported entries. The
    carried frontier is monotone: a restart on the same tick as the re-advance
    must contribute zero."""
    s = _committing_leader(node=1, frontier=3)
    # Node 0 held the cluster's old max commit (3) and restarts this tick.
    s = with_log(s, 0, [1, 1, 1])
    s = s._replace(commit_index=s.commit_index.at[0].set(3))
    s = types.with_commit_chk(s)
    inp = quiet_inputs(CLIENT_CFG)
    inp = inp._replace(restarted=inp.restarted.at[0].set(True))
    s2, info = step(CLIENT_CFG, s, inp)
    assert int(s2.commit_index[0]) == 0  # restart wiped to log_base
    assert int(s2.commit_index[1]) == 3  # leader re-advanced past old ground
    assert int(info.lat_cnt) == 0  # ... but nothing re-counted
    assert int(s2.lat_frontier) == 3


# ------------------------------------------------- latency coverage (lat_excluded)


def test_lat_excluded_counts_leaderless_frontier_advance():
    """The documented coverage gap, now measured: when the frontier crosses
    committed client entries on a tick with NO live leader, nothing lands in
    lat_sum/lat_cnt/lat_hist -- lat_excluded must count exactly those."""
    s = base_state(CLIENT_CFG)
    s = with_log(s, 1, [1, 1, 1])  # values 100..102: tick-plausible at now=200
    s = s._replace(commit_index=s.commit_index.at[1].set(3), now=jnp.int32(200))
    s = types.with_commit_chk(s)
    s2, info = step(CLIENT_CFG, s)  # all followers: frontier advances uncounted
    assert int(info.lat_cnt) == 0
    assert int(info.lat_excluded) == 3
    assert int(s2.lat_frontier) == 3
    # Crossed-once semantics: the next tick the frontier has passed them.
    _, info2 = step(CLIENT_CFG, s2)
    assert int(info2.lat_excluded) == 0


def test_lat_excluded_zero_when_leader_attributes():
    """A live leader's own frontier advance is fully attributed: counted and
    excluded are mutually exclusive views of the same crossing."""
    s2, info = step(CLIENT_CFG, _committing_leader(frontier=0))
    assert int(info.lat_cnt) == 3
    assert int(info.lat_excluded) == 0


def test_lat_excluded_in_fleet_summary():
    """summarize surfaces the fleet total, and organic trajectories (where
    every frontier crossing happens at a live, counting leader -- the dead-
    sender delivery gate closes the documented gap) report zero."""
    cfg = RaftConfig(
        n_nodes=5, log_capacity=64, client_interval=4,
        crash_prob=0.3, crash_period=32, crash_down_ticks=8, drop_prob=0.1,
    )
    _, m = scan.simulate(cfg, 0, 16, 400)
    s = summarize(m)
    assert s.lat_excluded == int(np.sum(np.asarray(m.lat_excluded)))
    assert s.lat_excluded == 0  # the structural claim docs/PERF.md now makes


# ------------------------------------------------------------ latency histogram


def test_hist_percentile_interpolation():
    h = np.zeros(16, np.int64)
    h[2] = 10  # all latencies in [4, 8)
    assert 4.0 <= _hist_percentile(h, 0.5) < 8.0
    assert _hist_percentile(np.zeros(16, np.int64), 0.5) is None
    h2 = np.zeros(16, np.int64)
    h2[0], h2[3] = 1, 1
    assert _hist_percentile(h2, 0.99) >= 8.0  # tail lands in the high bin
    assert _hist_percentile(h2, 0.25) < 2.0


def test_hist_percentile_first_bin_clamps_to_lower_edge():
    """Round-5 advisor finding: interpolating inside the FIRST nonempty bin
    invents mass below the distribution's minimum -- a run whose every latency
    is exactly 1 tick (all counts in bin 0 = [1, 2)) must report every
    percentile as 1.0, not 1.5."""
    h = np.zeros(16, np.int64)
    h[0] = 1000
    assert _hist_percentile(h, 0.50) == 1.0
    assert _hist_percentile(h, 0.95) == 1.0
    assert _hist_percentile(h, 0.99) == 1.0
    # Same rule at a higher first bin: all mass in [4, 8) clamps to 4.0 ...
    h2 = np.zeros(16, np.int64)
    h2[2] = 10
    assert _hist_percentile(h2, 0.5) == 4.0
    # ... while bins ABOVE the first nonempty one still interpolate.
    h3 = np.zeros(16, np.int64)
    h3[0], h3[2] = 10, 10
    assert 4.0 < _hist_percentile(h3, 0.99) < 8.0


def test_latency_histogram_matches_counts():
    """Fleet histogram mass equals the latency count, and the recovered
    percentiles bracket the known direct-mode latency (~3 ticks on a reliable
    net: append on the offer tick, ship on the next heartbeat, ack commits)."""
    cfg = RaftConfig(n_nodes=5, client_interval=8)
    _, m = scan.simulate(cfg, 0, 64, 400)
    md = jax.device_get(m)
    assert md.lat_hist.shape == (64, types.LAT_HIST_BINS)
    total = int(md.lat_cnt.sum())
    assert total > 0
    assert int(md.lat_hist.sum()) == total
    s = summarize(m)
    assert s.lat_p50 is not None and 2.0 <= s.lat_p50 <= 4.0
    assert s.lat_p50 <= s.lat_p95 <= s.lat_p99


def test_offer_tick_preserves_histogram_layout():
    """Session.offer round-trips metrics through the batch-minor layout; the
    histogram leaf must come back [B, BINS] and keep accumulating."""
    from raft_sim_tpu.driver import Session

    sess = Session(RaftConfig(n_nodes=5, client_interval=4), batch=4, seed=0)
    sess.run(40)
    r = sess.offer(-5, wait=8)
    assert sess.metrics.lat_hist.shape == (4, types.LAT_HIST_BINS)
    assert r["committed"] >= 1
    s = sess.summary()
    assert s["lat_p50"] is not None


# ----------------------------------------------------------- no-op liveness gauge


def test_noop_blocked_counted_when_ring_full():
    """An election win over a ring FULL of uncommitted entries cannot append its
    no-op: the latent 5.4.2 commit freeze must surface in the counter."""
    cap = RING_CFG.log_capacity
    s = base_state(RING_CFG)
    s = with_ring_log(s, 0, base=0, entries=hist(0, cap), commit=0)
    s = s._replace(
        role=s.role.at[0].set(CANDIDATE),
        term=s.term.at[0].set(2),
        voted_for=s.voted_for.at[0].set(0),
        votes=s.votes.at[0].set(bitplane.full_row(5)),
    )
    s2, info = step(RING_CFG, s)
    assert int(s2.role[0]) == LEADER  # the win itself goes through
    assert int(s2.log_len[0]) == cap  # ... but no no-op was appended
    assert int(info.noop_blocked) == 1


def test_noop_blocked_zero_with_room():
    s = base_state(RING_CFG)
    s = with_ring_log(s, 0, base=0, entries=hist(0, 3), commit=0)
    s = s._replace(
        role=s.role.at[0].set(CANDIDATE),
        term=s.term.at[0].set(2),
        voted_for=s.voted_for.at[0].set(0),
        votes=s.votes.at[0].set(bitplane.full_row(5)),
    )
    s2, info = step(RING_CFG, s)
    assert int(s2.role[0]) == LEADER
    assert int(s2.log_len[0]) == 4  # no-op appended
    assert int(info.noop_blocked) == 0


# ------------------------------------- log-matching sampling + skipped-pair gauge


def test_lm_skipped_pairs_counted():
    """Pairs where one node compacted past the other's commit are skipped by the
    ring check -- and now counted, so the check's coverage is measured."""
    cfg = dataclasses.replace(RING_CFG, check_log_matching=True)
    s = base_state(cfg)
    # Node 0 compacted to base 6 with commit 8; every other node's commit is
    # below 6, so all four (0, j) pairs are incomparable.
    s = with_ring_log(s, 0, base=6, entries=hist(6, 8), commit=8)
    s = with_ring_log(s, 1, base=0, entries=hist(0, 2), commit=2)
    s2, info = step(cfg, s)
    assert int(info.lm_skipped_pairs) == 4
    assert not bool(info.viol_log_matching)
    assert not bool(info.viol_commit)


def _mismatched_committed_logs(cfg):
    """Nodes 0 and 1 disagree on their one committed entry -- a genuine
    log-matching violation."""
    s = base_state(cfg)
    s = with_log(s, 0, [1])
    s = with_log(s, 1, [1])
    s = s._replace(
        log_val=s.log_val.at[1, 0].set(999),
        commit_index=s.commit_index.at[0].set(1).at[1].set(1),
    )
    return types.with_commit_chk(s)


def test_log_matching_interval_samples_on_cadence():
    cfg = RaftConfig(
        n_nodes=5, log_capacity=8, check_log_matching=True, log_matching_interval=4
    )
    s = _mismatched_committed_logs(cfg)
    # new.now = 2: off-cadence -> the (real) violation goes unobserved this tick.
    _, info = step(cfg, s._replace(now=jnp.int32(1)))
    assert not bool(info.viol_log_matching)
    # new.now = 4: check tick -> caught.
    _, info = step(cfg, s._replace(now=jnp.int32(3)))
    assert bool(info.viol_log_matching)
    # Interval 1 (the default) checks every tick.
    cfg1 = dataclasses.replace(cfg, log_matching_interval=1)
    _, info = step(cfg1, s._replace(now=jnp.int32(1)))
    assert bool(info.viol_log_matching)
