"""Node-axis sharding tier (parallel/nodeshard.py) on the 8-virtual-CPU-device
mesh: one giant cluster partitioned row-wise across devices must be BIT-EXACT
against the unsharded kernel -- final state (via unshard_state), run metrics,
and telemetry window records -- at every mesh shape, with the hot loop's
inter-device traffic limited to the whitelisted collectives
(analysis/jaxpr_audit.check_node_collectives).

Giant-N word-boundary coverage rides along: bitplane packing and quorum
popcounts at N=101 (W=4 words) and N=255 (W=8), including shard-boundary rows
where a device's local node range splits a packed word (N=101 over 8 devices:
nl=13, device 2 owns rows 26..38, crossing the 31/32 word edge)."""

import dataclasses

import jax
import numpy as np
import pytest

from raft_sim_tpu import RaftConfig
from raft_sim_tpu.analysis import jaxpr_audit
from raft_sim_tpu.ops import bitplane
from raft_sim_tpu.parallel import nodeshard
from raft_sim_tpu.sim import scan, telemetry
from raft_sim_tpu.types import compact_twin
from raft_sim_tpu.utils.config import PRESETS

# The full sharded v1 feature surface in one config: pre-vote, ring
# compaction, client traffic (offer-tick latency plane live), invariants,
# crash + drop churn. N=33 needs two packed words, so cross-word quorum
# popcounts are exercised, and 33 % 8 != 0 so pad rows exist on the mesh.
FEATURED_33 = RaftConfig(
    n_nodes=33,
    log_capacity=24,
    compact_margin=8,
    pre_vote=True,
    client_interval=5,
    drop_prob=0.1,
    crash_prob=0.1,
    crash_period=32,
    crash_down_ticks=8,
)


def _assert_tree_equal(a, b, tag=""):
    la, lb = jax.tree.leaves(jax.device_get(a)), jax.tree.leaves(jax.device_get(b))
    assert len(la) == len(lb)
    for i, (x, y) in enumerate(zip(la, lb)):
        np.testing.assert_array_equal(x, y, err_msg=f"{tag}[{i}]")


def _assert_parity(cfg, seed, batch, ticks, mesh):
    fs, ms = nodeshard.simulate_node_sharded(cfg, seed, batch, ticks, mesh)
    fd, md = scan.simulate(compact_twin(cfg, False), seed, batch, ticks)
    _assert_tree_equal(ms, md, "metrics")
    _assert_tree_equal(nodeshard.unshard_state(cfg, fs), fd, "state")
    return md


def test_parity_n5_eight_shards():
    """N=5 over 8 node shards: more devices than live rows after padding
    (n_pad=8, nl=1 -- every device holds exactly one row, three of them pad)."""
    cfg = RaftConfig(n_nodes=5, client_interval=8, drop_prob=0.1)
    md = _assert_parity(cfg, 3, 8, 120, nodeshard.make_node_mesh(8))
    assert int(np.max(np.asarray(jax.device_get(md).max_commit))) > 0


def test_parity_n33_featured():
    """The full v1 surface at N=33 (two packed words, pad rows on-mesh)."""
    md = _assert_parity(FEATURED_33, 7, 4, 150, nodeshard.make_node_mesh(8))
    assert int(np.max(np.asarray(jax.device_get(md).max_commit))) > 0


@pytest.mark.slow
def test_windowed_parity_n33():
    """Telemetry window records -- per-window metrics AND first_viol_tick --
    are bit-identical to the unsharded simulate_windowed. Slow tier (the CI
    mesh-smoke job owns this file's slow set): tier-1 keeps the scan-path
    n33 parity row; the windowed wrapper shares the sharded tick kernel."""
    cfg = FEATURED_33
    mesh = nodeshard.make_node_mesh(8)
    fs, ms, recs = nodeshard.simulate_node_sharded_windowed(
        cfg, 7, 4, 120, 30, mesh
    )
    fd, md, recd, _ = telemetry.simulate_windowed(cfg, 7, 4, 120, 30)
    _assert_tree_equal(ms, md, "metrics")
    _assert_tree_equal(recs, recd, "records")
    _assert_tree_equal(nodeshard.unshard_state(cfg, fs), fd, "state")


@pytest.mark.slow
def test_device_count_invariance():
    """2, 4, and 8 node shards produce identical trajectories (padding differs
    per count; the padded rows must be inert at every width). Slow tier: the
    widest mesh (8 shards, 3 pad rows) stays tier-1 via
    test_parity_n5_eight_shards; CI mesh-smoke re-proves the sweep every PR."""
    cfg = RaftConfig(n_nodes=5, client_interval=8, drop_prob=0.1)
    _, md = scan.simulate(cfg, 3, 8, 120)
    for d in (2, 4):
        _, ms = nodeshard.simulate_node_sharded(
            cfg, 3, 8, 120, nodeshard.make_node_mesh(d)
        )
        _assert_tree_equal(ms, md, f"{d}dev")


def test_two_dim_mesh():
    """Batch over "clusters" x nodes over "nodes" at once (2 x 4 devices)."""
    cfg = RaftConfig(n_nodes=5, client_interval=8, drop_prob=0.1)
    mesh = nodeshard.make_node_mesh(4, n_cluster_shards=2)
    _, ms = nodeshard.simulate_node_sharded(cfg, 3, 8, 120, mesh)
    _, md = scan.simulate(cfg, 3, 8, 120)
    _assert_tree_equal(ms, md, "2d")


def test_collective_whitelist():
    """The acceptance assert: the node-sharded config7 program's only
    inter-device primitives are the mailbox/invariant all_gathers and the
    metric psum/pmin/pmax folds (lowering only -- no compile)."""
    cfg, _ = PRESETS["config7"]
    findings = jaxpr_audit.check_node_collectives(
        "config7", cfg, nodeshard.make_node_mesh(8)
    )
    assert findings == [], [f.message for f in findings]
    # And the whitelisted kinds actually appear: the gather + folds exist.
    import jax.numpy as jnp

    closed = jax.make_jaxpr(
        lambda s: nodeshard.simulate_node_sharded(
            cfg, s, 8, 16, nodeshard.make_node_mesh(8)
        )
    )(jax.ShapeDtypeStruct((), jnp.int32))
    seen = {
        e.primitive.name
        for e in jaxpr_audit.iter_eqns(closed.jaxpr)
        if e.primitive.name in jaxpr_audit.NODE_COLLECTIVE_KINDS
    }
    assert "all_gather" in seen and "psum" in seen


@pytest.mark.slow
def test_parity_config7_smoke():
    """The giant-N acceptance smoke: the config7 preset (N=101, W=4) sharded
    over 8 devices, bit-exact vs unsharded, with commits advancing."""
    cfg, _ = PRESETS["config7"]
    md = _assert_parity(cfg, 3, 2, 60, nodeshard.make_node_mesh(8))
    assert int(np.max(np.asarray(jax.device_get(md).max_commit))) > 0


@pytest.mark.slow
def test_parity_config7x_smoke():
    """N=255 ceiling (W=8, int16 node ids): the sharded program runs the
    dense twin of the compacted preset; parity is against that twin."""
    cfg, _ = PRESETS["config7x"]
    md = _assert_parity(cfg, 3, 2, 60, nodeshard.make_node_mesh(8))
    assert int(np.max(np.asarray(jax.device_get(md).max_commit))) > 0


# ------------------------------------------------------- guards / error paths


def test_rejects_unsupported_features():
    for kw in (
        {"reconfig_interval": 10},
        {"transfer_interval": 10},
        {"read_interval": 4},
        {"client_redirect": True},
        {"check_log_matching": True},
    ):
        cfg = RaftConfig(n_nodes=9, log_capacity=64, **kw)
        with pytest.raises(ValueError, match="node sharding does not support"):
            nodeshard.check_shardable(cfg, 8)


def test_rejects_word_crossing_padding():
    """A shard count that pushes n_pad across a 32-bit word boundary must be
    rejected, not silently relayout the bitplanes (N=96 over 7 shards pads to
    98 -> 4 words vs 3)."""
    with pytest.raises(ValueError, match="word boundary"):
        nodeshard.check_shardable(RaftConfig(n_nodes=96), 7)


def test_rejects_indivisible_batch():
    mesh = nodeshard.make_node_mesh(4, n_cluster_shards=2)
    with pytest.raises(ValueError, match="batch"):
        nodeshard.simulate_node_sharded(RaftConfig(n_nodes=5), 0, 3, 10, mesh)


# ------------------------------------- giant-N word-boundary coverage (W=4/8)


@pytest.mark.parametrize("n", [101, 255])
def test_bitplane_roundtrip_giant(n):
    """pack/unpack round-trips and popcounts at W=4 (N=101) and W=8 (N=255),
    bits landing on every word including the partial last word."""
    rng = np.random.default_rng(n)
    rows = 16
    dense = rng.integers(0, 2, size=(rows, n)).astype(bool)
    packed = jax.device_get(bitplane.pack(np.asarray(dense), axis=1))
    assert packed.shape == (rows, bitplane.n_words(n))
    back = jax.device_get(bitplane.unpack(packed, n, axis=1))
    np.testing.assert_array_equal(back.astype(bool), dense)
    counts = jax.device_get(bitplane.count(packed, axis=1))
    np.testing.assert_array_equal(counts, dense.sum(axis=1).astype(np.int32))


@pytest.mark.parametrize("n,n_dev", [(101, 8), (255, 3)])
def test_shard_boundary_rows_split_packed_word(n, n_dev):
    """The local row ranges of a giant-N shard split packed words (N=101 over
    8: nl=13, device 2 owns rows 26..38 across the 31/32 edge; N=255 over 3:
    nl=85 crosses word edges on every device -- legal because n_pad=255 keeps
    W=8, shard counts need not be powers of two). Slicing rows and popcounting
    votes drawn per-row must agree with the dense counts."""
    n_pad = nodeshard.check_shardable(RaftConfig(n_nodes=n), n_dev)
    nl = n_pad // n_dev
    # At least one device's [row0, row0+nl) range must straddle a word edge.
    straddles = [
        d for d in range(n_dev)
        if (d * nl) // 32 != min(((d + 1) * nl - 1) // 32, (n - 1) // 32)
    ]
    assert straddles, f"no shard straddles a word edge at N={n}, D={n_dev}"
    rng = np.random.default_rng(n)
    votes_dense = rng.integers(0, 2, size=(n_pad, n)).astype(bool)
    votes_dense[n:] = False  # pad voters never vote
    packed = np.asarray(jax.device_get(bitplane.pack(votes_dense, axis=1)))
    for d in straddles:
        row0 = d * nl
        local = packed[row0:row0 + nl]
        counts = jax.device_get(bitplane.count(local, axis=1))
        np.testing.assert_array_equal(
            counts, votes_dense[row0:row0 + nl].sum(axis=1).astype(np.int32)
        )


@pytest.mark.parametrize("name", ["config7", "config7x"])
def test_giant_preset_quorum_forms(name):
    """config7 (CAP < N) must take the threshold-quorum form and config7x the
    int16 node-id tier -- the structural gates the giant presets exist to
    cover (types.node_dtype, the phase-5 quorum fork)."""
    from raft_sim_tpu import types as rst_types

    cfg, _ = PRESETS[name]
    assert cfg.log_capacity < cfg.n_nodes
    want = np.int8 if cfg.n_nodes <= rst_types.MAX_INT8_NODES else np.int16
    assert rst_types.node_dtype(cfg) == want


@pytest.mark.slow
def test_compile_count_pin_full_matrix():
    """Tier-1's compile-count pin (tests/test_golden_jaxpr.py) sweeps the
    standing presets only -- the giant-N tiers pay ~11s of N=101/255 tracing
    per run. This slow row re-runs the pin over the FULL preset matrix
    including config7/config7x, so a giant-tier lowering fork still fails in
    CI (the mesh-smoke job runs this file's slow set every PR)."""
    import json
    import os

    from raft_sim_tpu.analysis import jaxpr_audit as JA

    hist = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "golden_jaxpr_hist.json")
    with open(hist) as f:
        pins = json.load(f)["lowerings"]
    families = {
        "step": lambda c: JA.step_jaxpr(c, batched=True),
        "scan": JA.scan_jaxpr,
        "scenario_scan": JA.scenario_scan_jaxpr,
        "serve_scan": lambda c: JA.serve_scan_jaxpr(JA.serve_variant(c)),
        "trace_scan": lambda c: JA.trace_scan_jaxpr(JA.trace_variant(c)),
    }
    for fam, fn in families.items():
        hashes = {JA.program_hash(fn(cfg)) for cfg, _ in PRESETS.values()}
        assert len(hashes) <= pins[fam], (
            f"{fam}: {len(hashes)} distinct lowerings across the full preset "
            f"matrix (pinned {pins[fam]}): a config that should share a "
            "program now forks one -- see golden_jaxpr_hist.json 'lowerings'"
        )


def test_pad_tables_cover_every_leaf():
    """A new state/mailbox/input leg must get a pad rule before the sharded
    path can run it (the import-time asserts, restated as a test)."""
    from raft_sim_tpu.types import ClusterState, Mailbox, StepInputs

    assert set(nodeshard._STATE_PAD) | {"mailbox"} == set(ClusterState._fields)
    assert set(nodeshard._MAILBOX_PAD) == set(Mailbox._fields)
    assert set(nodeshard._INPUT_PAD) == set(StepInputs._fields)


@pytest.mark.slow
def test_compact_twin_routing():
    """compact_planes presets run the sharded carry DENSE: same metrics as
    both the dense twin and the compacted single-chip run. (Slow tier: the
    config7x smoke above also exercises this routing at N=255.)"""
    cfg = dataclasses.replace(FEATURED_33, compact_planes=True)
    mesh = nodeshard.make_node_mesh(8)
    _, ms = nodeshard.simulate_node_sharded(cfg, 7, 4, 100, mesh)
    _, md = scan.simulate(compact_twin(cfg, False), 7, 4, 100)
    _assert_tree_equal(ms, md, "compact-twin")
