"""Protocol trace plane: device-side Raft event histories, whole-history
safety checking, and transition coverage (the eighth subsystem).

PR 2's telemetry answers "how is the fleet doing" with window counters and a
violation-frozen flight recorder; this package answers "WHAT HAPPENED" with a
Jepsen-style checkable history. Three load-bearing pieces:

  events.py   device-side event extraction: a compact per-cluster protocol
              event stream (role transitions, term bumps, votes, commit
              advances, log appends/truncations, crash/restart/drop/partition
              fault events) derived from state deltas the kernels already
              compute -- the extraction never touches the trajectory.
  ring.py     the bounded per-cluster event buffer carried in the telemetry
              scan and drained every window (generalizing sim/telemetry.py's
              violation-frozen flight recorder into an always-recordable,
              trigger-armable stream), plus the packed transition-coverage
              bitmap (role x kind and kind -> kind adjacency, ops/bitplane).
  history.py  host-side reconstruction of per-cluster timelines from the
  checker.py  exported windows, and the whole-history checker verifying the
              five Raft safety properties (Election Safety, Leader
              Append-Only, Log Matching, Leader Completeness, State Machine
              Safety) over the COMPLETE history -- with named properties and
              minimal witnesses on rejection, and an explicit
              incomplete-history verdict instead of a vacuous pass.

Everything is gated by `cfg.track_trace` with the zero-cost-when-off contract
(utils/config.py): disabled, no compiled program carries a trace leg.
docs/OBSERVABILITY.md "Protocol traces" has the schema and sizing guidance.
"""

from raft_sim_tpu.trace.events import KIND_NAMES, KINDS, N_KINDS
from raft_sim_tpu.trace.ring import TracePersist, TraceSpec, TraceWin

__all__ = [
    "KINDS",
    "KIND_NAMES",
    "N_KINDS",
    "TraceSpec",
    "TraceWin",
    "TracePersist",
]
