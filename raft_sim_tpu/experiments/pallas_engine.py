"""Pallas execution engine: the whole tick as ONE fused TPU kernel.

XLA compiles the batch-minor tick (models/raft_batched.py) into a dozen-odd fusions
with HBM round trips for the intermediates between them. This engine instead runs
`step_b` itself inside a single `pallas_call`, gridded over blocks of clusters: each
block's entire state (~4KB/cluster) is read into VMEM once, the full nine-phase tick
runs on the VPU from VMEM, and the new state is written back once -- the minimum
possible HBM traffic per tick.

Because `step_b` is pure jnp on batch-minor arrays, the kernel body simply *calls it*
on values read from the block refs: there is no duplicated protocol logic, so the
bit-parity chain (oracle -> raft.py -> raft_batched.py) extends to this engine for
free, and tests/test_pallas.py pins it (interpret mode on CPU; the compiled TPU
path is toolchain-blocked, see STATUS below).

Shape handling: TPU Pallas wants >=2-D refs, so rank-1 leaves ([B]-shaped: state.now,
client_cmd, and every StepInfo field) cross the boundary as [1, B].

STATUS — EXPERIMENTAL (demoted from models/ in round 4; see docs/DESIGN.md "Pallas
engine"): interpret mode (CPU) works and is parity-tested every run
(tests/test_pallas.py), which pins that the tick kernel remains
pallas_call-compatible. The compiled TPU path is blocked by this image's Mosaic
toolchain, not by kernel structure: the original int32 tick graph SIGABRTed libtpu
at the final compile step (individual phases compiled fine), and after the v8 wire
format narrowed state to int16/int8 Mosaic rejects it earlier with "Reductions
over int16 not implemented" -- re-confirmed on the real chip in round 4, which
triggered the demotion round 2's park decision called for. Meanwhile the XLA
batch-minor path hit 38.2M cluster-ticks/s/chip (config3) with XLA's own fusions,
so the headroom a hand-fused kernel could add no longer justifies maintaining a
second compile path against a toolchain that cannot lower it. Round-5 probe
(one per round, per the standing plan): still blocked, now "Reductions over
int8 not implemented" after the v13 int8 index planes -- the same missing
narrow-int reduction support, one dtype lower. Revisit if libtpu/Mosaic gains
sub-int32 reductions.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_sim_tpu.models import raft_batched
from raft_sim_tpu.types import ClusterState, StepInfo, StepInputs
from raft_sim_tpu.utils.config import RaftConfig


# jax renamed TPUCompilerParams -> CompilerParams across the 0.5/0.6 line;
# resolve whichever this version has (same kwargs) so the compiled path reaches
# the real Mosaic verdict on every supported jax instead of an AttributeError
# -- the same version-portability treatment parallel/mesh.py's shard_map got.
_compiler_params = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)


def _lift(x):
    """[B] -> [1, B] so every ref is at least 2-D."""
    return x[None, :] if x.ndim == 1 else x


def _unlift(x, orig_ndim):
    return x[0] if orig_ndim == 1 else x


def step_pallas(
    cfg: RaftConfig,
    s: ClusterState,
    inp: StepInputs,
    block_b: int = 256,
    interpret: bool = False,
) -> tuple[ClusterState, StepInfo]:
    """One tick for B clusters (batch-minor layout), as a single fused kernel.

    B must be a multiple of block_b. Bit-identical to raft_batched.step_b.
    """
    b = s.role.shape[-1]
    if b % block_b:
        raise ValueError(f"batch {b} must be a multiple of block_b {block_b}")
    if cfg.compact_planes:
        # The compacted carry layout's pack/unpack boundary is reshape-heavy
        # (ops/tile.py), and Mosaic cannot lower the unit-dim reshapes this
        # kernel already avoids (log_ops.iota note in raft_batched.py) --
        # the Pallas engine stays a dense-layout experiment.
        raise NotImplementedError(
            "step_pallas does not support cfg.compact_planes (dense layout only)"
        )

    in_leaves, state_def = jax.tree.flatten(s)
    inp_leaves, inp_def = jax.tree.flatten(inp)
    n_state = len(in_leaves)
    all_in = [_lift(x) for x in in_leaves + inp_leaves]
    in_ndims = [x.ndim for x in in_leaves + inp_leaves]

    # Probe output structure once (abstractly) to build out_shapes.
    out_aval = jax.eval_shape(lambda s_, i_: raft_batched.step_b(cfg, s_, i_), s, inp)
    out_leaves_aval, out_def = jax.tree.flatten(out_aval)
    out_ndims = [x.ndim for x in out_leaves_aval]

    def spec_for(x):
        blk = tuple(x.shape[:-1]) + (block_b,)
        nlead = x.ndim - 1
        return pl.BlockSpec(blk, lambda i, _n=nlead: (0,) * _n + (i,))

    kernel = _make_kernel(cfg, n_state, len(inp_leaves), state_def, inp_def, in_ndims, out_def, out_ndims)

    # Out shapes from the avals, lifted to >=2-D.
    out_shapes = [
        jax.ShapeDtypeStruct((1, b) if a.ndim == 1 else a.shape, a.dtype)
        for a in out_leaves_aval
    ]

    out = pl.pallas_call(
        kernel,
        grid=(b // block_b,),
        in_specs=[spec_for(x) for x in all_in],
        out_specs=[spec_for(sh) for sh in out_shapes],
        out_shape=out_shapes,
        interpret=interpret,
        compiler_params=None
        if interpret
        else _compiler_params(
            dimension_semantics=("arbitrary",),
            # The one-hot intermediates ([N,N,E,CAP,BB] etc.) are VMEM-hungry; let
            # Mosaic use the whole budget instead of its conservative default.
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
    )(*all_in)

    out_leaves = [_unlift(x, nd) for x, nd in zip(out, out_ndims)]
    return jax.tree.unflatten(out_def, out_leaves)


def _make_kernel(cfg, n_state, n_inp, state_def, inp_def, in_ndims, out_def, out_ndims):
    def kernel(*refs):
        in_refs = refs[: n_state + n_inp]
        out_refs = refs[n_state + n_inp :]
        vals = [
            _unlift(r[...], nd) for r, nd in zip(in_refs, in_ndims)
        ]
        s = jax.tree.unflatten(state_def, vals[:n_state])
        inp = jax.tree.unflatten(inp_def, vals[n_state:])
        s2, info = raft_batched.step_b(cfg, s, inp)
        out_leaves, _ = jax.tree.flatten((s2, info))
        for r, v, nd in zip(out_refs, out_leaves, out_ndims):
            if isinstance(v, np.ndarray):
                # Structurally-gated-off StepInfo metrics are HOST CONSTANTS
                # (never jnp.zeros: an op would break the zero-cost-when-off
                # step goldens -- models/raft_batched.py). pallas_call
                # refuses closed-over array consts, so materialize them as
                # an in-kernel op here. Guard the zero assumption: a future
                # nonzero host-constant leaf (a NIL sentinel, say) must fail
                # loudly, not silently diverge from step_b.
                assert not np.any(v), "nonzero host-constant StepInfo leaf"
                v = jnp.zeros(v.shape, v.dtype)
            r[...] = _lift(v) if nd == 1 else v

    return kernel


@functools.partial(jax.jit, static_argnums=(0, 3, 4, 5))
def run_pallas(
    cfg: RaftConfig,
    state: ClusterState,
    keys: jax.Array,
    n_ticks: int,
    block_b: int = 256,
    interpret: bool = False,
):
    """Scan the Pallas tick over n_ticks (state [B, ...]-leading in/out). Reuses
    scan.run_batch_minor's scan body with the kernelized step, so fault inputs and
    metric accumulation are the shared code path and trajectories stay bit-identical
    to every other engine."""
    from raft_sim_tpu.sim import scan

    return scan.run_batch_minor(
        cfg,
        state,
        keys,
        n_ticks,
        step_fn=lambda c, s, i: step_pallas(c, s, i, block_b=block_b, interpret=interpret),
    )
