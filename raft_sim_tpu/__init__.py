"""raft_sim_tpu: a TPU-native batched Raft cluster simulator in JAX.

Re-expresses the per-node behavior of the reference implementation (one networked
Clojure Raft process per node, /root/reference/src/raft/) as a pure, vmap'able
state-transition kernel over struct-of-arrays state, with the network as an N x N
adjacency-masked message scatter and the event loop as a jit-compiled `lax.scan`.
See SURVEY.md for the structural map between the two designs.
"""

from raft_sim_tpu.types import (
    CANDIDATE,
    FOLLOWER,
    LEADER,
    NIL,
    ClusterState,
    Mailbox,
    StepInfo,
    StepInputs,
    init_batch,
    init_state,
)
from raft_sim_tpu.utils.config import PRESETS, RaftConfig

__all__ = [
    "CANDIDATE",
    "FOLLOWER",
    "LEADER",
    "NIL",
    "ClusterState",
    "Mailbox",
    "PRESETS",
    "RaftConfig",
    "StepInfo",
    "StepInputs",
    "init_batch",
    "init_state",
]

__version__ = "0.1.0"
