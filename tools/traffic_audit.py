"""Static bytes-moved-per-tick audit: the quantitative side of the bit-packing
work (and, where packing cannot win, the roofline argument).

Every `lax.scan` tick reads the whole carry (ClusterState + Mailbox +
RunMetrics) from HBM and writes it back, and materializes the per-tick
StepInputs; at large N those planes ARE the tick's HBM traffic (docs/PERF.md
"what the profile says"). The carry accounting's PRIMARY source is the
analyzer's cost model (`raft_sim_tpu/analysis/cost_model.py`, Pass C): the
scan-carry legs are read out of the LOWERED run program itself -- aval
shapes/dtypes from the scan body, moving-vs-elided derived from identity
passthrough in the jaxpr, the exact table `tools/check.py --cost` gates
against tests/golden_cost_model.json. The historical `jax.eval_shape` leaf
table over `init_state` is retained as a cross-check (derived and hand-priced
must agree within 1%; asserted in tests/test_cost_model.py, warned about here
at runtime). Each leaf is priced two ways:

  - logical bytes (shape x itemsize), and
  - TPU-padded bytes in the batch-minor layout ([..., B]: the minor dim rides
    the 128-wide lane tile, the second-minor dim pads to the dtype's sublane
    multiple -- 8 for 4-byte, 16 for 2-byte, 32 for 1-byte elements), the
    physical footprint models/raft_batched.py exists to control. The padding
    rules are single-sourced in `analysis/policy.py` (`padded_bytes`), shared
    with the gated cost model.

It then rebuilds the same table for the DENSE pre-packing layout (votes and
deliver_mask as [N, N] bool, pre-vote grants riding resp_kind, no pv_grant
plane) and reports the per-config delta plus a roofline projection: given the
recorded round-5 throughput of each config (docs/PERF.md history table,
measured on the real chip), the implied HBM rate is ticks/s x bytes/tick; a
layout change can speed up an HBM-bound config by at most the traffic ratio.
That makes the config5 verdict honest either way -- either the packed layout's
reduction projects past the 3M ticks/s bar, or this audit documents that the
bool planes were never a large enough fraction of the tick for packing to get
there (docs/PERF.md "bit-packing audit" section holds the conclusions).

The roofline anchor is no longer a hand table: it derives from the newest
BENCH_r*.json artifact in the repo root (`cost_model.bench_anchor`), so the
projections track the bench trajectory; with no artifact present it falls
back to the pinned round-5 chip numbers with a stderr warning.

Runs on CPU (nothing is executed on device -- eval_shape only):

    python tools/traffic_audit.py                     # configs 3/4/5 table
    python tools/traffic_audit.py --configs config5 --top 12
    python tools/traffic_audit.py --json              # machine-readable
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from raft_sim_tpu.analysis import cost_model, jaxpr_audit
from raft_sim_tpu.analysis.policy import (
    invariant_leaves, logical_bytes, padded_bytes,
)
from raft_sim_tpu.ops import bitplane
from raft_sim_tpu.sim import faults, scan
from raft_sim_tpu.types import init_state
from raft_sim_tpu.utils.config import PRESETS, RaftConfig


def roofline_anchor():
    """(anchors, source): per-config recorded cluster-ticks/s for the
    implied-HBM-rate roofline. Primary source: the newest BENCH_r*.json
    artifact (so the anchor updates with every recorded bench round);
    fallback: the pinned round-5 chip numbers
    (cost_model.FALLBACK_ANCHOR_R05), with a warning -- a stale anchor must
    be visible, not silent. A config absent from the anchor gets bytes
    accounting but no projection."""
    anchors, source, notes = cost_model.anchor()
    for n in notes:
        print(f"traffic_audit: WARNING: {n}", file=sys.stderr)
    return anchors, source


# Loop-invariant carry legs (excluded from the traffic totals: XLA elides
# them from the per-tick HBM round trip -- the round-4 lesson recorded in
# docs/PERF.md). Single-sourced from analysis/policy.py, where the jaxpr pass
# (rule carry-passthrough) STATICALLY enforces that the legs named there are
# in fact passed through the scan body untouched -- so this audit and the
# analyzer can never disagree about which legs are free.
_invariant_leaves = invariant_leaves


def _derived_carry_rows(cfg: RaftConfig):
    """(group, name, shape, itemsize) for every MOVING scan-carry leg, derived
    from the lowered run program by the cost model (the primary source: the
    same per-leg table `tools/check.py --cost` gates). Shapes are per cluster
    (the lowering's trailing batch axis stripped); legs the scan body passes
    through untouched are already excluded -- the jaxpr says so, no hand list
    involved."""
    cm = cost_model.carry_model(jaxpr_audit.scan_jaxpr(cfg), batch=1)
    rows = []
    for name, leg in cm["legs"].items():
        if not leg["moving"]:
            continue
        group = (
            "mailbox" if name.startswith("mb.")
            else "metrics" if name.startswith("metric.")
            else "state"
        )
        rows.append(
            (group, name, tuple(leg["shape"]), jnp.dtype(leg["dtype"]).itemsize)
        )
    return rows


def _leaf_rows(cfg: RaftConfig):
    """(group, name, shape, dtype) for every scan-carry leaf + per-tick input,
    taken from the real structures via eval_shape (shapes are per cluster);
    loop-invariant carry legs (see _invariant_leaves) are dropped.

    Since the cost-model refactor this table is the CROSS-CHECK, not the
    source of record: `audit()` prices the carry from `_derived_carry_rows`
    (the lowered program) and warns if this hand table disagrees beyond 1%
    (tests/test_cost_model.py asserts exact agreement)."""
    key = jax.eval_shape(lambda: jax.random.key(0))
    state = jax.eval_shape(lambda k: init_state(cfg, k), key)
    inputs = jax.eval_shape(
        lambda k: faults.make_inputs(cfg, k, jnp.int32(0)), key
    )
    metrics = jax.eval_shape(scan.init_metrics)
    rows = []
    for f, v in zip(state._fields, state):
        if f == "mailbox":
            continue
        rows.append(("state", f, tuple(v.shape), v.dtype.itemsize))
    for f, v in zip(state.mailbox._fields, state.mailbox):
        rows.append(("mailbox", f"mb.{f}", tuple(v.shape), v.dtype.itemsize))
    for f, v in zip(inputs._fields, inputs):
        rows.append(("inputs", f"in.{f}", tuple(v.shape), v.dtype.itemsize))
    for f, v in zip(metrics._fields, metrics):
        rows.append(("metrics", f"metric.{f}", tuple(v.shape), v.dtype.itemsize))
    skip = _invariant_leaves(cfg)
    return [r for r in rows if r[1] not in skip]


def _densify(rows, cfg: RaftConfig):
    """The pre-packing layout of the same carry: [N, N] bool votes and
    delivery mask, pre-vote grants riding resp_kind (no pv_grant plane)."""
    n = cfg.n_nodes
    out = []
    for g, name, shape, isize in rows:
        if name == "votes" or name == "in.deliver_mask":
            out.append((g, name + " (dense)", (n, n), 1))
        elif name == "mb.pv_grant":
            continue  # its bit rode the resp_kind byte plane
        else:
            out.append((g, name, shape, isize))
    return out


# The lane/sublane padding rules live in analysis/policy.py now (shared with
# the gated cost model); these aliases keep this file's call sites readable.
_logical = logical_bytes
_padded = padded_bytes


def _telemetry_rows(cfg: RaftConfig, ring_k: int):
    """(group, name, shape, dtype-size) rows for the telemetry carry legs
    (sim/telemetry.py), taken from the real structures via eval_shape like
    everything else: the windowed-aggregation leg is a second RunMetrics
    (window-local accumulator) + the first-violation tick; the flight-recorder
    leg is K stacked StepInfos + slot ticks + pos/frozen. All are scan-carry
    components (read + write per tick), which is exactly why the ring must
    stay small -- the audit prices the decision (docs/OBSERVABILITY.md)."""
    metrics = jax.eval_shape(scan.init_metrics)
    rows = [
        ("telemetry", f"tel.wm.{f}", tuple(v.shape), v.dtype.itemsize)
        for f, v in zip(metrics._fields, metrics)
    ]
    rows.append(("telemetry", "tel.first_viol", (), 4))
    if ring_k > 0:
        from raft_sim_tpu.sim import telemetry

        rec = jax.eval_shape(lambda: telemetry.init_recorder(cfg, ring_k, 1))
        for f, v in zip(rec.ring._fields, rec.ring):
            rows.append(
                ("telemetry", f"tel.ring.{f}", tuple(v.shape[:-1]), v.dtype.itemsize)
            )
        rows.append(("telemetry", "tel.ring.tick", (ring_k,), 4))
        rows.append(("telemetry", "tel.pos", (), 4))
        rows.append(("telemetry", "tel.frozen", (), 1))
    return rows


def _scenario_rows(s_count: int):
    """(group, name, shape, dtype-size) rows for the scenario-engine genome:
    7 `[S]` per-cluster leaves (uint32 thresholds / int32 cadences -- the set
    single-sourced from analysis/policy.py:scenario_genome_leaves, which the
    genome path actually reads). The genome rides the scan body as loop
    CONSTANTS -- priced once per tick like the other inputs (the per-tick
    segment gather touches one element per leaf; pricing the whole `[S]`
    table is the conservative bound)."""
    from raft_sim_tpu.analysis.policy import scenario_genome_leaves

    return [
        ("scenario", f"gen.{name}", (s_count,), 4)
        for name, _dtype in scenario_genome_leaves()
    ]


def audit(cfg: RaftConfig, batch: int):
    """Both layouts' per-cluster-tick byte totals. Carry leaves move twice per
    tick (read + write); inputs once (materialized from the key stream).
    Carry rows come from the derived cost model; the eval_shape hand table is
    re-priced as a cross-check and any >1% disagreement is warned to stderr
    (it means the lowered program and the declared structures diverged --
    exactly what the old hand-only accounting could not see)."""

    def total(rows):
        log = pad = 0.0
        for g, _, shape, isize in rows:
            mult = 1 if g == "inputs" else 2
            log += mult * _logical(shape, isize)
            pad += mult * _padded(shape, isize, batch)
        return log, pad

    hand_rows = _leaf_rows(cfg)
    carry_rows = _derived_carry_rows(cfg)
    input_rows = [r for r in hand_rows if r[0] == "inputs"]
    packed_rows = carry_rows + input_rows
    hand_carry = [r for r in hand_rows if r[0] != "inputs"]
    d_log, d_pad = total(carry_rows)
    h_log, h_pad = total(hand_carry)
    # Compare logical AND padded totals: a divergence can cancel out under
    # lane/sublane padding (dtype narrowing paired with a pad-up in the
    # same tile) and would pass a padded-only check.
    if (h_pad and abs(d_pad - h_pad) > 0.01 * h_pad) or (
            h_log and abs(d_log - h_log) > 0.01 * h_log):
        print(
            f"traffic_audit: WARNING: derived carry pricing ({d_pad:,.0f} B "
            f"padded / {d_log:,.0f} B logical) disagrees with the eval_shape "
            f"cross-check ({h_pad:,.0f} B / {h_log:,.0f} B) by >1% -- the "
            "lowered scan and the declared structures have diverged; trust "
            "the derived number and fix the drift (tests/test_cost_model.py "
            "pins agreement)",
            file=sys.stderr,
        )
    dense_rows = _densify(packed_rows, cfg)
    packed_log, packed_pad = total(packed_rows)
    dense_log, dense_pad = total(dense_rows)
    # The limiting case of ANY bool-plane compression: the boolean planes cost
    # zero bytes. If even this cannot reach a throughput bar, no packing can.
    boolfree = [
        r
        for r in packed_rows
        if r[1] not in ("votes", "in.deliver_mask", "mb.pv_grant")
    ]
    boolfree_log, boolfree_pad = total(boolfree)
    return {
        "packed_rows": packed_rows,
        "dense_rows": dense_rows,
        "packed_logical": packed_log,
        "packed_padded": packed_pad,
        "dense_logical": dense_log,
        "dense_padded": dense_pad,
        "boolfree_logical": boolfree_log,
        "boolfree_padded": boolfree_pad,
    }


def _fmt_bytes(b):
    return f"{b / 1024:.2f} KiB" if b >= 1024 else f"{b:.0f} B"


def report(name: str, cfg: RaftConfig, batch: int, top: int, out=sys.stdout,
           telemetry_ring: int | None = None, scenario_segments: int | None = None,
           serve: bool | None = None,
           anchors: dict | None = None, anchor_source: str | None = None):
    if anchors is None:
        anchors, anchor_source = roofline_anchor()
    a = audit(cfg, batch)
    w = bitplane.n_words(cfg.n_nodes)
    print(f"\n== {name}: N={cfg.n_nodes} (W={w}), CAP={cfg.log_capacity}, "
          f"E={cfg.max_entries_per_rpc}, batch={batch} ==", file=out)
    print(f"{'plane':28} {'shape':>14} {'logical':>10} {'padded':>10}", file=out)
    biggest = sorted(
        a["packed_rows"],
        key=lambda r: -_padded(r[2], r[3], batch),
    )[:top]
    for g, nm, shape, isize in biggest:
        print(
            f"{nm:28} {str(shape):>14} {_logical(shape, isize):>10,} "
            f"{_padded(shape, isize, batch):>10,.0f}",
            file=out,
        )
    dl, dp = a["dense_logical"], a["dense_padded"]
    pl, pp = a["packed_logical"], a["packed_padded"]
    print(f"{'per-cluster-tick DENSE':28} {'':>14} {dl:>10,.0f} {dp:>10,.0f}", file=out)
    print(f"{'per-cluster-tick PACKED':28} {'':>14} {pl:>10,.0f} {pp:>10,.0f}", file=out)
    print(
        f"reduction: logical {100 * (1 - pl / dl):.1f}%  "
        f"padded {100 * (1 - pp / dp):.1f}%",
        file=out,
    )
    rec = anchors.get(name)
    res = {
        "config": name,
        "n": cfg.n_nodes,
        "anchor_source": anchor_source,
        "dense_logical": dl,
        "dense_padded": dp,
        "packed_logical": pl,
        "packed_padded": pp,
        "boolfree_padded": a["boolfree_padded"],
    }
    # Compacted-layout column (ops/tile.py, cfg.compact_planes): the SAME
    # carry re-priced under the node-blocked tiling -- the per-edge value
    # planes bit-packed to their config-bounded ranges, the word/window
    # planes flattened past their sublane pads. Trajectories are
    # bit-identical (tests/test_tile.py), so the projection is a pure
    # layout-vs-layout bound at the same implied HBM rate.
    if not cfg.compact_planes:
        from raft_sim_tpu.types import compact_twin

        c = audit(compact_twin(cfg), batch)
        res |= {
            "compact_logical": c["packed_logical"],
            "compact_padded": c["packed_padded"],
        }
        print(
            f"{'per-cluster-tick COMPACTED':28} {'':>14} "
            f"{c['packed_logical']:>10,.0f} {c['packed_padded']:>10,.0f}",
            file=out,
        )
    if rec:
        bw = rec * dp
        ceiling = bw / pp
        bound = bw / a["boolfree_padded"]
        res |= {
            "recorded_ticks_per_s": rec,
            "implied_hbm_bytes_per_s": bw,
            "packed_roofline_ticks_per_s": ceiling,
            "boolfree_roofline_ticks_per_s": bound,
        }
        print(
            f"recorded ({anchor_source}): {rec / 1e6:.2f}M ticks/s -> implied "
            f"HBM rate {bw / 1e9:.1f} GB/s on the dense carry",
            file=out,
        )
        print(
            f"packed roofline at that rate: {ceiling / 1e6:.2f}M ticks/s "
            f"({ceiling / rec:.3f}x)",
            file=out,
        )
        print(
            f"bool-free bound (boolean planes at ZERO bytes): "
            f"{bound / 1e6:.2f}M ticks/s ({bound / rec:.3f}x) -- no bool-plane "
            "compression can beat this",
            file=out,
        )
        if "compact_padded" in res:
            croof = bw / res["compact_padded"]
            res["compact_roofline_ticks_per_s"] = croof
            print(
                f"COMPACTED roofline at that rate: {croof / 1e6:.2f}M ticks/s "
                f"({croof / rec:.3f}x) -- the node-blocked layout's bound "
                "(measure via the standing config5c bench row)",
                file=out,
            )
    if telemetry_ring is not None:
        # Observability overhead: the telemetry carry legs (window accumulator
        # always; ring buffer at depth K) priced against the packed tick.
        tel_rows = _telemetry_rows(cfg, telemetry_ring)
        tel_log = sum(2 * _logical(s, i) for _, _, s, i in tel_rows)
        tel_pad = sum(2 * _padded(s, i, batch) for _, _, s, i in tel_rows)
        wm_rows = [r for r in tel_rows if not r[1].startswith("tel.ring")
                   and r[1] not in ("tel.pos", "tel.frozen")]
        wm_pad = sum(2 * _padded(s, i, batch) for _, _, s, i in wm_rows)
        print(
            f"telemetry carry legs (window accumulator"
            + (f" + ring K={telemetry_ring}" if telemetry_ring else "")
            + f"): {_fmt_bytes(tel_log)} logical / {_fmt_bytes(tel_pad)} padded "
            f"per cluster-tick = +{100 * tel_pad / pp:.1f}% over the packed tick "
            f"(windows alone: +{100 * wm_pad / pp:.1f}%)",
            file=out,
        )
        res |= {
            "telemetry_ring": telemetry_ring,
            "telemetry_logical": tel_log,
            "telemetry_padded": tel_pad,
            "telemetry_window_only_padded": wm_pad,
            "telemetry_overhead_frac": tel_pad / pp,
        }
    if serve is not None and serve:
        # Serve-mode overhead: the offer-tick plane going live (log_tick +
        # mb.ent_tick + client_tick + lat_frontier become MOVING carry legs)
        # priced from the LOWERED serve program -- the same derived table the
        # gated cost model pins (ISSUE 6: the plane's cost is a number, not
        # prose). The perf tiers (no client traffic) pay ZERO on their plain
        # runs: the plane legs are loop-invariant there (analysis/policy.py).
        from raft_sim_tpu.analysis.jaxpr_audit import serve_scan_jaxpr, serve_variant

        plain_cm = cost_model.carry_model(jaxpr_audit.scan_jaxpr(cfg), batch=batch)
        serve_cm = cost_model.carry_model(
            serve_scan_jaxpr(serve_variant(cfg)), batch=batch
        )
        plane_rows = [
            (nm, leg) for nm, leg in serve_cm["legs"].items()
            if nm in ("log_tick", "mb.ent_tick", "client_tick") and leg["moving"]
        ]
        plane_pad = sum(2 * leg["padded"] for _, leg in plane_rows)
        delta = serve_cm["carry_padded"] - plain_cm["carry_padded"]
        print(
            f"serve mode (offer-tick plane live): scan carry "
            f"{_fmt_bytes(plain_cm['carry_padded'])} -> "
            f"{_fmt_bytes(serve_cm['carry_padded'])} padded per cluster-tick "
            f"(+{100 * delta / pp:.1f}% of the packed tick); the plane itself "
            f"({', '.join(nm for nm, _ in plane_rows)}) costs {_fmt_bytes(plane_pad)}",
            file=out,
        )
        res |= {
            "serve_carry_padded": serve_cm["carry_padded"],
            "serve_plane_padded": plane_pad,
            "serve_overhead_frac": delta / pp if pp else None,
        }
    if scenario_segments is not None:
        # Scenario-engine overhead: the genome broadcast (S-segment program
        # table, 7 leaves x 4 B per cluster) read each tick by the genome
        # input path. Inputs move ONCE per tick (like in.*); the carry is
        # untouched (the genome is a scan const, never a carry leg), so this
        # is the WHOLE per-cluster traffic cost of heterogeneous fault
        # space -- docs/PERF.md "scenario path" records the standing verdict.
        sc_rows = _scenario_rows(scenario_segments)
        sc_log = sum(_logical(s, i) for _, _, s, i in sc_rows)
        sc_pad = sum(_padded(s, i, batch) for _, _, s, i in sc_rows)
        print(
            f"scenario genome table (S={scenario_segments} segments, "
            f"{len(sc_rows)} leaves): {_fmt_bytes(sc_log)} logical / "
            f"{_fmt_bytes(sc_pad)} padded per cluster-tick = "
            f"+{100 * sc_pad / pp:.2f}% over the packed tick",
            file=out,
        )
        res |= {
            "scenario_segments": scenario_segments,
            "scenario_logical": sc_log,
            "scenario_padded": sc_pad,
            "scenario_overhead_frac": sc_pad / pp,
        }
    return res


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--configs",
        default="config3,config4,config5",
        help="comma-separated preset names (see raft_sim_tpu.utils.config.PRESETS)",
    )
    ap.add_argument("--top", type=int, default=8, help="largest planes listed")
    ap.add_argument("--json", action="store_true", help="emit one JSON line")
    ap.add_argument("--telemetry-ring", type=int, default=None, metavar="K",
                    help="also price the telemetry carry legs: the window "
                         "accumulator plus a K-deep flight-recorder ring "
                         "(K=0 prices windowed aggregation alone)")
    ap.add_argument("--scenario", type=int, default=None, metavar="S",
                    help="also price the scenario-engine genome broadcast: "
                         "an S-segment program table per cluster "
                         "(raft_sim_tpu/scenario; S=1 prices a plain "
                         "heterogeneous-fleet genome)")
    ap.add_argument("--serve", action="store_true",
                    help="also price serve mode: the offer-tick plane "
                         "(log_tick/ent_tick/client_tick) going live in the "
                         "standing-fleet program (raft_sim_tpu/serve), "
                         "derived from the lowered serve scan")
    args = ap.parse_args(argv)

    # With --json the human tables go to stderr so stdout is exactly one
    # parseable JSON line (the bench-artifact lesson: machine output must not
    # interleave with narration).
    table_out = sys.stderr if args.json else sys.stdout
    anchors, anchor_source = roofline_anchor()
    results = []
    for name in args.configs.split(","):
        name = name.strip()
        if name not in PRESETS:
            print(f"unknown preset {name!r}", file=sys.stderr)
            return 2
        cfg, batch = PRESETS[name]
        results.append(report(name, cfg, batch, args.top, out=table_out,
                              telemetry_ring=args.telemetry_ring,
                              scenario_segments=args.scenario,
                              serve=args.serve,
                              anchors=anchors, anchor_source=anchor_source))
    if args.json:
        print(json.dumps(results))
    return 0


if __name__ == "__main__":
    sys.exit(main())
