"""Fleet anomaly triage: name the clusters behind a firing alert.

The batch axis IS the fleet, so every windowed counter already has a
per-cluster breakdown riding the telemetry stream -- triage is a robust
outlier scan over it, not new instrumentation. Scores are modified z-scores
against the fleet median (median/MAD with the 1.4826 normal-consistency
factor), so one sick cluster in a healthy fleet scores enormous while a
fleet-wide burn scores everyone ~0 -- in which case the worst-K are still
named (an alert must always point somewhere), just without the outlier
label. Deterministic: ties break toward the larger raw metric, then the
lower cluster id.
"""

from __future__ import annotations

import numpy as np

# Robust scores are clamped here so a zero-MAD fleet (every cluster clean but
# one) stays JSON-representable instead of overflowing to inf.
SCORE_CLAMP = 1e6


def outlier_clusters(
    values,
    worst_k: int,
    score_threshold: float,
    cluster_base: int = 0,
) -> list[dict]:
    """Rank the clusters with a nonzero bad-metric by robust score; return at
    most `worst_k` rows {cluster, value, score, outlier}. `cluster_base`
    shifts local indices to fleet-global ids (tenant slices, farm members).
    [] when no cluster has a nonzero metric (a perf-plane alert, or a metric
    that cleared between detection and triage)."""
    x = np.asarray(values, dtype=np.float64)
    if x.size == 0:
        return []
    candidates = np.flatnonzero(x > 0)
    if not candidates.size:
        return []
    med = float(np.median(x))
    mad = float(np.median(np.abs(x - med)))
    scores = np.clip((x - med) / (1.4826 * mad + 1e-9), -SCORE_CLAMP, SCORE_CLAMP)
    order = sorted(
        (int(i) for i in candidates),
        key=lambda i: (-scores[i], -x[i], i),
    )
    return [
        {
            "cluster": cluster_base + i,
            "value": float(x[i]),
            "score": round(float(scores[i]), 3),
            "outlier": bool(scores[i] >= score_threshold),
        }
        for i in order[:worst_k]
    ]
