"""Host-side telemetry sink: a schema'd on-disk record of a run.

The reference prints everything to stdout and keeps nothing (core.clj:182-186);
`bench.py`, `driver.py`, and `summarize` each used to print ad-hoc JSON with no
shared shape. This module is the one schema all of them write now:

    <dir>/manifest.json        run identity: schema version, full config + its
                               hash, seed, batch, window/ring sizes, jax +
                               backend versions -- enough to reproduce the run
                               or to refuse to diff incomparable ones.
    <dir>/windows.jsonl        one line per telemetry window (fleet-aggregated
                               WindowRecord; sim/telemetry.py) -- the always-on
                               cheap observability stream.
    <dir>/flight_<c>.jsonl     the flight recorder's final K ticks for cluster
                               c (written only for violating clusters): full
                               per-tick StepInfo, renderable via
                               tools/metrics_report.py or sim/trace.info_lines.
    <dir>/summary.json         the end-of-run FleetSummary rollup (plus caller
                               extras like wall time).
    <dir>/trace_meta.json      OPTIONAL (driver --trace): the protocol trace
                               stream's self-description -- event-kind name
                               map, ring depth, coverage geometry -- so
                               trace.jsonl decodes without importing this
                               repo.
    <dir>/trace.jsonl          OPTIONAL: one line per protocol event
                               ({w, c, t, node, k, d}: window, cluster, tick,
                               node id or -1 for cluster scope, kind code,
                               detail), window-major then cluster then
                               device slot order -- per-cluster ticks are
                               non-decreasing, which validate() checks and
                               the history loader (trace/history.py) treats
                               as the stream-integrity invariant.
    <dir>/trace_windows.jsonl  OPTIONAL: one line per trace window (emitted/
                               retained/dropped event totals, sparse
                               per-cluster drop map, cumulative coverage
                               bits) -- the completeness ledger the checker
                               reads before it is willing to PASS a history.
    <dir>/perf.jsonl           OPTIONAL: per-chunk runtime attribution rows
                               (obs/timer.py ChunkTimer) -- wall/dispatch/
                               host/device-wait seconds, warmup flag, device
                               live_bytes, jit-cache sizes. Off by default
                               (--perf); the one stream with floats in it
                               (wall-clock measurements, not simulation
                               state), so it is exempt from the integer-exact
                               rule below.
    <dir>/health.jsonl         OPTIONAL (--health): one line per SLO
                               evaluation period per scope (fleet/tenant) --
                               SLI values, per-rule burn rates, worst state
                               (raft_sim_tpu/health monitor.py). Floats
                               allowed, same exemption as perf.jsonl.
    <dir>/alerts.jsonl         OPTIONAL: one line per burn-rate alert
                               TRANSITION (pending/firing/resolved/ok) with
                               the triaged worst-K clusters and, on firing,
                               the evidence_NNNN bundle it froze.
    <dir>/evidence_NNNN/       OPTIONAL: per-firing-alert forensics bundle
                               (health/evidence.py: alert.json + per-culprit
                               window rows, perf rows, flight snapshots).

Everything is line-delimited JSON with integer-exact values (no floats in the
window stream), so two runs diff textually and `validate()` can check the
whole directory without a schema library. `tools/metrics_report.py` renders
and diffs these directories; the tier-1 CI workflow validates one as a smoke
test and uploads it as an artifact.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time

import numpy as np

from raft_sim_tpu.types import LAT_HIST_BINS, StepInfo
from raft_sim_tpu.utils.config import RaftConfig

# Bump on any incompatible change to the manifest or line formats; validate()
# refuses mismatched directories and metrics_report refuses to diff them.
# v2: window lines gained multi_leader (split-brain exposure ticks --
#     RunMetrics metrics v4, the scenario search's election-safety precursor).
#  3: windows.jsonl gained the ReadIndex read-traffic columns (reads,
#     read_lat_sum, read_hist -- the read-side mirror of the commit-latency
#     fields; zeros unless cfg.read_index).
#  4: windows.jsonl gained the durable-storage fsync-lag columns
#     (fsync_lag_sum = node-tick-summed log_len - dur_len over the window,
#     fsync_lag_max = its per-tick per-node max -- the durability_lag SLI's
#     inputs, health/spec.py; zeros unless cfg.durable_storage).
TELEMETRY_SCHEMA_VERSION = 4

# A "never happened" tick sentinel (scan.NEVER) becomes JSON null.
_NEVER = 2**31 - 1

# Per-line required integer fields of windows.jsonl (lat_hist is checked
# separately: a list of LAT_HIST_BINS non-negative ints).
WINDOW_FIELDS = (
    "window",
    "start",
    "ticks",
    "violations",
    "violating_clusters",
    "msgs",
    "cmds",
    "max_term",
    "max_commit",
    "lat_sum",
    "lat_cnt",
    "lat_excluded",
    "noop_blocked",
    "lm_skipped_pairs",
    "multi_leader",
    "reads",
    "read_lat_sum",
    "fsync_lag_sum",
    "fsync_lag_max",
)

# Per-line required fields of perf.jsonl (obs/timer.py ChunkTimer rows).
# Ints, bools, and non-negative float seconds; live_bytes is int-or-null
# (CPU publishes no memory stats) and jit_cache a {entry point: size} map.
PERF_INT_FIELDS = ("chunk", "ticks")
PERF_BOOL_FIELDS = ("warmup", "recompiled")
PERF_FLOAT_FIELDS = ("wall_s", "dispatch_s", "host_s", "device_wait_s", "gap_s")

# Per-line required fields of health.jsonl / alerts.jsonl (health/monitor.py).
# `eval` indices are contiguous PER SCOPE (serve streams fleet + per-tenant
# monitors into the same files); status/state values are the burn-engine
# lifecycle words.
HEALTH_INT_FIELDS = ("eval", "window_start", "windows", "ticks")
HEALTH_STATUSES = ("ok", "pending", "firing")
ALERT_FLOAT_FIELDS = ("burn_short", "burn_long")
ALERT_STATES = ("ok", "pending", "firing", "resolved")

MANIFEST_FIELDS = (
    "schema_version",
    "source",
    "created_unix",
    "config",
    "config_hash",
    "seed",
    "batch",
    "window",
    "ring",
    "jax_version",
    "backend",
)


def window_lines(records, first_index: int) -> list[dict]:
    """Aggregate a stacked WindowRecord (public layout: leaves
    [B, n_windows, ...]) into windows.jsonl line dicts, numbered from
    `first_index`. THE one aggregation: the fleet sink and the per-tenant
    streams (serve/tenancy.py slices the same records by cluster range) both
    call it, so a tenant's windows.jsonl can never drift from the fleet
    schema. Pure integer sums/mins/maxes -- `metrics_report` re-merges lines
    losslessly."""
    start = np.asarray(records.start)  # [B, n_windows] (lockstep: rows equal)
    fv = np.asarray(records.first_viol_tick, dtype=np.int64)
    m = {f: np.asarray(getattr(records.metrics, f)) for f in records.metrics._fields}
    n_windows = start.shape[1]
    lines = []
    for w in range(n_windows):
        viol = m["violations"][:, w]
        fvw = int(fv[:, w].min())
        lines.append({
            "window": first_index + w,
            "start": int(start[0, w]),
            "ticks": int(m["ticks"][0, w]),
            "violations": int(viol.sum()),
            "violating_clusters": int((viol > 0).sum()),
            "first_viol_tick": None if fvw == _NEVER else fvw,
            "msgs": int(m["total_msgs"].astype(np.int64)[:, w].sum()),
            "cmds": int(m["total_cmds"].astype(np.int64)[:, w].sum()),
            "max_term": int(m["max_term"][:, w].max()),
            "max_commit": int(m["max_commit"][:, w].max()),
            "lat_sum": int(m["lat_sum"].astype(np.int64)[:, w].sum()),
            "lat_cnt": int(m["lat_cnt"].astype(np.int64)[:, w].sum()),
            "lat_excluded": int(m["lat_excluded"].astype(np.int64)[:, w].sum()),
            "noop_blocked": int(m["noop_blocked"].astype(np.int64)[:, w].sum()),
            "lm_skipped_pairs": int(
                m["lm_skipped_pairs"].astype(np.int64)[:, w].sum()
            ),
            "multi_leader": int(
                m["multi_leader"].astype(np.int64)[:, w].sum()
            ),
            "reads": int(m["reads_served"].astype(np.int64)[:, w].sum()),
            "read_lat_sum": int(
                m["read_lat_sum"].astype(np.int64)[:, w].sum()
            ),
            "fsync_lag_sum": int(
                m["fsync_lag_sum"].astype(np.int64)[:, w].sum()
            ),
            "fsync_lag_max": int(m["fsync_lag_max"][:, w].max()),
            "lat_hist": [
                int(x) for x in m["lat_hist"].astype(np.int64)[:, w].sum(axis=0)
            ],
            "read_hist": [
                int(x) for x in m["read_hist"].astype(np.int64)[:, w].sum(axis=0)
            ],
        })
    return lines


def flight_lines(ticks, infos: StepInfo) -> list[dict]:
    """One cluster's flight-recorder export (telemetry.export_cluster output)
    as line dicts: one per captured tick, every StepInfo field. THE one
    flight serialization -- the sink's violation flights and the health
    plane's evidence snapshots (health/evidence.py) both call it, so the two
    file families stay renderable by the same metrics_report path."""
    fields = {f: np.asarray(getattr(infos, f)) for f in infos._fields}
    lines = []
    for i, t in enumerate(np.asarray(ticks)):
        row = {"tick": int(t)}
        for name, arr in fields.items():
            v = arr[i]
            row[name] = (
                [int(x) for x in v] if v.ndim else (int(v) if v.dtype != bool else bool(v))
            )
        lines.append(row)
    return lines


def config_hash(cfg: RaftConfig) -> str:
    """Stable short hash of the full config (key-sorted JSON), the manifest's
    comparability key: two runs diff cleanly iff their hashes match."""
    blob = json.dumps(dataclasses.asdict(cfg), sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


class TelemetrySink:
    """Writer half of the schema. Creating a sink truncates the directory's
    stream files (a rebuilt experiment gets a rebuilt stream, like the
    apply-log writer) and writes the manifest immediately, so a crashed run
    still leaves a validatable directory behind."""

    def __init__(
        self,
        directory: str,
        cfg: RaftConfig,
        *,
        seed: int,
        batch: int,
        window: int,
        ring: int,
        source: str = "driver",
    ):
        import jax

        self.directory = directory
        self.cfg = cfg
        self.window = window
        self.ring = ring
        self._n_windows = 0
        os.makedirs(directory, exist_ok=True)
        backend = jax.default_backend()
        manifest = {
            "schema_version": TELEMETRY_SCHEMA_VERSION,
            "source": source,
            "created_unix": int(time.time()),
            "config": dataclasses.asdict(cfg),
            "config_hash": config_hash(cfg),
            "seed": int(seed),
            "batch": int(batch),
            "window": int(window),
            "ring": int(ring),
            "jax_version": jax.__version__,
            "backend": backend,
        }
        with open(self._path("manifest.json"), "w") as f:
            json.dump(manifest, f, indent=2, sort_keys=True)
            f.write("\n")
        open(self._path("windows.jsonl"), "w").close()  # truncate the stream
        self._n_trace_windows = 0
        # A rebuilt run must not inherit the previous run's violation
        # recordings, rollup, or perf/trace/health streams: stale files under
        # a fresh manifest would misattribute another run's data to this one.
        # (perf/trace/health files are only re-created when armed.)
        import shutil

        for name in os.listdir(directory):
            p = os.path.join(directory, name)
            if name.startswith("evidence_") and os.path.isdir(p):
                shutil.rmtree(p)
            elif (name.startswith("flight_") and name.endswith(".jsonl")) or (
                name in ("summary.json", "perf.jsonl", "trace.jsonl",
                         "trace_windows.jsonl", "trace_meta.json",
                         "health.jsonl", "alerts.jsonl")
            ):
                os.remove(p)

    def _path(self, name: str) -> str:
        return os.path.join(self.directory, name)

    def append_windows(self, records) -> int:
        """Fleet-aggregate a stacked WindowRecord (public layout: leaves
        [B, n_windows, ...]) and append one JSONL line per window. Returns the
        number of lines written. Aggregation is pure integer sums/mins/maxes,
        so `metrics_report` can re-merge lines losslessly."""
        lines = window_lines(records, self._n_windows)
        with open(self._path("windows.jsonl"), "a") as f:
            for line in lines:
                f.write(json.dumps(line) + "\n")
        self._n_windows += len(lines)
        return len(lines)

    def append_perf(self, rows: list[dict]) -> int:
        """Append per-chunk perf-attribution rows (obs/timer.py ChunkTimer)
        to perf.jsonl. Rows are already plain JSON-able dicts -- the timer is
        host-side by construction. Returns the number of lines written."""
        with open(self._path("perf.jsonl"), "a") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")
        return len(rows)

    def write_trace_meta(self, spec) -> str:
        """Self-description of the trace stream (a trace.TraceSpec): written
        once when tracing is armed so trace.jsonl decodes standalone."""
        from raft_sim_tpu.trace import KINDS
        from raft_sim_tpu.trace.ring import COV_BITS, COV_WORDS

        path = self._path("trace_meta.json")
        doc = {
            "trace_schema": 1,
            "kinds": dict(KINDS),
            "depth": int(spec.depth),
            "coverage": bool(spec.coverage),
            "coverage_bits": COV_BITS,
            "coverage_words": COV_WORDS,
            "freeze_kind": int(spec.freeze_kind),
        }
        with open(path, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        return path

    def append_trace(self, tracewins) -> int:
        """Append one chunk's stacked trace windows (batch-minor
        trace.TraceWindowOut, leaves [n_windows, ..., B]) as trace.jsonl event
        lines + trace_windows.jsonl completeness rows. Returns the number of
        windows appended. Event order on disk is window-major, then cluster,
        then device slot order -- per-cluster tick monotone, the invariant
        validate() and the history loader check."""
        from raft_sim_tpu.trace.history import iter_window_events

        n = np.asarray(tracewins.win.n)  # [W, B]
        n_windows, batch = n.shape
        depth = np.asarray(tracewins.win.ev_kind).shape[1]
        kept = np.minimum(n, depth)
        dropped = n - kept
        # Cumulative coverage at each window's end ([W, C, B] uint32 words):
        # report the fleet-max per-cluster popcount -- the "how much of the
        # transition space has the best cluster seen" progress number.
        from raft_sim_tpu.ops.bitplane import np_popcount_u32

        cov = np.asarray(tracewins.cov)
        cov_per = np.max(np_popcount_u32(cov).sum(axis=1), axis=-1)
        per_window_events: dict[int, list] = {w: [] for w in range(n_windows)}
        for w, c, evs in iter_window_events(tracewins):
            per_window_events[w].append((c, evs))
        with open(self._path("trace.jsonl"), "a") as f:
            for w in range(n_windows):
                widx = self._n_trace_windows + w
                for c, evs in per_window_events[w]:
                    for e in evs:
                        f.write(json.dumps({
                            "w": widx, "c": int(c), "t": e.tick,
                            "node": e.node, "k": e.kind, "d": e.detail,
                        }) + "\n")
        with open(self._path("trace_windows.jsonl"), "a") as f:
            for w in range(n_windows):
                drop_map = {
                    str(c): int(d)
                    for c, d in enumerate(dropped[w])
                    if d > 0
                }
                row = {
                    "window": self._n_trace_windows + w,
                    "emitted": int(n[w].sum()),
                    "retained": int(kept[w].sum()),
                    "dropped": int(dropped[w].sum()),
                    "dropped_by_cluster": drop_map,
                    "cov_bits_max": int(cov_per[w]),
                }
                f.write(json.dumps(row) + "\n")
        self._n_trace_windows += n_windows
        return n_windows

    def write_flight(self, cluster: int, ticks, infos: StepInfo) -> str:
        """Write one cluster's flight-recorder export (telemetry.export_cluster
        output) as flight_<cluster>.jsonl: one line per captured tick carrying
        every StepInfo field. Returns the path written."""
        path = self._path(f"flight_{cluster}.jsonl")
        with open(path, "w") as f:
            for row in flight_lines(ticks, infos):
                f.write(json.dumps(row) + "\n")
        return path

    def write_summary(self, summary: dict) -> str:
        """End-of-run rollup (FleetSummary._asdict() + caller extras)."""
        path = self._path("summary.json")
        with open(path, "w") as f:
            json.dump(summary, f, indent=2, sort_keys=True)
            f.write("\n")
        return path


def validate(directory: str) -> list[str]:
    """Check a telemetry directory against the schema. Returns a list of
    human-readable problems ([] = valid). Deliberately dependency-free (no
    jsonschema in the image): the schema IS this function plus the field
    tuples above."""
    errors = []
    man_path = os.path.join(directory, "manifest.json")
    if not os.path.isfile(man_path):
        return [f"missing manifest.json in {directory}"]
    try:
        with open(man_path) as f:
            man = json.load(f)
    except (OSError, json.JSONDecodeError) as ex:
        return [f"manifest.json unreadable: {ex}"]
    for k in MANIFEST_FIELDS:
        if k not in man:
            errors.append(f"manifest.json: missing field {k!r}")
    if man.get("schema_version") != TELEMETRY_SCHEMA_VERSION:
        errors.append(
            f"manifest.json: schema_version {man.get('schema_version')!r}, "
            f"expected {TELEMETRY_SCHEMA_VERSION}"
        )
    if "config" in man:
        try:
            cfg = RaftConfig(**man["config"])
            if "config_hash" in man and config_hash(cfg) != man["config_hash"]:
                errors.append("manifest.json: config_hash does not match config")
        except (TypeError, AssertionError) as ex:
            errors.append(f"manifest.json: config does not load: {ex}")

    win_path = os.path.join(directory, "windows.jsonl")
    if not os.path.isfile(win_path):
        errors.append("missing windows.jsonl")
        return errors
    prev_idx, prev_end = -1, None
    with open(win_path) as f:
        for ln, raw in enumerate(f, 1):
            try:
                row = json.loads(raw)
            except json.JSONDecodeError as ex:
                errors.append(f"windows.jsonl:{ln}: not JSON: {ex}")
                continue
            for k in WINDOW_FIELDS:
                if not isinstance(row.get(k), int):
                    errors.append(f"windows.jsonl:{ln}: field {k!r} missing or non-int")
            fv = row.get("first_viol_tick")
            if fv is not None and not isinstance(fv, int):
                errors.append(f"windows.jsonl:{ln}: first_viol_tick must be int or null")
            for hk in ("lat_hist", "read_hist"):
                hist = row.get(hk)
                if (
                    not isinstance(hist, list)
                    or len(hist) != LAT_HIST_BINS
                    or not all(isinstance(x, int) and x >= 0 for x in hist)
                ):
                    errors.append(
                        f"windows.jsonl:{ln}: {hk} must be {LAT_HIST_BINS} "
                        "non-negative ints"
                    )
            if isinstance(row.get("window"), int):
                if row["window"] != prev_idx + 1:
                    errors.append(
                        f"windows.jsonl:{ln}: window index {row['window']} "
                        f"(expected {prev_idx + 1})"
                    )
                prev_idx = row["window"]
            if (
                isinstance(row.get("start"), int)
                and isinstance(row.get("ticks"), int)
            ):
                if row["ticks"] < 1:
                    errors.append(f"windows.jsonl:{ln}: ticks must be >= 1")
                # Windows must advance monotonically without overlap. Gaps ARE
                # legal: ticks stepped outside run() (e.g. Session.offer) are
                # not windowed.
                if prev_end is not None and row["start"] < prev_end:
                    errors.append(
                        f"windows.jsonl:{ln}: start {row['start']} overlaps "
                        f"previous window (ends at {prev_end})"
                    )
                prev_end = row["start"] + row["ticks"]

    perf_path = os.path.join(directory, "perf.jsonl")
    if os.path.isfile(perf_path):
        prev_chunk = -1
        with open(perf_path) as f:
            for ln, raw in enumerate(f, 1):
                try:
                    row = json.loads(raw)
                except json.JSONDecodeError as ex:
                    errors.append(f"perf.jsonl:{ln}: not JSON: {ex}")
                    continue
                for k in PERF_INT_FIELDS:
                    if not isinstance(row.get(k), int) or row.get(k) is True:
                        errors.append(f"perf.jsonl:{ln}: field {k!r} missing or non-int")
                for k in PERF_BOOL_FIELDS:
                    if not isinstance(row.get(k), bool):
                        errors.append(f"perf.jsonl:{ln}: field {k!r} missing or non-bool")
                for k in PERF_FLOAT_FIELDS:
                    v = row.get(k)
                    if not isinstance(v, (int, float)) or isinstance(v, bool) or v < 0:
                        errors.append(
                            f"perf.jsonl:{ln}: field {k!r} missing or not a "
                            "non-negative number"
                        )
                lb = row.get("live_bytes")
                if lb is not None and (not isinstance(lb, int) or isinstance(lb, bool)):
                    errors.append(f"perf.jsonl:{ln}: live_bytes must be int or null")
                jc = row.get("jit_cache")
                if not isinstance(jc, dict) or not all(
                    isinstance(k, str) and isinstance(v, int)
                    and not isinstance(v, bool) for k, v in jc.items()
                ):
                    errors.append(
                        f"perf.jsonl:{ln}: jit_cache must map entry points to "
                        "int sizes"
                    )
                if isinstance(row.get("chunk"), int):
                    if row["chunk"] != prev_chunk + 1:
                        errors.append(
                            f"perf.jsonl:{ln}: chunk index {row['chunk']} "
                            f"(expected {prev_chunk + 1})"
                        )
                    prev_chunk = row["chunk"]

    trace_path = os.path.join(directory, "trace.jsonl")
    if os.path.isfile(trace_path):
        meta_path = os.path.join(directory, "trace_meta.json")
        n_kinds = None
        if not os.path.isfile(meta_path):
            errors.append("trace.jsonl present but trace_meta.json missing")
        else:
            try:
                with open(meta_path) as f:
                    tmeta = json.load(f)
                kinds = tmeta.get("kinds")
                if not isinstance(kinds, dict) or not kinds:
                    errors.append("trace_meta.json: missing kinds map")
                else:
                    n_kinds = max(kinds.values()) + 1
            except (OSError, json.JSONDecodeError) as ex:
                errors.append(f"trace_meta.json unreadable: {ex}")
        last_tick: dict[int, int] = {}
        with open(trace_path) as f:
            for ln, raw in enumerate(f, 1):
                try:
                    row = json.loads(raw)
                except json.JSONDecodeError as ex:
                    errors.append(f"trace.jsonl:{ln}: not JSON: {ex}")
                    continue
                bad = [
                    k for k in ("w", "c", "t", "node", "k", "d")
                    if not isinstance(row.get(k), int) or row.get(k) is True
                ]
                if bad:
                    errors.append(
                        f"trace.jsonl:{ln}: fields {bad} missing or non-int"
                    )
                    continue
                if n_kinds is not None and not 1 <= row["k"] < n_kinds:
                    errors.append(
                        f"trace.jsonl:{ln}: kind {row['k']} outside "
                        f"[1, {n_kinds})"
                    )
                c = row["c"]
                if row["t"] < last_tick.get(c, -1):
                    errors.append(
                        f"trace.jsonl:{ln}: cluster {c} tick {row['t']} "
                        f"regresses (stream truncated or reordered)"
                    )
                last_tick[c] = max(last_tick.get(c, -1), row["t"])
        tw_path = os.path.join(directory, "trace_windows.jsonl")
        if not os.path.isfile(tw_path):
            errors.append("trace.jsonl present but trace_windows.jsonl missing")
        else:
            prev_tw = -1
            with open(tw_path) as f:
                for ln, raw in enumerate(f, 1):
                    try:
                        row = json.loads(raw)
                    except json.JSONDecodeError as ex:
                        errors.append(f"trace_windows.jsonl:{ln}: not JSON: {ex}")
                        continue
                    for k in ("window", "emitted", "retained", "dropped"):
                        if not isinstance(row.get(k), int) or row.get(k) is True:
                            errors.append(
                                f"trace_windows.jsonl:{ln}: field {k!r} "
                                "missing or non-int"
                            )
                    if not isinstance(row.get("dropped_by_cluster"), dict):
                        errors.append(
                            f"trace_windows.jsonl:{ln}: dropped_by_cluster "
                            "must be a map"
                        )
                    if isinstance(row.get("window"), int):
                        if row["window"] != prev_tw + 1:
                            errors.append(
                                f"trace_windows.jsonl:{ln}: window index "
                                f"{row['window']} (expected {prev_tw + 1})"
                            )
                        prev_tw = row["window"]

    for name in sorted(os.listdir(directory)):
        if not (name.startswith("flight_") and name.endswith(".jsonl")):
            continue
        with open(os.path.join(directory, name)) as f:
            for ln, raw in enumerate(f, 1):
                try:
                    row = json.loads(raw)
                except json.JSONDecodeError as ex:
                    errors.append(f"{name}:{ln}: not JSON: {ex}")
                    continue
                missing = [k for k in ("tick", *StepInfo._fields) if k not in row]
                if missing:
                    errors.append(f"{name}:{ln}: missing fields {missing}")
    errors.extend(validate_health_files(directory))
    return errors


def validate_health_files(directory: str) -> list[str]:
    """Schema-check a directory's health.jsonl / alerts.jsonl / evidence
    bundles ([] = valid, including when none are present). Split out of
    validate() so farm out-dirs -- which carry the farm manifest schema, not
    a telemetry manifest -- check their health streams through the same
    code (farm/core.py validate_farm_dir)."""
    errors = []
    health_path = os.path.join(directory, "health.jsonl")
    alerts_path = os.path.join(directory, "alerts.jsonl")
    evidence_named: list[str] = []
    if os.path.isfile(health_path):
        if not os.path.isfile(alerts_path):
            errors.append("health.jsonl present but alerts.jsonl missing")
        prev_eval: dict[str, int] = {}
        with open(health_path) as f:
            for ln, raw in enumerate(f, 1):
                try:
                    row = json.loads(raw)
                except json.JSONDecodeError as ex:
                    errors.append(f"health.jsonl:{ln}: not JSON: {ex}")
                    continue
                for k in HEALTH_INT_FIELDS:
                    if not isinstance(row.get(k), int) or row.get(k) is True:
                        errors.append(
                            f"health.jsonl:{ln}: field {k!r} missing or non-int"
                        )
                scope = row.get("scope")
                if not isinstance(scope, str) or not scope:
                    errors.append(f"health.jsonl:{ln}: scope missing")
                    scope = "?"
                if row.get("status") not in HEALTH_STATUSES:
                    errors.append(
                        f"health.jsonl:{ln}: status {row.get('status')!r} "
                        f"(have: {', '.join(HEALTH_STATUSES)})"
                    )
                for k in ("slis", "burn"):
                    if not isinstance(row.get(k), dict):
                        errors.append(f"health.jsonl:{ln}: {k} must be a map")
                if isinstance(row.get("eval"), int):
                    want = prev_eval.get(scope, -1) + 1
                    if row["eval"] != want:
                        errors.append(
                            f"health.jsonl:{ln}: scope {scope!r} eval "
                            f"{row['eval']} (expected {want})"
                        )
                    prev_eval[scope] = row["eval"]
    if os.path.isfile(alerts_path):
        with open(alerts_path) as f:
            for ln, raw in enumerate(f, 1):
                try:
                    row = json.loads(raw)
                except json.JSONDecodeError as ex:
                    errors.append(f"alerts.jsonl:{ln}: not JSON: {ex}")
                    continue
                if not isinstance(row.get("eval"), int) or row.get("eval") is True:
                    errors.append(f"alerts.jsonl:{ln}: field 'eval' missing or non-int")
                for k in ("scope", "objective", "rule"):
                    if not isinstance(row.get(k), str) or not row.get(k):
                        errors.append(f"alerts.jsonl:{ln}: field {k!r} missing")
                if row.get("state") not in ALERT_STATES:
                    errors.append(
                        f"alerts.jsonl:{ln}: state {row.get('state')!r} "
                        f"(have: {', '.join(ALERT_STATES)})"
                    )
                for k in ALERT_FLOAT_FIELDS:
                    v = row.get(k)
                    if not isinstance(v, (int, float)) or isinstance(v, bool) or v < 0:
                        errors.append(
                            f"alerts.jsonl:{ln}: field {k!r} missing or not a "
                            "non-negative number"
                        )
                wc = row.get("worst_clusters")
                if not isinstance(wc, list) or not all(
                    isinstance(w, dict) and isinstance(w.get("cluster"), int)
                    for w in wc
                ):
                    errors.append(
                        f"alerts.jsonl:{ln}: worst_clusters must be a list of "
                        "{cluster, value, score} maps"
                    )
                ev = row.get("evidence")
                if ev is not None:
                    if not isinstance(ev, str):
                        errors.append(
                            f"alerts.jsonl:{ln}: evidence must be a dir name or null"
                        )
                    else:
                        evidence_named.append(ev)
                        if not os.path.isdir(os.path.join(directory, ev)):
                            errors.append(
                                f"alerts.jsonl:{ln}: evidence dir {ev} missing"
                            )
                if row.get("state") == "firing" and ev is None:
                    errors.append(
                        f"alerts.jsonl:{ln}: firing alert carries no evidence"
                    )
    for name in sorted(os.listdir(directory)):
        if name.startswith("evidence_") and os.path.isdir(
            os.path.join(directory, name)
        ):
            from raft_sim_tpu.health.evidence import validate_bundle

            errors.extend(validate_bundle(os.path.join(directory, name)))
            if name not in evidence_named:
                errors.append(
                    f"{name}: evidence bundle not named by any alerts.jsonl row"
                )
    return errors


# --------------------------------------------------------------- multichip
# The multi-chip proof artifact (MULTICHIP_r*.json, written by
# tools/multihost_check.py --out): one diffable row per round instead of the
# historical rc-only stub {n_devices, rc, ok}. `throughput_ticks_per_s` is
# cluster-ticks/s of the sharded run on THIS machine (CPU rows are never
# roofline anchors -- same rule as BENCH rows); `per_device_bytes_per_tick`
# is the Pass C carry+inputs price of one device's cluster slice;
# `parity_hash` is sha256 over the gathered metrics JSON, equal across the
# multi-process run and the single-process reference when (and only when)
# the trajectories matched bit-for-bit.
MULTICHIP_SCHEMA = "multichip-v2"
MULTICHIP_INT_FIELDS = ("n_devices", "n_processes", "batch", "ticks",
                        "violations")
MULTICHIP_BOOL_FIELDS = ("match",)
MULTICHIP_FLOAT_FIELDS = ("throughput_ticks_per_s", "per_device_bytes_per_tick")
MULTICHIP_STR_FIELDS = ("schema", "platform", "parity_hash")


def validate_multichip(path: str) -> list[str]:
    """Schema-check a MULTICHIP artifact ([] = valid). Legacy rc-only stubs
    (no "schema" key) are reported as legacy, not silently passed."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as ex:
        return [f"{path}: unreadable: {ex}"]
    if "schema" not in doc:
        return [f"{path}: legacy rc-only stub (pre-{MULTICHIP_SCHEMA}); "
                "regenerate with tools/multihost_check.py --out"]
    errors = []
    if doc.get("schema") != MULTICHIP_SCHEMA:
        errors.append(
            f"{path}: schema {doc.get('schema')!r}, expected {MULTICHIP_SCHEMA}"
        )
    for k in MULTICHIP_INT_FIELDS:
        if not isinstance(doc.get(k), int) or doc.get(k) is True:
            errors.append(f"{path}: field {k!r} missing or non-int")
    for k in MULTICHIP_BOOL_FIELDS:
        if not isinstance(doc.get(k), bool):
            errors.append(f"{path}: field {k!r} missing or non-bool")
    for k in MULTICHIP_FLOAT_FIELDS:
        v = doc.get(k)
        if not isinstance(v, (int, float)) or isinstance(v, bool) or v < 0:
            errors.append(
                f"{path}: field {k!r} missing or not a non-negative number"
            )
    for k in MULTICHIP_STR_FIELDS:
        if not isinstance(doc.get(k), str) or not doc.get(k):
            errors.append(f"{path}: field {k!r} missing or empty")
    ph = doc.get("parity_hash")
    if isinstance(ph, str) and len(ph) != 64:
        errors.append(f"{path}: parity_hash must be a sha256 hex digest")
    return errors


def read_windows(directory: str) -> list[dict]:
    """Load windows.jsonl as a list of dicts (validation is separate)."""
    with open(os.path.join(directory, "windows.jsonl")) as f:
        return [json.loads(line) for line in f if line.strip()]


def read_manifest(directory: str) -> dict:
    with open(os.path.join(directory, "manifest.json")) as f:
        return json.load(f)
