"""Packed boolean bit-planes: `[..., n, ...] bool` <-> `[..., W, ...] uint32`.

The [N, N]-shaped boolean planes (ClusterState.votes, the fault-injection
delivery mask, the pre-vote grant bits) ride one BYTE per bit in dense form and
dominate the per-tick HBM traffic of wide clusters next to the int8 edge planes
(types.Mailbox docstring; tools/traffic_audit.py accounts the exact bytes).
This module packs such a plane 32 bits per uint32 word along one node axis:
W = ceil(n / 32) words replace n bools (N=51 packs into 2 words).

Conventions and invariants:

  - Bit j of word w along the packed axis holds source index ``32*w + j``.
  - All functions take an explicit ``axis`` (the node axis being packed or
    unpacked) and work at ANY rank, so the same code serves the single-cluster
    kernel ([N, N] -> [N, W], vmap-lifted) and the batch-minor hot path
    ([N, N, B] -> [N, W, B]) -- shapes stay static, nothing gathers or
    reshapes (iota + shift + masked reduce only, the constraint every op
    shared with models/raft_batched.py observes -- see log_ops.iota).
  - CANONICAL planes keep their padding bits (bit positions >= n in the last
    word) ZERO. ``pack`` always produces canonical words, and `&`/`|` of
    canonical words are canonical, so `popcount`-based quorum counts are exact.
    The one operator that breaks canonicality is `~`: NOT a packed plane only
    inside an AND with a canonical operand (``a & ~b``), never bare.

Word-level boolean algebra is just the integer operators -- ``a & b``,
``a | b``, ``a & ~b`` (andnot) -- which is the point: a 32-lane boolean op per
instruction and an 8x (bool) to 32x (one-hot int32) denser memory footprint.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

WORD = 32


def n_words(n: int) -> int:
    """Words needed for an n-bit row: ceil(n / 32)."""
    return -(-n // WORD)


def _axis(a: int, ndim: int) -> int:
    return a % ndim


def pack(x: jax.Array, axis: int = -1) -> jax.Array:
    """Pack bools along `axis` into uint32 words: shape n -> ceil(n/32) there.

    Returns canonical words (padding bits zero). Works at any rank; vmap-safe.
    """
    ax = _axis(axis, x.ndim)
    n = x.shape[ax]
    w = n_words(n)
    kshape = tuple(n if d == ax else 1 for d in range(x.ndim))
    k = lax.broadcasted_iota(jnp.int32, kshape, ax)  # bit index along `axis`
    xb = x.astype(jnp.uint32)
    words = []
    for wi in range(w):
        sh = k - WORD * wi
        valid = (sh >= 0) & (sh < WORD)
        shifted = xb << jnp.where(valid, sh, 0).astype(jnp.uint32)
        contrib = jnp.where(valid, shifted, jnp.uint32(0))
        words.append(jnp.sum(contrib, axis=ax, keepdims=True, dtype=jnp.uint32))
    return jnp.concatenate(words, axis=ax)


def unpack(words: jax.Array, n: int, axis: int = -1) -> jax.Array:
    """Inverse of `pack`: uint32 words along `axis` -> n bools there."""
    ax = _axis(axis, words.ndim)
    w = words.shape[ax]
    assert w == n_words(n), f"{w} words cannot hold {n} bits"
    oshape = tuple(n if d == ax else words.shape[d] for d in range(words.ndim))
    kshape = tuple(n if d == ax else 1 for d in range(words.ndim))
    k = lax.broadcasted_iota(jnp.int32, kshape, ax)
    out = jnp.zeros(oshape, bool)
    for wi in range(w):
        word = lax.slice_in_dim(words, wi, wi + 1, axis=ax)
        sh = k - WORD * wi
        valid = (sh >= 0) & (sh < WORD)
        bit = (word >> jnp.where(valid, sh, 0).astype(jnp.uint32)) & jnp.uint32(1)
        out = out | (valid & (bit != 0))
    return out


def popcount(words: jax.Array) -> jax.Array:
    """Per-word population count (uint32 in, uint32 out), elementwise."""
    return lax.population_count(words)


def count(words: jax.Array, axis: int = -1) -> jax.Array:
    """Row popcount: total set bits along the word axis, int32.

    The packed-quorum primitive: `count(votes, axis=word_axis) >= cfg.quorum`
    replaces `jnp.sum(votes_bool, axis=node_axis) >= cfg.quorum`. Exact on
    canonical planes (padding bits zero)."""
    return jnp.sum(popcount(words).astype(jnp.int32), axis=_axis(axis, words.ndim))


def np_popcount_u32(arr) -> "np.ndarray":
    """Host-side per-word popcount of a uint32 ndarray (numpy-1.x compatible:
    unpack the word bytes, sum the bits) -- the numpy counterpart of
    `popcount` for consumers that fold exported packed planes on the host
    (telemetry sink coverage rollups, the coverage-fitness search). Single
    copy here so the two can never drift."""
    import numpy as np

    a = np.ascontiguousarray(np.asarray(arr, np.uint32))
    bytes_ = a.view(np.uint8).reshape(a.shape + (4,))
    return np.unpackbits(bytes_, axis=-1).sum(axis=-1, dtype=np.int64)


def andnot(a: jax.Array, b: jax.Array) -> jax.Array:
    """a & ~b. Canonical whenever `a` is canonical (the ~ never escapes the &)."""
    return a & ~b


def full_row(n: int) -> jax.Array:
    """[W] uint32 with every VALID bit set -- the canonical all-true row (the
    packed form's `jnp.ones((n,), bool)`)."""
    return pack(jnp.ones((n,), bool))


def bit_row(i: int, n: int) -> jax.Array:
    """[W] uint32 with only bit `i` set (a packed one-hot row)."""
    return pack(jnp.zeros((n,), bool).at[i].set(True))


def eye(n: int) -> jax.Array:
    """[N, W] packed identity: row i holds exactly bit i (the packed
    `jnp.eye(n, dtype=bool)` -- a candidate's self-vote rows)."""
    return pack(jnp.eye(n, dtype=bool), axis=1)


def one_bit(i: jax.Array, n: int) -> jax.Array:
    """[W] uint32 word row with only (traced) bit `i` set: the dynamic
    counterpart of `bit_row`. Broadcasts: a batched `i` of shape [B] yields
    [W, B] rows (the membership-plane layout of the reconfiguration plane,
    raft_sim_tpu/reconfig). Out-of-range `i` (e.g. the NIL sentinel) yields
    the all-zero row, so callers can feed sentinels unguarded."""
    i = jnp.asarray(i, jnp.int32)
    w = jnp.arange(n_words(n), dtype=jnp.int32).reshape(
        (n_words(n),) + (1,) * i.ndim
    )  # [W, *i.shape]
    hit = (w == i // WORD) & (i >= 0)[None] & (i < n)[None]
    return jnp.where(
        hit, jnp.uint32(1) << (i % WORD).astype(jnp.uint32)[None], jnp.uint32(0)
    )


def set_bit(plane: jax.Array, row, col, value: bool = True) -> jax.Array:
    """Set (or clear) single bit `col` of `plane[row]` on a [N, W] packed plane.
    Test/state-surgery helper; kernels use the word algebra directly."""
    w, b = col // WORD, jnp.uint32(1 << (col % WORD))
    word = plane[row, w]
    new = (word | b) if value else (word & ~b)
    return plane.at[row, w].set(new)


def get_bit(plane: jax.Array, row, col) -> jax.Array:
    """Test single bit `col` of `plane[row]` on a [N, W] packed plane -> bool."""
    return (plane[row, col // WORD] >> (col % WORD)) & 1 != 0
