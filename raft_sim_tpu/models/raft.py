"""The Raft tick kernel: one pure, vmap'able state transition per simulated tick.

This is the TPU-native re-expression of the reference's `wait` event loop
(core.clj:176-195): deliver -> handle -> collect. Where the reference blocks on
`alts!!` over [inbound requests, rpc responses, timeout] and dispatches ONE message per
loop iteration, the array kernel delivers the whole [N, N] mailbox at once and folds
every node's inbound edges through vectorized handler logic -- `jnp.where` lattices
instead of `cond` cascades, no Python control flow, static shapes throughout.

Handler provenance (all spec-correct; the reference's deviations are catalogued in
SURVEY.md section 2.3 and deliberately NOT carried):

  phase 1  term adoption         <- scattered `(> term current-term)` checks
                                    (core.clj:97, 129-130, 144-145); unlike the
                                    reference, RequestVote also adopts terms (bug 2.3.2)
  phase 2  vote requests         <- request-vote-handler (core.clj:91-103), with the
                                    spec up-to-date check instead of compare-prev?
  phase 3  append requests       <- append-entries-handler (core.clj:105-123), with
                                    spec conflict-truncate-then-append instead of the
                                    remove-from! bug (2.3.7) and real leader-commit
                                    handling instead of apply-everything (2.3.6);
                                    under compaction also the InstallSnapshot
                                    analogue (req_off == -1 edges install the
                                    sender's base/base_term/base_chk)
  phase 5.5 log compaction       <- absent in the reference (its log vector is
                                    unbounded, log.clj:33); the ring must free
                                    committed slots so client workloads never
                                    exhaust the fixed-capacity arrays
  phase 4  responses             <- vote-response-handler (core.clj:125-139) and
                                    append-response-handler (core.clj:141-149), with
                                    next-index = match+1 (bug 2.3.10)
  phase 5  leader commit         <- absent in the reference (bug 2.3.8): quorum-th
                                    largest match index, current-term restriction
  phase 6  client injection      <- client-set-handler's leader branch (core.clj:156-160)
  phase 7  timers                <- generate-timeout + the nil dispatch arm
                                    (core.clj:162-174, 193-195); election timers reset
                                    only on vote grant / valid AppendEntries, not on
                                    every message (bug 2.3.11)
  phase 8  outbox                <- request-vote-rpc / append-entries-rpc
                                    (core.clj:48-67) writing the next tick's mailbox
  phase 9  invariants + metrics  <- absent in the reference; north-star requirement
  phase -1 restart wipe          <- the reference's process-death model (only committed
                                    values are durable, log.clj:16-18); here restart
                                    keeps the Raft persistent triple up to the DURABLE
                                    watermarks (raft_sim_tpu/storage; with
                                    cfg.durable_storage off the disk is perfect and the
                                    full triple survives), wipes volatile state, and
                                    down nodes are gated out of delivery, timers,
                                    leadership, and commit
  phase 7.5 fsync flush          <- absent in the reference (its file-backed atom has
                                    no fsync discipline, log.clj:16-18): the durable
                                    watermarks advance on the device-side fsync model's
                                    completed flushes, and the section-3.8 gates hold
                                    AE acks and vote grants to durable state

Everything is written for ONE cluster (shapes [N], [N, N], [N, CAP]); `jax.vmap` lifts
to [batch, ...] and `lax.scan` (sim/scan.py) rolls ticks.

TRACE DELTA CONTRACT (raft_sim_tpu/trace, cfg.track_trace): the protocol
trace plane derives discrete events from this kernel's state DELTAS --
role, term, voted_for, commit_index, log_len, dur_len, and (reconfiguration
plane) cfg_epoch, log_cfg, xfer_to, read_idx -- outside the kernel (one
extractor serves both kernels and any step_fn override; zero step lowerings
added).
Phase-order properties load-bearing for the whole-history checker, which must
survive refactors: (1) a node that loses leadership and accepts entries in
one tick changes `role` in the SAME tick as `log_len` (phase 1 adoption
precedes phase 3 append -- the checker replays role changes before log
changes); (2) a win (phase 4) can never co-occur with an AE-accept
truncation on the same node (a candidate that accepted a current-term AE
stepped down in phase 3 and cannot win); (3) elections precede the
end-of-tick config derivation, so EV_LEADER events belong to the TICK-START
per-node configuration (EV_CFG_APPLY/ROLLBACK replay after the role
kinds); (4) a read slot dropped
while its holder stays a same-term un-restarted leader was SERVED -- every
cancel path changes role/term or sets `restarted` (phase 5.2's clear
rules); (5) a `dur_len` ADVANCE is always a completed flush (EV_FSYNC: the
only writer besides recovery is phase 7.5, and recovery never raises it),
and a `log_len` DROP on a `restarted` node is always the recovery
truncation (EV_RECOVER_TRUNC: restarted nodes receive nothing, so the
AE conflict truncation cannot co-occur on them). See trace/events.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from raft_sim_tpu.models import cfglog
from raft_sim_tpu.ops import bitplane, log_ops
from raft_sim_tpu.storage import plane as storage_plane
from raft_sim_tpu.types import (
    CANDIDATE,
    FOLLOWER,
    LAT_HIST_BINS,
    LEADER,
    NIL,
    NOOP,
    PRECANDIDATE,
    REQ_APPEND,
    REQ_PREVOTE,
    REQ_TIMEOUT_NOW,
    REQ_VOTE,
    RESP_APPEND,
    RESP_PREVOTE,
    RESP_VOTE,
    ClusterState,
    Mailbox,
    StepInfo,
    StepInputs,
    node_dtype,
)
from raft_sim_tpu.utils.config import RaftConfig


def step(cfg: RaftConfig, s: ClusterState, inp: StepInputs) -> tuple[ClusterState, StepInfo]:
    """Advance one cluster by one tick. Pure; jit/vmap/scan-safe.

    Under cfg.compact_planes the carry arrives in the compacted layout
    (ops/tile.py: per-edge value planes bit-packed into flat uint32 legs,
    word/window planes flattened); this boundary unpacks to the dense
    working view, runs the identical dense tick, and repacks -- gated-off
    mailbox legs are passed through verbatim (`reuse`) so the
    carry-passthrough contract holds exactly as in the dense layout.
    Trajectories are bit-identical either way (tests/test_tile.py)."""
    if not cfg.compact_planes:
        return _step(cfg, s, inp)
    from raft_sim_tpu.ops import tile

    s2, info = _step(cfg, tile.unpack_state(cfg, s), tile.unpack_inputs(cfg, inp))
    return tile.pack_state(cfg, s2, reuse=s), info


def _step(cfg: RaftConfig, s: ClusterState, inp: StepInputs) -> tuple[ClusterState, StepInfo]:
    """The dense tick body (the layout-independent protocol semantics)."""
    n, e, cap = cfg.n_nodes, cfg.max_entries_per_rpc, cfg.log_capacity
    comp = cfg.compaction  # static: ring-log compaction + snapshot catch-up active
    track = cfg.track_offer_ticks  # static: offer-tick plane + latency metric active
    rcf = cfg.reconfig  # static: joint-consensus membership plane active
    xfr = cfg.leader_transfer  # static: TimeoutNow transfer plane active
    dur = cfg.durable_storage  # static: fsync/WAL durability plane active
    rdx = cfg.read_index  # static: ReadIndex read traffic class active
    rdl = cfg.read_lease  # static: lease-based reads (thesis 6.4.1) active
    ids = jnp.arange(n, dtype=jnp.int32)
    eye = jnp.eye(n, dtype=bool)
    eye_p = bitplane.eye(n)  # [N, W] packed self-bit rows (votes plane layout)
    zw = jnp.uint32(0)
    snd_ids = jnp.broadcast_to(ids[:, None], (n, n))  # [sender, receiver] -> sender id

    # ---- phase -1: restart (crash fault) -----------------------------------------
    # A node restarting this tick rejoins as a fresh follower: the Raft persistent
    # triple (currentTerm, votedFor, log[]) survives -- including the snapshot
    # (log_base/base_term/base_chk), so commitIndex resumes at log_base, the
    # durable applied prefix -- everything else is volatile and wiped (Raft fig. 2
    # state table). The reference instead persists only committed values
    # (log.clj:16-18), so its restarted process forgets term/vote -- bug 2.3.12,
    # deliberately not carried. HOW MUCH of the triple survives is the durable
    # storage plane's gate (raft_sim_tpu/storage, cfg.durable_storage): with the
    # gate off the disk is perfect and the full triple survives instantly; with
    # it on, the recovery block below rewinds term/vote to the durable snapshot
    # and truncates the log tail the disk never confirmed (dissertation section
    # 3.8 -- the failure class the plane exists to express). Wiping commitIndex
    # here (before `old` is captured for phase 9) keeps the monotonic-commit
    # invariant meaningful.
    rs = inp.restarted
    s = s._replace(
        role=jnp.where(rs, FOLLOWER, s.role),
        leader_id=jnp.where(rs, NIL, s.leader_id),
        votes=jnp.where(rs[:, None], zw, s.votes),
        next_index=jnp.where(rs[:, None], 1, s.next_index),
        match_index=jnp.where(rs[:, None], 0, s.match_index),
        ack_age=jnp.where(rs[:, None], cfg.ack_age_sat, s.ack_age),
        commit_index=jnp.where(rs, s.log_base, s.commit_index),
        commit_chk=jnp.where(rs, s.base_chk, s.commit_chk),
        deadline=jnp.where(rs, s.clock + inp.timeout_draw, s.deadline),
    )
    if dur:
        # Crash recovery (storage/plane.recover): the disk holds the
        # fsynced prefix for sure plus whatever un-fsynced tail the
        # in-flight writes reached, minus the torn tail the recovery
        # checksum rejects (inp.torn_drop, drawn every tick, consumed only
        # here); term/votedFor rewind to the durable snapshot.
        r_term, r_vote, r_len = storage_plane.recover(
            cfg, rs, inp.torn_drop,
            s.dur_len, s.dur_term, s.dur_vote,
            s.term, s.voted_for, s.log_len,
        )
        s = s._replace(term=r_term, voted_for=r_vote, log_len=r_len)
    if cfg.pre_vote or rdl or cfg.reconfig:
        # A restarted node remembers no leader contact: "quiet" immediately
        # (pre-votes grantable, and -- under the lease or log-carried-config
        # denial gates -- real votes too: a restarted voter holds no
        # obligation toward a leader it no longer remembers).
        s = s._replace(
            heard_clock=jnp.where(
                rs, s.clock - cfg.election_min_ticks, s.heard_clock
            )
        )
    if xfr:
        # A pending transfer is volatile leader state: lost with the process.
        s = s._replace(xfer_to=jnp.where(rs, NIL, s.xfer_to))
    if rdx:
        # Pending reads die with the process too (the client retries).
        s = s._replace(
            read_idx=jnp.where(rs, 0, s.read_idx),
            read_tick=jnp.where(rs, 0, s.read_tick),
            read_acks=jnp.where(rs[:, None], zw, s.read_acks),
        )
        if rdl:
            # The staleness anchor dies with the slot it anchors.
            s = s._replace(read_fr=jnp.where(rs, 0, s.read_fr))
    mb = s.mailbox
    base, bterm, bchk = s.log_base, s.base_term, s.base_chk
    if rcf:
        # Snapshot config context (compaction x reconfig; constant full-row /
        # zero legs otherwise -- carried untouched when comp is off).
        bmold, bpend, bepoch = s.base_mold, s.base_pend, s.base_epoch

    # Reconfiguration plane (cfg.reconfig): log-carried, PER-NODE
    # configuration masking. member_old/member_new/cfg_pend are each node's
    # DERIVED view of its own log prefix (ClusterState docstring; the
    # end-of-tick block recomputes them via models/cfglog.py), so every
    # quorum test below masks by the TESTING NODE's own rows -- dual
    # (majorities of BOTH configurations) while that node's prefix holds an
    # uncompleted joint entry. Quorum tests read the TICK-START derivation;
    # entries appended this tick govern the next (apply-on-append at tick
    # granularity, the same one-tick rule every phase transition follows).
    if rcf:
        m_old, m_new = s.member_old, s.member_new  # [N, W]
        joint = s.cfg_pend > 0  # [N]
        maj_old = bitplane.count(m_old, axis=1) // 2 + 1  # [N] int32
        maj_new = bitplane.count(m_new, axis=1) // 2 + 1
        # Node i's own-membership bit: is i a voter of ITS OWN config union?
        # A node whose log carries its removal quiesces (never campaigns);
        # one whose log MISSES the removal still thinks it votes -- the
        # removed-server disruption the 4.2.3 denial below defends against.
        member_b = jnp.any(((m_old | m_new) & eye_p) != 0, axis=1)  # [N]

        def packed_quorum(rows):
            """[N, W] packed grant rows (node i's banked grants) -> [N] bool
            quorum under node i's OWN configuration(s)."""
            ok = bitplane.count(rows & m_old, axis=1) >= maj_old
            return ok & (
                ~joint | (bitplane.count(rows & m_new, axis=1) >= maj_new)
            )
    else:

        def packed_quorum(rows):
            return bitplane.count(rows, axis=1) >= cfg.quorum

    # ---- phase 0: delivery -------------------------------------------------------
    # The fault mask is the TPU-native form of the reference's silently-dropped HTTP
    # call (client.clj:38-40): a zeroed entry in the delivery mask. A down node is
    # silent in both directions: it receives nothing, and anything it had in flight
    # dies with it (the crashed process's sockets). Mailbox slots hold messages sent
    # last tick, so a node that just restarted must also not see them -- they were
    # addressed to a dead process (alive now & alive at send time = alive & ~restarted).
    # The input mask is indexed by physical directed edge [to, from] and arrives
    # BIT-PACKED over the source axis (StepInputs docstring): the response-side
    # chain ([receiver, responder] = [to, from], same orientation) runs on the
    # packed words -- per-source gates AND as packed rows, per-receiver gates as
    # row selects -- and unpacks once; request fields are stored
    # [sender, receiver] (= [from, to], Mailbox docstring), so the request
    # orientation unpacks the mask and transposes in bool space.
    dst_up = inp.alive & ~inp.restarted
    resp_del_p = jnp.where(
        dst_up[:, None],
        inp.deliver_mask & ~eye_p & bitplane.pack(inp.alive)[None, :],
        zw,
    )  # [N, W]; canonical (ANDed with the canonical input mask)
    deliver_resp = bitplane.unpack(resp_del_p, n, axis=1)
    deliver_req = (
        bitplane.unpack(inp.deliver_mask, n, axis=1).T
        & ~eye
        & inp.alive[:, None]
        & dst_up[None, :]
    )
    req_in = deliver_req & (mb.req_type != 0)[:, None]  # [sender, receiver]
    resp_in = deliver_resp & (mb.resp_kind != 0)  # [receiver, responder]

    # Heard-a-leader denial window (thesis 4.2.3), shared by the log-carried
    # membership defense (rcf: a removed server whose log misses its removal
    # still campaigns -- voters that heard a current leader recently must
    # neither adopt its inflated term nor grant it votes) and the lease vote
    # denial (rdl). Judged on the voter's LOCAL clock against the TICK-START
    # heard_clock -- this tick's AppendEntries land in phase 3, after votes
    # -- which only SHORTENS the window by one tick (the lease validator's
    # +4 slack covers it; docs/PROTOCOL.md). The disruptive-RequestVote
    # override (req_disrupt, set on transfer-triggered elections) bypasses
    # the denial: the leader being replaced sanctioned that election, so
    # denying it would deadlock every TimeoutNow transfer.
    if rcf or rdl:
        heard_recent = (s.clock + inp.skew) - s.heard_clock < cfg.election_min_ticks
        if xfr:
            rv_denied = heard_recent[None, :] & ~(mb.req_disrupt != 0)[:, None]
        else:
            rv_denied = jnp.broadcast_to(heard_recent[None, :], (n, n))

    # ---- phase 1: term adoption --------------------------------------------------
    # Spec: any RPC (request or response) with term T > currentTerm -> set
    # currentTerm = T, convert to follower. The reference does this for responses
    # (core.clj:129-130, 144-145) but not vote requests (bug 2.3.2). A PreVote
    # request's term is PROSPECTIVE (thesis 9.6) -- it must never be adopted.
    if cfg.pre_vote:
        term_req = req_in & (mb.req_type != REQ_PREVOTE)[:, None]
    else:
        term_req = req_in
    if rcf:
        # 4.2.3 in full: a denied RequestVote is not PROCESSED -- its term is
        # not adopted either, so a removed server's inflated term cannot
        # depose a live leader through its own voters (the disruption
        # defense; under rdl alone the PR-11 grant-only denial is kept
        # bit-for-bit -- adoption stays legal there).
        term_req = term_req & ~((mb.req_type == REQ_VOTE)[:, None] & rv_denied)
    in_term = jnp.maximum(
        jnp.max(jnp.where(term_req, mb.req_term[:, None], 0), axis=0),
        jnp.max(jnp.where(resp_in, mb.resp_term[None, :], 0), axis=1),
    )  # [N]
    saw_higher = in_term > s.term
    term = jnp.maximum(s.term, in_term)
    role = jnp.where(saw_higher, FOLLOWER, s.role)
    voted_for = jnp.where(saw_higher, NIL, s.voted_for)
    leader_id = jnp.where(saw_higher, NIL, s.leader_id)
    votes = jnp.where(saw_higher[:, None], zw, s.votes)

    if comp:
        my_last_idx = s.log_len
        my_last_term = log_ops.term_at_r(s.log_term, base, bterm, s.log_len)
    else:
        my_last_idx, my_last_term = log_ops.last_index_term(s.log_term, s.log_len)

    # ---- phase 2: RequestVote requests (request-vote-handler, core.clj:91-103) ----
    is_rv = req_in & (mb.req_type == REQ_VOTE)[:, None]  # [candidate, voter]
    cur_rv = is_rv & (mb.req_term[:, None] == term[None, :])  # stale terms are denied
    # Spec 5.4.1 up-to-date check (the reference's compare-prev? log.clj:55-59 compares
    # against the commit index and whole entry maps -- bugs 2.3.3/2.3.4).
    up_to_date = (mb.req_last_term[:, None] > my_last_term[None, :]) | (
        (mb.req_last_term[:, None] == my_last_term[None, :])
        & (mb.req_last_index[:, None] >= my_last_idx[None, :])
    )
    can_grant = cur_rv & up_to_date
    if rcf or rdl:
        # Heard-a-leader vote denial (thesis 4.2.3; the shared window above):
        # under the lease gate this is the rule 6.4.1 leans on -- a leader
        # whose heartbeats a quorum acked L ticks ago KNOWS no election can
        # complete for election_min_ticks/2 more global ticks (local clocks
        # advance at most 2/tick under skew; the config validator pins the
        # lease term under that bound). Under the log-carried membership
        # plane it is the removed-server disruption defense. The transfer
        # override (rv_denied folds in req_disrupt) lets TimeoutNow
        # elections through either way.
        can_grant = can_grant & ~rv_denied
    # At most one grant per node per tick: the lowest eligible candidate id wins the
    # race (the reference serializes naturally, one message per wait iteration).
    lowest = jnp.min(jnp.where(can_grant, snd_ids, n), axis=0)  # [N], n = none
    grant = jnp.where(
        (voted_for != NIL)[None, :],
        can_grant & (snd_ids == voted_for[None, :]),  # idempotent re-grant
        can_grant & (snd_ids == lowest[None, :]),
    )
    granted_any = jnp.any(grant, axis=0)
    voted_for = jnp.where((voted_for == NIL) & granted_any, lowest, voted_for)
    # Every delivered RV gets a response carrying our (possibly just-adopted) term;
    # [candidate, voter] is already the response orientation [receiver, responder].
    # The grant itself is per RESPONDER: at most one candidate per tick (Mailbox),
    # and a grant always targets the post-update voted_for (re-grants re-name it,
    # fresh grants just set it) -- no reduction over the grant plane needed. Safe
    # to read here: phase 7 cannot rebind voted_for for a granter this tick (a
    # grant resets the election deadline to clock + draw > clock, so the granter
    # cannot also expire).
    vr_out = is_rv
    grant_to = jnp.where(granted_any, voted_for, NIL).astype(node_dtype(cfg))  # [N]

    # ---- phase 3: AppendEntries requests (append-entries-handler, core.clj:105-123) --
    is_ae = req_in & (mb.req_type == REQ_APPEND)[:, None]  # [leader, follower]
    cur_ae = is_ae & (mb.req_term[:, None] == term[None, :])
    # Election safety gives at most one leader per term, so at most one current-term AE
    # sender exists; pick the lowest id defensively (ties indicate a safety violation,
    # which phase 9 flags).
    ae_src = jnp.min(jnp.where(cur_ae, snd_ids, n), axis=0)  # [N]
    has_ae = ae_src < n
    sel = cur_ae & (snd_ids == ae_src[None, :])  # one-hot [sender, receiver]

    # Reconstruct the per-edge AE header from the selected sender's broadcast record
    # plus this edge's window offset j (Mailbox docstring). When no sender is
    # selected everything is zeroed/garbage but gated by has_ae/ae_ok downstream.
    j_in = jnp.sum(jnp.where(sel, mb.req_off, 0), axis=0).astype(jnp.int32)  # [N] in 0..E
    sel_idx = jnp.minimum(ae_src, n - 1)
    # InstallSnapshot analogue (compaction only): offset sentinel -1 means "install
    # my compaction base instead of entries" -- sent when this peer's next_index
    # fell below the leader's log_base (phase 8), the array form of Raft fig. 13.
    # The reference can never need this (its log is unbounded, core.clj:59-67).
    snap = (has_ae & (j_in < 0)) if comp else jnp.zeros((n,), bool)
    ae_norm = has_ae & ~snap
    j_nn = jnp.clip(j_in, 0, e)  # snap's -1 routed to 0; gated by ae_norm downstream
    ws_in = mb.ent_start[sel_idx]  # [N]
    w_term = mb.ent_term[sel_idx]  # [N, E]
    w_val = mb.ent_val[sel_idx]
    w_tick = mb.ent_tick[sel_idx] if track else None
    w_cfg = mb.ent_cfg[sel_idx] if rcf else None
    prev_i = jnp.where(ae_norm, ws_in + j_nn, 0)
    lcommit = jnp.where(ae_norm, mb.req_commit[sel_idx], 0)
    n_ent = jnp.where(ae_norm, jnp.clip(mb.ent_count[sel_idx] - j_nn, 0, e), 0)
    # prev term: the window slot just before this receiver's entries (j-1), or the
    # sender's ent_prev_term for j == 0 -- ext[k] = term of 1-based entry ws+k.
    ext = jnp.concatenate([mb.ent_prev_term[sel_idx][:, None], w_term], axis=1)
    prev_t = jnp.take_along_axis(ext, j_nn[:, None], axis=1)[:, 0]  # [N]
    # This receiver's entries start at window slot j (slot k holds entry ws+k+1).
    off = jnp.clip(j_nn, 0, e - 1)  # j = E only when n_ent = 0 (fully masked)
    ent_term_in = log_ops.window(w_term, off, e)  # [N, E]
    ent_val_in = log_ops.window(w_val, off, e)
    ent_tick_in = log_ops.window(w_tick, off, e) if track else None
    ent_cfg_in = log_ops.window(w_cfg, off, e) if rcf else None

    # A valid AE from the current term makes candidates (and pre-candidates)
    # step down and identifies the leader (core.clj:121-123, minus the :follwer
    # typo, bug 2.3.1).
    if cfg.pre_vote:
        stepdown = (role == CANDIDATE) | (role == PRECANDIDATE)
    else:
        stepdown = role == CANDIDATE
    role = jnp.where(has_ae & stepdown, FOLLOWER, role)
    leader_id = jnp.where(has_ae, ae_src, leader_id)

    # Consistency check (spec 5.3; reference compare-prev? has bugs 2.3.4/2.3.5).
    if comp:
        # prev below the local base is committed-and-compacted: it matches by
        # leader completeness (a current-term leader's log holds every committed
        # entry); at prev == base, term_at_r yields base_term -- the snapshot
        # boundary check.
        prev_stored_term = log_ops.term_at_r(s.log_term, base, bterm, prev_i)
        consistent = (
            (prev_i == 0)
            | (prev_i < base)
            | ((prev_i <= s.log_len) & (prev_stored_term == prev_t))
        )
    else:
        prev_stored_term = log_ops.term_at(s.log_term, prev_i)
        consistent = (prev_i == 0) | (
            (prev_i <= s.log_len) & (prev_stored_term == prev_t)
        )
    ae_ok = ae_norm & consistent

    # Conflict scan over the shipped window: first mismatching entry truncates the rest
    # of the log; matching prefixes are never truncated (spec 5.3 "delete the existing
    # entry and all that follow it").
    ks = jnp.arange(e, dtype=jnp.int32)
    gidx0 = prev_i[:, None] + ks[None, :]  # [N, E] 0-based entry indices
    if comp:
        # Skip entries the ring already compacted (abs index <= base) and accept
        # only what it can hold (entries past base + CAP would evict live,
        # un-compacted slots; the partial ack makes the leader retry the rest
        # after this node's own commit+compaction frees room).
        lo = jnp.clip(base - prev_i, 0, e)  # [N]
        n_acc = jnp.minimum(n_ent, jnp.maximum(base + cap - prev_i, 0))
        in_ent = (ks[None, :] >= lo[:, None]) & (ks[None, :] < n_acc[:, None])
        stored = log_ops.window_r(s.log_term, prev_i, e)  # [N, E]
        appended_len = prev_i + n_acc
    else:
        n_acc = n_ent
        in_ent = ks[None, :] < n_ent[:, None]
        stored = log_ops.window(s.log_term, prev_i, e)  # [N, E]
        appended_len = jnp.minimum(prev_i + n_ent, cap)
    exists = gidx0 < s.log_len[:, None]
    mismatch = in_ent & exists & (stored != ent_term_in)
    any_mismatch = jnp.any(mismatch, axis=1)
    new_len = jnp.where(
        any_mismatch, appended_len, jnp.maximum(s.log_len, appended_len)
    )
    log_len = jnp.where(ae_ok, new_len, s.log_len)
    if dur:
        # Truncation makes the removed suffix non-durable AS LOG CONTENT: the
        # watermark clamps down with the log (the bytes may sit on disk, but
        # the durable-log contract is about the entries the recovery would
        # reconstruct, and those are gone). Appends do NOT advance it -- only
        # a completed flush does (phase 7.5).
        dur_mid = jnp.minimum(s.dur_len, log_len)
    wmask = ae_ok[:, None] & in_ent
    if comp:
        log_term_arr = log_ops.write_window_r(s.log_term, prev_i, ent_term_in, wmask)
        log_val_arr = log_ops.write_window_r(s.log_val, prev_i, ent_val_in, wmask)
    else:
        log_term_arr = log_ops.write_window(s.log_term, prev_i, ent_term_in, wmask)
        log_val_arr = log_ops.write_window(s.log_val, prev_i, ent_val_in, wmask)
    # The offer-stamp plane replicates with the entries it tags (same masks, so
    # it can never diverge from the value plane's slot occupancy).
    if track:
        wwr = log_ops.write_window_r if comp else log_ops.write_window
        log_tick_arr = wwr(s.log_tick, prev_i, ent_tick_in, wmask)
    else:
        log_tick_arr = s.log_tick  # untouched: loop-invariant carry leg
    # The config-entry plane replicates under the SAME masks: non-config
    # entries ship 0, so an accepted window scrubs any stale config command
    # off the slots it overwrites (the rollback hazard the derivation
    # depends on -- ClusterState.log_cfg docstring).
    if rcf:
        wwc = log_ops.write_window_r if comp else log_ops.write_window
        log_cfg_arr = wwc(s.log_cfg, prev_i, ent_cfg_in, wmask)
    else:
        log_cfg_arr = s.log_cfg  # untouched: loop-invariant carry leg

    # Follower commit: min(leaderCommit, index of last new entry), monotonic
    # (the reference's apply-entries! commits everything unconditionally, bug 2.3.6).
    # The floor at 0 is a no-op on the ae_ok path (prev_i/n_acc are
    # non-negative for a real AE) but bounds the masked-garbage lane so the
    # int8/int16 a_match narrowing below is provably in range (Pass E).
    last_new = jnp.maximum(jnp.minimum(prev_i + n_acc, log_len), 0)
    commit = jnp.where(
        ae_ok,
        jnp.maximum(s.commit_index, jnp.minimum(lcommit, last_new)),
        s.commit_index,
    )

    # Snapshot install (compaction only). L <= base needs nothing (we already hold
    # that prefix -- plain ack); otherwise, if our log extends through L with the
    # snapshot's term, retain the suffix (Raft fig. 13 rule 6), else discard the
    # whole log. Either way our compaction state becomes the leader's and commit
    # advances to at least L (everything below a snapshot is committed).
    if comp:
        L = jnp.where(snap, mb.req_base[sel_idx], 0)
        Lt = mb.req_base_term[sel_idx]
        Lchk = mb.req_base_chk[sel_idx]
        apply_snap = snap & (L > base)
        keep = (
            apply_snap
            & (L <= s.log_len)
            & (log_ops.term_at_r(s.log_term, base, bterm, L) == Lt)
        )
        wipe = apply_snap & ~keep
        bterm = jnp.where(apply_snap, Lt, bterm)
        bchk = jnp.where(apply_snap, Lchk, bchk)
        base = jnp.where(apply_snap, L, base)
        log_len = jnp.where(wipe, L, log_len)
        commit = jnp.where(apply_snap, jnp.maximum(commit, L), commit)
        if rcf:
            # The snapshot carries its configuration context: the sender's
            # C_old/pending-toggle/entry-count at L, so the receiver's
            # derivation stays exact over config entries it never saw.
            bmold = jnp.where(
                apply_snap[:, None], mb.req_base_mold[sel_idx], bmold
            )
            bpend = jnp.where(apply_snap, mb.req_base_pend[sel_idx], bpend)
            bepoch = jnp.where(apply_snap, mb.req_base_epoch[sel_idx], bepoch)
    else:
        apply_snap = jnp.zeros((n,), bool)

    # Respond to every delivered AE; success only for the selected, consistent one
    # (snapshot installs always ack, with match = the snapshot index). A NACK
    # carries the responder's log length as a catch-up hint: the leader jumps
    # next_index straight to hint+1 instead of decrementing once per heartbeat --
    # the standard conflict-index optimization (Raft paper section 5.3 "the
    # protocol can be optimized"). Without it a freshly elected leader walks next
    # down 1 per nack while client traffic grows its log ~1 per tick, and under
    # recurring crash churn no current-term entry ever reaches quorum (measured
    # livelock: commit frozen for thousands of ticks).
    # [leader, follower] is already the response orientation [receiver, responder];
    # the payload is per responder (at most one success target -- Mailbox).
    ar_out = is_ae
    if comp:
        a_ok = ae_ok | snap
        out_a_match = jnp.where(snap, L, jnp.where(ae_ok, last_new, 0))
    else:
        a_ok = ae_ok
        out_a_match = jnp.where(ae_ok, last_new, 0)
    idt = s.next_index.dtype
    out_a_ok_to = jnp.where(a_ok, ae_src, NIL).astype(node_dtype(cfg))  # NIL = no success
    out_a_match = out_a_match.astype(idt)  # bounded by the responder's log length
    out_a_hint = log_len.astype(idt)  # post-append, pre-injection (phase 6 rebinds)

    # ---- phase 3.5: PreVote requests (thesis 9.6; cfg.pre_vote) ------------------
    # Grant iff the probe's prospective term is not behind us, the probing log is
    # up to date (the phase-2 check -- probes fill the same req_last_* header),
    # and we are QUIET: not a leader ourselves and no valid AppendEntries
    # accepted within the minimum election timeout (including this tick's).
    # Grants are non-binding: no votedFor, no term change, no timer reset.
    if cfg.pre_vote or rdl or rcf:
        # heard_clock maintenance serves three consumers: the pre-vote quiet
        # rule (below), the lease vote denial, and the log-carried-config
        # removed-server denial (both phase 2) -- any gate keeps the leg
        # live.
        clock_pv = s.clock + inp.skew  # phase 7's clock; duplicated, CSE'd
        heard = jnp.where(has_ae, clock_pv, s.heard_clock)  # [N]
    else:
        heard = s.heard_clock
    if cfg.pre_vote:
        is_pv = req_in & (mb.req_type == REQ_PREVOTE)[:, None]  # [cand, voter]
        quiet = (clock_pv - heard >= cfg.election_min_ticks) & (role != LEADER)
        pv_grant = (
            is_pv & (mb.req_term[:, None] >= term[None, :]) & up_to_date & quiet[None, :]
        )
        pv_out = is_pv

    # ---- phase 3.7: TimeoutNow receipt (thesis 3.10; cfg.leader_transfer) --------
    # The transfer target starts a REAL election IMMEDIATELY: no timer, no
    # pre-vote probe (the thesis's explicit bypass -- the target is known
    # caught up, and the transferring leader's lease would make every voter
    # deny a probe). Gated on the request carrying the receiver's CURRENT
    # term, so a stale TimeoutNow from a deposed leader (or one that already
    # succeeded: the new leader's term moved past it) is inert. The election
    # itself fires in phase 7 alongside timer-driven starts.
    if xfr:
        is_tn = req_in & (mb.req_type == REQ_TIMEOUT_NOW)[:, None]  # [sender, recv]
        tn_cur = (
            is_tn
            & (mb.xfer_tgt[:, None] == ids[None, :])
            & (mb.req_term[:, None] == term[None, :])
        )
        xfer_elect = jnp.any(tn_cur, axis=0) & inp.alive & (role != LEADER)
        if rcf:
            xfer_elect = xfer_elect & member_b  # non-voters never campaign
        if not cfg.xfer_election:
            # TEST-ONLY mutant (cfg.xfer_election False): transfer as a coup.
            # The target assumes leadership DIRECTLY -- no vote round, no
            # up-to-date check -- so a behind target replicates its short log
            # over committed entries (the violation the hunt must re-find).
            coup = xfer_elect
            term = term + coup
            role = jnp.where(coup, LEADER, role)
            leader_id = jnp.where(coup, ids, leader_id)
            xfer_elect = jnp.zeros((n,), bool)
        else:
            coup = jnp.zeros((n,), bool)

    # ---- phase 4: responses ------------------------------------------------------
    # Vote tally (vote-response-handler core.clj:125-139; dedup via bitmap mirrors the
    # reference's set, core.clj:133-134). Granted = this responder's one grant
    # (v_to) names me (Mailbox response decode).
    vresp = resp_in & (mb.resp_kind == RESP_VOTE)
    new_votes = (
        vresp
        & (mb.v_to[None, :] == ids[:, None])
        & (mb.resp_term[None, :] == term[:, None])
        & (role == CANDIDATE)[:, None]
    )
    votes = votes | bitplane.pack(new_votes, axis=1)
    # Quorum test on the packed plane: word popcount instead of an [N, N]
    # bool-plane sum (the bitplane module's reason to exist). With the
    # reconfiguration plane live the popcount is configuration-masked (and
    # DUAL during a joint phase) -- packed_quorum above.
    # A down candidate cannot assume leadership from votes banked before it crashed.
    win = (role == CANDIDATE) & packed_quorum(votes) & inp.alive
    if rcf:
        # A node voted out of both configurations cannot assume leadership
        # from votes banked before its removal.
        win = win & member_b
    if xfr and not cfg.xfer_election:
        # Mutant coup targets take the fresh-leader bookkeeping path too.
        win = win | coup
    role = jnp.where(win, LEADER, role)
    leader_id = jnp.where(win, ids, leader_id)
    # Fresh leader bookkeeping (leader-state core.clj:40-42): nextIndex = last log
    # index + 1, matchIndex = 0. Indices ride int16 when bounded by log_capacity,
    # int32 under compaction (absolute indices; types.index_dtype).
    len_i = log_len.astype(s.next_index.dtype)
    next_index = jnp.where(win[:, None], (len_i + 1)[:, None], s.next_index)
    match_index = jnp.where(win[:, None], 0, s.match_index)

    # ---- phase 4.5: PreVote responses + promotion (thesis 9.6; cfg.pre_vote) -----
    # A pre-candidate banks grant bits in the votes bitmap (it is never a real
    # candidate at the same time, so the bitmap is free); a pre-quorum promotes
    # it to a REAL candidate: only now does the term bump, the self-vote land,
    # and a real RequestVote broadcast go out (phase 8 via start_election).
    if cfg.pre_vote:
        # The grant bit rides the packed pv_grant plane (Mailbox docstring):
        # AND the packed response-validity rows against it -- word algebra, no
        # per-edge byte plane.
        pvresp = resp_in & (mb.resp_kind == RESP_PREVOTE)
        new_pv = jnp.where(
            (role == PRECANDIDATE)[:, None],
            bitplane.pack(pvresp, axis=1) & mb.pv_grant,
            zw,
        )
        votes = votes | new_pv
        pre_win = (role == PRECANDIDATE) & packed_quorum(votes) & inp.alive
        if rcf:
            pre_win = pre_win & member_b
        term = term + pre_win
        role = jnp.where(pre_win, CANDIDATE, role)
        voted_for = jnp.where(pre_win, ids, voted_for)
        votes = jnp.where(pre_win[:, None], eye_p, votes)
    else:
        pre_win = jnp.zeros((n,), bool)

    # Append responses (append-response-handler core.clj:141-149), leaders only, same
    # term. Success: match = acked index, next = match+1 (the reference sets next =
    # log-index, bug 2.3.10); failure: decrement next-index and retry (core.clj:146).
    aresp = (
        resp_in
        & (mb.resp_kind == RESP_APPEND)
        & (role == LEADER)[:, None]
        & (mb.resp_term[None, :] == term[:, None])
    )
    ok_mine = mb.a_ok_to[None, :] == ids[:, None]  # responder's one success names me
    a_succ = aresp & ok_mine
    a_fail = aresp & ~ok_mine
    am = mb.a_match[None, :]  # already index_dtype (bounded by log length)
    ah = mb.a_hint[None, :]
    match_index = jnp.where(a_succ, jnp.maximum(match_index, am), match_index)
    next_index = jnp.where(a_succ, jnp.maximum(next_index, am + 1), next_index)
    # Failure: back off to min(next-1, hint+1) -- the nack hint is the responder's
    # log length (phase 3), so a far-behind or just-elected leader's probe
    # converges in one round trip instead of one slot per nack.
    next_index = jnp.where(
        a_fail, jnp.maximum(jnp.minimum(next_index - 1, ah + 1), 1), next_index
    )
    # Responsiveness ages for the shared-window filter (phase 8): everyone ages one
    # tick (saturating); any AE response (success or failure) proves the peer is up
    # and zeroes its age, and a fresh win grace-zeroes every peer so the first
    # window covers all of them.
    ack_age = jnp.minimum(s.ack_age + 1, cfg.ack_age_sat)
    ack_age = jnp.where(win[:, None] | aresp, 0, ack_age)

    # ---- phase 5: leader commit advancement (absent in reference, bug 2.3.8) ------
    is_leader = role == LEADER
    if dur and cfg.durable_acks:
        # Section-3.8 gate, leader self-match side: the leader's own log
        # counts toward commit only up to ITS durable watermark -- it is a
        # replica like any other, and commit means "on stable storage at a
        # quorum". Uses the pre-flush watermark (this tick's flush lands in
        # phase 7.5): one tick of lag, never a lie.
        match_with_self = jnp.where(eye, dur_mid[:, None], match_index)
    else:
        match_with_self = jnp.where(eye, log_len[:, None], match_index)  # [N, N]
    if rcf:
        # Configuration-masked quorum match under EACH LEADER's OWN derived
        # configuration: the largest replicated index v such that a majority
        # of that leader's member rows have match >= v. The quorum-th order
        # statistic of a multiset is an element of it, so candidates range
        # over the members' own match values (count form -- the member
        # majority is traced data, so the static sort-and-index form cannot
        # apply). While the leader's prefix is joint: the min over both its
        # configs (an index commits only when replicated to majorities of
        # BOTH).
        mws = match_with_self
        ge = mws[:, None, :] >= mws[:, :, None]  # [i, j(candidate), k(counted)]

        def masked_qmatch(mask_b, maj):
            # mask_b [N(i), N(k)]: node i's member view; maj [N(i)].
            cnt = jnp.sum(ge & mask_b[:, None, :], axis=2)  # [N, N]
            ok = (cnt >= maj[:, None]) & mask_b
            return jnp.max(jnp.where(ok, mws, 0), axis=1).astype(jnp.int32)

        mem_old_b = bitplane.unpack(m_old, n, axis=1)  # [N, N] bool
        mem_new_b = bitplane.unpack(m_new, n, axis=1)
        qm_old = masked_qmatch(mem_old_b, maj_old)
        quorum_match = jnp.where(
            joint, jnp.minimum(qm_old, masked_qmatch(mem_new_b, maj_new)), qm_old
        )
    else:
        sorted_desc = -jnp.sort(-match_with_self, axis=1)
        quorum_match = sorted_desc[:, cfg.quorum - 1]  # quorum-th largest match index
    # Spec 5.4.2: only commit entries from the current term by counting replicas.
    if comp:
        quorum_term = log_ops.term_at_r(log_term_arr, base, bterm, quorum_match)
    else:
        quorum_term = log_ops.term_at(log_term_arr, quorum_match)
    commit = jnp.where(
        is_leader & inp.alive & (quorum_match > commit) & (quorum_term == term),
        quorum_match,
        commit,
    )

    # ---- phase 5.2: reconfiguration transitions moved INTO the log --------------
    # (Log-carried membership: there is no admin transition block anymore.
    # Joint entry/exit are LOG APPENDS -- phase 6 originates them on the
    # leader, phase 3 replicates them -- and each node's effective
    # configuration is re-derived from its own prefix at end of tick
    # (models/cfglog.py), which is also where removed-leader stepdown and
    # the truncation rollback live.)
    # Leadership-transfer bookkeeping (cfg.leader_transfer): abort a pending
    # transfer whose holder lost leadership or whose target went unresponsive
    # (ack_age horizon -- a dead target must not freeze the write path), then
    # accept a fresh transfer command at the lowest-id live leader. The
    # TimeoutNow itself fires from phase 8, re-fired each heartbeat while the
    # target stays caught up (a dropped fire retries).
    if xfr:
        tcl = jnp.clip(s.xfer_to, 0, n - 1)
        age_t = jnp.take_along_axis(ack_age, tcl[:, None], axis=1)[:, 0]
        keep_x = is_leader & (s.xfer_to != NIL) & (age_t <= cfg.ack_timeout_ticks)
        xfer_to = jnp.where(keep_x, s.xfer_to, NIL)
        t_x = inp.transfer_cmd
        ld_ok_x = is_leader & inp.alive
        if rcf:
            ld_ok_x = ld_ok_x & member_b
            # The target must be a voter of the LEADER's own target config
            # (per-node derived rows; tick-start like every config read).
            t_voter = jnp.any((m_new & bitplane.one_bit(t_x, n)[None, :]) != 0, axis=1)
        else:
            t_voter = jnp.bool_(True)
        ldx = jnp.min(jnp.where(ld_ok_x, ids, n))
        can_x = (
            (t_x != NIL) & t_voter & (ids == ldx) & ld_ok_x
            & (t_x != ids) & (xfer_to == NIL)
        )
        xfer_to = jnp.where(can_x, t_x, xfer_to)
        xfer_pend = xfer_to != NIL
    # ReadIndex lifecycle (cfg.read_index): bank this tick's AppendEntries
    # responses into the pending read's confirmation set (responses received
    # now were sent at or after the capture tick, so each proves the
    # responder was in the leader's term no earlier than capture -- the
    # staleness argument docs/PROTOCOL.md spells out), serve once a
    # configuration-aware majority confirms, then capture a fresh offer into
    # a free slot.
    if rdx:
        pend0 = s.read_idx > 0  # pending at tick start
        keep_r = is_leader & pend0  # role loss / term adoption cancels
        read_acks = jnp.where(
            keep_r[:, None], s.read_acks | bitplane.pack(aresp, axis=1), zw
        )
        if cfg.read_confirm:
            serve = keep_r & inp.alive & packed_quorum(read_acks | eye_p)
        else:
            # TEST-ONLY mutant (cfg.read_confirm False): serve with NO
            # leadership confirmation -- a deposed leader in a minority
            # partition serves reads from its stale commit state (the
            # below-the-committed-frontier read the checker must reject).
            serve = keep_r & inp.alive
        if rdl:
            # Lease fast path (thesis 6.4.1): a leader holding a fresh
            # configuration quorum of AppendEntries acks -- every member
            # acked within the lease window on the GLOBAL tick clock (the
            # ack_age plane ages 1/tick regardless of skew; the leader's
            # own skewable clock is never consulted) -- serves immediately,
            # no confirmation round. The TEST-ONLY lease_skew_safe mutant
            # widens the window to election_min_ticks + 2: the no-skew
            # bound -- on 1:1 clocks a deposing election needs a full
            # election_min of denial expiry plus the vote+commit round
            # trips, and a capture must precede its serve by a tick, so the
            # widened lease still cannot produce a stale serve; under clock
            # skew the denial window halves in global time and it can.
            lease_w = (
                cfg.read_lease_ticks
                if cfg.lease_skew_safe
                else cfg.election_min_ticks + 2
            )
            fresh_p = bitplane.pack(ack_age <= lease_w, axis=1)  # [N, W]
            lease_ok = packed_quorum(fresh_p | eye_p)
            if xfr:
                # Transfer handoff covers the read path: once a transfer
                # pends, the lease fast path stops -- the target's override
                # election (req_disrupt) bypasses the 4.2.3 denial the lease
                # bound leans on, so only reads served BEFORE the handoff
                # may lean on it (docs/PROTOCOL.md staleness argument).
                lease_ok = lease_ok & ~xfer_pend
            serve = serve | (keep_r & inp.alive & lease_ok)
        lat_r = jnp.maximum(s.now + 1 - s.read_tick, 1)  # [N]
        reads_served = jnp.sum(serve).astype(jnp.int32)
        read_lat_sum = jnp.sum(jnp.where(serve, lat_r, 0)).astype(jnp.int32)
        bin_r = log_ops.log2_bin(lat_r, LAT_HIST_BINS)
        oh_r = (
            jnp.arange(LAT_HIST_BINS)[None, :] == bin_r[:, None]
        ) & serve[:, None]
        read_hist = jnp.sum(oh_r, axis=0).astype(jnp.int32)
        # Capture: gated on the leader having committed a current-term entry
        # (thesis 6.4 -- a fresh leader's commit may trail the global
        # committed frontier until its own no-op/first entry commits, and a
        # read captured before that would legally miss committed writes).
        # One offer per cluster per tick: the lowest-id eligible leader.
        if comp:
            cur_committed = log_ops.term_at_r(log_term_arr, base, bterm, commit) == term
        else:
            cur_committed = log_ops.term_at(log_term_arr, commit) == term
        can_cap = (inp.read_cmd != NIL) & is_leader & inp.alive & ~pend0
        if cfg.read_confirm:
            can_cap = can_cap & cur_committed
        if xfr:
            can_cap = can_cap & ~xfer_pend  # transferring leaders stop serving
        low_cap = jnp.min(jnp.where(can_cap, ids, n))
        cap_r = can_cap & (ids == low_cap)
        cleared = serve | (pend0 & ~keep_r)
        read_idx = jnp.where(cap_r, commit + 1, jnp.where(cleared, 0, s.read_idx))
        read_tick = jnp.where(cap_r, s.now + 1, jnp.where(cleared, 0, s.read_tick))
        read_acks = jnp.where((cap_r | serve)[:, None], zw, read_acks)
        if rdl:
            # Staleness anchor: bank the committed frontier (lat_frontier
            # semantics, incl. this tick's phase-5 advance) at capture; a
            # SERVE whose captured index sits below its banked frontier
            # missed committed writes -- the checker's read_linearizability
            # property as a device invariant, so the hunt's fitness sees
            # lease violations. Exact, not conservative: a legitimate
            # (confirmed or leased) leader's capture covers the frontier by
            # the current-term-commit gate, so the real kernel never flags.
            fr_now = jnp.maximum(s.lat_frontier, jnp.max(commit))
            read_fr = jnp.where(
                cap_r, fr_now, jnp.where(cleared, 0, s.read_fr)
            )
            if cfg.check_invariants:
                viol_read_stale = jnp.any(serve & (s.read_idx - 1 < s.read_fr))
            else:
                viol_read_stale = np.zeros((), np.bool_)
        else:
            viol_read_stale = np.zeros((), np.bool_)
    else:
        # Constants, not jnp.zeros: a zeros op would land in the lowered
        # step program and break the zero-cost-when-off golden (byte-
        # identical op histograms with every gate off).
        reads_served = np.int32(0)
        read_lat_sum = np.int32(0)
        read_hist = np.zeros((LAT_HIST_BINS,), np.int32)
        viol_read_stale = np.zeros((), np.bool_)

    # ---- offer->commit latency (client workloads only) ---------------------------
    # Each client entry's offer stamp rides the log_tick plane (phase 6 writes
    # it at injection; AE replication carries it via Mailbox.ent_tick), so the
    # live leader's commit advancement this tick contributes (now - offer_tick)
    # per newly committed client entry -- the measurement the reference's
    # commit watch was meant to feed (log.clj:83-87, never fired, bug 2.3.9).
    # VALUES are never read here: payloads are arbitrary int32 (VERDICT
    # missing #1 -- a value colliding with a tick can no longer corrupt the
    # histogram). Read before compaction/injection can touch slots (same
    # aliasing rule as the checksum pass).
    if track:
        sl = jnp.arange(cap, dtype=jnp.int32)[None, :]
        abs1 = (base[:, None] + (sl - base[:, None]) % cap + 1) if comp else (sl + 1)
        # Dedup across leader changes AND restarts: a freshly elected leader's
        # own commit trails the cluster's prior frontier and would re-count
        # entries its predecessor already reported, so only entries above the
        # CARRIED monotone frontier contribute (the per-node commit vector is
        # restart-mutable -- ClusterState.lat_frontier). Stamps are offer
        # tick + 1, always in (0, now] at commit time; slots holding no client
        # entry (no-ops, unwritten) carry stamp 0 and fall out of `cli`.
        newly = (abs1 > s.lat_frontier) & (abs1 <= commit[:, None])
        cli = (log_tick_arr >= 1) & (log_tick_arr <= s.now)  # client-stamped slots
        lm = (is_leader & inp.alive)[:, None] & newly & cli
        lats = jnp.where(lm, s.now - log_tick_arr + 1, 0)  # [N, CAP]
        lat_sum = jnp.sum(lats).astype(jnp.int32)
        lat_cnt = jnp.sum(lm).astype(jnp.int32)
        # Coverage gap counter (StepInfo.lat_excluded): client entries the
        # frontier advance crosses without attribution. The frontier advances
        # to max(commit) regardless of leadership; count the crossed client
        # entries on the (lowest-id) node HOLDING that max -- its log carries
        # everything in (frontier, max commit] by log matching -- and subtract
        # what lat_cnt attributed. Clamped at zero: under compaction the
        # max-commit node may have compacted a crossed slot the leader still
        # counted, and split-brain double-counts inflate lat_cnt.
        is_maxc = commit == jnp.max(commit)
        hnode = jnp.min(jnp.where(is_maxc, ids, n))
        crossed = (ids == hnode)[:, None] & newly & cli
        lat_excluded = jnp.maximum(
            jnp.sum(crossed).astype(jnp.int32) - lat_cnt, 0
        )
        # Histogram bin = floor(log2(l)), clamped to the last bin
        # (log_ops.log2_bin: the one binning copy, shared with the
        # read-latency histogram and both kernels).
        bin_ = log_ops.log2_bin(lats, LAT_HIST_BINS)
        oh_b = (jnp.arange(LAT_HIST_BINS)[None, None, :] == bin_[:, :, None]) & lm[:, :, None]
        lat_hist = jnp.sum(oh_b, axis=(0, 1)).astype(jnp.int32)  # [BINS]
        lat_frontier = jnp.maximum(s.lat_frontier, jnp.max(commit))
    else:
        lat_sum = jnp.int32(0)
        lat_cnt = jnp.int32(0)
        lat_hist = jnp.zeros((LAT_HIST_BINS,), jnp.int32)
        lat_excluded = jnp.int32(0)
        lat_frontier = s.lat_frontier

    # ---- phase 5.5: log compaction -------------------------------------------------
    # The reference's unbounded log vector (log.clj:33) needs none; the ring must
    # free committed slots or a long-horizon client workload would exhaust it
    # (commands rejected forever once log_len - log_base == CAP). Policy: whenever
    # fewer than compact_margin free slots remain, advance base toward commit so up
    # to CAP - compact_margin entries stay retained for laggard catch-up. base_chk
    # is extended over the newly compacted span in the checksum pass below.
    base_mid, bchk_mid = base, bchk  # post-install, pre-advance (checksum anchor)
    if comp:
        target = jnp.minimum(commit, log_len - (cap - cfg.compact_margin))
        base2 = jnp.maximum(base, target)
        bterm = log_ops.term_at_r(log_term_arr, base, bterm, base2)  # = bterm if unchanged
        if rcf:
            # Fold the compacted span's config entries into the snapshot
            # context (cfglog.fold_span; anchored at the PRE-advance base,
            # same aliasing rule as the checksum pass below -- must run
            # before phase 6 can reuse freed slots).
            bmold, bpend, bepoch = cfglog.fold_span(
                cfg, log_cfg_arr, base, base2, bmold, bpend, bepoch
            )
        base = base2

    # ---- committed-prefix checksum --------------------------------------------------
    # One masked pass over the post-append arrays yields the old-prefix sum
    # (invariant: equals the carried checksum), the compacted-prefix extension, and
    # the new-prefix sum (log_ops module comment). All sums anchor at base_mid, the
    # base BEFORE this tick's compaction advance. This pass MUST run before phase 6:
    # an injection into a slot freed by this very tick's rebase would otherwise be
    # read back under the just-compacted entry's weight (base_mid-anchored slot ->
    # absolute-index map), silently corrupting base_chk. AE writes cannot alias
    # (they only touch entries <= base + CAP, whose anchored indices are exact).
    # The sums are part of load-bearing snapshot state (shipped as req_base_chk,
    # persisted in checkpoints), so under compaction they are maintained even with
    # invariant CHECKING off -- only the chk_ok comparison is gated.
    if comp:
        co = jnp.maximum(s.commit_index, base_mid)  # snap installs skip the check
        s_co, s_bf, s_cn = log_ops.ring_chk(
            log_term_arr, log_val_arr, base_mid, (co, base, commit)
        )
        if cfg.check_invariants:
            chk_ok = (bchk_mid + s_co == s.commit_chk) | apply_snap
        else:
            chk_ok = jnp.ones((n,), bool)
        bchk = bchk_mid + s_bf
        chk_new = bchk_mid + s_cn
    elif cfg.check_invariants:
        chk_old, chk_new = log_ops.prefix_chk2(
            log_term_arr, log_val_arr, s.commit_index, commit
        )
        chk_ok = chk_old == s.commit_chk
    else:
        chk_new = s.commit_chk
        chk_ok = jnp.ones((n,), bool)

    # ---- phase 6: client command injection (client-set-handler core.clj:151-160) --
    # Routing: with client_redirect the client POSTs one node and chases 302
    # redirects at one tick per bounce (the reference's write path,
    # core.clj:151-160, server.clj:62-63); otherwise the omniscient simulator
    # client writes straight to every live leader. Under compaction, a fresh
    # election win appends a leader NO-OP entry instead (spec 5.4.2 workaround:
    # old-term entries only commit via a current-term entry at quorum, and a full
    # ring of old-term entries would otherwise deadlock commit forever -- see
    # docs/DESIGN.md); client injections keep `noop_reserve` slots free so a
    # no-op slot survives commit-free election chains up to that depth.
    if comp:
        reserve = max(1, cfg.compact_margin // 2)
        noop = win & (log_len - base < cap)
        room = log_len - base < cap - reserve
        # A win with NO room for its no-op: beyond the reserve's guarantee, the
        # latent 5.4.2 commit-freeze the no-op exists to break -- surfaced as a
        # liveness metric instead of stalling silently (StepInfo.noop_blocked).
        noop_blocked = jnp.sum(win & ~(log_len - base < cap)).astype(jnp.int32)
    else:
        noop = jnp.zeros((n,), bool)
        room = log_len - base < cap
        noop_blocked = jnp.int32(0)
    # ---- config-entry origination (log-carried membership, thesis 4.3) ----------
    # Config changes are LOG WRITES sharing phase 6's one-append-per-node
    # slot (priority: election no-op > config entry > client command), each
    # judged on the leader's OWN tick-start derived configuration:
    #   JOINT entry (+v+1): the admin's toggle, accepted by the lowest-id
    #   live voter-leader, refused while that leader's prefix is already
    #   joint or when the toggle would leave C_new below 2 voters.
    #   FINAL entry (-v-1): appended automatically once the governing joint
    #   entry commits on the leader (commit >= cfg_pend) -- the thesis's
    #   "C_old,new committed -> append C_new" step.
    if rcf:
        t_r = inp.reconfig_cmd
        tbit = bitplane.one_bit(t_r, n)  # [W]; all-zero row for NIL
        toggled = m_new ^ tbit[None, :]  # [N, W]: each node's view of the result
        ld_ok = is_leader & inp.alive & member_b & room & ~noop
        ldj = jnp.min(jnp.where(ld_ok & ~joint, ids, n))
        accept_j = (
            (t_r != NIL)
            & (ids == ldj)
            & ld_ok
            & ~joint
            & (bitplane.count(tbit, axis=0) > 0)
            & (bitplane.count(toggled, axis=1) >= 2)
        )
        if cfg.joint_consensus:
            # Pending toggle of this node's open joint phase: the one bit
            # its member_old and member_new rows differ on.
            pvbits = bitplane.unpack(m_old ^ m_new, n, axis=1)  # [N, N]
            pend_v = jnp.min(jnp.where(pvbits, ids[None, :], n), axis=1)
            accept_f = ld_ok & joint & (commit >= s.cfg_pend)
            cfg_code = jnp.where(
                accept_j, t_r + 1, jnp.where(accept_f, -(pend_v + 1), 0)
            ).astype(jnp.int32)
            cfg_write = accept_j | accept_f
        else:
            # TEST-ONLY mutant (single-server change, cfg.joint_consensus
            # False): one final-acting entry per change, no joint phase, no
            # completing entry -- the known-unsafe variant.
            cfg_code = jnp.where(accept_j, t_r + 1, 0).astype(jnp.int32)
            cfg_write = accept_j
    if cfg.client_redirect:
        # K commands in flight (cfg.client_pipeline -- the reference's
        # buffered(5) request channel, server.clj:37): a fresh offer takes the
        # FIRST free slot (dropped only when all K are busy); each active slot
        # independently chases redirects. Per node, at most ONE slot is
        # accepted per tick -- the reference's loop dequeues one message per
        # wait iteration -- lowest slot index first; slots targeting distinct
        # leaders (split-brain windows) can accept in parallel.
        kdim = cfg.client_pipeline
        kk = jnp.arange(kdim, dtype=jnp.int32)
        free = s.client_pend == NIL  # [K]
        first_free = free & (jnp.cumsum(free) == 1)
        fresh = (inp.client_cmd != NIL) & first_free
        pend = jnp.where(fresh, inp.client_cmd, s.client_pend)  # [K]
        tgt = jnp.where(fresh, inp.client_target, s.client_dst)
        # Offer stamp rides the slot beside the payload: latency is measured
        # from the OFFER tick, and the bounces happen after it.
        ptick = jnp.where(fresh, s.now + 1, s.client_tick) if track else None
        active = pend != NIL
        tgt_oh = active[:, None] & (tgt[:, None] == ids[None, :])  # [K, N]
        low_k = jnp.min(jnp.where(tgt_oh, kk[:, None], kdim), axis=0)  # [N]
        node_ok = is_leader & inp.alive & room & ~noop
        if rcf:
            node_ok = node_ok & ~cfg_write  # the slot holds a config entry
        if xfr:
            # Transfer lease handoff (thesis 3.10): a transferring leader
            # stops accepting client commands until the transfer completes
            # or aborts.
            node_ok = node_ok & ~xfer_pend
        client_ok = (low_k < kdim) & node_ok  # [N] nodes accepting a slot
        sel_k = tgt_oh & (kk[:, None] == low_k[None, :]) & node_ok[None, :]  # [K, N]
        wval_cl = jnp.sum(jnp.where(sel_k, pend[:, None], 0), axis=0)  # [N]
        wtick_cl = (
            jnp.sum(jnp.where(sel_k, ptick[:, None], 0), axis=0) if track else None
        )
        accepted_k = jnp.any(sel_k, axis=1)  # [K]
        # Distinct slots hold distinct offers: the count is exact (the direct
        # client's any() collapses split-brain double-accepts of ONE offer).
        cmds_cnt = jnp.sum(accepted_k).astype(jnp.int32)
        # Redirect still-pending slots: to the target's known leader when the
        # target is up and knows one, else to a random peer (core.clj:152-155).
        # A rejected POST at a full leader retries there next tick.
        tgt_ld = jnp.max(jnp.where(tgt_oh, leader_id[None, :], NIL), axis=1)  # [K]
        tgt_up = jnp.any(tgt_oh & inp.alive[None, :], axis=1)
        pend_on = active & ~accepted_k
        client_pend = jnp.where(pend_on, pend, NIL)
        client_dst = jnp.where(
            pend_on, jnp.where(tgt_up & (tgt_ld != NIL), tgt_ld, inp.client_bounce), 0
        )
        client_tick = jnp.where(pend_on, ptick, 0) if track else s.client_tick
    else:
        client_ok = (inp.client_cmd != NIL) & is_leader & inp.alive & room & ~noop
        if rcf:
            client_ok = client_ok & ~cfg_write  # the slot holds a config entry
        if xfr:
            client_ok = client_ok & ~xfer_pend  # transfer lease handoff
        wval_cl = jnp.broadcast_to(inp.client_cmd, (n,))
        # Direct mode accepts on the offer tick itself: stamp = now + 1 (the
        # same stamp the redirect pipeline records at slot entry).
        wtick_cl = jnp.broadcast_to(s.now + 1, (n,)) if track else None
        # any(), not sum(): during a split-brain window two live leaders can
        # both accept the same offered command; that is ONE offer accepted, and
        # the offered-vs-committed audit counts offers.
        cmds_cnt = jnp.any(client_ok).astype(jnp.int32)
        client_pend = s.client_pend
        client_dst = s.client_dst
        client_tick = s.client_tick
    do_write = (noop | cfg_write | client_ok) if rcf else (noop | client_ok)
    wval = jnp.where(noop, NOOP, wval_cl)
    if rcf:
        # Config entries carry value 0 (the command rides the log_cfg plane).
        wval = jnp.where(cfg_write, 0, wval)
    inj_pos = jnp.where(do_write, log_len % cap if comp else log_len, cap)
    log_term_arr = log_term_arr.at[ids, inj_pos].set(term, mode="drop")
    log_val_arr = log_val_arr.at[ids, inj_pos].set(
        jnp.broadcast_to(wval, (n,)), mode="drop"
    )
    if track:
        # No-op entries carry stamp 0: protocol filler, never a client offer.
        wtick = jnp.where(noop, 0, wtick_cl)
        if rcf:
            wtick = jnp.where(cfg_write, 0, wtick)  # config entries too
        log_tick_arr = log_tick_arr.at[ids, inj_pos].set(
            jnp.broadcast_to(wtick, (n,)), mode="drop"
        )
    if rcf:
        # EVERY append writes the config plane (0 for non-config entries):
        # a slot reused after truncation must never leak its old command.
        log_cfg_arr = log_cfg_arr.at[ids, inj_pos].set(
            jnp.where(cfg_write, cfg_code, 0), mode="drop"
        )
    log_len = log_len + do_write

    # ---- phase 7: timers (generate-timeout core.clj:171-174; dispatch :193-195) ----
    clock = s.clock + inp.skew
    # Election timer resets ONLY on vote grant or valid current-term AppendEntries (or
    # stepping down), not on every message (reference bug 2.3.11).
    reset_election = granted_any | has_ae | saw_higher
    deadline = jnp.where(reset_election, clock + inp.timeout_draw, s.deadline)
    deadline = jnp.where(win, clock + cfg.heartbeat_ticks, deadline)
    if cfg.pre_vote:
        # A just-promoted candidate draws a fresh election timeout.
        deadline = jnp.where(pre_win, clock + inp.timeout_draw, deadline)
    # A down node's timers cannot fire; its fresh deadline is set by the restart wipe.
    expired = (clock >= deadline) & inp.alive

    # Leader heartbeat (heartbeat-handler core.clj:162-164).
    heartbeat = expired & is_leader
    deadline = jnp.where(heartbeat, clock + cfg.heartbeat_ticks, deadline)

    # Follower/candidate timeout -> new election (timeout-handler core.clj:166-169,
    # follower->candidate core.clj:69-73: term++, vote self).
    if cfg.pre_vote:
        # Expiry starts a PRE-vote probe instead: no term bump, votedFor
        # untouched (grants stay possible), the self pre-vote rides the bitmap.
        # The REAL election start is this tick's promotions (phase 4.5).
        start_prevote = expired & ~is_leader
        if rcf:
            # Non-voters never campaign (the removed-node quiescence rule,
            # judged on the node's OWN derived config: a node whose log
            # carries its removal is a learner; one whose log misses it
            # still campaigns -- the disruption the 4.2.3 denial absorbs).
            start_prevote = start_prevote & member_b
        if xfr:
            # A TimeoutNow target skips the probe: its real election (below)
            # is the thesis-3.10 pre-vote bypass.
            start_prevote = start_prevote & ~xfer_elect
        role = jnp.where(start_prevote, PRECANDIDATE, role)
        leader_id = jnp.where(start_prevote, NIL, leader_id)
        votes = jnp.where(start_prevote[:, None], eye_p, votes)
        deadline = jnp.where(start_prevote, clock + inp.timeout_draw, deadline)
        start_election = pre_win
        if xfr:
            # TimeoutNow election: real term bump + self-vote + RequestVote
            # broadcast, exactly the promotion path minus the pre-quorum.
            # ~is_leader re-checked: the target may have WON an ordinary
            # election in phase 4 this very tick.
            xe = xfer_elect & ~pre_win & ~is_leader
            term = term + xe
            role = jnp.where(xe, CANDIDATE, role)
            voted_for = jnp.where(xe, ids, voted_for)
            leader_id = jnp.where(xe, NIL, leader_id)
            votes = jnp.where(xe[:, None], eye_p, votes)
            deadline = jnp.where(xe, clock + inp.timeout_draw, deadline)
            start_election = pre_win | xe
    else:
        start_prevote = jnp.zeros((n,), bool)
        start_election = expired & ~is_leader
        if rcf:
            start_election = start_election & member_b  # non-voters never campaign
        if xfr:
            # TimeoutNow election (~is_leader re-checked: the target may have
            # won an ordinary election in phase 4 this very tick).
            xe = xfer_elect & ~is_leader
            start_election = start_election | xe
        term = term + start_election
        role = jnp.where(start_election, CANDIDATE, role)
        voted_for = jnp.where(start_election, ids, voted_for)
        leader_id = jnp.where(start_election, NIL, leader_id)
        votes = jnp.where(start_election[:, None], eye_p, votes)
        deadline = jnp.where(start_election, clock + inp.timeout_draw, deadline)

    # ---- phase 7.5: fsync flush + section-3.8 durability gates -------------------
    # The device-side fsync model (raft_sim_tpu/storage): a completed flush
    # (inp.fsync_fire -- the cadence tick minus the per-node latency-jitter
    # stall, sim/faults._storage_draws; a dead disk never flushes) snaps the
    # durable snapshot to the node's FINAL live state this tick -- the
    # post-injection log length and the post-election term/vote. Between
    # flushes the watermark carries (clamped by truncation, dur_mid above).
    if dur:
        fs_fire = inp.fsync_fire & inp.alive
        dur2_len, dur2_term, dur2_vote = storage_plane.flush(
            fs_fire, dur_mid, s.dur_term, s.dur_vote, log_len, term, voted_for
        )
        if cfg.durable_acks:
            # Gate 1 -- AE acks: the acked match index never exceeds the
            # durable watermark. A follower behind a slow disk acks LESS
            # than it appended (the leader's match/next simply lag; the
            # idempotent consistency check absorbs the re-sends), so
            # replication STALLS behind the disk instead of lying about it.
            # The nack catch-up hint stays volatile: it is an optimization
            # target, never counted toward commit.
            out_a_match = jnp.minimum(
                out_a_match.astype(jnp.int32), dur2_len
            ).astype(idt)
            # Gate 2 -- vote grants: a grant is EXPOSED only once the
            # (term, votedFor) pair it commits to is durable. covered0 vs
            # covered2 splits "already exposed on an earlier tick" from
            # "this tick's flush just made it durable": the latter emits a
            # LATE vote-completion response below (phase 8) when the grant
            # tick itself could not -- the array form of "respond after the
            # fsync returns". A grant whose flush never lands before the
            # candidate gives up is simply lost (like a dropped response).
            covered0 = storage_plane.covered(s.dur_term, s.dur_vote, term, voted_for)
            covered2 = storage_plane.covered(dur2_term, dur2_vote, term, voted_for)
            grant_to = jnp.where(covered2, voted_for, NIL).astype(node_dtype(cfg))
            late_grant = covered2 & ~covered0 & ~granted_any

    # ---- phase 8: outbox ---------------------------------------------------------
    send_append = win | heartbeat  # fresh leaders heartbeat immediately (core.clj:137-138)
    if comp:
        new_last_idx = log_len
        new_last_term = log_ops.term_at_r(log_term_arr, base, bterm, log_len)
    else:
        new_last_idx, new_last_term = log_ops.last_index_term(log_term_arr, log_len)

    # Request headers are PER SENDER -- both RPCs are broadcasts (request-vote-rpc
    # core.clj:48-54, append-entries-rpc core.clj:56-67); the only per-edge request
    # datum is the AE window offset (Mailbox docstring).
    ae_edge = send_append[:, None] & ~eye
    out_req_type = jnp.where(
        start_election, REQ_VOTE, jnp.where(send_append, REQ_APPEND, 0)
    )  # [N]
    if cfg.pre_vote:
        out_req_type = jnp.where(start_prevote, REQ_PREVOTE, out_req_type)
        rv_like = start_election | start_prevote  # both fill the req_last header
    else:
        rv_like = start_election
    out_req_term = jnp.where(out_req_type != 0, term, 0)
    if cfg.pre_vote:
        # The probe carries the PROSPECTIVE term (term + 1, thesis 9.6); phase 1
        # excludes it from adoption.
        out_req_term = jnp.where(start_prevote, term + 1, out_req_term)
    if xfr:
        # TimeoutNow fire (thesis 3.10): on a heartbeat tick with a pending
        # transfer whose target has fully matched the leader's log, the
        # broadcast slot carries REQ_TIMEOUT_NOW instead of the heartbeat
        # (re-fired each heartbeat while pending: a dropped fire retries; a
        # successful one deposes this leader before the next). The AE window
        # fields stay populated as the heartbeat would have left them --
        # receivers gate every AE read on req_type == REQ_APPEND.
        tcl8 = jnp.clip(xfer_to, 0, n - 1)
        t_match = jnp.take_along_axis(match_index, tcl8[:, None], axis=1)[
            :, 0
        ].astype(jnp.int32)
        if cfg.xfer_election:
            caught = t_match >= log_len
        else:
            # TEST-ONLY mutant: fire without the catch-up wait (the coup
            # receipt on the other side doesn't check the log either).
            caught = jnp.ones((n,), bool)
        fire = send_append & (xfer_to != NIL) & caught
        out_req_type = jnp.where(fire, REQ_TIMEOUT_NOW, out_req_type)
        out_xfer_tgt = jnp.where(fire, xfer_to, NIL).astype(node_dtype(cfg))
    else:
        out_xfer_tgt = mb.xfer_tgt  # NIL, loop-invariant carry component
    if xfr and (rcf or rdl):
        # The disruptive-RequestVote override (thesis 3.10/4.2.3): a
        # transfer-triggered election's broadcast carries the flag, so
        # heard-recent voters still process it. Written only when a denial
        # gate can read it; zeros and carried untouched otherwise.
        out_req_disrupt = jnp.where(xe, 1, 0).astype(jnp.int8)
    else:
        out_req_disrupt = mb.req_disrupt  # zeros, loop-invariant component
    # AE: prev = nextIndex - 1 per edge, carried as the offset into the shared window.
    prev_out = jnp.clip(next_index - 1, 0, log_len[:, None])  # [src, dst]
    # Shared window start: minimum prev over RESPONSIVE peers (acked an AE within
    # ack_timeout_ticks). A peer that never acks -- crashed, partitioned away -- must
    # not pin the window, or no live follower could ever receive entries past
    # ws + E and commit would stall despite a live quorum. When no peer is
    # responsive (nothing to replicate to anyway) fall back to the min over all
    # peers. An unresponsive laggard's prev is clamped UP to ws below: spec-safe
    # (the consistency check at the too-high prev fails, it nacks, and that nack
    # both re-admits it to the responsive set and walks next_index back down).
    responsive = ack_age <= cfg.ack_timeout_ticks  # [src, dst]
    # big > any prev_out (prev_out <= log_len; absolute and unbounded under
    # compaction, <= cap otherwise).
    big = jnp.int32(2**31 - 1) if comp else (cap + 1)
    ws_resp = jnp.min(jnp.where(eye | ~responsive, big, prev_out), axis=1)  # [src]
    ws_all = jnp.min(jnp.where(eye, big, prev_out), axis=1)
    none_resp = (ws_resp == big) if comp else (ws_resp > cap)
    ws = jnp.where(none_resp, ws_all, ws_resp)
    ws = jnp.minimum(ws, log_len)
    if comp:
        # Entries below the compaction base are gone: the window cannot start
        # before it, and peers whose prev falls below it get the InstallSnapshot
        # sentinel (req_off = -1) instead of a window offset.
        ws = jnp.maximum(ws, base)
        snap_edge = ae_edge & (prev_out < base[:, None])
    # Clamp each peer's prev into [ws, ws+E]: spec-safe in both directions (a peer
    # ahead of the window gets a plain heartbeat over an older prefix it already
    # has, its redundant ack absorbed by the monotone max() updates of match/next
    # in phase 4; an unresponsive laggard's prev is lifted to ws, its nack walks
    # next_index back down and re-admits it to the responsive set), and it bounds
    # prev - ws to E+1 values so the batch-minor kernel can read prev terms from
    # the shared window instead of a CAP-wide one-hot per edge.
    # j = clip(prev, ws, ws+E) - ws == clip(prev - ws, 0, E); the difference
    # form bounds the offset syntactically for the value-range audit.
    off_j = jnp.clip(prev_out - ws[:, None], 0, e)
    prev_out = ws[:, None] + off_j
    # Per-edge window offset j = prev - ws in 0..E; receivers reconstruct prev,
    # prev_term, and n_entries from (j, ent_start, ent_prev_term, ent_count).
    out_req_off = jnp.where(ae_edge, off_j, 0).astype(jnp.int8)
    if comp:
        out_req_off = jnp.where(snap_edge, jnp.int8(-1), out_req_off)
    # Zero unused window slots so the mailbox is canonical (receivers mask with
    # the derived n_ent anyway, but a canonical wire format keeps trajectories
    # bit-comparable).
    n_ship = jnp.clip(log_len - ws, 0, e)  # [src]
    ship_used = send_append[:, None] & (ks[None, :] < n_ship[:, None])  # [src, E]
    wread = log_ops.window_r if comp else log_ops.window
    out_ent_term = jnp.where(ship_used, wread(log_term_arr, ws, e), 0)
    out_ent_val = jnp.where(ship_used, wread(log_val_arr, ws, e), 0)
    out_ent_tick = (
        jnp.where(ship_used, wread(log_tick_arr, ws, e), 0) if track
        else mb.ent_tick  # zeros, loop-invariant carry component
    )
    out_ent_cfg = (
        jnp.where(ship_used, wread(log_cfg_arr, ws, e), 0) if rcf
        else mb.ent_cfg  # zeros, loop-invariant carry component
    )

    # Responses: vr_out/ar_out are [request-sender, request-receiver], which IS the
    # response orientation [response-receiver, responder] (the reference's resp-chan
    # round trip, server.clj:59-60 -> client.clj:34-40); the edge plane carries only
    # the response TYPE -- payloads are per responder (Mailbox response decode).
    out_resp_kind = (
        jnp.where(vr_out, RESP_VOTE, 0) + jnp.where(ar_out, RESP_APPEND, 0)
    ).astype(jnp.int8)
    if cfg.pre_vote:
        # Pre-vote responses overlay the same plane; the grant BIT rides the
        # packed pv_grant plane (one voter may grant several probes per tick,
        # so it is genuinely per-edge -- Mailbox docstring).
        out_resp_kind = out_resp_kind + jnp.where(pv_out, RESP_PREVOTE, 0).astype(
            jnp.int8
        )
        out_pv_grant = bitplane.pack(pv_grant, axis=1)  # [cand, W(bit=voter)]
    else:
        out_pv_grant = mb.pv_grant  # zeros, loop-invariant carry component
    if dur and cfg.durable_acks:
        # Late vote-completion response (phase 7.5 gate 2): the flush that
        # just made this voter's grant durable emits the RESP_VOTE edge the
        # grant tick withheld -- toward the recorded candidate, only where
        # the edge carries no response already (a candidate that won
        # meanwhile is heartbeating us; its AE response outranks the vote it
        # no longer needs). v_to already names the candidate via covered2.
        vfc = jnp.clip(voted_for, 0, n - 1)
        late_edge = (ids[:, None] == vfc[None, :]) & late_grant[None, :]
        out_resp_kind = jnp.where(
            late_edge & (out_resp_kind == 0),
            jnp.int8(RESP_VOTE),
            out_resp_kind,
        )
    pterm = (
        log_ops.term_at_r(log_term_arr, base, bterm, ws)
        if comp
        else log_ops.term_at(log_term_arr, ws)
    )

    new_mb = Mailbox(
        req_type=out_req_type,
        req_term=out_req_term,
        req_commit=jnp.where(send_append, commit, 0),
        req_last_index=jnp.where(rv_like, new_last_idx, 0),
        req_last_term=jnp.where(rv_like, new_last_term, 0),
        ent_start=jnp.where(send_append, ws, 0),
        ent_prev_term=jnp.where(send_append, pterm, 0),
        ent_count=jnp.where(send_append, n_ship, 0),
        ent_term=out_ent_term,
        ent_val=out_ent_val,
        ent_tick=out_ent_tick,
        # Without compaction the snapshot header is dead weight: pass the zeros
        # through untouched so XLA sees a loop-invariant carry component.
        req_base=jnp.where(send_append, base, 0) if comp else mb.req_base,
        req_base_term=jnp.where(send_append, bterm, 0) if comp else mb.req_base_term,
        req_base_chk=(
            jnp.where(send_append, bchk, jnp.uint32(0)) if comp else mb.req_base_chk
        ),
        xfer_tgt=out_xfer_tgt,
        req_disrupt=out_req_disrupt,
        ent_cfg=out_ent_cfg,
        req_base_mold=(
            jnp.where(send_append[:, None], bmold, jnp.uint32(0))
            if (comp and rcf) else mb.req_base_mold
        ),
        req_base_pend=(
            jnp.where(send_append, bpend, 0) if (comp and rcf)
            else mb.req_base_pend
        ),
        req_base_epoch=(
            jnp.where(send_append, bepoch, 0) if (comp and rcf)
            else mb.req_base_epoch
        ),
        req_off=out_req_off,
        resp_kind=out_resp_kind,
        pv_grant=out_pv_grant,
        v_to=grant_to,
        a_ok_to=out_a_ok_to,
        a_match=out_a_match,
        a_hint=out_a_hint,
        resp_term=term,
    )

    # ---- end-of-tick config derivation (log-carried membership) ------------------
    # Each node's effective configuration recomputed from its post-append,
    # post-compaction log prefix (models/cfglog.py): apply-on-append and
    # roll-back-on-truncation are the SAME recomputation -- a truncated
    # config entry simply stops existing for the next tick's quorums.
    if rcf:
        # base_mold/base_pend/base_epoch initialize to the boot config
        # (types.init_state) and are carried untouched without compaction,
        # so they are always the valid context at `base`.
        d_mold, d_mnew, d_pend, d_epoch, d_hi = cfglog.derive(
            cfg, log_cfg_arr, log_len, commit, base, bmold, bpend, bepoch
        )
        if not cfg.truncation_rollback:
            # TEST-ONLY mutant (ignore-truncation-rollback): where the
            # prefix LOST config entries, keep acting on the stale carried
            # configuration -- the dissertation's rollback rule skipped.
            rolled = d_epoch < s.cfg_epoch
            d_mold = jnp.where(rolled[:, None], s.member_old, d_mold)
            d_mnew = jnp.where(rolled[:, None], s.member_new, d_mnew)
            d_pend = jnp.where(rolled, s.cfg_pend, d_pend)
            d_epoch = jnp.where(rolled, s.cfg_epoch, d_epoch)
        # Removed-server stepdown (thesis 4.3): a LEADER whose own config
        # union excludes it keeps leading -- replicating the very entry
        # that removes it -- until that entry commits on it, then steps
        # down (its log never counts toward masked quorums meanwhile: the
        # caretaker role). Candidacies of removed nodes die immediately.
        self_in = jnp.any(((d_mold | d_mnew) & eye_p) != 0, axis=1)
        is_cand = (role == CANDIDATE) | (role == PRECANDIDATE)
        demote = ~self_in & (
            ((role == LEADER) & (commit >= d_hi)) | is_cand
        )
        role = jnp.where(demote, FOLLOWER, role)
        leader_id = jnp.where(demote, NIL, leader_id)

    new_state = ClusterState(
        role=role,
        term=term,
        voted_for=voted_for,
        leader_id=leader_id,
        votes=votes,
        next_index=next_index,
        match_index=match_index,
        ack_age=ack_age,
        commit_index=commit,
        commit_chk=chk_new,
        log_base=base,
        base_term=bterm,
        base_chk=bchk,
        log_term=log_term_arr,
        log_val=log_val_arr,
        log_tick=log_tick_arr,
        log_len=log_len,
        dur_len=dur2_len if dur else s.dur_len,
        dur_term=dur2_term if dur else s.dur_term,
        dur_vote=dur2_vote if dur else s.dur_vote,
        clock=clock,
        deadline=deadline,
        heard_clock=heard,
        member_old=d_mold if rcf else s.member_old,
        member_new=d_mnew if rcf else s.member_new,
        cfg_epoch=d_epoch if rcf else s.cfg_epoch,
        cfg_pend=d_pend if rcf else s.cfg_pend,
        log_cfg=log_cfg_arr,
        base_mold=bmold if (rcf and comp) else s.base_mold,
        base_pend=bpend if (rcf and comp) else s.base_pend,
        base_epoch=bepoch if (rcf and comp) else s.base_epoch,
        xfer_to=xfer_to if xfr else s.xfer_to,
        read_idx=read_idx if rdx else s.read_idx,
        read_tick=read_tick if rdx else s.read_tick,
        read_acks=read_acks if rdx else s.read_acks,
        read_fr=read_fr if rdl else s.read_fr,
        client_pend=client_pend,
        client_dst=client_dst,
        client_tick=client_tick,
        lat_frontier=lat_frontier,
        now=s.now + 1,
        mailbox=new_mb,
    )

    # Durability-lag reductions (StepInfo; host-constant zeros when the plane
    # is off -- same zero-cost contract as the read metrics above).
    if dur:
        lag = log_len - dur2_len  # [N] >= 0 (flush snaps to log_len)
        fsync_lag_sum = jnp.sum(lag).astype(jnp.int32)
        fsync_lag_max = jnp.max(lag).astype(jnp.int32)
    else:
        fsync_lag_sum = np.int32(0)
        fsync_lag_max = np.int32(0)

    info = _step_info(
        cfg, s, new_state, req_in, resp_in, inp.alive, cmds_cnt, chk_ok,
        lat_sum, lat_cnt, lat_hist, lat_excluded, noop_blocked,
        reads_served, read_lat_sum, read_hist, viol_read_stale,
        fsync_lag_sum, fsync_lag_max,
    )
    return new_state, info


def _step_info(
    cfg: RaftConfig,
    old: ClusterState,
    new: ClusterState,
    req_in: jax.Array,
    resp_in: jax.Array,
    alive: jax.Array,
    cmds_cnt: jax.Array,
    chk_ok: jax.Array,
    lat_sum: jax.Array,
    lat_cnt: jax.Array,
    lat_hist: jax.Array,
    lat_excluded: jax.Array,
    noop_blocked: jax.Array,
    reads_served: jax.Array,
    read_lat_sum: jax.Array,
    read_hist: jax.Array,
    viol_read_stale: jax.Array,
    fsync_lag_sum: jax.Array,
    fsync_lag_max: jax.Array,
) -> StepInfo:
    """Phase 9: on-device safety invariants + observability reductions (per cluster)."""
    n = cfg.n_nodes
    eye = jnp.eye(n, dtype=bool)
    is_leader = new.role == LEADER
    # Observability counts only *live* leaders: a crashed node frozen in LEADER role
    # provides no leadership (the cluster is leaderless until re-election), and the
    # north-star ticks-to-stable-leader metric must reflect that. The safety checks
    # below keep the unmasked roles: a frozen stale leader still participates in the
    # at-most-one-leader-per-term invariant.
    live_leader = is_leader & alive
    f = jnp.bool_(False)

    if cfg.check_invariants:
        # Election safety: at most one leader per term (Raft fig. 3).
        pair_bad = (
            is_leader[:, None]
            & is_leader[None, :]
            & (new.term[:, None] == new.term[None, :])
            & ~eye
        )
        viol_election = jnp.any(pair_bad)
        # Commit sanity: monotonic, within the log, above the compaction base (with
        # the retained window inside the ring), and the committed prefix is
        # immutable -- entries below the old commit index never change term OR value
        # (state-machine-safety analogue of the reference's apply-entries! writing
        # committed values to an append-only file, log.clj:69-76). Immutability is
        # checked via the carried prefix checksum (chk_ok; log_ops module comment).
        viol_commit = jnp.any(
            (new.commit_index < old.commit_index)
            | (new.commit_index > new.log_len)
            | (new.commit_index < new.log_base)
            | (new.log_len - new.log_base > cfg.log_capacity)
            | ~chk_ok
        )
    else:
        viol_election = f
        viol_commit = f

    if cfg.check_log_matching:

        def _check(_):
            # Log matching on committed prefixes: any two nodes agree on every
            # entry (term AND value) up to m = min(commit_i, commit_j).
            # O(N^2 * CAP) -- gated, and sampled every log_matching_interval
            # ticks (below).
            minc = jnp.minimum(new.commit_index[:, None], new.commit_index[None, :])
            differ = (new.log_term[:, None, :] != new.log_term[None, :, :]) | (
                new.log_val[:, None, :] != new.log_val[None, :, :]
            )
            if not cfg.compaction:
                ks = jnp.arange(cfg.log_capacity, dtype=jnp.int32)
                both = ks[None, None, :] < minc[:, :, None]
                return jnp.any(both & differ), jnp.int32(0)
            # Ring form, in two parts per pair (i, j) with mb = max(base_i, base_j):
            # entries in (mb, m] are live in BOTH rings at the same slot (same
            # absolute index, same CAP) -> compare slots; the prefix up to mb is
            # compared via checksums-at-mb (chk_at(i, p) = base_chk_i + live sum
            # (base_i, p]), which is computable because mb >= base_i. Pairs where
            # one node compacted past the other's commit (m < mb) are skipped --
            # their agreement is pinned transitively through common peers -- and
            # COUNTED (StepInfo.lm_skipped_pairs) so the coverage is measured.
            cap_ = cfg.log_capacity
            sl = jnp.arange(cap_, dtype=jnp.int32)[None, :]
            b = new.log_base
            abs0 = b[:, None] + (sl - b[:, None]) % cap_  # [N, CAP] entry idx - 1
            mb_ = jnp.maximum(b[:, None], b[None, :])  # [N, N]
            comparable = minc >= mb_
            in_i = (abs0[:, None, :] >= mb_[:, :, None]) & (
                abs0[:, None, :] < minc[:, :, None]
            )
            in_j = (abs0[None, :, :] >= mb_[:, :, None]) & (
                abs0[None, :, :] < minc[:, :, None]
            )
            viol_suffix = jnp.any(comparable[:, :, None] & in_i & in_j & differ)
            w_t, w_v = log_ops.chk_weights_at(abs0)
            contrib = (
                new.log_term.astype(jnp.uint32) * w_t
                + new.log_val.astype(jnp.uint32) * w_v
            )  # [N, CAP]
            chk_at_mb = new.base_chk[:, None] + jnp.sum(
                jnp.where(abs0[:, None, :] < mb_[:, :, None], contrib[:, None, :], jnp.uint32(0)),
                axis=2,
                dtype=jnp.uint32,
            )  # [N(i), N(j)] = chk of node i's prefix at mb(i, j)
            viol_prefix = jnp.any(comparable & (chk_at_mb != chk_at_mb.T))
            skipped = (jnp.sum(~comparable & ~eye) // 2).astype(jnp.int32)
            return viol_suffix | viol_prefix, skipped

        if cfg.log_matching_interval == 1:
            viol_match, lm_skipped = _check(None)
        else:
            # Sampled cadence: the batch ticks in lockstep (config.py), so the
            # predicate is one scalar in the batch-minor hot path and lax.cond
            # truly skips the check off-cadence; under vmap (debug tier) cond
            # lowers to a select and both branches run -- same values either way.
            viol_match, lm_skipped = jax.lax.cond(
                new.now % cfg.log_matching_interval == 0,
                _check,
                lambda _: (f, jnp.int32(0)),
                None,
            )
    else:
        viol_match, lm_skipped = f, jnp.int32(0)

    leader = jnp.min(jnp.where(live_leader, jnp.arange(n, dtype=jnp.int32), n))
    return StepInfo(
        viol_election_safety=viol_election,
        viol_commit=viol_commit,
        viol_log_matching=viol_match,
        leader=jnp.where(leader < n, leader, NIL).astype(jnp.int32),
        n_leaders=jnp.sum(live_leader).astype(jnp.int32),
        max_term=jnp.max(new.term),
        max_commit=jnp.max(new.commit_index),
        min_commit=jnp.min(new.commit_index),
        msgs_delivered=(jnp.sum(req_in) + jnp.sum(resp_in)).astype(jnp.int32),
        # Offers accepted this tick, not appends: the direct client collapses
        # split-brain double-accepts of one offer via any(); the redirect
        # pipeline counts accepted slots (distinct offers) -- see phase 6.
        cmds_injected=cmds_cnt,
        lat_sum=lat_sum,
        lat_cnt=lat_cnt,
        lat_hist=lat_hist,
        lat_excluded=lat_excluded,
        noop_blocked=noop_blocked,
        lm_skipped_pairs=lm_skipped,
        reads_served=reads_served,
        read_lat_sum=read_lat_sum,
        read_hist=read_hist,
        viol_read_stale=viol_read_stale,
        fsync_lag_sum=fsync_lag_sum,
        fsync_lag_max=fsync_lag_max,
    )
