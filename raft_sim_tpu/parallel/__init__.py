from raft_sim_tpu.parallel.mesh import (
    AXIS,
    FleetSummary,
    init_distributed,
    make_mesh,
    simulate_sharded,
    summarize,
)

__all__ = [
    "AXIS",
    "FleetSummary",
    "init_distributed",
    "make_mesh",
    "simulate_sharded",
    "summarize",
]
