"""TEST-ONLY weakened kernel variants: the search loop's ground truth.

A violation hunter that never finds anything proves nothing -- maybe the
kernel is safe, maybe the hunt is blind. These config subclasses weaken the
kernel behind an explicit opt-in (driver `scenario search --mutant`, CI's
scenario smoke job, tests/test_scenario.py) so the search demo has a target
it MUST hit within a bounded generation budget: if the hunt cannot drive a
quorum-off-by-one kernel to an election-safety violation, the hunt is
broken, not the kernel. Never instantiate these outside tests/demos; the
class is deliberately NOT reachable from RaftConfig flags or scenario files.

The weakening rides the config (cfg.quorum feeds both kernels' vote counts
and commit rule), so no second kernel source exists to drift: the mutant
compiles the same step code at a different quorum literal -- one extra jit
compile, zero extra lowered program structures (literal-blind hashes equal;
analysis/jaxpr_audit.py structural_hash).
"""

from __future__ import annotations

from raft_sim_tpu.utils.config import RaftConfig


class WeakQuorumConfig(RaftConfig):
    """quorum - 1: floor(N/2) instead of floor(N/2)+1, so two split-vote
    candidates can both 'win' a term -- the reference's even-N majority bug
    (SURVEY.md quorum note) made unconditional. Election safety violates
    within a few elections once message drop forces vote splits."""

    @property
    def quorum(self) -> int:  # type: ignore[override]
        return self.n_nodes // 2


MUTANTS = {"weak-quorum": WeakQuorumConfig}


def mutant_config(name: str, cfg: RaftConfig) -> RaftConfig:
    """Rebuild `cfg` under the named mutant class (same field values)."""
    import dataclasses

    if name not in MUTANTS:
        raise ValueError(f"unknown mutant {name!r} (have {sorted(MUTANTS)})")
    return MUTANTS[name](**dataclasses.asdict(cfg))
