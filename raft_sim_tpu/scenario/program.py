"""Phased nemesis programs: declarative fault timelines over a fleet.

A ScenarioProgram is S genome segments played in order, `seg_len` ticks each
-- the Jepsen-nemesis shape ("partition for 200 ticks, heal, then crash the
leaders") as pure data. On device the timeline is a dense `[S]` table per
genome leaf indexed by `now // seg_len` (faults.genome_at): segments never
fork compiles, never enter the scan carry, and the final segment holds past
the program's end (so any horizon is legal). Programs load from a
declarative JSON file:

    {
      "name": "partition-heal-crash",
      "seg_len": 200,
      "segments": [
        {"partition_period": 32, "partition_prob": 1.0},
        {},
        {"crash_prob": 0.5, "crash_down_ticks": 12}
      ]
    }

Segment keys are exactly `genome.segment`'s keywords (human units: float
probabilities, tick cadences); an empty segment is fault-free. The same
schema embedded under "scenario" is what checkpoints (v20) and repro
artifacts carry, so every run is replayable from (scenario, seed).
"""

from __future__ import annotations

import dataclasses
import json

from raft_sim_tpu.scenario import genome as genome_mod
from raft_sim_tpu.scenario.genome import ScenarioGenome
from raft_sim_tpu.utils.config import RaftConfig

# The declarative segment vocabulary (genome.segment keywords).
SEGMENT_KEYS = frozenset({
    "drop_prob", "partition_period", "partition_prob", "crash_prob",
    "crash_down_ticks", "clock_skew_prob", "client_interval",
})


@dataclasses.dataclass(frozen=True)
class ScenarioProgram:
    """A named phased timeline: `genome` holds `[S]` per-segment leaves,
    `seg_len` is the static per-segment tick span."""

    name: str
    seg_len: int
    genome: ScenarioGenome

    @property
    def n_segments(self) -> int:
        return self.genome.drop.shape[0]

    @property
    def span(self) -> int:
        """Ticks until the final segment becomes standing (it holds forever)."""
        return self.seg_len * (self.n_segments - 1)


def from_dict(doc: dict, cfg: RaftConfig | None = None) -> ScenarioProgram:
    """Build (and validate) a program from the declarative schema above.
    `cfg` enables the config-coupled checks (crash_period ceiling, the
    client structural gate); pass it whenever the target config is known.

    A `genome_raw` key (exact integer leaves, `genome.to_raw`; what
    `to_dict(exact=True)` emits into checkpoints and artifacts) takes
    precedence over re-encoding the human-unit segments: decode() rounds
    probabilities, so rebuilding from segments alone could shift a uint32
    threshold by an ulp and silently resume a *different* trajectory --
    the exact failure the checkpoint-v20 scenario contract forbids."""
    unknown = set(doc) - {"name", "seg_len", "segments", "genome_raw"}
    if unknown:
        raise ValueError(f"unknown scenario keys {sorted(unknown)}")
    segments = doc.get("segments")
    if not isinstance(segments, list) or not segments:
        raise ValueError("scenario needs a non-empty 'segments' list")
    seg_len = int(doc.get("seg_len", 1))
    if seg_len < 1:
        raise ValueError(f"seg_len must be >= 1, got {seg_len}")
    for i, seg in enumerate(segments):
        bad = set(seg) - SEGMENT_KEYS
        if bad:
            raise ValueError(
                f"segment {i}: unknown keys {sorted(bad)} "
                f"(legal: {sorted(SEGMENT_KEYS)})"
            )
    if doc.get("genome_raw") is not None:
        g = genome_mod.from_raw(doc["genome_raw"])
        if g.drop.shape[0] != len(segments):
            raise ValueError(
                f"genome_raw carries {g.drop.shape[0]} segments but the "
                f"'segments' list has {len(segments)}"
            )
    else:
        # crash_down_ticks defaults to 1 (minimal span) so fault-free
        # segments validate under any crash_period.
        g = genome_mod.from_segments([
            genome_mod.segment(**{"crash_down_ticks": 1, **seg})
            for seg in segments
        ])
    if cfg is not None:
        genome_mod.validate(cfg, g)
    return ScenarioProgram(
        name=str(doc.get("name", "scenario")), seg_len=seg_len, genome=g
    )


def to_dict(program: ScenarioProgram, exact: bool = False) -> dict:
    """Inverse of from_dict (decoded human units; round-trips the schema).
    `exact=True` additionally embeds the integer genome leaves
    (`genome_raw`) so the round trip is BIT-exact, not merely
    9-decimal-exact -- required wherever the dict re-seeds a trajectory
    (checkpoints, repro artifacts)."""
    segs = []
    for row in genome_mod.decode(program.genome):
        seg = {
            "drop_prob": row["drop_prob"],
            "partition_period": row["partition_period"],
            "partition_prob": row["partition_prob"],
            "crash_prob": row["crash_prob"],
            "crash_down_ticks": row["crash_down_ticks"],
            "clock_skew_prob": row["clock_skew_prob"],
            "client_interval": row["client_interval"],
        }
        segs.append({k: v for k, v in seg.items() if v not in (0, 0.0)} or {})
    doc = {"name": program.name, "seg_len": program.seg_len, "segments": segs}
    if exact:
        doc["genome_raw"] = genome_mod.to_raw(program.genome)
    return doc


def load(path: str, cfg: RaftConfig | None = None) -> ScenarioProgram:
    with open(path) as f:
        return from_dict(json.load(f), cfg)


def save(path: str, program: ScenarioProgram) -> str:
    with open(path, "w") as f:
        json.dump(to_dict(program), f, indent=1)
        f.write("\n")
    return path
