"""Declarative SLO specs: what "healthy" means, as data.

A spec is a plain JSON document (schema `health-slo-v1`) naming objectives
over the SLIs the fleet already exports -- the telemetry windows' per-cluster
counters (sim/telemetry.py) and the perf.jsonl runtime rows (obs/timer.py).
Nothing here touches traced code: the health plane consumes streams the loops
were already producing, so an instrumented run is bit-exact vs a plain one.

    {
      "schema": "health-slo-v1",
      "eval_windows": 2,          # telemetry windows per evaluation period
      "worst_k": 3,               # clusters named per firing alert (triage)
      "outlier_score": 3.0,       # robust-score threshold for "outlier" label
      "resolve_evals": 2,         # clean evals before firing -> resolved
      "objectives": { name: {"sli": kind, ...params} },
      "rules":      [ {"name", "short", "long", "burn"} ]   # burn-rate pairs
    }

Objective params by SLI kind (sli.py computes them):

    availability       target           good = 1 - leaderless-window fraction
    commit_latency     threshold_ticks, target
                                        good = commits acked in < threshold
    read_staleness     stale_after_ticks, target
                                        good = reads served in < threshold
    throughput         min_ops_per_window, budget
                                        binary: ops/window under the floor
                                        burns `budget` (floor 0 = disabled)
    safety             (none)           budget 0: ANY violation is an
                                        instant max-burn page
    device_wait_share  min_share, budget
                                        binary: device-wait share of wall
                                        under the floor = the loop is host-
                                        starved (floor 0 = disabled; CPU
                                        images have no meaningful share)
    recompiles         (none)           budget 0: a steady-state chunk that
                                        recompiled is an instant page (the
                                        PR 8 watchdog, now an alert)
    durability_lag     max_lag, budget  binary: any window where a node's
                                        fsync lag (log entries not yet
                                        durable, storage plane) exceeded the
                                        ceiling burns `budget` (ceiling 0 =
                                        disabled; all-zero lag when the
                                        plane is off)

Ratio objectives burn error budget `1 - target`; binary objectives carry an
explicit `budget` (the tolerated trip fraction); budget-0 objectives page on
the first bad eval. Per-objective overrides: `pending_evals` (consecutive met
evals before pending -> firing; default 1, i.e. fire on the 2nd), and
`resolve_evals`. Burn-rate semantics live in burn.py; docs/OBSERVABILITY.md
"Fleet health & SLOs" is the prose version.
"""

from __future__ import annotations

import copy
import json

HEALTH_SPEC_SCHEMA = "health-slo-v1"

SLI_KINDS = (
    "availability",
    "commit_latency",
    "read_staleness",
    "throughput",
    "safety",
    "device_wait_share",
    "recompiles",
    "durability_lag",
)

# The default spec is deliberately quiet on a healthy run of ANY preset:
# the latency/availability targets sit well under what every config tier
# sustains, and the floors that would need per-preset tuning (throughput,
# device-wait share) ship disabled (0) -- a spec file turns them on.
DEFAULT_SPEC = {
    "schema": HEALTH_SPEC_SCHEMA,
    "eval_windows": 2,
    "worst_k": 3,
    "outlier_score": 3.0,
    "resolve_evals": 2,
    "objectives": {
        "availability": {"sli": "availability", "target": 0.9},
        "commit_latency": {
            "sli": "commit_latency", "threshold_ticks": 16, "target": 0.99,
        },
        "read_staleness": {
            "sli": "read_staleness", "stale_after_ticks": 16, "target": 0.99,
        },
        "throughput": {
            "sli": "throughput", "min_ops_per_window": 0, "budget": 0.25,
        },
        "safety": {"sli": "safety", "pending_evals": 0},
        "device_wait": {
            "sli": "device_wait_share", "min_share": 0.0, "budget": 0.25,
        },
        "recompile": {"sli": "recompiles", "pending_evals": 0},
        "durability": {"sli": "durability_lag", "max_lag": 0, "budget": 0.25},
    },
    # Google SRE Workbook ch.5 shape: a fast pair that pages on a steep burn
    # within ~2 eval periods, and a slow pair that catches a 1x bleed over a
    # longer horizon. Windows are counted in EVAL PERIODS, not wall time --
    # the fleet's clock is the telemetry window.
    "rules": [
        {"name": "fast", "short": 1, "long": 2, "burn": 6.0},
        {"name": "slow", "short": 2, "long": 8, "burn": 1.0},
    ],
}


def validate_spec(spec) -> list[str]:
    """Schema-check a spec document ([] = valid): same dependency-free style
    as telemetry_sink.validate -- the schema IS this function."""
    errors = []
    if not isinstance(spec, dict):
        return ["spec must be a JSON object"]
    if spec.get("schema") != HEALTH_SPEC_SCHEMA:
        errors.append(
            f"schema {spec.get('schema')!r}, expected {HEALTH_SPEC_SCHEMA}"
        )
    for k in ("eval_windows", "worst_k", "resolve_evals"):
        v = spec.get(k)
        if not isinstance(v, int) or isinstance(v, bool) or v < 1:
            errors.append(f"{k} must be an int >= 1")
    v = spec.get("outlier_score")
    if not isinstance(v, (int, float)) or isinstance(v, bool) or v <= 0:
        errors.append("outlier_score must be a positive number")
    objectives = spec.get("objectives")
    if not isinstance(objectives, dict) or not objectives:
        errors.append("objectives must be a non-empty map")
        objectives = {}
    for name, obj in objectives.items():
        if not isinstance(obj, dict):
            errors.append(f"objective {name!r} must be a map")
            continue
        kind = obj.get("sli")
        if kind not in SLI_KINDS:
            errors.append(
                f"objective {name!r}: sli {kind!r} (have: {', '.join(SLI_KINDS)})"
            )
            continue
        if kind in ("availability", "commit_latency", "read_staleness"):
            t = obj.get("target")
            if not isinstance(t, (int, float)) or isinstance(t, bool) \
                    or not 0 <= t < 1:
                errors.append(
                    f"objective {name!r}: target must be a number in [0, 1)"
                )
        if kind in ("commit_latency",) and not _pos_int(obj.get("threshold_ticks")):
            errors.append(f"objective {name!r}: threshold_ticks must be int >= 1")
        if kind in ("read_staleness",) and not _pos_int(obj.get("stale_after_ticks")):
            errors.append(f"objective {name!r}: stale_after_ticks must be int >= 1")
        if kind in ("throughput", "device_wait_share", "durability_lag"):
            b = obj.get("budget")
            if not isinstance(b, (int, float)) or isinstance(b, bool) or not 0 < b <= 1:
                errors.append(f"objective {name!r}: budget must be in (0, 1]")
        if kind == "durability_lag":
            ml = obj.get("max_lag")
            if not isinstance(ml, int) or isinstance(ml, bool) or ml < 0:
                errors.append(f"objective {name!r}: max_lag must be int >= 0")
        pe = obj.get("pending_evals")
        if pe is not None and (not isinstance(pe, int) or isinstance(pe, bool) or pe < 0):
            errors.append(f"objective {name!r}: pending_evals must be int >= 0")
    rules = spec.get("rules")
    if not isinstance(rules, list) or not rules:
        errors.append("rules must be a non-empty list")
        rules = []
    names = set()
    for i, r in enumerate(rules):
        if not isinstance(r, dict):
            errors.append(f"rules[{i}] must be a map")
            continue
        if not isinstance(r.get("name"), str) or not r.get("name"):
            errors.append(f"rules[{i}]: name missing")
        elif r["name"] in names:
            errors.append(f"rules[{i}]: duplicate rule name {r['name']!r}")
        else:
            names.add(r["name"])
        if not _pos_int(r.get("short")) or not _pos_int(r.get("long")):
            errors.append(f"rules[{i}]: short/long must be ints >= 1")
        elif r["short"] > r["long"]:
            errors.append(
                f"rules[{i}]: short window {r['short']} > long window "
                f"{r['long']} -- the fast confirmation must be the shorter one"
            )
        b = r.get("burn")
        if not isinstance(b, (int, float)) or isinstance(b, bool) or b <= 0:
            errors.append(f"rules[{i}]: burn must be a positive number")
    return errors


def _pos_int(v) -> bool:
    return isinstance(v, int) and not isinstance(v, bool) and v >= 1


def load_spec(arg: str | dict | None = None) -> dict:
    """Resolve a --health argument to a validated spec dict: None/"default"
    -> a copy of DEFAULT_SPEC; a path -> its JSON; a dict -> itself (tests).
    Raises ValueError naming every schema problem, so a bad spec fails at
    arm time, not mid-soak."""
    if arg is None or arg == "default":
        spec = copy.deepcopy(DEFAULT_SPEC)
    elif isinstance(arg, dict):
        spec = arg
    else:
        with open(arg) as f:
            spec = json.load(f)
    errors = validate_spec(spec)
    if errors:
        raise ValueError(
            "invalid health spec: " + "; ".join(errors)
        )
    return spec
