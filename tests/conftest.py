"""Test env: run JAX on CPU with 8 virtual devices so the multi-chip sharding tier can
be tested without TPU hardware (SURVEY.md section 4). Must run before any jax import in
the test process."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
