"""Pass A: lower the real step/scan programs and audit their jaxprs.

Lowering is tracing only -- no XLA compile, so the whole pass runs in seconds
on CPU even for the N=51 tier (`jax.make_jaxpr` over `raft.step`,
`raft_batched.step_b`, and the jitted `scan.simulate`). The rules encode the
invariants docs/PERF.md shows being lost silently:

  float-op             no floating-point primitive anywhere in an audited
                       program -- step kernels AND full scan programs: the
                       protocol state path is all-integer by design
                       (types.py), and since the uint32 threshold-compare
                       refactor of sim/faults.py the per-tick input pipeline
                       is too. A float sneaking in (a mean, a /, an
                       accidental promotion) is a dtype-discipline break AND a
                       perf hazard.
  plane-widening       no convert_element_type that widens an [N, N]-shaped
                       plane from its policy narrow dtype (int8/int16,
                       types.index_dtype / ack_dtype) into a wider type that
                       persists -- widening straight into a reduction
                       (sum/min/max accumulators) is the one legal form.
  carry-dtype          the scan carry's state planes enter and leave the tick
                       at the policy dtypes (a dropped `.astype(...)` at a
                       plane rebuild shows up here, not in a benchmark).
  carry-passthrough    every loop-invariant carry leg for the config
                       (policy.invariant_leaves) is passed through the scan
                       body UNTOUCHED -- var identity in the body jaxpr. XLA
                       elides untouched legs from the per-tick HBM round trip;
                       rewriting one as fresh values measurably regressed
                       config3 by ~16% in round 4 (docs/PERF.md).
  large-constant       no baked-in constant above a size threshold: a big
                       closed-over table silently bloats every executable and
                       usually means something meant to be computed or carried.
  recompile-fork       tunable-only config changes (fault probabilities,
                       cadences, timer values) must NOT change the lowered
                       program's structure: each (base, variant) pair in
                       FORK_PAIRS lowers the full scan program both ways and
                       compares structural hashes. A Python branch on a tuned
                       value (`if cfg.drop_prob > 0.2: <other algorithm>`)
                       forks one compiled program per sweep point and melts
                       the tier-1 compile budget (~15-40 s per distinct scan
                       program on CPU); this rule fails it statically.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib

import jax
import jax.numpy as jnp

from raft_sim_tpu import types as rst_types
from raft_sim_tpu.analysis import policy
from raft_sim_tpu.analysis.findings import Finding
from raft_sim_tpu.utils.config import PRESETS, RaftConfig

# Every rule slug this pass can emit (run.run_all scopes stale-waiver
# detection to the passes that actually ran).
RULES = frozenset({
    "float-op", "plane-widening", "carry-dtype", "carry-passthrough",
    "large-constant", "recompile-fork", "node-collectives",
})

# Reduction primitives a widening convert may legally feed: the widened plane
# is an accumulator XLA fuses into the reduce, never a materialized tensor.
REDUCERS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_and", "reduce_or",
    "reduce_prod", "argmax", "argmin",
})

# Baked-in constants above this are flagged (rule large-constant). The largest
# legitimate consts today are the [N, N] eye / [N, W] bit-weight planes --
# ~2.6 KB at N=51; anything past 64 KiB is a table that should be computed,
# carried, or fed as an input.
LARGE_CONST_BYTES = 64 * 1024

# Scan-program shape used for audits: small batch/ticks keep tracing fast and
# have no effect on the audited structure (shapes scale, programs don't).
_AUDIT_BATCH = 8
_AUDIT_TICKS = 32
# Telemetry window of the audited SERVE program (the served scan folds window
# records on device, telemetry-style): shape-like static, ticks must divide.
_AUDIT_WINDOW = 16
# Canonical scenario-program shape for the audited genome path: S segments of
# SEG_LEN ticks. S/seg_len are shape-like statics (a different S is a new
# program, like a different batch); genome VALUES are traced and can never
# fork a compile -- which is the whole point, and what the scenario fork
# check below pins.
_AUDIT_SEGMENTS = 2
_AUDIT_SEG_LEN = 16
# Event-ring depth of the audited TRACE program (trace/ring.py TraceSpec):
# shape-like static, small to keep the audit lowering fast -- depth scales
# the carry planes, never the program structure.
_AUDIT_TRACE_DEPTH = 32

# (preset, replacements) pairs for rule recompile-fork: every replacement is a
# pure tuning-knob change (probabilities, cadences, horizons) that must lower
# to a structurally identical scan program. Values are chosen to stay on the
# same side of every structural gate (> 0 checks, dtype ceilings like
# ack_age_sat's int8 tier and index_dtype's capacity tiers).
FORK_PAIRS: tuple[tuple[str, dict], ...] = (
    ("config2", {"client_interval": 12}),
    ("config3", {"heartbeat_ticks": 4, "ack_timeout_ticks": 16}),
    ("config4", {"drop_prob": 0.23, "clock_skew_prob": 0.13}),
    ("config5", {"partition_prob": 0.4}),
    # Compacted layout tier: tuning knobs inside the layout gate must not
    # fork (the pack/unpack boundary is shape-driven, never value-driven).
    ("config5c", {"partition_prob": 0.4}),
    ("config6", {"crash_prob": 0.2, "drop_prob": 0.15}),
    ("config6r", {"client_interval": 8, "crash_down_ticks": 10}),
    # Reconfiguration plane: the admin cadences are tuning knobs (values stay
    # nonzero -- the structural gates are `> 0` checks by design, like
    # client_interval), so retiming membership changes / transfers / reads
    # must never fork a compile (the scenario genome retimes them as data).
    ("config8", {
        "reconfig_interval": 53, "transfer_interval": 31, "read_interval": 5,
        "drop_prob": 0.15,
    }),
    # Lease reads: the lease TERM is a tuning knob inside its structural
    # gate (read_lease_ticks > 0, under the skew-safe ceiling) exactly like
    # the cadences -- retiming the lease must never fork a compile.
    ("config9", {
        "read_lease_ticks": 3, "read_interval": 5, "client_interval": 6,
        "clock_skew_prob": 0.2,
    }),
    # Durable storage plane: the fsync cadence and every disk-fault
    # probability are tuning knobs inside the structural gate
    # (fsync_interval > 0) -- retiming flushes or reshaping the disk-fault
    # lattice must never fork a compile. lost_suffix_span stays a traced
    # randint bound (precedent: crash_down_ticks).
    ("config10", {
        "fsync_interval": 5, "fsync_jitter_prob": 0.35,
        "torn_tail_prob": 0.15, "lost_suffix_span": 5, "crash_prob": 0.2,
    }),
)


# ---------------------------------------------------------------- program zoo


def _step_avals(cfg: RaftConfig, batch: int | None):
    state, inputs, _ = policy.state_avals(cfg)
    if batch is not None:
        addb = lambda x: jax.ShapeDtypeStruct(tuple(x.shape) + (batch,), x.dtype)
        state = jax.tree.map(addb, state)
        inputs = jax.tree.map(addb, inputs)
    return state, inputs


@functools.lru_cache(maxsize=None)
def step_jaxpr(cfg: RaftConfig, batched: bool = False):
    """ClosedJaxpr of one tick: `raft.step` (vmap form, per-cluster shapes) or
    `raft_batched.step_b` (batch-minor, trailing batch axis). Cached per
    (cfg, form): tracing dominates the gate's runtime and the rules, the fork
    guard, and the golden tests all want the same programs."""
    from raft_sim_tpu.models import raft, raft_batched

    if batched:
        state, inputs = _step_avals(cfg, _AUDIT_BATCH)
        fn = functools.partial(raft_batched.step_b, cfg)
    else:
        state, inputs = _step_avals(cfg, None)
        fn = functools.partial(raft.step, cfg)
    return jax.make_jaxpr(fn)(state, inputs)


@functools.lru_cache(maxsize=None)
def scan_jaxpr(cfg: RaftConfig, batch: int = _AUDIT_BATCH, ticks: int = _AUDIT_TICKS):
    """ClosedJaxpr of the full batched run (`scan.simulate`: init + batch-minor
    scan), traced through its jit wrapper. Cached: the per-tier rules and the
    recompile-fork guard audit the same base programs."""
    from raft_sim_tpu.sim import scan

    seed = jax.ShapeDtypeStruct((), jnp.int32)
    return jax.make_jaxpr(lambda s: scan.simulate(cfg, s, batch, ticks))(seed)


def _genome_avals(batch: int, s_count: int):
    from raft_sim_tpu.scenario.genome import ScenarioGenome, leaf_dtype

    return ScenarioGenome(**{
        f: jax.ShapeDtypeStruct((batch, s_count), leaf_dtype(f))
        for f in ScenarioGenome._fields
    })


@functools.lru_cache(maxsize=None)
def scenario_scan_jaxpr(
    cfg: RaftConfig,
    batch: int = _AUDIT_BATCH,
    ticks: int = _AUDIT_TICKS,
    s_count: int = _AUDIT_SEGMENTS,
    seg_len: int = _AUDIT_SEG_LEN,
):
    """ClosedJaxpr of the scenario-engine run (`scan.simulate_scenario`: the
    genome input path, every fault mechanism traced). The genome enters as
    `[B, S]` avals -- its VALUES are invisible to lowering, so one program
    serves the whole heterogeneous fleet; the same carry template as the
    plain scan (the genome rides the body as loop constants, never carry)."""
    from raft_sim_tpu.sim import scan

    seed = jax.ShapeDtypeStruct((), jnp.int32)
    gen = _genome_avals(batch, s_count)
    return jax.make_jaxpr(
        lambda s, g: scan.simulate_scenario(cfg, s, batch, ticks, g, seg_len)
    )(seed, gen)


def serve_variant(cfg: RaftConfig) -> RaftConfig:
    """The serve-mode config a tier's serve program is audited under (external
    ingest replaces the scheduled cadence; the offer-tick plane goes live)."""
    from raft_sim_tpu.serve.loop import serve_config

    return serve_config(cfg)


@functools.lru_cache(maxsize=None)
def serve_scan_jaxpr(
    cfg: RaftConfig,
    batch: int = _AUDIT_BATCH,
    ticks: int = _AUDIT_TICKS,
    window: int = _AUDIT_WINDOW,
):
    """ClosedJaxpr of the standing-fleet serve program
    (`serve.loop.simulate_serve`: init + served windowed scan). The offer
    plane enters as a [ticks, batch] int32 aval (the batch axis IS the
    tenancy axis: each cluster gets its tenant's own command per tick) --
    command VALUES and the tenant PARTITION are invisible to lowering, so one
    compiled chunk program serves the whole session at any tenant count and a
    multi-chunk `driver serve` run compiles nothing after warmup (the claim
    the distinct-lowering pin gates). Read-carrying serve variants
    (cfg.read_index: serve_reads / a scheduled cadence collapsed by
    serve_config) additionally take the [ticks, batch] read plane. NOTE:
    callers pass the SERVE-mode config (`serve_variant`), which is also the
    config the carry rules run under -- the offer-tick plane legs move here
    by design."""
    from raft_sim_tpu.serve import loop as serve_loop

    seed = jax.ShapeDtypeStruct((), jnp.int32)
    cmds = jax.ShapeDtypeStruct((ticks, batch), jnp.int32)
    if cfg.read_index:
        reads = jax.ShapeDtypeStruct((ticks, batch), jnp.int32)
        return jax.make_jaxpr(
            lambda s, c, r: serve_loop.simulate_serve(cfg, s, batch, c, window, r)
        )(seed, cmds, reads)
    return jax.make_jaxpr(
        lambda s, c: serve_loop.simulate_serve(cfg, s, batch, c, window)
    )(seed, cmds)


def trace_variant(cfg: RaftConfig) -> RaftConfig:
    """The trace-mode config a tier's traced program is audited under
    (cfg.track_trace raised; nothing else moves -- with it off the tier's
    standing programs carry NO trace leg, which the unchanged simulate/
    scenario/serve pins prove every gate run)."""
    return dataclasses.replace(cfg, track_trace=True)


@functools.lru_cache(maxsize=None)
def trace_scan_jaxpr(
    cfg: RaftConfig,
    batch: int = _AUDIT_BATCH,
    ticks: int = _AUDIT_TICKS,
    window: int = _AUDIT_WINDOW,
    depth: int = _AUDIT_TRACE_DEPTH,
):
    """ClosedJaxpr of the protocol-trace program (`telemetry.simulate_windowed`
    with a TraceSpec: the windowed scan plus the event ring + coverage carry
    legs, trace/ring.py). NOTE: callers pass the TRACE-mode config
    (`trace_variant`) -- the trace legs exist only there, and the carry rules
    run under it so their cost is a pinned number, not prose."""
    from raft_sim_tpu.sim import telemetry
    from raft_sim_tpu.trace.ring import TraceSpec

    seed = jax.ShapeDtypeStruct((), jnp.int32)
    spec = TraceSpec(depth=depth)
    return jax.make_jaxpr(
        lambda s: telemetry.simulate_windowed(
            cfg, s, batch, ticks, window, 0, None, 1, spec
        )
    )(seed)


def trace_extra_legs() -> int:
    """Auxiliary carry legs the trace program's tick loop rides beyond the
    (state, metrics) template: the window first-violation tick plus the
    TraceWin/TracePersist leaves (trace/ring.py)."""
    from raft_sim_tpu.trace.ring import TracePersist, TraceWin

    return 1 + len(TraceWin._fields) + len(TracePersist._fields)


def programs(name: str, cfg: RaftConfig):
    """The audited programs for one config tier: both step kernels, the full
    scan, the scenario (genome-path) scan, the standing-fleet serve scan, and
    the protocol-trace scan. Yields (program_name, closed_jaxpr, kind,
    rule_cfg) -- `rule_cfg` is the config the per-program rules (carry
    passthrough/dtype, input pricing) run under: the tier's own config,
    except for the serve/trace programs, which are audited under their
    serve-mode / trace-mode variants (offer-tick plane / trace legs live)."""
    yield f"jaxpr:{name}/step", step_jaxpr(cfg, batched=False), "step", cfg
    yield f"jaxpr:{name}/step_b", step_jaxpr(cfg, batched=True), "step", cfg
    yield f"jaxpr:{name}/simulate", scan_jaxpr(cfg), "scan", cfg
    yield f"jaxpr:{name}/scenario_simulate", scenario_scan_jaxpr(cfg), "scan", cfg
    scfg = serve_variant(cfg)
    yield f"jaxpr:{name}/serve_simulate", serve_scan_jaxpr(scfg), "serve_scan", scfg
    tcfg = trace_variant(cfg)
    yield f"jaxpr:{name}/trace_simulate", trace_scan_jaxpr(tcfg), "trace_scan", tcfg


# ------------------------------------------------------------- jaxpr walking


def _sub_jaxprs(eqn):
    for v in eqn.params.values():
        for sub in v if isinstance(v, (list, tuple)) else (v,):
            if hasattr(sub, "jaxpr"):  # ClosedJaxpr
                yield sub.jaxpr
            elif hasattr(sub, "eqns"):  # raw Jaxpr
                yield sub


def iter_eqns(jaxpr):
    """Every eqn of `jaxpr` and its nested sub-jaxprs (pjit/scan/cond bodies),
    depth-first."""
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn):
            yield from iter_eqns(sub)


def iter_consts(closed):
    """Every baked-in constant of a ClosedJaxpr, including nested bodies.
    Yields (path-ish depth marker, const)."""
    for c in closed.consts:
        yield c
    for eqn in iter_eqns(closed.jaxpr):
        for v in eqn.params.values():
            for sub in v if isinstance(v, (list, tuple)) else (v,):
                if hasattr(sub, "consts"):
                    for c in sub.consts:
                        yield c


def op_histogram(closed) -> dict[str, int]:
    """Primitive counts bucketed by output dtype: `{"prim dtype": count}` over
    the whole program including nested bodies. The golden-snapshot currency:
    a new [N, N, B] materialization or a dtype flip shows up as a reviewable
    count diff, not a benchmark surprise."""
    hist: dict[str, int] = {}
    for eqn in iter_eqns(closed.jaxpr):
        out = eqn.outvars[0]
        dt = str(out.aval.dtype) if hasattr(out.aval, "dtype") else "abstract"
        key = f"{eqn.primitive.name} {dt}"
        hist[key] = hist.get(key, 0) + 1
    return hist


def _param_digest(params) -> str:
    """Stable rendering of an eqn's non-jaxpr params (axes, dimension
    numbers, dtypes, paddings -- the structural knobs that do not show in
    avals). Sub-jaxprs are replaced by a marker (they are walked separately);
    only comparable within one process (callable reprs carry addresses)."""
    parts = []
    for k in sorted(params):
        v = params[k]
        vals = v if isinstance(v, (list, tuple)) else (v,)
        if any(hasattr(s, "jaxpr") or hasattr(s, "eqns") for s in vals):
            parts.append(f"{k}=<jaxpr>")
        else:
            parts.append(f"{k}={v!r}")
    return ";".join(parts)


def structural_hash(closed) -> str:
    """Hash of the program's structure: the depth-first sequence of
    (primitive, params, input avals, output avals). Literal VALUES are
    excluded (a literal contributes only its shape/dtype via its aval), so
    two lowerings that differ only in baked tuning constants --
    probabilities, cadences, thresholds -- hash equal, while any change to
    the op sequence, a shape, a dtype, or a primitive's structural params
    (reduce axes, gather dimension numbers, paddings) forks the hash.
    Process-local (param reprs may embed addresses): compare hashes from the
    same run only."""
    h = hashlib.sha256()
    for eqn in iter_eqns(closed.jaxpr):
        h.update(eqn.primitive.name.encode())
        h.update(_param_digest(eqn.params).encode())
        for v in (*eqn.invars, *eqn.outvars):
            aval = getattr(v, "aval", None)
            if aval is not None and hasattr(aval, "shape"):
                h.update(str((tuple(aval.shape), str(aval.dtype))).encode())
    return h.hexdigest()[:16]


def program_hash(closed) -> str:
    """Cache-key-like hash: the full jaxpr text (literals included). Two
    identical hashes => one jit compile can serve both."""
    return hashlib.sha256(str(closed).encode()).hexdigest()[:16]


# -------------------------------------------------------------------- rules


def check_float_ops(program: str, closed) -> list[Finding]:
    """Rule float-op: step kernels are all-integer by design."""
    out = []
    for eqn in iter_eqns(closed.jaxpr):
        for v in (*eqn.invars, *eqn.outvars):
            aval = getattr(v, "aval", None)
            if aval is None or not hasattr(aval, "dtype"):
                continue
            if jnp.issubdtype(aval.dtype, jnp.floating):
                out.append(Finding(
                    rule="float-op",
                    path=program,
                    message=(
                        f"float dtype {aval.dtype} at primitive "
                        f"'{eqn.primitive.name}' (shape {tuple(aval.shape)}): "
                        "the protocol-state path is integer-only (types.py)"
                    ),
                ))
                break  # one finding per eqn is enough
    return out


def _has_nn_pair(shape, n: int) -> bool:
    return any(shape[i] == n and shape[i + 1] == n for i in range(len(shape) - 1))


def check_plane_widening(program: str, closed, cfg: RaftConfig) -> list[Finding]:
    """Rule plane-widening: top-level convert_element_type eqns that widen an
    [N, N]-shaped int8/int16 plane, unless every consumer is a reduction (the
    widen-into-accumulator form XLA fuses away). Top level is where the
    kernels' own `.astype` discipline lives; jnp-internal promotions in nested
    bodies feed reductions by construction."""
    n = cfg.n_nodes
    consumers: dict = {}
    for eqn in closed.jaxpr.eqns:
        for v in eqn.invars:
            if hasattr(v, "count"):
                consumers.setdefault(v, []).append(eqn.primitive.name)
    escaping = set(v for v in closed.jaxpr.outvars if hasattr(v, "count"))
    out = []
    for eqn in closed.jaxpr.eqns:
        if eqn.primitive.name != "convert_element_type":
            continue
        src, dst = eqn.invars[0].aval, eqn.outvars[0].aval
        if src.dtype not in (jnp.int8, jnp.int16):
            continue
        if dst.dtype.itemsize <= src.dtype.itemsize:
            continue
        if not _has_nn_pair(tuple(src.shape), n):
            continue
        cons = consumers.get(eqn.outvars[0], [])
        if cons and all(c in REDUCERS for c in cons) and eqn.outvars[0] not in escaping:
            continue
        out.append(Finding(
            rule="plane-widening",
            path=program,
            message=(
                f"[N,N] plane widened {src.dtype} -> {dst.dtype} "
                f"(shape {tuple(src.shape)}, consumers {cons or ['<returned>']}): "
                "policy dtypes (types.index_dtype/ack_dtype) must persist; "
                "widening is only legal straight into a reduction"
            ),
        ))
    return out


def _find_scan(jaxpr, num_carry: int):
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "scan" and eqn.params["num_carry"] == num_carry:
            return eqn
        for sub in _sub_jaxprs(eqn):
            found = _find_scan(sub, num_carry)
            if found is not None:
                return found
    return None


def check_carry_passthrough(
    program: str, closed, cfg: RaftConfig, extra_legs: int = 0
) -> list[Finding]:
    """Rule carry-passthrough: in the run scan's body, every leg
    policy.invariant_leaves names for this config must be the SAME var in and
    out (identity passthrough -- XLA then elides it from the per-tick HBM
    round trip). Also rule carry-dtype: carried state planes hold their policy
    dtypes. `extra_legs` selects a TICK loop whose carry rides auxiliary legs
    after the (state, metrics) template -- the serve program's inner window
    scan carries the first-violation tick (serve/loop.py), so its tick loop
    has len(names) + 1 legs while its outer window loop (where passthrough
    legs are fresh scan outputs by construction) has exactly len(names)."""
    names = policy.carry_leaf_names()
    want = len(names) + extra_legs
    eqn = _find_scan(closed.jaxpr, want)
    if eqn is None:
        return [Finding(
            rule="carry-passthrough",
            path=program,
            message=(
                f"no scan with the expected {want}-leg carry found; the "
                "run-loop structure changed -- update analysis/policy.py's "
                "carry template alongside it"
            ),
        )]
    body = eqn.params["jaxpr"].jaxpr
    nc, nk = eqn.params["num_consts"], eqn.params["num_carry"]
    carry_in = body.invars[nc:nc + nk]
    carry_out = body.outvars[:nk]
    identity = {nm for nm, a, b in zip(names, carry_in, carry_out) if a is b}
    out = []
    for nm in sorted(policy.invariant_leaves(cfg)):
        if nm not in identity:
            out.append(Finding(
                rule="carry-passthrough",
                path=program,
                message=(
                    f"carry leg '{nm}' should be loop-invariant for this "
                    "config but is rewritten inside the scan body: pass the "
                    "old value through untouched so XLA elides its HBM round "
                    "trip (docs/PERF.md, round-4 lesson)"
                ),
            ))
    # carry-dtype: the narrow-plane policy, checked on the carried avals.
    expect = {
        "next_index": jnp.dtype(rst_types.index_dtype(cfg)),
        "match_index": jnp.dtype(rst_types.index_dtype(cfg)),
        "ack_age": jnp.dtype(rst_types.ack_dtype(cfg)),
        "mb.a_match": jnp.dtype(rst_types.index_dtype(cfg)),
        "mb.a_hint": jnp.dtype(rst_types.index_dtype(cfg)),
        "mb.req_off": jnp.dtype(jnp.int8),
        "mb.resp_kind": jnp.dtype(jnp.int8),
        "votes": jnp.dtype(jnp.uint32),
        "mb.pv_grant": jnp.dtype(jnp.uint32),
    }
    if cfg.compact_planes:
        # Compacted carry layout (ops/tile.py): the transformed legs ride
        # flat uint32 word vectors -- the dense narrow dtypes live INSIDE
        # the tick body (unpack at entry, repack at exit), so the carried
        # avals are expected at the packed dtypes instead.
        from raft_sim_tpu.ops import tile

        expect.update(tile.packed_carry_dtypes(cfg))
    for nm, v in zip(names, carry_out):
        want = expect.get(nm)
        if want is not None and v.aval.dtype != want:
            out.append(Finding(
                rule="carry-dtype",
                path=program,
                message=(
                    f"carried plane '{nm}' leaves the tick as {v.aval.dtype}, "
                    f"policy dtype is {want} (types.py)"
                ),
            ))
    return out


def check_large_constants(program: str, closed) -> list[Finding]:
    """Rule large-constant: baked-in arrays above LARGE_CONST_BYTES."""
    out = []
    for c in iter_consts(closed):
        nbytes = getattr(c, "nbytes", 0)
        if nbytes > LARGE_CONST_BYTES:
            out.append(Finding(
                rule="large-constant",
                path=program,
                message=(
                    f"baked-in constant of {nbytes} bytes (shape "
                    f"{getattr(c, 'shape', '?')}, dtype {getattr(c, 'dtype', '?')}) "
                    f"exceeds {LARGE_CONST_BYTES} B: compute it, carry it, or "
                    "feed it as an input instead of baking it into every "
                    "executable"
                ),
            ))
    return out


def check_recompile_forks(pairs=FORK_PAIRS) -> list[Finding]:
    """Rule recompile-fork: each (preset, tuning replacement) pair must lower
    to structurally identical programs -- for the plain scan AND the scenario
    (genome-path) scan. The scenario check is the stronger claim: the genome
    path exists so that fault-space sweeps are pure data, so ANY tuned-value
    leak into its structure would resurrect exactly the per-point recompile
    the scenario engine removes (one compile per genome/segment is the
    failure mode ISSUE 4 forbids)."""
    out = []
    for name, repl in pairs:
        base, _ = PRESETS[name]
        variant = dataclasses.replace(base, **repl)
        for label, lower in (
            ("simulate", scan_jaxpr),
            ("scenario_simulate", scenario_scan_jaxpr),
            # The serve loop's zero-recompiles-after-warmup claim, statically:
            # a tuned value leaking into the serve chunk's structure would
            # recompile the standing fleet mid-session.
            ("serve_simulate", lambda c: serve_scan_jaxpr(serve_variant(c))),
            # The coverage search's one-compiled-program claim: trace-mode
            # evaluations across a fault sweep must share a program too.
            ("trace_simulate", lambda c: trace_scan_jaxpr(trace_variant(c))),
        ):
            h_base = structural_hash(lower(base))
            h_var = structural_hash(lower(variant))
            if h_base != h_var:
                out.append(Finding(
                    rule="recompile-fork",
                    path=f"jaxpr:{name}/{label}",
                    message=(
                        f"tuning-only change {repl} forked the lowered program "
                        f"structure ({h_base} -> {h_var}): a Python branch or a "
                        "shape now depends on a tuned value, so every sweep point "
                        "would recompile (~15-40 s each on CPU, tier-1 budget)"
                    ),
                ))
    return out


def check_node_collectives(
    name: str, cfg: RaftConfig, mesh, batch: int = _AUDIT_BATCH,
    ticks: int = _AUDIT_TICKS,
) -> list[Finding]:
    """Rule node-collectives: the node-sharded program's ONLY inter-device
    primitives are the whitelisted ones -- the tiled mailbox/invariant
    all_gathers and the metric psum/pmin/pmax folds (parallel/nodeshard.py's
    layout contract). A ppermute, all_to_all, or reduce_scatter sneaking into
    the tick loop means a reduction stopped being receiver-local -- the exact
    regression the row-partition layout exists to make impossible. Needs a
    live multi-device "nodes" mesh to lower (the CI mesh-smoke job and
    tests/test_nodeshard.py run under 8 forced CPU devices); callers gate on
    device count, this function does not."""
    from raft_sim_tpu.parallel import nodeshard

    seed = jax.ShapeDtypeStruct((), jnp.int32)
    closed = jax.make_jaxpr(
        lambda s: nodeshard.simulate_node_sharded(cfg, s, batch, ticks, mesh)
    )(seed)
    seen = {
        eqn.primitive.name
        for eqn in iter_eqns(closed.jaxpr)
        if eqn.primitive.name in NODE_COLLECTIVE_KINDS
    }
    bad = sorted(seen - NODE_COLLECTIVE_WHITELIST)
    return [
        Finding(
            rule="node-collectives",
            path=f"jaxpr:{name}/node_sharded_simulate",
            message=(
                f"node-sharded program lowered non-whitelisted collective(s) "
                f"{bad}: the hot loop's inter-device traffic must stay the "
                "mailbox/invariant all_gathers + metric psum/pmin/pmax folds "
                "(parallel/nodeshard.py layout rules, docs/DESIGN.md)"
            ),
        )
    ] if bad else []


# Named-axis primitives the node-collectives rule classifies as inter-device
# communication (axis_index is positional metadata, not traffic, but is
# whitelisted explicitly so a future jax rename fails loudly as non-listed).
NODE_COLLECTIVE_KINDS = frozenset({
    "all_gather", "psum", "pmin", "pmax", "ppermute", "all_to_all",
    "reduce_scatter", "pbroadcast", "pgather", "axis_index",
})
NODE_COLLECTIVE_WHITELIST = frozenset({
    "all_gather", "psum", "pmin", "pmax", "axis_index",
})


# --------------------------------------------------------------- entry point

# The config tiers Pass A audits by default: one per structural family --
# plain (config3), wide + partitions + sampled log matching (config5),
# client + log matching (config1), faults (config4), compaction + crash
# (config6), redirect pipeline (config6r).
# config8 adds the reconfiguration-plane family (joint-consensus membership +
# TimeoutNow + ReadIndex legs live).
# config9 adds the lease-read family (lease serve predicate, vote denial,
# read_fr staleness leg -- compaction + offer-tick plane live too).
# config5c adds the compacted-carry-layout family (ops/tile.py: the config5
# workload with the per-edge planes bit-packed into flat uint32 legs) -- the
# tier whose Pass C pin IS the layout's predicted bytes/tick verdict
# (docs/PERF.md "the config5 roofline").
# config7/config7x add the giant-N family (N=101 threshold-quorum and the
# N=255 ceiling under the compacted layout): the single-chip programs audited
# here, the per-device mesh bytes priced by Pass C's mesh section, and the
# node-sharded program's collective whitelist checked whenever a multi-device
# mesh is live (check_node_collectives).
# config10 adds the durable-storage family (raft_sim_tpu/storage: the
# dur_len/dur_term/dur_vote watermark legs, the section-3.8 ack/grant gates,
# crash recovery's truncate-and-rewind, and the fsync/torn-tail disk-fault
# draws live).
AUDIT_CONFIGS = (
    "config1", "config3", "config4", "config5", "config5c", "config6",
    "config6r", "config7", "config7x", "config8", "config9", "config10",
)


def run_pass(config_names=AUDIT_CONFIGS, fork_pairs=FORK_PAIRS) -> list[Finding]:
    """The full jaxpr pass: per-tier program rules + the fork guard. Since the
    round-7 threshold-compare refactor (sim/faults.py) the ENTIRE input
    pipeline is integer too, so the float-op rule runs on every audited
    program -- scans included -- not just the step kernels."""
    out: list[Finding] = []
    for name in config_names:
        cfg, _ = PRESETS[name]
        for prog, closed, kind, rule_cfg in programs(name, cfg):
            out.extend(check_float_ops(prog, closed))
            if kind == "step":
                out.extend(check_plane_widening(prog, closed, rule_cfg))
            else:
                # The serve program's tick loop rides one auxiliary carry leg
                # (the window's first-violation tick -- serve/loop.py); the
                # trace program rides that plus the event ring + coverage
                # legs (trace/ring.py).
                extra = {"serve_scan": 1, "trace_scan": trace_extra_legs()}.get(
                    kind, 0
                )
                out.extend(
                    check_carry_passthrough(prog, closed, rule_cfg, extra_legs=extra)
                )
            out.extend(check_large_constants(prog, closed))
    out.extend(check_recompile_forks(fork_pairs))
    # The node-sharded program's collective whitelist, whenever this process
    # can lower one (>= 2 devices: the CI mesh-smoke job and the test suite
    # force 8 CPU devices; a single-device run skips it silently -- the gate
    # still runs wherever the sharded program can actually exist).
    if len(jax.devices()) >= 2 and "config7" in config_names:
        from raft_sim_tpu.parallel import nodeshard

        n_dev = 1 << (len(jax.devices()).bit_length() - 1)
        mesh = nodeshard.make_node_mesh(n_dev)
        cfg, _ = PRESETS["config7"]
        out.extend(check_node_collectives("config7", cfg, mesh))
    return out
