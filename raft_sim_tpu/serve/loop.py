"""The standing-fleet service loop: streaming ingest in, telemetry + deltas out.

`driver serve`'s engine -- the sixth subsystem's core. One compiled scan
program (`run_windowed_served`) advances the whole fleet chunk by chunk with
the per-tick client command coming from an EXPLICIT [T] offer plane (scan xs)
instead of the scheduled cadence, folding telemetry windows on device exactly
like sim/telemetry.py. Around it, `ServeSession` runs the double-buffered
host<->device exchange ISSUE 6 specifies:

    dispatch chunk k (async)  ->  pack chunk k+1's offer plane from the
    ingest queue while the device runs  ->  collect chunk k's telemetry
    windows + commit deltas  ->  repeat.

Buffer discipline matches the other long-horizon loops: the previous chunk's
fleet state is DONATED (`_serve_chunk`, pinned by the cost model's donation
audit), so a standing service holds ONE fleet in HBM; the ingest plane and the
delta watermark are the only per-chunk host traffic. After warmup the loop
compiles NOTHING: chunk shape, window, and config are fixed, commands are
traced data (the distinct-lowering pin in tests/golden_jaxpr_hist.json gates
this, and tests/test_serve.py asserts the jit cache stays at one entry).
"""

from __future__ import annotations

import dataclasses
import functools
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_sim_tpu.models import raft_batched
from raft_sim_tpu.serve import deltas as deltas_mod
from raft_sim_tpu.serve.ingest import CommandSource
from raft_sim_tpu.sim import scan
from raft_sim_tpu.sim.chunked import _own_copy, merge_metrics
from raft_sim_tpu.sim.telemetry import NEVER, WindowRecord
from raft_sim_tpu.types import NIL
from raft_sim_tpu.utils.config import RaftConfig


def serve_config(cfg: RaftConfig) -> RaftConfig:
    """The serve-mode variant of a config: external ingest replaces the
    scheduled cadences (client_interval forced 0 -- ALL write traffic is
    offered -- and, when the config carries the ReadIndex plane, the
    scheduled read cadence collapses into serve_reads the same way), with
    the offer-tick plane kept live via serve_ingest."""
    repl: dict = {}
    if not (cfg.serve_ingest and cfg.client_interval == 0):
        repl.update(serve_ingest=True, client_interval=0)
    if cfg.read_index and not (cfg.serve_reads and cfg.read_interval == 0):
        # Reads become externally offered too (per-tenant read planes /
        # Session.offer_read) -- the read-side mirror of the write collapse.
        repl.update(serve_reads=True, read_interval=0)
    return dataclasses.replace(cfg, **repl) if repl else cfg


def run_windowed_served(cfg: RaftConfig, state, keys, cmds, window: int,
                        reads=None):
    """Scan the fleet through one chunk of `cmds` ([T, B] int32 per-cluster
    offer plane, NIL = no offer in that (tick, cluster) slot -- the batch
    axis IS the tenancy axis), emitting one WindowRecord per `window` ticks.
    `reads` ([T, B] int32, 1 = offer a ReadIndex read, NIL = none; requires
    cfg.read_index) is the read-side plane: None on write-only configs, so
    their programs carry no read leg.

    Same shared tick body as every other loop (scan.tick_batch_minor with the
    per-tick client_cmd/read_cmd overrides Session.offer/offer_read use), so
    the served path can never drift from run(); same window algebra as
    telemetry.run_batch_minor_telemetry, so the streamed records merge
    bit-exactly into run-level metrics. T must divide by `window`.
    Returns (final_state, chunk_metrics, records) in public [B, ...] layouts.
    """
    n_ticks = cmds.shape[0]
    if n_ticks % window:
        raise ValueError(f"chunk of {n_ticks} ticks must divide by window {window}")
    if reads is not None and not cfg.read_index:
        raise ValueError(
            "a read plane needs the ReadIndex gate (cfg.serve_reads or a "
            "read cadence) -- utils/config.py"
        )
    batch = state.role.shape[0]
    s_t = raft_batched.to_batch_minor(state)
    m0 = raft_batched.to_batch_minor(scan.init_metrics_batch(batch))

    def inner(carry, xs):
        s, wm, fv = carry
        cmd, read = xs if reads is not None else (xs, None)
        now = s.now  # [B] absolute tick BEFORE the step (lockstep across B)
        s2, wm2, info = scan.tick_batch_minor(
            cfg, s, keys, wm, client_cmd=cmd, read_cmd=read
        )
        fv2 = jnp.minimum(fv, jnp.where(scan.step_bad(info), now, NEVER))
        return (s2, wm2, fv2), None

    def outer(carry, xs_win):
        s, m = carry
        start = s.now
        fv0 = jnp.full((batch,), NEVER, jnp.int32)
        (s2, wm, fv), _ = lax.scan(inner, (s, m0, fv0), xs_win)
        out = WindowRecord(start=start, first_viol_tick=fv, metrics=wm)
        return (s2, merge_metrics(m, wm)), out

    cmd_wins = cmds.reshape(n_ticks // window, window, batch)
    xs = (
        (cmd_wins, reads.reshape(n_ticks // window, window, batch))
        if reads is not None
        else cmd_wins
    )
    (final_t, metrics), recs = lax.scan(outer, (s_t, m0), xs)
    return (
        raft_batched.from_batch_minor(final_t),
        raft_batched.from_batch_minor(metrics),
        raft_batched.from_batch_minor(recs),
    )


@functools.partial(jax.jit, static_argnums=(0, 5), donate_argnums=(1,))
def _serve_chunk(cfg: RaftConfig, state, keys, cmds, reads, window: int):
    """The steady-state serve chunk: the previous chunk's fleet is DONATED
    back to XLA (one fleet in HBM, like chunked._chunk_donate -- donation
    status pinned by the cost model's `cost-donation` rule). `keys` and the
    offer/read planes are never donated."""
    return run_windowed_served(cfg, state, keys, cmds, window, reads=reads)


@functools.partial(jax.jit, static_argnums=(0, 2, 4))
def simulate_serve(cfg: RaftConfig, seed, batch: int, cmds, window: int,
                   reads=None):
    """One-call served simulation from a seed: init + served windowed scan.
    The audit entry the static gates lower (`jaxpr_audit.serve_scan_jaxpr` ->
    Pass A rules + Pass C pricing) and the parity-test entry (two runs
    differing only in offer VALUES share this one compiled program)."""
    root = jax.random.key(seed)
    k_init, k_run = jax.random.split(root)
    from raft_sim_tpu.types import init_batch

    state = init_batch(cfg, k_init, batch)
    keys = jax.random.split(k_run, batch)
    return run_windowed_served(cfg, state, keys, cmds, window, reads=reads)


class ServeSession:
    """A standing fleet accepting streamed commands between chunks.

    >>> s = ServeSession(RaftConfig(n_nodes=5), batch=8, seed=0, chunk=128)
    >>> stats = s.serve(CommandSource([7, 7, 2**31 - 1]), chunks=4)
    >>> s.delta_rows  # every cluster's committed (index, value, tick) stream

    Multi-tenant form (serve/tenancy.py): partition the cluster range among
    named tenants, each with its own source, read demand, and export streams
    -- one compiled program either way (the batch axis is the tenancy axis):

    >>> from raft_sim_tpu.serve.tenancy import Tenant
    >>> s = ServeSession(cfg, batch=8, tenants=[
    ...     Tenant("a", 4, source=[1, 2, 3]), Tenant("b", 4, reads=100)])
    >>> stats = s.serve()

    The steady-state loop is OVERLAPPED: while chunk k computes on device,
    the host exports chunk k-1's windows + delta rows and packs chunk k+1's
    planes (both timed into the perf row's host_s -- the dispatch->sync
    window -- via ChunkTimer.annotate, so the overlap structure is a
    perf.jsonl fact tests assert, not prose), and chunk k's delta
    extraction rounds are enqueued behind it on the device stream
    (DeltaStream.begin_rounds). Only the sync on chunk k's metrics and the
    dispatch of chunk k+1 sit on the serial path.

    `sink` (a utils/telemetry_sink.TelemetrySink) streams telemetry windows to
    windows.jsonl and commit deltas to deltas.jsonl continuously -- the
    schema'd export surface, validated by the CI serve smoke job; with
    tenants, per-tenant views land under tenants/<name>/ (tenancy.py).
    """

    def __init__(
        self,
        cfg: RaftConfig,
        batch: int = 1,
        seed: int = 0,
        chunk: int = 256,
        window: int = 64,
        delta_depth: int = 64,
        sink=None,
        warmup_ticks: int = 0,
        perf=None,
        tenants=None,
        health=None,
    ):
        if chunk % window:
            raise ValueError(f"chunk {chunk} must divide by window {window}")
        self.cfg = serve_config(cfg)
        self.batch = batch
        # The ReadIndex plane rides the chunk program iff the serve config
        # carries the gate; the read plane's SHAPE is then fixed too, so the
        # jit cache stays flat whether or not any tenant demands reads.
        self.reads_enabled = self.cfg.read_index
        self.router = None
        if tenants is not None:
            from raft_sim_tpu.serve.tenancy import TenantRouter

            self.router = TenantRouter(tenants, batch, self.reads_enabled)
            if sink is not None:
                self.router.attach_dir(sink.directory)
        # Fixed extraction-round count of the overlapped drain: commit
        # throughput is <= 1 entry/cluster/tick, so rounds * depth >= chunk
        # keeps the stream dry in steady state (+1 absorbs boundary slack);
        # any remainder is backpressure picked up next chunk, never loss.
        self._drain_rounds = -(-chunk // delta_depth) + 1
        self.seed = seed
        self.chunk = chunk
        self.window = window
        self.sink = sink
        # Per-chunk runtime attribution (obs.ChunkTimer): dispatch in
        # _dispatch, ingest packing as the host gap, the _collect device_get
        # as the device wait -- the double buffer's natural phase boundaries,
        # so serving pays NO extra sync for attribution. The serve chunk's
        # jit cache is sampled every boundary (the flat-cache discipline
        # tests/test_serve.py pins, now a streamed watchdog counter too).
        self.perf = perf
        if perf is not None:
            perf.add_probe("serve._serve_chunk", _serve_chunk)
            if warmup_ticks:
                # Warmup chunks (leader election before the first offer) are
                # compile + convergence time, never steady serving -- and the
                # FIRST serving chunk after them pays the one-time
                # donated-carry respecialization (timer docstring), so it is
                # excluded too.
                perf.warmup_chunks = max(
                    perf.warmup_chunks,
                    self._round_up(warmup_ticks) // chunk + 1,
                )
        if sink is not None:
            # The session owns the sink directory's delta stream (the sink
            # itself owns manifest/windows/summary): truncate any stale file
            # up front so per-cluster streams always start dense at index 1
            # (appending after an old run would trip validate_deltas).
            self._deltas_path = os.path.join(sink.directory, "deltas.jsonl")
            open(self._deltas_path, "w").close()
        root = jax.random.key(seed)
        k_init, k_run = jax.random.split(root)
        from raft_sim_tpu.types import init_batch

        # The loop owns its fleet copy (donation discipline: see _serve_chunk).
        self.state = _own_copy(init_batch(self.cfg, k_init, batch))
        self.keys = jax.random.split(k_run, batch)
        self.metrics = scan.init_metrics_batch(batch)
        self.deltas = deltas_mod.DeltaStream(batch, depth=delta_depth)
        self.delta_rows: list[dict] = []
        self.chunks_done = 0
        self.ticks_done = 0
        self.warmup_chunks = 0
        # SLO monitoring (raft_sim_tpu/health): armed AFTER warmup below, so
        # election convergence is never billed against the availability
        # budget -- the same exclusion the perf warmup_chunks bump applies.
        self.monitors: list = []
        self._health_spec = health
        self._health_status: tuple | None = None
        if warmup_ticks:
            # Elect leaders before the first real offer plane (an offer into a
            # leaderless tick is dropped, exactly like the reference's curl
            # against a booting cluster). Warmup is accounted separately:
            # serve()'s chunk budget and throughput stats cover SERVING only.
            self._advance(self._round_up(warmup_ticks))
            self.warmup_chunks, self.chunks_done = self.chunks_done, 0
            self.ticks_done = 0
        if health is not None:
            if sink is None:
                raise ValueError(
                    "health monitoring needs a sink: the health/alert streams "
                    "and evidence bundles live in its directory"
                )
            from raft_sim_tpu.health import HealthMonitor, HealthWriter, load_spec
            from raft_sim_tpu.utils.telemetry_sink import config_hash

            spec = load_spec(health)
            writer = HealthWriter(sink.directory)
            refs = {
                "config_hash": config_hash(self.cfg),
                "seed": int(seed),
                "batch": int(batch),
                "source": "serve",
            }
            capture = lambda alert, clusters: {"refs": refs}
            # One fleet monitor (it owns the runtime SLIs: the perf rows are
            # loop-wide) + one per tenant slice when the session is
            # multi-tenant -- all sharing one writer, scope-tagged lines.
            self.monitors.append(HealthMonitor(
                spec, batch=batch, writer=writer, scope="fleet",
                perf=perf, capture=capture,
            ))
            if self.router is not None:
                for t in self.router.tenants:
                    self.monitors.append(HealthMonitor(
                        spec, batch=t.hi - t.lo, writer=writer,
                        scope=f"tenant:{t.name}", cluster_base=t.lo,
                        capture=capture,
                    ))

    def _round_up(self, ticks: int) -> int:
        return -(-ticks // self.chunk) * self.chunk

    def _nil_planes(self, ticks: int):
        cmds = np.full((ticks, self.batch), NIL, np.int32)
        reads = (
            np.full((ticks, self.batch), NIL, np.int32)
            if self.reads_enabled
            else None
        )
        return cmds, reads

    def _advance(self, ticks: int) -> None:
        """Synchronous warmup advance (no offers): dispatch + collect per
        chunk through the SAME chunk program the serving loop uses."""
        for _ in range(ticks // self.chunk):
            self._dispatch(*self._nil_planes(self.chunk))
            self._collect()

    def _dispatch(self, cmds_np: np.ndarray, reads_np=None):
        """Issue one chunk (async under jax dispatch); the caller's host
        window (export + packing) runs while this one computes."""
        if self.perf is not None:
            self.perf.begin(int(cmds_np.shape[0]))
        cmds = jnp.asarray(cmds_np, jnp.int32)
        reads = None if reads_np is None else jnp.asarray(reads_np, jnp.int32)
        self.state, self._m_pending, self._recs_pending = _serve_chunk(
            self.cfg, self.state, self.keys, cmds, reads, self.window
        )
        if self.perf is not None:
            self.perf.dispatched()
        self.chunks_done += 1
        self.ticks_done += int(cmds_np.shape[0])
        self._last_offered = int(np.sum(cmds_np != NIL)) + (
            0 if reads_np is None else int(np.sum(reads_np != NIL))
        )

    def _export(self, recs, rows: list[dict]) -> None:
        """Host-side export of one collected chunk: fleet sink streams,
        per-tenant routing/credits, and the ack ledgers. In the overlapped
        loop this runs for chunk k-1 WHILE chunk k computes (its duration is
        the perf row's export_s annotation, inside host_s)."""
        if recs is not None:
            # ONE device->host fetch, fanned out to the fleet sink and every
            # tenant's slice (credit_windows would otherwise re-convert the
            # whole record tree per tenant, inside the timed export window).
            recs = jax.device_get(recs)
            if self.sink is not None:
                self.sink.append_windows(recs)
            if self.router is not None:
                self.router.credit_windows(recs)
            if self.monitors:
                self._observe_health(recs)
        self.delta_rows.extend(rows)
        if self.sink is not None and rows:
            deltas_mod.append_delta_rows(self._deltas_path, rows)
        if self.router is not None and rows:
            self.router.route_deltas(rows)

    def _observe_health(self, recs) -> None:
        """Fan one collected chunk's window units to the fleet + tenant
        monitors (units split once, tenant views are numpy slices) and print
        the live status line to stderr whenever any scope changes state."""
        from raft_sim_tpu.health.monitor import slice_units
        from raft_sim_tpu.sim import telemetry

        units = telemetry.window_cluster_counters(recs)
        for m in self.monitors:
            if m.cluster_base == 0 and m.batch == self.batch:
                m.observe_units(units)
            else:
                m.observe_units(
                    slice_units(units, m.cluster_base, m.cluster_base + m.batch)
                )
        status = tuple(m.status for m in self.monitors)
        if self._health_status is not None and status != self._health_status:
            print(
                "; ".join(m.status_line() for m in self.monitors),
                file=sys.stderr,
            )
        self._health_status = status

    def _collect(self) -> list[dict]:
        """Synchronous collect (warmup / single-step use): merge the
        dispatched chunk's outputs, drain its deltas to dryness, export."""
        self.metrics = merge_metrics(self.metrics, self._m_pending)
        if self.perf is not None:
            self.perf.end(sync=lambda: np.asarray(self._m_pending.ticks))
        recs = jax.device_get(self._recs_pending)
        rows = self.deltas.drain(self.state)
        self._export(recs, rows)
        return rows

    def serve(
        self,
        source: CommandSource | None = None,
        chunks: int | None = None,
        drain_chunks: int = 4,
        progress=None,
        stall_chunks: int = 256,
    ) -> dict:
        """Run the overlapped service loop.

        `source` (legacy single-tenant form) broadcasts each command to every
        cluster, exactly as before; a session built with `tenants=[...]`
        serves each tenant's source/read demand over its own cluster slice
        and takes no `source` here. Stops after `chunks` serving chunks when
        given (warmup chunks never consume the budget); otherwise when every
        source is exhausted AND every read demand is met AND `drain_chunks`
        further offer-free chunks have flushed trailing commits.
        `progress(stats_dict)` is called after each chunk. Returns the serve
        stats dict.

        `stall_chunks` guards the open-ended form against an UNSERVABLE
        demand: if no tenant ledger (acks, served reads) advances for that
        many consecutive chunks while demands remain, the loop raises naming
        the stuck tenants instead of spinning forever. The canonical way to
        hit it: a read-only tenant on a config whose elections append no
        no-op (no compaction), so no leader ever satisfies the 6.4
        current-term-commit capture gate -- docs/SERVE.md "read-only
        tenants". 0 disables the guard.
        """
        from raft_sim_tpu.serve.tenancy import Tenant, TenantRouter

        if self.router is None:
            if source is None:
                raise ValueError("serve() needs a source (or tenants=[...])")
            # Legacy broadcast tenant: one logical client over the whole
            # fleet, each command offered to every cluster.
            self.router = TenantRouter(
                [Tenant("default", self.batch, source=source, broadcast=True)],
                self.batch,
                self.reads_enabled,
            )
            if self.sink is not None:
                self.router.attach_dir(self.sink.directory)
        elif source is not None:
            raise ValueError(
                "this session was built with tenants=[...]; their sources "
                "replace serve(source)"
            )
        router = self.router
        t0 = time.perf_counter()
        drain_left = drain_chunks
        stall = 0
        last_ledger = None
        pending = None  # chunk k-1's (records, delta rows), exported under k
        self._dispatch(*router.pack(self.chunk))
        while True:
            # ---- host window: runs while the dispatched chunk computes ----
            # Everything until perf.end/finish_rounds below touches ONLY
            # last chunk's pending host copies and the routers' own state --
            # never the in-flight donated carry. That disjointness is a
            # checked fact: Pass D's overlap write-set audit derives this
            # window's writes (race_audit.overlap_write_sets) and gates any
            # carry touch as `race-window-mutation`; the donation-poison
            # sanitizer (--sanitize) re-proves it at runtime.
            e0 = time.perf_counter()
            if pending is not None:
                self._export(*pending)
            e1 = time.perf_counter()
            if chunks is not None:
                stop = self.chunks_done >= chunks
            else:
                if router.exhausted and self._last_offered == 0:
                    drain_left -= 1
                stop = router.exhausted and drain_left <= 0
                if not router.exhausted and stall_chunks:
                    ledger = tuple(
                        (len(t.acked_values), t.reads_served, t.offered)
                        for t in router.tenants
                    )
                    stall = stall + 1 if ledger == last_ledger else 0
                    last_ledger = ledger
                    if stall >= stall_chunks:
                        stuck = [
                            t.name for t in router.tenants
                            if not (t.writes_done and t.reads_done)
                        ]
                        raise RuntimeError(
                            f"serve loop stalled for {stall_chunks} chunks "
                            f"with unmet demands on tenants {stuck}: the "
                            "demand may be unservable under this config "
                            "(e.g. read-only tenants need elections that "
                            "append no-ops -- docs/SERVE.md)"
                        )
            next_planes = None if stop else router.pack(self.chunk)
            e2 = time.perf_counter()
            # Enqueue this chunk's extraction rounds BEHIND it on the device
            # stream; fetched after the sync below, so the next dispatch's
            # donation never races a pending read of this chunk's state.
            futs = self.deltas.begin_rounds(self.state, self._drain_rounds)
            if self.perf is not None:
                self.perf.annotate(
                    export_s=round(e1 - e0, 6), pack_s=round(e2 - e1, 6)
                )
            # ---- sync: the only serial points are this wait + dispatch ----
            self.metrics = merge_metrics(self.metrics, self._m_pending)
            if self.perf is not None:
                self.perf.end(sync=lambda: np.asarray(self._m_pending.ticks))
            pending = (self._recs_pending, self.deltas.finish_rounds(futs))
            if progress is not None:
                progress(self.stats())
            if stop:
                self._export(*pending)
                # Final flush: drain to dryness (the fixed overlapped rounds
                # are backpressure-bounded, not loss-bounded).
                tail = self.deltas.drain(self.state)
                if tail:
                    self._export(None, tail)
                break
            self._dispatch(*next_planes)
        stats = self.stats()
        stats["wall_s"] = round(time.perf_counter() - t0, 3)
        stats["offered"] = router.offered
        stats["reads_offered"] = router.reads_offered
        if self.perf is not None:
            # Steady-state rollup + the recompile-watchdog finding (stderr).
            stats["perf"] = self.perf.finish()
        if self.monitors:
            # Evaluate any partial trailing period, then replace the live
            # status map with each scope's full rollup for summary.json.
            stats["health"] = [m.finalize() for m in self.monitors]
        if self.sink is not None:
            from raft_sim_tpu.parallel import summarize

            self.sink.write_summary({**summarize(self.metrics)._asdict(), **stats})
            if self.router is not None:
                self.router.write_manifest(
                    os.path.join(self.sink.directory, "tenants.json")
                )
        return stats

    def stats(self) -> dict:
        reads_served = int(np.sum(np.asarray(self.metrics.reads_served)))
        return {
            "chunks": self.chunks_done,
            "ticks": self.ticks_done,
            "warmup_chunks": self.warmup_chunks,
            "batch": self.batch,
            "chunk": self.chunk,
            "window": self.window,
            "tenants": 0 if self.router is None else len(self.router.tenants),
            "deltas_exported": self.deltas.exported,
            "delta_gap_entries": self.deltas.gap_entries,
            # Client entries only (leader no-ops excluded): the commands
            # half of the throughput metric -- election churn's protocol
            # filler must never inflate it.
            "commands_acked": self.deltas.applied,
            "reads_served": reads_served,
            # The serve-throughput numerator (bench.py serve row): work the
            # service completed -- client commands acked through the delta
            # stream plus ReadIndex reads served. Ticks are the simulator's
            # clock, not the service's unit of work.
            "ops_done": self.deltas.applied + reads_served,
            "violations": int(np.sum(np.asarray(self.metrics.violations))),
            **(
                {"health": {m.scope: m.status for m in self.monitors}}
                if self.monitors
                else {}
            ),
        }

    def acked_values(self, cluster: int = 0) -> list[int]:
        """The commit-ack stream of one cluster: committed client values in
        commit order (no-ops filtered) -- what the reference's commit watch
        should have delivered per entry (log.clj:83-87, bug 2.3.9)."""
        return deltas_mod.applied_values(self.delta_rows, cluster)
