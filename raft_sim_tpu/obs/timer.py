"""ChunkTimer: per-chunk runtime attribution for the standing loops.

Every long-horizon driver in this repo advances the fleet in fixed-size jitted
chunks with host work between them (metric merges, telemetry export, ingest
packing, checkpoint callbacks). That boundary is the one place runtime
behaviour is observable without touching traced code, and the one place the
predictive cost model is blind: a chunk that runs 2x slower than its bytes/tick
projection could be host-stalled, dispatch-gapped, recompiling, or genuinely
memory-bound -- indistinguishable from a bench headline alone.

The timer splits each chunk's wall time into four host-measurable phases:

    begin ──(jitted call returns)── dispatched ──(host work)── sync ── end
      │         dispatch_s                │        host_s        │ device_wait_s
      └────────────────────────────── wall_s ─────────────────────────┘
    gap_s = time from the previous chunk's end to this begin (inter-chunk
            host work: export, fitness evaluation, source packing).

`device_wait_s` is the time blocked on a forced HOST COPY of a small chunk
output (the same defense bench.py uses: on this machine's TPU stack
`block_until_ready` can return early, data on the host cannot lie). It is a
*lower bound* on device execution -- whatever the device overlapped with the
host phases is invisible by construction; `dispatch_s + host_s + gap_s` is the
host gap the device could have been starved by. Enabling the timer adds one
host sync per chunk, which serializes pipelining a loop would otherwise
overlap -- attribution semantics, sizing, and that caveat are documented in
docs/OBSERVABILITY.md ("Runtime perf").

At every chunk boundary the timer also samples device-memory occupancy
(`live_bytes`, None where the backend publishes no memory stats -- CPU) and
the jit-cache size of each registered entry point. A cache that GROWS after
warmup is the recompile watchdog firing: the row is marked, the summary says
so, and `finish()` prints a visible finding -- the generalization of the
serve loop's pinned flat-cache discipline (PR 6) to every standing loop.

Rows stream to the telemetry sink as schema'd perf.jsonl
(utils/telemetry_sink.py validates them); everything here is host-side
stdlib + an optional jax device query, so the timer itself can never change a
trajectory, a lowering, or a compile count.
"""

from __future__ import annotations

import sys
import time


def device_live_bytes(device=None) -> int | None:
    """Current bytes in use on the (first local) device, or None where the
    backend publishes no memory stats (CPU) -- perf.jsonl rows carry null
    there, and reconciliation simply skips live-peak headroom."""
    try:
        import jax

        d = device if device is not None else jax.local_devices()[0]
        stats = d.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    v = stats.get("bytes_in_use")
    return int(v) if v is not None else None


class ChunkTimer:
    """Per-chunk runtime attribution (module docstring has the phase diagram).

    >>> t = ChunkTimer(label="run", batch=batch, sink=sink)
    >>> # inside the loop, per chunk:
    >>> t.begin(n_ticks); out = jitted_chunk(...); t.dispatched()
    >>> ...host work...; t.end(sync=lambda: np.asarray(out.ticks))
    >>> t.finish()  # summary dict; prints the recompile finding if it fired

    `warmup_chunks` rows are flagged warmup and excluded from the steady-state
    rollup. The default is 2, not 1: chunk 0 pays the program compile, and on
    this jax version every DONATING chunk loop re-specializes once more at its
    second call (the donated output's buffer signature differs from the
    caller-owned input's -- observed on _chunk_donate, _chunk_t_donate, and
    _serve_chunk alike), so chunk 1 pays that one-time compile. Steady state
    starts at chunk 2. The recompile-watchdog BASELINE is likewise the first
    steady chunk's cache sample, not the warmup's -- a growth between the last
    warmup chunk and the first steady chunk is expected respecialization; a
    growth after that is a real mid-run recompile. `sink` (a TelemetrySink)
    streams each row to perf.jsonl; without one, rows accumulate on
    `self.rows` only.
    """

    def __init__(self, label: str = "run", batch: int = 1, sink=None,
                 warmup_chunks: int = 2):
        if warmup_chunks < 0:
            raise ValueError(f"warmup_chunks must be >= 0, got {warmup_chunks}")
        self.label = label
        self.batch = int(batch)
        self.sink = sink
        self.warmup_chunks = warmup_chunks
        self.rows: list[dict] = []
        self._probes: dict[str, object] = {}
        # Per-probe cache size at the FIRST STEADY chunk: the watchdog
        # baseline. Growth past it on any later steady chunk = a recompile
        # the loop promised not to do. (Warmup samples are never the
        # baseline: the one-time donated-carry respecialization at chunk 1
        # would make every run a false positive -- see the class docstring.)
        self._probe_base: dict[str, int] = {}
        self._recompiled = False
        self._chunk = 0
        self._t_begin = self._t_disp = None
        self._t_prev_end = None
        self._ticks = 0
        self._gap = 0.0
        self._extra: dict = {}

    # -------------------------------------------------------------- probes

    def add_probe(self, name: str, fn) -> None:
        """Register a jit-cache probe sampled at every chunk boundary: `fn` is
        a jitted entry point (its `_cache_size` is read) or any zero-arg
        callable returning an int. Idempotent -- the loops register their own
        entry points unconditionally."""
        if name in self._probes:
            return
        self._probes[name] = (
            fn._cache_size if hasattr(fn, "_cache_size") else fn
        )

    def _cache_sizes(self) -> dict[str, int]:
        out = {}
        for name, fn in self._probes.items():
            try:
                out[name] = int(fn())
            except Exception:
                out[name] = -1  # unprobeable on this jax version: visible, not fatal
        return out

    # --------------------------------------------------------------- phases

    def begin(self, ticks: int) -> None:
        t = time.perf_counter()
        self._gap = 0.0 if self._t_prev_end is None else t - self._t_prev_end
        self._ticks = int(ticks)
        self._t_begin = t
        self._t_disp = None

    def dispatched(self) -> None:
        """Call right after the jitted chunk call returns (async dispatch)."""
        self._t_disp = time.perf_counter()

    def annotate(self, **extra) -> None:
        """Attach loop-measured sub-phase fields (JSON-able values) to the
        CURRENT chunk's row -- e.g. the serve loop's pack_s/export_s, timed
        inside its dispatch->sync host window so the overlap structure is a
        checkable perf.jsonl fact, not prose. Unknown keys ride the row
        as-is (the sink validates only the core schema fields)."""
        self._extra.update(extra)

    def end(self, sync=None) -> dict:
        """Close the chunk: `sync` forces a host copy of a small chunk output
        (its duration is the device wait); sample memory + jit caches, append
        the row (and stream it to the sink). `end(sync=...)` is also the
        sync point Pass D's overlap audit recognizes: it CLOSES the
        dispatch->sync window a donating chunk dispatch opened, so host
        writes to the carry before this call are `race-window-mutation`
        findings (analysis/race_audit.py)."""
        if self._t_begin is None:
            raise RuntimeError("ChunkTimer.end() without begin()")
        t_host = time.perf_counter()
        if sync is not None:
            sync()
        t = time.perf_counter()
        t_disp = self._t_disp if self._t_disp is not None else t_host
        caches = self._cache_sizes()
        warmup = self._chunk < self.warmup_chunks
        recompiled = False
        if not warmup:
            for name, size in caches.items():
                base = self._probe_base.setdefault(name, size)
                if size > base:
                    recompiled = True
                    self._recompiled = True
        row = {
            "chunk": self._chunk,
            "ticks": self._ticks,
            "warmup": warmup,
            "wall_s": round(t - self._t_begin, 6),
            "dispatch_s": round(t_disp - self._t_begin, 6),
            "host_s": round(t_host - t_disp, 6),
            "device_wait_s": round(t - t_host, 6),
            "gap_s": round(self._gap, 6),
            "live_bytes": device_live_bytes(),
            "jit_cache": caches,
            "recompiled": recompiled,
            **self._extra,
        }
        self._extra = {}
        self.rows.append(row)
        if self.sink is not None:
            self.sink.append_perf([row])
        self._chunk += 1
        self._t_begin = self._t_disp = None
        self._t_prev_end = t
        return row

    # -------------------------------------------------------------- rollups

    def summary(self) -> dict:
        """Steady-state rollup over the recorded rows (warmup excluded) --
        the same arithmetic `tools/metrics_report.py --perf` applies to a
        perf.jsonl stream, so the live summary and the file report agree."""
        return summarize_rows(
            self.rows, label=self.label, batch=self.batch,
            warmup_chunks=self.warmup_chunks,
        )

    def finish(self, out="stderr") -> dict:
        """End-of-run summary; prints the recompile-watchdog finding (and
        which probe grew) when a steady-state chunk compiled something.
        `out` defaults to the CURRENT sys.stderr (resolved at call time, not
        def time -- def-time binding breaks under stream capture); pass a
        stream to redirect, None to silence."""
        s = self.summary()
        if out == "stderr":
            out = sys.stderr
        if s["recompiled_after_warmup"] and out is not None:
            grown = [
                f"{name} {self._probe_base.get(name, '?')}->{size}"
                for name, size in (self.rows[-1]["jit_cache"] or {}).items()
                if size > self._probe_base.get(name, size)
            ]
            print(
                f"perf watchdog [{self.label}]: jit cache grew after warmup "
                f"({', '.join(grown) or 'see perf.jsonl jit_cache'}) -- a "
                "standing loop recompiled mid-run",
                file=out,
            )
        return s


def summarize_rows(rows: list[dict], label: str = "run", batch: int = 1,
                   warmup_chunks: int | None = None) -> dict:
    """Fold perf rows (live ChunkTimer rows or re-read perf.jsonl lines) into
    the steady-state summary. `warmup_chunks` defaults to trusting each row's
    own `warmup` flag (what the file form must do)."""
    if warmup_chunks is None:
        steady = [r for r in rows if not r.get("warmup")]
    else:
        steady = [r for r in rows if r["chunk"] >= warmup_chunks]
    ticks = sum(r["ticks"] for r in steady)
    wall = sum(r["wall_s"] + r["gap_s"] for r in steady)
    host_gap = sum(r["dispatch_s"] + r["host_s"] + r["gap_s"] for r in steady)
    wait = sum(r["device_wait_s"] for r in steady)
    live = [r["live_bytes"] for r in rows if r.get("live_bytes") is not None]
    return {
        "label": label,
        "batch": int(batch),
        "chunks": len(rows),
        "steady_chunks": len(steady),
        "steady_ticks": ticks,
        "steady_wall_s": round(wall, 6),
        "steady_ticks_per_s": round(ticks / wall, 1) if wall > 0 else None,
        "steady_cluster_ticks_per_s": (
            round(batch * ticks / wall, 1) if wall > 0 else None
        ),
        "device_wait_s": round(wait, 6),
        "host_gap_s": round(host_gap, 6),
        "host_gap_frac": round(host_gap / wall, 4) if wall > 0 else None,
        "live_bytes_peak": max(live) if live else None,
        "jit_cache_final": dict(rows[-1]["jit_cache"]) if rows else {},
        "recompiled_after_warmup": any(r.get("recompiled") for r in rows),
    }
