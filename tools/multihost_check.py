"""Multi-host execution proof: two cooperating OS processes, one global mesh.

The reference's deployment shape is N cooperating OS processes (`lein run 1 2 3`
etc., core.clj:197-203). This framework's multi-HOST analogue is pure
orchestration -- independent clusters shard over every chip of every host -- and
this tool proves the code path actually executes: it spawns TWO local processes
(CPU backend, 4 virtual devices each) that form a JAX distributed cluster over a
localhost coordinator, run `simulate_sharded` on the global 8-device mesh, gather
metrics to every process (`parallel.gather_metrics` -- the non-addressable-shard
path of `summarize`), and verifies process 0's result matches a single-process
8-device run of the same (cfg, seed, batch, ticks) BIT FOR BIT (the
device-layout-invariance property of tests/test_parallel.py, extended across
process boundaries).

Usage:
    python tools/multihost_check.py            # orchestrates everything; prints
                                               # one JSON verdict line, exit 0 on match

Internal modes (spawned by the orchestrator; fresh interpreters are required
because --xla_force_host_platform_device_count must precede backend init):
    _MH_MODE=child _MH_PID={0,1} _MH_PORT=...  distributed worker
    _MH_MODE=local                             single-process reference run
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

# One meaty workload: faults + client traffic + invariants, riding the full
# round-4 surface (compaction ring + snapshot catch-up + 302 redirect routing).
CFG_KW = dict(
    n_nodes=5,
    log_capacity=16,
    compact_margin=4,
    client_interval=4,
    client_redirect=True,
    drop_prob=0.1,
    clock_skew_prob=0.1,
)
SEED, BATCH, TICKS = 0, 16, 200


def _run_and_dump() -> dict:
    """Run the sharded simulation on the (possibly multi-process) global mesh and
    return every RunMetrics field as lists, plus the fleet summary."""
    import jax
    import numpy as np

    from raft_sim_tpu import RaftConfig
    from raft_sim_tpu.parallel import gather_metrics, make_mesh, simulate_sharded, summarize

    cfg = RaftConfig(**CFG_KW)
    mesh = make_mesh()
    final, metrics = simulate_sharded(cfg, SEED, BATCH, TICKS, mesh)
    summary = summarize(metrics)._asdict()  # exercises the gather path itself
    m = gather_metrics(metrics)
    fields = {f: np.asarray(v).tolist() for f, v in zip(m._fields, m)}
    return {"metrics": fields, "summary": summary}


def child(pid: int, port: str) -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    from raft_sim_tpu.parallel import init_distributed

    got_pid = init_distributed(
        coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid
    )
    assert got_pid == pid
    assert jax.device_count() == 8, jax.device_count()
    assert jax.local_device_count() == 4, jax.local_device_count()
    out = _run_and_dump()
    if pid == 0:
        print(json.dumps(out), flush=True)
    jax.distributed.shutdown()


def local() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    assert jax.device_count() == 8, jax.device_count()
    print(json.dumps(_run_and_dump()), flush=True)


def orchestrate() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = str(s.getsockname()[1])
    s.close()

    def env_for(mode: str, n_dev: int, pid: int | None = None) -> dict:
        env = dict(os.environ)
        env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_dev}"
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["_MH_MODE"] = mode
        env["_MH_PORT"] = port
        if pid is not None:
            env["_MH_PID"] = str(pid)
        return env

    me = os.path.abspath(__file__)
    workers = [
        subprocess.Popen(
            [sys.executable, "-u", me],
            env=env_for("child", 4, pid),
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            cwd=REPO,
        )
        for pid in range(2)
    ]
    ref = subprocess.Popen(
        [sys.executable, "-u", me],
        env=env_for("local", 8),
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        cwd=REPO,
    )

    outs = []
    for i, p in enumerate(workers + [ref]):
        try:
            out, err = p.communicate(timeout=480)
        except subprocess.TimeoutExpired:
            for q in workers + [ref]:
                q.kill()
            print(json.dumps({"match": False, "error": f"process {i} timed out"}))
            return 1
        if p.returncode != 0:
            print(json.dumps({"match": False, "error": f"process {i} rc={p.returncode}",
                              "stderr_tail": err[-2000:]}))
            return 1
        outs.append(out)

    # Gloo prints connection banners on stdout; the JSON payload is the last line.
    got = json.loads(outs[0].strip().splitlines()[-1])  # worker process 0
    want = json.loads(outs[2].strip().splitlines()[-1])  # single-process reference
    match = got == want
    print(json.dumps({
        "match": match,
        "n_processes": 2,
        "global_devices": 8,
        "batch": BATCH,
        "ticks": TICKS,
        "violations": sum(got["metrics"]["violations"]),
        "summary": got["summary"],
    }))
    return 0 if match else 1


def main() -> int:
    mode = os.environ.get("_MH_MODE")
    if mode == "child":
        child(int(os.environ["_MH_PID"]), os.environ["_MH_PORT"])
        return 0
    if mode == "local":
        local()
        return 0
    return orchestrate()


if __name__ == "__main__":
    sys.exit(main())
