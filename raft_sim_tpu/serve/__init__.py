"""Fleet-as-a-service: the standing-fleet serve subsystem (ISSUE 6 + the
ISSUE 11 tenancy plane).

Four pieces (docs/SERVE.md has the architecture):
  ingest.py  -- host command sources packed into per-chunk offer planes
  loop.py    -- the overlapped served scan + ServeSession driver
  deltas.py  -- device-side commit-delta extraction (the streaming apply/ack
                surface replacing the host snapshot-diff poll)
  tenancy.py -- multi-tenant partitioning of the fleet's cluster range
                (per-tenant sources, read demands, and export streams over
                ONE compiled program)
"""

from raft_sim_tpu.serve.deltas import DeltaStream, extract
from raft_sim_tpu.serve.ingest import (
    CommandSource,
    jsonl_commands,
    pack_chunk,
    pack_plane,
)
from raft_sim_tpu.serve.loop import ServeSession, serve_config, simulate_serve
from raft_sim_tpu.serve.tenancy import Tenant, TenantRouter

__all__ = [
    "CommandSource",
    "DeltaStream",
    "ServeSession",
    "Tenant",
    "TenantRouter",
    "extract",
    "jsonl_commands",
    "pack_chunk",
    "pack_plane",
    "serve_config",
    "simulate_serve",
]
