"""Protocol trace plane (raft_sim_tpu/trace): extraction parity, bit-exactness,
history reconstruction, the whole-history checker, coverage, and triggers.

Program budget: the traced/untraced windowed runs share module-cached results,
so the file compiles a handful of SMALL windowed programs (N=5, batch 4-8,
64-256 ticks) -- every test family reuses them.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_sim_tpu.scenario.mutation import mutant_config
from raft_sim_tpu.sim import scan, telemetry
from raft_sim_tpu.trace import checker as tchecker
from raft_sim_tpu.trace import events as tev
from raft_sim_tpu.trace import history as thistory
from raft_sim_tpu.trace.ring import COV_BITS, TraceSpec, cov_popcount
from raft_sim_tpu.types import NIL, init_batch
from raft_sim_tpu.utils import telemetry_sink as sink_mod
from raft_sim_tpu.utils.config import RaftConfig

# A fault-rich little fleet so every event kind has a chance to fire: client
# traffic, drops, crashes, AND rolling partitions.
CFG = RaftConfig(
    n_nodes=5, client_interval=4, drop_prob=0.2, crash_prob=0.2,
    crash_period=32, crash_down_ticks=8, partition_period=16,
    partition_prob=0.3,
)
CFG_T = dataclasses.replace(CFG, track_trace=True)
SEED, BATCH, TICKS, WINDOW = 3, 4, 128, 32
SPEC = TraceSpec(depth=256)


@functools.lru_cache(maxsize=1)
def plain_run():
    return telemetry.simulate_windowed(CFG, SEED, BATCH, TICKS, WINDOW)


@functools.lru_cache(maxsize=1)
def traced_run():
    return telemetry.simulate_windowed(
        CFG_T, SEED, BATCH, TICKS, WINDOW, 0, None, 1, SPEC
    )


@functools.lru_cache(maxsize=1)
def traced_history():
    return thistory.from_device(traced_run()[4])


def _tree_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        if not np.array_equal(np.asarray(x), np.asarray(y)):
            return False
    return True


# ----------------------------------------------------------- vocabulary


def test_slot_tables_and_kind_order():
    n = CFG.n_nodes
    kinds = tev.slot_kinds(n)
    nodes = tev.slot_nodes(n)
    assert len(kinds) == len(nodes) == tev.n_slots(n)
    # KINDS is a bijection onto 1..N_KINDS-1 (0 reserved for empty slots).
    assert sorted(tev.KINDS.values()) == list(range(1, tev.N_KINDS))
    assert all(tev.KIND_NAMES[v] == k for k, v in tev.KINDS.items())
    # Slot order is kind-major ascending: the within-tick processing order.
    assert list(kinds) == sorted(kinds)
    # The checker's load-bearing ordering: role transitions strictly before
    # commit/append/truncate, fault kinds after them (trace/events.py).
    assert max(tev.EV_FOLLOWER, tev.EV_PRECANDIDATE, tev.EV_CANDIDATE,
               tev.EV_LEADER) < tev.EV_COMMIT
    assert tev.EV_TRUNCATE < tev.EV_CRASH <= tev.EV_RESTART
    # Cluster-scope slots carry the NIL node id.
    assert list(nodes[-len(tev.CLUSTER_KINDS):]) == [NIL] * len(tev.CLUSTER_KINDS)


# ----------------------------------------------- zero-cost / bit-exactness


@pytest.mark.slow  # budget re-tier (PR 12): the gate-alone program is
# pinned BYTE-IDENTICAL to the untraced one by the Pass A disabled-mode
# step goldens (an identical lowering cannot diverge), and the stronger
# claim -- an ARMED trace does not perturb the trajectory -- stays tier-1
# (test_traced_run_does_not_perturb_trajectory).
def test_track_trace_gate_alone_is_bit_exact():
    # cfg.track_trace=True with NO trace requested: the program carries no
    # trace leg and the run is bit-identical to the untraced config's.
    got = telemetry.simulate_windowed(CFG_T, SEED, BATCH, TICKS, WINDOW)
    assert len(got) == 4
    assert _tree_equal(got, plain_run())


def test_traced_run_does_not_perturb_trajectory():
    # Extraction + ring + coverage on: state/metrics/windows bit-identical.
    got = traced_run()
    assert len(got) == 6
    assert _tree_equal(got[:3], plain_run()[:3])


def test_trace_requires_track_trace():
    with pytest.raises(ValueError, match="track_trace"):
        telemetry.simulate_windowed(CFG, SEED, BATCH, TICKS, WINDOW, 0, None, 1, SPEC)


# ------------------------------------------- device-vs-host event parity


def _host_delta_events(cfg, init, states, cluster):
    """Re-derive the delta-based event kinds (1..9) host-side from an
    UNBATCHED-kernel state stack -- the cross-kernel oracle: the batched
    path's device events must match what raft.step's trajectory implies."""
    fields = ("role", "term", "voted_for", "commit_index", "log_len")
    g0 = {f: np.asarray(getattr(init, f))[cluster] for f in fields}
    gs = {f: np.asarray(getattr(states, f))[cluster] for f in fields}
    n_ticks, n = gs["role"].shape
    out = []
    for t in range(n_ticks):
        old = g0 if t == 0 else {f: gs[f][t - 1] for f in fields}
        new = {f: gs[f][t] for f in fields}
        per_kind = {
            tev.EV_FOLLOWER: ((new["role"] == 0) & (old["role"] != 0), new["term"]),
            tev.EV_PRECANDIDATE: ((new["role"] == 3) & (old["role"] != 3), new["term"]),
            tev.EV_CANDIDATE: ((new["role"] == 1) & (old["role"] != 1), new["term"]),
            tev.EV_LEADER: ((new["role"] == 2) & (old["role"] != 2), new["term"]),
            tev.EV_TERM: (new["term"] > old["term"], new["term"]),
            tev.EV_VOTE: (
                (new["voted_for"] != old["voted_for"]) & (new["voted_for"] != NIL),
                new["voted_for"],
            ),
            tev.EV_COMMIT: (
                new["commit_index"] > old["commit_index"], new["commit_index"]
            ),
            tev.EV_APPEND: (new["log_len"] > old["log_len"], new["log_len"]),
            tev.EV_TRUNCATE: (new["log_len"] < old["log_len"], new["log_len"]),
        }
        for kind in sorted(per_kind):
            flags, detail = per_kind[kind]
            for node in range(n):
                if flags[node]:
                    out.append((t, node, kind, int(detail[node])))
    return out


def test_device_events_match_unbatched_kernel_replay():
    # Reproduce simulate_windowed's exact init/key derivation, then drive the
    # UNBATCHED kernel (raft.step) and re-extract events from its states.
    root = jax.random.key(SEED)
    k_init, k_run = jax.random.split(root)
    state0 = init_batch(CFG_T, k_init, BATCH)
    keys = jax.random.split(k_run, BATCH)
    _, _, outs = jax.jit(
        jax.vmap(lambda s, k: scan.run(CFG_T, s, k, TICKS, trace_states=True))
    )(state0, keys)
    _, states = outs  # leaves [B, T, N, ...]
    hist = traced_history()
    assert hist.complete, f"ring overflowed: {hist.dropped}"
    for c in range(BATCH):
        want = _host_delta_events(CFG_T, jax.tree.map(lambda x: x, state0), states, c)
        got = [
            (e.tick, e.node, e.kind, e.detail)
            for e in hist.events[c]
            if e.kind <= tev.EV_TRUNCATE
        ]
        assert got == want, f"cluster {c}: device/host event streams differ"


def test_fault_events_consistent_with_inputs():
    # Restart events must agree with the fault schedule: every EV_RESTART at
    # tick t on node i corresponds to make_inputs(t).restarted[i].
    from raft_sim_tpu.sim import faults

    root = jax.random.key(SEED)
    _, k_run = jax.random.split(root)
    keys = jax.random.split(k_run, BATCH)
    hist = traced_history()
    for c in range(BATCH):
        restarts = [(e.tick, e.node) for e in hist.events[c]
                    if e.kind == tev.EV_RESTART]
        for t, node in restarts:
            inp = faults.make_inputs(CFG_T, keys[c], jnp.int32(t))
            assert bool(np.asarray(inp.restarted)[node]), (c, t, node)


# ------------------------------------------------- history + completeness


def test_history_counts_and_windows():
    hist = traced_history()
    _, _, _, _, traws, tp = traced_run()
    assert hist.n_windows == TICKS // WINDOW
    total = np.asarray(tp.total)
    for c in range(BATCH):
        assert hist.emitted[c] == int(total[c])
        assert len(hist.events[c]) == hist.emitted[c] - hist.dropped[c]
        ticks = [e.tick for e in hist.events[c]]
        assert ticks == sorted(ticks)


def test_overflow_is_flagged_never_silent():
    shallow = TraceSpec(depth=4)
    out = telemetry.simulate_windowed(
        CFG_T, SEED, BATCH, 64, 32, 0, None, 1, shallow
    )
    hist = thistory.from_device(out[4])
    assert any(hist.dropped.values())
    assert not hist.complete
    rep = tchecker.check_history(hist)
    # No violation witnessed and the history has holes: every property is
    # UNDECIDED, not vacuously passing.
    assert not rep.ok
    assert rep.violated == []
    assert all(r.ok is None for r in rep.results.values())
    assert "incomplete" in rep.results["election_safety"].note


# ------------------------------------------------------- checker verdicts


def test_real_kernel_history_passes_all_five():
    rep = tchecker.check_history(traced_history())
    assert rep.complete
    assert rep.ok, {n: r.note for n, r in rep.results.items() if not r.ok}
    assert all(r.ok is True for r in rep.results.values())


@functools.lru_cache(maxsize=1)
def mutant_run():
    cfg = mutant_config(
        "weak-quorum",
        RaftConfig(n_nodes=5, drop_prob=0.35, track_trace=True),
    )
    return telemetry.simulate_windowed(
        cfg, 0, 8, 256, 64, 0, None, 1, TraceSpec(depth=512)
    )


def test_weak_quorum_history_rejected_with_witness():
    out = mutant_run()
    assert int(np.asarray(out[1].violations).sum()) > 0  # device flags agree
    hist = thistory.from_device(out[4])
    assert hist.complete
    rep = tchecker.check_history(hist)
    assert rep.ok is False
    assert "election_safety" in rep.violated
    es = rep.results["election_safety"]
    # Minimal witness: the two conflicting leader events, same term,
    # different nodes.
    assert len(es.witness) == 2
    assert all(w["kind"] == "leader" for w in es.witness)
    assert es.witness[0]["detail"] == es.witness[1]["detail"]
    assert es.witness[0]["node"] != es.witness[1]["node"]


def _hist(events_by_cluster, dropped=None):
    ev = {c: [thistory.Event(*e) for e in evs]
          for c, evs in events_by_cluster.items()}
    return thistory.History(
        events=ev,
        emitted={c: len(v) for c, v in ev.items()},
        dropped=dropped or {c: 0 for c in ev},
        n_windows=1,
        problems=[],
    )


def test_checker_synthetic_negatives():
    L, C, T_, R, V = (tev.EV_LEADER, tev.EV_COMMIT, tev.EV_TRUNCATE,
                      tev.EV_RESTART, tev.EV_VIOLATION)
    # leader_append_only: a leader truncates while holding leadership.
    rep = tchecker.check_history(_hist({0: [(5, 1, L, 3), (9, 1, T_, 2)]}))
    assert rep.violated == ["leader_append_only"]
    # leader_completeness: a new leader commits below the frontier.
    rep = tchecker.check_history(_hist({0: [
        (5, 0, L, 3), (8, 0, C, 10), (20, 1, L, 4), (25, 1, C, 5),
    ]}))
    assert rep.violated == ["leader_completeness"]
    # ... but a FOLLOWER trailing the frontier is legal.
    rep = tchecker.check_history(_hist({0: [
        (5, 0, L, 3), (8, 0, C, 10), (12, 1, C, 5),
    ]}))
    assert rep.ok
    # state_machine_safety: per-node commit regression without a restart ...
    rep = tchecker.check_history(_hist({0: [(8, 2, C, 5), (12, 2, C, 3)]}))
    assert rep.violated == ["state_machine_safety"]
    # ... is legal ACROSS a restart (commit resumes from the durable base).
    rep = tchecker.check_history(_hist({0: [
        (8, 2, C, 5), (10, 2, R, 0), (12, 2, C, 3),
    ]}))
    assert rep.ok
    # log_matching + commit bits of a device violation event.
    rep = tchecker.check_history(_hist({0: [(3, NIL, V, tev.VIOL_LOG_MATCHING)]}))
    assert rep.violated == ["log_matching"]
    rep = tchecker.check_history(_hist({0: [(3, NIL, V, tev.VIOL_COMMIT)]}))
    assert rep.violated == ["state_machine_safety"]
    # A violation witnessed in an INCOMPLETE history still fails (never
    # demoted to undecided).
    h = _hist({0: [(5, 1, L, 3), (9, 1, T_, 2)]}, dropped={0: 7})
    rep = tchecker.check_history(h)
    assert rep.violated == ["leader_append_only"]
    assert rep.results["election_safety"].ok is None
    # A freeze-armed capture is a deliberate prefix: same undecided-never-
    # pass rule, with the truncation named (ticks stay monotone and nothing
    # drops, so the armed flag is the only trace of it).
    h = _hist({0: [(5, 1, L, 3)]})
    h.freeze_armed = True
    rep = tchecker.check_history(h)
    assert not rep.ok and rep.violated == []
    assert "freeze-truncated" in rep.results["election_safety"].note


# ------------------------------------------------------ sink + jsonl trips


def test_sink_round_trip_and_validate(tmp_path):
    d = str(tmp_path / "sink")
    sink = sink_mod.TelemetrySink(d, CFG_T, seed=SEED, batch=BATCH,
                                  window=WINDOW, ring=0)
    sink.write_trace_meta(SPEC)
    _, _, _, _, traws, _ = traced_run()
    sink.append_trace(traws)
    assert sink_mod.validate(d) == []
    loaded = thistory.load(d)
    hist = traced_history()
    assert loaded.complete
    assert loaded.events == hist.events
    assert not any(loaded.dropped.values())  # sparse map: only real drops
    rep = tchecker.check_directory(d)
    assert rep.ok


def test_truncated_and_out_of_order_streams_flagged(tmp_path):
    d = str(tmp_path / "bad")
    os.makedirs(d)
    with open(os.path.join(d, "trace_windows.jsonl"), "w") as f:
        # window index jumps 0 -> 2: a truncated/spliced stream.
        f.write(json.dumps({"window": 0, "emitted": 1, "retained": 1,
                            "dropped": 0, "dropped_by_cluster": {}}) + "\n")
        f.write(json.dumps({"window": 2, "emitted": 1, "retained": 1,
                            "dropped": 0, "dropped_by_cluster": {}}) + "\n")
    with open(os.path.join(d, "trace.jsonl"), "w") as f:
        f.write(json.dumps({"w": 0, "c": 0, "t": 9, "node": 1,
                            "k": tev.EV_LEADER, "d": 2}) + "\n")
        # tick regression within one cluster: out of order.
        f.write(json.dumps({"w": 2, "c": 0, "t": 4, "node": 1,
                            "k": tev.EV_COMMIT, "d": 1}) + "\n")
    hist = thistory.load(d)
    assert hist.problems and not hist.complete
    rep = tchecker.check_history(hist)
    assert not rep.ok and rep.violated == []  # incomplete, NOT a pass
    assert all(r.ok is None for r in rep.results.values())
    # validate() (full sink schema) flags the same stream defects.
    errors = sink_mod.validate(str(tmp_path / "bad"))  # no manifest: hard fail
    assert errors


# ------------------------------------------------------- coverage plane


def test_coverage_deterministic_and_bounded():
    _, _, _, _, traws, tp = traced_run()
    again = telemetry.simulate_windowed(
        CFG_T, SEED, BATCH, TICKS, WINDOW, 0, None, 1, SPEC
    )
    assert np.array_equal(np.asarray(tp.cov), np.asarray(again[5].cov))
    bits = np.asarray(cov_popcount(tp.cov))
    assert (bits > 0).all() and (bits <= COV_BITS).all()
    # Per-window cov snapshots are monotone (cumulative OR).
    cov_w = np.asarray(traws.cov)  # [W, C, B]
    for w in range(1, cov_w.shape[0]):
        assert (cov_w[w] & cov_w[w - 1] == cov_w[w - 1]).all()


def test_coverage_fitness_search_one_program_deterministic():
    from raft_sim_tpu.scenario import search as search_mod

    cfg = RaftConfig(n_nodes=5, client_interval=8)
    spec = search_mod.SearchSpec(
        generations=2, population=8, ticks=64, window=32,
        fitness="coverage", trace_depth=16, stop_on_hit=False,
    )
    res = search_mod.search(cfg, spec)
    assert res.spec["fitness"] == "coverage"
    assert [g["cov_new_bits"] for g in res.generations][0] > 0
    totals = [g["cov_total_bits"] for g in res.generations]
    assert totals == sorted(totals)  # the seen-set only grows
    res2 = search_mod.search(cfg, spec)
    assert [g["best_fitness"] for g in res.generations] == [
        g["best_fitness"] for g in res2.generations
    ]
    assert totals == [g["cov_total_bits"] for g in res2.generations]


# ----------------------------------------------------- triggers + driver


def test_flight_recorder_event_trigger():
    # Arm the recorder on the first LEADER event: it must freeze at the
    # first election, violations or not -- the "lead-up to a non-violating
    # anomaly" capture docs/OBSERVABILITY.md used to name as a gap.
    out = telemetry.simulate_windowed(
        CFG_T, SEED, BATCH, 64, 32, 4, None, 1, SPEC,
        tev.EV_LEADER,
    )
    _, metrics, _, rec, traws, _ = out
    hist = thistory.from_device(traws)
    frozen = np.asarray(rec.frozen)
    for c in range(BATCH):
        leads = [e.tick for e in hist.events[c] if e.kind == tev.EV_LEADER]
        if leads:
            assert frozen[c]
            ticks, _ = telemetry.export_cluster(rec, c)
            # The triggering tick is the ring's newest entry (freeze is
            # latched AFTER the write).
            assert ticks[-1] == leads[0]
        else:
            assert not frozen[c]


def test_offer_refused_while_trace_armed(tmp_path):
    # Session.offer() ticks outside the windowed scan: with a trace armed
    # they would punch undetectable holes in the history (ticks stay
    # monotone, nothing counts as dropped), so offer() must refuse instead
    # of letting the checker pass a gappy stream.
    from raft_sim_tpu import driver

    sess = driver.Session(CFG_T, batch=2, seed=0)
    sess.attach_telemetry(str(tmp_path / "tel"), window=32, ring=0)
    sess.attach_trace(depth=16)
    with pytest.raises(RuntimeError, match="trace"):
        sess.offer(42)


def test_finalize_telemetry_reports_frozen_vs_exported(tmp_path):
    from raft_sim_tpu import driver

    sess = driver.Session(CFG, batch=4, seed=0)
    sess.attach_telemetry(str(tmp_path / "tel"), window=32, ring=4)
    rec = telemetry.init_recorder(CFG, 4, 4)
    sess._tel_rec = rec._replace(frozen=jnp.ones((4,), bool))
    out = sess.finalize_telemetry(max_flights=2)
    assert out["flights_frozen"] == 4
    assert out["flights_exported"] == 2
    assert out["flights"] == [0, 1]
    with open(out["summary"]) as f:
        summary = json.load(f)
    assert summary["flights_frozen"] == 4
    assert summary["flights_exported"] == 2
