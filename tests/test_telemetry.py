"""Telemetry tier: windowed on-device aggregation, the violation flight
recorder, and the schema'd host sink.

The load-bearing property is BIT-EXACTNESS: telemetry must be a pure
re-bucketing of what the monolithic scan already computes -- same
trajectories (shared tick body), and window records that reduce to exactly
the full run's metrics, which themselves equal folding the full per-tick
StepInfo stack. Anything weaker and a soak run observed through telemetry
would be a different experiment than the one it reports on.

Compile budget: the fuzz-config comparisons share ONE module-scoped run
(`fuzz_run`) -- plain scan, telemetry scan, and a per-tick stack built by
driving the SAME jitted tick body from the host -- so the tier-1 pass pays
three kernel compiles here, not one per test. The chunked/simulate wrappers
re-exercise the same machinery through more entry points and ride the slow
tier (the driver CLI tests below keep the chunked path covered in tier-1).
"""

import json
from types import SimpleNamespace

import jax
import numpy as np
import pytest

from raft_sim_tpu import LEADER, RaftConfig, StepInfo, init_batch
from raft_sim_tpu.models import raft_batched
from raft_sim_tpu.sim import scan, telemetry, trace
from raft_sim_tpu.utils import telemetry_sink

# A kitchen-sink fault mix (drop + crash + skew + client traffic) so windows
# carry nonzero values in every field the schema defines.
FUZZ_CFG = RaftConfig(
    n_nodes=5,
    log_capacity=16,
    client_interval=4,
    drop_prob=0.2,
    crash_prob=0.3,
    crash_period=32,
    crash_down_ticks=8,
    clock_skew_prob=0.1,
)
BATCH, TICKS, WINDOW, RING = 4, 64, 16, 8

# The driver-level tests share one (cfg, batch, window, ring) shape so the CLI
# test reuses the session test's compiled programs.
DRIVER_CFG = RaftConfig(n_nodes=5, client_interval=8)
DRIVER_BATCH, DRIVER_WINDOW = 2, 16


def _setup(cfg, batch, seed=0):
    root = jax.random.key(seed)
    ki, kr = jax.random.split(root)
    return init_batch(cfg, ki, batch), jax.random.split(kr, batch)


def tree_eq(a, b, msg=""):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb), err_msg=msg)


@pytest.fixture(scope="module")
def fuzz_run():
    """One fuzzed trajectory observed three ways: the monolithic batch-minor
    scan, the windowed telemetry scan (with flight recorder), and a full
    per-tick StepInfo stack produced by stepping the SAME shared tick body
    (scan.tick_batch_minor) from the host."""
    state, keys = _setup(FUZZ_CFG, BATCH)
    plain_final, plain_metrics = scan.run_batch_minor(FUZZ_CFG, state, keys, TICKS)
    rec0 = telemetry.init_recorder(FUZZ_CFG, RING, BATCH)
    tel_final, tel_metrics, records, recorder = telemetry.run_batch_minor_telemetry(
        FUZZ_CFG, state, keys, TICKS, window=WINDOW, recorder=rec0
    )
    # Per-tick ground truth: one jitted tick, driven T times from the host.
    s_t = raft_batched.to_batch_minor(state)
    m_t = raft_batched.to_batch_minor(scan.init_metrics_batch(BATCH))
    tick = jax.jit(lambda s, m: scan.tick_batch_minor(FUZZ_CFG, s, keys, m))
    per_tick = []
    for _ in range(TICKS):
        s_t, m_t, info = tick(s_t, m_t)
        per_tick.append(jax.device_get(raft_batched.from_batch_minor(info)))
    stack = StepInfo(
        *(
            np.stack([np.asarray(getattr(i, f)) for i in per_tick], axis=1)
            for f in StepInfo._fields
        )
    )  # leaves [B, T, ...], like scan.run_batch(trace=True)
    loop_final = raft_batched.from_batch_minor(s_t)
    loop_metrics = raft_batched.from_batch_minor(m_t)
    return SimpleNamespace(
        state=state, keys=keys,
        plain_final=plain_final, plain_metrics=plain_metrics,
        tel_final=tel_final, tel_metrics=tel_metrics,
        records=jax.device_get(records), recorder=jax.device_get(recorder),
        stack=stack, loop_final=loop_final, loop_metrics=loop_metrics,
    )


# ------------------------------------------------- windowed aggregation exactness


def test_windowed_records_reduce_to_monolithic_metrics(fuzz_run):
    """The tentpole contract: reducing the [T/W] window records equals the
    monolithic scan's RunMetrics bit-for-bit, and the telemetry carry legs do
    not perturb the trajectory."""
    tree_eq(fuzz_run.plain_final, fuzz_run.tel_final,
            "telemetry perturbed the trajectory")
    tree_eq(fuzz_run.plain_metrics, fuzz_run.tel_metrics,
            "telemetry perturbed the run metrics")
    tree_eq(fuzz_run.plain_metrics, telemetry.reduce_records(fuzz_run.records),
            "window reduction diverged from the monolithic metrics")


def test_windowed_records_match_full_per_tick_stack(fuzz_run):
    """Each window's sums equal summing the full per-tick StepInfo stack over
    exactly that window's ticks -- windowing loses resolution, not data. The
    stack comes from the same tick body driven tick-by-tick (which itself
    reproduces the scan bit-for-bit: integer kernel, same op order)."""
    tree_eq(fuzz_run.plain_final, fuzz_run.loop_final)
    tree_eq(fuzz_run.plain_metrics, fuzz_run.loop_metrics)
    recs, stack = fuzz_run.records, fuzz_run.stack
    assert np.asarray(recs.start).shape == (BATCH, TICKS // WINDOW)
    for wi in range(TICKS // WINDOW):
        sl = slice(wi * WINDOW, (wi + 1) * WINDOW)
        for stack_f, win_f in [
            ("msgs_delivered", "total_msgs"),
            ("cmds_injected", "total_cmds"),
            ("lat_sum", "lat_sum"),
            ("lat_cnt", "lat_cnt"),
            ("lat_excluded", "lat_excluded"),
            ("noop_blocked", "noop_blocked"),
            ("lm_skipped_pairs", "lm_skipped_pairs"),
        ]:
            per_tick = np.asarray(getattr(stack, stack_f))[:, sl].sum(axis=1)
            windowed = np.asarray(getattr(recs.metrics, win_f))[:, wi]
            np.testing.assert_array_equal(per_tick, windowed, err_msg=stack_f)
        # multi_leader folds the derived per-tick predicate (n_leaders >= 2).
        np.testing.assert_array_equal(
            (np.asarray(stack.n_leaders)[:, sl] >= 2).sum(axis=1),
            np.asarray(recs.metrics.multi_leader)[:, wi],
        )
        hist = np.asarray(stack.lat_hist)[:, sl].sum(axis=1)
        np.testing.assert_array_equal(hist, np.asarray(recs.metrics.lat_hist)[:, wi])
        np.testing.assert_array_equal(
            np.asarray(recs.start)[:, wi], np.full(BATCH, wi * WINDOW)
        )
        # Window max/min fold the per-tick stack's values too.
        np.testing.assert_array_equal(
            np.asarray(stack.max_term)[:, sl].max(axis=1),
            np.asarray(recs.metrics.max_term)[:, wi],
        )


def test_first_viol_tick_never_on_clean_run(fuzz_run):
    assert (np.asarray(fuzz_run.records.first_viol_tick) == telemetry.NEVER).all()


def test_window_must_divide():
    state, keys = _setup(FUZZ_CFG, 2)
    with pytest.raises(ValueError, match="divide"):
        telemetry.run_batch_minor_telemetry(FUZZ_CFG, state, keys, 100, window=32)


@pytest.mark.slow
def test_chunked_telemetry_matches_and_emits_remainder_window():
    """The chunked path merges to the same metrics at any chunking and
    self-describes a final short window when ticks do not divide. (Tier-1
    covers the same path through the driver CLI tests below.)"""
    state, keys = _setup(FUZZ_CFG, 4, seed=3)
    _, m_plain = scan.run_batch_minor(FUZZ_CFG, state, keys, 100)
    seen = []
    _, m_tel, _ = telemetry.run_chunked_telemetry(
        FUZZ_CFG, state, keys, 100, window=32, chunk=64,
        callback=lambda done, s, m, recs: seen.append(jax.device_get(recs)) and False,
    )
    tree_eq(m_plain, m_tel)
    widths = [int(t) for recs in seen for t in np.asarray(recs.metrics.ticks)[0]]
    assert widths == [32, 32, 32, 4]  # three full windows + the remainder


@pytest.mark.slow
def test_simulate_windowed_matches_simulate():
    cfg = RaftConfig(n_nodes=5, client_interval=8)
    f1, m1 = scan.simulate(cfg, 7, 16, 64)
    f2, m2, recs, rec = telemetry.simulate_windowed(cfg, 7, 16, 64, 16, ring=8)
    tree_eq(f1, f2)
    tree_eq(m1, m2)
    assert not np.asarray(rec.frozen).any()


# ------------------------------------------------------- violation flight recorder


def _two_leaders(state, cluster):
    """Hand-plant an election-safety violation: two live LEADERs sharing a
    term in one cluster (the invariant phase flags it on the next tick)."""
    role = state.role.at[cluster, 0].set(LEADER).at[cluster, 1].set(LEADER)
    term = state.term.at[cluster, 0].set(99).at[cluster, 1].set(99)
    return state._replace(role=role, term=term)


def test_flight_recorder_freezes_on_forced_violation():
    """A seeded forced violation: the ring holds the K ticks ENDING at the
    first violating tick, freezes there, and the export renders through
    trace.info_lines with the violation as the newest line."""
    k = 8
    state, keys = _setup(DRIVER_CFG, 2, seed=1)
    rec = telemetry.init_recorder(DRIVER_CFG, k, 2)
    # Clean prefix: 12 ticks (> K, so the ring has wrapped at least once).
    state, _, _, rec = telemetry.run_batch_minor_telemetry(
        DRIVER_CFG, state, keys, 12, window=4, recorder=rec
    )
    assert not np.asarray(rec.frozen).any()
    # Violation planted in cluster 1 only; flagged on the next tick (now=12).
    state = _two_leaders(state, cluster=1)
    state, _, recs, rec = telemetry.run_batch_minor_telemetry(
        DRIVER_CFG, state, keys, 8, window=4, recorder=rec
    )
    assert np.asarray(rec.frozen).tolist() == [False, True]
    # The window records locate the violation tick exactly.
    assert np.asarray(recs.first_viol_tick)[1].tolist() == [12, 16]
    assert (np.asarray(recs.first_viol_tick)[0] == telemetry.NEVER).all()

    ticks, infos = telemetry.export_cluster(rec, 1)
    # Ring = the K ticks ending at the freeze tick, in chronological order.
    assert ticks.tolist() == list(range(5, 13))
    assert bool(np.asarray(infos.viol_election_safety)[-1])
    lines = list(trace.info_lines(infos))
    assert len(lines) == k
    assert lines[-1].endswith("VIOLATION")
    assert not any(l.endswith("VIOLATION") for l in lines[:-1])

    # Frozen means frozen: more ticks leave cluster 1's ring untouched while
    # cluster 0 keeps recording.
    state, _, _, rec = telemetry.run_batch_minor_telemetry(
        DRIVER_CFG, state, keys, 8, window=4, recorder=rec
    )
    t2, i2 = telemetry.export_cluster(rec, 1)
    np.testing.assert_array_equal(t2, ticks)
    tree_eq(i2, infos)
    t0, _ = telemetry.export_cluster(rec, 0)
    assert t0.max() == 27  # cluster 0 ring still advancing


def test_flight_recorder_partial_fill_export(fuzz_run):
    """Fewer recorded ticks than K never happens in the shared 64-tick run --
    but slot ordering does: the ring has wrapped 64/8 times and must still
    export in chronological order with all slots valid."""
    ticks, infos = telemetry.export_cluster(fuzz_run.recorder, 2)
    assert ticks.tolist() == list(range(TICKS - RING, TICKS))
    assert len(list(trace.info_lines(infos))) == RING
    # Ring rows equal the per-tick stack's final RING ticks: full fidelity.
    for f in StepInfo._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(infos, f)),
            np.asarray(getattr(fuzz_run.stack, f))[2, TICKS - RING:],
            err_msg=f,
        )


# ------------------------------------------------------------------- host sink


def test_sink_roundtrip_and_validation(fuzz_run, tmp_path):
    d = str(tmp_path / "tel")
    sink = telemetry_sink.TelemetrySink(
        d, FUZZ_CFG, seed=0, batch=BATCH, window=WINDOW, ring=RING, source="test"
    )
    assert sink.append_windows(fuzz_run.records) == TICKS // WINDOW
    assert telemetry_sink.validate(d) == []

    man = telemetry_sink.read_manifest(d)
    assert man["schema_version"] == telemetry_sink.TELEMETRY_SCHEMA_VERSION
    assert man["config_hash"] == telemetry_sink.config_hash(FUZZ_CFG)
    rows = telemetry_sink.read_windows(d)
    assert [r["window"] for r in rows] == list(range(TICKS // WINDOW))
    assert [r["start"] for r in rows] == [WINDOW * i for i in range(TICKS // WINDOW)]
    # The JSONL stream is a lossless fleet aggregation of the records.
    md = fuzz_run.plain_metrics
    assert sum(r["msgs"] for r in rows) == int(np.sum(np.asarray(md.total_msgs)))
    assert sum(r["cmds"] for r in rows) == int(np.sum(np.asarray(md.total_cmds)))
    assert sum(sum(r["lat_hist"]) for r in rows) == int(np.sum(np.asarray(md.lat_cnt)))

    # Flight export file passes the schema check too.
    ticks, infos = telemetry.export_cluster(fuzz_run.recorder, 2)
    sink.write_flight(2, ticks, infos)
    assert telemetry_sink.validate(d) == []


def test_sink_validation_catches_breakage(fuzz_run, tmp_path):
    d = str(tmp_path / "tel")
    sink = telemetry_sink.TelemetrySink(
        d, FUZZ_CFG, seed=0, batch=BATCH, window=WINDOW, ring=RING, source="test"
    )
    sink.append_windows(fuzz_run.records)
    assert telemetry_sink.validate(d) == []

    win = tmp_path / "tel" / "windows.jsonl"
    lines = win.read_text().splitlines()
    broken = json.loads(lines[1])
    del broken["msgs"]
    broken["lat_hist"] = [1, 2, 3]  # wrong arity
    win.write_text(lines[0] + "\n" + json.dumps(broken) + "\n")
    errs = telemetry_sink.validate(d)
    assert any("msgs" in e for e in errs)
    assert any("lat_hist" in e for e in errs)

    man = tmp_path / "tel" / "manifest.json"
    m = json.loads(man.read_text())
    m["schema_version"] = 999
    man.write_text(json.dumps(m))
    assert any("schema_version" in e for e in telemetry_sink.validate(d))


def test_sink_rebuild_discards_stale_flights(fuzz_run, tmp_path):
    """Re-attaching a sink to a directory must not leave a previous run's
    violation recordings (or rollup) under the fresh manifest -- stale
    flight_*.jsonl would misattribute old violations to the new run."""
    d = str(tmp_path / "tel")
    sink = telemetry_sink.TelemetrySink(
        d, FUZZ_CFG, seed=0, batch=BATCH, window=WINDOW, ring=RING, source="test"
    )
    ticks, infos = telemetry.export_cluster(fuzz_run.recorder, 0)
    stale = sink.write_flight(0, ticks, infos)
    sink.write_summary({"total_violations": 7})
    import os

    assert os.path.exists(stale)
    telemetry_sink.TelemetrySink(  # run 2 into the same directory
        d, FUZZ_CFG, seed=1, batch=BATCH, window=WINDOW, ring=RING, source="test"
    )
    assert not os.path.exists(stale)
    assert not os.path.exists(os.path.join(d, "summary.json"))


def test_metrics_report_tool(fuzz_run, tmp_path, capsys):
    import sys

    sys.path.insert(0, ".")
    from tools import metrics_report

    d = str(tmp_path / "tel")
    sink = telemetry_sink.TelemetrySink(
        d, FUZZ_CFG, seed=0, batch=BATCH, window=WINDOW, ring=RING, source="test"
    )
    sink.append_windows(fuzz_run.records)
    from raft_sim_tpu.parallel import summarize

    sink.write_summary(summarize(fuzz_run.plain_metrics)._asdict())

    assert metrics_report.main([d, "--validate"]) == 0
    assert metrics_report.main([d]) == 0
    out = capsys.readouterr().out
    assert f"{TICKS // WINDOW} windows" in out and "lat_excluded" in out
    # Self-diff: every shared metric's delta is 0.
    assert metrics_report.main(["--diff", d, d]) == 0
    out = capsys.readouterr().out
    for line in out.splitlines():
        if line.startswith(("violations", "cmds", "msgs")):
            assert line.split()[-1] == "0"


# ------------------------------------------------------- driver + CLI integration


def test_session_telemetry_end_to_end(tmp_path):
    from raft_sim_tpu.driver import Session

    d = str(tmp_path / "tel")
    sess = Session(DRIVER_CFG, batch=DRIVER_BATCH, seed=0)
    sess.attach_telemetry(d, window=DRIVER_WINDOW, ring=32)
    sess.run(48)
    sess.run(16)  # window indices continue across run() calls
    fin = sess.finalize_telemetry()
    assert fin["flights"] == []  # clean run: nothing to export
    assert telemetry_sink.validate(d) == []
    rows = telemetry_sink.read_windows(d)
    assert [r["window"] for r in rows] == list(range(len(rows)))
    assert sum(r["ticks"] for r in rows) == 64
    assert not np.asarray(sess._tel_rec.frozen).any()


def test_cli_telemetry_flags(tmp_path, capsys):
    from raft_sim_tpu.driver import main

    d = str(tmp_path / "tel")
    rc = main([
        "run", "--batch", str(DRIVER_BATCH), "--ticks", "48",
        "--client-interval", "8",
        "--telemetry-dir", d, "--telemetry-window", str(DRIVER_WINDOW),
    ])
    assert rc == 0
    assert telemetry_sink.validate(d) == []
    out = capsys.readouterr().out
    assert '"lat_excluded"' in out  # summary line carries the coverage counter


def test_cli_telemetry_excluded_with_tracing(tmp_path):
    from raft_sim_tpu.driver import main

    with pytest.raises(SystemExit):
        main([
            "run", "--batch", "1", "--ticks", "8", "--trace-events",
            "--telemetry-dir", str(tmp_path / "t"),
        ])


# ------------------------------------------------------------ trace.events golden


def test_trace_events_golden():
    """Exact expected event stream from a hand-built state stack -- the
    decoder was previously only exercised indirectly (test_driver asserts
    substrings); this pins the full output."""
    from raft_sim_tpu.types import CANDIDATE, FOLLOWER

    F, C, L = FOLLOWER, CANDIDATE, LEADER
    states = SimpleNamespace(
        role=np.array([[F, F], [C, F], [L, F], [F, F]]),
        term=np.array([[1, 1], [2, 1], [2, 1], [3, 1]]),
        commit_index=np.array([[0, 0], [0, 0], [0, 0], [2, 0]]),
        log_base=np.array([[0, 0], [0, 0], [0, 0], [0, 1]]),
    )
    assert list(trace.events(states)) == [
        (1, "node 0 starts election for term 2"),
        (2, "node 0 becomes leader of term 2"),
        (3, "node 0 steps down (term 2 -> 3)"),
        (3, "node 0 commits through 2"),
        (3, "node 1 compacts through 1"),
    ]
