from raft_sim_tpu.parallel.mesh import (
    AXIS,
    FleetSummary,
    gather_metrics,
    init_distributed,
    make_mesh,
    simulate_sharded,
    simulate_windowed_sharded,
    summarize,
)
from raft_sim_tpu.parallel.nodeshard import (
    NODE_AXIS,
    check_shardable,
    make_node_mesh,
    simulate_node_sharded,
    simulate_node_sharded_windowed,
    unshard_state,
)

__all__ = [
    "AXIS",
    "FleetSummary",
    "NODE_AXIS",
    "check_shardable",
    "gather_metrics",
    "init_distributed",
    "make_mesh",
    "make_node_mesh",
    "simulate_node_sharded",
    "simulate_node_sharded_windowed",
    "simulate_sharded",
    "simulate_windowed_sharded",
    "summarize",
    "unshard_state",
]
